"""Cluster node APIs over RPC (reference lib/vminsertapi/api.go +
lib/vmselectapi/{api,server}.go + the cluster-branch netstorage semantics
documented in docs/victoriametrics/Cluster-VictoriaMetrics.md:851+).

- make_storage_handlers(storage): RPC method table served by vmstorage
  (both the insert-side writeRows_v1 and the select-side search_v1 family).
- StorageNodeClient: client half for one storage node.
- ClusterStorage: vminsert+vmselect composite backend — shards writes by
  consistent hash of the canonical metric name with replication and
  rerouting, fans reads out to every node and merges with partial-result
  tracking. Duck-compatible with storage.Storage for httpapi/query use.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..devtools.locktrace import make_lock
from ..storage.metric_name import MetricName
from ..storage.tag_filters import TagFilter
from ..utils import costacc, logger, querytracer
from ..utils import metrics as metricslib
from . import ringfilter
from .consistenthash import ConsistentHash
from .rpc import (HELLO_INSERT, HELLO_SELECT,  # noqa: F401 — re-exports
                  ClusterUnavailableError, PartialResultError, RPCClient,
                  RPCClientPool, RPCError, Reader, Writer)


def _json_payload(data: bytes, what: str):
    """Decode a JSON wire payload, converting a malformed peer's bytes
    into a typed RPCError (which round-trips both error boundaries)
    instead of a bare ValueError that would surface as an anonymous
    500 / unmarked error frame (VMT016)."""
    import json
    try:
        return json.loads(data)
    except ValueError as e:
        raise RPCError(f"bad {what} payload: {e}") from None

SERIES_PER_FRAME = 64

# fan-out failures whose data was provably still served by surviving
# replicas (RF coverage): NOT marked partial, counted here instead
_PARTIAL_AVOIDED = metricslib.REGISTRY.counter("vm_partial_avoided_total")
# live-resharding accounting (README "Elastic cluster serving"): parts
# adopted over migratePart_v1 (ticks on the receiving storage node AND
# on the driving router) and bytes moved by a join-rebalance/drain
_PARTS_MIGRATED = metricslib.REGISTRY.counter("vm_parts_migrated_total")
_REBALANCE_BYTES = metricslib.REGISTRY.counter(
    "vm_rebalance_moved_bytes_total")


# ---------------------------------------------------------------------------
# vmstorage-side handlers
# ---------------------------------------------------------------------------

def _read_filters(r: Reader) -> list[TagFilter]:
    n = r.u64()
    out = []
    for _ in range(n):
        key = r.bytes_()
        value = r.bytes_()
        flags = r.u64()
        out.append(TagFilter(key, value, negate=bool(flags & 1),
                             regex=bool(flags & 2)))
    return out


def _write_filters(w: Writer, filters: list[TagFilter]):
    w.u64(len(filters))
    for tf in filters:
        w.bytes_(tf.key)
        w.bytes_(tf.value)
        w.u64((1 if tf.negate else 0) | (2 if tf.regex else 0))


def _read_tenant(r: Reader) -> tuple:
    return (r.u64(), r.u64())


def _write_tenant(w: Writer, tenant) -> Writer:
    return w.u64(tenant[0]).u64(tenant[1])


def _split_filter_sets(filters):
    """Normalize a search's filters into (first_set, extra_sets): a
    plain list[TagFilter] has no extras; a selector-level `or` union
    (list of filter sets, see MetricExpr.or_sets) splits into the
    wire-legacy first set plus the trailing extras field."""
    if filters and isinstance(filters[0], (list, tuple)):
        sets = [list(fs) for fs in filters]
        return sets[0], sets[1:]
    return list(filters), []


def _legacy_meta() -> bool:
    """``VM_RPC_LEGACY_META=1`` makes this process speak the PRE-cost
    search_v1 dialect (no empty-trace slot, no extras frame, or_sets
    ignored) — the rolling-upgrade emulation knob the old<->new
    tolerance tests and canary drills use."""
    import os
    return os.environ.get("VM_RPC_LEGACY_META", "") == "1"


#: text series key -> canonical MetricName marshal, the ONE shard-
#: placement key both write paths and the ring-ownership read filter
#: agree on (a per-path key — text here, marshal there — would place
#: the same series on different nodes and break ownership filtering).
#: Pure function of the key bytes, so the memo is global and safe to
#: share across tenants/transforms.
_PLACEMENT_MEMO: dict[bytes, bytes] = {}
_PLACEMENT_LOCK = make_lock("parallel.cluster_api._PLACEMENT_MEMO")
_MAX_PLACEMENT_MEMO = 1 << 20


def placement_marshal(key: bytes) -> bytes:
    """Canonical marshal for a raw text series key; falls back to the
    raw bytes for keys that don't parse (the storage node drops those
    rows later anyway — consistent placement still holds)."""
    # racy-by-design fast path: a stale miss re-parses the key (pure
    # function), and the locked fill stores the identical marshaled name
    m = _PLACEMENT_MEMO.get(key)  # vmt: disable=VMT015
    if m is None:
        from ..ingest.parsers import labels_from_series_key
        try:
            m = MetricName.from_labels(labels_from_series_key(key)).marshal()
        except ValueError:
            m = key
        with _PLACEMENT_LOCK:
            if len(_PLACEMENT_MEMO) >= _MAX_PLACEMENT_MEMO:
                _PLACEMENT_MEMO.clear()
            _PLACEMENT_MEMO[key] = m
    return m


def make_storage_handlers(storage, rate_limiter=None) -> dict:
    """RPC dispatch table for a vmstorage node. `rate_limiter` applies
    -maxIngestionRate to RPC writes too (the multilevel/clusternative
    chaining path must honor the same ceiling as HTTP ingest)."""

    def h_write_rows(r: Reader):
        tenant = _read_tenant(r)
        n = r.u64()
        rows = []
        for _ in range(n):
            raw = r.bytes_()
            ts = r.i64()
            val = r.f64()
            rows.append((MetricName.unmarshal(raw), ts, val))
        # optional trailing reroute flag: these rows landed here because
        # an owner node was down — mark them always-served so the ring
        # read filter can never hide this (possibly only) copy
        exempt = bool(r.u64()) if r.remaining else False
        if rate_limiter is not None and rate_limiter.enabled():
            rate_limiter.register(len(rows), tenant)
        storage.add_rows(rows, tenant=tenant)
        if exempt and hasattr(storage, "add_ring_exempt_names"):
            # re-marshal is canonical, so this round-trips the wire raw
            # byte-for-byte; only the RARE reroute batch pays it
            storage.add_ring_exempt_names(
                {mn.marshal() for mn, _, _ in rows})
        return Writer().u64(len(rows))

    def h_write_rows_columnar(r: Reader):
        """writeRows_v2: ColumnarRows shipped raw — text series keys +
        ts/value columns. The storage node resolves whole batches through
        its native key map (no per-row Python unmarshal on either side;
        the reference's raw-row routing, lib/vminsertapi/api.go:15)."""
        tenant = _read_tenant(r)
        keybuf = r.bytes_()
        key_off = r.array()
        key_len = r.array()
        tss = r.array()
        vals = r.array()
        exempt = bool(r.u64()) if r.remaining else False
        if rate_limiter is not None and rate_limiter.enabled():
            rate_limiter.register(int(key_off.size), tenant)
        from .. import native
        cr = native.ColumnarRows(keybuf, key_off, key_len, tss, vals)
        if exempt and hasattr(storage, "add_ring_exempt_names"):
            mv = memoryview(keybuf)
            seen = set()
            for o, ln in zip(key_off, key_len):
                seen.add(bytes(mv[int(o):int(o) + int(ln)]))
            storage.add_ring_exempt_names(
                [placement_marshal(k) for k in seen])
        if getattr(storage, "add_rows_columnar", None) is not None:
            n = storage.add_rows_columnar(cr, tenant=tenant)
        else:  # storage without a columnar path: materialize rows
            from ..ingest.parsers import labels_from_series_key
            rows = []
            for k, ts, val in cr.to_rows():
                try:
                    rows.append((MetricName.from_labels(
                        labels_from_series_key(k)), ts, val))
                except ValueError:
                    continue
            n = storage.add_rows(rows, tenant=tenant)
        return Writer().u64(int(n))

    def h_is_readonly(r: Reader):
        return Writer().u64(1 if getattr(storage, "is_readonly", False) else 0)

    # sentinel "count" marking the trailing metadata frame of search_v1
    META_FRAME = (1 << 32) - 1

    def _read_trace_flag(r: Reader) -> bool:
        """Optional trailing trace-request flag (search_v1 extension).
        Old clients simply don't send it — Reader tolerance gives
        rolling-upgrade compat both ways."""
        return bool(r.u64()) if r.remaining else False

    def _read_deadline(r: Reader) -> float:
        """Optional trailing remaining-budget field (ms; second
        search_v1 extension, after the trace flag): converts to a local
        monotonic cutoff so this vmstorage aborts index scans and
        fetches mid-flight when the caller's budget expires, instead of
        burning a dead query's full cost.  Old clients don't send it
        (remaining==0 -> no deadline)."""
        budget_ms = r.u64() if r.remaining else 0
        if not budget_ms or not getattr(storage,
                                        "supports_search_deadline", False):
            return 0.0
        return time.monotonic() + budget_ms / 1e3

    def _read_or_sets(r: Reader) -> list:
        """Optional trailing OR'd-filter-set field (third search_v1
        extension, after the budget): a selector-level `or` union ships
        its first set in the legacy position and the remaining sets
        here.  Old clients don't send it; a legacy-dialect server
        (VM_RPC_LEGACY_META=1) ignores it — the client detects the
        missing union ack in the metadata frame and falls back to one
        legacy call per set."""
        if not r.remaining or _legacy_meta():
            return []
        n = r.u64()
        return [_read_filters(r) for _ in range(n)]

    def _union_filters(filters, or_sets):
        """(effective_filters, union_applied): apply the shipped extra
        sets when the storage can union them at the tsid level."""
        if not or_sets:
            return filters, True
        if getattr(storage, "supports_filter_union", False):
            return [filters] + or_sets, True
        # union-less duck-typed storage: serve the first set only and
        # DON'T ack — the client re-issues per-set legacy calls
        return filters, False

    def _read_ring(r: Reader):
        """Optional trailing ring-ownership field (fourth search_v1
        extension, after or_sets): the caller's consistent-hash view.
        Honored (and acked via the metadata frame) only by backends
        that actually hold ring-placed data — a multilevel vmselect's
        ClusterStorage ignores it and the caller's dedup keeps
        correctness (see parallel/ringfilter)."""
        if not r.remaining or _legacy_meta():
            return None
        ring_b = r.bytes_()
        if not getattr(storage, "supports_ring_filter", False):
            return None
        return ringfilter.intern_ring(ring_b)

    def _meta_frame(qt, cost=None, union_ok=True, ring_ok=False) -> Writer:
        """Trailing metadata frame: partial-result flag + the
        storage-side span tree (when tracing) + the extras dict (cost
        frame + filter-union ack).  Wire layout, Reader-tolerant both
        ways across versions:

        - old server: [partial u64] [trace bytes, only when tracing]
        - new server: [partial u64] [trace bytes, b"" when not tracing]
          [extras json bytes]

        An old CLIENT reading a new frame parses the trace slot (b""
        fails its json parse and is ignored by its existing malformed-
        trace guard) and never reads the extras.  A new client
        disambiguates by position: a second bytes field present means
        slot one was the (possibly empty) trace and slot two the
        extras; absent means an old server's trace-only frame."""
        import json
        meta = Writer().u64(META_FRAME)
        meta.u64(1 if getattr(storage, "last_partial", False) else 0)
        if qt.enabled:
            qt.donef("")
            meta.bytes_(json.dumps(qt.to_dict()).encode())
        elif not _legacy_meta():
            meta.bytes_(b"")  # empty trace slot pins the extras position
        if _legacy_meta():
            return meta
        extras = {"filterUnion": bool(union_ok)}
        if ring_ok:
            extras["ringFiltered"] = True
        if cost is not None:
            extras["cost"] = cost.remote_dict()
        meta.bytes_(json.dumps(extras).encode())
        return meta

    def h_search(r: Reader):
        tenant = _read_tenant(r)
        filters = _read_filters(r)
        min_ts, max_ts = r.i64(), r.i64()
        qt = querytracer.new(_read_trace_flag(r),
                             "vmstorage search_v1: %d filters, "
                             "timeRange=[%d..%d]", len(filters), min_ts,
                             max_ts)
        deadline = _read_deadline(r)
        or_sets = _read_or_sets(r)
        ring = _read_ring(r)
        filters, union_ok = _union_filters(filters, or_sets)
        if hasattr(storage, "reset_partial"):
            storage.reset_partial()
        # node-side cost accounting: every fetch seam under this search
        # reports into `cost`, shipped back in the metadata frame
        cost = costacc.CostTracker()
        prev_cost = costacc.set_current(cost)
        try:
            with qt.new_child("search_series") as sq:
                kw = {"deadline": deadline} if deadline else {}
                if getattr(storage, "supports_search_tracer", False):
                    # multilevel: a ClusterStorage backend grafts its
                    # per-node spans under this handler's span, so the
                    # caller's trace shows the WHOLE fan-out tree
                    kw["tracer"] = sq
                series = storage.search_series(filters, min_ts, max_ts,
                                               tenant=tenant, **kw)
                sq.donef("%d series", len(series))
            cost.add_samples(sum(sd.timestamps.size for sd in series))
            if ring is not None:
                keep, rerouted = ring.keep_mask(
                    tenant, [getattr(sd, "raw_name", None) or
                             sd.metric_name.marshal() for sd in series],
                    exempt=getattr(storage, "ring_exempt_names", None))
                series = [sd for sd, k in zip(series, keep) if k]
                if rerouted:
                    ringfilter.REROUTE_READS.inc()
        finally:
            costacc.set_current(prev_cost)
        costacc.record_usage(tenant, cost)

        def frames():
            for i in range(0, len(series), SERIES_PER_FRAME):
                w = Writer()
                chunk = series[i:i + SERIES_PER_FRAME]
                w.u64(len(chunk))
                for sd in chunk:
                    w.bytes_(sd.metric_name.marshal())
                    w.array(sd.timestamps)
                    w.array(sd.values)
                yield w
            yield _meta_frame(qt, cost, union_ok, ring_ok=ring is not None)
        return frames()

    def h_search_columns(r: Reader):
        """searchColumns_v1: the columnar read plane — per-frame batches
        of (raw names, counts, concatenated ts/value columns) instead of
        per-series decoded arrays. Cluster reads then feed the same
        columnar host path and device tile packer as single-node reads
        (the MetricBlock-streaming role, lib/vmselectapi/server.go:1010)."""
        tenant = _read_tenant(r)
        filters = _read_filters(r)
        min_ts, max_ts = r.i64(), r.i64()
        qt = querytracer.new(_read_trace_flag(r),
                             "vmstorage searchColumns_v1: %d filters, "
                             "timeRange=[%d..%d]", len(filters), min_ts,
                             max_ts)
        deadline = _read_deadline(r)
        or_sets = _read_or_sets(r)
        ring = _read_ring(r)
        filters, union_ok = _union_filters(filters, or_sets)
        if hasattr(storage, "reset_partial"):
            storage.reset_partial()
        cost = costacc.CostTracker()
        prev_cost = costacc.set_current(cost)
        try:
            if getattr(storage, "search_columns", None) is not None:
                with qt.new_child("search_columns") as sq:
                    kw = {"deadline": deadline} if deadline else {}
                    if getattr(storage, "supports_search_tracer", False):
                        kw["tracer"] = sq
                    cols = storage.search_columns(
                        filters, min_ts, max_ts, tenant=tenant, **kw)
                    sq.donef("%d series, %d samples", cols.n_series,
                             cols.n_samples)
                cost.add_samples(cols.n_samples)
                raw_names = cols.raw_names
                counts = cols.counts
                ts2, v2 = cols.ts, cols.vals
                if ring is not None and cols.n_series:
                    keep, rerouted = ring.keep_mask(
                        tenant, raw_names,
                        exempt=getattr(storage, "ring_exempt_names", None))
                    if not keep.all():
                        idx = np.flatnonzero(keep)
                        raw_names = [raw_names[i] for i in idx]
                        counts = counts[idx]
                        ts2, v2 = ts2[idx], v2[idx]
                    if rerouted:
                        ringfilter.REROUTE_READS.inc()
                S = len(raw_names)

                def series_arrays(a, b):
                    sel = np.arange(ts2.shape[1])[None, :] < \
                        counts[a:b, None]
                    return ts2[a:b][sel], v2[a:b][sel]
            else:  # per-series storage: adapt
                with qt.new_child("search_series (columnar adapt)") as sq:
                    series = storage.search_series(filters, min_ts, max_ts,
                                                   tenant=tenant)
                    sq.donef("%d series", len(series))
                cost.add_samples(sum(sd.timestamps.size for sd in series))
                raw_names = [getattr(sd, "raw_name", None) or
                             sd.metric_name.marshal() for sd in series]
                if ring is not None and series:
                    keep, rerouted = ring.keep_mask(
                        tenant, raw_names,
                        exempt=getattr(storage, "ring_exempt_names", None))
                    series = [sd for sd, k in zip(series, keep) if k]
                    raw_names = [nm for nm, k in zip(raw_names, keep) if k]
                    if rerouted:
                        ringfilter.REROUTE_READS.inc()
                counts = np.fromiter((sd.timestamps.size for sd in series),
                                     np.int64, len(series))
                S = len(series)

                def series_arrays(a, b):
                    ts_cat = (np.concatenate(
                        [sd.timestamps for sd in series[a:b]])
                        if b > a else np.zeros(0, np.int64))
                    v_cat = (np.concatenate(
                        [sd.values for sd in series[a:b]])
                        if b > a else np.zeros(0, np.float64))
                    return ts_cat, v_cat
        finally:
            costacc.set_current(prev_cost)
        costacc.record_usage(tenant, cost)

        def frames():
            for a in range(0, S, SERIES_PER_FRAME):
                b = min(a + SERIES_PER_FRAME, S)
                w = Writer()
                w.u64(b - a)
                names = raw_names[a:b]
                w.array(np.fromiter((len(nm) for nm in names), np.int64,
                                    b - a))
                w.bytes_(b"".join(names))
                w.array(np.asarray(counts[a:b], np.int64))
                ts_cat, v_cat = series_arrays(a, b)
                w.array(np.asarray(ts_cat, np.int64))
                w.array(np.asarray(v_cat, np.float64))
                yield w
            yield _meta_frame(qt, cost, union_ok, ring_ok=ring is not None)
        return frames()

    def h_search_metric_names(r: Reader):
        tenant = _read_tenant(r)
        filters = _read_filters(r)
        min_ts, max_ts = r.i64(), r.i64()
        names = storage.search_metric_names(filters, min_ts, max_ts,
                                            tenant=tenant)
        w = Writer().u64(len(names))
        for mn in names:
            w.bytes_(mn.marshal())
        return w

    def h_label_names(r: Reader):
        tenant = _read_tenant(r)
        min_ts, max_ts = r.i64(), r.i64()
        names = storage.label_names(min_ts or None, max_ts or None,
                                    tenant=tenant)
        w = Writer().u64(len(names))
        for n in names:
            w.str_(n)
        return w

    def h_label_values(r: Reader):
        tenant = _read_tenant(r)
        key = r.str_()
        min_ts, max_ts = r.i64(), r.i64()
        vals = storage.label_values(key, min_ts or None, max_ts or None,
                                    tenant=tenant)
        w = Writer().u64(len(vals))
        for v in vals:
            w.str_(v)
        return w

    def h_delete_series(r: Reader):
        tenant = _read_tenant(r)
        filters = _read_filters(r)
        return Writer().u64(storage.delete_series(filters, tenant=tenant))

    def h_series_count(r: Reader):
        tenant = _read_tenant(r)
        return Writer().u64(storage.series_count(tenant=tenant))

    def h_tsdb_status(r: Reader):
        import json
        tenant = _read_tenant(r)
        topn = r.u64()
        date_plus1 = r.u64()  # 0 = no date filter
        st = storage.tsdb_status(date_plus1 - 1 if date_plus1 else None, topn,
                                 tenant=tenant)
        return Writer().bytes_(json.dumps(st).encode())

    def h_register_metric_names(r: Reader):
        tenant = _read_tenant(r)
        n = r.u64()
        names = [MetricName.unmarshal(r.bytes_()) for _ in range(n)]
        if hasattr(storage, "register_metric_names"):
            storage.register_metric_names(names, tenant=tenant)
        return Writer().u64(n)

    def h_tenants(r: Reader):
        tenants = storage.tenants() if hasattr(storage, "tenants") \
            else [(0, 0)]
        w = Writer().u64(len(tenants))
        for a, p in tenants:
            w.u64(a).u64(p)
        return w

    def h_tag_value_suffixes(r: Reader):
        tenant = _read_tenant(r)
        min_ts, max_ts = r.i64(), r.i64()
        tag_key = r.str_()
        prefix = r.str_()
        delim = r.str_()
        max_sfx = r.u64()
        sfx = storage.tag_value_suffixes(
            tag_key, prefix, delim or ".", max_sfx,
            min_ts or None, max_ts or None, tenant) \
            if hasattr(storage, "tag_value_suffixes") else []
        w = Writer().u64(len(sfx))
        for s in sfx:
            w.str_(s)
        return w

    def h_metric_names_usage_stats(r: Reader):
        import json
        limit = r.u64()
        le_plus1 = r.u64()  # 0 = no le filter
        items = storage.metric_names_usage_stats(
            limit, le_plus1 - 1 if le_plus1 else None) \
            if hasattr(storage, "metric_names_usage_stats") else []
        return Writer().bytes_(json.dumps(items).encode())

    def h_reset_metric_names_stats(r: Reader):
        if hasattr(storage, "reset_metric_names_stats"):
            storage.reset_metric_names_stats()
        return Writer().u64(1)

    def h_search_metadata(r: Reader):
        import json
        limit = r.u64()
        metric = r.str_()
        md = storage.search_metadata(limit, metric) \
            if hasattr(storage, "search_metadata") else {}
        return Writer().bytes_(json.dumps(md).encode())

    def h_quarantine_report(r: Reader):
        import json
        rep = storage.quarantine_report() \
            if getattr(storage, "quarantine_report", None) is not None \
            else []
        return Writer().bytes_(json.dumps(rep).encode())

    def h_profile(r: Reader):
        """profile_v1: this node's continuous-profiler snapshot (folded
        stacks + sampling meta) so a vmselect can merge the cluster's
        CPU picture with node tags (the quarantineReport_v1 pattern).
        Optional trailing reset flag (old clients don't send it) clears
        this node's aggregates with the read, so a vmselect ?reset=1
        starts a fresh window CLUSTER-wide.  Disabled profiler answers
        an empty snapshot, never an error."""
        import json

        from ..utils import profiler
        reset = bool(r.u64()) if r.remaining else False
        if profiler.configured_hz() > 0:
            profiler.ensure_started()
            snap = profiler.PROFILER.snapshot(reset=reset)
        else:
            snap = {"disabled": True, "stacks": [], "samples": 0}
        return Writer().bytes_(json.dumps(snap).encode())

    def h_health(r: Reader):
        """health_v1: this node's local health verdict — quarantine,
        readonly, merge/work-queue backpressure gauges — as one json
        object (query/sloplane.local_health).  The vmselect roll-up
        fans this and merges; an old node without the method is
        tolerated client-side (verdict "unknown")."""
        import json

        from ..query import sloplane
        return Writer().bytes_(json.dumps(sloplane.local_health(
            storage=storage, role="vmstorage")).encode())

    # -- live resharding: the migrateParts_v1 family -----------------------

    def h_list_parts(r: Reader):
        """listParts_v1: finalized-part inventory for the rebalance
        driver.  Optional flags u64: bit0 = flush pending data to disk
        first, bit1 = force_merge first (compaction shrinks the part
        count a drain must move AND leaves no background merge racing
        the subsequent fetches)."""
        import json
        flags = r.u64() if r.remaining else 0
        if getattr(storage, "list_file_parts", None) is None:
            return Writer().bytes_(json.dumps([]).encode())
        if flags & 2 and hasattr(storage, "force_merge"):
            storage.force_merge()  # force_merge flushes first itself
        elif flags & 1 and hasattr(storage, "force_flush"):
            storage.force_flush()
        return Writer().bytes_(json.dumps(storage.list_file_parts())
                               .encode())

    def h_fetch_part(r: Reader):
        """fetchPart_v1: stream one finalized part — a json meta frame
        (with the file list), one frame per file (header order), then
        the series-registration frame (tsid marshal + name marshal per
        distinct series; metric_ids are node-local, the receiver cannot
        resolve the blocks without them)."""
        import json
        partition = r.str_()
        part = r.str_()
        files, entries, meta = storage.export_part(partition, part)

        def frames():
            yield Writer().bytes_(json.dumps(
                dict(meta, files=[n for n, _ in files])).encode())
            for _, data in files:
                yield Writer().bytes_(data)
            w = Writer().u64(len(entries))
            for tsid_b, raw in entries:
                w.bytes_(tsid_b)
                w.bytes_(raw)
            yield w
        return frames()

    def h_migrate_part(r: Reader):
        """migratePart_v1: adopt a finalized part shipped by the
        rebalance driver — series registrations first, then the bytes
        through the PR-10 crc/quarantine gate under the MergeGate
        (Storage.adopt_part).  Answers (rows, bytes) only after the
        part is durably published, so the driver's subsequent
        removeParts_v1 on the source can never strand acked data."""
        hdr = _json_payload(r.bytes_(), "migratePart_v1 header")
        files = [(str(name), r.bytes_()) for name in hdr["files"]]
        n = r.u64()
        entries = [(r.bytes_(), r.bytes_()) for _ in range(n)]
        if getattr(storage, "adopt_part", None) is None:
            raise RPCError("this node does not support part migration")
        rows, nbytes = storage.adopt_part(
            str(hdr["partition"]), files, entries,
            hdr.get("min_ts"), hdr.get("max_ts"))
        _PARTS_MIGRATED.inc()
        return Writer().u64(int(rows)).u64(int(nbytes))

    def h_remove_parts(r: Reader):
        """removeParts_v1: delist + delete migrated-away parts on the
        source, after the receiver's durable ack."""
        partition = r.str_()
        n = r.u64()
        names = [r.str_() for _ in range(n)]
        if getattr(storage, "remove_parts", None) is None:
            return Writer().u64(0)
        return Writer().u64(storage.remove_parts(partition, names))

    return {
        "writeRows_v1": h_write_rows,
        "writeRowsColumnar_v1": h_write_rows_columnar,
        "listParts_v1": h_list_parts,
        "fetchPart_v1": h_fetch_part,
        "migratePart_v1": h_migrate_part,
        "removeParts_v1": h_remove_parts,
        "isReadOnly_v1": h_is_readonly,
        "search_v1": h_search,
        "searchColumns_v1": h_search_columns,
        "searchMetricNames_v1": h_search_metric_names,
        "labelNames_v1": h_label_names,
        "labelValues_v1": h_label_values,
        "deleteSeries_v1": h_delete_series,
        "seriesCount_v1": h_series_count,
        "tsdbStatus_v1": h_tsdb_status,
        "registerMetricNames_v1": h_register_metric_names,
        "tenants_v1": h_tenants,
        "tagValueSuffixes_v1": h_tag_value_suffixes,
        "metricNamesUsageStats_v1": h_metric_names_usage_stats,
        "resetMetricNamesStats_v1": h_reset_metric_names_stats,
        "searchMetadata_v1": h_search_metadata,
        "quarantineReport_v1": h_quarantine_report,
        "profile_v1": h_profile,
        "health_v1": h_health,
    }


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

class StorageNodeClient:
    def __init__(self, host: str, insert_port: int, select_port: int,
                 name: str | None = None, timeout: float = 10.0):
        self.name = name or f"{host}:{insert_port}"
        self.insert = RPCClient(host, insert_port, HELLO_INSERT,
                                timeout=timeout)
        # select plane gets a CONNECTION POOL (VM_RPC_SELECT_CONNS,
        # default 4): concurrent queries to one node must not serialize
        # on a single TCP connection — head-of-line blocking there both
        # throttles reads and hides concurrent load from the node-side
        # TenantGate.  The insert plane stays single-connection: writes
        # are batched and sequenced per node by the router anyway.
        self.select = RPCClientPool(host, select_port, HELLO_SELECT,
                                    timeout=timeout)
        self.down_until = 0.0

    @property
    def healthy(self) -> bool:
        return time.monotonic() >= self.down_until

    def mark_down(self, seconds: float = 2.0):
        self.down_until = time.monotonic() + seconds
        logger.warnf("storage node %s marked down for %.1fs", self.name,
                     seconds)

    def write_rows(self, rows: list[tuple[bytes, int, float]],
                   tenant=(0, 0), reroute: bool = False):
        """``reroute=True`` marks the batch as landing OFF its ring
        owners (an owner was down): the receiving node records the
        series as always-served so the ring read filter can never hide
        what may be their only copy (old nodes ignore the flag — they
        never filter by ring either)."""
        w = _write_tenant(Writer(), tenant).u64(len(rows))
        for raw, ts, val in rows:
            w.bytes_(raw)
            w.i64(int(ts))
            w.f64(float(val))
        if reroute:
            w.u64(1)
        self.insert.call("writeRows_v1", w)

    supports_columnar_write = True  # cleared on first unknown-method error

    def write_rows_columnar(self, keybuf: bytes, key_off, key_len,
                            tss, vals, tenant=(0, 0),
                            reroute: bool = False) -> int:
        """Ship a ColumnarRows shard raw (writeRowsColumnar_v1); falls
        back to per-row writeRows_v1 against old storage nodes."""
        if self.supports_columnar_write:
            w = _write_tenant(Writer(), tenant)
            w.bytes_(keybuf)
            w.array(np.asarray(key_off, np.int64))
            w.array(np.asarray(key_len, np.int64))
            w.array(np.asarray(tss, np.int64))
            w.array(np.asarray(vals, np.float64))
            if reroute:
                w.u64(1)
            try:
                return self.insert.call("writeRowsColumnar_v1", w).u64()
            except RPCError as e:
                if "unknown rpc method" not in str(e):
                    raise
                self.supports_columnar_write = False
        # legacy node: canonical-marshal rows (slow path)
        from ..ingest.parsers import labels_from_series_key
        mv = memoryview(keybuf)
        rows = []
        for o, ln, ts, val in zip(key_off, key_len, tss, vals):
            key = bytes(mv[int(o):int(o) + int(ln)])
            try:
                mn = MetricName.from_labels(labels_from_series_key(key))
            except ValueError:
                continue
            rows.append((mn.marshal(), int(ts), float(val)))
        self.write_rows(rows, tenant, reroute=reroute)
        return len(rows)

    @staticmethod
    def _budget_ms(deadline: float) -> int:
        """Remaining budget to SHIP inside the request (storage-side
        deadline enforcement): the receiving vmstorage re-anchors it on
        its own monotonic clock, so wall-clock skew between nodes never
        matters.  0 = no deadline; an already-exhausted budget ships as
        1ms so the node aborts at its first check instead of scanning."""
        if not deadline:
            return 0
        return max(int((deadline - time.monotonic()) * 1e3), 1)

    @staticmethod
    def _wire_deadline(deadline: float) -> float:
        """Socket-level cutoff: the shipped budget plus bounded slack
        (20% of remaining, clamped to [0.1s, 2s]).  A budget-honoring
        vmstorage aborts server-side within ~one check interval of the
        SHIPPED cutoff, so its typed deadline error arrives before the
        socket gives up (no node-down marking, loud abort accounting);
        a dead/stalled node still costs at most ~1.2 deadlines, never a
        fixed per-hop timeout (the PR-9 property, slightly relaxed)."""
        if not deadline:
            return 0.0
        remaining = deadline - time.monotonic()
        return deadline + min(max(0.2 * remaining, 0.1), 2.0)

    @staticmethod
    def _read_meta(r: Reader, tracer) -> tuple[bool, dict | None]:
        """Parse the trailing metadata frame: (partial, extras).  Old
        servers send [partial][trace-when-tracing] — extras comes back
        None (degraded cost accounting, no union ack).  New servers
        always send [partial][trace-or-empty][extras-json]; the second
        bytes field present is what disambiguates the dialects."""
        partial = bool(r.u64())
        extras = None
        if r.remaining:
            import json
            b1 = r.bytes_()
            if r.remaining:
                # new dialect: b1 was the (possibly empty) trace slot
                try:
                    extras = json.loads(r.bytes_())
                except (ValueError, RPCError):
                    extras = None
            if b1:
                try:
                    tracer.add_remote(json.loads(b1))
                except (ValueError, RPCError):
                    pass  # malformed remote trace never fails the search
        return partial, extras

    @staticmethod
    def _finish_meta(extras: dict | None, or_sets) -> bool:
        """Common metadata-frame epilogue: merge the node's shipped cost
        frame into the current query's CostTracker (None degrades to
        partial cost accounting, never an error) and answer whether the
        shipped or_sets were ACKed as applied — False means the peer is
        an old/union-less node and the caller must fall back to one
        legacy call per set."""
        tr = costacc.current()
        if tr is not None:
            tr.merge_remote((extras or {}).get("cost"))
        if not or_sets:
            return True
        return bool((extras or {}).get("filterUnion"))

    def search_series(self, filters, min_ts, max_ts, tenant=(0, 0),
                      tracer=querytracer.NOP, deadline: float = 0.0,
                      ring=None):
        """Returns (series_list, remote_partial).  Selector-level `or`
        unions (filters = list of sets) ship the extra sets as the
        trailing or_sets field; a peer that doesn't ack the union gets
        one legacy call per remaining set instead (duplicate series
        across sets collapse in the caller's assemble, the same way
        replica overlap does).  ``ring`` (a ringfilter.RingConfig with
        this node's self index) asks the node to serve only the series
        it owns under the caller's hash view — unacked peers return
        everything and the caller's dedup collapses it."""
        first, extra_sets = _split_filter_sets(filters)
        w = _write_tenant(Writer(), tenant)
        _write_filters(w, first)
        w.i64(min_ts).i64(max_ts)
        w.u64(1 if tracer.enabled else 0)
        w.u64(self._budget_ms(deadline))
        if extra_sets or ring is not None:
            w.u64(len(extra_sets))
            for fs in extra_sets:
                _write_filters(w, fs)
        if ring is not None:
            w.bytes_(ring.to_json())
        out = []
        partial = False
        extras = None
        rpc_bytes = 0
        for r in self.select.call_stream("search_v1", w,
                                         deadline=self._wire_deadline(
                                             deadline)):
            rpc_bytes += len(r.data)
            n = r.u64()
            if n == (1 << 32) - 1:  # trailing metadata frame
                partial, extras = self._read_meta(r, tracer)
                continue
            for _ in range(n):
                mn = MetricName.unmarshal(r.bytes_())
                ts = r.array()
                vals = r.array()
                out.append((mn, ts, vals))
        costacc.add_rpc_bytes(rpc_bytes)
        if not self._finish_meta(extras, extra_sets):
            # union-less peer: it served only the first set — fetch the
            # remaining sets one legacy call at a time and concatenate
            for fs in extra_sets:
                more, p2 = self.search_series(fs, min_ts, max_ts, tenant,
                                              tracer=tracer,
                                              deadline=deadline, ring=ring)
                out.extend(more)
                partial = partial or p2
        return out, partial

    supports_columnar_read = True  # cleared on first unknown-method error

    def search_columns(self, filters, min_ts, max_ts, tenant=(0, 0),
                       tracer=querytracer.NOP, deadline: float = 0.0,
                       ring=None):
        """Columnar read plane: returns (raw_names list, counts int64[],
        ts_cat int64[], vals_cat float64[], remote_partial). Falls back to
        search_v1 against old nodes (same return shape).  `deadline` is
        the caller's time.monotonic() cutoff, enforced per socket
        operation by the RPC client; ``ring`` as in search_series."""
        if self.supports_columnar_read:
            first, extra_sets = _split_filter_sets(filters)
            w = _write_tenant(Writer(), tenant)
            _write_filters(w, first)
            w.i64(min_ts).i64(max_ts)
            w.u64(1 if tracer.enabled else 0)
            w.u64(self._budget_ms(deadline))
            if extra_sets or ring is not None:
                w.u64(len(extra_sets))
                for fs in extra_sets:
                    _write_filters(w, fs)
            if ring is not None:
                w.bytes_(ring.to_json())
            try:
                frames = self.select.call_stream(
                    "searchColumns_v1", w,
                    deadline=self._wire_deadline(deadline))
            except RPCError as e:
                if "unknown rpc method" not in str(e):
                    raise
                self.supports_columnar_read = False
                frames = None
            if frames is not None:
                names: list[bytes] = []
                cnt_parts, ts_parts, val_parts = [], [], []
                partial = False
                extras = None
                rpc_bytes = 0
                for r in frames:
                    rpc_bytes += len(r.data)
                    sf = r.u64()
                    if sf == (1 << 32) - 1:  # trailing metadata frame
                        partial, extras = self._read_meta(r, tracer)
                        continue
                    lens = r.array()
                    namebuf = r.bytes_()
                    off = 0
                    for ln in lens:
                        names.append(namebuf[off:off + int(ln)])
                        off += int(ln)
                    cnt_parts.append(r.array())
                    ts_parts.append(r.array())
                    val_parts.append(r.array())
                costacc.add_rpc_bytes(rpc_bytes)
                if not self._finish_meta(extras, extra_sets):
                    # union-less peer served only the first set: pull
                    # the remaining sets legacy-style and concatenate —
                    # duplicate series collapse in the caller's
                    # assemble exactly like replica overlap
                    for fs in extra_sets:
                        n2, c2, t2, v2, p2 = self.search_columns(
                            fs, min_ts, max_ts, tenant, tracer=tracer,
                            deadline=deadline, ring=ring)
                        names.extend(n2)
                        cnt_parts.append(c2)
                        ts_parts.append(t2)
                        val_parts.append(v2)
                        partial = partial or p2
                cat = (lambda ps, dt: np.concatenate(ps) if ps
                       else np.zeros(0, dt))
                return (names, cat(cnt_parts, np.int64),
                        cat(ts_parts, np.int64),
                        cat(val_parts, np.float64), partial)
        series, partial = self.search_series(filters, min_ts, max_ts,
                                             tenant, tracer=tracer,
                                             deadline=deadline, ring=ring)
        names = [mn.marshal() for mn, _, _ in series]
        counts = np.fromiter((ts.size for _, ts, _ in series), np.int64,
                             len(series))
        ts_cat = (np.concatenate([ts for _, ts, _ in series])
                  if series else np.zeros(0, np.int64))
        val_cat = (np.concatenate([v for _, _, v in series])
                   if series else np.zeros(0, np.float64))
        return names, counts, ts_cat, val_cat, partial

    def search_metric_names(self, filters, min_ts, max_ts, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant)
        _write_filters(w, filters)
        w.i64(min_ts).i64(max_ts)
        r = self.select.call("searchMetricNames_v1", w)
        return [MetricName.unmarshal(r.bytes_()) for _ in range(r.u64())]

    def label_names(self, min_ts, max_ts, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant).i64(min_ts or 0).i64(max_ts or 0)
        r = self.select.call("labelNames_v1", w)
        return [r.str_() for _ in range(r.u64())]

    def label_values(self, key, min_ts, max_ts, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant).str_(key)
        w.i64(min_ts or 0).i64(max_ts or 0)
        r = self.select.call("labelValues_v1", w)
        return [r.str_() for _ in range(r.u64())]

    def delete_series(self, filters, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant)
        _write_filters(w, filters)
        return self.select.call("deleteSeries_v1", w).u64()

    def series_count(self, tenant=(0, 0)):
        return self.select.call("seriesCount_v1",
                                _write_tenant(Writer(), tenant)).u64()

    def tsdb_status(self, topn, date=None, tenant=(0, 0)):
        import json
        w = _write_tenant(Writer(), tenant).u64(topn)
        w.u64(0 if date is None else date + 1)
        r = self.select.call("tsdbStatus_v1", w)
        return _json_payload(r.bytes_(), "tsdbStatus_v1")

    def tenants(self):
        r = self.select.call("tenants_v1", Writer())
        return [(r.u64(), r.u64()) for _ in range(r.u64())]

    def tag_value_suffixes(self, tag_key, prefix, delimiter=".",
                           max_suffixes=100_000, min_ts=None, max_ts=None,
                           tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant)
        w.i64(min_ts or 0).i64(max_ts or 0)
        w.str_(tag_key).str_(prefix).str_(delimiter)
        w.u64(max_suffixes)
        r = self.select.call("tagValueSuffixes_v1", w)
        return [r.str_() for _ in range(r.u64())]

    def metric_names_usage_stats(self, limit=1000, le=None):
        w = Writer().u64(limit).u64(0 if le is None else le + 1)
        r = self.select.call("metricNamesUsageStats_v1", w)
        return _json_payload(r.bytes_(), "metricNamesUsageStats_v1")

    def reset_metric_names_stats(self):
        self.select.call("resetMetricNamesStats_v1", Writer())

    def search_metadata(self, limit=1000, metric=""):
        w = Writer().u64(limit).str_(metric)
        r = self.select.call("searchMetadata_v1", w)
        return _json_payload(r.bytes_(), "searchMetadata_v1")

    def quarantine_report(self):
        try:
            r = self.select.call("quarantineReport_v1", Writer())
        except RPCError as e:
            if "unknown rpc method" in str(e):
                return []  # pre-quarantine storage node
            raise
        return _json_payload(r.bytes_(), "quarantineReport_v1")

    def profile(self, reset: bool = False) -> dict | None:
        """This node's continuous-profiler snapshot; None from an
        old node without profile_v1 (tolerated, the merge just lacks
        that node's stacks).  `reset` clears the node's aggregates
        atomically with the read (old nodes ignore the trailing flag —
        their window simply doesn't reset)."""
        import json
        try:
            r = self.select.call("profile_v1",
                                 Writer().u64(1 if reset else 0))
        except RPCError as e:
            if "unknown rpc method" in str(e):
                return None  # pre-profiler storage node
            raise
        return json.loads(r.bytes_())

    def health(self) -> dict | None:
        """This node's health_v1 verdict; None from an old node
        without the method (tolerated — the roll-up shows the node as
        verdict "unknown" instead of failing the whole report)."""
        import json
        try:
            r = self.select.call("health_v1", Writer())
        except RPCError as e:
            if "unknown rpc method" in str(e):
                return None  # pre-health storage node
            raise
        return json.loads(r.bytes_())

    # -- live resharding (part migration) -------------------------------

    def list_parts(self, flush: bool = False,
                   merge: bool = False) -> list[dict]:
        """Finalized-part inventory on this node (listParts_v1);
        ``flush``/``merge`` compact first — a drain wants few parts and
        no background merge racing the fetches."""
        import json
        w = Writer().u64((1 if flush else 0) | (2 if merge else 0))
        return json.loads(self.select.call("listParts_v1", w).bytes_())

    def fetch_part(self, partition: str, part: str):
        """Pull one finalized part (fetchPart_v1): returns
        (files [(name, bytes)], entries [(tsid, name)], meta dict)."""
        import json
        w = Writer().str_(partition).str_(part)
        frames = list(self.select.call_stream("fetchPart_v1", w))
        hdr = json.loads(frames[0].bytes_())
        fnames = hdr.pop("files")
        files = [(fnames[i], frames[1 + i].bytes_())
                 for i in range(len(fnames))]
        reg = frames[1 + len(fnames)]
        n = reg.u64()
        entries = [(reg.bytes_(), reg.bytes_()) for _ in range(n)]
        return files, entries, hdr

    def migrate_part(self, partition: str, files, entries,
                     meta=None) -> tuple[int, int]:
        """Push one finalized part into this node (migratePart_v1);
        returns (rows, bytes) after the node's durable publish."""
        import json
        meta = meta or {}
        w = Writer().bytes_(json.dumps(
            {"partition": partition, "files": [n for n, _ in files],
             "min_ts": meta.get("min_ts"),
             "max_ts": meta.get("max_ts")}).encode())
        for _, data in files:
            w.bytes_(data)
        w.u64(len(entries))
        for tsid_b, raw in entries:
            w.bytes_(tsid_b)
            w.bytes_(raw)
        r = self.select.call("migratePart_v1", w)
        return r.u64(), r.u64()

    def remove_parts(self, partition: str, names: list[str]) -> int:
        w = Writer().str_(partition).u64(len(names))
        for n in names:
            w.str_(n)
        return self.select.call("removeParts_v1", w).u64()

    def close(self):
        self.insert.close()
        self.select.close()


# ---------------------------------------------------------------------------
# ClusterStorage: the vminsert/vmselect composite backend
# ---------------------------------------------------------------------------

def parse_node_spec(spec: str) -> tuple[str, int, int]:
    """-storageNode spec -> (host, insert_port, select_port).  The
    3-field ``host:insertPort:selectPort`` form addresses a vmstorage;
    the 2-field ``host:port`` form addresses a multilevel child
    (a vmselect/vminsert -clusternativeListenAddr speaks ONE plane, so
    the same port serves both halves — the unused half connects
    lazily and is never dialed)."""
    fields = spec.rsplit(":", 2)
    if len(fields) == 3 and fields[1].isdigit() and fields[2].isdigit():
        return fields[0], int(fields[1]), int(fields[2])
    host, _, port = spec.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad storage node spec {spec!r} (want "
                         f"host:insertPort:selectPort or host:port)")
    return host, int(port), int(port)


def _node_name_of(spec: str) -> str:
    """Accept a full node spec OR a bare node name for admin calls."""
    host, ip_, _ = parse_node_spec(spec)
    return f"{host}:{ip_}"


def register_cluster_admin(srv, cluster: "ClusterStorage") -> None:
    """``/internal/cluster/*`` admin surface on vminsert/vmselect —
    the no-restart elasticity endpoints (ROADMAP item 3b) the chaos
    harness, tools and operators drive:

    - ``GET  /internal/cluster/nodes``                  topology + health
    - ``POST /internal/cluster/join?node=h:ip:sp[&rebalance=1]``
    - ``POST /internal/cluster/drain?node=h:ip[&remove=0]``
    - ``POST /internal/cluster/remove?node=h:ip``       (already-empty node)
    - ``POST /internal/cluster/rebalance?node=h:ip``
    - ``POST /internal/cluster/ring_filter?enable=0|1``

    Each process owns its view: a join/drain is announced to the
    vmselect AND the vminsert (reads first for joins, writes first for
    drains — the README walks the orderings)."""
    from ..httpapi.server import Response

    def ok(data):
        return Response.json({"status": "success", "data": data})

    def h_nodes(req):
        return ok(cluster.cluster_status())

    def h_join(req):
        spec = req.arg("node")
        if not spec:
            return Response.error("missing 'node' arg")
        try:
            out = cluster.add_node(spec)
            if req.arg("rebalance") == "1":
                out["rebalance"] = cluster.rebalance_to(
                    _node_name_of(spec))
        except (ValueError, KeyError) as e:
            return Response.error(str(e))
        except (OSError, RPCError, ConnectionError) as e:
            return Response.error(f"join failed: {e}", 503, "unavailable")
        return ok(out)

    def h_drain(req):
        spec = req.arg("node")
        if not spec:
            return Response.error("missing 'node' arg")
        try:
            return ok(cluster.drain_node(
                _node_name_of(spec), remove=req.arg("remove", "1") != "0"))
        except (ValueError, KeyError) as e:
            return Response.error(str(e))
        except (OSError, RPCError, ConnectionError) as e:
            return Response.error(f"drain failed: {e}", 503, "unavailable")

    def h_remove(req):
        spec = req.arg("node")
        if not spec:
            return Response.error("missing 'node' arg")
        try:
            return ok(cluster.remove_node(_node_name_of(spec)))
        except (ValueError, KeyError) as e:
            return Response.error(str(e))

    def h_rebalance(req):
        spec = req.arg("node")
        if not spec:
            return Response.error("missing 'node' arg")
        try:
            return ok(cluster.rebalance_to(_node_name_of(spec)))
        except (ValueError, KeyError) as e:
            return Response.error(str(e))
        except (OSError, RPCError, ConnectionError) as e:
            return Response.error(f"rebalance failed: {e}", 503,
                                  "unavailable")

    def h_ring_filter(req):
        en = req.arg("enable")
        if en is not None and en != "":
            cluster.set_ring_filter(en != "0")
        return ok({"ringFilter": cluster.ring_filter_active})

    srv.route("/internal/cluster/nodes", h_nodes)
    srv.route("/internal/cluster/join", h_join)
    srv.route("/internal/cluster/drain", h_drain)
    srv.route("/internal/cluster/remove", h_remove)
    srv.route("/internal/cluster/rebalance", h_rebalance)
    srv.route("/internal/cluster/ring_filter", h_ring_filter)


def start_native_server(addr: str, hello: bytes, storage,
                        rate_limiter=None):
    """Start a cluster-native RPC server exposing `storage` (used by the
    -clusternativeListenAddr multilevel flags on vminsert/vmselect)."""
    from .rpc import RPCServer
    host, _, port = addr.rpartition(":")
    srv = RPCServer(host or "0.0.0.0", int(port), hello,
                    make_storage_handlers(storage, rate_limiter))
    srv.start()
    return srv


_MISSING = object()


class ClusterStorage:
    """Shard writes / fan-out reads across storage nodes."""

    def __init__(self, nodes: list[StorageNodeClient],
                 replication_factor: int = 1,
                 deny_partial_response: bool = False):
        # (node list, ring) swap together in ONE attribute assignment so
        # a topology change (join/drain) can never hand an in-flight
        # batch a ring index into a different node list
        self._topology = (list(nodes),
                          ConsistentHash([n.name for n in nodes]))
        self.rf = replication_factor
        self.deny_partial = deny_partial_response
        #: nodes being drained: excluded from NEW writes while their
        #: parts migrate off (reads keep hitting them until removal)
        self._draining: set[str] = set()
        #: rf>1 + a topology change suspends ring-ownership read
        #: filtering on this router (full fan-out + dedup): with
        #: replicas, ownership under the NEW ring does not imply
        #: possession until a full anti-entropy pass — rf=1 stays
        #: filtered through every transition (ownership == placement
        #: there, and orphan/exemption rules cover moved data)
        self._ring_suspended = False
        # per-tenant raw-key -> send-key verdicts (relabel applied once
        # per distinct series key; see add_rows_columnar)
        self._key_verdicts: dict[tuple, dict] = {}
        from ..query.rollup_result_cache import next_storage_token
        self.cache_token = next_storage_token()
        # per-instance counters (metrics() is per-cluster; tests build
        # several ClusterStorages per process), mirrored into the process
        # registry below on every inc
        self._rows_sent = metricslib.Counter("rows_sent")
        self._reroutes = metricslib.Counter("reroutes")
        self._rows_sent_counter = metricslib.REGISTRY.counter(
            "vm_rpc_rows_sent_total")
        self._reroutes_counter = metricslib.REGISTRY.counter(
            "vm_rpc_rows_rerouted_total")
        # read fan-outs launched (one per search, NOT one per node): the
        # matstream fleet guard asserts this stays flat as subscribers
        # grow — N watchers of one expression must cost ONE fan-out per
        # interval
        self._search_fanouts = metricslib.Counter("search_fanouts")
        self._search_fanouts_counter = metricslib.REGISTRY.counter(
            "vm_cluster_search_fanouts_total")
        self._lock = make_lock("parallel.VMSelect._lock")
        # partial-result tracking is per handler thread and STICKY across
        # the fanouts of one query (a shared flag would race between
        # concurrent queries and be cleared by a later clean fanout)
        self._tls = threading.local()

    @property
    def nodes(self) -> list[StorageNodeClient]:
        return self._topology[0]

    @property
    def ch(self) -> ConsistentHash:
        return self._topology[1]

    @property
    def rows_sent(self) -> int:
        return self._rows_sent.get()

    @property
    def reroutes(self) -> int:
        return self._reroutes.get()

    def reset_partial(self):
        # threading.local: each request thread reads/writes only its own
        # slot, so cross-root access is partitioned by construction
        self._tls.partial = False  # vmt: disable=VMT015

    @property
    def last_partial(self) -> bool:
        return bool(getattr(self._tls, "partial", False))

    # -- write path (vminsert) ------------------------------------------

    def _write_excluded(self, nodes) -> set[int]:
        """Node indexes NEW writes must avoid: down + draining."""
        return {i for i, n in enumerate(nodes)
                if not n.healthy or n.name in self._draining}

    def add_rows(self, rows, tenant=(0, 0)) -> int:
        """rows: [(labels-dict-or-MetricName, ts, value)] — shard by
        (tenant, canonical metric name), replicate RF-ways, reroute on
        failure."""
        import struct as _struct
        tkey = _struct.pack(">II", tenant[0], tenant[1])
        nodes, ch = self._topology
        per_node: dict[int, list] = {}
        excluded = self._write_excluded(nodes)
        for labels, ts, val in rows:
            mn = labels if isinstance(labels, MetricName) else \
                MetricName.from_dict(labels) if isinstance(labels, dict) \
                else MetricName.from_labels(labels)
            raw = mn.marshal()
            targets = ch.nodes_for_key(tkey + raw, self.rf, excluded)
            if not targets:
                # all nodes down: try everything anyway
                targets = ch.nodes_for_key(tkey + raw, self.rf, set())
            for i in targets:
                per_node.setdefault(i, []).append((raw, ts, val))
        sent = 0
        for i, node_rows in per_node.items():
            node = nodes[i]
            try:
                node.write_rows(node_rows, tenant)
                sent += len(node_rows)
            except (OSError, RPCError, ConnectionError) as e:
                node.mark_down()
                self._reroutes.inc()
                self._reroutes_counter.inc()
                # regroup the failed batch by alternate node: one RPC per
                # target, not one per row
                ex = self._write_excluded(nodes) | {i}
                alt_batches: dict[int, list] = {}
                for row in node_rows:
                    alt = ch.nodes_for_key(tkey + row[0], 1, ex)
                    if not alt:
                        raise RPCError(
                            f"no healthy storage nodes for reroute: {e}")
                    alt_batches.setdefault(alt[0], []).append(row)
                for j, batch in alt_batches.items():
                    # reroute=True: the receiver marks these series
                    # always-served (ring-exempt) — it may now hold
                    # their only copy of this window
                    nodes[j].write_rows(batch, tenant, reroute=True)
                    sent += len(batch)
        self._rows_sent.inc(sent)
        self._rows_sent_counter.inc(sent)
        return len(rows)

    # columnar ingest: the vminsert HTTP fast path (native text parse ->
    # ColumnarRows) ships shards RAW over writeRowsColumnar_v1 — the
    # storage node's native key map resolves whole batches, no per-row
    # Python on either side (the r4 verdict measured the per-row RPC
    # path at <2k rows/s; this is the fix)
    supports_columnar = True
    _MAX_KEY_VERDICTS = 1 << 20

    def add_rows_columnar(self, cr, tenant=(0, 0), transform=None,
                          drop_stats: dict | None = None) -> int:
        import struct as _struct
        tkey = _struct.pack(">II", tenant[0], tenant[1])
        nodes, ch = self._topology
        n_rows = len(cr)
        if n_rows == 0:
            return 0
        key_off = np.asarray(cr.key_off, np.int64)
        key_len = np.asarray(cr.key_len, np.int64)
        mv = memoryview(cr.keybuf)
        # same (offset, len) => same key bytes: unique-ify cheaply first
        # (the native parser reuses key slots for repeat series)
        packed = key_off * (np.int64(1) << 24) + key_len
        uniq, inv = np.unique(packed, return_inverse=True)
        # rows grouped by unique key
        order = np.argsort(inv, kind="stable")
        bounds = np.searchsorted(inv[order], np.arange(uniq.size + 1))
        # verdict cache, TRANSFORM PATH ONLY: transform is a pure function
        # of the label set, so each distinct key is parsed/relabeled ONCE
        # across batches. The transform=None path (multilevel RPC ingest,
        # where relabeling already happened upstream) passes keys through
        # untouched and must NOT share verdicts — a cached no-transform
        # passthrough would silently skip a later HTTP request's relabel
        # rules (and vice versa).
        vc = None
        if transform is not None:
            with self._lock:
                vc = self._key_verdicts.setdefault(tenant, {})
        excluded = self._write_excluded(nodes)
        # per-node shards: node -> (key bytes list, PLACEMENT marshal
        # list — reroutes re-place by it — and row index arrays)
        shards: dict[int, tuple[list, list, list]] = {}
        # series whose transformed labels don't survive the text-key
        # round-trip (names with key-syntax bytes): per-row canonical path
        legacy_shards: dict[int, list] = {}
        dropped_transform = dropped_malformed = 0
        for j in range(uniq.size):
            o = int(uniq[j] >> 24)
            ln = int(uniq[j] & ((1 << 24) - 1))
            key = bytes(mv[o:o + ln])
            if transform is None:
                # placement by the CANONICAL marshal (memoized per
                # distinct key): both write paths and the ring read
                # filter must agree on one shard key, and spelling
                # variants of one series must co-locate
                sk = ("cols", key, placement_marshal(key))
            else:
                sk = vc.get(key, _MISSING)
                if sk is _MISSING:
                    sk = self._judge_key(key, transform)
                    if len(vc) >= self._MAX_KEY_VERDICTS:
                        vc.clear()
                    vc[key] = sk
            rows_j = order[bounds[j]:bounds[j + 1]]
            if sk is False:
                dropped_malformed += rows_j.size
                continue
            if sk is None:
                dropped_transform += rows_j.size
                continue
            if sk[0] == "legacy":  # ("legacy", canonical_marshal)
                raw = sk[1]
                targets = ch.nodes_for_key(tkey + raw, self.rf, excluded)
                if not targets:
                    targets = ch.nodes_for_key(tkey + raw, self.rf, set())
                for i in targets:
                    rl = legacy_shards.setdefault(i, [])
                    for rix in rows_j:
                        rl.append((raw, int(cr.tss[rix]),
                                   float(cr.values[rix])))
                continue
            _, send_key, pm = sk
            targets = ch.nodes_for_key(tkey + pm, self.rf, excluded)
            if not targets:
                targets = ch.nodes_for_key(tkey + pm, self.rf, set())
            for i in targets:
                keys, pkeys, rowsl = shards.setdefault(i, ([], [], []))
                keys.append(send_key)
                pkeys.append(pm)
                rowsl.append(rows_j)
        if drop_stats is not None:
            if dropped_transform:
                drop_stats["transform"] = drop_stats.get(
                    "transform", 0) + int(dropped_transform)
            if dropped_malformed:
                drop_stats["malformed"] = drop_stats.get(
                    "malformed", 0) + int(dropped_malformed)
        tss = np.asarray(cr.tss, np.int64)
        vals = np.asarray(cr.values, np.float64)
        sent = 0
        for i, rows in legacy_shards.items():
            try:
                nodes[i].write_rows(rows, tenant)
                sent += len(rows)
            except (OSError, RPCError, ConnectionError) as e:
                nodes[i].mark_down()
                self._reroutes.inc()
                self._reroutes_counter.inc()
                ex = self._write_excluded(nodes) | {i}
                alt_batches: dict[int, list] = {}
                for row in rows:
                    alt = ch.nodes_for_key(tkey + row[0], 1, ex)
                    if not alt:
                        raise RPCError(
                            f"no healthy storage nodes for reroute: {e}")
                    alt_batches.setdefault(alt[0], []).append(row)
                for j2, batch in alt_batches.items():
                    nodes[j2].write_rows(batch, tenant, reroute=True)
                    sent += len(batch)
        for i, (keys, pkeys, rowsl) in shards.items():
            try:
                sent += self._send_columnar_shard(nodes[i], keys,
                                                  rowsl, tss, vals, tenant)
            except (OSError, RPCError, ConnectionError) as e:
                nodes[i].mark_down()
                self._reroutes.inc()
                self._reroutes_counter.inc()
                ex = self._write_excluded(nodes) | {i}
                alt_shards: dict[int, tuple[list, list]] = {}
                for key, pm, rows_j in zip(keys, pkeys, rowsl):
                    alt = ch.nodes_for_key(tkey + pm, 1, ex)
                    if not alt:
                        raise RPCError(
                            f"no healthy storage nodes for reroute: {e}")
                    ks, rl = alt_shards.setdefault(alt[0], ([], []))
                    ks.append(key)
                    rl.append(rows_j)
                for j2, (ks, rl) in alt_shards.items():
                    sent += self._send_columnar_shard(nodes[j2], ks,
                                                      rl, tss, vals, tenant,
                                                      reroute=True)
        self._rows_sent.inc(sent)
        self._rows_sent_counter.inc(sent)
        return int(n_rows - dropped_transform - dropped_malformed)

    @staticmethod
    def _judge_key(key: bytes, transform):
        """One-time verdict for a distinct raw key under `transform`:
        ("cols", send_key, placement_marshal) = ship the (relabeled)
        text key columnar, shard by the canonical marshal; None =
        dropped by the transform; False = malformed; ("legacy",
        marshal) = the transformed labels don't survive the text
        round-trip (key-syntax bytes in names) and must go per-row
        canonical."""
        from ..ingest.parsers import (labels_from_series_key,
                                      series_key_from_labels)
        try:
            labels = labels_from_series_key(key)
        except ValueError:
            return False
        labels = transform(labels)
        if not labels:
            return None
        sk = series_key_from_labels(labels)
        try:
            back = labels_from_series_key(sk)
        except ValueError:
            back = None
        canon = sorted((k.decode() if isinstance(k, bytes) else k,
                        v.decode() if isinstance(v, bytes) else v)
                       for k, v in labels if v)
        marshal = MetricName.from_labels(labels).marshal()
        if back is None or sorted(back) != canon:
            return ("legacy", marshal)
        return ("cols", sk, marshal)

    def reset_columnar_spaces(self) -> None:
        """Invalidate cached raw-key -> send-key verdicts (call after the
        ingest transform config — relabel rules, series limits —
        changes)."""
        with self._lock:
            self._key_verdicts = {}

    def _send_columnar_shard(self, node, keys, rowsl, tss, vals,
                             tenant, reroute: bool = False) -> int:
        """One writeRowsColumnar_v1 call: build the shard's keybuf +
        per-row offset columns from (key, row-index-array) pairs."""
        counts = np.fromiter((r.size for r in rowsl), np.int64, len(rowsl))
        klens = np.fromiter((len(k) for k in keys), np.int64, len(keys))
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
        row_order = (np.concatenate(rowsl) if rowsl
                     else np.zeros(0, np.int64))
        node.write_rows_columnar(
            b"".join(keys), np.repeat(koffs, counts),
            np.repeat(klens, counts), tss[row_order], vals[row_order],
            tenant, reroute=reroute)
        return int(row_order.size)

    # -- read path (vmselect) -------------------------------------------

    def _fanout(self, fn, replica_covered_ok: bool = True):
        """Run fn(node) on every healthy node concurrently (scatter-gather;
        the reference fans out to all vmstorage nodes in parallel) via the
        shared work pool (utils/workpool) instead of spawning fresh
        threads per query — RPC reads release the GIL, and a fanout task
        hitting an in-process LocalNode may fan its own part collection
        across the same pool (the pool's helping waiters make that
        nesting deadlock-free). Trade-off: network waits share the
        cpu_count-sized pool with decode units, so very wide clusters
        (nodes >> cores) serialize some per-node waits; at this port's
        node counts that is cheaper than a thread per node per query,
        and the helping caller always makes progress. Known-down nodes
        are skipped but still count toward the partial flag.

        Replica-aware partial accounting (the vm_deny_partial-style key
        coverage): with rendezvous placement every key's RF-target set
        holds RF DISTINCT nodes, so when fewer than RF distinct nodes
        failed AND every survivor responded, each of the failed nodes'
        hash ranges is provably served by a surviving responder — the
        result is complete, not partial; ``vm_partial_avoided_total``
        ticks instead.  ``replica_covered_ok=False`` (mutating fanouts
        like deleteSeries, where a missed node means a missed tombstone
        regardless of read coverage) keeps the strict accounting."""
        results: list = []
        errors: list = []
        lock = make_lock("parallel.cluster_api.fanout_lock")
        # per-thread record of WHICH nodes failed this fan-out: the
        # ring-filtered read path re-fans (or goes honestly partial)
        # when a failure wasn't in the down set the rings shipped —
        # waited=False failures (pre-exhausted budget, local pool
        # capacity) never flip node.healthy, so health alone can't
        # detect that survivors suppressed the failed node's shares
        self._tls.fanout_failed = frozenset()

        def run(node):
            try:
                r = fn(node)
                with lock:
                    results.append(r)
            except (OSError, RPCError, ConnectionError) as e:
                # a deadline that was exhausted BEFORE any I/O touched
                # the node (waited=False) is the query's fault: count
                # the error/partial, but don't poison the node's health
                # for other queries' next 2s
                if getattr(e, "waited", True):
                    node.mark_down()
                with lock:
                    errors.append((node.name, e))

        all_nodes = self.nodes
        live = [n for n in all_nodes if n.healthy]
        for n in all_nodes:
            if not n.healthy:
                errors.append((n.name, RPCError("node marked down")))
        if len(live) <= 1:
            for n in live:
                run(n)
        else:
            from functools import partial

            from ..utils import workpool
            workpool.POOL.run([partial(run, n) for n in live])
        if errors and not results:
            raise ClusterUnavailableError(
                f"all storage nodes failed: {errors[0][0]}: "
                f"{errors[0][1]}")
        if errors:
            failed = {name for name, _ in errors}
            self._tls.fanout_failed = frozenset(failed)
            if replica_covered_ok and self.rf > 1 and \
                    len(failed) < self.rf:
                # every hash range of every failed node is RF-covered by
                # a surviving responder (all non-failed nodes produced a
                # result above): the merged answer is complete
                _PARTIAL_AVOIDED.inc()
            else:
                self._tls.partial = True
                if self.deny_partial:
                    raise PartialResultError(
                        f"partial response denied: {errors[0][0]}: "
                        f"{errors[0][1]}")
        return results

    # eval passes ec.tracer down so storage-node spans land in the query
    # trace (the vmselect->vmstorage half of cross-RPC tracing)
    supports_search_tracer = True
    # selector-level `or` filters ({a="b" or c="d"}) are shipped through
    # search_v1/searchColumns_v1 as a trailing or_sets field; union-less
    # peers degrade to one legacy call per set (see StorageNodeClient)
    supports_filter_union = True
    # eval passes ec.deadline down so per-node RPC socket timeouts are
    # derived from the query's REMAINING budget: a hung vmstorage costs
    # one query deadline, not a fixed default timeout per hop
    supports_search_deadline = True

    def _read_rings(self) -> tuple[dict, frozenset]:
        """(per-node RingConfig for one read fan-out — node name ->
        ring with that node's self index and the current down set —,
        the down NODE NAMES those rings embed).  ({}, frozenset()) when
        ring-ownership filtering is off (VM_RING_FILTER=0, a single
        node, or suspended after an rf>1 topology change).  The down
        set is returned so the re-fan check compares against exactly
        what the rings claimed (a second health read could differ).
        Ticks ``vm_reroute_reads_total`` when the shipped down set is
        non-empty — survivors will explicitly serve the down nodes'
        hash ranges from their replicas."""
        nodes = self.nodes
        if not ringfilter.enabled() or self._ring_suspended or \
                len(nodes) <= 1:
            return {}, frozenset()
        names = [n.name for n in nodes]
        down = frozenset(i for i, n in enumerate(nodes) if not n.healthy)
        if down:
            ringfilter.REROUTE_READS.inc()
        return ({n.name: ringfilter.get_ring(names, self.rf, i, down)
                 for i, n in enumerate(nodes) if n.healthy},
                frozenset(names[i] for i in down))

    def search_columns(self, filters, min_ts, max_ts,
                       dedup_interval_ms=None, max_series=None,
                       tenant=(0, 0), tracer=querytracer.NOP,
                       deadline: float = 0.0):
        """Columnar scatter-gather: every node streams (raw names,
        counts, concatenated columns) over searchColumns_v1; the merge is
        ONE vectorized assembly into the padded (S, N) layout — cluster
        reads feed the same columnar host rollups and device tile packer
        as single-node reads. Replica overlap is handled by assemble()'s
        per-row sort fix + exact-duplicate-timestamp dedup (keep last),
        identical to the old per-series merge semantics."""
        from ..storage.columnar import ColumnarSeries, assemble
        self._search_fanouts.inc()
        self._search_fanouts_counter.inc()
        for _attempt in range(2):
            # down_before = the EXACT down set the shipped rings embed
            # (a second health snapshot could already differ and hide a
            # just-failed node from the re-fan check)
            rings, down_before = self._read_rings()

            def query_node(n, rings=rings):
                # one child span per storage node; children.append is
                # GIL-atomic, so concurrent fan-out threads are safe
                with tracer.new_child("rpc searchColumns_v1 node %s",
                                      n.name) as nqt:
                    return n.search_columns(filters, min_ts, max_ts,
                                            tenant, tracer=nqt,
                                            deadline=deadline,
                                            ring=rings.get(n.name))

            node_results = self._fanout(query_node)
            if not rings or self.rf <= 1:
                break
            # ANY failure the shipped rings didn't list as down means
            # the survivors suppressed shares the failed node owned —
            # node.healthy flips cover crashes, fanout_failed covers
            # waited=False failures (pre-exhausted budget, local pool
            # capacity) that never mark the node down
            fresh = (({n.name for n in self.nodes if not n.healthy} |
                      set(getattr(self._tls, "fanout_failed", ()))) -
                     down_before)
            if not fresh:
                break
            if _attempt == 1:
                # the re-fan ALSO failed a node the rings called
                # healthy: replica coverage cannot be claimed — the
                # suppressed shares may be missing, so go honestly
                # partial instead of silently incomplete
                self._tls.partial = True
                if self.deny_partial:
                    raise PartialResultError(
                        "partial response denied: ring-filtered "
                        "fan-out kept failing node(s) "
                        + ",".join(sorted(fresh)))
                break
            # a node died DURING this fan-out, after the shipped rings
            # claimed it healthy: its replicas suppressed the shares it
            # owned, so the merged result is silently missing them.
            # One bounded re-fan with the updated down set makes the
            # survivors serve those ranges explicitly (KNOWN-down nodes
            # never re-fan — their shares ship rerouted the first time).
            logger.warnf("cluster: node(s) %s failed mid-fan-out; "
                         "re-fanning with rerouted ring",
                         ",".join(sorted(fresh)))
        names_all: list[bytes] = []
        cnt_parts, ts_parts, val_parts = [], [], []
        for names, counts, ts_cat, val_cat, remote_partial in node_results:
            if remote_partial:
                # a lower level (multilevel chain) saw an incomplete
                # fan-out
                self._tls.partial = True
            names_all.extend(names)
            cnt_parts.append(counts)
            ts_parts.append(ts_cat)
            val_parts.append(val_cat)
        if not names_all:
            return ColumnarSeries.empty()
        cnts = np.concatenate(cnt_parts)
        ts_cat = np.concatenate(ts_parts)
        val_cat = np.concatenate(val_parts)
        # canonical row order = sorted raw names (matches single-node
        # search_columns); same bytes from replicas collapse to one row
        if any(nm[-1:] == b"\x00" for nm in names_all):
            arr = np.array(names_all, dtype=object)
        else:
            arr = np.array(names_all)
        uniq_names, rows = np.unique(arr, return_inverse=True)
        S = int(uniq_names.size)
        if max_series is not None and S > max_series:
            raise ResourceWarning(
                f"query matches {S} series, limit {max_series}")
        keep = cnts > 0
        if not keep.all():
            sample_keep = np.repeat(keep, cnts)
            rows, cnts = rows[keep], cnts[keep]
            ts_cat, val_cat = ts_cat[sample_keep], val_cat[sample_keep]
            if rows.size == 0:
                return ColumnarSeries.empty()
        cols = assemble(np.asarray(rows, np.int64), S,
                        np.asarray(cnts, np.int64), ts_cat, val_cat,
                        min_ts, max_ts, dedup_interval_ms or 0,
                        metric_ids=np.arange(S, dtype=np.int64))
        raws = [bytes(u) for u in uniq_names]
        if cols.dropped_rows is not None:
            live = np.delete(np.arange(S), cols.dropped_rows)
            raws = [raws[i] for i in live]
        cols.raw_names = raws
        cols.metric_names = [MetricName.unmarshal(r) for r in raws]
        cols.compute_stale_rows()
        return cols

    def search_series(self, filters, min_ts, max_ts, dedup_interval_ms=None,
                      max_series=None, tenant=(0, 0),
                      tracer=querytracer.NOP, deadline: float = 0.0):
        return self.search_columns(
            filters, min_ts, max_ts, dedup_interval_ms=dedup_interval_ms,
            max_series=max_series, tenant=tenant,
            tracer=tracer, deadline=deadline).to_series_list()

    def search_metric_names(self, filters, min_ts, max_ts, limit=2**31,
                            tenant=(0, 0)):
        node_results = self._fanout(
            lambda n: n.search_metric_names(filters, min_ts, max_ts, tenant))
        seen = {}
        for res in node_results:
            for mn in res:
                seen.setdefault(mn.marshal(), mn)
        return [seen[k] for k in sorted(seen)][:limit]

    def label_names(self, min_ts=None, max_ts=None, tenant=(0, 0)):
        res = self._fanout(lambda n: n.label_names(min_ts, max_ts, tenant))
        return sorted(set().union(*map(set, res))) if res else []

    def label_values(self, key, min_ts=None, max_ts=None, tenant=(0, 0)):
        res = self._fanout(
            lambda n: n.label_values(key, min_ts, max_ts, tenant))
        return sorted(set().union(*map(set, res))) if res else []

    def tag_value_suffixes(self, tag_key, prefix, delimiter=".",
                           max_suffixes=100_000, min_ts=None, max_ts=None,
                           tenant=(0, 0)):
        res = self._fanout(lambda n: n.tag_value_suffixes(
            tag_key, prefix, delimiter, max_suffixes, min_ts, max_ts,
            tenant))
        return sorted(set().union(*map(set, res)))[:max_suffixes] \
            if res else []

    def metric_names_usage_stats(self, limit=1000, le=None):
        # per-node counters: a missing node's counts change the answer
        # regardless of data replication — strict partial accounting
        merged: dict[str, list] = {}
        for items in self._fanout(
                lambda n: n.metric_names_usage_stats(limit, le),
                replica_covered_ok=False):
            for x in items:
                e = merged.setdefault(x["metricName"], [0, 0])
                e[0] += x["requestsCount"]
                e[1] = max(e[1], x["lastRequestTimestamp"])
        items = [{"metricName": k, "requestsCount": c,
                  "lastRequestTimestamp": t}
                 for k, (c, t) in merged.items()]
        if le is not None:
            items = [x for x in items if x["requestsCount"] <= le]
        items.sort(key=lambda x: x["requestsCount"])
        return items[:limit]

    def reset_metric_names_stats(self):
        # mutation: a missed node keeps its stats — never claim coverage
        self._fanout(lambda n: n.reset_metric_names_stats(),
                     replica_covered_ok=False)

    def search_metadata(self, limit=1000, metric=""):
        # TYPE/HELP metadata is node-local state, not RF-replicated data
        out: dict = {}
        for md in self._fanout(
                lambda n: n.search_metadata(limit, metric),
                replica_covered_ok=False):
            for k, v in md.items():
                out.setdefault(k, v)
        return dict(list(out.items())[:limit])

    def quarantine_report(self) -> list[dict]:
        """Cluster-wide quarantine listing: fan the storage nodes'
        reports together (tagged per node) so the vmselect's
        /api/v1/status/quarantine is the operator's single worksheet."""
        out: list[dict] = []

        def one(n):
            return [dict(q, node=n.name) for q in n.quarantine_report()]

        # strict accounting: a node whose report is missing may be the
        # one HOLDING quarantined parts — replica coverage can cover its
        # data, never its per-node quarantine state
        for rep in self._fanout(one, replica_covered_ok=False):
            out.extend(rep)
        return out

    def profile_report(self, reset: bool = False) -> list[dict]:
        """Cluster-wide profiler fan-out: every node's folded-stack
        snapshot tagged with its node name, so the vmselect's
        ``/api/v1/status/profile`` answers for the whole cluster.
        ``reset`` propagates so ?reset=1 opens a fresh measurement
        window on every node, not just the vmselect.  Node-local
        state — strict partial accounting, like quarantine."""
        def one(n):
            snap = n.profile(reset=reset)
            if snap is None or snap.get("disabled"):
                return []
            snap["node"] = n.name
            return [snap]

        out: list[dict] = []
        for rep in self._fanout(one, replica_covered_ok=False):
            out.extend(rep)
        return out

    def health_report(self) -> list[dict]:
        """Per-node health_v1 verdicts tagged with node names — the
        input to the /api/v1/status/health roll-up.  Best-effort by
        design: a node that cannot answer simply has no report (the
        roll-up already names it down/unreachable from liveness), and
        an old node without the method reports verdict "unknown"
        rather than failing the fan-out."""
        def one(n):
            rep = n.health()
            if rep is None:
                rep = {"verdict": "unknown"}
            rep["node"] = n.name
            return rep

        try:
            # node-local state: strict accounting like quarantine
            return self._fanout(one, replica_covered_ok=False)
        except (ClusterUnavailableError, PartialResultError):
            return []

    def delete_series(self, filters, tenant=(0, 0)):
        # a node that missed the fan-out missed its TOMBSTONES: replica
        # coverage cannot make that complete (the down node's copy will
        # resurrect), so deletes keep strict partial accounting
        return sum(self._fanout(lambda n: n.delete_series(filters, tenant),
                                replica_covered_ok=False))

    def series_count(self, tenant=(0, 0)):
        # summed per-node counts change value when a node is missing —
        # RF coverage proves its DATA is served elsewhere, not that the
        # sum is unchanged (with RF>1 replicas are double-counted when
        # healthy): strict partial accounting
        return sum(self._fanout(lambda n: n.series_count(tenant),
                                replica_covered_ok=False))

    def tenants(self):
        res = self._fanout(lambda n: n.tenants())
        return sorted(set().union(*map(set, res))) if res else []

    def tsdb_status(self, date=None, topn=10, tenant=(0, 0)):
        # per-node top-N counts, same reasoning as series_count
        results = self._fanout(lambda n: n.tsdb_status(topn, date, tenant),
                               replica_covered_ok=False)
        total = sum(r["totalSeries"] for r in results)

        def merge_top(key):
            acc = {}
            for r in results:
                for e in r.get(key, []):
                    acc[e["name"]] = acc.get(e["name"], 0) + e["count"]
            return [{"name": k, "count": c} for k, c in
                    sorted(acc.items(), key=lambda kv: -kv[1])[:topn]]

        return {"totalSeries": total,
                "seriesCountByMetricName": merge_top("seriesCountByMetricName"),
                "seriesCountByLabelName": merge_top("seriesCountByLabelName"),
                "seriesCountByLabelValuePair":
                    merge_top("seriesCountByLabelValuePair")}

    # -- elastic topology: join / drain / rebalance ---------------------
    #
    # The cluster grows and shrinks WITHOUT restarts (ROADMAP item 3b):
    # join adds a node to the hash ring (new writes shard to it at the
    # next batch), drain write-excludes a node, migrates every
    # finalized part off it over the migrateParts_v1 family, and only
    # then drops it — each part is removed from its source AFTER the
    # receiver's durable ack, so acked writes survive every transition.
    # Reads stay byte-exact throughout: moved parts are ring-exempt on
    # their new node and duplicates collapse in the fan-out merge.

    def node_names(self) -> list[str]:
        return [n.name for n in self.nodes]

    def set_ring_filter(self, enabled: bool) -> None:
        """Re-arm (or suspend) ring-ownership read filtering on this
        router — rf>1 topology changes suspend it automatically (see
        __init__); the operator re-enables once the data layout has
        settled."""
        with self._lock:
            self._ring_suspended = not enabled

    @property
    def ring_filter_active(self) -> bool:
        return ringfilter.enabled() and not self._ring_suspended and \
            len(self.nodes) > 1

    def _set_nodes_locked(self, nodes: list[StorageNodeClient]) -> None:
        """Swap the (nodes, ring) tuple; caller holds self._lock."""
        self._topology = (list(nodes),
                          ConsistentHash([n.name for n in nodes]))
        if self.rf > 1:
            # with replicas, ownership under the NEW ring does not
            # imply possession — suspend ownership filtering until
            # the operator re-arms it (full fan-out stays correct)
            self._ring_suspended = True

    def _set_nodes(self, nodes: list[StorageNodeClient]) -> None:
        with self._lock:
            self._set_nodes_locked(nodes)

    def add_node(self, spec: str, timeout: float = 10.0) -> dict:
        """JOIN host:insertPort:selectPort (or host:port for a
        multilevel child): new writes shard to the node from the next
        batch on.  Call :meth:`rebalance_to` afterwards to move a fair
        byte share of existing parts onto it."""
        host, ip_, sp_ = parse_node_spec(spec)
        node = StorageNodeClient(host, ip_, sp_, timeout=timeout)
        # read-modify-write under the topology lock: two concurrent
        # joins (admin handlers run on separate HTTP threads) must not
        # lose each other's node
        with self._lock:
            if node.name in {n.name for n in self.nodes}:
                dup = True
            else:
                dup = False
                logger.infof("cluster: joining node %s", node.name)
                self._draining.discard(node.name)
                self._set_nodes_locked(self.nodes + [node])
        if dup:
            node.close()
            raise ValueError(f"node {node.name} is already in the ring")
        return {"nodes": self.node_names()}

    def remove_node(self, name: str) -> dict:
        """Drop a node from the ring (reads/writes stop immediately).
        Use :meth:`drain_node` instead when the node still holds data."""
        with self._lock:
            nodes = list(self.nodes)
            keep = [n for n in nodes if n.name != name]
            if len(keep) == len(nodes):
                raise KeyError(f"no node named {name!r}")
            if not keep:
                raise ValueError("cannot remove the last storage node")
            logger.infof("cluster: removing node %s", name)
            self._set_nodes_locked(keep)
            self._draining.discard(name)
        for n in nodes:
            if n.name == name:
                n.close()
        return {"nodes": self.node_names()}

    @staticmethod
    def _migrate_grace_s() -> float:
        """How long a migrated part's SOURCE copy outlives the
        receiver's ack (``VM_MIGRATE_GRACE_MS``, default 1500).  A
        fan-out is not atomic: a query can read the target BEFORE the
        part lands there and the source AFTER a prompt delete — missing
        the part on both, silently.  Keeping the source copy for one
        grace window (>= the longest query's wall time) closes that
        race: any fan-out that missed the part on the target started
        early enough to still find it on the source (duplicates from
        the overlap collapse in the merge like replica overlap)."""
        import os
        try:
            return max(float(os.environ.get("VM_MIGRATE_GRACE_MS",
                                            "1500")), 0.0) / 1e3
        except ValueError:
            return 1.5

    def _copy_one(self, src: StorageNodeClient, dst: StorageNodeClient,
                  partition: str, part: str) -> tuple[int, int]:
        """Copy one finalized part src -> dst: pull (fetchPart_v1) and
        push (migratePart_v1 — the receiver verifies crc32s and
        publishes durably).  The SOURCE copy stays; callers delete it
        after the migration grace window (see _migrate_grace_s).

        Known bound: the transfer materializes the part in memory at
        each hop and the push is one RPC frame, so parts are capped by
        RAM and rpc.MAX_FRAME (256MB compressed) — an over-cap part
        fails loudly and stays on its source (ROADMAP names streamed
        bounded-memory transfer as the follow-up)."""
        files, entries, meta = src.fetch_part(partition, part)
        rows, nbytes = dst.migrate_part(partition, files, entries, meta)
        _PARTS_MIGRATED.inc()
        _REBALANCE_BYTES.inc(nbytes)
        logger.infof("cluster: migrated %s/%s %s -> %s (%d rows, %d "
                     "bytes)", partition, part, src.name, dst.name, rows,
                     nbytes)
        return rows, nbytes

    @staticmethod
    def _remove_after_grace(src: StorageNodeClient, moved: dict) -> None:
        """Delete migrated-away source copies once the grace window has
        passed (``moved``: partition -> [part names])."""
        if not moved:
            return
        time.sleep(ClusterStorage._migrate_grace_s())
        for partition, names in moved.items():
            src.remove_parts(partition, names)

    def drain_node(self, name: str, remove: bool = True,
                   max_passes: int = 6) -> dict:
        """DRAIN: write-exclude the node, then migrate every finalized
        part off it (each listing flushes first, so rows acked before
        or during the drain are included; the first pass force-merges
        so few parts move and no background merge races the fetches).
        Multiple passes absorb parts that appear between listings.
        ``remove`` drops the node from the ring once it is empty."""
        if name not in self.node_names():
            raise KeyError(f"no node named {name!r}")
        self._draining.add(name)
        try:
            return self._drain_node(name, remove, max_passes)
        except BaseException:
            # a failed drain must not leave the node write-excluded
            # forever (a successful one removes it from the ring, or —
            # with remove=False — the caller owns the follow-up)
            self._draining.discard(name)
            raise

    def _drain_node(self, name: str, remove: bool,
                    max_passes: int) -> dict:
        # ONE topology snapshot for the whole (long, sleeping) drain:
        # part names are node-local counters, so index-addressing
        # self.nodes across a concurrent topology change could point a
        # remove_parts at the WRONG node's identically-named parts
        nodes, ch = self._topology
        idx = [n.name for n in nodes].index(name)
        src = nodes[idx]
        moved = {"parts": 0, "rows": 0, "bytes": 0}
        for attempt in range(max_passes):
            parts = src.list_parts(flush=True, merge=attempt == 0)
            if not parts:
                break
            copied: dict[str, list[str]] = {}
            for row in parts:
                excluded = {i for i, n in enumerate(nodes)
                            if not n.healthy or n.name in self._draining}
                excluded.add(idx)
                key = (b"part:" + row["partition"].encode() + b"/" +
                       row["part"].encode() + src.name.encode())
                tgt = ch.nodes_for_key(key, 1, excluded)
                if not tgt:
                    raise RPCError(
                        f"drain {name}: no healthy target nodes")
                try:
                    rows_n, bytes_n = self._copy_one(
                        src, nodes[tgt[0]], row["partition"],
                        row["part"])
                except (RPCError, KeyError) as e:
                    # merged away since listing (or a racing pass):
                    # the re-list on the next attempt settles it
                    logger.warnf("drain %s: part %s/%s skipped: %s",
                                 name, row["partition"], row["part"], e)
                    continue
                copied.setdefault(row["partition"], []).append(row["part"])
                moved["parts"] += 1
                moved["rows"] += rows_n
                moved["bytes"] += bytes_n
            # source copies outlive the ack by the migration grace so
            # in-flight fan-outs that read the target pre-adopt still
            # find the bytes on the source (then the re-list can't see
            # the removed parts again)
            self._remove_after_grace(src, copied)
        else:
            raise RPCError(f"drain {name}: parts still appearing after "
                           f"{max_passes} passes")
        out = dict(moved, node=name, removed=False)
        if remove:
            self.remove_node(name)
            out["removed"] = True
        return out

    def rebalance_to(self, name: str) -> dict:
        """After a JOIN: greedily move finalized parts from the most
        loaded nodes onto ``name`` until it holds ~1/N of the cluster's
        part bytes.  A part moves when the move brings BOTH sides at
        least as close to the fair share as staying put — so a single
        compacted part larger than the fair share still moves to an
        empty joiner (the 1-node -> 2-node case) instead of silently
        rebalancing nothing.  Byte-exact reads throughout: adopted
        parts serve ring-exempt, and each source copy outlives the
        receiver's durable ack (one grace window for the whole pass)."""
        # one topology snapshot for the whole pass (see _drain_node:
        # index- or ring-addressing across a concurrent change could
        # delete identically-named parts on the WRONG node)
        nodes, _ = self._topology
        try:
            tgt_i = [n.name for n in nodes].index(name)
        except ValueError:
            raise KeyError(f"no node named {name!r}")
        tgt = nodes[tgt_i]
        inv: dict[int, list] = {}
        for i, n in enumerate(nodes):
            if n.healthy and n.name not in self._draining:
                inv[i] = n.list_parts(flush=True)
        total = sum(r["bytes"] for parts in inv.values() for r in parts)
        fair = total / max(len(inv), 1)
        have = sum(r["bytes"] for r in inv.get(tgt_i, ()))
        moved = {"parts": 0, "rows": 0, "bytes": 0}
        copied: dict[int, dict[str, list[str]]] = {}
        order = sorted((i for i in inv if i != tgt_i),
                       key=lambda i: -sum(r["bytes"] for r in inv[i]))
        for i in order:
            src_bytes = sum(r["bytes"] for r in inv[i])
            for row in sorted(inv[i], key=lambda r: -r["bytes"]):
                b = row["bytes"]
                # move only if neither side ends FARTHER from fair
                # than it started (<= : a neutral move still fills an
                # empty joiner)
                if b <= 0 or b > 2 * (fair - have) or \
                        b > 2 * (src_bytes - fair):
                    continue
                try:
                    rows_n, bytes_n = self._copy_one(
                        nodes[i], tgt, row["partition"], row["part"])
                except (RPCError, KeyError) as e:
                    logger.warnf("rebalance: part %s/%s skipped: %s",
                                 row["partition"], row["part"], e)
                    continue
                copied.setdefault(i, {}).setdefault(
                    row["partition"], []).append(row["part"])
                have += bytes_n
                src_bytes -= bytes_n
                moved["parts"] += 1
                moved["rows"] += rows_n
                moved["bytes"] += bytes_n
        if copied:
            # ONE grace window after the last ack covers every in-flight
            # fan-out, regardless of how many source nodes contributed
            time.sleep(self._migrate_grace_s())
            for i, by_part in copied.items():
                for partition, names in by_part.items():
                    nodes[i].remove_parts(partition, names)
        return dict(moved, node=name)

    def cluster_status(self) -> dict:
        """Topology worksheet for /internal/cluster/nodes."""
        return {"nodes": [{"name": n.name, "healthy": n.healthy,
                           "draining": n.name in self._draining}
                          for n in self.nodes],
                "replicationFactor": self.rf,
                "ringFilter": self.ring_filter_active}

    @property
    def search_fanouts(self) -> int:
        """Read fan-outs launched by this vmselect (one per scatter-
        gather, regardless of node count) — the O(distinct expressions)
        fleet guard's observable."""
        return self._search_fanouts.get()

    def metrics(self):
        return {"vm_cluster_nodes": len(self.nodes),
                "vm_cluster_rows_sent_total": self.rows_sent,
                "vm_cluster_reroutes_total": self.reroutes,
                "vm_cluster_search_fanouts_total": self.search_fanouts,
                "vm_cluster_healthy_nodes":
                    sum(1 for n in self.nodes if n.healthy)}

    def close(self):
        # snapshot under the topology lock: nodes constructed by a
        # join handler thread are published under it (_set_nodes), and
        # this acquire is the happens-before edge that makes their
        # freshly-initialized client state visible here
        with self._lock:
            nodes = self.nodes
        for n in nodes:
            n.close()
