"""Cluster node APIs over RPC (reference lib/vminsertapi/api.go +
lib/vmselectapi/{api,server}.go + the cluster-branch netstorage semantics
documented in docs/victoriametrics/Cluster-VictoriaMetrics.md:851+).

- make_storage_handlers(storage): RPC method table served by vmstorage
  (both the insert-side writeRows_v1 and the select-side search_v1 family).
- StorageNodeClient: client half for one storage node.
- ClusterStorage: vminsert+vmselect composite backend — shards writes by
  consistent hash of the canonical metric name with replication and
  rerouting, fans reads out to every node and merges with partial-result
  tracking. Duck-compatible with storage.Storage for httpapi/query use.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..storage.metric_name import MetricName
from ..storage.tag_filters import TagFilter
from ..utils import logger
from .consistenthash import ConsistentHash
from .rpc import HELLO_INSERT, HELLO_SELECT, RPCClient, RPCError, Reader, Writer

SERIES_PER_FRAME = 64


# ---------------------------------------------------------------------------
# vmstorage-side handlers
# ---------------------------------------------------------------------------

def _read_filters(r: Reader) -> list[TagFilter]:
    n = r.u64()
    out = []
    for _ in range(n):
        key = r.bytes_()
        value = r.bytes_()
        flags = r.u64()
        out.append(TagFilter(key, value, negate=bool(flags & 1),
                             regex=bool(flags & 2)))
    return out


def _write_filters(w: Writer, filters: list[TagFilter]):
    w.u64(len(filters))
    for tf in filters:
        w.bytes_(tf.key)
        w.bytes_(tf.value)
        w.u64((1 if tf.negate else 0) | (2 if tf.regex else 0))


def _read_tenant(r: Reader) -> tuple:
    return (r.u64(), r.u64())


def _write_tenant(w: Writer, tenant) -> Writer:
    return w.u64(tenant[0]).u64(tenant[1])


def make_storage_handlers(storage, rate_limiter=None) -> dict:
    """RPC dispatch table for a vmstorage node. `rate_limiter` applies
    -maxIngestionRate to RPC writes too (the multilevel/clusternative
    chaining path must honor the same ceiling as HTTP ingest)."""

    def h_write_rows(r: Reader):
        tenant = _read_tenant(r)
        n = r.u64()
        rows = []
        for _ in range(n):
            raw = r.bytes_()
            ts = r.i64()
            val = r.f64()
            rows.append((MetricName.unmarshal(raw), ts, val))
        if rate_limiter is not None and rate_limiter.enabled():
            rate_limiter.register(len(rows), tenant)
        storage.add_rows(rows, tenant=tenant)
        return Writer().u64(len(rows))

    def h_is_readonly(r: Reader):
        return Writer().u64(1 if getattr(storage, "is_readonly", False) else 0)

    # sentinel "count" marking the trailing metadata frame of search_v1
    META_FRAME = (1 << 32) - 1

    def h_search(r: Reader):
        tenant = _read_tenant(r)
        filters = _read_filters(r)
        min_ts, max_ts = r.i64(), r.i64()
        if hasattr(storage, "reset_partial"):
            storage.reset_partial()
        series = storage.search_series(filters, min_ts, max_ts,
                                       tenant=tenant)

        def frames():
            for i in range(0, len(series), SERIES_PER_FRAME):
                w = Writer()
                chunk = series[i:i + SERIES_PER_FRAME]
                w.u64(len(chunk))
                for sd in chunk:
                    w.bytes_(sd.metric_name.marshal())
                    w.array(sd.timestamps)
                    w.array(sd.values)
                yield w
            # trailing metadata frame: propagate partial-result state up
            # through multilevel chains
            meta = Writer().u64(META_FRAME)
            meta.u64(1 if getattr(storage, "last_partial", False) else 0)
            yield meta
        return frames()

    def h_search_metric_names(r: Reader):
        tenant = _read_tenant(r)
        filters = _read_filters(r)
        min_ts, max_ts = r.i64(), r.i64()
        names = storage.search_metric_names(filters, min_ts, max_ts,
                                            tenant=tenant)
        w = Writer().u64(len(names))
        for mn in names:
            w.bytes_(mn.marshal())
        return w

    def h_label_names(r: Reader):
        tenant = _read_tenant(r)
        min_ts, max_ts = r.i64(), r.i64()
        names = storage.label_names(min_ts or None, max_ts or None,
                                    tenant=tenant)
        w = Writer().u64(len(names))
        for n in names:
            w.str_(n)
        return w

    def h_label_values(r: Reader):
        tenant = _read_tenant(r)
        key = r.str_()
        min_ts, max_ts = r.i64(), r.i64()
        vals = storage.label_values(key, min_ts or None, max_ts or None,
                                    tenant=tenant)
        w = Writer().u64(len(vals))
        for v in vals:
            w.str_(v)
        return w

    def h_delete_series(r: Reader):
        tenant = _read_tenant(r)
        filters = _read_filters(r)
        return Writer().u64(storage.delete_series(filters, tenant=tenant))

    def h_series_count(r: Reader):
        tenant = _read_tenant(r)
        return Writer().u64(storage.series_count(tenant=tenant))

    def h_tsdb_status(r: Reader):
        import json
        tenant = _read_tenant(r)
        topn = r.u64()
        date_plus1 = r.u64()  # 0 = no date filter
        st = storage.tsdb_status(date_plus1 - 1 if date_plus1 else None, topn,
                                 tenant=tenant)
        return Writer().bytes_(json.dumps(st).encode())

    def h_register_metric_names(r: Reader):
        tenant = _read_tenant(r)
        n = r.u64()
        names = [MetricName.unmarshal(r.bytes_()) for _ in range(n)]
        if hasattr(storage, "register_metric_names"):
            storage.register_metric_names(names, tenant=tenant)
        return Writer().u64(n)

    def h_tenants(r: Reader):
        tenants = storage.tenants() if hasattr(storage, "tenants") \
            else [(0, 0)]
        w = Writer().u64(len(tenants))
        for a, p in tenants:
            w.u64(a).u64(p)
        return w

    return {
        "writeRows_v1": h_write_rows,
        "isReadOnly_v1": h_is_readonly,
        "search_v1": h_search,
        "searchMetricNames_v1": h_search_metric_names,
        "labelNames_v1": h_label_names,
        "labelValues_v1": h_label_values,
        "deleteSeries_v1": h_delete_series,
        "seriesCount_v1": h_series_count,
        "tsdbStatus_v1": h_tsdb_status,
        "registerMetricNames_v1": h_register_metric_names,
        "tenants_v1": h_tenants,
    }


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

class StorageNodeClient:
    def __init__(self, host: str, insert_port: int, select_port: int,
                 name: str | None = None, timeout: float = 10.0):
        self.name = name or f"{host}:{insert_port}"
        self.insert = RPCClient(host, insert_port, HELLO_INSERT,
                                timeout=timeout)
        self.select = RPCClient(host, select_port, HELLO_SELECT,
                                timeout=timeout)
        self.down_until = 0.0

    @property
    def healthy(self) -> bool:
        return time.monotonic() >= self.down_until

    def mark_down(self, seconds: float = 2.0):
        self.down_until = time.monotonic() + seconds
        logger.warnf("storage node %s marked down for %.1fs", self.name,
                     seconds)

    def write_rows(self, rows: list[tuple[bytes, int, float]],
                   tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant).u64(len(rows))
        for raw, ts, val in rows:
            w.bytes_(raw)
            w.i64(int(ts))
            w.f64(float(val))
        self.insert.call("writeRows_v1", w)

    def search_series(self, filters, min_ts, max_ts, tenant=(0, 0)):
        """Returns (series_list, remote_partial)."""
        w = _write_tenant(Writer(), tenant)
        _write_filters(w, filters)
        w.i64(min_ts).i64(max_ts)
        out = []
        partial = False
        for r in self.select.call_stream("search_v1", w):
            n = r.u64()
            if n == (1 << 32) - 1:  # trailing metadata frame
                partial = bool(r.u64())
                continue
            for _ in range(n):
                mn = MetricName.unmarshal(r.bytes_())
                ts = r.array()
                vals = r.array()
                out.append((mn, ts, vals))
        return out, partial

    def search_metric_names(self, filters, min_ts, max_ts, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant)
        _write_filters(w, filters)
        w.i64(min_ts).i64(max_ts)
        r = self.select.call("searchMetricNames_v1", w)
        return [MetricName.unmarshal(r.bytes_()) for _ in range(r.u64())]

    def label_names(self, min_ts, max_ts, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant).i64(min_ts or 0).i64(max_ts or 0)
        r = self.select.call("labelNames_v1", w)
        return [r.str_() for _ in range(r.u64())]

    def label_values(self, key, min_ts, max_ts, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant).str_(key)
        w.i64(min_ts or 0).i64(max_ts or 0)
        r = self.select.call("labelValues_v1", w)
        return [r.str_() for _ in range(r.u64())]

    def delete_series(self, filters, tenant=(0, 0)):
        w = _write_tenant(Writer(), tenant)
        _write_filters(w, filters)
        return self.select.call("deleteSeries_v1", w).u64()

    def series_count(self, tenant=(0, 0)):
        return self.select.call("seriesCount_v1",
                                _write_tenant(Writer(), tenant)).u64()

    def tsdb_status(self, topn, date=None, tenant=(0, 0)):
        import json
        w = _write_tenant(Writer(), tenant).u64(topn)
        w.u64(0 if date is None else date + 1)
        r = self.select.call("tsdbStatus_v1", w)
        return json.loads(r.bytes_())

    def tenants(self):
        r = self.select.call("tenants_v1", Writer())
        return [(r.u64(), r.u64()) for _ in range(r.u64())]

    def close(self):
        self.insert.close()
        self.select.close()


# ---------------------------------------------------------------------------
# ClusterStorage: the vminsert/vmselect composite backend
# ---------------------------------------------------------------------------

class PartialResultError(RuntimeError):
    pass


def start_native_server(addr: str, hello: bytes, storage,
                        rate_limiter=None):
    """Start a cluster-native RPC server exposing `storage` (used by the
    -clusternativeListenAddr multilevel flags on vminsert/vmselect)."""
    from .rpc import RPCServer
    host, _, port = addr.rpartition(":")
    srv = RPCServer(host or "0.0.0.0", int(port), hello,
                    make_storage_handlers(storage, rate_limiter))
    srv.start()
    return srv


class SeriesData:
    __slots__ = ("metric_name", "timestamps", "values")

    def __init__(self, mn, ts, vals):
        self.metric_name = mn
        self.timestamps = ts
        self.values = vals


class ClusterStorage:
    """Shard writes / fan-out reads across storage nodes."""

    def __init__(self, nodes: list[StorageNodeClient],
                 replication_factor: int = 1,
                 deny_partial_response: bool = False):
        self.nodes = nodes
        self.rf = replication_factor
        self.deny_partial = deny_partial_response
        self.ch = ConsistentHash([n.name for n in nodes])
        from ..query.rollup_result_cache import next_storage_token
        self.cache_token = next_storage_token()
        self.rows_sent = 0
        self.reroutes = 0
        self._lock = threading.Lock()
        # partial-result tracking is per handler thread and STICKY across
        # the fanouts of one query (a shared flag would race between
        # concurrent queries and be cleared by a later clean fanout)
        self._tls = threading.local()

    def reset_partial(self):
        self._tls.partial = False

    @property
    def last_partial(self) -> bool:
        return bool(getattr(self._tls, "partial", False))

    # -- write path (vminsert) ------------------------------------------

    def add_rows(self, rows, tenant=(0, 0)) -> int:
        """rows: [(labels-dict-or-MetricName, ts, value)] — shard by
        (tenant, canonical metric name), replicate RF-ways, reroute on
        failure."""
        import struct as _struct
        tkey = _struct.pack(">II", tenant[0], tenant[1])
        per_node: dict[int, list] = {}
        excluded = {i for i, n in enumerate(self.nodes) if not n.healthy}
        for labels, ts, val in rows:
            mn = labels if isinstance(labels, MetricName) else \
                MetricName.from_dict(labels) if isinstance(labels, dict) \
                else MetricName.from_labels(labels)
            raw = mn.marshal()
            targets = self.ch.nodes_for_key(tkey + raw, self.rf, excluded)
            if not targets:
                # all nodes down: try everything anyway
                targets = self.ch.nodes_for_key(tkey + raw, self.rf, set())
            for i in targets:
                per_node.setdefault(i, []).append((raw, ts, val))
        sent = 0
        for i, node_rows in per_node.items():
            node = self.nodes[i]
            try:
                node.write_rows(node_rows, tenant)
                sent += len(node_rows)
            except (OSError, RPCError, ConnectionError) as e:
                node.mark_down()
                with self._lock:
                    self.reroutes += 1
                # regroup the failed batch by alternate node: one RPC per
                # target, not one per row
                ex = {j for j, n in enumerate(self.nodes)
                      if not n.healthy} | {i}
                alt_batches: dict[int, list] = {}
                for row in node_rows:
                    alt = self.ch.nodes_for_key(tkey + row[0], 1, ex)
                    if not alt:
                        raise RPCError(
                            f"no healthy storage nodes for reroute: {e}")
                    alt_batches.setdefault(alt[0], []).append(row)
                for j, batch in alt_batches.items():
                    self.nodes[j].write_rows(batch, tenant)
                    sent += len(batch)
        self.rows_sent += sent
        return len(rows)

    # -- read path (vmselect) -------------------------------------------

    def _fanout(self, fn):
        """Run fn(node) on every healthy node concurrently (scatter-gather;
        the reference fans out to all vmstorage nodes in parallel). Known-down
        nodes are skipped but still count toward the partial flag."""
        results: list = []
        errors: list = []
        lock = threading.Lock()

        def run(node):
            try:
                r = fn(node)
                with lock:
                    results.append(r)
            except (OSError, RPCError, ConnectionError) as e:
                node.mark_down()
                with lock:
                    errors.append((node.name, e))

        live = [n for n in self.nodes if n.healthy]
        for n in self.nodes:
            if not n.healthy:
                errors.append((n.name, RPCError("node marked down")))
        if len(live) <= 1:
            for n in live:
                run(n)
        else:
            threads = [threading.Thread(target=run, args=(n,), daemon=True)
                       for n in live]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors and not results:
            raise RPCError(f"all storage nodes failed: {errors[0][1]}")
        if errors:
            self._tls.partial = True
        if errors and self.deny_partial:
            raise PartialResultError(
                f"partial response denied: {errors[0][0]}: {errors[0][1]}")
        return results

    def search_series(self, filters, min_ts, max_ts, dedup_interval_ms=None,
                      max_series=None, tenant=(0, 0)):
        node_results = self._fanout(
            lambda n: n.search_series(filters, min_ts, max_ts, tenant))
        merged: dict[bytes, list] = {}
        names: dict[bytes, MetricName] = {}
        for res, remote_partial in node_results:
            if remote_partial:
                # a lower level (multilevel chain) saw an incomplete fan-out
                self._tls.partial = True
            for mn, ts, vals in res:
                raw = mn.marshal()
                merged.setdefault(raw, []).append((ts, vals))
                names.setdefault(raw, mn)
        out = []
        for raw, chunks in merged.items():
            if len(chunks) == 1:
                ts, vals = chunks[0]
            else:
                ts = np.concatenate([c[0] for c in chunks])
                vals = np.concatenate([c[1] for c in chunks])
                order = np.argsort(ts, kind="stable")
                ts, vals = ts[order], vals[order]
                # replica dedup: collapse equal timestamps (keep last)
                if ts.size > 1:
                    dup = np.concatenate([ts[1:] == ts[:-1], [False]])
                    ts, vals = ts[~dup], vals[~dup]
            out.append(SeriesData(names[raw], ts, vals))
        if max_series is not None and len(out) > max_series:
            raise ResourceWarning(
                f"query matches {len(out)} series, limit {max_series}")
        out.sort(key=lambda s: s.metric_name.marshal())
        return out

    def search_metric_names(self, filters, min_ts, max_ts, limit=2**31,
                            tenant=(0, 0)):
        node_results = self._fanout(
            lambda n: n.search_metric_names(filters, min_ts, max_ts, tenant))
        seen = {}
        for res in node_results:
            for mn in res:
                seen.setdefault(mn.marshal(), mn)
        return [seen[k] for k in sorted(seen)][:limit]

    def label_names(self, min_ts=None, max_ts=None, tenant=(0, 0)):
        res = self._fanout(lambda n: n.label_names(min_ts, max_ts, tenant))
        return sorted(set().union(*map(set, res))) if res else []

    def label_values(self, key, min_ts=None, max_ts=None, tenant=(0, 0)):
        res = self._fanout(
            lambda n: n.label_values(key, min_ts, max_ts, tenant))
        return sorted(set().union(*map(set, res))) if res else []

    def delete_series(self, filters, tenant=(0, 0)):
        return sum(self._fanout(lambda n: n.delete_series(filters, tenant)))

    def series_count(self, tenant=(0, 0)):
        return sum(self._fanout(lambda n: n.series_count(tenant)))

    def tenants(self):
        res = self._fanout(lambda n: n.tenants())
        return sorted(set().union(*map(set, res))) if res else []

    def tsdb_status(self, date=None, topn=10, tenant=(0, 0)):
        results = self._fanout(lambda n: n.tsdb_status(topn, date, tenant))
        total = sum(r["totalSeries"] for r in results)

        def merge_top(key):
            acc = {}
            for r in results:
                for e in r.get(key, []):
                    acc[e["name"]] = acc.get(e["name"], 0) + e["count"]
            return [{"name": k, "count": c} for k, c in
                    sorted(acc.items(), key=lambda kv: -kv[1])[:topn]]

        return {"totalSeries": total,
                "seriesCountByMetricName": merge_top("seriesCountByMetricName"),
                "seriesCountByLabelName": merge_top("seriesCountByLabelName"),
                "seriesCountByLabelValuePair":
                    merge_top("seriesCountByLabelValuePair")}

    def metrics(self):
        return {"vm_cluster_nodes": len(self.nodes),
                "vm_cluster_rows_sent_total": self.rows_sent,
                "vm_cluster_reroutes_total": self.reroutes,
                "vm_cluster_healthy_nodes":
                    sum(1 for n in self.nodes if n.healthy)}

    def close(self):
        for n in self.nodes:
            n.close()
