"""Device-mesh sharding for the query engine.

The reference scales reads by fanning a query out to every vmstorage node and
merging per-node partial aggregates (lib/vmselectapi scatter-gather +
aggr_incremental.go map-reduce). On TPU the same shape becomes: shard the
series axis over a `jax.sharding.Mesh` and let GSPMD partition the
segment-reduction — the cross-shard merge is the XLA-inserted all-reduce,
not a hand-written psum loop.

Two parallel axes are first-class:

- AXIS_SERIES ("series"): data-parallel over series. The single-device
  fused kernel (ops.device_rollup.rollup_aggregate_tile) is jit'd with
  declarative in/out shardings from the partition-rule table
  (parallel/partition.py); each device rolls up its series shard and XLA
  reduces the [G, T] group moments across shards.
- AXIS_TIME ("time"): sequence-parallel over the *sample* axis (the
  long-context analog). Each device holds a contiguous time-slice of every
  series' samples; rollup windows crossing the slice boundary need the tail
  of the left neighbor, exchanged with `lax.ppermute` (ring halo exchange,
  like ring attention passes KV blocks). This path keeps an explicit
  shard_map: the halo exchange is a genuinely manual collective that has
  no declarative spelling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax exposes it under experimental only
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.device_rollup import rollup_tile
from ..ops.rollup_np import RollupConfig
from .partition import (AXIS_SERIES, AXIS_STREAM, AXIS_TIME,
                        input_shardings, replicated, sharding_for)


def make_mesh(n_series: int | None = None, n_time: int = 1,
              devices=None) -> Mesh:
    """Build a (series, time) mesh over the available devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if n_series is None:
        n_series = n // n_time
    if n_series * n_time != n:
        raise ValueError(f"mesh {n_series}x{n_time} != {n} devices")
    arr = np.asarray(devices).reshape(n_series, n_time)
    return Mesh(arr, (AXIS_SERIES, AXIS_TIME))


def make_fleet_mesh(devices=None) -> Mesh:
    """One-axis mesh sharding the fleet's leading STREAM axis over every
    device: each device runs a contiguous slice of the resident streams'
    whole programs (rollup windows never cross streams, so this axis
    needs no halo exchange or cross-device reduction at all)."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (AXIS_STREAM,))


@functools.lru_cache(maxsize=256)
def cached_fleet_rollup_aggregate(mesh: Mesh, rollup_func: str,
                                  cfg: RollupConfig, num_groups: int):
    """Memoized fleet kernel for one bucket shape: the [B, S, N] planes
    shard over AXIS_STREAM per the partition-rule table; the aggregate is
    a per-stream traced code, so one compile covers every aggregate mix
    (see ops.device_rollup.fleet_rollup_aggregate_impl).  The [B, G, T]
    output stays stream-sharded — the single host pull gathers it."""
    from ..ops.device_rollup import fleet_rollup_aggregate_impl
    in_sh = input_shardings(
        mesh, (("fleet_ts", 3), ("fleet_values", 3), ("fleet_counts", 2),
               ("fleet_gids", 2), ("fleet_aggr", 1), ("fleet_shift", 1),
               ("fleet_min_ts", 1), ("fleet_v0", 2)))

    @functools.partial(jax.jit, in_shardings=in_sh,
                       out_shardings=sharding_for(mesh, "fleet_out", 3))
    def step(fleet_ts, fleet_values, fleet_counts, fleet_gids, fleet_aggr,
             fleet_shift, fleet_min_ts, fleet_v0):
        return fleet_rollup_aggregate_impl(
            rollup_func, cfg, num_groups, fleet_ts, fleet_values,
            fleet_counts, fleet_gids, fleet_aggr, fleet_shift,
            fleet_min_ts, fleet_v0)

    from ..query.tpu_engine import with_executable_cache
    return with_executable_cache(step, f"fleet_rollup:{rollup_func}")


@functools.lru_cache(maxsize=256)
def cached_sharded_rollup_aggregate(mesh: Mesh, rollup_func: str, aggr: str,
                                    cfg: RollupConfig, num_groups: int):
    """Memoized sharded_rollup_aggregate: the serving engine calls this per
    query; without memoization every call would build a fresh closure and
    miss jax's jit cache."""
    return sharded_rollup_aggregate(mesh, rollup_func, aggr, cfg, num_groups)


def sharded_rollup_aggregate(mesh: Mesh, rollup_func: str, aggr: str,
                             cfg: RollupConfig, num_groups: int):
    """Build a jitted aggr(rollup(...)) running series-sharded on the mesh.

    Declarative GSPMD partitioning: the SAME fused kernel the single-device
    engine runs (ops.device_rollup.rollup_aggregate_tile) is jit'd with
    in/out shardings derived from the partition-rule table — the
    per-shard segment moments and the cross-shard reduction are one XLA
    program, with the all-reduce inserted by the partitioner instead of a
    hand-rolled shard_map closure + psum.

    Inputs: ts [S, N] int32, values [S, N], counts [S] int32,
    group_ids [S] int32, shift int32 scalar (rolling-tile grid rebase, 0
    for freshly built tiles), min_ts int32 scalar, v0 [S] (per-series
    rebase offsets of f32 tiles; zeros otherwise); S must be divisible by
    the series-axis size. Output: [G, T] fully replicated.
    """
    from ..ops.device_rollup import rollup_aggregate_tile
    in_sh = input_shardings(mesh, (("ts", 2), ("values", 2), ("counts", 1),
                                   ("group_ids", 1), ("shift", 0),
                                   ("min_ts", 0), ("v0", 1)))

    @functools.partial(jax.jit, in_shardings=in_sh,
                       out_shardings=replicated(mesh))
    def step(ts, values, counts, group_ids, shift, min_ts, v0):
        return rollup_aggregate_tile(rollup_func, aggr, ts, values, counts,
                                     group_ids, cfg, num_groups, shift,
                                     min_ts, v0)

    def call(ts, values, counts, group_ids, shift, min_ts, v0=None):
        if v0 is None:
            v0 = jnp.zeros(ts.shape[0], values.dtype)
        return step(ts, values, counts, group_ids, jnp.int32(shift),
                    jnp.int32(min_ts), v0)

    return call


def time_sharded_rollup(mesh: Mesh, rollup_func: str, cfg: RollupConfig,
                        halo: int):
    """Sequence-parallel rollup: the sample axis is sharded over AXIS_TIME.

    Each device holds a contiguous chunk of every series' samples (padded to
    equal chunk length; chunk boundaries aligned to time so chunk i's samples
    all precede chunk i+1's). Before rolling up, each device receives the
    trailing `halo` samples of its left neighbor via lax.ppermute — enough to
    cover one lookback window plus the real-prev-value gather — then computes
    only the output steps whose windows it owns.

    Output-step ownership: step j belongs to the device whose time range
    contains the step's timestamp; here we simply split the T output steps
    contiguously across AXIS_TIME and all-gather at the end.

    Counter-reset correction stays exact across chunks because the halo
    overlap lets each device reconstruct resets local to its windows; resets
    older than one window+halo do not affect windowed rollups (they cancel in
    the window difference).
    """
    if rollup_func in _TIME_SHARD_UNSUPPORTED:
        raise ValueError(
            f"{rollup_func} needs whole-series context (first sample) and "
            "cannot run on the time-sharded path; use series sharding")
    n_time = mesh.shape[AXIS_TIME]
    T_total = (cfg.end - cfg.start) // cfg.step + 1
    if T_total % n_time:
        raise ValueError(f"T={T_total} not divisible by time axis {n_time}")
    t_shard = T_total // n_time

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS_SERIES, AXIS_TIME), P(AXIS_SERIES, AXIS_TIME),
                  P(AXIS_SERIES, AXIS_TIME)),
        out_specs=P(AXIS_SERIES, AXIS_TIME))
    def step(ts, values, valid):
        # ring halo: receive left neighbor's tail
        idx = jax.lax.axis_index(AXIS_TIME)
        perm = [(i, (i + 1) % n_time) for i in range(n_time)]
        tail_ts = jax.lax.ppermute(ts[:, -halo:], AXIS_TIME, perm)
        tail_v = jax.lax.ppermute(values[:, -halo:], AXIS_TIME, perm)
        tail_ok = jax.lax.ppermute(valid[:, -halo:], AXIS_TIME, perm)
        # device 0 has no left neighbor: its received halo is garbage; mask.
        tail_ok = jnp.where(idx == 0, False, tail_ok)
        ts_ext = jnp.concatenate([tail_ts, ts], axis=1)
        v_ext = jnp.concatenate([tail_v, values], axis=1)
        ok_ext = jnp.concatenate([tail_ok, valid], axis=1)
        counts = jnp.sum(ok_ext, axis=1).astype(jnp.int32)
        # Compact valid samples to the front (stable sort on the invalid
        # flag keeps time order: halo precedes local by construction).
        order = jnp.argsort(jnp.where(ok_ext, 0, 1), axis=1, stable=True)
        ts_c = jnp.take_along_axis(jnp.where(ok_ext, ts_ext, 2**31 - 1), order, axis=1)
        v_c = jnp.take_along_axis(jnp.where(ok_ext, v_ext, 0.0), order, axis=1)
        # local output grid slice
        local_cfg = RollupConfig(
            start=cfg.start, end=cfg.start + (t_shard - 1) * cfg.step,
            step=cfg.step, window=cfg.window)
        shift = idx * t_shard * cfg.step
        rolled = rollup_tile_shifted(rollup_func, ts_c, v_c, counts,
                                     local_cfg, shift)
        return rolled

    return jax.jit(step)


# Funcs needing whole-series context that chunked time sharding cannot see.
_TIME_SHARD_UNSUPPORTED = frozenset({"lifetime"})

# Funcs returning absolute times: rollup_tile adds cfg.start back, so the
# chunk's grid shift must be re-added on top.
_TIME_VALUED = frozenset({"tfirst_over_time", "tlast_over_time", "timestamp"})


def rollup_tile_shifted(func, ts, values, counts, cfg, shift):
    """rollup_tile with the output grid shifted by a traced offset (used by
    time-sharded evaluation where each device owns a grid slice)."""
    out = rollup_tile(func, ts - shift, values, counts, cfg)
    if func in _TIME_VALUED:
        out = out + shift.astype(out.dtype) / 1e3
    return out
