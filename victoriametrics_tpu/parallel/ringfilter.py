"""Reroute-aware read serving: per-series ring-ownership filtering on the
storage node (ROADMAP item 3a, PR 10's named leftover).

The vmselect ships its consistent-hash view — node names, replication
factor, the target node's own index, and the currently-down node
indexes — as a trailing ``search_v1``/``searchColumns_v1`` field.  A
storage node that understands it serves only the series it OWNS under
that ring instead of everything it has:

- healthy ring: node i serves exactly the series whose rendezvous
  first choice is i.  With RF=N a full fan-out otherwise returns N
  copies of every series (the vmselect dedups them after shipping),
  so ownership filtering divides wire bytes and vmselect merge work
  by RF.  The filter currently runs AFTER the node's own fetch (the
  handlers apply keep_mask to the search result), so node-side disk
  scan/decode still reads every replica copy — pushing the mask into
  the index-resolution stage is the named follow-up (ROADMAP item 3
  leftovers);
- down node d: the first choice is re-computed EXCLUDING d
  (``ConsistentHash.nodes_for_key`` exclusion sets), so each survivor
  explicitly serves the slice of d's hash ranges for which it is the
  RF-2 replica — a one-node outage costs only that node's key share,
  never a partial result or a full re-fan (``vm_reroute_reads_total``
  ticks on both sides);
- orphan data — series a node holds although the ring says it is not
  among their RF owners (write reroutes while an owner was down, parts
  adopted by live resharding, a ring that shrank) — is ALWAYS served:
  the rightful owner may not have those bytes, and duplicate rows
  collapse in the vmselect's raw-name merge exactly like replica
  overlap.

The filter is an ownership claim, so it is only honored by backends
that actually hold ring-placed data: ``storage.Storage`` declares
``supports_ring_filter``; a multilevel vmselect's ClusterStorage does
NOT (its own nodes were not placed by the caller's ring), so the
mid-level returns unfiltered rows, acks nothing, and the top-level
dedup keeps correctness.  Peers that never ack (old nodes) degrade the
optimization, never the result.

Known trade (documented in README): a node that was down and lost
writes to its RF-2 replica serves its primary share again the moment
it is back, so rows written during its downtime are hidden until the
replica copy lands back on it (a merge/migration concern, not a test
concern — the down-marking window is ~2s).  ``VM_RING_FILTER=0``
restores the full-coverage fan-out and is the bit-equality oracle.
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

from ..devtools.locktrace import make_lock
from ..utils import metrics as metricslib
from .consistenthash import ConsistentHash

#: reads served from a replica for a DOWN node's hash ranges (ticks on
#: the vmselect per rerouted fan-out and on each storage node per
#: rerouted search it answered)
REROUTE_READS = metricslib.REGISTRY.counter("vm_reroute_reads_total")

_TEN = struct.Struct(">II")


def enabled() -> bool:
    """Ring-ownership read filtering (default on); ``VM_RING_FILTER=0``
    is the escape hatch and full-fan-out bit-equality oracle."""
    return os.environ.get("VM_RING_FILTER", "1") != "0"


class RingConfig:
    """One (node list, rf, self index, down set) view, with a bounded
    per-series ownership memo — a rolling dashboard re-reads the same
    series every refresh, so the two rendezvous hashes per series run
    once per ring state, not once per query."""

    _MAX_MEMO = 1 << 20

    def __init__(self, nodes: list[str], rf: int, self_index: int,
                 down: frozenset[int]):
        self.nodes = list(nodes)
        self.rf = max(int(rf), 1)
        self.self_index = int(self_index)
        self.down = frozenset(int(d) for d in down)
        self.ch = ConsistentHash(self.nodes)
        self._memo: dict[bytes, tuple[bool, bool]] = {}
        self._lock = make_lock("parallel.RingConfig._memo")

    def to_json(self) -> bytes:
        return json.dumps({"nodes": self.nodes, "rf": self.rf,
                           "self": self.self_index,
                           "down": sorted(self.down)}).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "RingConfig | None":
        try:
            d = json.loads(data)
            return cls(list(d["nodes"]), int(d.get("rf", 1)),
                       int(d["self"]), frozenset(d.get("down", ())))
        except (ValueError, KeyError, TypeError):
            return None  # malformed ring never fails the search

    def _verdict(self, key: bytes) -> tuple[bool, bool]:
        """(serve, rerouted) for one placement key (tenant prefix +
        canonical metric-name marshal — the write router's shard key)."""
        owners = self.ch.nodes_for_key(key, self.rf)
        if self.self_index not in owners:
            # orphan data: the ring says this node should not hold the
            # series, so nobody else is guaranteed to — always serve
            return True, False
        first = self.ch.nodes_for_key(key, 1, set(self.down))
        serve = bool(first) and first[0] == self.self_index
        # rerouted: this node serves a share whose unexcluded primary
        # is currently down (the explicit replica read)
        rerouted = serve and bool(self.down) and owners[0] in self.down
        return serve, rerouted

    def keep_mask(self, tenant, raw_names,
                  exempt=None) -> tuple[np.ndarray, int]:
        """Boolean keep mask over ``raw_names`` (canonical marshals) +
        how many kept series were served via reroute.  ``exempt`` is
        the node's always-serve set (``Storage.ring_exempt_names``:
        series adopted by part migration or landed by write reroutes —
        this node may hold their only copy, so ownership suppression
        never applies)."""
        tkey = _TEN.pack(tenant[0], tenant[1])
        keep = np.empty(len(raw_names), bool)
        rerouted = 0
        memo = self._memo
        for i, raw in enumerate(raw_names):
            if exempt is not None and raw in exempt:
                keep[i] = True
                continue
            key = tkey + raw
            got = memo.get(key)
            if got is None:
                got = self._verdict(key)
                with self._lock:
                    if len(memo) >= self._MAX_MEMO:
                        memo.clear()
                    memo[key] = got
            keep[i] = got[0]
            rerouted += got[1]
        return keep, rerouted


# ring states are few (node lists x small down sets); intern them so the
# per-series memo survives across calls
_RINGS: dict[tuple, RingConfig] = {}
_RINGS_LOCK = make_lock("parallel.ringfilter._RINGS")
_MAX_RINGS = 64


def get_ring(nodes, rf: int, self_index: int, down) -> RingConfig:
    """Interned RingConfig for one (nodes, rf, self, down) state — both
    sides use this so the per-series memos survive across calls."""
    sig = (tuple(nodes), int(rf), int(self_index), frozenset(down))
    with _RINGS_LOCK:
        got = _RINGS.get(sig)
        if got is not None:
            return got
    rc = RingConfig(list(nodes), rf, self_index, frozenset(down))
    with _RINGS_LOCK:
        if len(_RINGS) >= _MAX_RINGS:
            _RINGS.clear()
        return _RINGS.setdefault(sig, rc)


def intern_ring(data: bytes) -> RingConfig | None:
    """Parse + intern a shipped ring config (None on malformed)."""
    rc = RingConfig.from_json(data)
    if rc is None:
        return None
    return get_ring(rc.nodes, rc.rf, rc.self_index, rc.down)
