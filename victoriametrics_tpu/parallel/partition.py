"""Declarative partition rules for the (series, time) device mesh.

The serving engine used to wire hand-rolled ``shard_map`` closures per
kernel (manual in_specs/out_specs + explicit psum of partial moments) and
repeated ad-hoc ``NamedSharding(mesh, P(...))`` construction at every
device_put site.  This module replaces both with ONE rule table in the
``match_partition_rules`` style (SNIPPETS [2]/[3]): tile leaves are
*named*, a regex table maps each name onto the mesh axes, and every
placement/jit decision derives from that single source of truth.

Layout contract (the one place it is written down):

- packed sample planes and rollup blocks ``[S, ...]`` — ``ts``,
  ``values``, the delta planes' ``*_d2`` — shard their leading (series)
  row axis over ``AXIS_SERIES``; the sample/time axis stays local so
  windowed rollups never need halo exchange on this path.
- per-series vectors ``[S]`` — ``counts``, ``group_ids``, ``v0``,
  ``scale``, ``slots``, the delta planes' firsts/fdeltas — shard over
  ``AXIS_SERIES`` too.
- aggregated ``[G, T]`` outputs and scalars (``shift``, ``min_ts``) are
  replicated: every host pull reads one device's copy, and group moments
  cross shards through the XLA-inserted all-reduce (GSPMD), not a
  hand-written psum.

``shard_put`` pads the series axis to a multiple of the mesh's series
axis (kernels mask padded rows via ``counts == 0`` / ``TS_PAD``) and
counts uploaded bytes into the device-plane metrics.
"""

from __future__ import annotations

import re

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_SERIES = "series"
AXIS_TIME = "time"
AXIS_STREAM = "stream"

# regex -> spec-per-rank: rank 1 leaves drop the trailing None axes.
# First match wins; unknown leaf names fail loudly (a silently replicated
# (S, N) plane would upload S*N bytes to EVERY device).
PARTITION_RULES: tuple[tuple[str, P], ...] = (
    # fleet-batched planes: a leading stream axis stacks every resident
    # window into one [B, ...] program (query/fleet.py) — the batch axis
    # shards over AXIS_STREAM, everything below it stays device-local so
    # per-stream rollups never exchange halos
    (r"^fleet_(ts|values|vals|out)$", P(AXIS_STREAM, None, None)),
    (r"^fleet_(counts|gids|v0)$", P(AXIS_STREAM, None)),
    (r"^fleet_(shift|min_ts|aggr)$", P(AXIS_STREAM)),
    # packed (S, N) sample planes / (S, T) rollup blocks / delta planes
    (r"^(ts|values|vals)$", P(AXIS_SERIES, None)),
    (r"_d2$", P(AXIS_SERIES, None)),
    # per-series vectors
    (r"^(counts|group_ids|gids|slots|v0|scale)$", P(AXIS_SERIES)),
    (r"(_first|_fdelta)$", P(AXIS_SERIES)),
    # aggregated outputs and traced scalars: replicated
    (r"^(out|shift|min_ts|phi)$", P()),
)


def match_partition_rules(name: str, ndim: int,
                          rules=PARTITION_RULES) -> P:
    """PartitionSpec for a named tile leaf (first matching rule wins),
    truncated to the leaf's rank.  Scalars are always replicated —
    partitioning a 0-d value is meaningless (SNIPPETS [3] does the same
    short-circuit)."""
    if ndim == 0:
        return P()
    for rule, spec in rules:
        if re.search(rule, name) is not None:
            return P(*spec[:ndim])
    raise ValueError(f"no partition rule matches tile leaf {name!r}")


def sharding_for(mesh: Mesh, name: str, ndim: int) -> NamedSharding:
    return NamedSharding(mesh, match_partition_rules(name, ndim))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_multiple(mesh: Mesh) -> int:
    """Series-axis padding multiple for row-sharded tiles."""
    return int(mesh.shape[AXIS_SERIES])


def axis_multiple(mesh: Mesh, axis: str) -> int:
    """Padding multiple for tiles whose leading axis shards over `axis`
    (1 when the mesh doesn't carry that axis)."""
    return int(mesh.shape.get(axis, 1)) if mesh is not None else 1


def pad_rows_to_mesh(mesh: Mesh, a: np.ndarray, pad_value=0,
                     axis: str = AXIS_SERIES) -> np.ndarray:
    """Pad the leading axis to a multiple of the mesh axis it shards over
    so the shards are equal-sized."""
    n_sh = axis_multiple(mesh, axis)
    S = a.shape[0]
    S_pad = -(-S // n_sh) * n_sh
    if S_pad == S:
        return a
    widths = ((0, S_pad - S),) + ((0, 0),) * (a.ndim - 1)
    return np.pad(a, widths, constant_values=pad_value)


def shard_put(mesh: Mesh | None, name: str, a: np.ndarray, pad_value=0):
    """Place one named host array onto the mesh per the rule table
    (row-padded when row-sharded); single-device engines (mesh None)
    take the chunked upload path.  All device-plane uploads funnel
    through here or tile_cache.chunked_device_put, so
    vm_device_bytes_uploaded_total sees every H2D byte."""
    from ..models.tile_cache import chunked_device_put, timed_transfer
    if mesh is None:
        return chunked_device_put(np.asarray(a))
    import jax
    a = np.asarray(a)
    spec = match_partition_rules(name, a.ndim)
    if a.ndim and spec[0] in (AXIS_SERIES, AXIS_STREAM):
        a = pad_rows_to_mesh(mesh, a, pad_value, axis=spec[0])
    return timed_transfer(
        "device:upload", a.nbytes,
        lambda: jax.device_put(a, NamedSharding(mesh, spec)))


def input_shardings(mesh: Mesh, names_ndims) -> tuple:
    """in_shardings tuple for a jit'd kernel, one entry per (name, ndim)."""
    return tuple(sharding_for(mesh, n, d) for n, d in names_ndims)
