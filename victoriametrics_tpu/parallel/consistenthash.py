"""Rendezvous (highest-random-weight) hashing for series->storage-node
placement with exclusion lists for rerouting around unhealthy nodes
(reference lib/consistenthash/consistent_hash.go:11-55)."""

from __future__ import annotations

import xxhash


class ConsistentHash:
    def __init__(self, node_ids: list[str], seed: int = 0):
        self.node_ids = list(node_ids)
        self._node_hashes = [
            xxhash.xxh64_intdigest(n.encode(), seed=seed) for n in node_ids]

    def node_index(self, key_hash: int, excluded: set[int] | None = None) -> int:
        """Pick the node for a key (already hashed), skipping excluded
        indexes. Returns -1 if all nodes are excluded."""
        best = -1
        best_w = -1
        for i, nh in enumerate(self._node_hashes):
            if excluded and i in excluded:
                continue
            # mix the key hash with the node hash (rendezvous weight)
            w = xxhash.xxh64_intdigest(
                key_hash.to_bytes(8, "little"), seed=nh & 0xFFFFFFFF)
            if w > best_w:
                best_w = w
                best = i
        return best

    def nodes_for_key(self, key: bytes, replication: int = 1,
                      excluded: set[int] | None = None) -> list[int]:
        """Top-N distinct nodes for a key (write fan-out under
        -replicationFactor=N)."""
        kh = xxhash.xxh64_intdigest(key)
        out: list[int] = []
        ex = set(excluded or ())
        while len(out) < replication:
            i = self.node_index(kh, ex)
            if i < 0:
                break
            out.append(i)
            ex.add(i)
        return out
