"""Per-series cardinality/shape limits applied at ingestion (reference
lib/timeserieslimits/timeseries_limits.go:34-134): series exceeding the
limits are dropped (counted, throttled-logged), protecting the index from
malformed or abusive payloads."""

from __future__ import annotations

from ..utils import logger


class SeriesLimits:
    def __init__(self, max_labels_per_series: int = 40,
                 max_label_name_len: int = 256,
                 max_label_value_len: int = 4 * 1024):
        self.max_labels = max_labels_per_series
        self.max_name_len = max_label_name_len
        self.max_value_len = max_label_value_len
        self.dropped_labels_limit = 0
        self.dropped_name_len = 0
        self.dropped_value_len = 0

    def check(self, labels: dict) -> bool:
        """True if the series passes; False = drop (with throttled log).
        A limit <= 0 disables that check (reference semantics)."""
        if self.max_labels > 0 and len(labels) > self.max_labels:
            self.dropped_labels_limit += 1
            logger.throttled_warnf(
                "serieslimit-count", 5,
                "dropping series with %d labels (limit %d)",
                len(labels), self.max_labels)
            return False
        for k, v in labels.items():
            if self.max_name_len > 0 and len(k) > self.max_name_len:
                self.dropped_name_len += 1
                logger.throttled_warnf(
                    "serieslimit-name", 5,
                    "dropping series with %d-byte label name (limit %d)",
                    len(k), self.max_name_len)
                return False
            if self.max_value_len > 0 and len(str(v)) > self.max_value_len:
                self.dropped_value_len += 1
                logger.throttled_warnf(
                    "serieslimit-value", 5,
                    "dropping series with %d-byte label value (limit %d)",
                    len(str(v)), self.max_value_len)
                return False
        return True

    def metrics(self) -> dict:
        # labeled form matches the reference's vm_rows_ignored_total{reason}
        return {
            'vm_rows_ignored_total{reason="too_many_labels"}':
                self.dropped_labels_limit,
            'vm_rows_ignored_total{reason="too_long_label_name"}':
                self.dropped_name_len,
            'vm_rows_ignored_total{reason="too_long_label_value"}':
                self.dropped_value_len,
        }
