"""Stream aggregation (reference lib/streamaggr/streamaggr.go: YAML-configured
aggregators with 20 output kinds, by/without grouping, interval flushers,
plus the standalone deduplicator).

Config entry:
  match: '{__name__=~"http_.*"}'     # optional series selector(s)
  interval: 60s
  outputs: [total, sum_samples, quantiles(0.9, 0.99), ...]
  by: [instance] | without: [pod]
  keep_metric_names: false
  dedup_interval: 0s

Aggregated rows flush every `interval` to the push callback as
{name}:{interval}_{output} series (the reference naming scheme).
"""

from __future__ import annotations

import math
import re
import threading

from ..query.metricsql import parse as mql_parse
from ..query.metricsql.ast import MetricExpr
from ..query.metricsql.parser import parse_duration_ms
from ..storage.tag_filters import TagFilter
from ..utils import fasttime

OUTPUT_KINDS = (
    "avg count_samples count_series histogram_bucket increase "
    "increase_prometheus last max min quantiles rate_avg rate_sum stddev "
    "stdvar sum_samples total total_prometheus unique_samples "
    "count_samples_total sum_samples_total"
).split()

_HIST_BUCKETS = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100,
                 500, 1000, float("inf")]


class _SeriesState:
    __slots__ = ("count", "sum", "sum2", "min", "max", "last", "last_ts",
                 "first", "prev_value", "total", "uniq", "hist", "rate_prev",
                 "rate_prev_ts", "rate_acc")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.sum2 = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.last = math.nan
        self.last_ts = 0
        self.first = None
        self.prev_value = None      # across flushes, for total/increase
        self.total = 0.0
        self.uniq = set()
        self.hist = None
        self.rate_prev = None
        self.rate_prev_ts = None
        self.rate_acc = 0.0


def _match_selectors(expr):
    if expr is None:
        return None
    exprs = expr if isinstance(expr, list) else [expr]
    out = []
    for e in exprs:
        ast = mql_parse(str(e))
        if not isinstance(ast, MetricExpr):
            raise ValueError(f"streamaggr match must be a selector: {e}")
        # the match list is already a union, so a selector's OR'd filter
        # sets ({a="b" or c="d"}) expand into extra entries; one shared
        # lowering (query/eval) keeps ingest- and query-side semantics
        # identical
        from ..query.eval import filter_sets_from_metric_expr
        out.extend(filter_sets_from_metric_expr(ast))
    return out


class Aggregator:
    def __init__(self, cfg: dict, push_fn):
        self.interval_ms = int(parse_duration_ms(cfg["interval"])[0])
        if self.interval_ms <= 0:
            raise ValueError("streamaggr: bad interval")
        self.outputs = []
        self.quantile_phis = []
        for o in cfg["outputs"]:
            m = re.fullmatch(r"quantiles\(([^)]*)\)", o)
            if m:
                self.outputs.append("quantiles")
                self.quantile_phis = [float(x) for x in m.group(1).split(",")]
            elif o in OUTPUT_KINDS:
                self.outputs.append(o)
            else:
                raise ValueError(f"streamaggr: unknown output {o!r}")
        self.by = cfg.get("by") or []
        self.without = cfg.get("without") or []
        self.keep_metric_names = bool(cfg.get("keep_metric_names"))
        self.match = _match_selectors(cfg.get("match"))
        self.push_fn = push_fn
        self._lock = threading.Lock()
        self._state: dict[tuple, tuple[dict, _SeriesState, list]] = {}
        self._samples_buf: dict[tuple, list] = {}

    def matches(self, labels: dict) -> bool:
        if self.match is None:
            return True
        for filters in self.match:
            ok = True
            for tf in filters:
                key = "__name__" if tf.key == b"" else tf.key.decode()
                if not tf.match_value(labels.get(key, "").encode()):
                    ok = False
                    break
            if ok:
                return True
        return False

    def _group_key(self, labels: dict) -> tuple[tuple, dict]:
        name = labels.get("__name__", "")
        if self.by:
            kept = {k: labels[k] for k in self.by if k in labels}
        elif self.without:
            kept = {k: v for k, v in labels.items()
                    if k not in self.without and k != "__name__"}
        else:
            kept = {k: v for k, v in labels.items() if k != "__name__"}
        key = (name,) + tuple(sorted(kept.items()))
        return key, kept

    def push(self, labels: dict, ts_ms: int, value: float) -> None:
        if math.isnan(value):
            return
        key, kept = self._group_key(labels)
        with self._lock:
            entry = self._state.get(key)
            if entry is None:
                entry = (kept, _SeriesState(), [])
                self._state[key] = entry
            _, st, samples = entry
            st.count += 1
            st.sum += value
            st.sum2 += value * value
            st.min = min(st.min, value)
            st.max = max(st.max, value)
            st.last = value
            st.last_ts = ts_ms
            if st.first is None:
                st.first = value
            if "unique_samples" in self.outputs:
                st.uniq.add(value)
            if "quantiles" in self.outputs:
                samples.append(value)
            if "histogram_bucket" in self.outputs:
                if st.hist is None:
                    st.hist = [0] * len(_HIST_BUCKETS)
                for i, ub in enumerate(_HIST_BUCKETS):
                    if value <= ub:
                        st.hist[i] += 1
                        break
            if {"total", "total_prometheus", "increase",
                    "increase_prometheus", "rate_sum", "rate_avg"} & \
                    set(self.outputs):
                prev = st.rate_prev
                if prev is not None:
                    d = value - prev
                    if d < 0:  # counter reset
                        d = value
                    st.total += d
                    if st.rate_prev_ts and ts_ms > st.rate_prev_ts:
                        st.rate_acc += d / ((ts_ms - st.rate_prev_ts) / 1e3)
                elif self_outputs_include_initial(self.outputs):
                    st.total += value
                st.rate_prev = value
                st.rate_prev_ts = ts_ms

    def flush(self, now_ms: int | None = None) -> None:
        now_ms = now_ms or fasttime.unix_ms()
        with self._lock:
            state, self._state = self._state, {}
        suffix_base = _interval_str(self.interval_ms)
        out_rows = []
        n_series = {}
        for key, (kept, st, samples) in state.items():
            name = key[0]
            for o in self.outputs:
                vals: list[tuple[str, float, dict]] = []
                if o == "avg":
                    vals.append(("avg", st.sum / st.count, {}))
                elif o == "count_samples":
                    vals.append(("count_samples", float(st.count), {}))
                elif o in ("count_samples_total",):
                    vals.append(("count_samples_total", float(st.count), {}))
                elif o == "count_series":
                    vals.append(("count_series", 1.0, {}))
                elif o == "last":
                    vals.append(("last", st.last, {}))
                elif o == "min":
                    vals.append(("min", st.min, {}))
                elif o == "max":
                    vals.append(("max", st.max, {}))
                elif o in ("sum_samples", "sum_samples_total"):
                    vals.append((o, st.sum, {}))
                elif o == "stddev":
                    var = max(st.sum2 / st.count - (st.sum / st.count) ** 2, 0)
                    vals.append(("stddev", math.sqrt(var), {}))
                elif o == "stdvar":
                    var = max(st.sum2 / st.count - (st.sum / st.count) ** 2, 0)
                    vals.append(("stdvar", var, {}))
                elif o in ("total", "total_prometheus", "increase",
                           "increase_prometheus"):
                    vals.append((o, st.total, {}))
                elif o in ("rate_sum", "rate_avg"):
                    r = st.rate_acc
                    if o == "rate_avg":
                        r = r  # per-series avg handled at merge below
                    vals.append((o, r, {}))
                elif o == "unique_samples":
                    vals.append(("unique_samples", float(len(st.uniq)), {}))
                elif o == "quantiles":
                    s = sorted(samples)
                    for phi in self.quantile_phis:
                        if s:
                            idx = min(int(phi * len(s)), len(s) - 1)
                            vals.append(("quantiles", s[idx],
                                         {"quantile": str(phi)}))
                elif o == "histogram_bucket":
                    if st.hist:
                        cum = 0
                        for i, ub in enumerate(_HIST_BUCKETS):
                            cum += st.hist[i]
                            le = "+Inf" if math.isinf(ub) else str(ub)
                            vals.append(("histogram_bucket", float(cum),
                                         {"le": le}))
                for suffix, v, extra in vals:
                    if self.keep_metric_names:
                        out_name = name
                    else:
                        out_name = f"{name}:{suffix_base}_{suffix}"
                    labels = {"__name__": out_name, **kept, **extra}
                    out_rows.append((labels, now_ms, v))
        if out_rows:
            self.push_fn(out_rows)


def self_outputs_include_initial(outputs) -> bool:
    """total/increase count a series' first seen value from zero; the
    _prometheus variants don't (strict Prometheus semantics)."""
    return bool({"total", "increase"} & set(outputs)) and not (
        {"total_prometheus", "increase_prometheus"} & set(outputs))


def _interval_str(ms: int) -> str:
    if ms % 3_600_000 == 0:
        return f"{ms // 3_600_000}h"
    if ms % 60_000 == 0:
        return f"{ms // 60_000}m"
    return f"{ms // 1000}s"


class Deduplicator:
    """Standalone streaming dedup (lib/streamaggr/deduplicator.go): keeps the
    last sample per series per interval."""

    def __init__(self, interval_ms: int, push_fn):
        self.interval_ms = interval_ms
        self.push_fn = push_fn
        self._lock = threading.Lock()
        self._state: dict[tuple, tuple[dict, int, float]] = {}

    def push(self, labels: dict, ts_ms: int, value: float):
        key = tuple(sorted(labels.items()))
        with self._lock:
            cur = self._state.get(key)
            if cur is None or ts_ms >= cur[1]:
                self._state[key] = (labels, ts_ms, value)

    def flush(self, now_ms: int | None = None):
        with self._lock:
            state, self._state = self._state, {}
        rows = [(labels, ts, v) for labels, ts, v in state.values()]
        if rows:
            self.push_fn(rows)


def load_from_text(yaml_text: str, push_fn) -> "StreamAggregators":
    """Parse a YAML aggregation config (list of aggregator entries) — the
    streamaggr.LoadFromData entry point used by vmsingle/vminsert/vmagent."""
    import yaml
    cfgs = yaml.safe_load(yaml_text) or []
    if not isinstance(cfgs, list):
        raise ValueError("streamaggr config must be a YAML list of "
                         "aggregator entries")
    return StreamAggregators(cfgs, push_fn)


class StreamAggregators:
    """The aggregator set + its flusher thread (streamaggr.LoadFromData)."""

    def __init__(self, configs: list[dict], push_fn):
        self.aggregators = [Aggregator(c, push_fn) for c in configs]
        self._stop = threading.Event()
        self._threads = []

    def push(self, labels: dict, ts_ms: int, value: float) -> bool:
        """Returns True if any aggregator consumed the sample."""
        consumed = False
        for a in self.aggregators:
            if a.matches(labels):
                a.push(labels, ts_ms, value)
                consumed = True
        return consumed

    def start(self):
        for a in self.aggregators:
            # one long-lived flush ticker per aggregator — not fan-out
            t = threading.Thread(  # vmt: disable=VMT011
                target=self._flush_loop, args=(a,), daemon=True)
            t.start()
            self._threads.append(t)

    def _flush_loop(self, a: Aggregator):
        while not self._stop.wait(a.interval_ms / 1e3):
            try:
                a.flush()
            except Exception:  # pragma: no cover
                import traceback
                traceback.print_exc()

    def stop(self, final_flush=True):
        self._stop.set()
        if final_flush:
            for a in self.aggregators:
                a.flush()
