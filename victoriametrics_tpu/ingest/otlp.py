"""OpenTelemetry OTLP/HTTP metrics ingestion (reference lib/protoparser/
opentelemetry, 2626 LoC of easyproto decoding — here via the same protowire
reader used for remote-write).

Wire schema subset (opentelemetry/proto/metrics/v1/metrics.proto):

  ExportMetricsServiceRequest { repeated ResourceMetrics resource_metrics=1 }
  ResourceMetrics { Resource resource=1; repeated ScopeMetrics scope_metrics=2 }
  Resource        { repeated KeyValue attributes=1 }
  ScopeMetrics    { repeated Metric metrics=2 }
  Metric { string name=1; ...; oneof { Gauge gauge=5; Sum sum=7;
           Histogram histogram=9; Summary summary=11 } }
  Gauge/Sum       { repeated NumberDataPoint data_points=1 }
  Histogram       { repeated HistogramDataPoint data_points=1 }
  Summary         { repeated SummaryDataPoint data_points=1 }
  NumberDataPoint { time_unix_nano=3 fixed64; as_double=4; as_int=6 sfixed64;
                    attributes=7 }
  HistogramDataPoint { count=4 fixed64; sum=5 double; bucket_counts=6 packed
                    fixed64; explicit_bounds=7 packed double;
                    time_unix_nano=3; attributes=9 }
  SummaryDataPoint { time_unix_nano=3; count=4; sum=5;
                    quantile_values=6 { quantile=1 double; value=2 double };
                    attributes=7 }
  KeyValue { key=1; AnyValue value=2 { string=1 bool=2 int=3 double=4 } }

Prometheus mapping follows the reference defaults: metric and label names
are stored AS-IS (no dot/dash rewriting — that is the opt-in
usePrometheusNaming mode); histograms expand to `<name>_bucket{le}` +
`<name>_sum` + `<name>_count`, summaries to `<name>{quantile}` + sum/count;
resource attributes become labels. Datapoints flagged NO_RECORDED_VALUE
ingest as staleness markers.
"""

from __future__ import annotations

import struct

from .parsers import Row
from .protowire import as_double, as_signed, iter_fields


def _fmt_num(v: float) -> str:
    """Prometheus-style number formatting for le/quantile labels: 1.0 -> "1"."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _parse_any_value(data: bytes) -> str:
    for f, wt, v in iter_fields(data):
        if f == 1 and wt == 2:
            return v.decode("utf-8", "replace")
        if f == 2 and wt == 0:
            return "true" if v else "false"
        if f == 3 and wt == 0:
            return str(as_signed(v))
        if f == 4 and wt == 1:
            return repr(as_double(v))
    return ""


def _parse_attributes(fields, attr_field: int) -> list:
    out = []
    for f, wt, v in fields:
        if f == attr_field and wt == 2:
            key = val = ""
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 2:
                    key = v2.decode("utf-8", "replace")
                elif f2 == 2 and w2 == 2:
                    val = _parse_any_value(v2)
            if key and val:
                out.append((key, val))
    return out


def _packed_fixed64(data: bytes) -> list[int]:
    return [struct.unpack_from("<Q", data, i)[0]
            for i in range(0, len(data), 8)]


def _packed_double(data: bytes) -> list[float]:
    return [struct.unpack_from("<d", data, i)[0]
            for i in range(0, len(data), 8)]


def parse_otlp(body: bytes):
    """Yields Row objects from an ExportMetricsServiceRequest."""
    for f, wt, rm in iter_fields(body):
        if f != 1 or wt != 2:
            continue
        resource_labels: list = []
        scope_metrics = []
        for f2, w2, v2 in iter_fields(rm):
            if f2 == 1 and w2 == 2:  # Resource
                resource_labels = _parse_attributes(iter_fields(v2), 1)
            elif f2 == 2 and w2 == 2:  # ScopeMetrics
                scope_metrics.append(v2)
        for sm in scope_metrics:
            for f3, w3, metric in iter_fields(sm):
                if f3 == 2 and w3 == 2:
                    yield from _parse_metric(metric, resource_labels)


def _parse_metric(data: bytes, resource_labels: list):
    name = ""
    bodies = []
    for f, wt, v in iter_fields(data):
        if f == 1 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif f in (5, 7, 9, 11) and wt == 2:
            bodies.append((f, v))
    for kind, body in bodies:
        for f, wt, dp in iter_fields(body):
            if f != 1 or wt != 2:
                continue
            if kind in (5, 7):   # Gauge / Sum
                yield from _number_point(name, dp, resource_labels)
            elif kind == 9:      # Histogram
                yield from _histogram_point(name, dp, resource_labels)
            elif kind == 11:     # Summary
                yield from _summary_point(name, dp, resource_labels)


_FLAG_NO_RECORDED_VALUE = 1


def _dp_common(dp: bytes, attr_field: int, flags_field: int = 8):
    ts_ms = 0
    stale = False
    fields = list(iter_fields(dp))
    for f, wt, v in fields:
        if f == 3 and wt == 1:
            ts_ms = v // 1_000_000
        elif f == flags_field and wt == 0 and (v & _FLAG_NO_RECORDED_VALUE):
            stale = True
    attrs = _parse_attributes(fields, attr_field)
    return ts_ms, attrs, fields, stale


def _number_point(name: str, dp: bytes, resource_labels: list):
    ts_ms, attrs, fields, stale = _dp_common(dp, 7)
    value = None
    for f, wt, v in fields:
        if f == 4 and wt == 1:
            value = as_double(v)
        elif f == 6 and wt == 1:
            value = float(struct.unpack("<q", struct.pack("<Q", v))[0])
    if stale:
        from ..ops.decimal import STALE_NAN
        value = STALE_NAN
    if value is None:
        return
    yield Row([("__name__", name)] + resource_labels + attrs, ts_ms, value)


def _histogram_point(name: str, dp: bytes, resource_labels: list):
    ts_ms, attrs, fields, stale = _dp_common(dp, 9, flags_field=10)
    if stale:
        return
    count = 0
    total = None
    bucket_counts: list[int] = []
    bounds: list[float] = []
    for f, wt, v in fields:
        if f == 4 and wt == 1:
            count = v
        elif f == 5 and wt == 1:
            total = as_double(v)
        elif f == 6 and wt == 2:
            bucket_counts = _packed_fixed64(v)
        elif f == 7 and wt == 2:
            bounds = _packed_double(v)
    cum = 0
    for i, bc in enumerate(bucket_counts):
        cum += bc
        le = _fmt_num(bounds[i]) if i < len(bounds) else "+Inf"
        labels = [("__name__", f"{name}_bucket")] + resource_labels + \
            attrs + [("le", le)]
        yield Row(labels, ts_ms, float(cum))
    if total is not None:
        yield Row([("__name__", f"{name}_sum")] + resource_labels + attrs,
                  ts_ms, total)
    yield Row([("__name__", f"{name}_count")] + resource_labels + attrs,
              ts_ms, float(count))


def _summary_point(name: str, dp: bytes, resource_labels: list):
    ts_ms, attrs, fields, stale = _dp_common(dp, 7)
    if stale:
        return
    count = 0
    total = 0.0
    for f, wt, v in fields:
        if f == 4 and wt == 1:
            count = v
        elif f == 5 and wt == 1:
            total = as_double(v)
        elif f == 6 and wt == 2:
            q = val = None
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 1:
                    q = as_double(v2)
                elif f2 == 2 and w2 == 1:
                    val = as_double(v2)
            if q is not None and val is not None:
                yield Row([("__name__", name)] + resource_labels + attrs +
                          [("quantile", _fmt_num(q))], ts_ms, val)
    yield Row([("__name__", f"{name}_sum")] + resource_labels + attrs,
              ts_ms, total)
    yield Row([("__name__", f"{name}_count")] + resource_labels + attrs,
              ts_ms, float(count))
