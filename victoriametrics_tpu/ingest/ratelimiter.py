"""Ingestion rate limiter (reference lib/ratelimiter/ratelimiter.go,
wired at app/vminsert/common/insert_ctx.go:286 Register(len(ctx.mrs))).

Budget-bucket semantics match the reference: the budget grows by
`per_second_limit` once per second-deadline; `register` BLOCKS while the
budget is exhausted (bursts are smoothed to the configured rate), and a
stop event unblocks waiters at shutdown. `register_bounded` additionally
gives HTTP callers a rejection path: it blocks at most `max_wait_s` and
then reports the seconds until the next refill so the handler can return
429 + Retry-After instead of pinning a connection (the reference's
vmagent remote-write client does the equivalent with its own retry
backoff).

Per-tenant limits compose with the global one through TenantRateLimiters
(lib/tenantmetrics-style lazy map)."""

from __future__ import annotations

import math
import threading
import time


class RateLimiter:
    """Limits per-second rate of arbitrary resources (rows)."""

    def __init__(self, per_second_limit: int, stop_event=None,
                 clock=time.monotonic):
        self.per_second_limit = int(per_second_limit)
        self._stop = stop_event if stop_event is not None \
            else threading.Event()
        self._clock = clock
        self._mu = threading.Lock()
        self._budget = 0
        self._deadline = 0.0
        self.limit_reached = 0  # vm_ingestion_rate_limit_reached_total

    def stop(self) -> None:
        """Unblock all current and future register() waiters."""
        self._stop.set()

    def register(self, count: int) -> None:
        """Consume `count` resources, blocking while over the limit."""
        self.register_bounded(count, max_wait_s=None)

    def register_bounded(self, count: int,
                         max_wait_s: float | None = 1.0) -> float:
        """Consume `count` resources. Blocks up to `max_wait_s` seconds
        (None = indefinitely, reference semantics). Returns 0.0 when the
        resources were admitted, else the suggested Retry-After seconds
        (> 0) — the caller must NOT ingest in that case."""
        limit = self.per_second_limit
        if limit <= 0 or count <= 0:
            return 0.0  # empty batches (metadata-only posts) never 429
        waited = 0.0
        with self._mu:
            while self._budget <= 0:
                if self._stop.is_set():
                    return 0.0  # shutdown: let the caller finish fast
                now = self._clock()
                d = self._deadline - now
                if d > 0:
                    self.limit_reached += 1
                    if max_wait_s is not None and waited + d > max_wait_s:
                        # seconds until enough refills cover this burst
                        deficit = -self._budget + count
                        return d + max(
                            math.ceil(deficit / limit) - 1, 0)
                    # drop the lock while sleeping so other callers fail
                    # fast instead of queueing behind the sleeper
                    self._mu.release()
                    try:
                        interrupted = self._stop.wait(d)
                    finally:
                        self._mu.acquire()
                    waited += d
                    if interrupted:
                        return 0.0
                    continue
                self._budget += limit
                self._deadline = now + 1.0
            self._budget -= int(count)
        return 0.0

    def refund(self, count: int) -> None:
        """Return resources debited for a batch that was NOT ingested
        (a later limiter in a chain rejected it) — otherwise rejected
        retries would starve everyone else's budget."""
        if self.per_second_limit <= 0 or count <= 0:
            return
        with self._mu:
            self._budget += int(count)


class RateLimitedError(Exception):
    """Raised by ingest paths when a batch is rejected; the HTTP layer
    converts it to 429 with Retry-After."""

    def __init__(self, retry_after_s: float):
        self.retry_after_s = max(1, math.ceil(retry_after_s))
        super().__init__(
            f"ingestion rate limit exceeded; retry after "
            f"{self.retry_after_s}s (see -maxIngestionRate)")


class TenantRateLimiters:
    """Global + lazily-created per-tenant limiters. `register` applies
    the global limit first (it is the capacity guard), then the tenant's
    own budget."""

    def __init__(self, global_limit: int = 0, per_tenant_limit: int = 0,
                 max_wait_s: float | None = 1.0, clock=time.monotonic):
        self._clock = clock
        self.max_wait_s = max_wait_s
        self.global_rl = (RateLimiter(global_limit, clock=clock)
                          if global_limit > 0 else None)
        self._per_tenant_limit = per_tenant_limit
        self._tenant_rls: dict[tuple, RateLimiter] = {}
        self._mu = threading.Lock()

    def enabled(self) -> bool:
        return self.global_rl is not None or self._per_tenant_limit > 0

    def _tenant_rl(self, tenant) -> RateLimiter | None:
        if self._per_tenant_limit <= 0:
            return None
        # racy-by-design fast path: a stale miss just falls through to
        # the locked setdefault, which both racers resolve to ONE limiter
        rl = self._tenant_rls.get(tenant)  # vmt: disable=VMT015
        if rl is None:
            with self._mu:
                rl = self._tenant_rls.setdefault(
                    tenant,
                    RateLimiter(self._per_tenant_limit, clock=self._clock))
        return rl

    def register(self, count: int, tenant=(0, 0)) -> None:
        """Admit `count` rows or raise RateLimitedError. The tenant's own
        (narrower) budget is checked FIRST and refunded if the global
        limiter then rejects — a saturated tenant's retries must not
        drain the global budget and starve other tenants."""
        tenant_rl = self._tenant_rl(tenant)
        if tenant_rl is not None:
            retry = tenant_rl.register_bounded(count, self.max_wait_s)
            if retry > 0:
                raise RateLimitedError(retry)
        if self.global_rl is not None:
            retry = self.global_rl.register_bounded(count, self.max_wait_s)
            if retry > 0:
                if tenant_rl is not None:
                    tenant_rl.refund(count)
                raise RateLimitedError(retry)

    def stop(self) -> None:
        if self.global_rl is not None:
            self.global_rl.stop()
        for rl in self._tenant_rls.values():
            rl.stop()
