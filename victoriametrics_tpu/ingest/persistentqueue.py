"""Crash-safe FIFO queue: in-RAM fast path spilling to disk chunk files
(reference lib/persistentqueue/{fastqueue,persistentqueue}.go:33-640).

Blocks (byte strings) are appended to chunk files as u32-length-prefixed
records; metainfo.json tracks the reader position. Corrupted trailing
records (crash mid-write) are skipped on open (skipBrokenChunkFile
analog). The in-RAM deque front avoids disk I/O while the consumer keeps
up; memory pressure spills to disk."""

from __future__ import annotations

import collections
import json
import os
import struct
import threading

_U32 = struct.Struct("<I")
CHUNK_MAX_BYTES = 16 << 20


class PersistentQueue:
    def __init__(self, path: str, max_inmemory_blocks: int = 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Condition()
        self._mem: collections.deque[bytes] = collections.deque()
        self._max_mem = max_inmemory_blocks
        self._meta_path = os.path.join(path, "metainfo.json")
        self._read_chunk = 0
        self._read_off = 0
        self._write_chunk = 0
        self._write_f = None
        self._load_meta()
        self._stopped = False

    # -- persistence -----------------------------------------------------

    def _chunk_path(self, idx: int) -> str:
        return os.path.join(self.path, f"chunk_{idx:010d}")

    def _load_meta(self):
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                m = json.load(f)
            self._read_chunk = m.get("read_chunk", 0)
            self._read_off = m.get("read_off", 0)
        chunks = sorted(int(n.split("_")[1]) for n in os.listdir(self.path)
                        if n.startswith("chunk_"))
        self._write_chunk = (chunks[-1] if chunks else self._read_chunk)
        # drop chunks older than the read position (already consumed)
        for c in chunks:
            if c < self._read_chunk:
                os.unlink(self._chunk_path(c))

    def _save_meta(self):
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"read_chunk": self._read_chunk,
                       "read_off": self._read_off}, f)
        os.replace(tmp, self._meta_path)

    def _open_write_chunk_locked(self):
        if self._write_f is None:
            self._write_f = open(self._chunk_path(self._write_chunk), "ab")
        elif self._write_f.tell() >= CHUNK_MAX_BYTES:
            self._write_f.close()
            self._write_chunk += 1
            self._write_f = open(self._chunk_path(self._write_chunk), "ab")

    def _write_block_to_disk(self, block: bytes):
        self._open_write_chunk_locked()
        self._write_f.write(_U32.pack(len(block)) + block)
        self._write_f.flush()

    def _read_block_from_disk(self) -> bytes | None:
        while self._read_chunk <= self._write_chunk:
            p = self._chunk_path(self._read_chunk)
            if not os.path.exists(p):
                self._read_chunk += 1
                self._read_off = 0
                continue
            with open(p, "rb") as f:
                f.seek(self._read_off)
                hdr = f.read(4)
                if len(hdr) < 4:
                    # end of chunk (or truncated crash tail)
                    if self._read_chunk < self._write_chunk:
                        os.unlink(p)
                        self._read_chunk += 1
                        self._read_off = 0
                        continue
                    return None
                n = _U32.unpack(hdr)[0]
                data = f.read(n)
                if len(data) < n:
                    # crash mid-write: skip the broken tail
                    if self._read_chunk < self._write_chunk:
                        os.unlink(p)
                        self._read_chunk += 1
                        self._read_off = 0
                        continue
                    return None
                self._read_off = f.tell()
                self._save_meta()
                return data
        return None

    # -- API ---------------------------------------------------------------

    def put(self, block: bytes) -> None:
        with self._lock:
            if not self._disk_pending() and len(self._mem) < self._max_mem:
                self._mem.append(block)
            else:
                # preserve FIFO: once anything is on disk, everything goes
                # through disk
                while self._mem:
                    self._write_block_to_disk(self._mem.popleft())
                self._write_block_to_disk(block)
            self._lock.notify()

    def _disk_pending(self) -> bool:
        if self._write_f is not None and (
                self._read_chunk < self._write_chunk or
                self._read_off < self._write_f.tell()):
            return True
        return False

    def get(self, timeout: float | None = None) -> bytes | None:
        with self._lock:
            if not self._mem and not self._disk_pending():
                self._lock.wait(timeout)
            if self._mem:
                return self._mem.popleft()
            return self._read_block_from_disk()

    def flush_to_disk(self):
        """Persist the RAM front (shutdown path)."""
        with self._lock:
            while self._mem:
                self._write_block_to_disk(self._mem.popleft())
            if self._write_f:
                self._write_f.flush()
                os.fsync(self._write_f.fileno())
            self._save_meta()

    def close(self):
        self.flush_to_disk()
        with self._lock:
            if self._write_f:
                self._write_f.close()
                self._write_f = None

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._mem) + (1 if self._disk_pending() else 0)
