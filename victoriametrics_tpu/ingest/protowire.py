"""Minimal protobuf wire-format reader/writer (the easyproto analog —
reference vendors VictoriaMetrics/easyproto for alloc-free proto handling;
we hand-roll the same subset: varint, fixed64, length-delimited)."""

from __future__ import annotations

import struct


def read_varint(data: bytes, i: int) -> tuple[int, int]:
    x = 0
    shift = 0
    while True:
        if i >= len(data):
            raise ValueError("proto: truncated varint")
        b = data[i]
        i += 1
        x |= (b & 0x7F) << shift
        if not b & 0x80:
            return x, i
        shift += 7
        if shift > 70:
            raise ValueError("proto: varint too long")


def iter_fields(data: bytes, start: int = 0, end: int | None = None):
    """Yield (field_number, wire_type, value, next_i). value is int for
    varint/fixed, bytes for length-delimited."""
    i = start
    end = len(data) if end is None else end
    while i < end:
        key, i = read_varint(data, i)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, i = read_varint(data, i)
            yield fnum, wt, v
        elif wt == 1:
            if i + 8 > end:
                raise ValueError("proto: truncated fixed64")
            v = struct.unpack_from("<Q", data, i)[0]
            i += 8
            yield fnum, wt, v
        elif wt == 2:
            ln, i = read_varint(data, i)
            if i + ln > end:
                raise ValueError("proto: truncated bytes field")
            yield fnum, wt, data[i:i + ln]
            i += ln
        elif wt == 5:
            if i + 4 > end:
                raise ValueError("proto: truncated fixed32")
            v = struct.unpack_from("<I", data, i)[0]
            i += 4
            yield fnum, wt, v
        else:
            raise ValueError(f"proto: unsupported wire type {wt}")


def zigzag64(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def as_double(v: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", v))[0]


def as_signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


# -- writer ------------------------------------------------------------------

def w_varint(out: bytearray, x: int):
    if x < 0:
        x += 1 << 64
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def w_tag(out: bytearray, fnum: int, wt: int):
    w_varint(out, (fnum << 3) | wt)


def w_bytes(out: bytearray, fnum: int, data: bytes):
    w_tag(out, fnum, 2)
    w_varint(out, len(data))
    out += data


def w_double(out: bytearray, fnum: int, v: float):
    w_tag(out, fnum, 1)
    out += struct.pack("<d", v)


def w_int64(out: bytearray, fnum: int, v: int):
    w_tag(out, fnum, 0)
    w_varint(out, v)
