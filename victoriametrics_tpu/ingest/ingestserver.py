"""TCP/UDP ingestion listeners (reference lib/ingestserver/{graphite,influx,
opentsdb}/server.go): line-protocol servers for Graphite plaintext, Influx
line protocol and OpenTSDB telnet `put`, each accepting both TCP streams and
UDP datagrams."""

from __future__ import annotations

import socket
import socketserver
import threading

from ..utils import logger
from . import parsers

PARSERS = {
    "graphite": parsers.parse_graphite,
    "influx": parsers.parse_influx,
    "opentsdb": parsers.parse_opentsdb_telnet,
}


class IngestServer:
    """One protocol listener on TCP + UDP sharing a port."""

    MAX_LINE = 64 << 10

    def __init__(self, proto: str, addr: str, port: int, ingest_rows_fn):
        """ingest_rows_fn receives an iterator of parsers.Row (so the shared
        ingestion tail applies timestamp defaulting / relabeling)."""
        if proto not in PARSERS:
            raise ValueError(f"unknown ingest protocol {proto!r}")
        parse = PARSERS[proto]
        self.proto = proto
        max_line = self.MAX_LINE

        def ingest_text(text: str):
            ingest_rows_fn(parse(text))

        class TCPHandler(socketserver.StreamRequestHandler):
            def handle(self):
                buf = []
                while True:
                    # bounded reads: a newline-less stream must not buffer
                    # unboundedly in RAM; oversized lines get dropped by the
                    # parser as garbage
                    line = self.rfile.readline(max_line)
                    if not line:
                        break
                    buf.append(line.decode("utf-8", "replace"))
                    if len(buf) >= 500:
                        ingest_text("".join(buf))
                        buf = []
                if buf:
                    ingest_text("".join(buf))

        class UDPHandler(socketserver.BaseRequestHandler):
            def handle(self):
                data = self.request[0]
                ingest_text(data.decode("utf-8", "replace"))

        class TCP(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        class UDP(socketserver.ThreadingUDPServer):
            daemon_threads = True
            allow_reuse_address = True
            max_packet_size = 64 * 1024  # default 8KB truncates batched lines

        self._tcp = TCP((addr, port), TCPHandler)
        self.port = self._tcp.server_address[1]
        self._udp = UDP((addr, self.port), UDPHandler)
        # long-lived TCP/UDP accept loops, one each — not fan-out work
        self._threads = [
            threading.Thread(target=self._tcp.serve_forever,  # vmt: disable=VMT011
                             daemon=True),
            threading.Thread(target=self._udp.serve_forever,  # vmt: disable=VMT011
                             daemon=True),
        ]

    def start(self):
        for t in self._threads:
            t.start()
        logger.infof("%s ingest server listening on tcp+udp :%d",
                     self.proto, self.port)

    def stop(self):
        self._tcp.shutdown()
        self._tcp.server_close()
        self._udp.shutdown()
        self._udp.server_close()
