"""Prometheus relabeling, full superset (reference lib/promrelabel/
relabel.go:20,163-430 — 19 actions incl. the VictoriaMetrics extensions —
plus if_expression.go series-selector guards).

Configs are dicts (parsed from YAML):
  {source_labels: [..], separator: ";", target_label: x, regex: "..",
   modulus: N, replacement: "$1", action: replace, if: '{selector}'}

apply(configs, labels) -> new labels list or None (dropped).
"""

from __future__ import annotations

import re

import xxhash

from ..query.metricsql import parse as mql_parse
from ..query.metricsql.ast import MetricExpr
from ..storage.tag_filters import TagFilter


class RelabelConfig:
    def __init__(self, cfg: dict):
        self.source_labels = [s for s in cfg.get("source_labels", [])]
        self.separator = cfg.get("separator", ";")
        self.target_label = cfg.get("target_label", "")
        regex = cfg.get("regex")
        self.regex_orig = regex
        if regex is None:
            # Prometheus default regex is (.*) — one capture group for $1
            self.regex = re.compile("(?s)(.*)\\Z")
        else:
            self.regex = re.compile("(?:" + str(regex) + ")\\Z")
        self.modulus = int(cfg.get("modulus", 0))
        self.replacement = str(cfg.get("replacement", "$1"))
        self.action = cfg.get("action", "replace")
        self.if_selectors = self._parse_if(cfg.get("if"))
        self.labels_cfg = cfg.get("labels", {})  # for graphite action
        self.match_cfg = cfg.get("match", "")

    @staticmethod
    def _parse_if(expr):
        if not expr:
            return None
        exprs = expr if isinstance(expr, list) else [expr]
        out = []
        for e in exprs:
            ast = mql_parse(str(e))
            if not isinstance(ast, MetricExpr):
                raise ValueError(f"relabel if must be a series selector: {e}")
            # `if` selectors OR across entries already; OR'd filter sets
            # ({a="b" or c="d"}) expand into extra entries; one shared
            # lowering (query/eval) keeps `if` semantics identical to
            # query-side selectors
            from ..query.eval import filter_sets_from_metric_expr
            out.extend(filter_sets_from_metric_expr(ast))
        return out

    def _if_matches(self, labels: dict) -> bool:
        if self.if_selectors is None:
            return True
        for filters in self.if_selectors:
            ok = True
            for tf in filters:
                key = "__name__" if tf.key == b"" else tf.key.decode()
                val = labels.get(key, "").encode()
                if not tf.match_value(val):
                    ok = False
                    break
            if ok:
                return True
        return False

    def _source_value(self, labels: dict) -> str:
        return self.separator.join(labels.get(s, "")
                                   for s in self.source_labels)

    def _expand(self, m: re.Match) -> str:
        # $1 / ${1} / $name expansion
        repl = re.sub(r"\$(\d+)", r"\\\1", self.replacement)
        repl = re.sub(r"\$\{(\w+)\}", r"\\g<\1>", repl)
        try:
            return m.expand(repl)
        except re.error:
            return self.replacement

    def apply(self, labels: dict) -> dict | None:
        """Returns the new labels dict or None if the target is dropped."""
        if not self._if_matches(labels):
            if self.action == "keep" and self.if_selectors is not None \
                    and "regex" not in self.__dict__:
                pass
            # `if` mismatch: keep/keep_metrics DROP when guarded only by if
            if self.action in ("keep", "keep_metrics") and \
                    self.regex_orig is None:
                return None
            return labels
        a = self.action
        if a == "replace":
            src = self._source_value(labels)
            m = self.regex.match(src)
            if m is None:
                return labels
            val = self._expand(m)
            out = dict(labels)
            if val:
                out[self.target_label] = val
            else:
                out.pop(self.target_label, None)
            return out
        if a == "replace_all":
            src = self._source_value(labels)
            rx = re.compile(str(self.regex_orig)) if self.regex_orig else None
            if rx is None:
                return labels
            repl = re.sub(r"\$(\d+)", r"\\\1", self.replacement)
            out = dict(labels)
            out[self.target_label] = rx.sub(repl, src)
            return out
        if a == "keep":
            return labels if self.regex.match(self._source_value(labels)) \
                else None
        if a == "drop":
            return None if self.regex.match(self._source_value(labels)) \
                else labels
        if a == "keep_metrics":
            return labels if self.regex.match(labels.get("__name__", "")) \
                else None
        if a == "drop_metrics":
            return None if self.regex.match(labels.get("__name__", "")) \
                else labels
        if a in ("keep_if_equal", "keepequal"):
            if a == "keepequal":
                ok = labels.get(self.target_label, "") == \
                    self._source_value(labels)
            else:
                vals = {labels.get(s, "") for s in self.source_labels}
                ok = len(vals) == 1
            return labels if ok else None
        if a in ("drop_if_equal", "dropequal"):
            if a == "dropequal":
                eq = labels.get(self.target_label, "") == \
                    self._source_value(labels)
            else:
                vals = {labels.get(s, "") for s in self.source_labels}
                eq = len(vals) == 1
            return None if eq else labels
        if a == "keep_if_contains":
            hay = labels.get(self.target_label, "")
            return labels if all(labels.get(s, "") in hay.split(",")
                                 for s in self.source_labels) else None
        if a == "drop_if_contains":
            hay = labels.get(self.target_label, "")
            return None if all(labels.get(s, "") in hay.split(",")
                               for s in self.source_labels) else labels
        if a == "hashmod":
            src = self._source_value(labels)
            out = dict(labels)
            out[self.target_label] = str(
                xxhash.xxh64_intdigest(src.encode()) % max(self.modulus, 1))
            return out
        if a == "labelmap":
            out = dict(labels)
            for k, v in list(labels.items()):
                m = self.regex.match(k)
                if m:
                    out[self._expand(m)] = v
            return out
        if a == "labelmap_all":
            rx = re.compile(str(self.regex_orig)) if self.regex_orig else None
            out = {}
            repl = re.sub(r"\$(\d+)", r"\\\1", self.replacement)
            for k, v in labels.items():
                out[rx.sub(repl, k) if rx else k] = v
            return out
        if a == "labeldrop":
            return {k: v for k, v in labels.items()
                    if not self.regex.match(k)}
        if a == "labelkeep":
            return {k: v for k, v in labels.items()
                    if k == "__name__" or self.regex.match(k)}
        if a == "lowercase":
            out = dict(labels)
            out[self.target_label] = self._source_value(labels).lower()
            return out
        if a == "uppercase":
            out = dict(labels)
            out[self.target_label] = self._source_value(labels).upper()
            return out
        if a == "graphite":
            return self._apply_graphite(labels)
        raise ValueError(f"unknown relabel action {a!r}")

    def _apply_graphite(self, labels: dict) -> dict:
        """match: "foo.*.bar" with `labels: {job: "$1"}` templates
        (the reference's graphite action)."""
        name = labels.get("__name__", "")
        pattern = self.match_cfg
        rx = re.compile("(?:" + re.escape(pattern).replace("\\*", "([^.]*)")
                        + ")\\Z")
        m = rx.match(name)
        if not m:
            return labels
        out = dict(labels)
        for k, tmpl in self.labels_cfg.items():
            val = re.sub(r"\$(\d+)", lambda mm: m.group(int(mm.group(1))),
                         str(tmpl))
            out[k] = val
        return out


class ParsedConfigs:
    def __init__(self, configs: list[dict]):
        self.configs = [RelabelConfig(c) for c in configs]

    def apply(self, labels: dict) -> dict | None:
        out = dict(labels)
        for rc in self.configs:
            out = rc.apply(out)
            if out is None:
                return None
        return {k: v for k, v in out.items() if v != ""}


def parse_relabel_configs(yaml_text_or_list) -> ParsedConfigs:
    if isinstance(yaml_text_or_list, str):
        import yaml
        yaml_text_or_list = yaml.safe_load(yaml_text_or_list) or []
    return ParsedConfigs(yaml_text_or_list)
