"""Prometheus remote-write protocol (reference lib/protoparser/
promremotewrite + lib/prompb/prompb.go): snappy- or zstd-compressed
protobuf WriteRequest.

prompb schema subset:
  WriteRequest { repeated TimeSeries timeseries = 1;
                 repeated MetricMetadata metadata = 3; }
  TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
  Label        { string name = 1; string value = 2; }
  Sample       { double value = 1; int64 timestamp = 2; }
"""

from __future__ import annotations

from ..ops import compress as zstd
from . import snappy
from .protowire import (as_double, as_signed, iter_fields, w_bytes, w_double,
                        w_int64)


def parse_write_request(body: bytes, encoding: str = "snappy"):
    """Yields (labels: list[(str, str)], samples: list[(ts_ms, value)])."""
    if encoding == "snappy":
        data = snappy.decompress(body)
    elif encoding == "zstd":
        data = zstd.decompress(body)
    elif encoding in ("", "none", "identity"):
        data = body
    else:
        raise ValueError(f"unsupported remote-write encoding {encoding!r}")
    for fnum, wt, v in iter_fields(data):
        if fnum == 1 and wt == 2:
            yield _parse_timeseries(v)


def _parse_timeseries(data: bytes):
    labels = []
    samples = []
    for fnum, wt, v in iter_fields(data):
        if fnum == 1 and wt == 2:
            name = value = ""
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 2:
                    value = v2.decode("utf-8", "replace")
            labels.append((name, value))
        elif fnum == 2 and wt == 2:
            val = 0.0
            ts = 0
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 1:
                    val = as_double(v2)
                elif f2 == 2 and w2 == 0:
                    ts = as_signed(v2)
            samples.append((ts, val))
    return labels, samples


def build_write_request(series, compress: str = "snappy") -> bytes:
    """series: iterable of (labels list[(str,str)], samples list[(ts, val)]).
    Used by the remote-write client (vmagent) and tests."""
    out = bytearray()
    for labels, samples in series:
        ts_buf = bytearray()
        for name, value in labels:
            lbuf = bytearray()
            w_bytes(lbuf, 1, name.encode())
            w_bytes(lbuf, 2, value.encode())
            w_bytes(ts_buf, 1, bytes(lbuf))
        for ts, val in samples:
            sbuf = bytearray()
            w_double(sbuf, 1, float(val))
            w_int64(sbuf, 2, int(ts))
            w_bytes(ts_buf, 2, bytes(sbuf))
        w_bytes(out, 1, bytes(ts_buf))
    raw = bytes(out)
    if compress == "snappy":
        return snappy.compress(raw)
    if compress == "zstd":
        return zstd.compress(raw)
    return raw


# -- Prometheus remote_read (prompb ReadRequest/ReadResponse) ----------------

_MATCH_OPS = {"=": 0, "!=": 1, "=~": 2, "!~": 3}


def build_read_request(start_ms: int, end_ms: int,
                       matchers: list[tuple[str, str, str]]) -> bytes:
    """ReadRequest proto, snappy-compressed. matchers: [(op, name, value)]
    with op in =, !=, =~, !~."""
    q = bytearray()
    w_int64(q, 1, start_ms)
    w_int64(q, 2, end_ms)
    for op, name, value in matchers:
        m = bytearray()
        t = _MATCH_OPS[op]
        if t:
            w_int64(m, 1, t)
        w_bytes(m, 2, name.encode())
        w_bytes(m, 3, value.encode())
        w_bytes(q, 3, bytes(m))
    req = bytearray()
    w_bytes(req, 1, bytes(q))
    return snappy.compress(bytes(req))


def parse_read_response(body: bytes):
    """Yields (labels, [(ts_ms, value)]) per series from a
    snappy-compressed ReadResponse."""
    data = snappy.decompress(body)
    for fnum, wt, val in iter_fields(data):
        if fnum != 1 or wt != 2:        # QueryResult
            continue
        for f2, w2, ts_data in iter_fields(val):
            if f2 != 1 or w2 != 2:      # TimeSeries
                continue
            yield _parse_timeseries(ts_data)


def parse_read_request(body: bytes, encoding: str = "snappy"):
    """Yields (start_ms, end_ms, [(op, name, value)]) per Query from a
    ReadRequest (the server side of remote_read)."""
    ops = {v: k for k, v in _MATCH_OPS.items()}
    data = snappy.decompress(body) if encoding == "snappy" else body
    for fnum, wt, q in iter_fields(data):
        if fnum != 1 or wt != 2:
            continue
        start = end = 0
        matchers = []
        for f2, w2, v in iter_fields(q):
            if f2 == 1 and w2 == 0:
                start = as_signed(v)
            elif f2 == 2 and w2 == 0:
                end = as_signed(v)
            elif f2 == 3 and w2 == 2:
                t = 0
                name = value = ""
                for f3, w3, v3 in iter_fields(v):
                    if f3 == 1 and w3 == 0:
                        t = v3
                    elif f3 == 2:
                        name = v3.decode("utf-8", "replace")
                    elif f3 == 3:
                        value = v3.decode("utf-8", "replace")
                matchers.append((ops.get(t, "="), name, value))
        yield start, end, matchers


def build_read_response(results: list) -> bytes:
    """results: [[(labels_dict, ts_array, vals_array), ...]] one inner list
    per query. Returns snappy(ReadResponse)."""
    out = bytearray()
    for series_list in results:
        qr = bytearray()
        for labels, ts, vals in series_list:
            tsb = bytearray()
            for k, v in sorted(labels.items()):
                lb = bytearray()
                w_bytes(lb, 1, k.encode())
                w_bytes(lb, 2, v.encode())
                w_bytes(tsb, 1, bytes(lb))
            for t, v in zip(ts, vals):
                sb = bytearray()
                w_double(sb, 1, float(v))
                w_int64(sb, 2, int(t))
                w_bytes(tsb, 2, bytes(sb))
            w_bytes(qr, 1, bytes(tsb))
        w_bytes(out, 1, bytes(qr))
    return snappy.compress(bytes(out))
