"""Prometheus remote-write protocol (reference lib/protoparser/
promremotewrite + lib/prompb/prompb.go): snappy- or zstd-compressed
protobuf WriteRequest.

prompb schema subset:
  WriteRequest { repeated TimeSeries timeseries = 1;
                 repeated MetricMetadata metadata = 3; }
  TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
  Label        { string name = 1; string value = 2; }
  Sample       { double value = 1; int64 timestamp = 2; }
"""

from __future__ import annotations

from ..ops import compress as zstd
from . import snappy
from .protowire import (as_double, as_signed, iter_fields, w_bytes, w_double,
                        w_int64)


def parse_write_request(body: bytes, encoding: str = "snappy"):
    """Yields (labels: list[(str, str)], samples: list[(ts_ms, value)])."""
    if encoding == "snappy":
        data = snappy.decompress(body)
    elif encoding == "zstd":
        data = zstd.decompress(body)
    elif encoding in ("", "none", "identity"):
        data = body
    else:
        raise ValueError(f"unsupported remote-write encoding {encoding!r}")
    for fnum, wt, v in iter_fields(data):
        if fnum == 1 and wt == 2:
            yield _parse_timeseries(v)


def _parse_timeseries(data: bytes):
    labels = []
    samples = []
    for fnum, wt, v in iter_fields(data):
        if fnum == 1 and wt == 2:
            name = value = ""
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 2:
                    value = v2.decode("utf-8", "replace")
            labels.append((name, value))
        elif fnum == 2 and wt == 2:
            val = 0.0
            ts = 0
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 1:
                    val = as_double(v2)
                elif f2 == 2 and w2 == 0:
                    ts = as_signed(v2)
            samples.append((ts, val))
    return labels, samples


def build_write_request(series, compress: str = "snappy") -> bytes:
    """series: iterable of (labels list[(str,str)], samples list[(ts, val)]).
    Used by the remote-write client (vmagent) and tests."""
    out = bytearray()
    for labels, samples in series:
        ts_buf = bytearray()
        for name, value in labels:
            lbuf = bytearray()
            w_bytes(lbuf, 1, name.encode())
            w_bytes(lbuf, 2, value.encode())
            w_bytes(ts_buf, 1, bytes(lbuf))
        for ts, val in samples:
            sbuf = bytearray()
            w_double(sbuf, 1, float(val))
            w_int64(sbuf, 2, int(ts))
            w_bytes(ts_buf, 2, bytes(sbuf))
        w_bytes(out, 1, bytes(ts_buf))
    raw = bytes(out)
    if compress == "snappy":
        return snappy.compress(raw)
    if compress == "zstd":
        return zstd.compress(raw)
    return raw
