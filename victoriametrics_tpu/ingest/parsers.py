"""Text-based ingestion parsers (reference lib/protoparser/*):

- Prometheus text exposition (lib/protoparser/prometheus)
- InfluxDB line protocol (lib/protoparser/influx)
- VM JSON-lines import/export format (lib/protoparser/vmimport)
- CSV with format spec (lib/protoparser/csvimport)
- Graphite plaintext (lib/protoparser/graphite)
- OpenTSDB telnet put + HTTP JSON (lib/protoparser/opentsdb{,http})
- DataDog v1/v2 JSON (lib/protoparser/datadog{v1,v2})
- NewRelic infra JSON (lib/protoparser/newrelic)

Every parser yields Row(labels, timestamp_ms, value); labels is a list of
(name, value) str pairs including __name__.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time


@dataclasses.dataclass
class Row:
    labels: list          # [(name, value)]
    timestamp: int        # unix ms; 0 = "now"
    value: float

    def with_default_ts(self, now_ms: int) -> "Row":
        if self.timestamp == 0:
            self.timestamp = now_ms
        return self


def _now_ms() -> int:
    from ..utils import fasttime
    return fasttime.unix_ms()


# -- Prometheus text exposition ----------------------------------------------

def parse_prometheus(text: str, default_ts: int = 0):
    """`metric{a="b"} value [timestamp_ms]` lines; # comments skipped."""
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        row = _parse_prom_line(line)
        if row is not None:
            yield row.with_default_ts(default_ts or _now_ms())


def _find_closing_brace(s: str, start: int) -> int:
    """Quote-aware scan for the '}' ending a label set ('}' may appear
    inside quoted label values). Returns -1 when unterminated."""
    in_q = False
    i = start
    n = len(s)
    while i < n:
        c = s[i]
        if in_q:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_q = False
        elif c == '"':
            in_q = True
        elif c == "}":
            return i
        i += 1
    return -1


def _parse_prom_line(line: str) -> Row | None:
    labels = []
    brace = line.find("{")
    sp = line.find(" ")
    if brace >= 0 and (sp < 0 or brace < sp):
        name = line[:brace]
        close = _find_closing_brace(line, brace + 1)
        if close < 0:
            return None
        lab_str = line[brace + 1:close]
        rest = line[close + 1:]
        labels.append(("__name__", name.strip()))
        labels += _parse_prom_labels(lab_str)
    else:
        parts = line.split(None, 1)
        if len(parts) < 2:
            return None
        name, rest = parts
        labels.append(("__name__", name))
    fields = rest.split()
    if not fields:
        return None
    try:
        value = _parse_float(fields[0])
    except ValueError:
        return None
    ts = 0
    if len(fields) > 1:
        try:
            ts = int(float(fields[1]))
        except ValueError:
            ts = 0
    return Row(labels, ts, value)


def _parse_prom_labels(s: str) -> list:
    out = []
    i = 0
    n = len(s)
    while i < n:
        while i < n and s[i] in ", \t":
            i += 1
        if i >= n:
            break
        j = s.index("=", i)
        name = s[i:j].strip()
        i = j + 1
        if i < n and s[i] == '"':
            i += 1
            buf = []
            while i < n and s[i] != '"':
                if s[i] == "\\" and i + 1 < n:
                    c = s[i + 1]
                    buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(c, "\\" + c))
                    i += 2
                else:
                    buf.append(s[i])
                    i += 1
            i += 1
            out.append((name, "".join(buf)))
        else:
            j = i
            while j < n and s[j] not in ",":
                j += 1
            out.append((name, s[i:j].strip()))
            i = j
    return [(k, v) for k, v in out if v]


def _parse_float(s: str) -> float:
    sl = s.lower()
    if sl in ("nan",):
        return math.nan
    if sl in ("+inf", "inf"):
        return math.inf
    if sl == "-inf":
        return -math.inf
    return float(s)


# -- InfluxDB line protocol ---------------------------------------------------

def parse_influx(text: str, default_ts: int = 0, db: str = ""):
    """measurement[,tag=v...] field=value[,field2=v2...] [timestamp_ns]

    Each field becomes a metric named {measurement}_{field} (the reference's
    default influx mapping with -influxMeasurementFieldSeparator="_")."""
    now = default_ts or _now_ms()
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        yield from _parse_influx_line(line, now, db)


def _split_unescaped(s: str, sep: str, escapable=",= ", keep=False):
    """Split on unescaped `sep`. With keep=True the escape sequences are
    preserved in the pieces (so nested splits still see them); unescape
    with _influx_unescape after the LAST split."""
    out = []
    cur = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s) and s[i + 1] in escapable + "\\":
            if keep:
                cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _influx_unescape(s: str, escapable=",= "):
    if "\\" not in s:
        return s
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s) and s[i + 1] in escapable + "\\":
            out.append(s[i + 1])
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_influx_line(line: str, now: int, db: str):
    if "\\" not in line and '"' not in line:
        # fast path: no escapes / quoted strings — plain splits (the
        # overwhelmingly common shape from telegraf and tsbs load)
        sections = line.split(" ", 2)
        if len(sections) < 2:
            return
        ts = now
        if len(sections) > 2 and sections[2].strip():
            ts = int(sections[2].strip()) // 1_000_000  # ns -> ms
        parts = sections[0].split(",")
        measurement = parts[0]
        tags = [("db", db)] if db else []
        for t in parts[1:]:
            k, sep, v = t.partition("=")
            if sep and v:
                tags.append((k, v))
        for f in sections[1].split(","):
            fname, sep, fval = f.partition("=")
            if not sep:
                continue
            v = _influx_field_value(fval)
            if v is None:
                continue
            name = f"{measurement}_{fname}" if fname != "value" else measurement
            yield Row([("__name__", name)] + tags, ts, v)
        return
    # slow path: split into up to 3 space-separated sections honoring
    # escapes/quotes
    sections = []
    cur = []
    in_quotes = False
    i = 0
    while i < len(line):
        c = line[i]
        if c == '"':
            in_quotes = not in_quotes
            cur.append(c)
        elif c == "\\" and i + 1 < len(line):
            cur.append(c)
            cur.append(line[i + 1])
            i += 1
        elif c == " " and not in_quotes and len(sections) < 2:
            sections.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    sections.append("".join(cur))
    if len(sections) < 2:
        return
    key = sections[0]
    fields_str = sections[1]
    ts = now
    if len(sections) > 2 and sections[2].strip():
        ts = int(sections[2].strip()) // 1_000_000  # ns -> ms
    parts = _split_unescaped(key, ",", keep=True)
    measurement = _influx_unescape(parts[0])
    tags = []
    if db:
        tags.append(("db", db))
    for t in parts[1:]:
        # split on the FIRST unescaped '=' (matches the fast path's
        # partition(): later '=' belong to the value)
        kv = _split_unescaped(t, "=", keep=True)
        if len(kv) >= 2 and kv[1]:
            tags.append((_influx_unescape(kv[0]),
                         _influx_unescape("=".join(kv[1:]))))
    for f in _split_unescaped(fields_str, ",", keep=True):
        kv = _split_unescaped(f, "=", keep=True)
        if len(kv) < 2:
            continue
        fname, fval = _influx_unescape(kv[0]), "=".join(kv[1:])
        v = _influx_field_value(fval)
        if v is None:
            continue
        name = f"{measurement}_{fname}" if fname != "value" else measurement
        yield Row([("__name__", name)] + tags, ts, v)


def _influx_field_value(s: str):
    if not s:
        return None
    if s[0] == '"':
        return None  # string field: not a sample
    if s in ("t", "T", "true", "True", "TRUE"):
        return 1.0
    if s in ("f", "F", "false", "False", "FALSE"):
        return 0.0
    if s.endswith(("i", "u")):
        s = s[:-1]
    try:
        return float(s)
    except ValueError:
        return None


# -- VM JSON lines (import/export) -------------------------------------------

def parse_jsonl(text: str):
    """{"metric":{"__name__":"m","l":"v"},"values":[..],"timestamps":[..]}"""
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        obj = json.loads(line)
        labels = list(obj["metric"].items())
        vals = obj.get("values", [])
        tss = obj.get("timestamps", [])
        for ts, v in zip(tss, vals):
            yield Row(labels, int(ts),
                      math.nan if v is None else float(v))


def series_to_jsonl(metric: dict, timestamps, values) -> str:
    vals = [None if (isinstance(v, float) and math.isnan(v)) else v
            for v in values]
    return json.dumps({"metric": metric, "values": vals,
                       "timestamps": [int(t) for t in timestamps]},
                      separators=(",", ":"))


# -- CSV with format spec ------------------------------------------------------

def parse_csv(text: str, fmt: str, default_ts: int = 0):
    """fmt: comma-separated column rules like
    "2:metric:temperature,1:label:city,3:time:unix_ms"
    (reference lib/protoparser/csvimport/column_descriptor.go)."""
    import csv as _csv
    import io
    rules = []
    for item in fmt.split(","):
        pos, kind, arg = (item.split(":", 2) + [""])[:3]
        rules.append((int(pos) - 1, kind, arg))
    now = default_ts or _now_ms()
    for rec in _csv.reader(io.StringIO(text)):
        if not rec:
            continue
        labels = []
        ts = now
        metrics = []
        try:
            for pos, kind, arg in rules:
                cell = rec[pos]
                if kind == "label":
                    if cell:
                        labels.append((arg, cell))
                elif kind == "metric":
                    metrics.append((arg, _parse_float(cell)))
                elif kind == "time":
                    if arg == "unix_s":
                        ts = int(float(cell) * 1000)
                    elif arg == "unix_ms":
                        ts = int(float(cell))
                    elif arg == "unix_ns":
                        ts = int(float(cell)) // 1_000_000
                    elif arg.startswith("rfc3339"):
                        import datetime
                        ts = int(datetime.datetime.fromisoformat(
                            cell.replace("Z", "+00:00")).timestamp() * 1000)
        except (IndexError, ValueError):
            continue
        for name, val in metrics:
            yield Row([("__name__", name)] + labels, ts, val)


# -- Graphite plaintext --------------------------------------------------------

def parse_graphite(text: str, default_ts: int = 0):
    """`metric.path[;tag=value...] value [timestamp_s]`"""
    now = default_ts or _now_ms()
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) < 2:
            continue
        name_part = parts[0]
        tags = []
        if ";" in name_part:
            segs = name_part.split(";")
            name_part = segs[0]
            for t in segs[1:]:
                if "=" in t:
                    k, v = t.split("=", 1)
                    if v:
                        tags.append((k, v))
        try:
            value = _parse_float(parts[1])
        except ValueError:
            continue
        ts = now
        if len(parts) > 2:
            try:
                t = float(parts[2])
                ts = int(t * 1000) if t > 0 else now
            except ValueError:
                pass
        yield Row([("__name__", name_part)] + tags, ts, value)


# -- OpenTSDB ------------------------------------------------------------------

def parse_opentsdb_telnet(text: str):
    """`put metric ts value tag=v ...` (seconds or ms timestamps)."""
    for line in text.splitlines():
        parts = line.split()
        if len(parts) < 4 or parts[0] != "put":
            continue
        try:
            ts = int(float(parts[2]))
            value = _parse_float(parts[3])
        except ValueError:
            continue
        if ts < 1e12:
            ts *= 1000
        tags = []
        for t in parts[4:]:
            if "=" in t:
                k, v = t.split("=", 1)
                if v:
                    tags.append((k, v))
        yield Row([("__name__", parts[1])] + tags, int(ts), value)


def parse_opentsdb_http(body: bytes):
    """JSON: single object or array of {metric, timestamp, value, tags}."""
    obj = json.loads(body)
    items = obj if isinstance(obj, list) else [obj]
    for it in items:
        ts = int(it.get("timestamp", 0))
        if ts and ts < 1e12:
            ts *= 1000
        tags = [(k, str(v)) for k, v in it.get("tags", {}).items() if v]
        yield Row([("__name__", str(it["metric"]))] + tags,
                  ts or _now_ms(), float(it["value"]))


# -- DataDog -------------------------------------------------------------------

def parse_datadog_v1(body: bytes):
    """POST /api/v1/series: {"series":[{"metric","points":[[ts_s, v]],
    "tags":["k:v"], "host"}]}"""
    obj = json.loads(body)
    for s in obj.get("series", []):
        labels = [("__name__", _dd_name(s["metric"]))]
        if s.get("host"):
            labels.append(("host", s["host"]))
        if s.get("device"):
            labels.append(("device", s["device"]))
        for tag in s.get("tags") or []:
            if ":" in tag:
                k, v = tag.split(":", 1)
                if v:
                    labels.append((k.replace("-", "_").replace(".", "_"), v))
        for point in s.get("points", []):
            ts, v = point[0], point[1]
            yield Row(list(labels), int(float(ts) * 1000), float(v))


def parse_datadog_v2(body: bytes):
    """POST /api/v2/series: points have {"timestamp": s, "value": v}."""
    obj = json.loads(body)
    for s in obj.get("series", []):
        labels = [("__name__", _dd_name(s["metric"]))]
        for r in s.get("resources") or []:
            if r.get("type") and r.get("name"):
                labels.append((r["type"], r["name"]))
        for tag in s.get("tags") or []:
            if ":" in tag:
                k, v = tag.split(":", 1)
                if v:
                    labels.append((k.replace("-", "_").replace(".", "_"), v))
        for p in s.get("points", []):
            yield Row(list(labels), int(p["timestamp"]) * 1000,
                      float(p["value"]))


def _dd_name(name: str) -> str:
    return name.replace("-", "_").replace(".", "_").replace(" ", "_")


# -- NewRelic ------------------------------------------------------------------

def parse_newrelic(body: bytes):
    """Infra agent events JSON -> samples (numeric event fields)."""
    obj = json.loads(body)
    for ev_list in obj if isinstance(obj, list) else [obj]:
        events = ev_list.get("Events", [])
        for ev in events:
            etype = _snake(str(ev.get("eventType", "newrelic")))
            ts = int(ev.get("timestamp", 0))
            if ts and ts < 1e12:
                ts *= 1000
            labels = []
            samples = []
            for k, v in ev.items():
                if k in ("eventType", "timestamp"):
                    continue
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    samples.append((k, float(v)))
                elif isinstance(v, str) and v:
                    labels.append((_snake(k), v))
            for k, v in samples:
                yield Row([("__name__", f"{etype}_{_snake(k)}")] + labels,
                          ts or _now_ms(), v)


def _snake(s: str) -> str:
    out = []
    for i, c in enumerate(s):
        if c.isupper() and i and (not s[i - 1].isupper()):
            out.append("_")
        out.append(c.lower())
    return "".join(out).replace(".", "_").replace("-", "_")


# -- Zabbix Connector (lib/protoparser/zabbixconnector/parser.go) -------------

def parse_zabbixconnector(text: str):
    """JSON lines from Zabbix real-time export (item values):
    {"host":{"host":"h","name":"visible"},"name":"item","value":1.5,
     "clock":..., "ns":..., "item_tags":[{"tag":"t","value":"v"},...]}
    Labels: __name__=name, host, hostname, tag_<k>=<v>."""
    import json as _json
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            o = _json.loads(line)
        except ValueError:
            continue
        host = o.get("host") or {}
        name = o.get("name")
        if not host.get("host") or not host.get("name") or not name:
            continue
        if "value" not in o or "clock" not in o:
            continue
        try:
            value = float(o["value"])
            ts = int(o["clock"]) * 1000 + int(o.get("ns", 0)) // 1_000_000
        except (TypeError, ValueError):
            continue
        labels = [("__name__", str(name)), ("host", str(host["host"])),
                  ("hostname", str(host["name"]))]
        for t in o.get("item_tags") or []:
            k = t.get("tag")
            v = t.get("value", "")
            if k and v:
                labels.append((f"tag_{k}", str(v)))
        yield Row(labels, ts, value)


def parse_prometheus_metadata(text: str) -> dict:
    """# HELP / # TYPE comments -> {metric: {"type": t, "help": h}}
    (lib/storage/metricsmetadata source data)."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("#"):
            continue
        parts = line.split(None, 3)
        # strictly "# TYPE <name> <type>" / "# HELP <name> <text>" — any
        # other comment is ignored
        if len(parts) < 4 or parts[0] != "#" or \
                parts[1] not in ("HELP", "TYPE"):
            continue
        kind, name, rest = parts[1], parts[2], parts[3]
        e = out.setdefault(name, {"type": "", "help": ""})
        if kind == "TYPE":
            e["type"] = rest.strip()
        else:
            e["help"] = rest
    return out


def labels_from_series_key(key: bytes) -> list:
    """Decompose a raw `name{labels}` series key (as produced by the native
    parser, native/parse.cpp) into [(name, value), ...] — the slow path
    taken only on TSID-cache misses. Duplicate label names collapse
    last-wins, matching the dict(labels) Python ingest path. Raises
    ValueError on malformed keys (callers skip the row)."""
    text = key.decode("utf-8", "replace")
    try:
        row = _parse_prom_line(text + " 0")
    except ValueError as e:
        raise ValueError(f"invalid series key {text!r}: {e}") from None
    if row is None:
        raise ValueError(f"invalid series key {text!r}")
    return list(dict(row.labels).items())


def series_key_from_labels(labels) -> bytes:
    """Inverse of labels_from_series_key: build the canonical raw
    `name{k="v",...}` text key from [(name, value)] pairs (labels sorted,
    values escaped). Used by cluster vminsert to ship RELABELED series
    keys columnar — the storage node must see the post-transform key."""
    name = ""
    rest = []
    for k, v in labels:
        ks = k.decode() if isinstance(k, bytes) else k
        vs = v.decode() if isinstance(v, bytes) else v
        if ks == "__name__":
            name = vs
        else:
            rest.append((ks, vs))
    rest.sort()
    if not rest:
        return name.encode()
    parts = []
    for ks, vs in rest:
        vs = vs.replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")
        parts.append(f'{ks}="{vs}"')
    return f"{name}{{{','.join(parts)}}}".encode()


def parse_prometheus_fast(data: bytes, default_ts: int = 0):
    """Native-accelerated prometheus parse returning raw-key rows
    [(series_key_bytes, ts_ms, value)] suitable for Storage.add_rows.
    Falls back to the Python parser (materialized labels) when the native
    library is unavailable."""
    from .. import native
    rows = native.parse_prom_raw(data, default_ts or _now_ms())
    if rows is not None:
        return rows
    out = []
    for row in parse_prometheus(data.decode("utf-8", "replace"), default_ts):
        out.append((row.labels, row.timestamp, row.value))
    return out
