"""Service discovery providers (reference lib/promscrape/discovery/):
kubernetes (pod/node/service/endpoints roles), consul, ec2, plus the
static/file providers handled inline by vmagent.

Each provider resolves a scrape config section to [(address, labels)]
where labels carry the provider's __meta_* set (the subset most relabel
configs use; reference emits a wider set). Providers are plain HTTP
clients so tests can point them at fake API servers (the reference tests
do the same via custom endpoints).
"""

from __future__ import annotations

import json
import urllib.request

from ..utils import logger


class DiscoveryError(RuntimeError):
    """Provider API failure — callers keep their last-known-good targets
    instead of treating this as an empty target list."""


def _get_json(url: str, headers: dict | None = None, timeout: float = 10.0):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


# -- kubernetes (discovery/kubernetes/) --------------------------------------

def kubernetes_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Supported roles: pod, node, service, endpoints."""
    api = cfg.get("api_server", "http://127.0.0.1:8001").rstrip("/")
    role = cfg.get("role", "pod")
    headers = {}
    token = cfg.get("bearer_token", "")
    token_file = cfg.get("bearer_token_file", "")
    if token_file:
        try:
            token = open(token_file).read().strip()
        except OSError as e:
            logger.errorf("kubernetes_sd: cannot read token: %s", e)
    if token:
        headers["Authorization"] = f"Bearer {token}"
    ns = cfg.get("namespaces", {}).get("names", [])
    out: list[tuple[str, dict]] = []

    def paths(kind):
        if ns:
            return [f"{api}/api/v1/namespaces/{n}/{kind}" for n in ns]
        return [f"{api}/api/v1/{kind}"]

    try:
        if role == "pod":
            for url in paths("pods"):
                for item in _get_json(url, headers).get("items", []):
                    meta = item.get("metadata", {})
                    status = item.get("status", {})
                    ip = status.get("podIP")
                    if not ip:
                        continue
                    base = {
                        "__meta_kubernetes_namespace":
                            meta.get("namespace", ""),
                        "__meta_kubernetes_pod_name": meta.get("name", ""),
                        "__meta_kubernetes_pod_ip": ip,
                        "__meta_kubernetes_pod_node_name":
                            item.get("spec", {}).get("nodeName", ""),
                        "__meta_kubernetes_pod_phase":
                            status.get("phase", ""),
                    }
                    for k, v in (meta.get("labels") or {}).items():
                        base["__meta_kubernetes_pod_label_" +
                             _sanitize(k)] = v
                    ports = [p for c in item.get("spec", {}).get(
                        "containers", []) for p in c.get("ports", [])]
                    if not ports:
                        out.append((ip, dict(base)))
                    for p in ports:
                        labels = dict(base)
                        labels["__meta_kubernetes_pod_container_port_number"] \
                            = str(p.get("containerPort", ""))
                        if p.get("name"):
                            labels["__meta_kubernetes_pod_container_port_name"] \
                                = p["name"]
                        out.append((f"{ip}:{p.get('containerPort')}", labels))
        elif role == "node":
            for item in _get_json(f"{api}/api/v1/nodes",
                                  headers).get("items", []):
                meta = item.get("metadata", {})
                addrs = {a.get("type"): a.get("address") for a in
                         item.get("status", {}).get("addresses", [])}
                ip = addrs.get("InternalIP") or addrs.get("Hostname")
                if not ip:
                    continue
                labels = {"__meta_kubernetes_node_name":
                          meta.get("name", "")}
                for k, v in (meta.get("labels") or {}).items():
                    labels["__meta_kubernetes_node_label_" +
                           _sanitize(k)] = v
                out.append((f"{ip}:10250", labels))
        elif role in ("service", "endpoints"):
            kind = "services" if role == "service" else "endpoints"
            for url in paths(kind):
                for item in _get_json(url, headers).get("items", []):
                    meta = item.get("metadata", {})
                    base = {
                        "__meta_kubernetes_namespace":
                            meta.get("namespace", ""),
                        f"__meta_kubernetes_{role}_name":
                            meta.get("name", ""),
                    }
                    if role == "service":
                        ip = item.get("spec", {}).get("clusterIP")
                        if not ip or ip == "None":  # headless services
                            continue
                        for p in item.get("spec", {}).get("ports", []):
                            labels = dict(base)
                            labels["__meta_kubernetes_service_port_number"] \
                                = str(p.get("port", ""))
                            out.append((f"{ip}:{p.get('port')}", labels))
                    else:
                        for ss in item.get("subsets", []):
                            for a in ss.get("addresses", []):
                                for p in ss.get("ports", []):
                                    out.append((
                                        f"{a.get('ip')}:{p.get('port')}",
                                        dict(base)))
        else:
            logger.errorf("kubernetes_sd: unsupported role %r", role)
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"kubernetes_sd {api} role={role}: {e}") from e
    return out


def _sanitize(k: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in k)


# -- consul (discovery/consul/) ----------------------------------------------

def consul_sd(cfg: dict) -> list[tuple[str, dict]]:
    server = cfg.get("server", "127.0.0.1:8500")
    scheme = cfg.get("scheme", "http")
    base = f"{scheme}://{server}/v1"
    headers = {}
    if cfg.get("token"):
        headers["X-Consul-Token"] = cfg["token"]
    out: list[tuple[str, dict]] = []
    try:
        services = cfg.get("services") or list(
            _get_json(f"{base}/catalog/services", headers))
        for svc in services:
            for e in _get_json(f"{base}/health/service/{svc}", headers):
                node = e.get("Node", {})
                s = e.get("Service", {})
                addr = s.get("Address") or node.get("Address", "")
                port = s.get("Port", 0)
                labels = {
                    "__meta_consul_service": s.get("Service", svc),
                    "__meta_consul_node": node.get("Node", ""),
                    "__meta_consul_address": node.get("Address", ""),
                    "__meta_consul_service_address": addr,
                    "__meta_consul_service_port": str(port),
                    "__meta_consul_tags":
                        "," + ",".join(s.get("Tags") or []) + ",",
                    "__meta_consul_dc": node.get("Datacenter", ""),
                }
                out.append((f"{addr}:{port}", labels))
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"consul_sd {server}: {e}") from e
    return out


# -- ec2 (discovery/ec2/) -----------------------------------------------------

def ec2_sd(cfg: dict) -> list[tuple[str, dict]]:
    """DescribeInstances with SigV4 signing; `endpoint` override makes it
    testable against a fake server (the reference supports the same)."""
    region = cfg.get("region", "us-east-1")
    endpoint = cfg.get("endpoint",
                       f"https://ec2.{region}.amazonaws.com")
    port = int(cfg.get("port", 80))
    access_key = cfg.get("access_key", "")
    secret_key = cfg.get("secret_key", "")
    body = "Action=DescribeInstances&Version=2013-10-15"
    headers = {"Content-Type":
               "application/x-www-form-urlencoded; charset=utf-8"}
    if access_key and secret_key:
        headers.update(_sigv4_headers(
            "POST", endpoint, body, region, "ec2", access_key, secret_key))
    out: list[tuple[str, dict]] = []
    try:
        req = urllib.request.Request(endpoint, data=body.encode(),
                                     headers=headers, method="POST")
        with urllib.request.urlopen(req, timeout=15) as r:
            xml = r.read().decode("utf-8", "replace")
        for inst in _parse_ec2_instances(xml):
            ip = inst.get("privateIpAddress")
            if not ip:
                continue
            labels = {
                "__meta_ec2_instance_id": inst.get("instanceId", ""),
                "__meta_ec2_private_ip": ip,
                "__meta_ec2_instance_type": inst.get("instanceType", ""),
                "__meta_ec2_availability_zone":
                    inst.get("availabilityZone", ""),
                "__meta_ec2_instance_state": inst.get("state", ""),
            }
            if inst.get("publicIpAddress"):
                labels["__meta_ec2_public_ip"] = inst["publicIpAddress"]
            for k, v in inst.get("tags", {}).items():
                labels["__meta_ec2_tag_" + _sanitize(k)] = v
            out.append((f"{ip}:{port}", labels))
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"ec2_sd {endpoint}: {e}") from e
    return out


def _parse_ec2_instances(xml: str) -> list[dict]:
    import xml.etree.ElementTree as ET
    root = ET.fromstring(xml)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[:root.tag.index("}") + 1]
    out = []
    for item in root.iter(f"{ns}instancesSet"):
        for inst in item.findall(f"{ns}item"):
            d = {}
            for field in ("instanceId", "instanceType",
                          "privateIpAddress", "publicIpAddress"):
                el = inst.find(f"{ns}{field}")
                if el is not None and el.text:
                    d[field] = el.text
            st = inst.find(f"{ns}instanceState/{ns}name")
            if st is not None:
                d["state"] = st.text
            az = inst.find(f"{ns}placement/{ns}availabilityZone")
            if az is not None:
                d["availabilityZone"] = az.text
            tags = {}
            for t in inst.findall(f"{ns}tagSet/{ns}item"):
                k = t.find(f"{ns}key")
                v = t.find(f"{ns}value")
                if k is not None and v is not None:
                    tags[k.text] = v.text or ""
            d["tags"] = tags
            out.append(d)
    return out


def _sigv4_headers(method: str, url: str, body, region: str,
                   service: str, access_key: str, secret_key: str) -> dict:
    """AWS Signature Version 4 (lib/awsapi/sign.go analog): hashes the RAW
    byte payload, sends x-amz-content-sha256 (required by S3), and
    canonicalizes the query string in sorted order."""
    import datetime
    import hashlib
    import hmac
    from urllib.parse import parse_qsl, quote, urlparse
    if isinstance(body, str):
        body = body.encode()
    u = urlparse(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()
    q = sorted(parse_qsl(u.query, keep_blank_values=True))
    canonical_query = "&".join(
        f"{quote(k, safe='-_.~')}={quote(v, safe='-_.~')}" for k, v in q)
    canonical_headers = (f"host:{u.netloc}\n"
                         f"x-amz-content-sha256:{payload_hash}\n"
                         f"x-amz-date:{amz_date}\n")
    signed_headers = "host;x-amz-content-sha256;x-amz-date"
    canonical = "\n".join([method, quote(u.path or "/", safe="/-_.~"),
                           canonical_query, canonical_headers,
                           signed_headers, payload_hash])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    auth = (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={sig}")
    return {"Authorization": auth, "X-Amz-Date": amz_date,
            "X-Amz-Content-Sha256": payload_hash}


PROVIDERS = {
    "kubernetes_sd_configs": kubernetes_sd,
    "consul_sd_configs": consul_sd,
    "ec2_sd_configs": ec2_sd,
}


def discover_targets(sc: dict, last_good: dict | None = None
                     ) -> list[tuple[str, dict]]:
    """All dynamic-provider targets for one scrape config section. On a
    provider error the provider's previous successful result is reused
    (Prometheus keeps last-known-good targets across SD hiccups); pass a
    persistent `last_good` dict to enable that."""
    import json as _json
    out: list[tuple[str, dict]] = []
    for key, fn in PROVIDERS.items():
        for cfg in sc.get(key, []) or []:
            ck = (key, _json.dumps(cfg, sort_keys=True))
            try:
                got = fn(cfg)
            except DiscoveryError as e:
                logger.errorf("%s; keeping last-known-good targets", e)
                got = (last_good or {}).get(ck, [])
            else:
                if last_good is not None:
                    last_good[ck] = got
            out.extend(got)
    return out
