"""Service discovery providers (reference lib/promscrape/discovery/):
kubernetes (pod/node/service/endpoints roles), consul, ec2, plus the
static/file providers handled inline by vmagent.

Each provider resolves a scrape config section to [(address, labels)]
where labels carry the provider's __meta_* set (the subset most relabel
configs use; reference emits a wider set). Providers are plain HTTP
clients so tests can point them at fake API servers (the reference tests
do the same via custom endpoints).
"""

from __future__ import annotations

import json
import urllib.request

from ..utils import logger


class DiscoveryError(RuntimeError):
    """Provider API failure — callers keep their last-known-good targets
    instead of treating this as an empty target list."""


def _get_json(url: str, headers: dict | None = None, timeout: float = 10.0):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


# -- kubernetes (discovery/kubernetes/) --------------------------------------

def kubernetes_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Supported roles: pod, node, service, endpoints."""
    api = cfg.get("api_server", "http://127.0.0.1:8001").rstrip("/")
    role = cfg.get("role", "pod")
    headers = {}
    token = cfg.get("bearer_token", "")
    token_file = cfg.get("bearer_token_file", "")
    if token_file:
        try:
            token = open(token_file).read().strip()
        except OSError as e:
            logger.errorf("kubernetes_sd: cannot read token: %s", e)
    if token:
        headers["Authorization"] = f"Bearer {token}"
    ns = cfg.get("namespaces", {}).get("names", [])
    out: list[tuple[str, dict]] = []

    def paths(kind):
        if ns:
            return [f"{api}/api/v1/namespaces/{n}/{kind}" for n in ns]
        return [f"{api}/api/v1/{kind}"]

    try:
        if role == "pod":
            for url in paths("pods"):
                for item in _get_json(url, headers).get("items", []):
                    meta = item.get("metadata", {})
                    status = item.get("status", {})
                    ip = status.get("podIP")
                    if not ip:
                        continue
                    base = {
                        "__meta_kubernetes_namespace":
                            meta.get("namespace", ""),
                        "__meta_kubernetes_pod_name": meta.get("name", ""),
                        "__meta_kubernetes_pod_ip": ip,
                        "__meta_kubernetes_pod_node_name":
                            item.get("spec", {}).get("nodeName", ""),
                        "__meta_kubernetes_pod_phase":
                            status.get("phase", ""),
                    }
                    for k, v in (meta.get("labels") or {}).items():
                        base["__meta_kubernetes_pod_label_" +
                             _sanitize(k)] = v
                    ports = [p for c in item.get("spec", {}).get(
                        "containers", []) for p in c.get("ports", [])]
                    if not ports:
                        out.append((ip, dict(base)))
                    for p in ports:
                        labels = dict(base)
                        labels["__meta_kubernetes_pod_container_port_number"] \
                            = str(p.get("containerPort", ""))
                        if p.get("name"):
                            labels["__meta_kubernetes_pod_container_port_name"] \
                                = p["name"]
                        out.append((f"{ip}:{p.get('containerPort')}", labels))
        elif role == "node":
            for item in _get_json(f"{api}/api/v1/nodes",
                                  headers).get("items", []):
                meta = item.get("metadata", {})
                addrs = {a.get("type"): a.get("address") for a in
                         item.get("status", {}).get("addresses", [])}
                ip = addrs.get("InternalIP") or addrs.get("Hostname")
                if not ip:
                    continue
                labels = {"__meta_kubernetes_node_name":
                          meta.get("name", "")}
                for k, v in (meta.get("labels") or {}).items():
                    labels["__meta_kubernetes_node_label_" +
                           _sanitize(k)] = v
                out.append((f"{ip}:10250", labels))
        elif role in ("service", "endpoints"):
            kind = "services" if role == "service" else "endpoints"
            for url in paths(kind):
                for item in _get_json(url, headers).get("items", []):
                    meta = item.get("metadata", {})
                    base = {
                        "__meta_kubernetes_namespace":
                            meta.get("namespace", ""),
                        f"__meta_kubernetes_{role}_name":
                            meta.get("name", ""),
                    }
                    if role == "service":
                        ip = item.get("spec", {}).get("clusterIP")
                        if not ip or ip == "None":  # headless services
                            continue
                        for p in item.get("spec", {}).get("ports", []):
                            labels = dict(base)
                            labels["__meta_kubernetes_service_port_number"] \
                                = str(p.get("port", ""))
                            out.append((f"{ip}:{p.get('port')}", labels))
                    else:
                        for ss in item.get("subsets", []):
                            for a in ss.get("addresses", []):
                                for p in ss.get("ports", []):
                                    out.append((
                                        f"{a.get('ip')}:{p.get('port')}",
                                        dict(base)))
        else:
            logger.errorf("kubernetes_sd: unsupported role %r", role)
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"kubernetes_sd {api} role={role}: {e}") from e
    return out


def _sanitize(k: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in k)


# -- consul (discovery/consul/) ----------------------------------------------

def consul_sd(cfg: dict) -> list[tuple[str, dict]]:
    server = cfg.get("server", "127.0.0.1:8500")
    scheme = cfg.get("scheme", "http")
    base = f"{scheme}://{server}/v1"
    headers = {}
    if cfg.get("token"):
        headers["X-Consul-Token"] = cfg["token"]
    out: list[tuple[str, dict]] = []
    try:
        services = cfg.get("services") or list(
            _get_json(f"{base}/catalog/services", headers))
        for svc in services:
            for e in _get_json(f"{base}/health/service/{svc}", headers):
                node = e.get("Node", {})
                s = e.get("Service", {})
                addr = s.get("Address") or node.get("Address", "")
                port = s.get("Port", 0)
                labels = {
                    "__meta_consul_service": s.get("Service", svc),
                    "__meta_consul_node": node.get("Node", ""),
                    "__meta_consul_address": node.get("Address", ""),
                    "__meta_consul_service_address": addr,
                    "__meta_consul_service_port": str(port),
                    "__meta_consul_tags":
                        "," + ",".join(s.get("Tags") or []) + ",",
                    "__meta_consul_dc": node.get("Datacenter", ""),
                }
                out.append((f"{addr}:{port}", labels))
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"consul_sd {server}: {e}") from e
    return out


# -- ec2 (discovery/ec2/) -----------------------------------------------------

def ec2_sd(cfg: dict) -> list[tuple[str, dict]]:
    """DescribeInstances with SigV4 signing; `endpoint` override makes it
    testable against a fake server (the reference supports the same)."""
    region = cfg.get("region", "us-east-1")
    endpoint = cfg.get("endpoint",
                       f"https://ec2.{region}.amazonaws.com")
    port = int(cfg.get("port", 80))
    access_key = cfg.get("access_key", "")
    secret_key = cfg.get("secret_key", "")
    body = "Action=DescribeInstances&Version=2013-10-15"
    headers = {"Content-Type":
               "application/x-www-form-urlencoded; charset=utf-8"}
    if access_key and secret_key:
        headers.update(_sigv4_headers(
            "POST", endpoint, body, region, "ec2", access_key, secret_key))
    out: list[tuple[str, dict]] = []
    try:
        req = urllib.request.Request(endpoint, data=body.encode(),
                                     headers=headers, method="POST")
        with urllib.request.urlopen(req, timeout=15) as r:
            xml = r.read().decode("utf-8", "replace")
        for inst in _parse_ec2_instances(xml):
            ip = inst.get("privateIpAddress")
            if not ip:
                continue
            labels = {
                "__meta_ec2_instance_id": inst.get("instanceId", ""),
                "__meta_ec2_private_ip": ip,
                "__meta_ec2_instance_type": inst.get("instanceType", ""),
                "__meta_ec2_availability_zone":
                    inst.get("availabilityZone", ""),
                "__meta_ec2_instance_state": inst.get("state", ""),
            }
            if inst.get("publicIpAddress"):
                labels["__meta_ec2_public_ip"] = inst["publicIpAddress"]
            for k, v in inst.get("tags", {}).items():
                labels["__meta_ec2_tag_" + _sanitize(k)] = v
            out.append((f"{ip}:{port}", labels))
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"ec2_sd {endpoint}: {e}") from e
    return out


def _parse_ec2_instances(xml: str) -> list[dict]:
    import xml.etree.ElementTree as ET
    root = ET.fromstring(xml)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[:root.tag.index("}") + 1]
    out = []
    for item in root.iter(f"{ns}instancesSet"):
        for inst in item.findall(f"{ns}item"):
            d = {}
            for field in ("instanceId", "instanceType",
                          "privateIpAddress", "publicIpAddress"):
                el = inst.find(f"{ns}{field}")
                if el is not None and el.text:
                    d[field] = el.text
            st = inst.find(f"{ns}instanceState/{ns}name")
            if st is not None:
                d["state"] = st.text
            az = inst.find(f"{ns}placement/{ns}availabilityZone")
            if az is not None:
                d["availabilityZone"] = az.text
            tags = {}
            for t in inst.findall(f"{ns}tagSet/{ns}item"):
                k = t.find(f"{ns}key")
                v = t.find(f"{ns}value")
                if k is not None and v is not None:
                    tags[k.text] = v.text or ""
            d["tags"] = tags
            out.append(d)
    return out


def _sigv4_headers(method: str, url: str, body, region: str,
                   service: str, access_key: str, secret_key: str) -> dict:
    """AWS Signature Version 4 (lib/awsapi/sign.go analog): hashes the RAW
    byte payload, sends x-amz-content-sha256 (required by S3), and
    canonicalizes the query string in sorted order."""
    import datetime
    import hashlib
    import hmac
    from urllib.parse import parse_qsl, quote, urlparse
    if isinstance(body, str):
        body = body.encode()
    u = urlparse(url)
    # SigV4 signing embeds an absolute timestamp the server skew-checks
    now = datetime.datetime.now(datetime.timezone.utc)  # vmt: disable=VMT001
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()
    q = sorted(parse_qsl(u.query, keep_blank_values=True))
    canonical_query = "&".join(
        f"{quote(k, safe='-_.~')}={quote(v, safe='-_.~')}" for k, v in q)
    canonical_headers = (f"host:{u.netloc}\n"
                         f"x-amz-content-sha256:{payload_hash}\n"
                         f"x-amz-date:{amz_date}\n")
    signed_headers = "host;x-amz-content-sha256;x-amz-date"
    canonical = "\n".join([method, quote(u.path or "/", safe="/-_.~"),
                           canonical_query, canonical_headers,
                           signed_headers, payload_hash])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    auth = (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={sig}")
    return {"Authorization": auth, "X-Amz-Date": amz_date,
            "X-Amz-Content-Sha256": payload_hash}


# -- http (discovery/http/) --------------------------------------------------

def http_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Generic HTTP SD (the escape hatch everything else can feed):
    GET url -> [{"targets": [...], "labels": {...}}, ...]
    (reference lib/promscrape/discovery/http/api.go)."""
    url = cfg.get("url", "")
    if not url:
        raise DiscoveryError("http_sd: missing url")
    headers = {}
    token = cfg.get("bearer_token", "")
    if cfg.get("bearer_token_file"):
        try:
            token = open(cfg["bearer_token_file"]).read().strip()
        except OSError as e:
            logger.errorf("http_sd: cannot read token: %s", e)
    if token:
        headers["Authorization"] = f"Bearer {token}"
    ba = cfg.get("basic_auth") or {}
    if ba.get("username"):
        import base64
        cred = f"{ba['username']}:{ba.get('password', '')}".encode()
        headers["Authorization"] = \
            "Basic " + base64.b64encode(cred).decode()
    try:
        groups = _get_json(url, headers)
    except Exception as e:
        raise DiscoveryError(f"http_sd {url}: {e}") from e
    out: list[tuple[str, dict]] = []
    for g in groups or []:
        labels = {f"__meta_{k}" if not k.startswith("__") else k: str(v)
                  for k, v in (g.get("labels") or {}).items()}
        labels["__meta_url"] = url
        for t in g.get("targets") or []:
            out.append((t, dict(labels)))
    return out


# -- dns (discovery/dns/) ----------------------------------------------------

_DNS_TYPES = {"SRV": 33, "A": 1, "AAAA": 28}


def _dns_encode_name(name: str) -> bytes:
    out = b""
    for part in name.rstrip(".").split("."):
        p = part.encode()
        out += bytes([len(p)]) + p
    return out + b"\x00"


def _dns_read_name(msg: bytes, off: int) -> tuple[str, int]:
    """Compression-aware name decode; returns (name, next offset)."""
    parts = []
    jumped = False
    end = off
    for _ in range(128):  # loop guard
        ln = msg[off]
        if ln & 0xC0 == 0xC0:  # pointer
            ptr = ((ln & 0x3F) << 8) | msg[off + 1]
            if not jumped:
                end = off + 2
            off = ptr
            jumped = True
            continue
        if ln == 0:
            if not jumped:
                end = off + 1
            break
        parts.append(msg[off + 1:off + 1 + ln].decode("ascii", "replace"))
        off += 1 + ln
    return ".".join(parts), end


def _dns_query(name: str, qtype: int, server: str, port: int = 53,
               timeout: float = 3.0) -> list[tuple]:
    """Minimal UDP DNS client: returns [(rtype, rdata)] answers, where SRV
    rdata = (prio, weight, port, target) and A/AAAA rdata = ip string."""
    import socket
    import struct as _s
    qid = (hash(name) ^ id(object())) & 0xFFFF
    msg = _s.pack(">HHHHHH", qid, 0x0100, 1, 0, 0, 0) + \
        _dns_encode_name(name) + _s.pack(">HH", qtype, 1)
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(msg, (server, port))
        resp, _ = s.recvfrom(8192)
    rid, flags, qd, an, _, _ = _s.unpack(">HHHHHH", resp[:12])
    if rid != qid or (flags & 0x000F) != 0:
        raise DiscoveryError(f"dns_sd: bad response for {name}")
    off = 12
    for _ in range(qd):  # skip questions
        _, off = _dns_read_name(resp, off)
        off += 4
    out = []
    for _ in range(an):
        _, off = _dns_read_name(resp, off)
        rtype, _, _, rdlen = _s.unpack(">HHIH", resp[off:off + 10])
        off += 10
        rd = resp[off:off + rdlen]
        if rtype == 33:  # SRV
            prio, weight, prt = _s.unpack(">HHH", rd[:6])
            target, _ = _dns_read_name(resp, off + 6)
            out.append((rtype, (prio, weight, prt, target)))
        elif rtype == 1 and rdlen == 4:
            out.append((rtype, ".".join(str(b) for b in rd)))
        elif rtype == 28 and rdlen == 16:
            import socket as _sock
            out.append((rtype, _sock.inet_ntop(_sock.AF_INET6, rd)))
        off += rdlen
    return out


def _system_resolver() -> tuple[str, int]:
    try:
        with open("/etc/resolv.conf") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2 and parts[0] == "nameserver":
                    return parts[1], 53
    except OSError:
        pass
    return "127.0.0.1", 53


def dns_sd(cfg: dict) -> list[tuple[str, dict]]:
    """SRV/A/AAAA record discovery (lib/promscrape/discovery/dns). The
    resolver defaults to /etc/resolv.conf; `resolver` ("host:port")
    overrides it — tests point it at a fake UDP server."""
    qtype_name = (cfg.get("type") or "SRV").upper()
    qtype = _DNS_TYPES.get(qtype_name)
    if qtype is None:
        raise DiscoveryError(f"dns_sd: unsupported type {qtype_name!r}")
    port = cfg.get("port")
    if qtype_name != "SRV" and port is None:
        raise DiscoveryError("dns_sd: `port` is required for A/AAAA")
    resolver = cfg.get("resolver", "")
    if resolver:
        host, _, rp = resolver.partition(":")
        server = (host, int(rp or 53))
    else:
        server = _system_resolver()
    out: list[tuple[str, dict]] = []
    for name in cfg.get("names", []) or []:
        import struct
        try:
            answers = _dns_query(name, qtype, server[0], server[1])
        except (OSError, DiscoveryError, IndexError, ValueError,
                struct.error) as e:
            # Index/struct errors = malformed/truncated datagrams; they must
            # degrade to last-known-good targets, not kill the SD loop
            raise DiscoveryError(f"dns_sd {name}: {e}") from e
        for rtype, rd in answers:
            meta = {"__meta_dns_name": name}
            if rtype == 33:
                prio, weight, prt, target = rd
                meta["__meta_dns_srv_record_target"] = target
                meta["__meta_dns_srv_record_port"] = str(prt)
                addr = f"{target}:{port if port is not None else prt}"
            else:
                addr = f"{rd}:{port}"
            out.append((addr, meta))
    return out


# -- docker (discovery/docker/) ----------------------------------------------

def _docker_get(host: str, path: str, timeout: float = 10.0):
    """GET against a docker daemon: tcp/http hosts via urllib, unix://
    sockets via a raw HTTPConnection bound to the socket path."""
    if host.startswith("unix://"):
        import http.client
        import socket

        class _UnixConn(http.client.HTTPConnection):
            def __init__(self, spath):
                super().__init__("localhost", timeout=timeout)
                self._spath = spath

            def connect(self):
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(timeout)
                s.connect(self._spath)
                self.sock = s

        conn = _UnixConn(host[len("unix://"):])
        conn.request("GET", path)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        if resp.status != 200:
            raise DiscoveryError(f"docker {path}: HTTP {resp.status}")
        return json.loads(data)
    base = host.rstrip("/")
    if base.startswith("tcp://"):
        base = "http://" + base[len("tcp://"):]
    return _get_json(base + path)


def docker_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Container discovery against the Docker Engine API
    (lib/promscrape/discovery/docker): one target per container network,
    port = first private port (or `port` from the config)."""
    host = cfg.get("host", "unix:///var/run/docker.sock")
    dport = int(cfg.get("port", 80))
    try:
        containers = _docker_get(host, "/containers/json")
    except (OSError, ValueError, DiscoveryError) as e:
        raise DiscoveryError(f"docker_sd {host}: {e}") from e
    out: list[tuple[str, dict]] = []
    for c in containers or []:
        names = c.get("Names") or ["/"]
        meta_base = {
            "__meta_docker_container_id": c.get("Id", ""),
            "__meta_docker_container_name": names[0],
            "__meta_docker_container_state": c.get("State", ""),
        }
        for k, v in (c.get("Labels") or {}).items():
            meta_base[f"__meta_docker_container_label_{_sanitize(k)}"] = v
        ports = [p for p in (c.get("Ports") or [])
                 if p.get("PrivatePort")]
        nets = (c.get("NetworkSettings") or {}).get("Networks") or {}
        for net_name, net in nets.items():
            ip = net.get("IPAddress", "")
            if not ip:
                continue
            meta = dict(meta_base)
            meta["__meta_docker_network_name"] = net_name
            meta["__meta_docker_network_ip"] = ip
            if ports:
                p = ports[0]
                meta["__meta_docker_port_private"] = str(p["PrivatePort"])
                if p.get("PublicPort"):
                    meta["__meta_docker_port_public"] = str(p["PublicPort"])
                out.append((f"{ip}:{p['PrivatePort']}", meta))
            else:
                out.append((f"{ip}:{dport}", meta))
    return out


# -- gce (discovery/gce/) ----------------------------------------------------

def gce_sd(cfg: dict) -> list[tuple[str, dict]]:
    """GCE instance discovery (lib/promscrape/discovery/gce): compute API
    instance list with metadata-server auth; `api_server` points it at
    fakes."""
    project = cfg.get("project", "")
    zone = cfg.get("zone", "")
    if not project or not zone:
        raise DiscoveryError("gce_sd: project and zone are required")
    api = cfg.get("api_server",
                  "https://compute.googleapis.com").rstrip("/")
    port = int(cfg.get("port", 80))
    headers = {}
    token = cfg.get("access_token", "")
    if not token and "googleapis.com" in api:
        try:
            req = urllib.request.Request(
                "http://metadata.google.internal/computeMetadata/v1/"
                "instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=5) as r:
                token = json.load(r)["access_token"]
        except Exception as e:
            raise DiscoveryError(f"gce_sd: metadata token: {e}") from e
    if token:
        headers["Authorization"] = f"Bearer {token}"
    url = (f"{api}/compute/v1/projects/{project}/zones/{zone}/instances")
    out: list[tuple[str, dict]] = []
    try:
        while True:
            resp = _get_json(url, headers)
            for inst in resp.get("items", []):
                ifaces = inst.get("networkInterfaces") or []
                ip = ifaces[0].get("networkIP", "") if ifaces else ""
                if not ip:
                    continue
                meta = {
                    "__meta_gce_instance_id": str(inst.get("id", "")),
                    "__meta_gce_instance_name": inst.get("name", ""),
                    "__meta_gce_instance_status": inst.get("status", ""),
                    "__meta_gce_machine_type":
                        inst.get("machineType", "").rsplit("/", 1)[-1],
                    "__meta_gce_network":
                        (ifaces[0].get("network", "").rsplit("/", 1)[-1]
                         if ifaces else ""),
                    "__meta_gce_private_ip": ip,
                    "__meta_gce_project": project,
                    "__meta_gce_zone": zone,
                }
                for it in (inst.get("metadata") or {}).get("items", []):
                    meta[f"__meta_gce_metadata_{_sanitize(it['key'])}"] = \
                        it.get("value", "")
                tags = (inst.get("tags") or {}).get("items", [])
                if tags:
                    # separator-wrapped, so `,tag,` regexes match every
                    # position (Prometheus gce_sd format)
                    meta["__meta_gce_tags"] = "," + ",".join(tags) + ","
                ac = ifaces[0].get("accessConfigs") if ifaces else None
                if ac and ac[0].get("natIP"):
                    meta["__meta_gce_public_ip"] = ac[0]["natIP"]
                out.append((f"{ip}:{port}", meta))
            tok = resp.get("nextPageToken")
            if not tok:
                break
            url = (f"{api}/compute/v1/projects/{project}/zones/{zone}"
                   f"/instances?pageToken={tok}")
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"gce_sd {api}: {e}") from e
    return out


# -- azure (discovery/azure/) ------------------------------------------------

def azure_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Azure VM discovery (lib/promscrape/discovery/azure): ARM VM list +
    NIC private-IP resolution, OAuth client-credentials auth.
    `api_server`/`token_url` overrides point it at fakes."""
    sub = cfg.get("subscription_id", "")
    if not sub:
        raise DiscoveryError("azure_sd: subscription_id is required")
    api = cfg.get("api_server",
                  "https://management.azure.com").rstrip("/")
    port = int(cfg.get("port", 80))
    headers = {}
    token = cfg.get("access_token", "")
    if not token and cfg.get("client_id"):
        import urllib.parse
        tenant = cfg.get("tenant_id", "")
        token_url = cfg.get(
            "token_url",
            f"https://login.microsoftonline.com/{tenant}/oauth2/token")
        body = urllib.parse.urlencode({
            "grant_type": "client_credentials",
            "client_id": cfg["client_id"],
            "client_secret": cfg.get("client_secret", ""),
            "resource": api + "/",
        }).encode()
        try:
            req = urllib.request.Request(token_url, data=body)
            with urllib.request.urlopen(req, timeout=10) as r:
                token = json.load(r)["access_token"]
        except Exception as e:
            raise DiscoveryError(f"azure_sd: token: {e}") from e
    if token:
        headers["Authorization"] = f"Bearer {token}"
    rg = cfg.get("resource_group", "")
    scope = (f"/subscriptions/{sub}/resourceGroups/{rg}" if rg
             else f"/subscriptions/{sub}")
    url = (f"{api}{scope}/providers/Microsoft.Compute/virtualMachines"
           f"?api-version=2022-03-01")
    out: list[tuple[str, dict]] = []
    try:
        while url:
            resp = _get_json(url, headers)
            for vm in resp.get("value", []):
                props = vm.get("properties") or {}
                meta = {
                    "__meta_azure_machine_id": vm.get("id", ""),
                    "__meta_azure_machine_name": vm.get("name", ""),
                    "__meta_azure_machine_location":
                        vm.get("location", ""),
                    "__meta_azure_machine_resource_group":
                        vm.get("id", "").split("/resourceGroups/")[-1]
                        .split("/")[0] if "/resourceGroups/" in
                        vm.get("id", "") else "",
                    "__meta_azure_machine_os_type":
                        ((props.get("storageProfile") or {})
                         .get("osDisk") or {}).get("osType", ""),
                    "__meta_azure_subscription_id": sub,
                }
                for k, v in (vm.get("tags") or {}).items():
                    meta[f"__meta_azure_machine_tag_{_sanitize(k)}"] = v
                ip = ""
                nics = ((props.get("networkProfile") or {})
                        .get("networkInterfaces") or [])
                if nics:
                    nic_url = (f"{api}{nics[0].get('id', '')}"
                               f"?api-version=2022-05-01")
                    nic = _get_json(nic_url, headers)
                    for ipc in ((nic.get("properties") or {})
                                .get("ipConfigurations") or []):
                        ip = (ipc.get("properties") or {}).get(
                            "privateIPAddress", "")
                        if ip:
                            break
                if not ip:
                    continue
                meta["__meta_azure_machine_private_ip"] = ip
                out.append((f"{ip}:{port}", meta))
            url = resp.get("nextLink", "")
    except (OSError, ValueError) as e:
        raise DiscoveryError(f"azure_sd {api}: {e}") from e
    return out


# -- nomad (discovery/nomad/) ------------------------------------------------

def nomad_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Nomad service discovery (lib/promscrape/discovery/nomad): list
    service names, then each service's registrations; one target per
    registration at Address:Port."""
    import urllib.parse as _up
    server = cfg.get("server", "localhost:4646")
    if not server.startswith(("http://", "https://")):
        server = "http://" + server
    base = f"{server.rstrip('/')}/v1"
    q = "?" + _up.urlencode({"namespace": cfg.get("namespace", "default"),
                             "region": cfg.get("region", "global")})
    try:
        listing = _get_json(f"{base}/services{q}")
        out: list[tuple[str, dict]] = []
        for group in listing or []:
            for svc in group.get("Services") or []:
                name = svc.get("ServiceName", "")
                if not name:
                    continue
                for reg in _get_json(
                        f"{base}/service/"
                        f"{_up.quote(name, safe='')}{q}") or []:
                    addr = reg.get("Address", "")
                    port = reg.get("Port", 0)
                    meta = {
                        "__meta_nomad_address": addr,
                        "__meta_nomad_dc": reg.get("Datacenter", ""),
                        "__meta_nomad_namespace":
                            reg.get("Namespace", ""),
                        "__meta_nomad_node_id": reg.get("NodeID", ""),
                        "__meta_nomad_service":
                            reg.get("ServiceName", ""),
                        "__meta_nomad_service_address": addr,
                        "__meta_nomad_service_alloc_id":
                            reg.get("AllocID", ""),
                        "__meta_nomad_service_id": reg.get("ID", ""),
                        "__meta_nomad_service_job_id":
                            reg.get("JobID", ""),
                        "__meta_nomad_service_port": str(port),
                        "__meta_nomad_tags":
                            "," + ",".join(reg.get("Tags") or []) + ",",
                    }
                    for tag in reg.get("Tags") or []:
                        k, sep, v = tag.partition("=")
                        if sep:
                            meta[f"__meta_nomad_tag_{_sanitize(k)}"] = v
                        meta[f"__meta_nomad_tagpresent_{_sanitize(k)}"] \
                            = "true"
                    out.append((f"{addr}:{port}", meta))
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"nomad_sd {server}: {e}") from e


# -- dockerswarm (discovery/dockerswarm/) ------------------------------------

def dockerswarm_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Docker Swarm discovery (lib/promscrape/discovery/dockerswarm):
    roles tasks (default), services, nodes against the engine API."""
    host = cfg.get("host", "unix:///var/run/docker.sock")
    role = cfg.get("role", "tasks")
    dport = int(cfg.get("port", 80))
    try:
        if role == "nodes":
            out = []
            for n in _docker_get(host, "/nodes") or []:
                desc = n.get("Description") or {}
                status = n.get("Status") or {}
                spec = n.get("Spec") or {}
                meta = {
                    "__meta_dockerswarm_node_id": n.get("ID", ""),
                    "__meta_dockerswarm_node_address":
                        status.get("Addr", ""),
                    "__meta_dockerswarm_node_availability":
                        spec.get("Availability", ""),
                    "__meta_dockerswarm_node_hostname":
                        desc.get("Hostname", ""),
                    "__meta_dockerswarm_node_role": spec.get("Role", ""),
                    "__meta_dockerswarm_node_status":
                        status.get("State", ""),
                    "__meta_dockerswarm_node_platform_architecture":
                        (desc.get("Platform") or {}).get(
                            "Architecture", ""),
                    "__meta_dockerswarm_node_platform_os":
                        (desc.get("Platform") or {}).get("OS", ""),
                    "__meta_dockerswarm_node_engine_version":
                        (desc.get("Engine") or {}).get(
                            "EngineVersion", ""),
                }
                for k, v in (spec.get("Labels") or {}).items():
                    meta["__meta_dockerswarm_node_label_"
                         f"{_sanitize(k)}"] = v
                out.append((f"{status.get('Addr', '')}:{dport}", meta))
            return out
        services = {s["ID"]: s for s in _docker_get(host, "/services")
                    or []}
        if role == "services":
            out = []
            for s in services.values():
                spec = s.get("Spec") or {}
                meta = {
                    "__meta_dockerswarm_service_id": s.get("ID", ""),
                    "__meta_dockerswarm_service_name":
                        spec.get("Name", ""),
                    "__meta_dockerswarm_service_mode":
                        next(iter(spec.get("Mode") or {"": None})).lower(),
                }
                for k, v in (spec.get("Labels") or {}).items():
                    meta["__meta_dockerswarm_service_label_"
                         f"{_sanitize(k)}"] = v
                eps = ((s.get("Endpoint") or {}).get("VirtualIPs")
                       or [])
                for ep in eps:
                    ip = (ep.get("Addr") or "").split("/")[0]
                    if ip:
                        out.append((f"{ip}:{dport}", dict(meta)))
            return out
        # role == tasks
        nodes = {n["ID"]: n for n in _docker_get(host, "/nodes") or []}
        out = []
        for t in _docker_get(host, "/tasks") or []:
            svc = services.get(t.get("ServiceID", "")) or {}
            node = nodes.get(t.get("NodeID", "")) or {}
            meta = {
                "__meta_dockerswarm_task_id": t.get("ID", ""),
                "__meta_dockerswarm_task_desired_state":
                    t.get("DesiredState", ""),
                "__meta_dockerswarm_task_state":
                    (t.get("Status") or {}).get("State", ""),
                "__meta_dockerswarm_task_slot": str(t.get("Slot", "")),
                "__meta_dockerswarm_service_id":
                    t.get("ServiceID", ""),
                "__meta_dockerswarm_service_name":
                    (svc.get("Spec") or {}).get("Name", ""),
                "__meta_dockerswarm_node_id": t.get("NodeID", ""),
                "__meta_dockerswarm_node_hostname":
                    ((node.get("Description") or {})
                     .get("Hostname", "")),
                "__meta_dockerswarm_node_address":
                    (node.get("Status") or {}).get("Addr", ""),
            }
            for k, v in (((t.get("Spec") or {}).get("ContainerSpec")
                          or {}).get("Labels") or {}).items():
                meta["__meta_dockerswarm_container_label_"
                     f"{_sanitize(k)}"] = v
            nets = t.get("NetworksAttachments") or []
            placed = False
            for na in nets:
                for addr in na.get("Addresses") or []:
                    ip = addr.split("/")[0]
                    out.append((f"{ip}:{dport}", dict(meta)))
                    placed = True
            if not placed:
                node_addr = (node.get("Status") or {}).get("Addr", "")
                if node_addr:
                    out.append((f"{node_addr}:{dport}", meta))
        return out
    except (OSError, ValueError, KeyError, DiscoveryError) as e:
        raise DiscoveryError(f"dockerswarm_sd {host}: {e}") from e


# -- eureka (discovery/eureka/) ----------------------------------------------

def eureka_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Eureka app-instance discovery (lib/promscrape/discovery/eureka):
    GET {server}/apps, one target per instance at hostName:port."""
    server = cfg.get("server", "localhost:8080/eureka/v2")
    if not server.startswith(("http://", "https://")):
        server = "http://" + server
    try:
        data = _get_json(f"{server.rstrip('/')}/apps",
                         headers={"Accept": "application/json"})
        out: list[tuple[str, dict]] = []
        apps = ((data or {}).get("applications") or {}) \
            .get("application") or []
        if isinstance(apps, dict):
            apps = [apps]
        for app in apps:
            instances = app.get("instance") or []
            if isinstance(instances, dict):
                instances = [instances]
            for inst in instances:
                port_info = inst.get("port") or {}
                port = int(port_info.get("$", 80))
                meta = {
                    "__meta_eureka_app_name": app.get("name", ""),
                    "__meta_eureka_app_instance_id":
                        inst.get("instanceId", ""),
                    "__meta_eureka_app_instance_hostname":
                        inst.get("hostName", ""),
                    "__meta_eureka_app_instance_ip_addr":
                        inst.get("ipAddr", ""),
                    "__meta_eureka_app_instance_status":
                        inst.get("status", ""),
                    "__meta_eureka_app_instance_port": str(port),
                    "__meta_eureka_app_instance_port_enabled":
                        str(port_info.get("@enabled", "")),
                    "__meta_eureka_app_instance_vip_address":
                        inst.get("vipAddress", ""),
                    "__meta_eureka_app_instance_secure_vip_address":
                        inst.get("secureVipAddress", ""),
                    "__meta_eureka_app_instance_homepage_url":
                        inst.get("homePageUrl", ""),
                    "__meta_eureka_app_instance_statuspage_url":
                        inst.get("statusPageUrl", ""),
                    "__meta_eureka_app_instance_healthcheck_url":
                        inst.get("healthCheckUrl", ""),
                    "__meta_eureka_app_instance_country_id":
                        str(inst.get("countryId", "")),
                    "__meta_eureka_app_instance_datacenterinfo_name":
                        (inst.get("dataCenterInfo") or {})
                        .get("name", ""),
                }
                for k, v in (inst.get("metadata") or {}).items():
                    meta["__meta_eureka_app_instance_metadata_"
                         f"{_sanitize(k)}"] = str(v)
                out.append((f"{inst.get('hostName', '')}:{port}", meta))
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"eureka_sd {server}: {e}") from e


# -- openstack (discovery/openstack/) ----------------------------------------

def openstack_sd(cfg: dict) -> list[tuple[str, dict]]:
    """OpenStack Nova instance discovery
    (lib/promscrape/discovery/openstack): keystone password auth for a
    token, then /servers/detail; role=hypervisor lists hypervisors."""
    identity = cfg.get("identity_endpoint", "")
    if not identity:
        raise DiscoveryError("openstack_sd: identity_endpoint is required")
    dport = int(cfg.get("port", 80))
    role = cfg.get("role", "instance")
    try:
        auth = {"auth": {
            "identity": {"methods": ["password"], "password": {"user": {
                "name": cfg.get("username", ""),
                "domain": {"name": cfg.get("domain_name", "Default")},
                "password": cfg.get("password", "")}}},
            "scope": {"project": {
                "name": cfg.get("project_name", ""),
                "domain": {"name": cfg.get("domain_name", "Default")}}}}}
        req = urllib.request.Request(
            f"{identity.rstrip('/')}/auth/tokens",
            data=json.dumps(auth).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            token = resp.headers.get("X-Subject-Token", "")
            body = json.loads(resp.read())
        catalog = ((body.get("token") or {}).get("catalog")) or []
        nova = ""
        for svc in catalog:
            if svc.get("type") == "compute":
                for ep in svc.get("endpoints") or []:
                    if ep.get("interface") == "public":
                        nova = ep.get("url", "")
        if not nova:
            raise DiscoveryError("no compute endpoint in catalog")
        hdrs = {"X-Auth-Token": token}
        out: list[tuple[str, dict]] = []
        if role == "hypervisor":
            data = _get_json(f"{nova.rstrip('/')}/os-hypervisors/detail",
                             headers=hdrs)
            for h in data.get("hypervisors") or []:
                meta = {
                    "__meta_openstack_hypervisor_id": str(h.get("id", "")),
                    "__meta_openstack_hypervisor_hostname":
                        h.get("hypervisor_hostname", ""),
                    "__meta_openstack_hypervisor_host_ip":
                        h.get("host_ip", ""),
                    "__meta_openstack_hypervisor_state":
                        h.get("state", ""),
                    "__meta_openstack_hypervisor_status":
                        h.get("status", ""),
                    "__meta_openstack_hypervisor_type":
                        h.get("hypervisor_type", ""),
                }
                out.append((f"{h.get('host_ip', '')}:{dport}", meta))
            return out
        url = f"{nova.rstrip('/')}/servers/detail"
        while url:
            data = _get_json(url, headers=hdrs)
            for srv in data.get("servers") or []:
                flavor = (srv.get("flavor") or {})
                meta_base = {
                    "__meta_openstack_instance_id": srv.get("id", ""),
                    "__meta_openstack_instance_name":
                        srv.get("name", ""),
                    "__meta_openstack_instance_status":
                        srv.get("status", ""),
                    "__meta_openstack_instance_flavor":
                        flavor.get("original_name", flavor.get("id", "")),
                    "__meta_openstack_project_id":
                        srv.get("tenant_id", ""),
                    "__meta_openstack_user_id": srv.get("user_id", ""),
                }
                for k, v in (srv.get("metadata") or {}).items():
                    meta_base[f"__meta_openstack_tag_{_sanitize(k)}"] = \
                        str(v)
                for pool, addrs in (srv.get("addresses") or {}).items():
                    for a in addrs or []:
                        ip = a.get("addr", "")
                        if not ip:
                            continue
                        meta = dict(meta_base)
                        meta["__meta_openstack_address_pool"] = pool
                        meta["__meta_openstack_private_ip"] = ip
                        out.append((f"{ip}:{dport}", meta))
            # Nova caps page size server-side; follow the next link
            url = next((ln.get("href", "")
                        for ln in data.get("servers_links") or []
                        if ln.get("rel") == "next"), "")
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"openstack_sd {identity}: {e}") from e


# -- digitalocean (discovery/digitalocean/) ----------------------------------

def digitalocean_sd(cfg: dict) -> list[tuple[str, dict]]:
    """DigitalOcean droplet discovery
    (lib/promscrape/discovery/digitalocean): /v2/droplets with bearer
    auth; target = public IPv4:port."""
    server = cfg.get("server", "https://api.digitalocean.com")
    dport = int(cfg.get("port", 80))
    headers = {}
    if cfg.get("bearer_token"):
        headers["Authorization"] = f"Bearer {cfg['bearer_token']}"
    out: list[tuple[str, dict]] = []
    url = f"{server.rstrip('/')}/v2/droplets?per_page=200"
    try:
        while url:
            data = _get_json(url, headers=headers)
            for d in data.get("droplets") or []:
                v4 = (d.get("networks") or {}).get("v4") or []
                pub = next((n["ip_address"] for n in v4
                            if n.get("type") == "public"), "")
                priv = next((n["ip_address"] for n in v4
                             if n.get("type") == "private"), "")
                if not pub:
                    continue
                meta = {
                    "__meta_digitalocean_droplet_id":
                        str(d.get("id", "")),
                    "__meta_digitalocean_droplet_name":
                        d.get("name", ""),
                    "__meta_digitalocean_image":
                        (d.get("image") or {}).get("slug", ""),
                    "__meta_digitalocean_image_name":
                        (d.get("image") or {}).get("name", ""),
                    "__meta_digitalocean_private_ipv4": priv,
                    "__meta_digitalocean_public_ipv4": pub,
                    "__meta_digitalocean_region":
                        (d.get("region") or {}).get("slug", ""),
                    "__meta_digitalocean_size":
                        (d.get("size") or {}).get("slug", ""),
                    "__meta_digitalocean_status": d.get("status", ""),
                    "__meta_digitalocean_vpc": d.get("vpc_uuid", ""),
                    "__meta_digitalocean_tags":
                        "," + ",".join(d.get("tags") or []) + ",",
                    "__meta_digitalocean_features":
                        "," + ",".join(d.get("features") or []) + ",",
                }
                out.append((f"{pub}:{dport}", meta))
            url = (((data.get("links") or {}).get("pages") or {})
                   .get("next", ""))
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"digitalocean_sd {server}: {e}") from e


# -- consulagent (discovery/consulagent/) ------------------------------------

def consulagent_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Consul local-agent discovery (lib/promscrape/discovery/
    consulagent): /v1/agent/self + /v1/agent/services — the agent's own
    registrations, no catalog and no health filtering (services in
    critical state are still emitted; relabel on the health metadata if
    you need to drop them)."""
    server = cfg.get("server", "localhost:8500")
    if not server.startswith(("http://", "https://")):
        server = "http://" + server
    base = server.rstrip("/")
    try:
        node = _get_json(f"{base}/v1/agent/self") or {}
        member = node.get("Member") or {}
        node_name = member.get("Name", "")
        dc = (node.get("Config") or {}).get("Datacenter", "")
        services = _get_json(f"{base}/v1/agent/services") or {}
        want = set(cfg.get("services") or [])
        out: list[tuple[str, dict]] = []
        for svc in services.values():
            name = svc.get("Service", "")
            if want and name not in want:
                continue
            addr = svc.get("Address") or member.get("Addr", "")
            port = svc.get("Port", 0)
            meta = {
                "__meta_consulagent_address": member.get("Addr", ""),
                "__meta_consulagent_dc": dc,
                "__meta_consulagent_namespace":
                    svc.get("Namespace", ""),
                "__meta_consulagent_node": node_name,
                "__meta_consulagent_service": name,
                "__meta_consulagent_service_address": addr,
                "__meta_consulagent_service_id": svc.get("ID", ""),
                "__meta_consulagent_service_port": str(port),
                "__meta_consulagent_tags":
                    "," + ",".join(svc.get("Tags") or []) + ",",
            }
            for t in svc.get("Tags") or []:
                meta[f"__meta_consulagent_tag_{_sanitize(t)}"] = t
            for k, v in (svc.get("Meta") or {}).items():
                meta["__meta_consulagent_service_metadata_"
                     f"{_sanitize(k)}"] = str(v)
            out.append((f"{addr}:{port}", meta))
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"consulagent_sd {server}: {e}") from e


# -- hetzner (discovery/hetzner/) --------------------------------------------

def hetzner_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Hetzner Cloud discovery (lib/promscrape/discovery/hetzner,
    role=hcloud): /v1/servers with bearer auth, paginated."""
    role = cfg.get("role", "hcloud")
    if role != "hcloud":
        raise DiscoveryError(f"hetzner_sd: unsupported role {role!r}")
    server = cfg.get("endpoint", "https://api.hetzner.cloud")
    dport = int(cfg.get("port", 80))
    headers = {}
    if cfg.get("bearer_token"):
        headers["Authorization"] = f"Bearer {cfg['bearer_token']}"
    url = f"{server.rstrip('/')}/v1/servers?page=1&per_page=50"
    out: list[tuple[str, dict]] = []
    try:
        # network id -> name (private_net entries carry numeric ids; the
        # documented label shape uses the network NAME); paginated like
        # /v1/servers
        net_names = {}
        try:
            nurl = f"{server.rstrip('/')}/v1/networks?page=1&per_page=50"
            while nurl:
                ndata = _get_json(nurl, headers=headers) or {}
                for nw in ndata.get("networks") or []:
                    net_names[nw.get("id")] = nw.get("name", "")
                nxt = (((ndata.get("meta") or {}).get("pagination") or {})
                       .get("next_page"))
                nurl = (f"{server.rstrip('/')}/v1/networks?page={nxt}"
                        f"&per_page=50") if nxt else ""
        except (OSError, ValueError, KeyError):
            pass  # label falls back to the id
        while url:
            data = _get_json(url, headers=headers)
            for s in data.get("servers") or []:
                pub = ((s.get("public_net") or {}).get("ipv4")
                       or {}).get("ip", "")
                dc = s.get("datacenter") or {}
                loc = dc.get("location") or {}
                stype = s.get("server_type") or {}
                img = s.get("image") or {}
                meta = {
                    "__meta_hetzner_server_id": str(s.get("id", "")),
                    "__meta_hetzner_server_name": s.get("name", ""),
                    "__meta_hetzner_server_status": s.get("status", ""),
                    "__meta_hetzner_public_ipv4": pub,
                    "__meta_hetzner_datacenter": dc.get("name", ""),
                    "__meta_hetzner_hcloud_datacenter_location":
                        loc.get("name", ""),
                    "__meta_hetzner_hcloud_datacenter_location_network_zone":
                        loc.get("network_zone", ""),
                    "__meta_hetzner_hcloud_server_type":
                        stype.get("name", ""),
                    "__meta_hetzner_hcloud_cpu_cores":
                        str(stype.get("cores", "")),
                    "__meta_hetzner_hcloud_cpu_type":
                        stype.get("cpu_type", ""),
                    "__meta_hetzner_hcloud_memory_size_gb":
                        str(stype.get("memory", "")),
                    "__meta_hetzner_hcloud_disk_size_gb":
                        str(stype.get("disk", "")),
                    "__meta_hetzner_hcloud_image_name":
                        img.get("name", ""),
                    "__meta_hetzner_hcloud_image_os_flavor":
                        img.get("os_flavor", ""),
                    "__meta_hetzner_hcloud_image_os_version":
                        img.get("os_version", ""),
                }
                for k, v in (s.get("labels") or {}).items():
                    meta[f"__meta_hetzner_hcloud_label_{_sanitize(k)}"] \
                        = str(v)
                    meta["__meta_hetzner_hcloud_labelpresent_"
                         f"{_sanitize(k)}"] = "true"
                for pn in (s.get("private_net") or []):
                    ip = pn.get("ip", "")
                    if ip:
                        nid = pn.get("network", "")
                        nname = net_names.get(nid, str(nid))
                        meta.setdefault(
                            "__meta_hetzner_hcloud_private_ipv4_"
                            f"{_sanitize(str(nname))}", ip)
                if pub:
                    out.append((f"{pub}:{dport}", meta))
            nxt = (((data.get("meta") or {}).get("pagination") or {})
                   .get("next_page"))
            url = (f"{server.rstrip('/')}/v1/servers?page={nxt}"
                   f"&per_page=50") if nxt else ""
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"hetzner_sd {server}: {e}") from e


# -- vultr (discovery/vultr/) ------------------------------------------------

def vultr_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Vultr instance discovery (lib/promscrape/discovery/vultr):
    /v2/instances with bearer auth, cursor-paginated."""
    server = cfg.get("endpoint", "https://api.vultr.com")
    dport = int(cfg.get("port", 80))
    headers = {}
    if cfg.get("bearer_token"):
        headers["Authorization"] = f"Bearer {cfg['bearer_token']}"
    url = f"{server.rstrip('/')}/v2/instances?per_page=100"
    out: list[tuple[str, dict]] = []
    try:
        while url:
            data = _get_json(url, headers=headers)
            for inst in data.get("instances") or []:
                ip = inst.get("main_ip", "")
                if not ip:
                    continue
                meta = {
                    "__meta_vultr_instance_id": inst.get("id", ""),
                    "__meta_vultr_instance_label": inst.get("label", ""),
                    "__meta_vultr_instance_hostname":
                        inst.get("hostname", ""),
                    "__meta_vultr_instance_os": inst.get("os", ""),
                    "__meta_vultr_instance_os_id":
                        str(inst.get("os_id", "")),
                    "__meta_vultr_instance_region":
                        inst.get("region", ""),
                    "__meta_vultr_instance_plan": inst.get("plan", ""),
                    "__meta_vultr_instance_main_ip": ip,
                    "__meta_vultr_instance_internal_ip":
                        inst.get("internal_ip", ""),
                    "__meta_vultr_instance_main_ipv6":
                        inst.get("v6_main_ip", ""),
                    "__meta_vultr_instance_server_status":
                        inst.get("server_status", ""),
                    "__meta_vultr_instance_vcpu_count":
                        str(inst.get("vcpu_count", "")),
                    "__meta_vultr_instance_ram_mb":
                        str(inst.get("ram", "")),
                    "__meta_vultr_instance_disk_gb":
                        str(inst.get("disk", "")),
                    "__meta_vultr_instance_allowed_bandwidth_gb":
                        str(inst.get("allowed_bandwidth", "")),
                    "__meta_vultr_instance_features":
                        "," + ",".join(inst.get("features") or []) + ",",
                    "__meta_vultr_instance_tags":
                        "," + ",".join(inst.get("tags") or []) + ",",
                }
                out.append((f"{ip}:{dport}", meta))
            cursor = (((data.get("meta") or {}).get("links") or {})
                      .get("next", ""))
            import urllib.parse as _up
            url = (f"{server.rstrip('/')}/v2/instances?per_page=100"
                   f"&cursor={_up.quote(cursor, safe='')}") \
                if cursor else ""
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"vultr_sd {server}: {e}") from e


# -- marathon (discovery/marathon/) ------------------------------------------

def marathon_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Marathon app/task discovery (lib/promscrape/discovery/marathon):
    /v2/apps?embed=apps.tasks, one target per task port."""
    servers = cfg.get("servers") or ["http://localhost:8080"]
    data = None
    errs = []
    for srv_url in servers:  # try each configured server (failover)
        base = srv_url.rstrip("/")
        try:
            data = _get_json(f"{base}/v2/apps?embed=apps.tasks")
            break
        except (OSError, ValueError) as e:
            errs.append(f"{base}: {e}")
    if data is None:
        raise DiscoveryError(f"marathon_sd: all servers failed: "
                             f"{'; '.join(errs)}")
    try:
        out: list[tuple[str, dict]] = []
        for app in (data.get("apps") or []):
            app_id = app.get("id", "")
            labels_app = app.get("labels") or {}
            container = app.get("container") or {}
            image = (container.get("docker") or {}).get("image", "")
            port_defs = app.get("portDefinitions") or []
            for task in app.get("tasks") or []:
                host = task.get("host", "")
                ports = task.get("ports") or []
                for pi, port in enumerate(ports):
                    meta = {
                        "__meta_marathon_app": app_id,
                        "__meta_marathon_task": task.get("id", ""),
                        "__meta_marathon_image": image,
                        "__meta_marathon_port_index": str(pi),
                    }
                    for k, v in labels_app.items():
                        meta[f"__meta_marathon_app_label_{_sanitize(k)}"] \
                            = str(v)
                    if pi < len(port_defs):
                        for k, v in (port_defs[pi].get("labels")
                                     or {}).items():
                            meta["__meta_marathon_port_definition_label_"
                                 f"{_sanitize(k)}"] = str(v)
                    out.append((f"{host}:{port}", meta))
        return out
    except (ValueError, KeyError) as e:
        raise DiscoveryError(f"marathon_sd {base}: {e}") from e


# -- puppetdb (discovery/puppetdb/) ------------------------------------------

def puppetdb_sd(cfg: dict) -> list[tuple[str, dict]]:
    """PuppetDB resource discovery (lib/promscrape/discovery/puppetdb):
    POST a PQL query to /pdb/query/v4, one target per resource."""
    url = cfg.get("url", "")
    query = cfg.get("query", "")
    if not url or not query:
        raise DiscoveryError("puppetdb_sd: url and query are required")
    dport = int(cfg.get("port", 80))
    include_params = bool(cfg.get("include_parameters"))
    try:
        req = urllib.request.Request(
            f"{url.rstrip('/')}/pdb/query/v4",
            data=json.dumps({"query": query}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            resources = json.loads(resp.read())
        out: list[tuple[str, dict]] = []
        for r in resources or []:
            certname = r.get("certname", "")
            if not certname:
                continue
            meta = {
                "__meta_puppetdb_certname": certname,
                "__meta_puppetdb_environment": r.get("environment", ""),
                "__meta_puppetdb_exported":
                    str(bool(r.get("exported"))).lower(),
                "__meta_puppetdb_file": r.get("file", "") or "",
                "__meta_puppetdb_query": query,
                "__meta_puppetdb_resource": r.get("resource", ""),
                "__meta_puppetdb_tags":
                    "," + ",".join(r.get("tags") or []) + ",",
            }
            if include_params:
                for k, v in (r.get("parameters") or {}).items():
                    meta[f"__meta_puppetdb_parameter_{_sanitize(k)}"] = \
                        str(v)
            out.append((f"{certname}:{dport}", meta))
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"puppetdb_sd {url}: {e}") from e


# -- ovhcloud (discovery/ovhcloud/) ------------------------------------------

# per-endpoint server/local clock delta for OVH request signing, fetched
# once and reused (the official client does the same)
_OVH_TIME_DELTA: dict[str, int] = {}


def _ovh_get(cfg: dict, endpoint: str, path: str):
    """Signed OVH API GET (discovery/ovhcloud/common.go): signature =
    "$1$" + sha1(AS+CK+method+url+body+timestamp). A failed /auth/time
    is LOUD — local time would just produce mysterious 403s on skewed
    hosts."""
    import hashlib
    import time as _time
    app_key = cfg.get("application_key", "")
    app_secret = cfg.get("application_secret", "")
    consumer = cfg.get("consumer_key", "")
    delta = _OVH_TIME_DELTA.get(endpoint)
    if delta is None:
        try:
            delta = int(_get_json(f"{endpoint}/auth/time")) - \
                int(_time.time())  # vmt: disable=VMT001 (signing skew)
        except (OSError, ValueError, TypeError) as e:
            raise DiscoveryError(
                f"ovhcloud: cannot fetch {endpoint}/auth/time for "
                f"request signing: {e}") from e
        _OVH_TIME_DELTA[endpoint] = delta
    # request signing needs the real wall clock, not the cached one: the
    # signature embeds an absolute timestamp the server checks for skew
    ts = int(_time.time()) + delta  # vmt: disable=VMT001
    url = endpoint + path
    sig = hashlib.sha1(
        f"{app_secret}+{consumer}+GET+{url}++{ts}".encode()).hexdigest()
    return _get_json(url, headers={
        "X-Ovh-Application": app_key,
        "X-Ovh-Consumer": consumer,
        "X-Ovh-Timestamp": str(ts),
        "X-Ovh-Signature": f"$1${sig}",
        "Accept": "application/json"})


def ovhcloud_sd(cfg: dict) -> list[tuple[str, dict]]:
    """OVHcloud discovery (lib/promscrape/discovery/ovhcloud): roles
    vps (default) and dedicated_server, per-name detail + /ips calls."""
    import urllib.parse as _up
    endpoint = cfg.get("endpoint", "https://eu.api.ovh.com/1.0")
    role = cfg.get("service", cfg.get("role", "vps"))
    if role not in ("vps", "dedicated_server"):
        raise DiscoveryError(
            f"ovhcloud_sd: unknown service {role!r} "
            "(want `vps` or `dedicated_server`)")
    dport = int(cfg.get("port", 80))
    out: list[tuple[str, dict]] = []
    try:
        if role == "dedicated_server":
            for name in _ovh_get(cfg, endpoint, "/dedicated/server") or []:
                qn = _up.quote(name, safe="")
                d = _ovh_get(cfg, endpoint, f"/dedicated/server/{qn}")
                ips = _ovh_get(cfg, endpoint,
                               f"/dedicated/server/{qn}/ips") or []
                v4 = next((ip for ip in ips if ":" not in ip), "")
                v6 = next((ip for ip in ips if ":" in ip), "")
                meta = {
                    "__meta_ovhcloud_dedicated_server_name":
                        d.get("name", name),
                    "__meta_ovhcloud_dedicated_server_server_id":
                        str(d.get("serverId", "")),
                    "__meta_ovhcloud_dedicated_server_state":
                        d.get("state", ""),
                    "__meta_ovhcloud_dedicated_server_os":
                        d.get("os", ""),
                    "__meta_ovhcloud_dedicated_server_datacenter":
                        d.get("datacenter", ""),
                    "__meta_ovhcloud_dedicated_server_rack":
                        d.get("rack", ""),
                    "__meta_ovhcloud_dedicated_server_reverse":
                        d.get("reverse", ""),
                    "__meta_ovhcloud_dedicated_server_commercial_range":
                        d.get("commercialRange", ""),
                    "__meta_ovhcloud_dedicated_server_link_speed":
                        str(d.get("linkSpeed", "")),
                    "__meta_ovhcloud_dedicated_server_support_level":
                        d.get("supportLevel", ""),
                    "__meta_ovhcloud_dedicated_server_no_intervention":
                        str(bool(d.get("noIntervention"))).lower(),
                    "__meta_ovhcloud_dedicated_server_ipv4": v4.split(
                        "/")[0],
                    "__meta_ovhcloud_dedicated_server_ipv6": v6.split(
                        "/")[0],
                }
                addr = v4.split("/")[0] or d.get("reverse", name)
                out.append((f"{addr}:{dport}", meta))
            return out
        for name in _ovh_get(cfg, endpoint, "/vps") or []:
            qn = _up.quote(name, safe="")
            d = _ovh_get(cfg, endpoint, f"/vps/{qn}")
            ips = _ovh_get(cfg, endpoint, f"/vps/{qn}/ips") or []
            v4 = next((ip for ip in ips if ":" not in ip), "")
            v6 = next((ip for ip in ips if ":" in ip), "")
            model = d.get("model") or {}
            meta = {
                "__meta_ovhcloud_vps_name": d.get("name", name),
                "__meta_ovhcloud_vps_display_name":
                    d.get("displayName", ""),
                "__meta_ovhcloud_vps_cluster": d.get("cluster", ""),
                "__meta_ovhcloud_vps_state": d.get("state", ""),
                "__meta_ovhcloud_vps_zone": d.get("zone", ""),
                "__meta_ovhcloud_vps_datacenter":
                    str(d.get("datacenter", "")),
                "__meta_ovhcloud_vps_disk": str(model.get("disk", "")),
                "__meta_ovhcloud_vps_memory_limit":
                    str(d.get("memoryLimit", "")),
                "__meta_ovhcloud_vps_memory":
                    str(model.get("memory", "")),
                "__meta_ovhcloud_vps_model_name":
                    model.get("name", ""),
                "__meta_ovhcloud_vps_model_vcore":
                    str(model.get("vcore", "")),
                "__meta_ovhcloud_vps_maximum_additional_ip":
                    str(model.get("maximumAdditionnalIp", "")),
                "__meta_ovhcloud_vps_version": str(model.get(
                    "version", "")),
                "__meta_ovhcloud_vps_ipv4": v4.split("/")[0],
                "__meta_ovhcloud_vps_ipv6": v6.split("/")[0],
            }
            addr = v4.split("/")[0] or name
            out.append((f"{addr}:{dport}", meta))
        return out
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise DiscoveryError(f"ovhcloud_sd {endpoint}: {e}") from e


# -- yandexcloud (discovery/yandexcloud/) ------------------------------------

def yandexcloud_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Yandex Cloud compute discovery
    (lib/promscrape/discovery/yandexcloud): IAM-token auth, then
    clouds -> folders -> instances; one target per instance with
    per-interface ip/dns labels."""
    api = cfg.get("api_endpoint", "https://api.cloud.yandex.net") \
        .rstrip("/")
    dport = int(cfg.get("port", 80))
    token = cfg.get("iam_token", "")
    try:
        if not token:
            md = _get_json(
                "http://169.254.169.254/computeMetadata/v1/instance/"
                "service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"})
            token = md.get("access_token", "")
        hdrs = {"Authorization": f"Bearer {token}"}

        def paged(url: str, key: str):
            """Follow nextPageToken like every other paginated provider
            here."""
            sep = "&" if "?" in url else "?"
            page = ""
            while True:
                got = _get_json(url + (f"{sep}pageToken={page}" if page
                                       else ""), headers=hdrs) or {}
                yield from got.get(key) or []
                page = got.get("nextPageToken", "")
                if not page:
                    return

        folders = []
        for cloud in paged(f"{api}/resource-manager/v1/clouds", "clouds"):
            folders.extend(paged(
                f"{api}/resource-manager/v1/folders?cloudId="
                f"{cloud.get('id', '')}", "folders"))
        out: list[tuple[str, dict]] = []
        for folder in folders:
            fid = folder.get("id", "")
            for inst in paged(
                    f"{api}/compute/v1/instances?folderId={fid}",
                    "instances"):
                res = inst.get("resources") or {}
                meta = {
                    "__meta_yandexcloud_instance_id": inst.get("id", ""),
                    "__meta_yandexcloud_instance_name":
                        inst.get("name", ""),
                    "__meta_yandexcloud_instance_fqdn":
                        inst.get("fqdn", ""),
                    "__meta_yandexcloud_instance_status":
                        inst.get("status", ""),
                    "__meta_yandexcloud_instance_platform_id":
                        inst.get("platformId", ""),
                    "__meta_yandexcloud_folder_id": fid,
                    "__meta_yandexcloud_instance_resources_cores":
                        str(res.get("cores", "")),
                    "__meta_yandexcloud_instance_resources_core_fraction":
                        str(res.get("coreFraction", "")),
                    "__meta_yandexcloud_instance_resources_memory":
                        str(res.get("memory", "")),
                }
                for k, v in (inst.get("labels") or {}).items():
                    meta["__meta_yandexcloud_instance_label_"
                         f"{_sanitize(k)}"] = str(v)
                addr = ""
                for i, nic in enumerate(
                        inst.get("networkInterfaces") or []):
                    v4 = nic.get("primaryV4Address") or {}
                    priv = v4.get("address", "")
                    if priv:
                        meta[f"__meta_yandexcloud_instance_private_ip_"
                             f"{i}"] = priv
                        addr = addr or priv
                    nat = (v4.get("oneToOneNat") or {}).get("address", "")
                    if nat:
                        meta[f"__meta_yandexcloud_instance_public_ip_"
                             f"{i}"] = nat
                        if cfg.get("prefer_public_ip"):
                            addr = nat
                    for di, rec in enumerate(
                            v4.get("dnsRecords") or []):
                        meta[f"__meta_yandexcloud_instance_private_dns_"
                             f"{di}"] = rec.get("fqdn", "")
                if not addr:
                    addr = inst.get("fqdn", "")
                if addr:
                    out.append((f"{addr}:{dport}", meta))
        return out
    except (OSError, ValueError, KeyError) as e:
        raise DiscoveryError(f"yandexcloud_sd {api}: {e}") from e


# -- kuma (discovery/kuma/) --------------------------------------------------

def kuma_sd(cfg: dict) -> list[tuple[str, dict]]:
    """Kuma service-mesh discovery (lib/promscrape/discovery/kuma): one
    xDS DiscoveryRequest POSTed as JSON to
    {server}/v3/discovery:monitoringassignments (the MADS REST variant;
    an empty version/nonce fetches the full assignment set — the
    stateless pull matching every other provider here)."""
    import urllib.parse as _up
    server = cfg.get("server", "")
    if not server:
        raise DiscoveryError("kuma_sd: missing server")
    if "://" not in server:
        server = "http://" + server
    psu = _up.urlparse(server)
    path = psu.path
    if not path.endswith("/"):
        path += "/"
    url = (f"{psu.scheme}://{psu.netloc}{path}"
           "v3/discovery:monitoringassignments")
    if psu.query:
        url += "?" + psu.query
    body = json.dumps({
        "version_info": "",
        "node": {"id": cfg.get("client_id", "victoriametrics_tpu")},
        "resource_names": [],
        "type_url": "type.googleapis.com/"
                    "kuma.observability.v1.MonitoringAssignment",
        "response_nonce": "",
    }).encode()
    try:
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json",
                                     "Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            dresp = json.loads(resp.read())
        if not isinstance(dresp, dict):
            raise DiscoveryError(
                f"kuma_sd {server}: unexpected response shape "
                f"{type(dresp).__name__}")
        out: list[tuple[str, dict]] = []
        for r in dresp.get("resources") or []:
            for t in r.get("targets") or []:
                meta = {
                    "instance": t.get("name", ""),
                    "__scheme__": t.get("scheme", ""),
                    "__metrics_path__": t.get("metrics_path", ""),
                    "__meta_kuma_dataplane": t.get("name", ""),
                    "__meta_kuma_mesh": r.get("mesh", ""),
                    "__meta_kuma_service": r.get("service", ""),
                }
                for src in (r.get("labels") or {}, t.get("labels") or {}):
                    for k, v in src.items():
                        meta[f"__meta_kuma_label_{_sanitize(k)}"] = str(v)
                meta = {k: v for k, v in meta.items() if v}
                addr = t.get("address", "")
                if addr:
                    out.append((addr, meta))
        return out
    except (OSError, ValueError, KeyError, AttributeError,
            TypeError) as e:
        raise DiscoveryError(f"kuma_sd {server}: {e}") from e


PROVIDERS = {
    "kubernetes_sd_configs": kubernetes_sd,
    "consul_sd_configs": consul_sd,
    "ec2_sd_configs": ec2_sd,
    "http_sd_configs": http_sd,
    "dns_sd_configs": dns_sd,
    "docker_sd_configs": docker_sd,
    "gce_sd_configs": gce_sd,
    "azure_sd_configs": azure_sd,
    "nomad_sd_configs": nomad_sd,
    "dockerswarm_sd_configs": dockerswarm_sd,
    "eureka_sd_configs": eureka_sd,
    "openstack_sd_configs": openstack_sd,
    "digitalocean_sd_configs": digitalocean_sd,
    "consulagent_sd_configs": consulagent_sd,
    "hetzner_sd_configs": hetzner_sd,
    "vultr_sd_configs": vultr_sd,
    "marathon_sd_configs": marathon_sd,
    "puppetdb_sd_configs": puppetdb_sd,
    "ovhcloud_sd_configs": ovhcloud_sd,
    "yandexcloud_sd_configs": yandexcloud_sd,
    "kuma_sd_configs": kuma_sd,
}


def discover_targets(sc: dict, last_good: dict | None = None
                     ) -> list[tuple[str, dict]]:
    """All dynamic-provider targets for one scrape config section. On a
    provider error the provider's previous successful result is reused
    (Prometheus keeps last-known-good targets across SD hiccups); pass a
    persistent `last_good` dict to enable that."""
    import json as _json
    out: list[tuple[str, dict]] = []
    for key, fn in PROVIDERS.items():
        for cfg in sc.get(key, []) or []:
            ck = (key, _json.dumps(cfg, sort_keys=True))
            try:
                got = fn(cfg)
            except DiscoveryError as e:
                logger.errorf("%s; keeping last-known-good targets", e)
                got = (last_good or {}).get(ck, [])
            else:
                if last_good is not None:
                    last_good[ck] = got
            out.extend(got)
    return out
