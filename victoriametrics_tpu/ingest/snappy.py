"""Snappy block codec via the system libsnappy C API (ctypes), with a
pure-Python decoder fallback. Prometheus remote-write bodies are
snappy-block-compressed protobufs (reference lib/protoparser/
promremotewrite handles the same two codecs: snappy and zstd)."""

from __future__ import annotations

import ctypes
import struct

_lib = None
try:
    _lib = ctypes.CDLL("libsnappy.so.1")
    _lib.snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_size_t)]
    _lib.snappy_uncompress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_size_t)]
    _lib.snappy_uncompressed_length.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t)]
    _lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
    _lib.snappy_max_compressed_length.restype = ctypes.c_size_t
except OSError:  # pragma: no cover
    _lib = None


def compress(data: bytes) -> bytes:
    if _lib is not None:
        n = _lib.snappy_max_compressed_length(len(data))
        out = ctypes.create_string_buffer(n)
        out_len = ctypes.c_size_t(n)
        rc = _lib.snappy_compress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise ValueError(f"snappy_compress failed: {rc}")
        return out.raw[:out_len.value]
    return _py_compress(data)


def decompress(data: bytes) -> bytes:
    if _lib is not None:
        n = ctypes.c_size_t(0)
        if _lib.snappy_uncompressed_length(data, len(data), ctypes.byref(n)) != 0:
            raise ValueError("snappy: bad header")
        if n.value > 1 << 31:
            raise ValueError("snappy: unreasonable uncompressed length")
        out = ctypes.create_string_buffer(n.value or 1)
        out_len = ctypes.c_size_t(n.value)
        rc = _lib.snappy_uncompress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise ValueError(f"snappy_uncompress failed: {rc}")
        return out.raw[:out_len.value]
    return _py_decompress(data)


# -- pure-python fallback (spec: github.com/google/snappy format docs) -------

def _py_compress(data: bytes) -> bytes:
    # all-literal encoding: valid snappy, just not compressed
    out = bytearray()
    n = len(data)
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            break
    i = 0
    while i < len(data):
        chunk = data[i:i + 65536]
        ln = len(chunk) - 1
        if ln < 60:
            out.append((ln << 2) | 0)
        else:
            out.append((60 << 2) | 0)
            out.append(ln & 0xFF)
            out.append((ln >> 8) & 0xFF)
            out[-3] = (61 << 2) | 0
        out += chunk
        i += 65536
    return bytes(out)


def _py_decompress(data: bytes) -> bytes:
    # decode uncompressed length varint
    n = 0
    shift = 0
    i = 0
    while True:
        b = data[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while i < len(data):
        tag = data[i]
        i += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                ln = int.from_bytes(data[i:i + extra], "little")
                i += extra
            ln += 1
            out += data[i:i + ln]
            i += ln
        else:
            if kind == 1:
                ln = ((tag >> 2) & 7) + 4
                off = ((tag >> 5) << 8) | data[i]
                i += 1
            elif kind == 2:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[i:i + 2], "little")
                i += 2
            else:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[i:i + 4], "little")
                i += 4
            if off == 0 or off > len(out):
                raise ValueError("snappy: bad copy offset")
            for _ in range(ln):
                out.append(out[-off])
    if len(out) != n:
        raise ValueError("snappy: length mismatch")
    return bytes(out)
