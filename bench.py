"""Benchmark: END-TO-END samples/sec through the real served query path.

Workload modeled on BASELINE.md config 2 (`sum by(instance)(rate(m[5m]))`
range query over high-cardinality counters): ingest 8192 counter series x
1440 samples (6h @ 15s) into a real on-disk Storage (parts, index, codecs),
then serve the full evaluator — index search -> part block decode -> series
assembly -> device tiles -> fused rollup+aggregation.

Headline = STEADY-STATE serving rate for the realistic dashboard loop: the
window advances one step per refresh while live ingest appends new scrapes
between refreshes, and every refresh goes through the SAME cached range
executor the HTTP layer serves (result-cache tail merge over the full
eval stack). Each refresh therefore computes only the uncovered suffix —
fetch, rollup, aggregation — and merges it onto the cached prefix; a
built-in assert proves the served rows equal a cold nocache evaluation
(bit-for-bit on the f64 host path, within the f32 tile bound on device).
Neither backend can serve a pure cache hit: every refresh sees new bounds
AND new data. Cold (nocache first query, incl. jit compile) and ingest
rates are reported inside the metric label.

Backend policy — LOUD, never silent: the accelerator is probed in a
subprocess with a hard deadline (utils/tpu_probe.py) before any in-process
jax init. The probe outcome is printed to stderr and recorded in the JSON
as "backend" ("tpu" / "cpu-device" / "host-only:<reason>"); a
requested-but-absent device engine can no longer masquerade as a device
result (the round-3 artifact failure). Tile dtype follows the engine's
auto rule: f32 rebased tiles on real TPU (f64 is emulated there; error
bounds in tests/test_f32_tiles.py), f64 on CPU-XLA.

Throughput accounting: each refresh logically serves the samples a cold
evaluation of that window would scan (series x fetch-range samples); the
rate divides that by the measured p50 refresh latency.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N,
   "backend": ..., "refresh_p50_ms": N, "refresh_p99_ms": N,
   "refresh_ms": [per-refresh latencies], "cache": {inplace/rebuild/
   merge_seconds/merge_gate_yields}, "flight": {per-leg flight-recorder
   attribution: slow-refresh captures + the slowest one's overlap
   summary}, "cost": {per-refresh CostTracker split: samples/bytes/
   cpu-ms + wall/cpu by phase + wall_accounted_pct >= 90}, "profiler":
   {sample count at VM_PROFILE_HZ — the run is measured with the
   continuous profiler AND cost accounting ON}}
The refresh-latency DISTRIBUTION (p99 + the raw list) is part of the
artifact: the p50-vs-trace variance ROADMAP item 1 tracks is invisible
in a single median.

vs_baseline divides by 1e8 samples/sec — the order of the reference's
single-core block-unpack + rollup scan rate (its netstorage unpack workers
+ rollupConfig.Do; BASELINE.md notes the repo publishes capacity figures,
not absolute scan rates, so this is the documented working assumption).

A querytracer span tree for one steady-state refresh (and the cold query)
is written to bench_trace.json — the where-does-the-time-go artifact.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

N_SERIES = 8192
N_SAMPLES = 1440         # 6h @ 15s
N_INSTANCES = 256
STEP = 60_000
REFRESHES = 6
JITTER_MS = 2_000  # scrape-time jitter; the end0 ceil below depends on it

# per-phase attribution (vm_fetch_phase_seconds_total, storage + eval):
# deltas across a timed region divide the time between the fetch stages
# and the host rollup, so a bench round says WHERE a win/regression lives.
# "assemble_native" is the fused VM_NATIVE_ASSEMBLE kernel (one native
# fetch→decode→clip→float call per part); collect/decode only tick on the
# split fallback path.
PHASES = ("queue_wait", "index_search", "collect", "decode",
          "assemble_native", "assemble", "rollup")
# the write-path twin (vm_ingest_phase_seconds_total): where the live
# steady-state ingest spends its time, per refresh
ING_PHASES = ("resolve", "register", "append")


def _phase_totals() -> dict:
    from victoriametrics_tpu.utils import metrics as metricslib
    return {ph: metricslib.REGISTRY.float_counter(
        f'vm_fetch_phase_seconds_total{{phase="{ph}"}}').get()
        for ph in PHASES}


def _phase_label(d0: dict, d1: dict, n: int) -> str:
    """'qwait=0/idx=2/collect=0/decode=0/native=25/assemble=9/rollup=12ms'."""
    short = {"queue_wait": "qwait", "index_search": "idx",
             "collect": "collect", "decode": "decode",
             "assemble_native": "native", "assemble": "assemble",
             "rollup": "rollup"}
    parts = [f"{short[ph]}={(d1[ph] - d0[ph]) * 1e3 / max(n, 1):.0f}"
             for ph in PHASES]
    return "/".join(parts) + "ms"


def _device_plane_totals() -> dict:
    """Device link/residency counters (models.tile_cache): uploaded /
    downloaded bytes and resident-window hits — the residency win is
    upload_steady << upload_cold in the artifact."""
    from victoriametrics_tpu.models import tile_cache as tclib
    from victoriametrics_tpu.utils import metrics as metricslib
    return {
        "uploaded_bytes": tclib.bytes_uploaded(),
        "downloaded_bytes": tclib.bytes_downloaded(),
        "window_hits": metricslib.REGISTRY.counter(
            "vm_device_window_cache_hits_total").get(),
        "window_compactions": metricslib.REGISTRY.counter(
            "vm_device_window_compactions_total").get(),
    }


def _device_plane_delta(d0: dict) -> dict:
    return {k: v - d0[k] for k, v in _device_plane_totals().items()}


def _cache_merge_totals() -> dict:
    """Cumulative result-cache merge counters (see _cache_merge_delta)."""
    from victoriametrics_tpu.utils import metrics as metricslib
    return {
        "inplace": metricslib.REGISTRY.counter(
            "vm_rollup_cache_inplace_total").get(),
        "rebuild": metricslib.REGISTRY.counter(
            "vm_rollup_cache_rebuild_total").get(),
        "put_reuse": metricslib.REGISTRY.counter(
            "vm_rollup_cache_put_identity_reused_total").get(),
        "merge_seconds": metricslib.REGISTRY.float_counter(
            "vm_rollup_cache_merge_seconds_total").get(),
        "merge_gate_yields": metricslib.REGISTRY.counter(
            "vm_merge_gate_yields_total").get(),
    }


def _cache_merge_delta(c0: dict) -> dict:
    """Result-cache merge handling DURING one backend's steady-state
    loop (acceptance: inplace > 0): deltas against the pre-loop
    snapshot, like the phase labels — absolute reads would fold the
    other backend leg's and warm-up activity into the winner's stats."""
    return {k: round(v - c0[k], 4) for k, v in
            _cache_merge_totals().items()}


def _ingest_phase_totals() -> dict:
    from victoriametrics_tpu.utils import metrics as metricslib
    return {ph: metricslib.ingest_phase(ph).get() for ph in ING_PHASES}


def _ingest_phase_label(d0: dict, d1: dict, n: int) -> str:
    """'resolve=3/register=0/append=1ms' of live ingest per refresh."""
    parts = [f"{ph}={(d1[ph] - d0[ph]) * 1e3 / max(n, 1):.0f}"
             for ph in ING_PHASES]
    return "/".join(parts) + "ms"


def _cost_leg_summary(costs, lat) -> dict:
    """Per-leg cost attribution from the refreshes' CostTrackers (the
    per-query accounting plane, utils/costacc): what one steady refresh
    scans/reads/burns, plus how much of the measured refresh wall time
    the named cost buckets account for (the honesty ratio — anything
    below ~90% means an unnamed phase is eating serving time)."""
    n = max(len(costs), 1)
    wall: dict = {}
    cpu: dict = {}
    samples = bytes_read = dev_up = dev_down = rpc = 0
    for c in costs:
        samples += c.samples
        bytes_read += c.part_bytes
        dev_up += c.device_up
        dev_down += c.device_down
        rpc += c.rpc_bytes
        for k, v in c.wall_ms.items():
            wall[k] = wall.get(k, 0.0) + v
        for k, v in c.cpu_ms.items():
            cpu[k] = cpu.get(k, 0.0) + v
    refresh_wall_ms = sum(lat) * 1e3
    return {
        "samples_scanned_per_refresh": samples // n,
        "bytes_read_per_refresh": bytes_read // n,
        "cpu_ms_per_refresh": round(sum(cpu.values()) / n, 2),
        "device_bytes_per_refresh": (dev_up + dev_down) // n,
        "rpc_bytes_per_refresh": rpc // n,
        "wall_ms_by_phase": {k: round(v / n, 2)
                             for k, v in sorted(wall.items())},
        "cpu_ms_by_phase": {k: round(v / n, 2)
                            for k, v in sorted(cpu.items())},
        "wall_accounted_pct": round(
            sum(wall.values()) / refresh_wall_ms * 100, 1)
        if refresh_wall_ms > 0 else 0.0,
    }


def _leg_flight_summary(id0: int, threshold_ms: float) -> dict:
    """Flight-recorder outcome of one backend leg: how many slow-refresh
    captures fired past `id0`, and the attribution summary of the
    slowest one.  When the whole loop stayed under the threshold, an
    on-demand capture of the still-live ring window stands in — the
    artifact always ships a timeline (ROADMAP item 1's open question is
    exactly "what overlapped the slow refresh", and the answer must not
    depend on the slow refresh happening to recur)."""
    from victoriametrics_tpu.utils import flightrec
    if not flightrec.enabled():
        return {"enabled": False}
    # fired counts every capture of the leg; the retention ring
    # (VM_FLIGHT_CAPTURES) bounds how many are still inspectable, so
    # the slowest RETAINED capture may not be the slowest fired —
    # "evicted" makes that truncation visible in the artifact
    fired = flightrec.RECORDER.total() - id0
    caps = [c for c in flightrec.RECORDER.list() if c["id"] > id0]
    source = "slow_refresh"
    if not caps:
        cap = flightrec.RECORDER.capture("bench_on_demand")
        caps = [c for c in flightrec.RECORDER.list()
                if c["id"] == cap["id"]]
        source = "on_demand"
    slowest = max(caps,
                  key=lambda c: (c.get("refresh_ms", 0.0), c["id"]))
    out = {"enabled": True, "threshold_ms": round(threshold_ms, 1),
           "captures": fired, "source": source,
           "capture_id": slowest["id"],
           "summary": slowest.get("summary", {})}
    if fired > len(caps):
        out["evicted"] = fired - len(caps)
    if "refresh_ms" in slowest:
        out["refresh_ms"] = slowest["refresh_ms"]
    return out


def _finish_provision(probe_handle, probe_timeout: float):
    """Resolve the in-flight accelerator probe and build the device
    engine. Returns (engine, backend_label, probe_info). NEVER silent:
    every degradation prints its reason to stderr, and a failed probe's
    outcome (including the hung subprocess's last faulthandler stack)
    lands in probe_info for the JSON artifact."""
    res = probe_handle.result()
    probe_info = {"timeout_s": probe_timeout,
                  "elapsed_s": round(res.elapsed_s, 1)}
    if res.error is not None:
        probe_info["error"] = res.error
        if res.stack:
            probe_info["last_stack"] = res.stack
        print(f"bench: DEVICE BACKEND UNAVAILABLE -> host-only path: "
              f"{res.error}", file=sys.stderr)
        if res.stack:
            print(f"bench: hung probe's last stack:\n{res.stack}",
                  file=sys.stderr)
        return None, f"host-only:{res.error.split(':')[0]}", probe_info
    probe_info["platform"] = res.platform
    probe_info["n_devices"] = res.n
    print(f"bench: accelerator probe OK: {res.n} {res.platform} device(s) "
          f"in {res.elapsed_s:.1f}s", file=sys.stderr)
    try:
        import jax
        from victoriametrics_tpu.query.tpu_engine import is_tpu_platform
        if not is_tpu_platform(res.platform):
            # Pin the in-process backend to what the probe proved healthy:
            # the axon TPU plugin overrides JAX_PLATFORMS at import time,
            # so without this the main process could still hang in the
            # plugin init the probe just rejected. CPU-XLA f64 tiles also
            # need x64 (config.update works after import; env var would
            # be too late — jax is already loaded by the ingest imports
            # that ran while the probe was in flight).
            jax.config.update("jax_platforms", res.platform)
            jax.config.update("jax_enable_x64", True)
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        engine = TPUEngine()
        label = ("tpu" if is_tpu_platform(res.platform) else "cpu-device") \
            + f"-{np.dtype(engine.value_dtype).name}"
        return engine, label, probe_info
    except Exception as e:  # loud: the engine must not vanish silently
        print(f"bench: DEVICE ENGINE INIT FAILED -> host-only path: {e!r}",
              file=sys.stderr)
        probe_info["engine_error"] = repr(e)
        return None, f"host-only:{type(e).__name__}", probe_info


def _assert_rows_equal(a, b, rtol: float = 0.0) -> None:
    """Served (cached) rows must match a cold eval: bit-identical on the
    f64 host path (rtol=0, equal_nan covers NaN==NaN), within the f32
    tile error bound on the device path (see tests/test_f32_tiles.py —
    prefix and suffix tiles round independently). f64 DEVICE legs compare
    at rtol=1e-12: XLA compiles the suffix grid and the full-window grid
    separately and may order the group-sum reductions differently
    (measured ~2e-15 relative), so exact bit equality is only guaranteed
    on the host path; structural divergence still fails loudly."""
    da = {ts.metric_name.marshal(): ts.values for ts in a}
    db = {ts.metric_name.marshal(): ts.values for ts in b}
    assert set(da) == set(db), (len(da), len(db))
    for k, va in da.items():
        vb = db[k]
        if rtol == 0.0:
            ok = np.array_equal(va, vb, equal_nan=True)
        else:
            fa, fb = np.isnan(va), np.isnan(vb)
            m = ~fa
            ok = bool((fa == fb).all()) and bool(
                np.allclose(va[m], vb[m], rtol=rtol, equal_nan=True))
        assert ok, "served result diverged from cold evaluation"


_SELF_METRIC_FAMS = (
    "vm_selfscrape_scrapes_total", "vm_selfscrape_rows_total",
    "vm_selfscrape_errors_total", "vm_slo_evals_total",
    "vm_slo_eval_rounds_total", "vm_matstream_evals_total",
    "vm_gc_collections_total", "vm_log_messages_total",
)


def _self_metrics_totals() -> dict:
    """Key vm_* counters from the process registry, summed per family —
    the observability plane's own view of a bench leg."""
    from victoriametrics_tpu.utils import metrics as metricslib
    out: dict = {}
    for name, val in metricslib.REGISTRY.collect_values(
            include_process=False):
        fam = metricslib.split_name(name)[0]
        if fam in _SELF_METRIC_FAMS:
            out[fam] = out.get(fam, 0.0) + val
    return out


def _self_metrics_delta(t0: dict, t1: dict) -> dict:
    return {k: round(t1.get(k, 0.0) - t0.get(k, 0.0), 3)
            for k in sorted(set(t0) | set(t1))}


def main() -> None:
    # Launch the accelerator probe FIRST and let it run concurrently with
    # ingest (~100s): a slow-but-alive TPU backend is not discarded, and a
    # hung one costs no extra wall-clock until ingest is done.
    from victoriametrics_tpu.utils.tpu_probe import start_probe
    # 450s default: the probe overlaps ingest and the driver gives the
    # whole bench ~580s — ingest+serve take <120s now, so 450s is the
    # largest budget that still leaves the artifact guaranteed to
    # exist (the serving apps keep the full 600s default)
    probe_timeout = float(os.environ.get("VM_TPU_PROBE_TIMEOUT_S", "450"))
    probe_handle = start_probe(probe_timeout)

    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.types import EvalConfig
    from victoriametrics_tpu.storage.storage import Storage
    from victoriametrics_tpu.utils.querytracer import Tracer

    # the continuous profiler runs for the WHOLE bench (acceptance: the
    # headline is measured with profiler + cost accounting ON)
    from victoriametrics_tpu.utils import profiler
    profiler.ensure_started()

    tmp = tempfile.mkdtemp(prefix="vmtpu-bench-")
    # anchor to wall clock so steady-state ingest is "live" data (the
    # result-cache backfill reset and retention behave as in production)
    now_ms = int(time.time() * 1000)
    t_start = (now_ms - (N_SAMPLES - 1) * 15_000) // STEP * STEP
    rng = np.random.default_rng(0)
    scraper = None
    try:
        s = Storage(tmp)

        # the self-monitoring plane runs for the WHOLE bench (acceptance:
        # the headline is measured with self-scrape + SLO engine ON): the
        # process's own registry lands in the bench storage as real
        # series, and burn-rate evals ride each scrape tick
        from victoriametrics_tpu.httpapi.prometheus_api import \
            PrometheusAPI as _PlaneAPI
        from victoriametrics_tpu.utils import selfscrape as _selfscrape
        from victoriametrics_tpu.utils.selfscrape import SelfScraper
        plane_api = _PlaneAPI(s)
        plane_engine = plane_api.init_sloplane()
        # VM_SELF_SCRAPE_INTERVAL=0 means OFF (the documented flag-table
        # semantics) — the plane-overhead A/B leg, NOT a 20Hz loop
        # (SelfScraper clamps interval_s to 0.05s, so passing 0 through
        # would measure the opposite of "plane disabled")
        scrape_interval = _selfscrape.configured_interval("5")
        if scrape_interval > 0:
            scraper = SelfScraper(
                s.add_rows, instance="bench", interval_s=scrape_interval,
                extra=plane_api.app_metrics,
                on_tick=lambda now_ms: plane_engine.maybe_eval(now_ms))
            scraper.start()
        else:
            print("bench: self-monitoring plane OFF "
                  "(VM_SELF_SCRAPE_INTERVAL=0) — plane-overhead A/B leg",
                  file=sys.stderr)

        # -- ingest: realistic jittered counters through the real write
        # path — the COLUMNAR pipeline HTTP ingest uses (raw text series
        # keys resolved by the native key map, no per-row Python)
        from victoriametrics_tpu import native
        base = np.arange(N_SAMPLES, dtype=np.int64) * 15_000 + t_start
        keys = [(f'http_requests_total{{idx="{i}",'
                 f'instance="host-{i % N_INSTANCES}",'
                 f'job="job-{i % 17}"}}').encode()
                for i in range(N_SERIES)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, N_SERIES)
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
        last_val = np.zeros(N_SERIES)

        def columnar_rows(ts2, vals2):
            """(S, K) timestamp/value arrays -> one ColumnarRows batch."""
            k = ts2.shape[1]
            return native.ColumnarRows(
                keybuf, np.repeat(koffs, k), np.repeat(klens, k),
                ts2.reshape(-1).astype(np.int64), vals2.reshape(-1))

        t0 = time.perf_counter()
        chunk = 256  # series per batch: ~368k-row columnar batches
        for i0 in range(0, N_SERIES, chunk):
            i1 = min(i0 + chunk, N_SERIES)
            ts2 = np.sort(base[None, :] +
                          rng.integers(-JITTER_MS, JITTER_MS + 1, (i1 - i0, N_SAMPLES)),
                          axis=1)
            vals2 = np.cumsum(rng.integers(0, 50, (i1 - i0, N_SAMPLES)),
                              axis=1).astype(np.float64)
            last_val[i0:i1] = vals2[:, -1]
            cr = native.ColumnarRows(
                keybuf, np.repeat(koffs[i0:i1], N_SAMPLES),
                np.repeat(klens[i0:i1], N_SAMPLES),
                ts2.reshape(-1), vals2.reshape(-1))
            s.add_rows_columnar(cr)
        ingest_dt = time.perf_counter() - t0
        ingest_rate = N_SERIES * N_SAMPLES / ingest_dt
        s.force_flush()
        s.force_merge()

        # resolve the probe that ran during ingest; build the device
        # engine ONLY if the probe proved the backend healthy
        tpu, backend_label, probe_info = _finish_provision(probe_handle,
                                                           probe_timeout)
        q = "sum by (instance)(rate(http_requests_total[5m]))"
        duration = (N_SAMPLES - 1) * 15_000 - 300_000
        # logical scan size of one window (series x fetch-range samples)
        samples = N_SERIES * ((duration + 600_000) // 15_000)

        def ingest_fresh(end_ms: int) -> None:
            """4 new scrapes per series in (end_ms - STEP, end_ms]."""
            incr = rng.integers(0, 50, (N_SERIES, 4))
            vals2 = last_val[:, None] + np.cumsum(incr, axis=1)
            last_val[:] = vals2[:, -1]
            ts2 = (end_ms - STEP +
                   (np.arange(4, dtype=np.int64) + 1)[None, :] * 15_000 +
                   rng.integers(-JITTER_MS, JITTER_MS + 1, (N_SERIES, 4)))
            ts2.sort(axis=1)
            s.add_rows_columnar(columnar_rows(ts2, vals2.astype(np.float64)))

        results = {}
        traces = {}
        flights = {}
        device_plane = None
        # an operator-set VM_SLOW_REFRESH_MS wins over the per-leg
        # calibration below (the env var is rewritten per leg otherwise)
        try:
            user_slow_refresh_ms = float(
                os.environ["VM_SLOW_REFRESH_MS"])
        except (KeyError, ValueError):
            user_slow_refresh_ms = None
        # first refresh window must start BEYOND every initial sample
        # (incl. jitter): rounding down would interleave the first fresh
        # scrapes with the initial batch's tail, fabricating counter
        # decreases that are resets to neither backend's credit
        end0 = t_start + -(-((N_SAMPLES - 1) * 15_000 + JITTER_MS)
                           // STEP) * STEP
        from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
        for backend, engine in (("device", tpu), ("host-batch", None)):
            if backend == "device" and engine is None:
                continue
            # the result cache is process-global and NOT backend-keyed:
            # reset between legs so the host leg can't serve (or be
            # timed against) device-seeded entries
            from victoriametrics_tpu.query.rollup_result_cache import \
                GLOBAL as _rcache
            _rcache.reset()
            # steady-state refreshes go through the SAME cached executor
            # the HTTP layer serves (result-cache tail merge + full eval
            # stack) — this is the path a dashboard actually pays
            api = PrometheusAPI(s, engine)
            selfm0 = _self_metrics_totals()
            start = end0 - duration
            kw = dict(step=STEP, storage=s, tpu=engine)
            # cold: full fetch+decode+compute, result caches off, jit
            # compile included
            dev_cold0 = _device_plane_totals()
            tr = Tracer(True)
            t0 = time.perf_counter()
            rows = exec_query(EvalConfig(start=start, end=end0, **kw,
                                         disable_cache=True, tracer=tr),
                              q)
            cold_dt = time.perf_counter() - t0
            traces[backend + "-cold"] = tr.to_dict()
            # cold upload = the one full-window ship, measured BEFORE the
            # warm-up/preflight evals (tile-cache reuse makes those free,
            # but the accounting must not depend on that)
            dev_cold = _device_plane_delta(dev_cold0)
            assert len(rows) == N_INSTANCES, len(rows)
            # warm-up with caches on: builds the rolling tile / seeds the
            # result + eval caches
            api._exec_range_cached(EvalConfig(start=start, end=end0, **kw),
                                   q, end0)
            # preflight: two uncounted steady refreshes calibrate the
            # slow-refresh flight trigger for THIS host/leg — refreshes
            # >1.25x the calibrated floor freeze a cross-thread capture
            # mid-loop (an operator-set VM_SLOW_REFRESH_MS wins)
            from victoriametrics_tpu.utils import flightrec
            end = end0
            pre = []
            for _ in range(2):
                end += STEP
                ingest_fresh(end)
                t0 = time.perf_counter()
                api._exec_range_cached(
                    EvalConfig(start=end - duration, end=end, **kw), q, end)
                pre.append(time.perf_counter() - t0)
            if user_slow_refresh_ms is None:
                thresh_ms = max(min(pre) * 1.25e3, 25.0)
                os.environ["VM_SLOW_REFRESH_MS"] = str(thresh_ms)
            else:
                thresh_ms = user_slow_refresh_ms
            flight_id0 = flightrec.RECORDER.total()
            # steady-state: live ingest + window advance per refresh
            dev0 = _device_plane_totals()
            lat = []
            ph0 = _phase_totals()
            ing0 = _ingest_phase_totals()
            c0 = _cache_merge_totals()
            leg_costs = []
            for _ in range(REFRESHES):
                end += STEP
                start = end - duration
                ingest_fresh(end)
                tr = Tracer(True)
                ec_r = EvalConfig(start=start, end=end, **kw, tracer=tr)
                t0 = time.perf_counter()
                rows = api._exec_range_cached(ec_r, q, end)
                lat.append(time.perf_counter() - t0)
                leg_costs.append(ec_r.cost)
                assert len(rows) == N_INSTANCES, len(rows)
            traces[backend + "-steady"] = tr.to_dict()
            # snapshot the per-refresh phase split BEFORE the honesty
            # check: its cold full-window eval would otherwise pollute
            # the steady-state attribution
            phase_lbl = _phase_label(ph0, _phase_totals(), REFRESHES)
            ing_lbl = _ingest_phase_label(ing0, _ingest_phase_totals(),
                                          REFRESHES)
            cache_stats = _cache_merge_delta(c0)
            # device-plane deltas too: the honesty check's cold eval
            # would otherwise count as steady-state upload traffic
            dev_steady = _device_plane_delta(dev0)
            # flight attribution BEFORE the honesty check: its cold eval
            # would flood the rings with full-window fetch spans
            flights[backend] = _leg_flight_summary(flight_id0, thresh_ms)
            cost_summary = _cost_leg_summary(leg_costs, lat)
            self_delta = _self_metrics_delta(selfm0,
                                             _self_metrics_totals())
            # honesty check: the served refresh must equal a cold
            # (nocache) evaluation of the same window — bit-for-bit on
            # the f64 host path, within the f32 tile bound on device
            cold_rows = exec_query(EvalConfig(start=start, end=end, **kw,
                                              disable_cache=True), q)
            f32 = engine is not None and engine.is_f32()
            rtol = 0.0 if engine is None else (1e-4 if f32 else 1e-12)
            _assert_rows_equal(rows, cold_rows, rtol=rtol)
            results[backend] = (float(np.median(lat)), cold_dt,
                                phase_lbl, ing_lbl, list(lat), cache_stats,
                                cost_summary, self_delta)
            if backend == "device":
                # the residency story in the artifact: a steady refresh
                # must ship tail columns, not the window (ISSUE 12)
                device_plane = {
                    "cold_uploaded_bytes": dev_cold["uploaded_bytes"],
                    "steady_uploaded_bytes": dev_steady["uploaded_bytes"],
                    "steady_uploaded_per_refresh":
                        dev_steady["uploaded_bytes"] // max(REFRESHES, 1),
                    "steady_downloaded_bytes":
                        dev_steady["downloaded_bytes"],
                    "window_hits": dev_steady["window_hits"],
                    "window_compactions": dev_steady["window_compactions"],
                    "upload_ratio": round(
                        dev_steady["uploaded_bytes"] / max(REFRESHES, 1) /
                        max(dev_cold["uploaded_bytes"], 1), 5),
                }
            end0 = end  # the next backend continues on the grown storage

        backend, (warm_dt, cold_dt, phase_lbl, ing_lbl, lat,
                  cache_stats, cost_summary, _) = min(
            results.items(), key=lambda kv: kv[1][0])
        rate = samples / warm_dt
        # the refresh-latency DISTRIBUTION, not just p50: ROADMAP item 1's
        # variance hunt needs p99 and the raw list in the artifact
        p99_dt = float(np.percentile(lat, 99))
        from victoriametrics_tpu import native as native_mod
        from victoriametrics_tpu.utils import workpool
        n_workers = workpool.POOL.workers()
        assemble_mode = ("native" if native_mod.assemble_enabled()
                         else "python")
        with open("bench_trace.json", "w") as f:
            json.dump(traces, f, indent=1)
        baseline = 1e8  # single-core reference scan rate (see docstring)
        # honest backend accounting: the headline backend, with the probed
        # device label ("tpu-float32" etc.) or the probe-failure reason
        backend_field = (backend_label if backend == "device"
                         else f"host-batch ({backend_label})")
        print(json.dumps({
            "metric": (f"steady-state rolling-window sum by(rate) serving, "
                       f"{N_SERIES}x{N_SAMPLES} counters, live ingest, via "
                       f"storage+index+decode+{backend} (cold "
                       f"{samples / cold_dt / 1e6:.0f}M/s, refresh p50 "
                       f"{warm_dt * 1e3:.0f}ms p99 {p99_dt * 1e3:.0f}ms, "
                       f"ingest "
                       f"{ingest_rate / 1e3:.0f}k rows/s, "
                       f"{n_workers} fetch workers, "
                       f"{workpool.configured_shards()} ingest shards, "
                       f"assemble={assemble_mode}, "
                       f"phases {phase_lbl}, "
                       f"ingest phases {ing_lbl})"),
            "value": round(rate),
            "unit": "samples/sec",
            "vs_baseline": round(rate / baseline, 2),
            "backend": backend_field,
            "refresh_p50_ms": round(warm_dt * 1e3, 2),
            "refresh_p99_ms": round(p99_dt * 1e3, 2),
            "refresh_ms": [round(x * 1e3, 2) for x in lat],
            "cache": cache_stats,
            # per-refresh cost attribution from the CostTracker plane
            # (profiler + accounting were ON for the whole run)
            "cost": cost_summary,
            "profiler": {
                "samples": profiler.PROFILER.snapshot()["samples"],
                "hz": profiler.configured_hz(),
            },
            # per-leg cold/steady timings: the device leg's numbers stay
            # visible even when the host leg wins the headline
            "legs": {b: {"refresh_p50_ms": round(r[0] * 1e3, 2),
                         "cold_s": round(r[1], 2),
                         "cost": r[6],
                         # the observability plane's own view of the leg
                         "self_metrics": r[7]}
                     for b, r in results.items()},
            "device_plane": device_plane,
            "flight": flights,
            "probe": probe_info,
            # end-of-run verdict from the self-monitoring plane (one
            # final scrape + eval round so it reflects the full run)
            "self_monitoring": _bench_health(scraper, plane_api,
                                             plane_engine, s),
        }))
    finally:
        try:
            # a hung probe child must not outlive the bench holding the
            # device (no-op once the probe was resolved)
            probe_handle.cancel()
        except Exception:
            pass
        try:
            if scraper is not None:
                # before s.close(): a late scrape must not write into a
                # closed storage
                scraper.stop()
        except Exception:
            pass
        try:
            s.close()
        except Exception:
            pass
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_health(scraper, plane_api, plane_engine, storage) -> dict:
    """One final scrape + eval round, then the health verdict — the
    artifact carries the plane's own view of the whole run."""
    from victoriametrics_tpu.query import sloplane
    if scraper is None:
        return {"disabled": "VM_SELF_SCRAPE_INTERVAL=0 (plane-overhead "
                            "A/B leg)"}
    try:
        scraper.scrape_once()
        plane_engine.maybe_eval(force=True)
        h = sloplane.local_health(storage=storage, engine=plane_engine,
                                  role="bench")
        return {
            "interval_s": scraper.interval_s,
            "scrapes": int(_self_metrics_totals().get(
                "vm_selfscrape_scrapes_total", 0)),
            "slo_eval_rounds": plane_engine.eval_rounds,
            "slo_exprs_per_round": plane_engine.exprs_last_round,
            "verdict": h["verdict"],
            "reasons": h["reasons"],
            "firing": [name for name, _ in plane_engine.firing()],
        }
    except Exception as e:  # noqa: BLE001 — artifact must still ship
        return {"error": str(e)}


FLEET_PANELS = (
    "sum by (instance)(rate(http_requests_total[5m]))",
    "sum by (job)(rate(http_requests_total[5m]))",
    "max by (instance)(rate(http_requests_total[5m]))",
    "count by (job)(rate(http_requests_total[5m]))",
)
FLEET_SUBS = 10          # subscribers PER PANEL (dashboards watching it)
FLEET_INTERVALS = 6


def fleet_main() -> None:
    """``--scenario=fleet``: N subscribers x M shared-selector panels
    served through the materialized-stream plane (query/matstream) —
    the first entry of ROADMAP item 5's bench matrix and ISSUE 14's
    acceptance artifact (BENCH_r11).

    Ingest the dashboard scenario's store (8192 counters x 1440
    samples, columnar write path), then:

    - FLAT-SCAN PROOF: per-interval ``samples_scanned`` with 1 vs
      ``FLEET_SUBS`` subscribers per panel — storage reads per interval
      must be independent of subscriber count (the tier-1 guard's
      number, measured at bench scale);
    - THROUGHPUT: ``FLEET_INTERVALS`` live-ingest intervals serving
      ``FLEET_SUBS x len(FLEET_PANELS)`` subscriptions; aggregate rate
      counts the window every SUBSCRIBER's dashboard logically renders
      per interval (the fleet accounting: N dashboards served, one
      evaluation each per distinct expression) over the measured
      advance+fan-out wall time;
    - POLL BASELINE: the same interval served by one
      ``_exec_range_cached`` poll per subscription (the PR-7 sharing
      story without push) — the artifact reports both, so the push
      win is not conflated with the ring cache's;
    - ORACLE: each panel's reassembled client state equals a cold
      nocache evaluation, bit for bit.

    Host-only by design (the acceptance target names host-only
    aggregate throughput); profiler + cost accounting stay ON."""
    from victoriametrics_tpu import native
    from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
    from victoriametrics_tpu.query import rollup_result_cache as rrc
    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.matstream import StreamClient
    from victoriametrics_tpu.query.types import EvalConfig
    from victoriametrics_tpu.storage.storage import Storage
    from victoriametrics_tpu.utils import profiler

    profiler.ensure_started()
    tmp = tempfile.mkdtemp(prefix="vmtpu-fleet-")
    now_ms = int(time.time() * 1000)
    t_start = (now_ms - (N_SAMPLES - 1) * 15_000) // STEP * STEP
    rng = np.random.default_rng(0)
    try:
        s = Storage(tmp)
        base = np.arange(N_SAMPLES, dtype=np.int64) * 15_000 + t_start
        keys = [(f'http_requests_total{{idx="{i}",'
                 f'instance="host-{i % N_INSTANCES}",'
                 f'job="job-{i % 17}"}}').encode()
                for i in range(N_SERIES)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, N_SERIES)
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
        last_val = np.zeros(N_SERIES)
        t0 = time.perf_counter()
        chunk = 256
        for i0 in range(0, N_SERIES, chunk):
            i1 = min(i0 + chunk, N_SERIES)
            ts2 = np.sort(base[None, :] + rng.integers(
                -JITTER_MS, JITTER_MS + 1, (i1 - i0, N_SAMPLES)), axis=1)
            vals2 = np.cumsum(rng.integers(0, 50, (i1 - i0, N_SAMPLES)),
                              axis=1).astype(np.float64)
            last_val[i0:i1] = vals2[:, -1]
            s.add_rows_columnar(native.ColumnarRows(
                keybuf, np.repeat(koffs[i0:i1], N_SAMPLES),
                np.repeat(klens[i0:i1], N_SAMPLES),
                ts2.reshape(-1), vals2.reshape(-1)))
        ingest_rate = N_SERIES * N_SAMPLES / (time.perf_counter() - t0)
        s.force_flush()
        s.force_merge()

        # step-aligned: subscribe() rounds the window up to a step
        # multiple, and the end-of-run oracle must evaluate the exact
        # grid the stream serves
        duration = ((N_SAMPLES - 1) * 15_000 - 300_000) // STEP * STEP
        window_samples = N_SERIES * ((duration + 600_000) // 15_000)
        end = t_start + -(-((N_SAMPLES - 1) * 15_000 + JITTER_MS)
                          // STEP) * STEP

        def ingest_fresh(end_ms: int) -> None:
            incr = rng.integers(0, 50, (N_SERIES, 4))
            vals2 = last_val[:, None] + np.cumsum(incr, axis=1)
            last_val[:] = vals2[:, -1]
            ts2 = (end_ms - STEP +
                   (np.arange(4, dtype=np.int64) + 1)[None, :] * 15_000 +
                   rng.integers(-JITTER_MS, JITTER_MS + 1, (N_SERIES, 4)))
            ts2.sort(axis=1)
            s.add_rows_columnar(native.ColumnarRows(
                keybuf, np.repeat(koffs, 4), np.repeat(klens, 4),
                ts2.reshape(-1),
                vals2.reshape(-1).astype(np.float64)))

        rrc.GLOBAL.reset()
        api = PrometheusAPI(s)

        def drain(subs_by_panel, now):
            """Every subscriber consumes frames until its reassembled
            window reaches the current interval (no-op for subscribers
            already there)."""
            target = (now // STEP) * STEP
            for subs in subs_by_panel:
                for sub, cli in subs:
                    while not (cli.window and cli.window[1] >= target):
                        f = sub.next_frame(timeout_s=5.0, now_ms=now)
                        if f is None:
                            raise RuntimeError("subscriber starved")
                        cli.apply(f)

        def new_subs(n_per_panel):
            return [[(api.matstreams.subscribe(q, STEP, duration),
                      StreamClient()) for _ in range(n_per_panel)]
                    for q in FLEET_PANELS]

        # ---- flat-scan proof: 1 subscriber per panel ----
        subs = new_subs(1)
        drain(subs, end)           # cold: one eval per panel
        streams = [subs[p][0][0].stream for p in range(len(FLEET_PANELS))]
        samples_1sub = []
        for r in range(2):
            end += STEP
            ingest_fresh(end)
            api.matstreams.advance_due(end)
            drain(subs, end)
            samples_1sub.append(sum(st.last_samples_scanned
                                    for st in streams))
        # fan out to FLEET_SUBS per panel (cold replays, no eval)
        evals0 = sum(st.evals for st in streams)
        for p, q in enumerate(FLEET_PANELS):
            subs[p].extend(
                (api.matstreams.subscribe(q, STEP, duration),
                 StreamClient()) for _ in range(FLEET_SUBS - 1))
        drain(subs, end)
        assert sum(st.evals for st in streams) == evals0, \
            "cold subscribes re-evaluated"
        samples_nsub = []
        for r in range(2):
            end += STEP
            ingest_fresh(end)
            api.matstreams.advance_due(end)
            drain(subs, end)
            samples_nsub.append(sum(st.last_samples_scanned
                                    for st in streams))

        # ---- throughput: FLEET_INTERVALS pushed intervals ----
        n_subscriptions = FLEET_SUBS * len(FLEET_PANELS)
        push_wall = []
        interval_samples = []
        for r in range(FLEET_INTERVALS):
            end += STEP
            ingest_fresh(end)
            t0 = time.perf_counter()
            api.matstreams.advance_due(end)
            drain(subs, end)
            push_wall.append(time.perf_counter() - t0)
            interval_samples.append(sum(st.last_samples_scanned
                                        for st in streams))
        # ---- poll baseline: the same interval, one cached poll per
        # subscription — canonical text, so the polls share the
        # STREAMS' warm ring entries (the strongest PR-7 baseline:
        # suffix merge once per panel, then pure full hits) ----
        canon = [api.matstreams.canonical(q) for q in FLEET_PANELS]
        poll_wall = []
        for r in range(3):
            end += STEP
            ingest_fresh(end)
            t0 = time.perf_counter()
            for q in canon:
                for _ in range(FLEET_SUBS):
                    api._exec_range_cached(
                        EvalConfig(start=end - duration, end=end,
                                   step=STEP, storage=s), q, end)
            if r > 0:  # first interval warms the poll path's entries
                poll_wall.append(time.perf_counter() - t0)

        # ---- oracle: every panel's pushed state == cold eval ----
        # (polls above advanced the shared ring entries past the last
        # pushed frame, so push one final interval first)
        end += STEP
        ingest_fresh(end)
        api.matstreams.advance_due(end)
        drain(subs, end)
        import math as _math
        for p, q in enumerate(FLEET_PANELS):
            ec = EvalConfig(start=end - duration, end=end, step=STEP,
                            storage=s, disable_cache=True)
            cold = exec_query(ec, q)
            grid = ec.timestamps() / 1e3
            from victoriametrics_tpu.query.format_value import fmt_value
            want = []
            for rr in cold:
                vals = [[float(t), fmt_value(v)]
                        for t, v in zip(grid, rr.values)
                        if not _math.isnan(v)]
                if vals:
                    want.append({"metric": rr.metric_name.to_dict(),
                                 "values": vals})
            want.sort(key=lambda e: json.dumps(e["metric"],
                                               sort_keys=True))
            for sub, cli in subs[p]:
                assert cli.result() == want, \
                    f"panel {p} pushed state diverged from cold eval"

        usage = api.matstreams.usage_rows()
        p50_push = float(np.median(push_wall))
        p50_poll = float(np.median(poll_wall))
        agg_rate = n_subscriptions * window_samples / p50_push
        baseline = 1e8
        med_1 = int(np.median(samples_1sub))
        med_n = int(np.median(samples_nsub))
        for subs_p in subs:
            for sub, _ in subs_p:
                sub.close()
        print(json.dumps({
            "metric": (
                f"fleet subscription push: {n_subscriptions} "
                f"subscriptions ({FLEET_SUBS} dashboards x "
                f"{len(FLEET_PANELS)} shared-selector panels), "
                f"{N_SERIES}x{N_SAMPLES} counters, live ingest, "
                f"served via materialized streams (one eval per "
                f"distinct expression per interval; aggregate rate "
                f"counts each subscriber's rendered window; ingest "
                f"{ingest_rate / 1e3:.0f}k rows/s; poll-loop baseline "
                f"= {FLEET_SUBS} cached query_range polls per panel)"),
            "value": round(agg_rate),
            "unit": "samples/sec",
            "vs_baseline": round(agg_rate / baseline, 2),
            "backend": "host-batch",
            "scenario": "fleet",
            "subscribers_per_panel": FLEET_SUBS,
            "panels": len(FLEET_PANELS),
            "streams": api.matstreams.stream_count(),
            "push_interval_ms": [round(x * 1e3, 2) for x in push_wall],
            "push_interval_p50_ms": round(p50_push * 1e3, 2),
            "poll_interval_ms": [round(x * 1e3, 2) for x in poll_wall],
            "poll_interval_p50_ms": round(p50_poll * 1e3, 2),
            "push_vs_poll_speedup": round(p50_poll / p50_push, 2),
            "storage_reads_flat": {
                "samples_per_interval_1sub": med_1,
                f"samples_per_interval_{FLEET_SUBS}sub": med_n,
                "flat": bool(med_n <= med_1 * 1.2),
            },
            "samples_scanned_per_interval": interval_samples,
            "per_stream_usage": usage,
            "profiler": {
                "samples": profiler.PROFILER.snapshot()["samples"],
                "hz": profiler.configured_hz(),
            },
        }))
        assert med_n <= med_1 * 1.2, (
            "storage reads per interval grew with subscribers")
    finally:
        try:
            s.close()
        except Exception:
            pass
        shutil.rmtree(tmp, ignore_errors=True)


FLEETD_SERIES = 4096       # 64 instances x 64 jobs, every pair distinct
FLEETD_INSTANCES = 64
FLEETD_JOBS = 64
FLEETD_SAMPLES = 240       # 1h @ 15s
FLEETD_SCRAPE = 15_000
FLEETD_DUR = 20 * STEP     # rendered window per subscription
FLEETD_WARM = 2            # adoption intervals before measurement starts
FLEETD_PANELS = (
    "sum by (instance)(rate(http_requests_total[5m]))",
    "sum by (job)(rate(http_requests_total[5m]))",
    "max by (instance)(rate(http_requests_total[5m]))",
    "count by (job)(rate(http_requests_total[5m]))",
)


def fleet_device_main() -> None:
    """``--scenario=fleet --device``: the MULTICHIP_r07 acceptance leg
    (ISSUE 19 / ROADMAP item 3) — fleet-batched device serving on the
    virtual 8-device mesh.

    ``FLEET_SUBS x len(FLEETD_PANELS)`` = 40 subscriptions over a corpus
    shaped so every panel lands in ONE fleet bucket (4096 counters =
    64 instances x 64 jobs, so ``by (instance)`` and ``by (job)`` both
    reduce to G=64 and share the G rung; same selector -> same S=4096
    rung; same duration/step -> same T rung).  The run then proves, per
    measured interval: exactly ONE fused mesh launch serves all four
    member streams, zero backend recompiles (<= 2 XLA compiles per
    bucket over the whole run), the rows-share cost split of the shared
    launch sums to the launch wall across the usage rows, and the
    served windows match BOTH oracles at rtol=1e-12 — a cold host
    evaluation and a deterministic ``VM_DEVICE_FLEET=0`` per-stream
    replay of the same sequence.  A two-subprocess probe (same
    machinery as the tools/lint.sh compile-cache smoke) shows a warm
    restart compiles 0 kernels with ``VM_COMPILE_CACHE_DIR`` set."""
    from victoriametrics_tpu import native
    from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
    from victoriametrics_tpu.query import rollup_result_cache as rrc
    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.matstream import StreamClient
    from victoriametrics_tpu.query.types import EvalConfig
    from victoriametrics_tpu.utils import flightrec, profiler

    from __graft_entry__ import _provision_devices
    devices = _provision_devices(8)
    import jax
    jax.config.update("jax_enable_x64", True)
    from victoriametrics_tpu.parallel.mesh import make_mesh
    from victoriametrics_tpu.query.tpu_engine import (TPUEngine,
                                                      backend_compiles)
    from victoriametrics_tpu.storage.storage import Storage

    profiler.ensure_started()
    mesh = make_mesh(n_series=8, n_time=1, devices=devices[:8])
    now_ms = int(time.time() * 1000)
    t0 = (now_ms - (FLEETD_SAMPLES - 1) * FLEETD_SCRAPE) // STEP * STEP
    end0 = t0 + ((FLEETD_SAMPLES - 1) * FLEETD_SCRAPE // STEP + 1) * STEP
    keys = [(f'http_requests_total{{instance="host-{i // FLEETD_JOBS}",'
             f'job="job-{i % FLEETD_JOBS}"}}').encode()
            for i in range(FLEETD_SERIES)]
    keybuf = b"".join(keys)
    klens = np.fromiter((len(k) for k in keys), np.int64, FLEETD_SERIES)
    koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
    tmp = tempfile.mkdtemp(prefix="vmtpu-fleetdev-")

    def _rows(entries):
        return {json.dumps(e["metric"], sort_keys=True):
                np.array([[float(t), float(v)] for t, v in e["values"]])
                for e in entries}

    def _max_rel(got, want, ctx):
        """assert_allclose at the rtol=1e-12 contract AND report the
        actual worst relative error for the artifact."""
        assert set(got) == set(want), (ctx, sorted(set(got) ^ set(want))[:4])
        worst = 0.0
        for k in sorted(got):
            g, w = got[k], want[k]
            assert g.shape == w.shape, (ctx, k, g.shape, w.shape)
            np.testing.assert_allclose(g, w, rtol=1e-12, atol=0,
                                       err_msg=f"{ctx} {k}")
            denom = np.maximum(np.abs(w), 1e-300)
            worst = max(worst, float(np.max(np.abs(g - w) / denom))
                        if g.size else 0.0)
        return worst

    def leg(sub_dir, fleet_on, n_per_panel, n_intervals):
        """One deterministic serving sequence over a fresh storage (same
        t0 + same rng seed => identical rows leg-to-leg).  Returns the
        per-interval reassembled windows plus the fleet counters and, on
        the fleet leg, the measured interval walls / cost split / cold
        oracle."""
        rng = np.random.default_rng(0)
        last = np.zeros(FLEETD_SERIES)
        prev_env = os.environ.pop("VM_DEVICE_FLEET", None)
        if not fleet_on:
            os.environ["VM_DEVICE_FLEET"] = "0"
        s = Storage(os.path.join(tmp, sub_dir))
        orig_rec = flightrec.rec
        try:
            base = (np.arange(FLEETD_SAMPLES, dtype=np.int64)
                    * FLEETD_SCRAPE + t0)
            chunk = 512
            for i0 in range(0, FLEETD_SERIES, chunk):
                i1 = min(i0 + chunk, FLEETD_SERIES)
                vals2 = np.cumsum(
                    rng.integers(0, 50, (i1 - i0, FLEETD_SAMPLES)),
                    axis=1).astype(np.float64)
                last[i0:i1] = vals2[:, -1]
                ts2 = np.ascontiguousarray(np.broadcast_to(
                    base, (i1 - i0, FLEETD_SAMPLES)))
                s.add_rows_columnar(native.ColumnarRows(
                    keybuf, np.repeat(koffs[i0:i1], FLEETD_SAMPLES),
                    np.repeat(klens[i0:i1], FLEETD_SAMPLES),
                    ts2.reshape(-1), vals2.reshape(-1)))
            s.force_flush()
            s.force_merge()

            def ingest_fresh(end_ms):
                incr = rng.integers(0, 50, (FLEETD_SERIES, 4))
                vals2 = last[:, None] + np.cumsum(incr, axis=1)
                last[:] = vals2[:, -1]
                ts2 = np.broadcast_to(
                    end_ms - STEP + (np.arange(4, dtype=np.int64) + 1)
                    * FLEETD_SCRAPE, (FLEETD_SERIES, 4))
                s.add_rows_columnar(native.ColumnarRows(
                    keybuf, np.repeat(koffs, 4), np.repeat(klens, 4),
                    np.ascontiguousarray(ts2).reshape(-1),
                    vals2.reshape(-1).astype(np.float64)))

            rrc.GLOBAL.reset()
            engine = TPUEngine(min_series=4, mesh=mesh)
            api = PrometheusAPI(s, engine)
            subs = [[(api.matstreams.subscribe(q, STEP, FLEETD_DUR),
                      StreamClient()) for _ in range(n_per_panel)]
                    for q in FLEETD_PANELS]

            def drain(now):
                target = now // STEP * STEP
                for panel in subs:
                    for sub, cli in panel:
                        while not (cli.window and cli.window[1] >= target):
                            f = sub.next_frame(timeout_s=60.0, now_ms=now)
                            if f is None:
                                raise RuntimeError("subscriber starved")
                            cli.apply(f)

            drain(end0)
            plane = engine.fleet()
            walls = []

            def spy(name, t_s, dur, arg=None):
                if name == "device:fleet_launch":
                    walls.append(dur)
                return orig_rec(name, t_s, dur, arg)

            flightrec.rec = spy

            def exec_ms():
                return sum(ms.usage_row().get("deviceExecMs", 0.0)
                           for ms in api.matstreams.streams())

            out = {"results": [], "push_wall": [], "intervals": [],
                   "cost": []}
            end = end0
            for r in range(n_intervals):
                end += STEP
                ingest_fresh(end)
                walls.clear()
                st0 = plane.stats()
                e0 = exec_ms()
                tw = time.perf_counter()
                api.matstreams.advance_due(end)
                drain(end)
                wall = time.perf_counter() - tw
                st1 = plane.stats()
                out["results"].append(
                    {q: _rows(panel[0][1].result())
                     for q, panel in zip(FLEETD_PANELS, subs)})
                for q, panel in zip(FLEETD_PANELS, subs):
                    head = panel[0][1].result()
                    for _, cli in panel[1:]:
                        assert cli.result() == head, (
                            f"fan-out subscribers of {q!r} diverged")
                if not (fleet_on and r >= FLEETD_WARM):
                    continue
                out["push_wall"].append(wall)
                d = {k: st1[k] - st0[k]
                     for k in ("launches", "served", "compiles")}
                assert st1["buckets"] == 1, (
                    f"panels split across {st1['buckets']} buckets — the "
                    "64x64 corpus no longer shares one G/S/T rung")
                assert st1["members"] == len(FLEETD_PANELS), st1
                assert d["launches"] == 1, (
                    f"interval {r}: {d['launches']} launches for 1 bucket "
                    "— fleet batching regressed to per-stream programs")
                assert d["served"] == len(FLEETD_PANELS), (r, d)
                assert d["compiles"] == 0, (
                    f"interval {r}: warm interval paid a backend compile")
                out["intervals"].append(d)
                billed = exec_ms() - e0
                launch_ms = sum(walls) * 1e3
                assert launch_ms > 0, "no fleet launch recorded"
                assert abs(billed - launch_ms) < \
                    0.05 + 0.002 * len(FLEETD_PANELS), (
                    f"interval {r}: usage rows billed {billed:.3f}ms for "
                    f"{launch_ms:.3f}ms of shared launches")
                out["cost"].append({"billed_ms": round(billed, 3),
                                    "launch_ms": round(launch_ms, 3)})
            out["stats"] = plane.stats()
            out["usage"] = api.matstreams.usage_rows()
            if fleet_on:
                # cold host oracle at the final interval
                import math as _math

                from victoriametrics_tpu.query.format_value import fmt_value
                worst = 0.0
                for q, panel in zip(FLEETD_PANELS, subs):
                    ec = EvalConfig(start=end - FLEETD_DUR, end=end,
                                    step=STEP, storage=s,
                                    disable_cache=True)
                    grid = ec.timestamps() / 1e3
                    want = {}
                    for rr in exec_query(ec, q):
                        vals = np.array(
                            [[float(t), float(fmt_value(v))]
                             for t, v in zip(grid, rr.values)
                             if not _math.isnan(v)])
                        if len(vals):
                            want[json.dumps(rr.metric_name.to_dict(),
                                            sort_keys=True)] = vals
                    worst = max(worst, _max_rel(
                        _rows(panel[0][1].result()), want,
                        f"cold oracle {q!r}"))
                out["cold_max_rel"] = worst
            for panel in subs:
                for sub, _ in panel:
                    sub.close()
            return out
        finally:
            flightrec.rec = orig_rec
            os.environ.pop("VM_DEVICE_FLEET", None)
            if prev_env is not None:
                os.environ["VM_DEVICE_FLEET"] = prev_env
            try:
                s.close()
            except Exception:
                pass

    try:
        t_leg = time.perf_counter()
        fleet = leg("fleet-on", True, FLEET_SUBS,
                    FLEETD_WARM + FLEET_INTERVALS)
        fleet_wall_s = time.perf_counter() - t_leg
        compiles_proc = backend_compiles()
        t_leg = time.perf_counter()
        off = leg("fleet-off", False, 1, FLEETD_WARM + 4)
        off_wall_s = time.perf_counter() - t_leg
        assert off["stats"]["launches"] == 0, (
            "VM_DEVICE_FLEET=0 still launched fleet programs")
        # batched == per-stream across every overlapping interval of the
        # deterministic replay
        ps_max_rel = 0.0
        for r, (g, w) in enumerate(zip(fleet["results"], off["results"])):
            for q in FLEETD_PANELS:
                ps_max_rel = max(ps_max_rel, _max_rel(
                    g[q], w[q], f"per-stream oracle interval {r} {q!r}"))

        # warm-restart probe: two cold subprocesses sharing one
        # VM_COMPILE_CACHE_DIR — the second must compile nothing
        from victoriametrics_tpu.devtools.compile_cache_smoke import _spawn
        cache_dir = tempfile.mkdtemp(prefix="vmtpu-fleetdev-ccache-")
        try:
            cold = _spawn(cache_dir, own_fmt=False)
            if not cold["telemetry"]:
                warm_restart = {"skipped": "compile-event telemetry "
                                           "unavailable"}
            else:
                if cold["native_refused"]:
                    shutil.rmtree(cache_dir, ignore_errors=True)
                    cache_dir = tempfile.mkdtemp(
                        prefix="vmtpu-fleetdev-ccache-")
                    cold = _spawn(cache_dir, own_fmt=True)
                warm = _spawn(cache_dir, own_fmt=cold["native_refused"])
                assert warm["compiles"] == 0, (
                    f"warm restart recompiled {warm['compiles']} kernels "
                    "with the persistent cache enabled")
                warm_restart = {
                    "mechanism": ("ownfmt" if cold["native_refused"]
                                  else "native"),
                    "cold_compiles": cold["compiles"],
                    "warm_compiles": warm["compiles"],
                    "warm_cache_hits": warm["hits"],
                }
        finally:
            shutil.rmtree(cache_dir, ignore_errors=True)

        n_subscriptions = FLEET_SUBS * len(FLEETD_PANELS)
        window_samples = FLEETD_SERIES * ((FLEETD_DUR + 600_000)
                                          // FLEETD_SCRAPE)
        p50_push = float(np.median(fleet["push_wall"]))
        agg_rate = n_subscriptions * window_samples / p50_push
        st = fleet["stats"]
        assert st["compiles"] <= 2 * st["buckets"], (
            f"{st['compiles']} backend compiles for {st['buckets']} "
            "bucket(s) — the <=2-per-bucket acceptance bound broke")
        print(json.dumps({
            "metric": (
                f"fleet-batched device serving: {n_subscriptions} "
                f"subscriptions ({FLEET_SUBS} dashboards x "
                f"{len(FLEETD_PANELS)} shared-selector panels) over "
                f"{FLEETD_SERIES} counters ({FLEETD_INSTANCES} instances "
                f"x {FLEETD_JOBS} jobs, so by(instance)/by(job) share "
                f"the G=64 rung) on the virtual 8-device mesh — ONE "
                f"fused launch per interval serves the whole fleet, "
                f"{st['compiles']} backend compile(s) total, parity at "
                f"rtol=1e-12 with both the cold host oracle and the "
                f"VM_DEVICE_FLEET=0 per-stream replay"),
            "artifact": "MULTICHIP_r07",
            "value": round(agg_rate),
            "unit": "samples/sec",
            "backend": "cpu-device-float64",
            "scenario": "fleet-device",
            "n_devices": len(devices),
            "subscriptions": n_subscriptions,
            "subscribers_per_panel": FLEET_SUBS,
            "panels": len(FLEETD_PANELS),
            "series": FLEETD_SERIES,
            "groups_per_panel": FLEETD_INSTANCES,
            "push_interval_ms": [round(x * 1e3, 2)
                                 for x in fleet["push_wall"]],
            "push_interval_p50_ms": round(p50_push * 1e3, 2),
            "fleet": {
                "buckets": st["buckets"],
                "members": st["members"],
                "adoptions": st["adoptions"],
                "evictions": st["evictions"],
                "launches_total": st["launches"],
                "served_total": st["served"],
                "bucket_compiles_total": st["compiles"],
                "per_measured_interval": fleet["intervals"],
            },
            "cost_split": {
                "per_interval": fleet["cost"],
                "max_abs_gap_ms": round(max(
                    abs(c["billed_ms"] - c["launch_ms"])
                    for c in fleet["cost"]), 3),
            },
            "oracles": {
                "rtol": 1e-12,
                "served_vs_cold_max_rel": fleet["cold_max_rel"],
                "served_vs_per_stream_max_rel": ps_max_rel,
                "per_stream_leg": {
                    "intervals_compared": min(len(fleet["results"]),
                                              len(off["results"])),
                    "fleet_launches": off["stats"]["launches"],
                    "wall_s": round(off_wall_s, 1),
                },
            },
            "warm_restart": warm_restart,
            "process_backend_compiles_after_fleet_leg": compiles_proc,
            "fleet_leg_wall_s": round(fleet_wall_s, 1),
            "per_stream_usage": fleet["usage"],
            "reference": {
                "BENCH_r11_host_fleet": {
                    "samples_per_sec": 956106707,
                    "push_interval_p50_ms": 499.01,
                },
                "BENCH_r12_device_leg": {
                    "refresh_p50_ms": 1406.85,
                    "device_execute_ms_per_capture": 1332.14,
                    "device_compile_ms_per_capture": 2825.11,
                    "note": ("r12 paid one compile and one launch per "
                             "query shape per process; this run pays "
                             "one fused launch per interval for the "
                             "whole fleet and restarts warm"),
                },
            },
            "profiler": {
                "samples": profiler.PROFILER.snapshot()["samples"],
                "hz": profiler.configured_hz(),
            },
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


CL_SERIES = int(os.environ.get("VM_BENCH_CLUSTER_SERIES", "4096"))
CL_SAMPLES = int(os.environ.get("VM_BENCH_CLUSTER_SAMPLES", "360"))
CL_READS = 5


def _spawn_vmstorage(base_dir: str, tag: str):
    """One real vmstorage OS process on loopback ports; returns
    (Popen, http_port, node_spec)."""
    import socket
    import subprocess
    import urllib.request

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    hp, ip_, sp = free_port(), free_port(), free_port()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "victoriametrics_tpu.apps.vmstorage",
         f"-storageDataPath={base_dir}/{tag}",
         f"-httpListenAddr=127.0.0.1:{hp}",
         f"-vminsertAddr=127.0.0.1:{ip_}",
         f"-vmselectAddr=127.0.0.1:{sp}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{hp}/health", timeout=1):
                break
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError(f"vmstorage {tag} died at startup")
            time.sleep(0.1)
    else:
        raise TimeoutError(f"vmstorage {tag} never became ready")
    return proc, hp, f"127.0.0.1:{ip_}:{sp}"


def _cluster_corpus():
    """(keybuf, koffs, klens, per-chunk ingest fn inputs) for the
    cluster corpus: CL_SERIES counters x CL_SAMPLES scrapes."""
    rng = np.random.default_rng(12)
    t0 = 1_753_700_000_000
    keys = [(f'cbench{{idx="{i}",instance="h{i % 64}",'
             f'job="j{i % 7}"}}').encode() for i in range(CL_SERIES)]
    klens = np.fromiter((len(k) for k in keys), np.int64, CL_SERIES)
    koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
    base = np.arange(CL_SAMPLES, dtype=np.int64) * 15_000 + t0
    vals = np.cumsum(rng.integers(0, 40, (CL_SERIES, CL_SAMPLES)),
                     axis=1).astype(np.float64)
    return b"".join(keys), koffs, klens, base, vals, t0


def _cluster_ingest(cluster, keybuf, koffs, klens, base, vals,
                    chunk=512):
    from victoriametrics_tpu import native
    t0 = time.perf_counter()
    for i0 in range(0, CL_SERIES, chunk):
        i1 = min(i0 + chunk, CL_SERIES)
        n = i1 - i0
        cluster.add_rows_columnar(native.ColumnarRows(
            keybuf, np.repeat(koffs[i0:i1], CL_SAMPLES),
            np.repeat(klens[i0:i1], CL_SAMPLES),
            np.tile(base, n),
            vals[i0:i1].reshape(-1)))
    return CL_SERIES * CL_SAMPLES / (time.perf_counter() - t0)


def cluster_main() -> None:
    """``--scenario=cluster`` (ISSUE 15 / ROADMAP item 3 acceptance
    artifact, CLUSTER_r12): real vmstorage OS processes behind the
    in-process ClusterStorage router (the vmselect/vminsert role).

    Sections, each with its invariant asserted in-run:

    - SCALING 1 -> 4 nodes: the same corpus served by 1 and by 4
      vmstorage processes.  ``work_efficiency`` (how evenly the ring
      spreads per-node scan work: total/(N x max-node share)) is the
      scaling claim on an adequately-cored box; measured wall times on
      THIS box ship alongside (on 1 shared core, wall cannot improve).
    - RF=2 RING FILTERING: bytes over the read fan-out with
      ring-ownership filtering on vs off (off reads every replica
      twice), plus bit-equality of both results.
    - REROUTE: with one of the RF=2 nodes down, the full vector is
      byte-identical to the healthy read (vm_reroute_reads_total
      ticking, not partial).
    - REBALANCE UNDER LIVE INGEST: a node joins mid-ingest and
      rebalance_to moves real parts while writes continue — zero write
      errors, exact final counts/sums, byte-exact reads.
    - TENANT QoS THROUGH REROUTE: a quota-capped tenant storms while a
      node is down; the other tenant's p99 stays within 3x unloaded.
    """
    import threading
    import urllib.request

    from victoriametrics_tpu import native
    from victoriametrics_tpu.parallel import ringfilter
    from victoriametrics_tpu.parallel.cluster_api import (
        ClusterStorage, StorageNodeClient, parse_node_spec)
    from victoriametrics_tpu.storage.tag_filters import TagFilter
    from victoriametrics_tpu.utils import costacc
    from victoriametrics_tpu.utils import metrics as metricslib

    os.environ.setdefault("VM_MIGRATE_GRACE_MS", "300")
    tmp = tempfile.mkdtemp(prefix="vmtpu-cluster-")
    procs = []
    out: dict = {"scenario": "cluster", "series": CL_SERIES,
                 "samples_per_series": CL_SAMPLES,
                 "cores": os.cpu_count()}
    keybuf, koffs, klens, base, vals, t0 = _cluster_corpus()
    t_lo, t_hi = int(base[0]), int(base[-1]) + 1
    f = [TagFilter(b"", b"cbench")]

    def spawn(tag):
        p, hp, spec = _spawn_vmstorage(tmp, tag)
        procs.append(p)
        return hp, spec

    def read_wall(cluster):
        walls = []
        cols = None
        for _ in range(CL_READS):
            w0 = time.perf_counter()
            cols = cluster.search_columns(f, t_lo, t_hi)
            walls.append(time.perf_counter() - w0)
        assert cols.n_series == CL_SERIES
        assert cols.n_samples == CL_SERIES * CL_SAMPLES
        return float(np.median(walls)), cols

    try:
        # ---- scaling: 1 node vs 4 nodes -------------------------------
        _, spec1 = spawn("n1")
        c1 = ClusterStorage([StorageNodeClient(*parse_node_spec(spec1))])
        rate1 = _cluster_ingest(c1, keybuf, koffs, klens, base, vals)
        wall1, cols1 = read_wall(c1)

        specs4 = [spawn(f"m{i}")[1] for i in range(4)]
        c4 = ClusterStorage([StorageNodeClient(*parse_node_spec(s))
                             for s in specs4])
        rate4 = _cluster_ingest(c4, keybuf, koffs, klens, base, vals)
        wall4, cols4 = read_wall(c4)
        assert cols4.raw_names == cols1.raw_names
        assert np.array_equal(cols4.vals, cols1.vals), \
            "4-node read diverged from 1-node read"
        shares = [n.series_count() for n in c4.nodes]
        total = sum(shares)
        work_eff = total / (len(shares) * max(shares))
        out["scaling"] = {
            "read_wall_1node_ms": round(wall1 * 1e3, 1),
            "read_wall_4node_ms": round(wall4 * 1e3, 1),
            "wall_speedup_1_to_4": round(wall1 / wall4, 2),
            "ingest_rows_per_s_1node": round(rate1),
            "ingest_rows_per_s_4node": round(rate4),
            "per_node_series": shares,
            "work_efficiency_1_to_4": round(work_eff, 3),
            "note": ("work_efficiency = total/(N*max node share): the "
                     "ring's per-node scan-work split, i.e. read "
                     "scaling on a box with >= N cores; this box has "
                     f"{os.cpu_count()} core(s), so wall times are "
                     "CPU-serialized"),
        }
        assert work_eff >= 0.7, f"scaling efficiency {work_eff} < 0.7"
        c1.close()

        # ---- rf=2 ring filtering: read amplification ------------------
        # (these nodes also host the QoS-through-reroute section, so
        # tenant 1 is quota-capped on the storage side)
        os.environ["VM_TENANT_QUOTAS"] = "1:0=1:100:low"
        try:
            specs2 = [spawn(f"r{i}")[1] for i in range(2)]
        finally:
            del os.environ["VM_TENANT_QUOTAS"]
        c2 = ClusterStorage([StorageNodeClient(*parse_node_spec(s))
                             for s in specs2], replication_factor=2)
        _cluster_ingest(c2, keybuf, koffs, klens, base, vals)

        def fanout_bytes():
            tr = costacc.CostTracker()
            prev = costacc.set_current(tr)
            try:
                cols = c2.search_columns(f, t_lo, t_hi)
            finally:
                costacc.set_current(prev)
            return tr.rpc_bytes, cols

        by_on, cols_on = fanout_bytes()
        os.environ["VM_RING_FILTER"] = "0"
        try:
            by_off, cols_off = fanout_bytes()
        finally:
            del os.environ["VM_RING_FILTER"]
        assert cols_on.raw_names == cols_off.raw_names
        assert np.array_equal(cols_on.vals, cols_off.vals)
        out["rf2_ring_filter"] = {
            "fanout_rpc_bytes_ring_on": int(by_on),
            "fanout_rpc_bytes_ring_off": int(by_off),
            "read_amplification_saved": round(by_off / by_on, 2),
        }
        assert by_off > by_on * 1.6, \
            "ring filtering did not cut replica read amplification"

        # ---- reroute: down node, complete results ---------------------
        rr = metricslib.REGISTRY.counter("vm_reroute_reads_total")
        r0 = rr.get()
        c2.nodes[0].mark_down(3600.0)
        c2.reset_partial()
        w0 = time.perf_counter()
        cols_rr = c2.search_columns(f, t_lo, t_hi)
        reroute_wall = time.perf_counter() - w0
        assert cols_rr.raw_names == cols_on.raw_names
        assert np.array_equal(cols_rr.vals, cols_on.vals), \
            "rerouted read not byte-identical"
        assert not c2.last_partial, "rerouted read flagged partial"
        out["reroute"] = {
            "complete": True,
            "partial": bool(c2.last_partial),
            "read_wall_ms": round(reroute_wall * 1e3, 1),
            "vm_reroute_reads_total_delta": int(rr.get() - r0),
        }
        assert rr.get() > r0

        # ---- tenant QoS through the reroute path ----------------------
        def q(tenant, i):
            w0 = time.perf_counter()
            c2.search_columns(f, t_lo, t_lo + 90_000, tenant=tenant)
            return time.perf_counter() - w0

        unloaded = sorted(q((2, 0), i) for i in range(15))
        stop = threading.Event()
        sheds = [0]
        t1_served = [0]

        def storm():
            while not stop.is_set():
                try:
                    q((1, 0), 0)
                    t1_served[0] += 1
                except Exception:
                    sheds[0] += 1  # quota shed (429-equivalent)

        storms = [threading.Thread(target=storm) for _ in range(2)]
        for th in storms:
            th.start()
        time.sleep(0.2)
        try:
            loaded = sorted(q((2, 0), i) for i in range(15))
        finally:
            stop.set()
            for th in storms:
                th.join(timeout=10)
        p99u = unloaded[-1]
        p99l = loaded[-1]
        out["tenant_qos_through_reroute"] = {
            "tenant1_quota": "1 concurrent / 100ms queue (low prio)",
            "tenant1_served": t1_served[0],
            "tenant1_shed": sheds[0],
            "tenant2_p99_unloaded_ms": round(p99u * 1e3, 1),
            "tenant2_p99_loaded_ms": round(p99l * 1e3, 1),
            "isolation_ratio": round(p99l / p99u, 2),
        }
        assert p99l <= 3 * p99u, \
            f"tenant-2 isolation broke through reroute: {p99l / p99u:.1f}x"
        c2.nodes[0].down_until = 0.0
        c2.close()

        # ---- rebalance under live ingest ------------------------------
        c4b = c4
        write_errors = []
        stop = threading.Event()
        wrote = [0]

        def writer():
            b = 0
            while not stop.is_set():
                rows = [({"__name__": "live", "series": str(i)},
                         t_hi + b * 15_000, float(i + b))
                        for i in range(128)]
                try:
                    c4b.add_rows(rows)
                    wrote[0] = b + 1
                except Exception as e:
                    write_errors.append(str(e))
                b += 1
                time.sleep(0.01)

        wt = threading.Thread(target=writer)
        wt.start()
        time.sleep(0.3)
        _, spec5 = spawn("n5")
        mig0 = metricslib.REGISTRY.counter(
            "vm_parts_migrated_total").get()
        c4b.add_node(spec5)
        stat = c4b.rebalance_to(parse_node_spec(spec5)[0] + ":" +
                                str(parse_node_spec(spec5)[1]))
        time.sleep(0.3)
        stop.set()
        wt.join(timeout=30)
        n_batches = wrote[0]
        got = c4b.search_columns(
            [TagFilter(b"", b"live")], t_hi,
            t_hi + (n_batches + 1) * 15_000)
        assert not write_errors, write_errors[:3]
        assert got.n_series == 128
        # zero dropped acked writes: every acked batch's samples present
        assert int(got.counts.sum()) == 128 * n_batches, \
            (int(got.counts.sum()), 128 * n_batches)
        # the original corpus still reads byte-exact post-rebalance
        wall5, cols5 = read_wall(c4b)
        assert cols5.raw_names == cols1.raw_names
        assert np.array_equal(cols5.vals, cols1.vals), \
            "post-rebalance read diverged"
        out["rebalance_under_ingest"] = {
            "parts_moved": stat["parts"],
            "bytes_moved": stat["bytes"],
            "vm_parts_migrated_total_delta": int(
                metricslib.REGISTRY.counter(
                    "vm_parts_migrated_total").get() - mig0),
            "acked_write_batches": n_batches,
            "write_errors": 0,
            "dropped_acked_writes": 0,
            "post_rebalance_read_wall_ms": round(wall5 * 1e3, 1),
            "byte_exact": True,
        }
        c4b.close()
        out["metric"] = (
            f"elastic cluster serving: {CL_SERIES}x{CL_SAMPLES} corpus "
            f"over real vmstorage processes — ring work-split "
            f"efficiency {out['scaling']['work_efficiency_1_to_4']} "
            f"(1->4 nodes), rf2 ring filtering saves "
            f"{out['rf2_ring_filter']['read_amplification_saved']}x "
            f"read bytes, down-shard reroute complete, join+rebalance "
            f"under live ingest with 0 dropped acked writes "
            f"({stat['parts']} parts / {stat['bytes']} bytes moved)")
        print(json.dumps(out))
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# r13 multi-workload matrix: churn / backfill / qstorm / longrange.
#
# Four workload shapes the single dashboard loop cannot see, each a
# first-class scenario emitting its own BENCH_r13_<scenario>.json with
# the standard attribution splits (per-phase fetch time, result-cache
# merge handling, per-refresh CostTracker, flight-recorder captures):
#
#   churn      every refresh retires part of the live fleet and births
#              replacement identities while part writes run CONCURRENT
#              with serving — merges must DEFER to refreshes
#              (vm_merge_gate_yields_total ticks) and the latency
#              distribution must stay flat (p99 <= 2x p50);
#   backfill   historical chunks land between refreshes — the result
#              cache takes the correctness-mandated rebuild instead of
#              serving stale prefixes;
#   qstorm     a thread-pool storm of distinct queries through the
#              SearchGate admission path (queue_wait becomes visible);
#   longrange  a year-long query over two-tier downsampled data vs the
#              raw oracle (VM_DOWNSAMPLE_READ=0): >=20x fewer samples
#              (target 100x), >=10x lower p50, bit-exact result.
# ---------------------------------------------------------------------------

R13_SERIES = int(os.environ.get("VM_BENCH_R13_SERIES", "2048"))
R13_SAMPLES = int(os.environ.get("VM_BENCH_R13_SAMPLES", "360"))
R13_REFRESHES = int(os.environ.get("VM_BENCH_R13_REFRESHES", "16"))
LR_SERIES = int(os.environ.get("VM_BENCH_R13_LR_SERIES", "16"))
LR_DAYS = int(os.environ.get("VM_BENCH_R13_LR_DAYS", "365"))
DAY_MS = 86_400_000


def _r13_emit(scenario: str, payload: dict) -> None:
    path = f"BENCH_r13_{scenario}.json"
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps(payload))


def _r13_keys(n_series: int, gen) -> list:
    """One metric family, identity = (idx, g): bumping g for a slot is
    CHURN — a brand-new series through index insert + key-map miss."""
    if isinstance(gen, int):
        gen = [gen] * n_series
    return [(f'm{{idx="{i}",g="{gen[i]}",job="job-{i % 17}",'
             f'instance="host-{i % 64}"}}').encode()
            for i in range(n_series)]


def _r13_ingest(s, keys: list, ts2, vals2) -> None:
    from victoriametrics_tpu import native
    klens = np.fromiter((len(k) for k in keys), np.int64, len(keys))
    koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
    k = ts2.shape[1]
    s.add_rows_columnar(native.ColumnarRows(
        b"".join(keys), np.repeat(koffs, k), np.repeat(klens, k),
        ts2.reshape(-1).astype(np.int64),
        vals2.reshape(-1).astype(np.float64)))


def _r13_corpus(s, rng, t_start: int, keys: list):
    """R13_SERIES jittered counters x R13_SAMPLES @15s; returns the
    running counter values for the steady-state ingest to continue."""
    base = np.arange(R13_SAMPLES, dtype=np.int64) * 15_000 + t_start
    last_val = np.zeros(len(keys))
    chunk = 256
    for i0 in range(0, len(keys), chunk):
        i1 = min(i0 + chunk, len(keys))
        ts2 = np.sort(base[None, :] + rng.integers(
            -JITTER_MS, JITTER_MS + 1, (i1 - i0, R13_SAMPLES)), axis=1)
        vals2 = np.cumsum(rng.integers(0, 50, (i1 - i0, R13_SAMPLES)),
                          axis=1).astype(np.float64)
        last_val[i0:i1] = vals2[:, -1]
        _r13_ingest(s, keys[i0:i1], ts2, vals2)
    s.force_flush()
    s.force_merge()
    return last_val


def _r13_steady(api, s, kw, q, end0: int, duration: int, rng, keys,
                last_val, per_refresh=None, concurrent_flush=False):
    """The shared steady loop: live ingest + window advance per refresh
    through the cached-range executor, with the standard attribution
    snapshots. `per_refresh(i, end)` runs extra workload (churn,
    backfill) before the timed refresh; `concurrent_flush` overlaps a
    flush+merge with every timed refresh (the churn merge-pressure
    leg). Returns (lat, stats dict)."""
    import threading

    from victoriametrics_tpu.query.types import EvalConfig
    from victoriametrics_tpu.utils import flightrec

    def ingest_fresh(end_ms: int) -> None:
        incr = rng.integers(0, 50, (len(keys), 4))
        vals2 = last_val[:, None] + np.cumsum(incr, axis=1)
        last_val[:] = vals2[:, -1]
        ts2 = (end_ms - STEP +
               (np.arange(4, dtype=np.int64) + 1)[None, :] * 15_000 +
               rng.integers(-JITTER_MS, JITTER_MS + 1, (len(keys), 4)))
        ts2.sort(axis=1)
        _r13_ingest(s, keys, ts2, vals2)

    end = end0
    api._exec_range_cached(EvalConfig(start=end - duration, end=end,
                                      **kw), q, end)
    pre = []
    for _ in range(2):  # preflight: calibrate the slow-refresh trigger
        end += STEP
        ingest_fresh(end)
        t0 = time.perf_counter()
        api._exec_range_cached(EvalConfig(start=end - duration, end=end,
                                          **kw), q, end)
        pre.append(time.perf_counter() - t0)
    if "VM_SLOW_REFRESH_MS" not in os.environ:
        os.environ["VM_SLOW_REFRESH_MS"] = str(
            max(min(pre) * 1.25e3, 25.0))
    thresh_ms = float(os.environ["VM_SLOW_REFRESH_MS"])
    flight_id0 = flightrec.RECORDER.total()
    ph0, c0 = _phase_totals(), _cache_merge_totals()
    lat, leg_costs = [], []
    for i in range(R13_REFRESHES):
        end += STEP
        ingest_fresh(end)
        if per_refresh is not None:
            per_refresh(i, end)
        fl = None
        if concurrent_flush:
            fl = threading.Thread(
                target=lambda: (s.force_flush(), s.force_merge()))
            fl.start()
        ec = EvalConfig(start=end - duration, end=end, **kw)
        t0 = time.perf_counter()
        api._exec_range_cached(ec, q, end)
        lat.append(time.perf_counter() - t0)
        leg_costs.append(ec.cost)
        if fl is not None:
            fl.join()
    stats = {
        "phase": _phase_label(ph0, _phase_totals(), R13_REFRESHES),
        "cache": _cache_merge_delta(c0),
        "cost": _cost_leg_summary(leg_costs, lat),
        "flight": _leg_flight_summary(flight_id0, thresh_ms),
    }
    return lat, stats


def _r13_setup(tmp: str, downsample=None, retention_ms=None):
    from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
    from victoriametrics_tpu.storage.storage import Storage
    kw = {}
    if downsample is not None:
        kw["downsample"] = downsample
    if retention_ms is not None:
        kw["retention_ms"] = retention_ms
    s = Storage(tmp, **kw)
    return s, PrometheusAPI(s, None)


def churn_main() -> None:
    """Scenario `churn`: identity turnover under merge pressure.

    Every refresh retires ~2% of the live fleet and births replacement
    identities (new g= label -> index inserts + key-map misses), and a
    flush+merge runs CONCURRENT with the timed refresh. Acceptance:
    vm_merge_gate_yields_total ticks (part writes defer to in-flight
    serving instead of stealing its cores) and refresh p99 stays within
    2x p50 — churn must degrade the MEDIAN honestly, not fabricate a
    tail cliff."""
    tmp = tempfile.mkdtemp(prefix="vmtpu-bench-churn-")
    rng = np.random.default_rng(13)
    try:
        s, api = _r13_setup(tmp)
        now_ms = int(time.time() * 1000)
        t_start = (now_ms - (R13_SAMPLES - 1) * 15_000) // STEP * STEP
        keys = _r13_keys(R13_SERIES, 0)
        gens = [0] * R13_SERIES
        last_val = _r13_corpus(s, rng, t_start, keys)
        q = "sum by (job)(rate(m[5m]))"
        duration = (R13_SAMPLES - 1) * 15_000 - 300_000
        end0 = t_start + -(-((R13_SAMPLES - 1) * 15_000 + JITTER_MS)
                           // STEP) * STEP
        kw = dict(step=STEP, storage=s, tpu=None)
        churn_n = max(1, R13_SERIES // 50)
        churned = 0

        def per_refresh(i, end):
            nonlocal churned
            lo = (i * churn_n) % R13_SERIES
            idxs = [(lo + j) % R13_SERIES for j in range(churn_n)]
            for j in idxs:
                gens[j] = i + 1            # new identity for the slot
                keys[j] = _r13_keys(R13_SERIES, gens)[j]
                last_val[j] = 0.0          # fresh counter from zero
            churned += churn_n

        lat, stats = _r13_steady(api, s, kw, q, end0, duration, rng,
                                 keys, last_val, per_refresh=per_refresh,
                                 concurrent_flush=True)
        p50 = float(np.median(lat)) * 1e3
        p99 = float(np.percentile(lat, 99)) * 1e3
        yields = stats["cache"]["merge_gate_yields"]
        assert yields > 0, \
            "churn loop never deferred a merge to serving"
        assert p99 <= 2 * p50, (p99, p50)
        _r13_emit("churn", {
            "scenario": "churn",
            "metric": f"series churn: {R13_SERIES} live series, "
                      f"{churn_n}/refresh replaced over "
                      f"{R13_REFRESHES} refreshes with concurrent "
                      f"flush+merge — merges deferred to serving "
                      f"{yields}x, p99/p50 {p99 / p50:.2f}",
            "value": round(p50, 2), "unit": "ms refresh p50",
            "series": R13_SERIES, "churned_total": churned,
            "refresh_p50_ms": round(p50, 2),
            "refresh_p99_ms": round(p99, 2),
            "refresh_ms": [round(x * 1e3, 2) for x in lat],
            "acceptance": {"merge_gate_yields_gt_0": yields > 0,
                           "p99_within_2x_p50": p99 <= 2 * p50},
            **stats,
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def backfill_main() -> None:
    """Scenario `backfill`: historical chunks land between refreshes.

    Each refresh is preceded by an out-of-order ingest of a 15-minute
    historical chunk (2 days old) for every live series — the write
    path the remote-write backfill/migration tools exercise. The
    result cache must take the correctness-mandated rebuild (a cached
    prefix over a window that just changed underneath is a LIE), so
    the artifact records the rebuild/inplace split plus the sustained
    backfill rate alongside the refresh distribution."""
    tmp = tempfile.mkdtemp(prefix="vmtpu-bench-backfill-")
    rng = np.random.default_rng(17)
    try:
        s, api = _r13_setup(tmp)
        now_ms = int(time.time() * 1000)
        t_start = (now_ms - (R13_SAMPLES - 1) * 15_000) // STEP * STEP
        keys = _r13_keys(R13_SERIES, 0)
        last_val = _r13_corpus(s, rng, t_start, keys)
        q = "sum by (job)(rate(m[5m]))"
        duration = (R13_SAMPLES - 1) * 15_000 - 300_000
        end0 = t_start + -(-((R13_SAMPLES - 1) * 15_000 + JITTER_MS)
                           // STEP) * STEP
        kw = dict(step=STEP, storage=s, tpu=None)
        bf_base = t_start - 2 * DAY_MS
        bf_chunk = 60                      # 15min @ 15s per refresh
        bf_rows = [0]
        bf_secs = [0.0]

        def per_refresh(i, end):
            ts0 = bf_base + i * bf_chunk * 15_000
            ts2 = (ts0 + np.arange(bf_chunk, dtype=np.int64)[None, :]
                   * 15_000 + np.zeros((R13_SERIES, 1), np.int64))
            vals2 = np.cumsum(
                rng.integers(0, 50, (R13_SERIES, bf_chunk)),
                axis=1).astype(np.float64)
            t0 = time.perf_counter()
            _r13_ingest(s, keys, ts2, vals2)
            bf_secs[0] += time.perf_counter() - t0
            bf_rows[0] += R13_SERIES * bf_chunk

        lat, stats = _r13_steady(api, s, kw, q, end0, duration, rng,
                                 keys, last_val, per_refresh=per_refresh)
        p50 = float(np.median(lat)) * 1e3
        p99 = float(np.percentile(lat, 99)) * 1e3
        bf_rate = bf_rows[0] / max(bf_secs[0], 1e-9)
        _r13_emit("backfill", {
            "scenario": "backfill",
            "metric": f"backfill under serving: {bf_rows[0]} historical "
                      f"rows ({bf_rate / 1e6:.2f}M rows/s) interleaved "
                      f"with {R13_REFRESHES} refreshes — "
                      + (f"cache took {stats['cache']['rebuild']} "
                         f"rebuilds / {stats['cache']['inplace']} "
                         f"in-place merges"
                         if stats["cache"]["rebuild"]
                         or stats["cache"]["inplace"] else
                         "every refresh recomputed cold (the backfill "
                         "invalidates the cached window — correctness "
                         "over cache reuse)"),
            "value": round(p50, 2), "unit": "ms refresh p50",
            "series": R13_SERIES, "backfill_rows": bf_rows[0],
            "backfill_rows_per_s": int(bf_rate),
            "refresh_p50_ms": round(p50, 2),
            "refresh_p99_ms": round(p99, 2),
            "refresh_ms": [round(x * 1e3, 2) for x in lat],
            **stats,
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def qstorm_main() -> None:
    """Scenario `qstorm`: a burst of DISTINCT queries through the
    SearchGate admission path — 8 client threads x 4 rounds x 16
    different (function, selector) combinations, caches off (every
    query is a first sight, the anti-dashboard). The per-phase split
    makes queue_wait visible; VM_SEARCH_CONCURRENCY is pinned to 4 so
    admission genuinely queues instead of vanishing on a wide host."""
    os.environ.setdefault("VM_SEARCH_CONCURRENCY", "4")
    import concurrent.futures as cf

    tmp = tempfile.mkdtemp(prefix="vmtpu-bench-qstorm-")
    rng = np.random.default_rng(23)
    try:
        from victoriametrics_tpu.query.exec import exec_query
        from victoriametrics_tpu.query.types import EvalConfig
        from victoriametrics_tpu.utils import flightrec
        s, _api = _r13_setup(tmp)
        now_ms = int(time.time() * 1000)
        t_start = (now_ms - (R13_SAMPLES - 1) * 15_000) // STEP * STEP
        keys = _r13_keys(R13_SERIES, 0)
        _r13_corpus(s, rng, t_start, keys)
        duration = (R13_SAMPLES - 1) * 15_000 - 300_000
        end = t_start + -(-((R13_SAMPLES - 1) * 15_000 + JITTER_MS)
                          // STEP) * STEP
        funcs = ["rate", "increase", "max_over_time", "avg_over_time"]
        queries = [f'sum by (instance)({fn}(m{{job="job-{j}"}}[5m]))'
                   for fn in funcs for j in (1, 3, 5, 7)]

        def one(q):
            ec = EvalConfig(start=end - duration, end=end, step=STEP,
                            storage=s, tpu=None, disable_cache=True)
            t0 = time.perf_counter()
            rows = exec_query(ec, q)
            dt = time.perf_counter() - t0
            assert rows, q
            return dt, ec.cost

        os.environ.setdefault("VM_SLOW_REFRESH_MS", "1000")
        flight_id0 = flightrec.RECORDER.total()
        ph0, c0 = _phase_totals(), _cache_merge_totals()
        lat, leg_costs = [], []
        rounds = 4
        t_wall = time.perf_counter()
        with cf.ThreadPoolExecutor(max_workers=8) as pool:
            for _ in range(rounds):
                for dt, cost in pool.map(one, queries):
                    lat.append(dt)
                    leg_costs.append(cost)
        wall = time.perf_counter() - t_wall
        n = len(lat)
        p50 = float(np.median(lat)) * 1e3
        p99 = float(np.percentile(lat, 99)) * 1e3
        d1 = _phase_totals()
        _r13_emit("qstorm", {
            "scenario": "qstorm",
            "metric": f"query storm: {n} distinct cold queries over "
                      f"{R13_SERIES} series via 8 threads at "
                      f"VM_SEARCH_CONCURRENCY="
                      f"{os.environ['VM_SEARCH_CONCURRENCY']} — "
                      f"{n / wall:.1f} qps, queue_wait "
                      f"{(d1['queue_wait'] - ph0['queue_wait']) * 1e3 / n:.0f}"
                      f"ms/query",
            "value": round(n / wall, 2), "unit": "queries/sec",
            "threads": 8, "distinct_queries": len(queries),
            "rounds": rounds,
            "query_p50_ms": round(p50, 2),
            "query_p99_ms": round(p99, 2),
            "queue_wait_ms_per_query": round(
                (d1["queue_wait"] - ph0["queue_wait"]) * 1e3 / n, 2),
            "phase": _phase_label(ph0, d1, n),
            "cache": _cache_merge_delta(c0),
            "cost": _cost_leg_summary(leg_costs, lat),
            "flight": _leg_flight_summary(
                flight_id0, float(os.environ["VM_SLOW_REFRESH_MS"])),
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def longrange_main() -> None:
    """Scenario `longrange`: the downsampling headline (ISSUE 20).

    A year of 30s raw data under VM_DOWNSAMPLE=1d:5m,30d:1h, one
    re-rollup cycle, then the same year-long `sum_over_time(m[1d])`
    step-1d query through the tier-serving read path vs the raw oracle
    (VM_DOWNSAMPLE_READ=0). Acceptance: >=20x fewer samples read
    (target 100x), >=10x lower p50, bit-exact equality on the
    day-aligned grid."""
    tmp = tempfile.mkdtemp(prefix="vmtpu-bench-longrange-")
    rng = np.random.default_rng(29)
    try:
        from victoriametrics_tpu.query.exec import exec_query
        from victoriametrics_tpu.query.types import EvalConfig
        from victoriametrics_tpu.utils import flightrec
        s, _api = _r13_setup(tmp, downsample="1d:5m,30d:1h",
                             retention_ms=2 * 366 * DAY_MS)
        now_ms = int(time.time() * 1000)
        t_start = (now_ms // DAY_MS - LR_DAYS) * DAY_MS
        keys = _r13_keys(LR_SERIES, 0)
        n_per_day = DAY_MS // 30_000
        t0 = time.perf_counter()
        for d0 in range(0, LR_DAYS, 30):       # monthly ingest chunks
            nd = min(30, LR_DAYS - d0)
            base = (t_start + d0 * DAY_MS + np.arange(
                nd * n_per_day, dtype=np.int64) * 30_000)
            ts2 = np.broadcast_to(base, (LR_SERIES, base.size))
            vals2 = rng.integers(
                0, 1000, (LR_SERIES, base.size)).astype(np.float64)
            _r13_ingest(s, keys, np.ascontiguousarray(ts2), vals2)
            s.force_flush()
        ingest_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        s.run_downsample_cycle(now_ms=now_ms)
        ds_dt = time.perf_counter() - t0

        q = "sum_over_time(m[1d])"
        start = t_start + DAY_MS
        end = (now_ms // DAY_MS) * DAY_MS - DAY_MS
        raw_samples = LR_SERIES * LR_DAYS * n_per_day

        def leg(n_evals):
            s.reset_partial()
            lats, costs, rows = [], [], None
            id0 = flightrec.RECORDER.total()
            ph0 = _phase_totals()
            for _ in range(n_evals):
                ec = EvalConfig(start=start, end=end, step=DAY_MS,
                                storage=s, tpu=None, disable_cache=True)
                t0 = time.perf_counter()
                rows = exec_query(ec, q)
                lats.append(time.perf_counter() - t0)
                costs.append(ec.cost)
            return rows, {
                "p50_ms": round(float(np.median(lats)) * 1e3, 2),
                "samples_read": costs[-1].samples,
                "phase": _phase_label(ph0, _phase_totals(), n_evals),
                "cost": _cost_leg_summary(costs, lats),
                "flight": _leg_flight_summary(
                    id0, float(os.environ.get("VM_SLOW_REFRESH_MS",
                                              "1000"))),
            }

        os.environ.setdefault("VM_SLOW_REFRESH_MS", "10000")
        tier_rows, tier = leg(3)
        os.environ["VM_DOWNSAMPLE_READ"] = "0"
        try:
            raw_rows, raw = leg(3)
        finally:
            del os.environ["VM_DOWNSAMPLE_READ"]
        _assert_rows_equal(tier_rows, raw_rows)   # bit-exact, host path
        samples_ratio = raw["samples_read"] / max(tier["samples_read"], 1)
        p50_ratio = raw["p50_ms"] / max(tier["p50_ms"], 1e-9)
        assert samples_ratio >= 20, samples_ratio
        assert p50_ratio >= 10, p50_ratio
        _r13_emit("longrange", {
            "scenario": "longrange",
            "metric": f"long-range over tiers: {LR_DAYS}d x {LR_SERIES} "
                      f"series @30s ({raw_samples / 1e6:.1f}M raw "
                      f"samples), year query step 1d reads "
                      f"{samples_ratio:.0f}x fewer samples and runs "
                      f"{p50_ratio:.0f}x faster than the raw oracle, "
                      f"bit-exact",
            "value": round(samples_ratio, 1),
            "unit": "x fewer samples read",
            "tiers": "1d:5m,30d:1h",
            "raw_samples": raw_samples,
            "ingest_s": round(ingest_dt, 1),
            "downsample_pass_s": round(ds_dt, 1),
            "p50_speedup": round(p50_ratio, 1),
            "tier_leg": tier, "raw_leg": raw,
            "acceptance": {"samples_ratio_ge_20": samples_ratio >= 20,
                           "samples_ratio": round(samples_ratio, 1),
                           "p50_ratio_ge_10": p50_ratio >= 10,
                           "p50_ratio": round(p50_ratio, 1),
                           "oracle_bit_exact": True},
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import argparse
    _p = argparse.ArgumentParser(prog="bench.py")
    _p.add_argument("--scenario", default="dashboard",
                    choices=["dashboard", "fleet", "cluster", "churn",
                             "backfill", "qstorm", "longrange"],
                    help="dashboard: the classic rolling-window loop "
                         "(default, the BENCH_r* headline); fleet: N "
                         "subscribers x M shared-selector panels via "
                         "materialized streams (BENCH_r11); cluster: "
                         "elastic scale-out over real vmstorage "
                         "processes (CLUSTER_r12); churn/backfill/"
                         "qstorm/longrange: the r13 workload matrix "
                         "(BENCH_r13_<scenario>.json — identity "
                         "turnover under merge pressure, historical "
                         "ingest under serving, an admission-gated "
                         "query storm, and the downsample-tier "
                         "long-range headline)")
    _p.add_argument("--device", action="store_true",
                    help="with --scenario=fleet: the fleet-batched "
                         "DEVICE serving leg on the virtual 8-device "
                         "mesh (MULTICHIP_r07) — one fused launch per "
                         "interval for every resident stream")
    _args = _p.parse_args()
    if _args.scenario == "fleet" and _args.device:
        fleet_device_main()
    elif _args.scenario == "fleet":
        fleet_main()
    elif _args.scenario == "cluster":
        cluster_main()
    elif _args.scenario == "churn":
        churn_main()
    elif _args.scenario == "backfill":
        backfill_main()
    elif _args.scenario == "qstorm":
        qstorm_main()
    elif _args.scenario == "longrange":
        longrange_main()
    else:
        main()
