"""Benchmark: samples/sec scanned by the TPU query pipeline.

Workload modeled on BASELINE.md config 2 (`sum by(instance)(rate(m[5m]))`
range query over high-cardinality counters): 8192 counter series x 1440
samples (6h @ 15s), rate over 5m windows on a 60s step grid, summed into
1024 groups — all on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N}

vs_baseline divides by 1e8 samples/sec — the order of the reference's
single-core block-unpack + rollup scan rate (its netstorage unpack workers
+ rollupConfig.Do; BASELINE.md notes the repo publishes capacity figures,
not absolute scan rates, so this is the documented working assumption).

Methodology: queries run against the HBM tile cache (models/tile_cache.py)
after one cold populating query — matching how the reference benchmarks
range queries against its RAM blockcache/page-cache-hot parts. The cold
(chunked-H2D) rate is measured too and reported inside the metric label.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from victoriametrics_tpu.models.rollup_pipeline import (QueryPipeline,
                                                            synth_workload)
    from victoriametrics_tpu.models.tile_cache import TileCache
    from victoriametrics_tpu.ops.rollup_np import RollupConfig

    start = 1_753_700_000_000
    n_series, n_samples, num_groups = 8192, 1440, 1024
    cfg = RollupConfig(start=start, end=start + 6 * 3600_000,
                       step=60_000, window=300_000)
    pipe = QueryPipeline(cfg=cfg, rollup_func="rate", aggr="sum",
                         num_groups=num_groups)
    host_tiles = synth_workload(n_series, n_samples, cfg, num_groups,
                                dtype=np.float32)

    fn = jax.jit(pipe.jitted())
    cache = TileCache(capacity_bytes=2 << 30)
    samples = n_series * n_samples

    # cold path: compact delta planes over the link, decoded on device
    # (ops/device_decode; ~4x fewer bytes than dense tiles)
    import dataclasses

    from victoriametrics_tpu.models.tile_cache import chunked_device_put
    from victoriametrics_tpu.ops import device_decode as dd
    rng = np.random.default_rng(0)
    triples = []
    base = np.arange(n_samples, dtype=np.int64) * 15_000 + cfg.start
    for i in range(n_series):
        ts = np.sort(base + rng.integers(-2000, 2001, n_samples))
        mant = np.cumsum(rng.integers(0, 50, n_samples)).astype(np.int64)
        triples.append((ts, mant, -2))
    planes = dd.pack_delta_planes(triples, cfg.start, np.float32)
    npad = int(planes.counts.max())

    def cold_once():
        dev = [chunked_device_put(getattr(planes, f.name))
               for f in dataclasses.fields(planes)]
        out = dd.decode_and_rollup("rate", *dev[:6], dev[6], dev[7], cfg,
                                   npad, np.float32)
        out.block_until_ready()

    cold_once()  # compile
    t0 = time.perf_counter()
    cold_once()
    cold_s = time.perf_counter() - t0

    # compile + populate the hot path
    fn(*cache.get_or_put(("bench", 0), lambda: host_tiles)).block_until_ready()

    # hot: cache-resident tiles, as in steady-state serving
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        tiles = cache.get_or_put(("bench", 0), lambda: host_tiles)
        fn(*tiles).block_until_ready()
    dt = (time.perf_counter() - t0) / iters

    rate = samples / dt
    cold_rate = samples / cold_s
    baseline = 1e8  # single-core reference scan rate (see module docstring)
    print(json.dumps({
        "metric": ("hot-shard sum by(rate) scan, 8192x1440 f32, HBM tile "
                   f"cache (cold via device-decoded delta planes: "
                   f"{cold_rate/1e6:.0f}M/s)"),
        "value": round(rate),
        "unit": "samples/sec",
        "vs_baseline": round(rate / baseline, 2),
    }))


if __name__ == "__main__":
    main()
