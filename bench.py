"""Benchmark: END-TO-END samples/sec through the real served query path.

Workload modeled on BASELINE.md config 2 (`sum by(instance)(rate(m[5m]))`
range query over high-cardinality counters): ingest 8192 counter series x
360 samples (1.5h @ 15s) into a real on-disk Storage (parts, index,
codecs), then run the full evaluator — index search -> part block decode ->
series assembly -> pack -> rollup (device kernels when a TPU/accelerator is
present, vectorized host batch otherwise) -> aggregation.

Headline = warm end-to-end scan rate (steady-state serving, block caches
and HBM tiles hot — matching how the reference benchmarks against its RAM
blockcache). Cold (first query) rate, ingest rate, and warm latency are
reported inside the metric label.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec", "vs_baseline": N}

vs_baseline divides by 1e8 samples/sec — the order of the reference's
single-core block-unpack + rollup scan rate (its netstorage unpack workers
+ rollupConfig.Do; BASELINE.md notes the repo publishes capacity figures,
not absolute scan rates, so this is the documented working assumption).
"""

from __future__ import annotations

import json
import shutil
import tempfile
import time

import numpy as np

N_SERIES = 8192
N_SAMPLES = 1440         # 6h @ 15s
N_INSTANCES = 256


def main() -> None:
    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.types import EvalConfig
    from victoriametrics_tpu.storage.storage import Storage

    tmp = tempfile.mkdtemp(prefix="vmtpu-bench-")
    t_start = 1_753_700_000_000
    try:
        s = Storage(tmp)

        # -- ingest: realistic jittered counters through the real write path
        rng = np.random.default_rng(0)
        base = np.arange(N_SAMPLES, dtype=np.int64) * 15_000 + t_start
        labels = [{"__name__": "http_requests_total",
                   "instance": f"host-{i % N_INSTANCES}",
                   "job": f"job-{i % 17}", "idx": str(i)}
                  for i in range(N_SERIES)]
        t0 = time.perf_counter()
        for i in range(N_SERIES):
            ts = np.sort(base + rng.integers(-2000, 2001, N_SAMPLES))
            vals = np.cumsum(rng.integers(0, 50, N_SAMPLES)).astype(float)
            s.add_rows(list(zip([labels[i]] * N_SAMPLES, ts.tolist(),
                                vals.tolist())))
        ingest_dt = time.perf_counter() - t0
        s.force_flush()
        s.force_merge()

        # -- query through the full evaluator, device backend if available
        tpu = None
        try:
            import jax
            if jax.devices():
                from victoriametrics_tpu.query.tpu_engine import TPUEngine
                tpu = TPUEngine(value_dtype=np.float32)
        except Exception:
            pass
        end = t_start + (N_SAMPLES - 1) * 15_000
        q = "sum by (instance)(rate(http_requests_total[5m]))"
        samples = N_SERIES * N_SAMPLES

        # measure both backends on the same storage; serve the better one
        # (the axon-tunneled dev chip pays ~0.2s fixed D2H latency per
        # query, so the host batch path can win at small sizes; a locally
        # attached TPU would not)
        results = {}
        for backend, engine in (("device", tpu), ("host-batch", None)):
            if backend == "device" and engine is None:
                continue
            # disable_cache: the bench measures the real fetch+compute
            # path, not result-cache hits
            ec_kw = dict(start=t_start + 300_000, end=end, step=60_000,
                         storage=s, tpu=engine, disable_cache=True)
            t0 = time.perf_counter()
            rows = exec_query(EvalConfig(**ec_kw), q)
            cold_dt = time.perf_counter() - t0
            assert len(rows) == N_INSTANCES, len(rows)
            iters = 3
            t0 = time.perf_counter()
            for _ in range(iters):
                rows = exec_query(EvalConfig(**ec_kw), q)
            results[backend] = ((time.perf_counter() - t0) / iters, cold_dt)

        backend, (warm_dt, cold_dt) = min(results.items(),
                                          key=lambda kv: kv[1][0])
        rate = samples / warm_dt
        baseline = 1e8  # single-core reference scan rate (see docstring)
        print(json.dumps({
            "metric": (f"e2e sum by(rate) range query, {N_SERIES}x"
                       f"{N_SAMPLES} counters via storage+index+decode+"
                       f"{backend} (cold {samples / cold_dt / 1e6:.0f}M/s, "
                       f"warm p50 {warm_dt * 1e3:.0f}ms, ingest "
                       f"{N_SERIES * N_SAMPLES / ingest_dt / 1e3:.0f}k "
                       f"rows/s)"),
            "value": round(rate),
            "unit": "samples/sec",
            "vs_baseline": round(rate / baseline, 2),
        }))
    finally:
        try:
            s.close()
        except Exception:
            pass
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
