#!/bin/sh
# Chaos-suite entry point (ROADMAP item 3): two slow-marked families.
#
# 1. Cluster liveness (tests/test_chaos_cluster.py, PR 9): kill/restart
#    vmstorage mid-query, slow-node injection (fault-injected RPC
#    stalls), storage-side deadline aborts (budget shipped in the
#    search request, typed error, no node-down marking), RF=2 failover
#    byte-equality with replica-covered (non-partial) accounting, an
#    ingest storm racing force_merge, per-tenant QoS isolation — plus
#    the PR-15 elasticity scenarios: a vmstorage JOINS mid-ingest and
#    another DRAINS mid-query-storm over /internal/cluster/* (zero
#    dropped acked writes, byte-exact post-migration reads,
#    vm_parts_migrated_total accounting), and a multilevel
#    vmselect->vmselect->2x-vmstorage tree serving rows byte-identical
#    to the flat fan-out.
#
# 2. Crash recovery (tests/test_crash_recovery.py): the kill -9 matrix —
#    a subprocess ingest storm racing flush/force_merge/snapshot is
#    SIGKILLed at >= 20 randomized instants against one accumulating
#    store, reopened, and checked against the recovery invariants
#    (acked-before-flush data byte-exact, no orphan tmp dirs, no silent
#    part loss, quarantine only when bytes actually tore).  The per-seam
#    crashpoint matrix (part:finalize:{pre,post}_rename,
#    partition:parts_json:pre_replace, merge:post_rename_pre_manifest,
#    mergeset:flush, indexdb:rotate, snapshot:mid — armed via
#    VM_FAULTS='<seam>=crash') and the torn-part quarantine matrix run
#    in tier-1 and are NOT repeated here.
#
# The cluster scenarios spawn real vmstorage/vminsert/vmselect/vmsingle
# OS processes; faults are armed per node via each process's
# /internal/faults endpoint or the VM_FAULTS env var
# (devtools/faultinject.py — delay/stall/error/reset/crash at the RPC
# server, storage-search/scan, and part-lifecycle seams).
#
# These tests are `slow`-marked, so tier-1 (`-m 'not slow'`) never pays
# for them; this script opts back in.  Whole run is bounded ~90s on the
# 2-core box (~35s cluster + ~45s crash matrix).
#
# Knobs (see README "Multi-tenant QoS & chaos testing" and "Crash
# recovery & durability"):
#   VM_TENANT_QUOTAS   per-tenant concurrency/queue/priority quotas
#   VM_FAULTS          fault table armed at process start
#   VM_RPC_RETRIES / VM_RPC_BACKOFF_MS / VM_RPC_BACKOFF_MAX_MS
#
# Extra args pass through to pytest, e.g.:
#   tools/chaos.sh -k qos
#   tools/chaos.sh -k kill9 -x
set -eu
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_chaos_cluster.py tests/test_crash_recovery.py \
    -q -m slow -p no:cacheprovider "$@"
