#!/bin/sh
# Chaos-suite entry point (ROADMAP item 3): runs the slow-marked
# process-level chaos scenarios in tests/test_chaos_cluster.py —
# kill/restart vmstorage mid-query, slow-node injection (fault-injected
# RPC stalls), RF=2 failover byte-equality, an ingest storm racing
# force_merge, per-tenant QoS isolation under a saturating tenant, and
# deadline propagation (a stalled node costs one query deadline).
#
# The scenarios spawn real vmstorage/vminsert/vmselect/vmsingle OS
# processes; faults are armed per node via each process's
# /internal/faults endpoint or the VM_FAULTS env var
# (devtools/faultinject.py — delay/stall/error/reset at the RPC server
# and storage-search seams).
#
# These tests are `slow`-marked, so tier-1 (`-m 'not slow'`) never pays
# for them; this script opts back in.  The fast halves of the same
# machinery (TenantGate admission semantics, the race-marked stress
# under the deterministic scheduler, in-process RPC deadline tests) run
# in tier-1 via tests/test_tenant_gate.py and under tools/race.sh.
#
# Knobs (see README "Multi-tenant QoS & chaos testing"):
#   VM_TENANT_QUOTAS   per-tenant concurrency/queue/priority quotas
#   VM_FAULTS          fault table armed at process start
#   VM_RPC_RETRIES / VM_RPC_BACKOFF_MS / VM_RPC_BACKOFF_MAX_MS
#
# Extra args pass through to pytest, e.g.:
#   tools/chaos.sh -k qos
#   tools/chaos.sh -k deadline -x
set -eu
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/test_chaos_cluster.py -q -m slow \
    -p no:cacheprovider "$@"
