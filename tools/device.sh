#!/bin/sh
# Device-plane suite on the VIRTUAL 8-device CPU mesh (the MULTICHIP_r*
# proving path: XLA_FLAGS=--xla_force_host_platform_device_count=8).
# Real TPUs are a config change (unset JAX_PLATFORMS, run under
# VMTPU_TEST_TPU=1), not a rewrite.
#
# Loud-fallback contract: the backend is probed FIRST in a subprocess
# with a hard deadline — a hung backend init (the axon PJRT plugin hangs
# on some boxes, DEVICE_RUN_r05.json) SKIPS with a message and exit 0,
# never hangs the caller and never reads as a silent pass ("SKIPPED" is
# printed on stderr, and the suite line never appears).
#
#   tools/device.sh                      # full device suite
#   tools/device.sh fleet                # fleet-batched serving suite only
#   tools/device.sh warmup               # pre-compile fleet kernels into
#                                        # the persistent compile cache
#                                        # (VM_COMPILE_CACHE_DIR) so the
#                                        # next serving restart starts warm
#   tools/device.sh tests/test_x.py::t   # specific tests (lint smoke)
#   VMT_DEVICE_PROBE_TIMEOUT_S=30 tools/device.sh
set -eu
cd "$(dirname "$0")/.."
TIMEOUT="${VMT_DEVICE_PROBE_TIMEOUT_S:-120}"
if ! env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        timeout -k 5 "$TIMEOUT" python -c "
import jax
jax.config.update('jax_platforms', 'cpu')
n = len(jax.devices())
assert n >= 8, f'only {n} virtual devices came up'
print(f'device.sh probe OK: {n} virtual cpu devices')
"; then
    echo "device.sh: SKIPPED - virtual-mesh probe failed or hung" \
         "(>${TIMEOUT}s); the device suite DID NOT RUN (not a pass)." >&2
    exit 0
fi
if [ "${1:-}" = "warmup" ]; then
    shift
    exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
        JAX_ENABLE_X64="${JAX_ENABLE_X64:-1}" \
        python -m victoriametrics_tpu.devtools.compile_cache_smoke \
        --warmup "$@"
fi
if [ "${1:-}" = "fleet" ]; then
    shift
    set -- tests/test_device_fleet.py "$@"
fi
if [ "$#" -eq 0 ]; then
    set -- tests/test_device_residency.py tests/test_exec_query_mesh.py \
           tests/test_rolling_tile.py tests/test_served_device_path.py \
           tests/test_device_rollup.py tests/test_f32_tiles.py \
           tests/test_device_fleet.py
fi
exec env JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider "$@"
