#!/bin/sh
# Unified pre-merge gate: every static pass, every overhead/integration
# smoke, then the fast tier-1 test markers — one command, one exit code,
# per-stage wall-clock timing so a slow stage is visible instead of
# smeared into "CI is slow".
#
#   tools/check.sh            # run everything
#   VMT_NO_TIER1=1 tools/check.sh   # static + smokes only
#
# Stages (each independently skippable, same flags tools/lint.sh uses):
#   lint       full lint: per-file rules + call-graph passes (VMT012
#              deadline taint, VMT013 stale disables, VMT014 env-flag
#              inventory, VMT015 lockset, VMT016 errorflow) + the
#              wire-schema ratchet (exit 4 breaking /
#              2 additive drift)            VMT_NO_LINT=1
#   lockset    VMT015 standalone (guarded-by inference, own timing
#              and witness output)          VMT_NO_LOCKSET=1
#   errorflow  VMT016 standalone (exception-escape audit)
#                                           VMT_NO_ERRORFLOW=1
#   flight     flight-recorder overhead     VMT_NO_FLIGHT_SMOKE=1
#   profile    continuous-profiler overhead VMT_NO_PROFILE_SMOKE=1
#   matstream  materialized-stream fan-out  VMT_NO_MATSTREAM_SMOKE=1
#   selfscrape self-scrape+SLO duty cycle   VMT_NO_SELFSCRAPE_SMOKE=1
#   reshard    elastic scale-out reshard    VMT_NO_RESHARD_SMOKE=1
#   dsample    downsample tier read path  VMT_NO_DOWNSAMPLE_SMOKE=1
#   ccache     persistent compile cache: a second cold process must
#              compile 0 kernels for a warmed bucket shape (native jax
#              cache + own-format fallback)  VMT_NO_COMPILE_CACHE_SMOKE=1
#   device     8-device residency + fleet   VMT_NO_DEVICE_SMOKE=1
#   crash      one crashpoint seam + reopen VMT_NO_CRASH_SMOKE=1
#   tier1      pytest tests/ -m 'not slow'  VMT_NO_TIER1=1
#
# All stages run even after a failure (the summary shows every broken
# stage, not just the first); the exit code is the first failing
# stage's.
set -u
cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export JAX_PLATFORMS

fail_rc=0
summary=""

run_stage() {
    _name=$1
    shift
    _t0=$(date +%s)
    if "$@"; then
        _st=ok
    else
        _rc=$?
        _st="FAIL(rc=$_rc)"
        [ "$fail_rc" -eq 0 ] && fail_rc=$_rc
    fi
    _dt=$(( $(date +%s) - _t0 ))
    printf 'check: %-9s %-12s %4ds\n' "$_name" "$_st" "$_dt"
    summary="$summary
  $_name: $_st (${_dt}s)"
}

skipped() {
    printf 'check: %-9s %-12s\n' "$1" skipped
    summary="$summary
  $1: skipped"
}

if [ "${VMT_NO_LINT:-0}" != "1" ]; then
    run_stage lint python -m victoriametrics_tpu.devtools.lint
else
    skipped lint
fi
if [ "${VMT_NO_LOCKSET:-0}" != "1" ]; then
    run_stage lockset python -m victoriametrics_tpu.devtools.lockset
else
    skipped lockset
fi
if [ "${VMT_NO_ERRORFLOW:-0}" != "1" ]; then
    run_stage errorflow python -m victoriametrics_tpu.devtools.errorflow
else
    skipped errorflow
fi
if [ "${VMT_NO_FLIGHT_SMOKE:-0}" != "1" ]; then
    run_stage flight python -m victoriametrics_tpu.devtools.flight_overhead
else
    skipped flight
fi
if [ "${VMT_NO_PROFILE_SMOKE:-0}" != "1" ]; then
    run_stage profile python -m victoriametrics_tpu.devtools.profile_overhead
else
    skipped profile
fi
if [ "${VMT_NO_MATSTREAM_SMOKE:-0}" != "1" ]; then
    run_stage matstream \
        python -m victoriametrics_tpu.devtools.matstream_overhead
else
    skipped matstream
fi
if [ "${VMT_NO_SELFSCRAPE_SMOKE:-0}" != "1" ]; then
    run_stage selfscrape \
        python -m victoriametrics_tpu.devtools.selfscrape_overhead
else
    skipped selfscrape
fi
if [ "${VMT_NO_RESHARD_SMOKE:-0}" != "1" ]; then
    run_stage reshard python -m victoriametrics_tpu.devtools.reshard_smoke
else
    skipped reshard
fi
if [ "${VMT_NO_DOWNSAMPLE_SMOKE:-0}" != "1" ]; then
    run_stage dsample \
        python -m victoriametrics_tpu.devtools.downsample_smoke
else
    skipped dsample
fi
if [ "${VMT_NO_COMPILE_CACHE_SMOKE:-0}" != "1" ]; then
    run_stage ccache \
        python -m victoriametrics_tpu.devtools.compile_cache_smoke
else
    skipped ccache
fi
if [ "${VMT_NO_DEVICE_SMOKE:-0}" != "1" ]; then
    run_stage device sh tools/device.sh \
        "tests/test_device_residency.py::test_refresh_uploads_only_tail_on_mesh" \
        "tests/test_device_fleet.py::test_fleet_single_launch_per_interval"
else
    skipped device
fi
if [ "${VMT_NO_CRASH_SMOKE:-0}" != "1" ]; then
    run_stage crash python -m pytest \
        "tests/test_crash_recovery.py::test_crashpoint_seam[part:finalize:pre_rename]" \
        -q -p no:cacheprovider
else
    skipped crash
fi
if [ "${VMT_NO_TIER1:-0}" != "1" ]; then
    run_stage tier1 python -m pytest tests/ -q -m "not slow" \
        -p no:cacheprovider
else
    skipped tier1
fi

echo "check: summary$summary"
if [ "$fail_rc" -ne 0 ]; then
    echo "check: FAILED (exit $fail_rc)"
else
    echo "check: all stages passed"
fi
exit "$fail_rc"
