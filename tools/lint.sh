#!/bin/sh
# Canonical static-analysis entry point (tier-1 / CI): runs the project
# lint engine over the package. Exit codes:
#   0  clean against devtools/lint_baseline.txt
#   1  new findings (not grandfathered, not inline-disabled)
#   3  baseline staleness: grandfathered entries that no longer fire —
#      slack in the ratchet; regenerate with --update-baseline
# Extra args are passed through, e.g.:
#   tools/lint.sh --update-baseline
#   tools/lint.sh --no-baseline victoriametrics_tpu/storage/
#
# After a clean lint, the flight-recorder overhead smoke check runs
# (devtools/flight_overhead.py): the always-on record path must stay
# under a per-event ns budget AND within VM_FLIGHT_SMOKE_PCT (default
# 2%) of VM_FLIGHTREC=0 on a serving-shaped workload — exit 1 on an
# overhead regression.  VMT_NO_FLIGHT_SMOKE=1 skips it (e.g. when
# iterating on lint findings only).
#
# Then a single-crashpoint smoke (one armed kill -9 seam + clean-reopen
# check, ~3s): the crash-injection harness itself must not rot between
# full tools/chaos.sh runs.  VMT_NO_CRASH_SMOKE=1 skips it.
#
# And a device-residency smoke (tools/device.sh with the tier-1 guard
# test): the virtual 8-device mesh + resident-window upload guard must
# not rot between full device.sh runs; probe hang -> loud skip.
# VMT_NO_DEVICE_SMOKE=1 skips it.
set -eu
cd "$(dirname "$0")/.."
# --changed-only: lint just the .py files that differ from the merge
# base (VMT_CHANGED_BASE, default main) plus untracked ones — the fast
# inner loop while editing.  The call-graph passes (VMT012/VMT015/
# VMT016) still run — built over the WHOLE package, since they are
# interprocedural — but report only findings landing in the changed
# files (--scoped-program-passes); wireschema and the smokes stay
# full-gate-only (tools/check.sh).
if [ "${1:-}" = "--changed-only" ]; then
    shift
    base=$(git merge-base HEAD "${VMT_CHANGED_BASE:-main}" 2>/dev/null \
           || git rev-parse HEAD)
    changed=$( { git diff --name-only "$base" -- '*.py';
                 git ls-files --others --exclude-standard -- '*.py'; } \
               | sort -u)
    files=""
    for f in $changed; do
        [ -f "$f" ] && files="$files $f"
    done
    if [ -z "$files" ]; then
        echo "lint: no changed .py files vs $(git rev-parse --short "$base")"
        exit 0
    fi
    # shellcheck disable=SC2086
    exec python -m victoriametrics_tpu.devtools.lint \
        --scoped-program-passes $files "$@"
fi
if [ "$#" -eq 0 ]; then
    set -- victoriametrics_tpu/
fi
python -m victoriametrics_tpu.devtools.lint "$@"
if [ "${VMT_NO_FLIGHT_SMOKE:-0}" != "1" ]; then
    python -m victoriametrics_tpu.devtools.flight_overhead
fi
# Continuous-profiler overhead smoke (devtools/profile_overhead.py):
# the default-on sampling thread must stay within VM_PROFILE_SMOKE_PCT
# (default 2%) of profiler-stopped on a serving-shaped workload.
# VMT_NO_PROFILE_SMOKE=1 skips it.
if [ "${VMT_NO_PROFILE_SMOKE:-0}" != "1" ]; then
    python -m victoriametrics_tpu.devtools.profile_overhead
fi
# Materialized-stream fan-out smoke (devtools/matstream_overhead.py):
# one interval with N subscribers must cost ONE evaluation with flat
# samples-scanned and near-zero per-subscriber fan-out cost.
# VMT_NO_MATSTREAM_SMOKE=1 skips it.
if [ "${VMT_NO_MATSTREAM_SMOKE:-0}" != "1" ]; then
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python -m victoriametrics_tpu.devtools.matstream_overhead
fi
# Self-monitoring plane overhead smoke (devtools/selfscrape_overhead.py):
# one scrape+SLO-eval cycle against a real Storage must stay within
# VM_SELFSCRAPE_SMOKE_PCT (default 2%) duty cycle of the 15s interval.
# VMT_NO_SELFSCRAPE_SMOKE=1 skips it.
if [ "${VMT_NO_SELFSCRAPE_SMOKE:-0}" != "1" ]; then
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python -m victoriametrics_tpu.devtools.selfscrape_overhead
fi
# Elastic-cluster reshard smoke (devtools/reshard_smoke.py): a second
# vmstorage joins a 1-node cluster without a restart, rebalance moves
# real parts over migrateParts_v1 byte-exactly, and an RF=2 down node
# serves COMPLETE results through the explicit reroute path.  Skips
# itself (exit 0) when no zstd codec exists; VMT_NO_RESHARD_SMOKE=1
# skips it outright.
if [ "${VMT_NO_RESHARD_SMOKE:-0}" != "1" ]; then
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python -m victoriametrics_tpu.devtools.reshard_smoke
fi
# Downsample tier smoke (devtools/downsample_smoke.py): one re-rollup
# cycle against a real Storage; the 5m tier must serve a hinted
# long-range fetch with >=4x fewer samples and stay bit-exact vs the
# raw oracle.  VMT_NO_DOWNSAMPLE_SMOKE=1 skips it.
if [ "${VMT_NO_DOWNSAMPLE_SMOKE:-0}" != "1" ]; then
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python -m victoriametrics_tpu.devtools.downsample_smoke
fi
# Persistent compile-cache smoke (devtools/compile_cache_smoke.py): a
# second cold process must compile 0 kernels for a fleet bucket shape
# the first process warmed — native jax cache AND the own-format
# serialized-executable fallback.  Skips itself loudly when the runtime
# supports neither; VMT_NO_COMPILE_CACHE_SMOKE=1 skips it outright.
if [ "${VMT_NO_COMPILE_CACHE_SMOKE:-0}" != "1" ]; then
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        python -m victoriametrics_tpu.devtools.compile_cache_smoke
fi
if [ "${VMT_NO_DEVICE_SMOKE:-0}" != "1" ]; then
    sh tools/device.sh \
        "tests/test_device_residency.py::test_refresh_uploads_only_tail_on_mesh"
fi
if [ "${VMT_NO_CRASH_SMOKE:-0}" != "1" ]; then
    exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
        "tests/test_crash_recovery.py::test_crashpoint_seam[part:finalize:pre_rename]" \
        -q -p no:cacheprovider
fi
