#!/bin/sh
# Canonical static-analysis entry point (tier-1 / CI): runs the project
# lint engine over the package and exits non-zero on any finding not in
# devtools/lint_baseline.txt. Extra args are passed through, e.g.:
#   tools/lint.sh --update-baseline
#   tools/lint.sh --no-baseline victoriametrics_tpu/storage/
set -eu
cd "$(dirname "$0")/.."
if [ "$#" -eq 0 ]; then
    set -- victoriametrics_tpu/
fi
exec python -m victoriametrics_tpu.devtools.lint "$@"
