#!/bin/sh
# Canonical static-analysis entry point (tier-1 / CI): runs the project
# lint engine over the package. Exit codes:
#   0  clean against devtools/lint_baseline.txt
#   1  new findings (not grandfathered, not inline-disabled)
#   3  baseline staleness: grandfathered entries that no longer fire —
#      slack in the ratchet; regenerate with --update-baseline
# Extra args are passed through, e.g.:
#   tools/lint.sh --update-baseline
#   tools/lint.sh --no-baseline victoriametrics_tpu/storage/
set -eu
cd "$(dirname "$0")/.."
if [ "$#" -eq 0 ]; then
    set -- victoriametrics_tpu/
fi
exec python -m victoriametrics_tpu.devtools.lint "$@"
