#!/bin/sh
# Stdlib-only SSE client for the materialized-stream push surface
# (/api/v1/watch): hold one subscription and print suffix frames as they
# arrive — the dashboard-side half of the cross-query amortization plane.
#
# Usage:
#   tools/watch.sh 'sum by (g)(rate(m[5m]))'                # defaults
#   tools/watch.sh 'rate(m[1m])' -step 15s -range 30m -n 10
#   tools/watch.sh 'rate(m[1m])' -url http://host:8428 -assemble
#
# Flags:
#   -url U       serving base URL        (default http://127.0.0.1:8428)
#   -step S      grid step               (default 1m)
#   -range R     rolling window length   (default 30m)
#   -n N         stop after N frames     (default 0 = until ^C)
#   -assemble    maintain client-side state and print the REASSEMBLED
#                query_range-shaped result after each frame (the
#                StreamClient the bit-equality oracle uses) instead of
#                the raw frames
set -eu
cd "$(dirname "$0")/.."
[ "$#" -ge 1 ] || { echo "usage: tools/watch.sh QUERY [flags]" >&2; exit 2; }
QUERY=$1; shift
URL=http://127.0.0.1:8428 STEP=1m RANGE=30m N=0 ASSEMBLE=0
while [ "$#" -gt 0 ]; do
    case "$1" in
        -url) URL=$2; shift 2;;
        -step) STEP=$2; shift 2;;
        -range) RANGE=$2; shift 2;;
        -n) N=$2; shift 2;;
        -assemble) ASSEMBLE=1; shift;;
        *) echo "unknown flag $1" >&2; exit 2;;
    esac
done
exec env WATCH_QUERY="$QUERY" WATCH_URL="$URL" WATCH_STEP="$STEP" \
    WATCH_RANGE="$RANGE" WATCH_N="$N" WATCH_ASSEMBLE="$ASSEMBLE" \
    python - <<'EOF'
import json, os, sys, urllib.parse, urllib.request

from victoriametrics_tpu.query.matstream import StreamClient

params = {"query": os.environ["WATCH_QUERY"],
          "step": os.environ["WATCH_STEP"],
          "range": os.environ["WATCH_RANGE"]}
n = int(os.environ["WATCH_N"])
if n:
    params["max_frames"] = str(n)
url = (os.environ["WATCH_URL"].rstrip("/") + "/api/v1/watch?"
       + urllib.parse.urlencode(params))
assemble = os.environ["WATCH_ASSEMBLE"] == "1"
cli = StreamClient()
try:
    with urllib.request.urlopen(url) as r:
        for raw in r:
            line = raw.decode("utf-8", "replace").rstrip("\n")
            if not line.startswith("data: "):
                continue
            frame = json.loads(line[len("data: "):])
            if not assemble:
                print(json.dumps(frame), flush=True)
                continue
            cli.apply(frame)
            print(json.dumps({
                "frame": {k: frame.get(k) for k in
                          ("type", "seq", "newStartMs", "partial",
                           "resync", "error") if k in frame},
                "window": cli.window,
                "result": cli.result()}), flush=True)
except KeyboardInterrupt:
    pass
except urllib.error.HTTPError as e:
    sys.stderr.write(f"watch: HTTP {e.code}: {e.read().decode()}\n")
    sys.exit(1)
EOF
