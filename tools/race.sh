#!/bin/sh
# Race-detection entry point (the `go test -race` analog): runs the
# race-marked tests with the happens-before sanitizer enabled.
#
#   VMT_RACETRACE=1   vector-clock sanitizer on (devtools/racetrace.py):
#                     traced fields in storage/parallel/models are checked,
#                     make_lock/make_rlock return TracedLocks, Thread
#                     start/join and queue.Queue put/get carry clocks.
#
# Reports print both stack traces, count into vm_race_reports_total, and
# surface as RaceWarning; a failing interleaving is replayed from the
# seed shown in the failure via devtools.sched.DeterministicScheduler.
#
# Covers the parallel read path too: the concurrent fetch stress runs
# with VM_SEARCH_WORKERS=2 so the shared work pool's submit/result seam
# (utils/workpool) is exercised under the sanitizer, and the
# DeterministicScheduler tests pin down the pool's inline-under-
# scheduler behavior.
#
# The parallel WRITE path (sharded ingest) is covered by the sharded
# ingest+query stress with VM_INGEST_SHARDS=4: striped registration,
# async pending conversion and gated merges all run under the
# sanitizer.  When bisecting a write-path failure, VM_INGEST_SHARDS=1
# restores the exact sequential ingest pipeline (the escape hatch
# mirroring VM_SEARCH_WORKERS=1 on the read path).
#
# The fused native read kernel (VM_NATIVE_ASSEMBLE, vm_assemble_part)
# runs the concurrent fetch stress in BOTH modes: the kernel-enabled
# leg exercises the per-part GIL-released calls racing on the decode-
# memo/budget seams, the VM_NATIVE_ASSEMBLE=0 leg is the split Python
# oracle — which is also the escape hatch when bisecting a read-path
# miscompare (flip it before reaching for VM_SEARCH_WORKERS=1).
# The ring result cache (in-place tail merges, VM_RESULT_CACHE_RING) is
# covered by the race-marked test in tests/test_result_cache_ring.py:
# concurrent refreshes, live ingest and a mid-flight backfill reset over
# one entry, asserting served==cold sha256 equality per refresh.  When
# bisecting a cache miscompare, VM_RESULT_CACHE_RING=0 restores the
# rebuild-every-merge oracle (and VM_HOST_FUSED_AGGR=0 the unfused
# aggregation path).
#
# The flight recorder (utils/flightrec) is covered by the race-marked
# stress in tests/test_flightrec.py: concurrent per-thread ring writers
# hammered while captures walk the rings, asserting the seqlock-reader
# discipline never yields a torn event or an unserializable trace.
# VM_FLIGHTREC=0 is the escape hatch when bisecting (also disables the
# pool's ctx-propagation records around each task).
#
# The materialized-stream plane (query/matstream) is covered by the
# race-marked stress in tests/test_matstream.py: subscriber churn +
# live ingest + concurrent cooperative pumps over one stream, asserting
# the steady subscriber's reassembled state equals the polled oracle,
# queues stay bounded, and no exception escapes.  VM_MATSTREAM=0 is the
# escape hatch (watch subscribers fall back to polling query_range).
#
# The fleet-batched device plane (query/fleet) is covered by the
# race-marked stress in tests/test_device_fleet.py: subscriber churn +
# live ingest + concurrent cooperative pumps while the fleet adopts,
# launches and serves on the virtual 8-device mesh, asserting the steady
# subscriber matches the host oracle after quiescing.  VM_DEVICE_FLEET=0
# is the escape hatch (streams fall back to per-stream rolling serving).
#
# The per-tenant admission gate (utils/workpool.TenantGate) is covered
# by the race-marked stress in tests/test_tenant_gate.py: two tenants'
# workers under the deterministic scheduler, asserting the per-tenant
# and global caps hold at every observation point, every worker
# completes (starvation-freedom), and the same seed replays the same
# outcome.  VM_TENANT_QUOTAS= (unset) restores the plain global gate
# when bisecting an admission issue.
#
# Extra args pass through to pytest, e.g.:
#   tools/race.sh -k scheduler
#   tools/race.sh tests/test_stress_race.py::TestRaceTrace
set -eu
cd "$(dirname "$0")/.."
# Scoped to the race-marked modules (not tests/) so collection errors in
# unrelated zstandard-dependent modules can't fail a green race run.
exec env VMT_RACETRACE=1 VMT_LOCKTRACE_MAX_HOLD_MS=60000 \
    python -m pytest tests/test_stress_race.py \
    tests/test_result_cache_ring.py tests/test_flightrec.py \
    tests/test_tenant_gate.py tests/test_matstream.py \
    tests/test_device_fleet.py -q -m race \
    -p no:cacheprovider "$@"
