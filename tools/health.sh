#!/bin/sh
# Self-monitoring plane helper: pull the health verdict, SLO burn-rate
# status and incident log from a running vmsingle/vmselect/vmstorage.
#
# Usage:
#   tools/health.sh [-a host:port] health            # roll-up verdict
#   tools/health.sh [-a host:port] slo               # burn-rate status
#   tools/health.sh [-a host:port] slo pump          # force an eval now
#   tools/health.sh [-a host:port] incidents         # incident log
#   tools/health.sh [-a host:port] incidents ID      # one full record
#   tools/health.sh watch A:P [B:Q ...]              # merged cluster view
#
# `health` on a vmselect fans health_v1 out to every storage node and
# rolls the verdicts up (node_down / node_degraded reasons name the
# node); on a vmstorage/vmsingle it is the node-local verdict.  `watch`
# polls several processes directly and prints one verdict line each —
# the poor man's cluster dashboard when no vmselect is running.
set -eu
ADDR="127.0.0.1:8428"
if [ "${1:-}" = "-a" ]; then
    ADDR="$2"
    shift 2
fi
CMD="${1:-health}"

fetch() {
    # stdlib only: curl is not guaranteed in the dev containers
    python - "$1" <<'EOF'
import json, sys, urllib.request
body = urllib.request.urlopen(sys.argv[1], timeout=30).read()
try:
    out = json.dumps(json.loads(body), indent=2).encode() + b"\n"
except ValueError:
    out = body
try:
    sys.stdout.buffer.write(out)
    sys.stdout.buffer.flush()
except BrokenPipeError:  # reader closed early (| head, | grep -q)
    sys.exit(0)
EOF
}

case "$CMD" in
health)
    fetch "http://$ADDR/api/v1/status/health"
    ;;
slo)
    if [ "${2:-}" = "pump" ]; then
        fetch "http://$ADDR/api/v1/status/slo?pump=1"
    else
        fetch "http://$ADDR/api/v1/status/slo"
    fi
    ;;
incidents)
    if [ -n "${2:-}" ]; then
        fetch "http://$ADDR/api/v1/status/incidents?id=$2"
    else
        fetch "http://$ADDR/api/v1/status/incidents"
    fi
    ;;
watch)
    shift
    [ "$#" -ge 1 ] || {
        echo "usage: tools/health.sh watch host:port [host:port ...]" >&2
        exit 2
    }
    python - "$@" <<'EOF'
import json, signal, sys, urllib.request
signal.signal(signal.SIGPIPE, signal.SIG_DFL)  # die quietly on | head
worst = 0
rank = {"ok": 0, "degraded": 1, "critical": 2}
for addr in sys.argv[1:]:
    try:
        body = urllib.request.urlopen(
            f"http://{addr}/api/v1/status/health", timeout=10).read()
        h = json.loads(body)
        verdict = h.get("verdict", "unknown")
        reasons = ",".join(r.get("code", "?") for r in h.get("reasons", []))
        print(f"{addr:24s} {h.get('role', '?'):10s} {verdict:9s}"
              f" {reasons or '-'}")
        worst = max(worst, rank.get(verdict, 2))
    except Exception as e:
        print(f"{addr:24s} {'?':10s} {'unreachable':9s} {e}")
        worst = max(worst, 2)
sys.exit(0 if worst == 0 else 1)
EOF
    ;;
*)
    echo "unknown command: $CMD (health|slo|incidents|watch)" >&2
    exit 2
    ;;
esac
