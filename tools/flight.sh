#!/bin/sh
# Flight-recorder helper: pull cross-thread latency captures and the
# slow-query log from a running vmsingle/vmselect.
#
# Usage:
#   tools/flight.sh [-a host:port] list              # capture metadata
#   tools/flight.sh [-a host:port] capture           # trigger on-demand
#   tools/flight.sh [-a host:port] get ID [out.json] # Perfetto-loadable
#   tools/flight.sh [-a host:port] slow              # slow-query log
#
# `get` writes the bare Chrome trace-event JSON — open it at
# https://ui.perfetto.dev (or chrome://tracing).  Captures fire
# automatically when a refresh exceeds VM_SLOW_REFRESH_MS; `capture`
# freezes the live ring window on demand.  VM_FLIGHTREC=0 disables the
# recorder (the endpoint answers 503).
set -eu
ADDR="127.0.0.1:8428"
if [ "${1:-}" = "-a" ]; then
    ADDR="$2"
    shift 2
fi
CMD="${1:-list}"
BASE="http://$ADDR/api/v1/status"

fetch() {
    # stdlib only: curl is not guaranteed in the dev containers
    python - "$1" "${2:-}" <<'EOF'
import json, sys, urllib.request
url, out = sys.argv[1], sys.argv[2]
body = urllib.request.urlopen(url, timeout=30).read()
if out:
    with open(out, "wb") as f:
        f.write(body)
    print(f"wrote {len(body)} bytes to {out}")
else:
    try:
        print(json.dumps(json.loads(body), indent=2))
    except ValueError:
        sys.stdout.buffer.write(body)
EOF
}

case "$CMD" in
list)
    fetch "$BASE/flight"
    ;;
capture)
    fetch "$BASE/flight?capture=1"
    ;;
get)
    ID="${2:?usage: tools/flight.sh get ID [out.json]}"
    fetch "$BASE/flight?id=$ID" "${3:-flight_$ID.json}"
    ;;
slow)
    fetch "$BASE/slow_queries"
    ;;
*)
    echo "unknown command: $CMD (list|capture|get|slow)" >&2
    exit 2
    ;;
esac
