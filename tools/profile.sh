#!/bin/sh
# Continuous-profiler helper: pull folded-stack / speedscope profiles
# from running vmsingle/vmselect/vmstorage processes and merge several
# nodes' raw snapshots into one speedscope file.  Stdlib-only (no curl,
# no package imports) — works in minimal containers.
#
# Usage:
#   tools/profile.sh [-a host:port] collapsed            # folded stacks
#   tools/profile.sh [-a host:port] speedscope [out.json]
#   tools/profile.sh [-a host:port] raw                  # snapshot JSON
#   tools/profile.sh [-a host:port] usage                # per-tenant cost
#   tools/profile.sh merge out.json host1:port1 [host2:port2 ...]
#
# `speedscope` output loads at https://www.speedscope.app.  A vmselect
# answers with its storage nodes' profiles merged in (profile_v1
# fan-out, node-tagged); `merge` does the same client-side across any
# set of nodes.  VM_PROFILE_HZ=0 disables the profiler (503).
set -eu
ADDR="127.0.0.1:8428"
if [ "${1:-}" = "-a" ]; then
    ADDR="$2"
    shift 2
fi
CMD="${1:-collapsed}"

fetch() {
    # stdlib only: curl is not guaranteed in the dev containers
    python - "$1" "${2:-}" <<'EOF'
import json, sys, urllib.request
url, out = sys.argv[1], sys.argv[2]
body = urllib.request.urlopen(url, timeout=30).read()
if out:
    with open(out, "wb") as f:
        f.write(body)
    print(f"wrote {len(body)} bytes to {out}")
else:
    try:
        print(json.dumps(json.loads(body), indent=2))
    except ValueError:
        sys.stdout.buffer.write(body)
EOF
}

case "$CMD" in
collapsed)
    fetch "http://$ADDR/api/v1/status/profile"
    ;;
speedscope)
    fetch "http://$ADDR/api/v1/status/profile?format=speedscope" \
        "${2:-profile_speedscope.json}"
    ;;
raw)
    fetch "http://$ADDR/api/v1/status/profile?format=raw"
    ;;
usage)
    fetch "http://$ADDR/api/v1/status/usage"
    ;;
merge)
    OUT="${2:?usage: tools/profile.sh merge out.json host:port [...]}"
    shift 2
    [ "$#" -ge 1 ] || { echo "merge: need at least one host:port" >&2; exit 2; }
    python - "$OUT" "$@" <<'EOF'
import json, sys, urllib.request
out, addrs = sys.argv[1], sys.argv[2:]
# fetch every node's raw snapshots, tag untagged ones with the address,
# and fold everything into one speedscope file (sampled profiles, one
# per node/role) — the same shape utils/profiler.speedscope builds,
# kept stdlib-only here so the helper runs anywhere
snaps = []
for addr in addrs:
    url = f"http://{addr}/api/v1/status/profile?format=raw"
    body = json.loads(urllib.request.urlopen(url, timeout=30).read())
    for snap in body.get("data", []):
        snap.setdefault("node", None)
        if snap["node"] is None:
            snap["node"] = addr
        snaps.append(snap)
frames, fidx = [], {}
def fi(label):
    if label not in fidx:
        fidx[label] = len(frames)
        frames.append({"name": label})
    return fidx[label]
groups = {}
for snap in snaps:
    for row in snap.get("stacks", []):
        g = f"{snap['node']}/{row['role']}"
        s, w = groups.setdefault(g, ([], []))
        s.append([fi(f) for f in row["stack"]])
        w.append(int(row["count"]))
profiles = []
for g in sorted(groups):
    s, w = groups[g]
    profiles.append({"type": "sampled", "name": g, "unit": "none",
                     "startValue": 0, "endValue": sum(w),
                     "samples": s, "weights": w})
doc = {"$schema": "https://www.speedscope.app/file-format-schema.json",
       "shared": {"frames": frames}, "profiles": profiles,
       "name": "merged cluster profile", "activeProfileIndex": 0,
       "exporter": "tools/profile.sh merge"}
with open(out, "w") as f:
    json.dump(doc, f)
print(f"merged {len(snaps)} snapshot(s) from {len(addrs)} node(s) "
      f"into {out} ({len(profiles)} profiles)")
EOF
    ;;
*)
    echo "unknown command: $CMD (collapsed|speedscope|raw|usage|merge)" >&2
    exit 2
    ;;
esac
