"""Bit-exactness of the fused native read kernel (VM_NATIVE_ASSEMBLE=1:
native/codec.cpp vm_assemble_part + vm_dedup_rows) against the split
Python-orchestrated path (VM_NATIVE_ASSEMBLE=0 — the escape hatch AND the
correctness oracle).

The equality matrix covers: multi-partition/multi-part stores, dedup
boundaries (interval multiples, equal-timestamp ties, staleness markers),
range clips landing mid-block, zstd AND zlib-fallback compressed parts,
and VM_SEARCH_WORKERS>1 pool fan-out. Every comparison is a sha256 over
the full assembled columnar result, so a single flipped byte fails."""

import hashlib
import os

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.ops import compress
from victoriametrics_tpu.ops.decimal import STALE_NAN
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import TagFilter

pytestmark = pytest.mark.requires_native

BASE = 1_700_000_000_000
MONTH = 32 * 86_400_000  # next monthly partition for sure


def _filters(name: str):
    return [TagFilter(b"", name.encode())]


def _digest(cols) -> str:
    h = hashlib.sha256()
    h.update(cols.metric_ids.tobytes())
    h.update(cols.counts.tobytes())
    h.update(np.ascontiguousarray(cols.ts).tobytes())
    h.update(np.ascontiguousarray(cols.vals).tobytes())
    h.update(repr(cols.ts.shape).encode())
    for r in cols.raw_names:
        h.update(r)
    return h.hexdigest()


def _search_digest(st, name, lo, hi, dedup=None) -> str:
    return _digest(st.search_columns(_filters(name), lo, hi,
                                     dedup_interval_ms=dedup))


def _assert_modes_equal(monkeypatch, st, name, lo, hi, dedup=None):
    monkeypatch.setenv("VM_NATIVE_ASSEMBLE", "1")
    fused = _search_digest(st, name, lo, hi, dedup)
    monkeypatch.setenv("VM_NATIVE_ASSEMBLE", "0")
    oracle = _search_digest(st, name, lo, hi, dedup)
    monkeypatch.delenv("VM_NATIVE_ASSEMBLE", raising=False)
    assert fused == oracle, (name, lo - BASE, hi - BASE, dedup)
    return fused


@pytest.fixture
def store(tmp_path):
    st = Storage(str(tmp_path / "st"))
    yield st
    st.close()


class TestEqualityMatrix:
    def test_multi_partition_multi_part(self, store, monkeypatch):
        """Two monthly partitions, several file parts each (no merge),
        plus unflushed pending rows; full-range and interior fetches."""
        rng = np.random.default_rng(7)
        for part in range(3):
            rows = []
            for i in range(24):
                lbl = {"__name__": "mp", "i": str(i)}
                t0 = BASE + part * 200_000
                vals = np.cumsum(rng.integers(0, 9, 150)).astype(float)
                rows += [(lbl, t0 + j * 1000, float(vals[j]))
                         for j in range(150)]
                rows += [(lbl, t0 + MONTH + j * 1000, float(vals[j]))
                         for j in range(150)]
            store.add_rows(rows)
            store.force_flush()
        # pending tail on top of file parts
        store.add_rows([({"__name__": "mp", "i": str(i)},
                         BASE + 900_000 + j * 500, float(j))
                        for i in range(6) for j in range(40)])
        for lo, hi in ((BASE, BASE + MONTH + 10**6),
                       (BASE + 123_456, BASE + 456_789),
                       (BASE + MONTH + 50_500, BASE + MONTH + 250_250)):
            _assert_modes_equal(monkeypatch, store, "mp", lo, hi)

    def test_mid_block_clips(self, store, monkeypatch):
        """Range edges inside blocks: every boundary alignment (first
        sample, mid, exact edge, one-past) against the oracle."""
        rows = [({"__name__": "clip", "i": str(i)}, BASE + j * 1000,
                 float(i * 1000 + j))
                for i in range(8) for j in range(500)]
        store.add_rows(rows)
        store.force_flush()
        for lo_off, hi_off in ((0, 499_000), (1, 498_999),
                               (250_000, 250_000), (249_500, 250_499),
                               (498_999, 10**7), (-10**6, 500)):
            _assert_modes_equal(monkeypatch, store, "clip",
                                BASE + lo_off, BASE + hi_off)

    def test_dedup_boundaries_ties_and_stale(self, store, monkeypatch):
        """Interval dedup across exact window multiples, equal-timestamp
        ties (max non-stale value must win), staleness markers, and
        replica-style exact duplicates — with and without dedup."""
        rows = []
        for i in range(5):
            lbl = {"__name__": "dd", "i": str(i)}
            for j in range(120):
                ts = BASE + j * 500  # 2 samples per 1000ms window
                rows.append((lbl, ts, float(j)))
            # equal-ts ties: higher value later AND earlier (both orders)
            rows.append((lbl, BASE + 70_000, 5.0))
            rows.append((lbl, BASE + 70_000, 9.0))
            rows.append((lbl, BASE + 71_000, 9.0))
            rows.append((lbl, BASE + 71_000, 5.0))
            # stale-marker tie: non-stale must win
            rows.append((lbl, BASE + 72_000, STALE_NAN))
            rows.append((lbl, BASE + 72_000, 3.0))
            # all-stale window
            rows.append((lbl, BASE + 73_000, STALE_NAN))
        store.add_rows(rows)
        store.force_flush()
        # a second overlapping part makes cross-part duplicates
        store.add_rows([({"__name__": "dd", "i": str(i)},
                         BASE + j * 1000, float(2 * j))
                        for i in range(5) for j in range(60)])
        store.force_flush()
        for dedup in (None, 1000, 3000):
            _assert_modes_equal(monkeypatch, store, "dd",
                                BASE - 10, BASE + 80_000, dedup)

    def test_zlib_fallback_parts(self, store, monkeypatch):
        """Parts whose compressed blocks are zlib streams (the minimal-
        container write path): the native kernel must inflate them too."""
        # force the zlib fallback for this ingest (no zstandard, native
        # zstd hidden)
        monkeypatch.setattr(compress, "zstandard", None)
        monkeypatch.setattr(compress, "_native_zstd", False)
        self._ingest_compressible(store, "zl")
        monkeypatch.undo()
        self._check_compressed(store, monkeypatch, "zl")

    def test_zstd_parts(self, store, monkeypatch):
        """Parts whose compressed blocks are zstd frames (python binding
        or the dlopen'd runtime library)."""
        if not compress.zstd_available():
            pytest.skip("no zstd binding in this container")
        self._ingest_compressible(store, "zs")
        self._check_compressed(store, monkeypatch, "zs")

    @staticmethod
    def _ingest_compressible(store, name):
        # highly repetitive deltas -> payloads beat the 12.5% zstd/zlib
        # compression gate, so blocks marshal as type 5/6
        rows = [({"__name__": name, "i": str(i)}, BASE + j * 1000,
                 float(j % 3))
                for i in range(6) for j in range(2000)]
        store.add_rows(rows)
        store.force_flush()

    @staticmethod
    def _check_compressed(store, monkeypatch, name):
        # the matrix is vacuous unless compressed blocks actually exist
        parts = [p for part in store.table._partitions.values()
                 for p in part._file_parts]
        assert parts
        hc_mts = np.concatenate(
            [np.concatenate([p.header_columns()["ts_mt"],
                             p.header_columns()["val_mt"]]) for p in parts])
        assert bool((hc_mts >= 5).any()), "no compressed blocks were written"
        _assert_modes_equal(monkeypatch, store, name, BASE - 1,
                            BASE + 2_000_000)
        _assert_modes_equal(monkeypatch, store, name, BASE + 500_500,
                            BASE + 1_200_499)

    def test_zlib_parts_degrade_without_libz(self, store, monkeypatch):
        """A build whose runtime resolved zstd but NOT zlib (caps==1) must
        route zlib-compressed parts onto the per-block Python fallback —
        same bytes, no crash (both the fused and the split path gates
        consult the per-payload capability check)."""
        monkeypatch.setattr(compress, "zstandard", None)
        monkeypatch.setattr(compress, "_native_zstd", False)
        self._ingest_compressible(store, "nolibz")
        monkeypatch.undo()
        want = _search_digest(store, "nolibz", BASE - 1, BASE + 2_000_000)
        monkeypatch.setattr(native, "decompress_caps", lambda: 1)
        got = _assert_modes_equal(monkeypatch, store, "nolibz", BASE - 1,
                                  BASE + 2_000_000)
        assert got == want

    def test_multiworker_fanout(self, store, monkeypatch):
        """VM_SEARCH_WORKERS>1 fans per-part kernel calls across the pool;
        results must equal the sequential run of either mode."""
        rng = np.random.default_rng(3)
        for part in range(4):
            rows = [({"__name__": "fan", "i": str(i)},
                     BASE + part * 111_000 + j * 1000,
                     float(rng.integers(0, 1000)))
                    for i in range(16) for j in range(200)]
            store.add_rows(rows)
            store.force_flush()
        digests = set()
        for workers in ("1", "4"):
            monkeypatch.setenv("VM_SEARCH_WORKERS", workers)
            digests.add(_assert_modes_equal(monkeypatch, store, "fan",
                                            BASE + 5_500, BASE + 400_000))
        assert len(digests) == 1, "pool fan-out changed the bytes"


class TestKernelInternals:
    def test_part_float_memo_round_trip(self, store, monkeypatch):
        """An unclipped fused fetch memoizes decoded float columns; the
        next (clipped) fetch serves from the memo with identical bytes."""
        monkeypatch.setenv("VM_NATIVE_ASSEMBLE", "1")
        rows = [({"__name__": "memo", "i": str(i)}, BASE + j * 1000,
                 float(i + j)) for i in range(4) for j in range(300)]
        store.add_rows(rows)
        store.force_flush()
        d_cold = _search_digest(store, "memo", BASE - 10**6, BASE + 10**9)
        parts = [p for part in store.table._partitions.values()
                 for p in part._file_parts]
        assert any(p._dec is not None and p._dec[0] == "float"
                   for p in parts), "full fetch did not memoize"
        assert _search_digest(store, "memo", BASE - 10**6,
                              BASE + 10**9) == d_cold
        # clipped fetch from the memo == oracle
        _assert_modes_equal(monkeypatch, store, "memo", BASE + 50_500,
                            BASE + 200_499)

    def test_dedup_rows_kernel_matches_python_loop(self, monkeypatch):
        """vm_dedup_rows vs the assemble() per-row Python loop on crafted
        duplicate/tie/stale rows (incl. a column-sliced view layout)."""
        from victoriametrics_tpu.storage import columnar

        def build():
            rows = np.array([0, 0, 1, 1, 2], np.int64)
            cnts = np.array([3, 4, 2, 3, 6], np.int64)
            ts = np.concatenate([
                [1000, 1500, 2000], [2000, 2500, 2500, 3100],
                [900, 900], [900, 1700, 1700],
                [100, 600, 600, 600, 1100, 1100]]).astype(np.int64)
            vals = np.array([1.0, STALE_NAN, 2.0,
                             5.0, 4.0, STALE_NAN, 7.0,
                             3.0, 1.0,
                             STALE_NAN, STALE_NAN, 2.0,
                             9.0, 1.0, 8.0, STALE_NAN, 4.0, 4.5])
            return rows, cnts, ts, vals

        outs = []
        for use_native in (True, False):
            if not use_native:
                monkeypatch.setattr(
                    "victoriametrics_tpu.native.available", lambda: False)
            rows, cnts, ts, vals = build()
            cols = columnar.assemble(rows, 3, cnts, ts, vals, 0, 10**6,
                                     dedup_interval_ms=1000)
            outs.append((cols.ts.tobytes(), cols.vals.tobytes(),
                         cols.counts.tobytes()))
            monkeypatch.undo()
        assert outs[0] == outs[1]

    def test_fused_phase_attribution(self, store, monkeypatch):
        """Fused queries tick phase="assemble_native"; the split path
        keeps ticking collect/decode — labels never lie about the mode."""
        from victoriametrics_tpu.utils import metrics as metricslib

        def phase(ph):
            return metricslib.REGISTRY.float_counter(
                f'vm_fetch_phase_seconds_total{{phase="{ph}"}}').get()

        store.add_rows([({"__name__": "ph", "i": str(i)},
                         BASE + j * 1000, float(j))
                        for i in range(4) for j in range(100)])
        store.force_flush()
        monkeypatch.setenv("VM_NATIVE_ASSEMBLE", "1")
        before = {p: phase(p) for p in ("collect", "decode",
                                        "assemble_native")}
        _search_digest(store, "ph", BASE, BASE + 10**6)
        assert phase("assemble_native") > before["assemble_native"]
        assert phase("collect") == before["collect"]
        assert phase("decode") == before["decode"]
        monkeypatch.setenv("VM_NATIVE_ASSEMBLE", "0")
        before = {p: phase(p) for p in ("collect", "assemble_native")}
        _search_digest(store, "ph", BASE, BASE + 10**6)
        assert phase("collect") > before["collect"]
        assert phase("assemble_native") == before["assemble_native"]

    def test_dec_budget_balanced_under_concurrency(self, tmp_path):
        """The global decode-memo budget must return to its baseline after
        concurrent fused fetches + part closes (the satellite fix: the
        budget seam is a locktrace lock now)."""
        import threading

        from victoriametrics_tpu.storage import part as part_mod
        st = Storage(str(tmp_path / "b"))
        try:
            for p in range(3):
                st.add_rows([({"__name__": "bud", "i": str(i)},
                              BASE + p * 50_000 + j * 1000, float(j))
                             for i in range(8) for j in range(50)])
                st.force_flush()
            with part_mod._dec_budget_lock:
                base_used = part_mod._dec_budget_used
            errs = []

            def fetch():
                try:
                    for _ in range(10):
                        _search_digest(st, "bud", BASE - 10**6, BASE + 10**9)
                except BaseException as e:  # noqa: BLE001 — test harness
                    errs.append(e)

            ths = [threading.Thread(target=fetch) for _ in range(4)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout=60)
            assert not errs, errs
        finally:
            st.close()
        # closing the storage released every memo the fetches built
        with part_mod._dec_budget_lock:
            assert part_mod._dec_budget_used == base_used