"""Cost accounting + continuous profiler (the cost-and-profile
observability plane): CostTracker semantics, the sampling profiler's
bounded aggregates and renderings, and the vmsingle HTTP surfaces
(/api/v1/status/{usage,profile}, cost columns in top/slow queries)."""

import json
import threading
import time

import numpy as np
import pytest

from tests.apptest_helpers import Client
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.utils import costacc, profiler
from victoriametrics_tpu.utils.costacc import CostTracker, TenantUsage

T0 = 1_753_700_000_000
STEP = 60_000


@pytest.fixture()
def store(tmp_path):
    from victoriametrics_tpu.storage.storage import Storage
    s = Storage(str(tmp_path / "s"))
    rows = []
    for i in range(16):
        lab = {"__name__": "cm", "idx": str(i)}
        for j in range(40):
            rows.append((lab, T0 - 600_000 + j * 15_000, float(i + j)))
    s.add_rows(rows)
    s.force_flush()
    yield s
    s.close()


# -- CostTracker ----------------------------------------------------------

class TestCostTracker:
    def test_eval_accounts_samples_bytes_and_phases(self, store):
        ec = EvalConfig(start=T0 - 300_000, end=T0, step=STEP,
                        storage=store)
        rows = exec_query(ec, "sum(rate(cm[5m]))")
        assert len(rows) == 1
        s = ec.cost.summary()
        # samples must agree with the established accumulator
        assert s["samplesScanned"] == ec.samples_scanned > 0
        # bytes read = ts + value column bytes of the fetch
        assert s["bytesRead"] > 0
        # the phase buckets hold the fetch/rollup laps, CPU <= wall
        assert any(k.startswith("fetch:") for k in s["wallMsByPhase"])
        for k, cpu in s["cpuMsByPhase"].items():
            assert cpu <= s["wallMsByPhase"][k] + 1e-6, k
        assert s["cpuMs"] > 0

    def test_children_share_one_tracker(self):
        ec = EvalConfig(start=T0, end=T0 + STEP, step=STEP)
        child = ec.child(start=T0 + STEP)
        assert child._cost is ec._cost
        child._cost.add_samples(7)
        assert ec.cost.summary()["samplesScanned"] == 7

    def test_lap_cpu_clamped_to_wall(self):
        tr = CostTracker()
        tr.lap("b", 0.010, 0.500)  # stale CPU stamp: clamp to the wall
        s = tr.summary()
        assert s["cpuMsByPhase"]["b"] <= s["wallMsByPhase"]["b"]

    def test_merge_remote_none_degrades_to_partial(self):
        tr = CostTracker()
        tr.merge_remote({"samples": 5, "partBytes": 80,
                         "cpuMs": {"fetch:rollup": 1.5}})
        tr.merge_remote(None)  # an old node shipped no cost frame
        s = tr.summary()
        assert s["storageSamplesScanned"] == 5
        assert s["bytesRead"] == 80
        assert s["costPartial"] is True
        assert tr.remote_nodes == 1

    def test_tls_current_propagates_through_workpool(self):
        from victoriametrics_tpu.utils import workpool
        tr = CostTracker()
        prev = costacc.set_current(tr)
        try:
            workpool.POOL.run(
                [lambda: costacc.add_part_bytes(10) for _ in range(4)])
        finally:
            costacc.set_current(prev)
        assert tr.part_bytes == 40


class TestTenantUsage:
    def test_bounded_sticky_folding(self):
        tu = TenantUsage(max_tenants=2)
        t = CostTracker()
        t.add_samples(3)
        tu.record((0, 0), t)
        tu.record((1, 0), t)
        for acc in range(2, 30):  # past the cap: fold into "other"
            tu.record((acc, 0), t)
        snap = tu.snapshot()
        tenants = {r["tenant"] for r in snap}
        assert tenants == {"0:0", "1:0", "other"}
        other = next(r for r in snap if r["tenant"] == "other")
        assert other["queries"] == 28
        # sticky: a seen tenant keeps its own row after the fold began
        tu.record((1, 0), t)
        assert next(r for r in tu.snapshot()
                    if r["tenant"] == "1:0")["queries"] == 2

    def test_snapshot_reset_is_atomic_and_clears(self):
        tu = TenantUsage()
        t = CostTracker()
        t.add_samples(5)
        tu.record((0, 0), t)
        rows = tu.snapshot(reset=True)
        assert rows and rows[0]["samplesScanned"] == 5
        assert tu.snapshot() == []  # cleared in the same lock hold

    def test_record_accepts_prebuilt_summary_without_mutation(self):
        tu = TenantUsage()
        t = CostTracker()
        t.add_samples(3)
        s = t.summary()
        tu.record((0, 0), t, summary=s)
        assert "queries" not in s  # caller's dict not mutated
        assert tu.snapshot()[0]["samplesScanned"] == 3

    def test_remote_wall_merge_keeps_local_leftover_baseline(self):
        """Merged remote laps accrue CONCURRENTLY across nodes and may
        sum past local wall; the eval:other/serve:other leftover must
        subtract from the LOCAL lap total only, or a fan-out query's
        glue time silently vanishes."""
        tr = CostTracker()
        tr.lap("fetch:rollup", 0.010, 0.010)
        tr.merge_remote({"wallMs": {"fetch:assemble_native": 500.0}})
        assert tr.wall_ms_total() > 500
        assert tr.local_wall_ms_total() == pytest.approx(10.0)

    def test_usage_metrics_exported(self):
        from victoriametrics_tpu.utils import metrics as metricslib
        tu = TenantUsage()
        t = CostTracker()
        t.add_samples(11)
        tu.record((3, 9), t)
        text = metricslib.REGISTRY.write_prometheus()
        assert 'vm_tenant_usage_samples_scanned_total{tenant="3:9"} 11' \
            in text
        assert 'vm_tenant_usage_queries_total{tenant="3:9"} 1' in text


# -- profiler -------------------------------------------------------------

class TestProfiler:
    def test_hz_zero_is_a_no_thread_no_op(self, monkeypatch):
        monkeypatch.setenv("VM_PROFILE_HZ", "0")
        p = profiler.SampleProfiler()
        assert p.ensure_started() is False
        assert not p.running()
        assert not any(t.name == "vm-profiler"
                       for t in threading.enumerate())

    def test_sample_rate_accounting(self, monkeypatch):
        monkeypatch.setenv("VM_PROFILE_HZ", "100")
        p = profiler.SampleProfiler()
        assert p.ensure_started()
        try:
            time.sleep(0.3)
            snap = p.snapshot()
        finally:
            p.stop()
        # 0.3s at 100Hz: allow wide margins for CI noise, but the
        # sampler must neither stall nor spin
        assert 5 <= snap["samples"] <= 60
        assert 10 <= snap["approxHz"] <= 150
        assert snap["configuredHz"] == 100

    def test_take_sample_folds_by_role(self):
        p = profiler.SampleProfiler()
        n = p.take_sample()
        assert n >= 1  # at least this thread
        snap = p.snapshot()
        roles = {r["role"] for r in snap["stacks"]}
        assert "MainThread" in roles
        # stacks are root->leaf frame labels "file.py:func"
        row = next(r for r in snap["stacks"] if r["role"] == "MainThread")
        assert all(":" in f for f in row["stack"])

    def test_bounded_stacks_with_overflow_bucket(self, monkeypatch):
        monkeypatch.setenv("VM_PROFILE_MAX_STACKS", "16")
        p = profiler.SampleProfiler()
        for i in range(50):
            p._ingest("roleA", (f"f{i}:x",))
        snap = p.snapshot()
        assert len(snap["stacks"]) <= 17  # cap + the (other) bucket
        other = [r for r in snap["stacks"] if r["stack"] == ["(other)"]]
        assert other and other[0]["count"] == 50 - 16
        assert snap["droppedStacks"] == 50 - 16

    def test_thread_role_normalization(self):
        assert profiler.thread_role("vm-workpool-3") == "vm-workpool"
        assert profiler.thread_role("Thread-12 (process_request_thread)") \
            == "process_request_thread"
        assert profiler.thread_role("MainThread") == "MainThread"

    def test_speedscope_shape(self):
        p = profiler.SampleProfiler()
        p._ingest("r1", ("a.py:f", "b.py:g"))
        p._ingest("r1", ("a.py:f",))
        p._ingest("r2", ("c.py:h",))
        doc = profiler.speedscope([p.snapshot()])
        assert doc["$schema"].startswith("https://www.speedscope.app/")
        assert {f["name"] for f in doc["shared"]["frames"]} == \
            {"a.py:f", "b.py:g", "c.py:h"}
        assert {pr["name"] for pr in doc["profiles"]} == {"r1", "r2"}
        for pr in doc["profiles"]:
            assert pr["type"] == "sampled"
            assert len(pr["samples"]) == len(pr["weights"])
            assert pr["endValue"] == sum(pr["weights"])
            for s in pr["samples"]:
                assert all(0 <= i < len(doc["shared"]["frames"])
                           for i in s)

    def test_collapsed_merges_node_tags(self):
        s1 = {"node": None,
              "stacks": [{"role": "r", "stack": ["a:f"], "count": 2}]}
        s2 = {"node": "n1",
              "stacks": [{"role": "r", "stack": ["a:f"], "count": 3}]}
        text = profiler.collapsed([s1, s2])
        assert "r;a:f 2" in text
        assert "n1/r;a:f 3" in text


# -- HTTP surfaces (vmsingle) ---------------------------------------------

@pytest.fixture()
def app(tmp_path, monkeypatch):
    monkeypatch.setenv("VM_PROFILE_HZ", "50")
    from victoriametrics_tpu.apps.vmsingle import build, parse_flags
    args = parse_flags([f"-storageDataPath={tmp_path}/data",
                        "-httpListenAddr=127.0.0.1:0"])
    storage, srv, api = build(args)
    srv.start()
    yield Client(srv.port), api
    srv.stop()
    storage.close()
    profiler.PROFILER.stop()


def _seed(client, n=6):
    from victoriametrics_tpu.ingest import remote_write
    series = []
    for i in range(n):
        series.append(([("__name__", "hm"), ("idx", str(i))],
                       [(T0 + j * 15_000, float(i + j))
                        for j in range(40)]))
    body = remote_write.build_write_request(series)
    code, resp = client.post("/api/v1/write", body,
                             headers={"Content-Encoding": "snappy"})
    assert code == 204, resp


class TestHTTPSurfaces:
    def test_usage_endpoint_accumulates_per_tenant(self, app):
        client, _ = app
        costacc.TENANT_USAGE.reset()
        _seed(client)
        res = client.query_range("sum(rate(hm[5m]))", T0 / 1e3,
                                 (T0 + 300_000) / 1e3, 60)
        assert res["status"] == "success"
        code, body = client.get("/api/v1/status/usage")
        assert code == 200
        data = json.loads(body)["data"]["tenants"]
        row = next(r for r in data if r["tenant"] == "0:0")
        assert row["queries"] >= 1
        assert row["samplesScanned"] > 0
        assert row["bytesRead"] > 0
        assert row["rowsReturned"] >= 1

    def test_top_queries_cost_columns_and_sort(self, app):
        client, _ = app
        _seed(client)
        client.query_range("sum(rate(hm[5m]))", T0 / 1e3,
                           (T0 + 300_000) / 1e3, 60)
        client.query_range("hm", T0 / 1e3, (T0 + 300_000) / 1e3, 60)
        code, body = client.get("/api/v1/status/top_queries")
        assert code == 200
        doc = json.loads(body)
        assert "topBySumCpuMs" in doc and "topBySumSamplesScanned" in doc
        by_cost = doc["topBySumSamplesScanned"]
        assert by_cost and by_cost[0]["sumSamplesScanned"] > 0
        assert "sumCpuMs" in by_cost[0] and "sumBytesRead" in by_cost[0]
        # ordering: descending by the cost key
        vals = [r["sumSamplesScanned"] for r in by_cost]
        assert vals == sorted(vals, reverse=True)

    def test_slow_query_log_carries_cost(self, app, monkeypatch):
        client, api = app
        _seed(client)
        monkeypatch.setenv("VM_SLOW_QUERY_MS", "0.0001")
        client.query_range("sum(rate(hm[5m]))", T0 / 1e3,
                           (T0 + 300_000) / 1e3, 60)
        code, body = client.get("/api/v1/status/slow_queries")
        assert code == 200
        recs = json.loads(body)["data"]
        assert recs
        cost = recs[0].get("cost")
        assert cost and cost["samplesScanned"] > 0
        assert cost["rowsReturned"] >= 1

    def test_profile_endpoint_formats(self, app):
        client, _ = app
        time.sleep(0.15)  # let the sampler tick a few times
        code, body = client.get("/api/v1/status/profile")
        assert code == 200
        assert b";" in body  # folded lines "role;frame;... count"
        code, body = client.get("/api/v1/status/profile",
                                format="speedscope")
        assert code == 200
        doc = json.loads(body)
        assert doc["profiles"] and doc["shared"]["frames"]
        code, body = client.get("/api/v1/status/profile", format="raw")
        assert code == 200
        snaps = json.loads(body)["data"]
        assert snaps and snaps[0]["samples"] > 0

    def test_profile_disabled_answers_503(self, app, monkeypatch):
        client, _ = app
        monkeypatch.setenv("VM_PROFILE_HZ", "0")
        code, body = client.get("/api/v1/status/profile")
        assert code == 503


class TestProfileOverheadSmoke:
    def test_smoke_runs_and_passes_loose_budget(self):
        # the lint.sh gate runs at 2%; the tier-1 copy only asserts the
        # harness works (a loaded CI box must not flake the suite)
        from victoriametrics_tpu.devtools.profile_overhead import run_smoke
        res = run_smoke(max_delta_pct=50.0, retries=1)
        assert res["ok"], res
