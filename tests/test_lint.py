"""Lint-engine tests: each rule id fires on a known-bad fixture, stays
quiet on a known-good one, suppressions and the baseline ratchet work,
and the real package is clean against the checked-in baseline (this is
the tier-1 wiring of `python -m victoriametrics_tpu.devtools.lint`)."""

import os

import pytest

from victoriametrics_tpu.devtools import lint
from victoriametrics_tpu.devtools.lint import (lint_paths, lint_source,
                                               load_baseline, new_findings)

# (rule, bad snippet that must fire exactly there, good twin that must not)
FIXTURES = {
    "VMT001": (
        "import time\n"
        "def stamp(rows):\n"
        "    now = int(time.time() * 1000)\n"
        "    return [(now, r) for r in rows]\n",
        "from victoriametrics_tpu.utils import fasttime\n"
        "import time\n"
        "def stamp(rows):\n"
        "    now = fasttime.unix_ms()\n"
        "    t0 = time.monotonic()  # monotonic is fine\n"
        "    return [(now, r) for r in rows], t0\n",
    ),
    "VMT002": (
        "def fetch(url, _memo={}):\n"
        "    return _memo.setdefault(url, url.upper())\n",
        "_MEMO = {}\n"
        "def fetch(url, timeout=10, tags=()):\n"
        "    return _MEMO.setdefault(url, url.upper())\n",
    ),
    "VMT003": (
        "def load(path):\n"
        "    try:\n"
        "        return open(path).read()\n"
        "    except Exception:\n"
        "        pass\n",
        "def load(path):\n"
        "    try:\n"
        "        return open(path).read()\n"
        "    except (OSError, ValueError) as e:\n"
        "        log(e)\n"
        "    except ValueError:\n"
        "        pass  # narrow except-pass is idiomatic control flow\n",
    ),
    "VMT004": (
        "import time\n"
        "class Q:\n"
        "    def drain(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.1)\n",
        "import time\n"
        "class Q:\n"
        "    def drain(self):\n"
        "        with self._lock:\n"
        "            items = list(self._items)\n"
        "        time.sleep(0.1)\n"
        "    def reload(self):\n"
        "        def later():\n"
        "            time.sleep(1)  # runs outside the critical section\n"
        "        with self._lock:\n"
        "            self._cb = later\n",
    ),
    "VMT005": (
        "class C:\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def reset(self):\n"
        "        self.n = 0\n",
        "class C:\n"
        "    def __init__(self):\n"
        "        self.n = 0  # __init__ is single-threaded\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def reset(self):\n"
        "        with self._lock:\n"
        "            self._reset_locked()\n"
        "    def _reset_locked(self):\n"
        "        self.n = 0\n",
    ),
    "VMT006": (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def rollup(x):\n"
        "    return float(np.asarray(x).sum())\n",
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def rollup(x):\n"
        "    return x.sum()\n"
        "def host_side(x):\n"
        "    return float(np.asarray(x).sum())  # not traced: fine\n",
    ),
    "VMT007": (
        "class Ingestor:\n"
        "    def push(self, rows):\n"
        "        self.rows_pushed_total += 1\n"
        "        self.errors += len(rows)\n",
        "from victoriametrics_tpu.utils import metrics as metricslib\n"
        "class Ingestor:\n"
        "    def push(self, rows):\n"
        "        metricslib.REGISTRY.counter(\n"
        "            'vm_rows_pushed_total').inc()\n"
        "        self.batch_size += len(rows)  # not a counter name\n"
        "        total = 0\n"
        "        total += 1  # plain local accumulator: fine\n",
    ),
    "VMT008": (
        "import threading\n"
        "def serve(fns, names):\n"
        "    banner = ','.join(names)  # str.join must not suppress\n"
        "    for fn in fns:\n"
        "        threading.Thread(target=fn).start()\n",
        "import threading\n"
        "def serve(fns):\n"
        "    ts = [threading.Thread(target=fn) for fn in fns]\n"
        "    for t in ts:\n"
        "        t.start()\n"
        "    for t in ts:\n"
        "        t.join()\n"
        "def background(fn):\n"
        "    threading.Thread(target=fn, daemon=True).start()\n",
    ),
    "VMT009": (
        "class Node:\n"
        "    def mark(self):\n"
        "        with self._lock:\n"
        "            self.healthy = False\n"
        "def poke(node):\n"
        "    node.healthy = True\n",
        "class Node:\n"
        "    def mark(self):\n"
        "        with self._lock:\n"
        "            self.healthy = False\n"
        "def poke(node, lock):\n"
        "    with lock:\n"
        "        node.healthy = True\n"
        "def poke_locked(node):\n"
        "    node.healthy = True  # *_locked: caller holds the lock\n",
    ),
    "VMT010": (
        "import queue\n"
        "def drain(q):\n"
        "    try:\n"
        "        return q.get(timeout=1.0)\n"
        "    except queue.Empty:\n"
        "        pass\n",
        "import queue\n"
        "def drain(q, log):\n"
        "    try:\n"
        "        return q.get(timeout=1.0)\n"
        "    except queue.Empty:\n"
        "        log('drain starved for 1s')\n"
        "    try:\n"
        "        return q.get()\n"
        "    except queue.Empty:\n"
        "        pass  # no timeout in play: interrupted blocking get\n",
    ),
    "VMT011": (
        "import threading\n"
        "def fetch_parts(parts):\n"
        "    ts = [threading.Thread(target=p.decode, daemon=True)\n"
        "          for p in parts]\n"
        "    for t in ts:\n"
        "        t.start()\n"
        "    for t in ts:\n"
        "        t.join()\n",
        "from functools import partial\n"
        "from victoriametrics_tpu.utils import workpool\n"
        "def fetch_parts(parts):\n"
        "    return workpool.POOL.run(\n"
        "        [partial(p.decode) for p in parts])\n",
    ),
}


def test_vmt011_exempts_devtools_and_apps_paths():
    """Long-lived service threads live in devtools/ and apps/; the rule
    keys off the file path, so the same source is clean there."""
    bad, _ = FIXTURES["VMT011"]
    for rel in ("victoriametrics_tpu/devtools/sched_helper.py",
                "victoriametrics_tpu/apps/vmworker.py"):
        found = {f.rule for f in lint_source(bad, rel)}
        assert "VMT011" not in found, rel


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_fires_on_bad_fixture(rule):
    bad, _ = FIXTURES[rule]
    found = {f.rule for f in lint_source(bad, f"fixture_{rule}_bad.py")}
    assert rule in found, f"{rule} did not fire on its bad fixture"


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_quiet_on_good_fixture(rule):
    _, good = FIXTURES[rule]
    found = [f for f in lint_source(good, f"fixture_{rule}_good.py")
             if f.rule == rule]
    assert not found, f"false positives: {[str(f) for f in found]}"


def test_inline_suppression_silences_only_that_line_and_rule():
    src = ("import time\n"
           "a = time.time()  # vmt: disable=VMT001\n"
           "b = time.time()\n")
    found = lint_source(src, "supp.py")
    assert [(f.rule, f.line) for f in found] == [("VMT001", 3)]


def test_baseline_ratchet(tmp_path):
    src = "import time\na = time.time()\nb = time.time()\n"
    findings = lint_source(src, str(tmp_path / "mod.py"))
    assert len(findings) == 2
    bl = tmp_path / "baseline.txt"
    lint.write_baseline(str(bl), findings)
    # grandfathered: nothing new
    assert new_findings(findings, load_baseline(str(bl))) == []
    # one more hit in the same file exceeds the baselined count
    worse = lint_source(src + "c = time.time()\n", str(tmp_path / "mod.py"))
    assert len(new_findings(worse, load_baseline(str(bl)))) == 3


def test_package_is_clean_against_checked_in_baseline():
    """The canonical tier-1 invariant: linting the real package against
    devtools/lint_baseline.txt yields zero new findings."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(lint.__file__)))
    findings = lint_paths([pkg])
    assert not any(f.rule == "VMT000" for f in findings), "syntax errors?!"
    baseline = load_baseline(lint.DEFAULT_BASELINE)
    fresh = new_findings(findings, baseline)
    assert fresh == [], "new lint findings:\n" + \
        "\n".join(str(f) for f in fresh)


def test_stale_baseline_entries_fail_with_exit_3(tmp_path, capsys):
    """A baseline entry whose findings were fixed is slack in the ratchet
    (it could hide that many regressions); the CLI must fail distinctly
    (exit 3) until the baseline is regenerated."""
    mod = tmp_path / "mod.py"
    mod.write_text("import time\na = time.time()\n")
    bl = tmp_path / "baseline.txt"
    findings = lint.lint_paths([str(mod)])
    lint.write_baseline(str(bl), findings)
    assert lint.main([str(mod), "--baseline", str(bl)]) == 0
    # fix the finding; the baselined count is now stale
    mod.write_text("import time\na = time.monotonic()\n")
    rc = lint.main([str(mod), "--baseline", str(bl)])
    assert rc == 3
    err = capsys.readouterr().err
    assert "BASELINE STALE" in err and "--update-baseline" in err
    # new findings still win over staleness (exit 1 beats exit 3)
    mod.write_text("import time\na = time.time()\nb = time.time()\n"
                   "c = eval('1')  # vmt: disable=VMT001\n")
    assert lint.main([str(mod), "--baseline", str(bl)]) == 1
    # regenerating clears it
    lint.write_baseline(str(bl), lint.lint_paths([str(mod)]))
    assert lint.main([str(mod), "--baseline", str(bl)]) == 0


def test_cli_main_exits_zero_on_clean_tree():
    assert lint.main([]) == 0


def test_cli_lists_all_rules(capsys):
    assert lint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in sorted(FIXTURES):
        assert rid in out


# -- VMT013: stale suppressions ---------------------------------------------

def _ctxs_for(tmp_path, src):
    mod = tmp_path / "mod.py"
    mod.write_text(src, encoding="utf-8")
    ctxs: list = []
    findings = lint.lint_paths([str(mod)], collect_ctxs=ctxs)
    return findings, ctxs


def test_vmt013_flags_disable_that_silenced_nothing(tmp_path):
    findings, ctxs = _ctxs_for(
        tmp_path,
        "import time\n"
        "a = time.monotonic()  # vmt: disable=VMT001\n")
    assert findings == []
    stale = lint.stale_disable_findings(ctxs)
    assert [(f.rule, f.line) for f in stale] == \
        [(lint.STALE_DISABLE_RULE, 2)]
    assert "VMT001" in stale[0].message


def test_vmt013_quiet_when_disable_is_consumed(tmp_path):
    findings, ctxs = _ctxs_for(
        tmp_path,
        "import time\n"
        "a = time.time()  # vmt: disable=VMT001\n")
    assert findings == []  # suppression ate the VMT001 finding
    assert lint.stale_disable_findings(ctxs) == []


def test_vmt013_ignores_disable_text_inside_strings(tmp_path):
    """Suppressions come from real COMMENT tokens; a docstring that
    *mentions* the syntax (e.g. the lint module's own docs) is inert —
    neither a suppression nor a stale-suppression finding."""
    findings, ctxs = _ctxs_for(
        tmp_path,
        '"""usage: add  # vmt: disable=VMT001  to the line."""\n'
        "import time\n"
        "a = time.time()\n")
    assert [f.rule for f in findings] == ["VMT001"]  # NOT suppressed
    assert lint.stale_disable_findings(ctxs) == []


def test_vmt013_judges_only_rules_that_ran(tmp_path):
    """A path-scoped lint run doesn't execute the program passes, so a
    VMT012 disable can't be proven stale there — it must not be flagged
    unless VMT012 is in ran_rules (or consumed via extra_used)."""
    _findings, ctxs = _ctxs_for(
        tmp_path,
        "import time\n"
        "time.sleep(1)  # vmt: disable=VMT012\n")
    ran = {r.rule_id for r in lint.all_rules()}
    assert lint.stale_disable_findings(ctxs, ran_rules=ran) == []
    # when the pass DID run and consumed it, extra_used clears it too
    rel = ctxs[0].rel_path
    ran_all = ran | {"VMT012"}
    assert lint.stale_disable_findings(
        ctxs, extra_used={rel: {(2, "VMT012")}}, ran_rules=ran_all) == []
    # ...and with the pass run but nothing consumed, it IS stale
    stale = lint.stale_disable_findings(ctxs, ran_rules=ran_all)
    assert [f.rule for f in stale] == [lint.STALE_DISABLE_RULE]


# -- VMT014: env-flag inventory vs README -----------------------------------

def test_vmt014_fires_on_undocumented_flag(tmp_path):
    _findings, ctxs = _ctxs_for(
        tmp_path,
        "import os\n"
        'w = os.environ.get("VM_NOT_DOCUMENTED_XYZ", "0")\n')
    flagged = lint.env_flag_findings(ctxs)
    assert [f.rule for f in flagged] == [lint.ENV_FLAG_RULE]
    assert "VM_NOT_DOCUMENTED_XYZ" in flagged[0].message


def test_vmt014_quiet_on_documented_flag(tmp_path):
    _findings, ctxs = _ctxs_for(
        tmp_path,
        "import os\n"
        'w = os.environ.get("VM_SEARCH_WORKERS", "0")\n')
    assert lint.env_flag_findings(ctxs) == []


def test_vmt014_rule_ids_do_not_look_like_flags():
    """The flag regex must not mistake rule ids (VMT012) or prose tokens
    for env flags."""
    assert lint._FLAG_RE.match("VM_SEARCH_WORKERS")
    assert lint._FLAG_RE.match("VMT_NO_CRASH_SMOKE")
    assert not lint._FLAG_RE.match("VMT012")
    assert not lint._FLAG_RE.match("VM_")
    assert not lint._FLAG_RE.match("XVM_FOO")


def test_package_flag_inventory_is_fully_documented():
    """Every VM_*/VMT_* flag read anywhere in the package appears in
    README.md's flag table (the VMT014 invariant, asserted directly)."""
    ctxs: list = []
    lint.lint_paths([os.path.join(lint.REPO_ROOT, "victoriametrics_tpu")],
                    collect_ctxs=ctxs)
    inv = set(lint.env_flag_inventory(ctxs))
    undocumented = sorted(inv - lint.readme_flags())
    assert undocumented == []


def test_cli_list_flags(capsys):
    assert lint.main(["--list-flags"]) == 0
    out = capsys.readouterr().out
    assert "VM_SEARCH_WORKERS" in out
