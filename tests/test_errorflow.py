"""Exception-escape audit tests (devtools/errorflow.py, rule VMT016).

Fixture packages are synthesized in tmp_path with the boundary table
pointed at the fixture's own ``_dispatch``: a project exception type
escaping a serving entry with no typed boundary mapping must be flagged
at its raise site with the witness chain; the mapped / re-raised-as-
typed / swallowed twins must be clean.  The runtime half pins the
boundary behavior VMT016 forced: typed RPC wire markers that re-raise
client-side, and the HTTP 503/502 arms."""

import json
import textwrap

import pytest

from victoriametrics_tpu.devtools import errorflow as ef

# An RPC dispatch dict is recognized as a serving entry when it has
# >= 3 "*_vN" string keys mapping to same-module handler names.
_TAIL = """
        def h_b(r):
            pass

        def h_c(r):
            pass

        HANDLERS = {
            "a_v1": h_a,
            "b_v1": h_b,
            "c_v1": h_c,
        }
"""


def _run(tmp_path, monkeypatch, body: str):
    d = tmp_path / "fixture_pkg"
    d.mkdir()
    p = d / "srv.py"
    p.write_text(textwrap.dedent(body + _TAIL), encoding="utf-8")
    # the fixture module IS the boundary: its _dispatch's top-level
    # except arms are the scanned mapped set
    monkeypatch.setattr(ef, "BOUNDARIES", (("rpc", str(p), "_dispatch"),))
    return ef.run_pass(paths=[str(p)])


def test_unmapped_escape_is_flagged(tmp_path, monkeypatch):
    findings, _used = _run(tmp_path, monkeypatch, """
        class AppError(Exception):
            pass

        class MappedError(Exception):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except MappedError as e:
                return ("mapped", str(e))

        def helper():
            raise AppError("boom")

        def h_a(r):
            helper()
    """)
    assert len(findings) == 1, [f.message for f in findings]
    f = findings[0]
    assert f.rule == ef.RULE_ID
    assert "AppError" in f.message and "rpc boundary" in f.message
    # witness chain: entry -> ... -> origin
    assert "h_a -> helper" in f.message
    # anchored at the raise site, not the entry
    assert "raise AppError" in open(f.path).read().splitlines()[f.line - 1]


def test_boundary_mapping_retires_the_finding(tmp_path, monkeypatch):
    """Adding the typed except arm at the boundary is the fix — the
    mapped set is scanned from the AST, so the finding retires without
    touching the pass."""
    findings, _used = _run(tmp_path, monkeypatch, """
        class AppError(Exception):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except AppError as e:
                return ("mapped", str(e))

        def helper():
            raise AppError("boom")

        def h_a(r):
            helper()
    """)
    assert findings == [], [f.message for f in findings]


def test_mapping_covers_subclasses(tmp_path, monkeypatch):
    """``except Base`` at the boundary maps every derived type — the
    catch test walks the project class hierarchy."""
    findings, _used = _run(tmp_path, monkeypatch, """
        class AppError(Exception):
            pass

        class SubError(AppError):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except AppError as e:
                return ("mapped", str(e))

        def h_a(r):
            raise SubError("boom")
    """)
    assert findings == [], [f.message for f in findings]


def test_reraise_as_mapped_type_is_clean(tmp_path, monkeypatch):
    """Catching en route and re-raising as an already-mapped type is a
    sanctioned translation, not an escape."""
    findings, _used = _run(tmp_path, monkeypatch, """
        class AppError(Exception):
            pass

        class MappedError(Exception):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except MappedError as e:
                return ("mapped", str(e))

        def helper():
            raise AppError("boom")

        def h_a(r):
            try:
                helper()
            except AppError as e:
                raise MappedError(str(e))
    """)
    assert findings == [], [f.message for f in findings]


def test_swallowed_en_route_is_clean(tmp_path, monkeypatch):
    findings, _used = _run(tmp_path, monkeypatch, """
        class AppError(Exception):
            pass

        class MappedError(Exception):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except MappedError as e:
                return ("mapped", str(e))

        def helper():
            raise AppError("boom")

        def h_a(r):
            try:
                helper()
            except AppError:
                return None
    """)
    assert findings == [], [f.message for f in findings]


def test_ext_raiser_builtin_is_flagged(tmp_path, monkeypatch):
    """json.loads on untrusted bytes raises ValueError — a documented
    external raiser IS flagged (unlike bare project-raised builtins)."""
    findings, _used = _run(tmp_path, monkeypatch, """
        import json

        class MappedError(Exception):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except MappedError as e:
                return ("mapped", str(e))

        def h_a(r):
            return json.loads(r)
    """)
    assert len(findings) == 1, [f.message for f in findings]
    assert "json.loads()" in findings[0].message


def test_bare_builtin_raise_is_not_flagged(tmp_path, monkeypatch):
    """A validator raising ValueError itself is handler-layer 4xx
    territory, not a boundary-contract gap."""
    findings, _used = _run(tmp_path, monkeypatch, """
        class MappedError(Exception):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except MappedError as e:
                return ("mapped", str(e))

        def h_a(r):
            raise ValueError("bad arg")
    """)
    assert findings == [], [f.message for f in findings]


def test_suppressed_raise_site_counts_as_used(tmp_path, monkeypatch):
    findings, used = _run(tmp_path, monkeypatch, """
        class AppError(Exception):
            pass

        class MappedError(Exception):
            pass

        def _dispatch(r):
            try:
                return h_a(r)
            except MappedError as e:
                return ("mapped", str(e))

        def h_a(r):
            raise AppError("ok")  # vmt: disable=VMT016
    """)
    assert findings == [], [f.message for f in findings]
    (rel,) = used
    assert any(rule == ef.RULE_ID for _ln, rule in used[rel])


# -- the real tree's boundary contract --------------------------------------

def test_real_boundaries_map_the_typed_failures():
    """The scanned mapped sets carry the full contract: every typed
    capacity/degradation failure has a non-anonymous arm at both
    boundaries."""
    from victoriametrics_tpu.devtools.callgraph import build_callgraph
    g = build_callgraph(ef._default_paths())
    bounds = ef.boundary_mappings(g)
    http = {k.rpartition("::")[2].rpartition(".")[2]
            for k in bounds["http"]["mapped"]}
    for name in ("RateLimitedError", "SearchLimitError",
                 "ClusterUnavailableError", "PartialResultError",
                 "RPCError"):
        assert name in http, (name, sorted(http))
    rpc = {k.rpartition("::")[2].rpartition(".")[2]
           for k in bounds["rpc"]["mapped"]}
    for name in ("RateLimitedError", "SearchLimitError",
                 "ClusterUnavailableError", "PartialResultError",
                 "RPCError", "DeadlineExceededError"):
        assert name in rpc, (name, sorted(rpc))


def test_repo_tree_is_clean():
    """The real tree carries ZERO baselined VMT016 findings — the
    escapes the pass found got typed mappings (or their invariant
    disables), not a grandfather list."""
    findings, _used = ef.run_pass()
    assert findings == [], [f.message for f in findings]


# -- the runtime fixes VMT016 forced ----------------------------------------

def test_rpc_typed_errors_reraise_client_side():
    """The wire markers VMT016 forced: RateLimitedError crosses as
    vm:rate-limited (retry_after_s preserved), ClusterUnavailableError
    as vm:unavailable, PartialResultError as vm:partial-denied, and a
    generic RPCError still round-trips as exactly RPCError."""
    from victoriametrics_tpu.ingest.ratelimiter import RateLimitedError
    from victoriametrics_tpu.parallel.rpc import (
        HELLO_SELECT, ClusterUnavailableError, PartialResultError,
        RPCClient, RPCError, RPCServer, Writer)

    def h_rate(r):
        raise RateLimitedError(7.2)

    def h_unavail(r):
        raise ClusterUnavailableError("no live storage node")

    def h_partial(r):
        raise PartialResultError("1 of 2 nodes answered")

    def h_generic(r):
        raise RPCError("rpc: truncated bytes field")

    srv = RPCServer("127.0.0.1", 0, HELLO_SELECT,
                    {"rate_v1": h_rate, "unavail_v1": h_unavail,
                     "partial_v1": h_partial, "generic_v1": h_generic})
    srv.start()
    c = RPCClient("127.0.0.1", srv.port, HELLO_SELECT, timeout=30.0)
    try:
        with pytest.raises(RateLimitedError) as ei:
            c.call("rate_v1", Writer())
        assert ei.value.retry_after_s == 8  # ceil(7.2)
        with pytest.raises(ClusterUnavailableError) as ei:
            c.call("unavail_v1", Writer())
        assert "no live storage node" in str(ei.value)
        with pytest.raises(PartialResultError) as ei:
            c.call("partial_v1", Writer())
        assert "1 of 2 nodes" in str(ei.value)
        with pytest.raises(RPCError) as ei:
            c.call("generic_v1", Writer())
        assert type(ei.value) is RPCError
        assert "truncated bytes" in str(ei.value)
    finally:
        c.close()
        srv.stop()


def test_http_boundary_maps_cluster_errors():
    """The HTTP arms VMT016 forced: ClusterUnavailableError -> 503
    "unavailable" (capacity: retry elsewhere/later), PartialResultError
    -> 503, RPCError -> 502 "storage_rpc" (bad backend, not a serving
    bug) — never the anonymous 500."""
    from tests.apptest_helpers import Client
    from victoriametrics_tpu.httpapi.server import HTTPServer
    from victoriametrics_tpu.parallel.rpc import (ClusterUnavailableError,
                                                  PartialResultError,
                                                  RPCError)

    srv = HTTPServer(port=0)
    srv.route("/boom/unavail",
              lambda req: (_ for _ in ()).throw(
                  ClusterUnavailableError("no node")))
    srv.route("/boom/partial",
              lambda req: (_ for _ in ()).throw(
                  PartialResultError("denied")))
    srv.route("/boom/rpc",
              lambda req: (_ for _ in ()).throw(
                  RPCError("peer hung up")))
    srv.start()
    cli = Client(srv.port)
    try:
        code, body = cli.get("/boom/unavail")
        assert code == 503, body
        assert json.loads(body)["errorType"] == "unavailable"
        code, body = cli.get("/boom/partial")
        assert code == 503, body
        assert json.loads(body)["errorType"] == "unavailable"
        code, body = cli.get("/boom/rpc")
        assert code == 502, body
        assert json.loads(body)["errorType"] == "storage_rpc"
    finally:
        srv.stop()
