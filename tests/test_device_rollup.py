"""Device rollup kernels vs the NumPy oracle, including the sharded mesh
paths on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from victoriametrics_tpu.ops import rollup_np
from victoriametrics_tpu.ops.device_rollup import (
    aggregate_groups, pack_series, rollup_aggregate_tile, rollup_tile)
from victoriametrics_tpu.ops.rollup_np import RollupConfig
from victoriametrics_tpu.parallel import mesh as meshlib

START = 1_753_700_000_000  # unix ms


def make_series(rng, n, kind="gauge", interval=15_000, jitter=True):
    ts = np.arange(n, dtype=np.int64) * interval + START
    if jitter:
        ts = ts + rng.integers(-2000, 2000, n)
        ts.sort()
    if kind == "gauge":
        v = np.round(rng.uniform(0, 100, n), 3)
    elif kind == "counter":
        v = np.cumsum(rng.integers(0, 50, n)).astype(np.float64)
    elif kind == "counter_resets":
        v = np.cumsum(rng.integers(0, 50, n)).astype(np.float64)
        for p in rng.integers(1, n, 3):
            v[p:] -= v[p]  # hard reset to 0 at p
        v = np.abs(v)
    return ts, v


CFG = RollupConfig(start=START + 600_000, end=START + 1_800_000,
                   step=60_000, window=300_000)

FUNCS = list(rollup_np.CORE_SUPPORTED)


@pytest.fixture(scope="module")
def ragged_data():
    rng = np.random.default_rng(11)
    series = []
    for i in range(17):
        kind = ("gauge", "counter", "counter_resets")[i % 3]
        n = int(rng.integers(3, 200))
        series.append(make_series(rng, n, kind))
    # edge cases: single sample, two samples, empty-window series (all before
    # query range), sparse series with big gaps
    series.append((np.array([START + 700_000]), np.array([42.0])))
    series.append((np.array([START + 700_000, START + 710_000]),
                   np.array([1.0, 5.0])))
    series.append((np.array([START - 50_000]), np.array([7.0])))
    sp_ts = np.array([START, START + 900_000, START + 1_700_000])
    series.append((sp_ts, np.array([1.0, 100.0, 3.0])))
    return series


@pytest.mark.parametrize("func", FUNCS)
def test_rollup_matches_oracle(ragged_data, func):
    series = ragged_data
    ts, vals, counts = pack_series(series, CFG.start)
    got = np.asarray(rollup_tile(func, jnp.asarray(ts), jnp.asarray(vals),
                                 jnp.asarray(counts), CFG))
    # stddev/stdvar use prefix-sum moments: ~1e-8 absolute noise relative to
    # the data scale (exactly-zero variances come back ~1e-7); all other
    # funcs must match the oracle to fp association order.
    atol = 1e-4 if func.startswith("std") else 1e-9
    for i, (s_ts, s_v) in enumerate(series):
        want = rollup_np.rollup(func, s_ts, s_v, CFG)
        np.testing.assert_allclose(
            got[i], want, rtol=1e-6 if func.startswith("std") else 1e-9,
            atol=atol, equal_nan=True, err_msg=f"series {i} func {func}")


@pytest.mark.parametrize("aggr", ["sum", "count", "avg", "min", "max", "stddev"])
def test_aggregate_groups_matches_numpy(ragged_data, aggr):
    series = ragged_data
    ts, vals, counts = pack_series(series, CFG.start)
    S = len(series)
    rng = np.random.default_rng(5)
    gids = rng.integers(0, 4, S).astype(np.int32)
    rolled = np.asarray(rollup_tile("rate", jnp.asarray(ts), jnp.asarray(vals),
                                    jnp.asarray(counts), CFG))
    got = np.asarray(aggregate_groups(aggr, jnp.asarray(rolled),
                                      jnp.asarray(gids), 4))
    T = rolled.shape[1]
    want = np.full((4, T), np.nan)
    for g in range(4):
        rows = rolled[gids == g]
        for t in range(T):
            col = rows[:, t]
            col = col[~np.isnan(col)]
            if col.size == 0:
                continue
            want[g, t] = dict(
                sum=col.sum(), count=float(col.size), avg=col.mean(),
                min=col.min(), max=col.max(), stddev=col.std())[aggr]
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9, equal_nan=True)


def test_fused_tile_equals_two_stage(ragged_data):
    series = ragged_data
    ts, vals, counts = pack_series(series, CFG.start)
    gids = np.arange(len(series), dtype=np.int32) % 3
    fused = np.asarray(rollup_aggregate_tile(
        "rate", "sum", jnp.asarray(ts), jnp.asarray(vals),
        jnp.asarray(counts), jnp.asarray(gids), CFG, 3))
    rolled = rollup_tile("rate", jnp.asarray(ts), jnp.asarray(vals),
                         jnp.asarray(counts), CFG)
    two = np.asarray(aggregate_groups("sum", rolled, jnp.asarray(gids), 3))
    np.testing.assert_allclose(fused, two, equal_nan=True)


class TestMesh:
    def _data(self, S=32, n=120):
        rng = np.random.default_rng(23)
        series = [make_series(rng, int(rng.integers(5, n)),
                              ("gauge", "counter")[i % 2]) for i in range(S)]
        ts, vals, counts = pack_series(series, CFG.start)
        gids = (np.arange(S) % 5).astype(np.int32)
        return series, ts, vals, counts, gids

    @pytest.mark.parametrize("aggr", ["sum", "avg", "max", "count"])
    def test_series_sharded_matches_single_device(self, aggr):
        series, ts, vals, counts, gids = self._data()
        mesh = meshlib.make_mesh(n_series=8, n_time=1)
        fn = meshlib.sharded_rollup_aggregate(mesh, "rate", aggr, CFG, 5)
        from victoriametrics_tpu.ops.device_rollup import MIN_TS_NONE
        got = np.asarray(fn(jnp.asarray(ts), jnp.asarray(vals),
                            jnp.asarray(counts), jnp.asarray(gids),
                            np.int32(0), MIN_TS_NONE))
        rolled = rollup_tile("rate", jnp.asarray(ts), jnp.asarray(vals),
                             jnp.asarray(counts), CFG)
        want = np.asarray(aggregate_groups(aggr, rolled, jnp.asarray(gids), 5))
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9,
                                   equal_nan=True)

    @pytest.mark.parametrize("func", ["rate", "sum_over_time", "timestamp",
                                      "max_over_time", "changes"])
    def test_time_sharded_matches_single_device(self, func):
        # sequence-parallel: samples split into contiguous time chunks
        rng = np.random.default_rng(31)
        S, N = 8, 512
        interval = 10_000
        ts = np.tile(np.arange(N, dtype=np.int64) * interval, (S, 1))
        vals = np.cumsum(rng.integers(0, 20, (S, N)), axis=1).astype(np.float64)
        cfg = RollupConfig(start=0, end=N * interval - interval,
                           step=interval * 4, window=interval * 8)
        T = (cfg.end - cfg.start) // cfg.step + 1
        assert T % 4 == 0
        mesh = meshlib.make_mesh(n_series=2, n_time=4)
        valid = np.ones((S, N), dtype=bool)
        halo = 16  # > window/interval + 1
        fn = meshlib.time_sharded_rollup(mesh, func, cfg, halo)
        got = np.asarray(fn(jnp.asarray(ts.astype(np.int32)),
                            jnp.asarray(vals), jnp.asarray(valid)))
        counts = np.full(S, N, dtype=np.int32)
        want = np.asarray(rollup_tile(func, jnp.asarray(ts.astype(np.int32)),
                                      jnp.asarray(vals), jnp.asarray(counts),
                                      cfg))
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9,
                                   equal_nan=True)

    def test_time_sharded_rejects_whole_series_funcs(self):
        mesh = meshlib.make_mesh(n_series=2, n_time=4)
        with pytest.raises(ValueError, match="whole-series"):
            meshlib.time_sharded_rollup(mesh, "lifetime", CFG, 8)


class TestDeviceDecode:
    def _series(self, S=24, N=200):
        rng = np.random.default_rng(41)
        out = []
        for i in range(S):
            n = int(rng.integers(3, N))
            ts = np.arange(n, dtype=np.int64) * 15_000 + START + \
                rng.integers(-500, 500, n)
            ts.sort()
            mant = np.cumsum(rng.integers(0, 50, n)).astype(np.int64)
            out.append((ts, mant, -2))
        return out

    @pytest.mark.parametrize("func", ["rate", "sum_over_time",
                                      "max_over_time", "last_over_time"])
    def test_fused_decode_rollup_matches_dense(self, func):
        from victoriametrics_tpu.ops import device_decode as dd
        from victoriametrics_tpu.ops import decimal as dec
        series = self._series()
        planes = dd.pack_delta_planes(series, CFG.start, np.float64)
        assert planes is not None
        # plane compression actually narrows the payload
        dense_bytes = sum(t.size * 12 for t, _, _ in series)
        assert planes.nbytes < dense_bytes / 2
        n = int(planes.counts.max())
        got = np.asarray(dd.decode_and_rollup(
            func, jnp.asarray(planes.ts_first), jnp.asarray(planes.ts_fdelta),
            jnp.asarray(planes.ts_d2), jnp.asarray(planes.val_first),
            jnp.asarray(planes.val_fdelta), jnp.asarray(planes.val_d2),
            jnp.asarray(planes.scale), jnp.asarray(planes.counts),
            CFG, n, np.float64))
        for i, (ts, mant, exp) in enumerate(series):
            vals = dec.decimal_to_float(
                np.pad(mant, (0, 0)), exp) if False else mant * (10.0 ** exp)
            want = rollup_np.rollup(func, ts, vals, CFG)
            np.testing.assert_allclose(got[i], want, rtol=1e-9, atol=1e-9,
                                       equal_nan=True,
                                       err_msg=f"series {i} {func}")

    def test_overflow_falls_back(self):
        from victoriametrics_tpu.ops import device_decode as dd
        series = [(np.array([START, START + 1000], dtype=np.int64),
                   np.array([0, 1 << 40], dtype=np.int64), 0)]
        assert dd.pack_delta_planes(series, CFG.start) is None


class TestRollupBatchVsLoop:
    """rollup_batch must match the per-series rollup() loop exactly for
    every SUPPORTED func on ragged, reset-y, gap-y data."""

    def _mk_series(self, seed):
        import numpy as np
        rng = np.random.default_rng(seed)
        series = []
        T0 = 1_753_700_000_000
        for s in range(37):
            n = int(rng.integers(1, 60))
            # jittered 15s cadence with occasional gaps
            gaps = rng.integers(1, 5, n).cumsum()
            ts = T0 - 900_000 + gaps * 15_000 + rng.integers(-500, 500, n)
            ts.sort()
            if rng.random() < 0.5:
                vals = rng.integers(0, 50, n).cumsum().astype(float)
                if n > 5 and rng.random() < 0.5:
                    vals[n // 2:] -= vals[n // 2]  # counter reset
            else:
                vals = rng.normal(100, 10, n)
            series.append((ts.astype(np.int64), vals.astype(np.float64)))
        return series

    def test_all_supported_funcs_match(self):
        import numpy as np
        from victoriametrics_tpu.ops import rollup_np
        from victoriametrics_tpu.ops.rollup_np import RollupConfig, rollup
        T0 = 1_753_700_000_000
        cfg = RollupConfig(start=T0 - 600_000, end=T0, step=60_000,
                           window=120_000)
        cfg2 = RollupConfig(start=T0 - 600_000, end=T0, step=60_000,
                            window=0)  # lookback = step
        for seed in (0, 1):
            series = self._mk_series(seed)
            for c in (cfg, cfg2):
                for func in rollup_np.CORE_SUPPORTED:
                    batch = rollup_np.rollup_batch(func, series, c)
                    assert batch is not None, func
                    # stddev/stdvar go through prefix sums: zero-variance
                    # windows see ~1e-7 absolute noise (documented; far
                    # below metric precision)
                    atol = (1e-5 if func in ("stddev_over_time",
                                             "stdvar_over_time") else 1e-9)
                    for s, (ts, vals) in enumerate(series):
                        want = rollup(func, ts, vals, c)
                        got = batch[s]
                        np.testing.assert_allclose(
                            got, want, rtol=1e-6, atol=atol, equal_nan=True,
                            err_msg=f"{func} seed={seed} series={s}")

    def test_nan_values_fall_back(self):
        import numpy as np
        from victoriametrics_tpu.ops import rollup_np
        from victoriametrics_tpu.ops.rollup_np import RollupConfig
        T0 = 1_753_700_000_000
        cfg = RollupConfig(start=T0, end=T0 + 60_000, step=60_000,
                           window=120_000)
        series = [(np.array([T0 - 10_000, T0 - 5_000], dtype=np.int64),
                   np.array([1.0, np.nan]))]
        assert rollup_np.rollup_batch("sum_over_time", series, cfg) is None


class TestFusedDeviceAggr:
    """_try_device_fused_aggr must match the host aggregation exactly."""

    @pytest.fixture(scope="class")
    def store(self, tmp_path_factory):
        import numpy as np
        from victoriametrics_tpu.storage.storage import Storage
        s = Storage(str(tmp_path_factory.mktemp("fused") / "s"))
        rng = np.random.default_rng(7)
        T0 = 1_753_700_000_000
        rows = []
        for i in range(96):
            base = np.arange(60, dtype=np.int64) * 15_000 + T0 - 600_000
            ts = np.sort(base + rng.integers(-2000, 2001, 60))
            vals = np.cumsum(rng.integers(0, 30, 60)).astype(float)
            lab = {"__name__": "fm", "instance": f"h{i % 8}",
                   "job": f"j{i % 3}"}
            rows.extend(zip([lab] * 60, ts.tolist(), vals.tolist()))
        s.add_rows(rows)
        s.force_flush()
        yield s
        s.close()

    @pytest.mark.parametrize("q", [
        "sum by (instance)(rate(fm[5m]))",
        "avg by (job)(increase(fm[3m]))",
        "count(last_over_time(fm[2m]))",
        "max by (instance,job)(delta(fm[4m]))",
        "min without (job,instance)(rate(fm[5m]))",
        "stddev by (job)(avg_over_time(fm[5m]))",
        "quantile(0.9, rate(fm[5m])) by (instance)",
        "quantile(0.25, last_over_time(fm[2m])) by (job)",
        "quantile(1.5, rate(fm[5m])) by (job)",
        "median(increase(fm[3m])) by (instance)",
        "quantile(0.5, rate(fm[5m]))",
    ])
    def test_fused_matches_host(self, store, q):
        import numpy as np
        from victoriametrics_tpu.query.exec import exec_query
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        from victoriametrics_tpu.query.types import EvalConfig
        T0 = 1_753_700_000_000
        kw = dict(start=T0 - 300_000, end=T0, step=60_000, storage=store)
        host = exec_query(EvalConfig(**kw), q)
        dev = exec_query(EvalConfig(**kw, tpu=TPUEngine(min_series=4)), q)
        assert len(dev) == len(host) and len(host) > 0
        hm = {r.metric_name.marshal(): r.values for r in host}
        dm = {r.metric_name.marshal(): r.values for r in dev}
        assert set(hm) == set(dm)
        for k in hm:
            np.testing.assert_allclose(dm[k], hm[k], rtol=1e-6, atol=1e-6,
                                       equal_nan=True, err_msg=q)


    @pytest.mark.parametrize("q", [
        "topk(3, rate(fm[5m]))",
        "bottomk(3, rate(fm[5m]))",
        "topk(5, fm)",
        "bottomk(120, rate(fm[5m]))",        # k > S: keep everything
        "topk_max(4, rate(fm[5m]))",
        "topk_min(4, increase(fm[3m]))",
        "topk_avg(6, rate(fm[5m]))",
        "topk_median(4, rate(fm[5m]))",
        "topk_last(4, last_over_time(fm[2m]))",
        "bottomk_max(4, rate(fm[5m]))",
        "bottomk_avg(3, rate(fm[5m]))",
        "topk(0, rate(fm[5m]))",
    ])
    def test_topk_matches_host(self, store, q):
        """Device topk selection (topk_select_tile/rank_tile) must pick the
        same series with the same masked values as _eval_topk_family."""
        import numpy as np
        from victoriametrics_tpu.query.exec import exec_query
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        from victoriametrics_tpu.query.types import EvalConfig
        T0 = 1_753_700_000_000
        kw = dict(start=T0 - 300_000, end=T0, step=60_000, storage=store)
        host = exec_query(EvalConfig(**kw), q)
        dev = exec_query(EvalConfig(**kw, tpu=TPUEngine(min_series=4)), q)
        assert len(dev) == len(host)
        hm = {r.metric_name.marshal(): r.values for r in host}
        dm = {r.metric_name.marshal(): r.values for r in dev}
        assert set(hm) == set(dm)
        for k in hm:
            np.testing.assert_allclose(dm[k], hm[k], rtol=1e-6, atol=1e-6,
                                       equal_nan=True, err_msg=q)

    def test_topk_decline_rolls_back_sample_count(self, store):
        """A device decline (min_series too high) must not double-count
        samples against maxSamplesPerQuery when the host path re-fetches."""
        from victoriametrics_tpu.query.exec import exec_query
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        from victoriametrics_tpu.query.types import EvalConfig
        T0 = 1_753_700_000_000
        # 96 series x <=60 samples: cap at ~1.5x one fetch — double
        # counting would blow it
        kw = dict(start=T0 - 300_000, end=T0, step=60_000, storage=store,
                  max_samples_per_query=9_000)
        out = exec_query(EvalConfig(**kw, tpu=TPUEngine(min_series=10_000)),
                         "topk(3, rate(fm[5m]))")
        host = exec_query(EvalConfig(**kw), "topk(3, rate(fm[5m]))")
        assert len(out) == len(host) > 0

    def test_fused_warm_path_matches(self, store):
        """Second run hits the aux/resident-tile shortcut and must agree."""
        import numpy as np
        from victoriametrics_tpu.query.exec import exec_query
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        from victoriametrics_tpu.query.types import EvalConfig
        T0 = 1_753_700_000_000
        engine = TPUEngine(min_series=4)
        for q in ("sum by (instance)(rate(fm[5m]))",
                  "quantile(0.9, rate(fm[5m])) by (instance)"):
            kw = dict(start=T0 - 300_000, end=T0, step=60_000, storage=store)
            host = exec_query(EvalConfig(**kw), q)
            cold = exec_query(EvalConfig(**kw, tpu=engine), q)
            warm = exec_query(EvalConfig(**kw, tpu=engine), q)
            hm = {r.metric_name.marshal(): r.values for r in host}
            for res in (cold, warm):
                rm = {r.metric_name.marshal(): r.values for r in res}
                assert set(rm) == set(hm), q
                for k in hm:
                    np.testing.assert_allclose(rm[k], hm[k], rtol=1e-6,
                                               atol=1e-6, equal_nan=True,
                                               err_msg=q)
