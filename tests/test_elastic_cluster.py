"""Elastic scale-out serving (ROADMAP item 3): reroute-aware
ring-filtered reads, live resharding over the migrateParts_v1 family,
and multilevel vmselect fan-out — the in-process tier-1 half (the
subprocess chaos scenarios live in test_chaos_cluster.py).

Everything here runs real RPC over loopback TCP against real Storage
engines, just inside one process for speed.
"""

import os
import tempfile

import numpy as np
import pytest

from victoriametrics_tpu.parallel import ringfilter
from victoriametrics_tpu.parallel.cluster_api import (
    ClusterStorage, StorageNodeClient, make_storage_handlers,
    parse_node_spec, start_native_server)
from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT, HELLO_SELECT,
                                              RPCError, RPCServer)
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import TagFilter
from victoriametrics_tpu.utils import metrics as metricslib

zstd_missing = False
try:  # the RPC frame layer needs a zstd codec (python pkg or dlopen)
    from victoriametrics_tpu.ops import compress as _c
    _c.compress(b"probe")
except Exception:  # pragma: no cover - env without any zstd
    zstd_missing = True

pytestmark = pytest.mark.skipif(zstd_missing,
                                reason="no zstd codec available")

T0 = 1_753_700_000_000
_REROUTES = metricslib.REGISTRY.counter("vm_reroute_reads_total")
_MIGRATED = metricslib.REGISTRY.counter("vm_parts_migrated_total")
_MOVED_BYTES = metricslib.REGISTRY.counter("vm_rebalance_moved_bytes_total")


class Node:
    """One in-process 'vmstorage': Storage + both RPC planes."""

    def __init__(self, tag: str):
        self.store = Storage(tempfile.mkdtemp(prefix=f"elastic-{tag}-"))
        handlers = make_storage_handlers(self.store)
        self.ins = RPCServer("127.0.0.1", 0, HELLO_INSERT, handlers)
        self.sel = RPCServer("127.0.0.1", 0, HELLO_SELECT, handlers)
        self.ins.start()
        self.sel.start()

    def client(self) -> StorageNodeClient:
        return StorageNodeClient("127.0.0.1", self.ins.port, self.sel.port)

    @property
    def spec(self) -> str:
        return f"127.0.0.1:{self.ins.port}:{self.sel.port}"

    def close(self):
        self.ins.stop()
        self.sel.stop()
        self.store.close()


@pytest.fixture(autouse=True)
def _fast_migration_grace(monkeypatch):
    """No concurrent readers in these tests: shrink the source-copy
    grace window (VM_MIGRATE_GRACE_MS) so drains don't sleep 1.5s."""
    monkeypatch.setenv("VM_MIGRATE_GRACE_MS", "50")


@pytest.fixture()
def nodes2():
    ns = [Node("a"), Node("b")]
    yield ns
    for n in ns:
        n.close()


@pytest.fixture()
def nodes3():
    ns = [Node("a"), Node("b"), Node("c")]
    yield ns
    for n in ns:
        n.close()


def seed(cluster, name="em", n=60, k=3):
    rows = [({"__name__": name, "series": str(i)},
             T0 + j * 15_000, float(i * 100 + j))
            for i in range(n) for j in range(k)]
    cluster.add_rows(rows)
    return rows


def fetch(cluster, name="em", lo=T0, hi=T0 + 60_000):
    return cluster.search_columns([TagFilter(b"", name.encode())], lo, hi)


def assert_same(a, b):
    assert a.raw_names == b.raw_names
    assert np.array_equal(a.counts, b.counts)
    assert np.array_equal(a.ts, b.ts)
    assert np.array_equal(a.vals, b.vals)


# ---------------------------------------------------------------------------
# ring-ownership read filtering
# ---------------------------------------------------------------------------

class TestRingFilteredReads:
    def test_ring_on_equals_ring_off(self, nodes2):
        """The oracle: ring-filtered reads are bit-equal to the full
        fan-out (VM_RING_FILTER=0), healthy and with rf=1/rf=2."""
        for rf in (1, 2):
            cluster = ClusterStorage([n.client() for n in nodes2],
                                     replication_factor=rf)
            seed(cluster, name=f"rr{rf}")
            on = fetch(cluster, f"rr{rf}")
            os.environ["VM_RING_FILTER"] = "0"
            try:
                off = fetch(cluster, f"rr{rf}")
            finally:
                del os.environ["VM_RING_FILTER"]
            assert on.n_series == 60
            assert_same(on, off)
            cluster.close()

    def test_rf2_suppresses_duplicate_replica_rows(self, nodes2):
        """With RF=2 every series lives on both nodes; ring filtering
        makes each node serve only its primary share, so the bytes
        crossing the wire drop ~2x (the read-amplification win)."""
        cluster = ClusterStorage([n.client() for n in nodes2],
                                 replication_factor=2)
        seed(cluster)
        ring0 = ringfilter.get_ring(cluster.node_names(), 2, 0,
                                    frozenset())
        ring1 = ringfilter.get_ring(cluster.node_names(), 2, 1,
                                    frozenset())
        f = [TagFilter(b"", b"em")]
        n0 = cluster.nodes[0].search_columns(f, T0, T0 + 60_000,
                                             ring=ring0)
        n1 = cluster.nodes[1].search_columns(f, T0, T0 + 60_000,
                                             ring=ring1)
        served = len(n0[0]) + len(n1[0])
        assert served == 60, f"primary shares must partition: {served}"
        # unfiltered, both nodes return everything (2x amplification)
        u0 = cluster.nodes[0].search_columns(f, T0, T0 + 60_000)
        u1 = cluster.nodes[1].search_columns(f, T0, T0 + 60_000)
        assert len(u0[0]) + len(u1[0]) == 120
        cluster.close()

    def test_down_node_rerouted_complete(self, nodes2):
        """ISSUE acceptance: a down shard is served via explicit
        reroute — complete (not partial) results, with
        vm_reroute_reads_total ticking on the vmselect side."""
        cluster = ClusterStorage([n.client() for n in nodes2],
                                 replication_factor=2)
        seed(cluster)
        before = fetch(cluster)
        r0 = _REROUTES.get()
        cluster.nodes[0].mark_down(30.0)
        cluster.reset_partial()
        after = fetch(cluster)
        assert_same(before, after)
        assert not cluster.last_partial
        assert _REROUTES.get() > r0
        cluster.nodes[0].down_until = 0.0
        cluster.close()

    def test_unmarked_failure_goes_partial_not_silent(self, nodes2):
        """A fan-out failure that never flips node.healthy
        (waited=False: pre-exhausted budget, local pool capacity) must
        not be claimed replica-covered under ring filtering — the
        survivors suppressed the failed node's shares, so the result
        goes HONESTLY partial after the one bounded re-fan."""
        from victoriametrics_tpu.parallel.rpc import RPCDeadlineError
        cluster = ClusterStorage([n.client() for n in nodes2],
                                 replication_factor=2)
        seed(cluster, name="uf")
        orig = cluster.nodes[0].search_columns

        def boom(*a, **k):
            err = RPCDeadlineError("budget pre-exhausted before I/O")
            err.waited = False
            raise err

        cluster.nodes[0].search_columns = boom
        try:
            cluster.reset_partial()
            cols = fetch(cluster, "uf")
            assert cluster.last_partial, \
                "suppressed shares silently claimed complete"
            assert 0 < cols.n_series < 60
            # waited=False never poisons the node's health
            assert cluster.nodes[0].healthy
        finally:
            cluster.nodes[0].search_columns = orig
        cluster.reset_partial()
        assert fetch(cluster, "uf").n_series == 60
        assert not cluster.last_partial
        cluster.close()

    def test_write_reroute_marks_exempt(self, nodes2):
        """rf=1: rows rerouted while their owner was down are marked
        ring-exempt on the node that took them — after the owner comes
        back, ring-filtered reads still serve every row."""
        cluster = ClusterStorage([n.client() for n in nodes2])
        seed(cluster, name="wr", n=40)
        # kill node 0's servers so writes to it fail over to node 1
        # (stop() only closes the LISTENER; drop the kept-alive client
        # connection too so the reconnect actually fails)
        nodes2[0].ins.stop()
        nodes2[0].sel.stop()
        cluster.nodes[0].insert.close()
        rows = [({"__name__": "wr", "series": str(i)},
                 T0 + 90_000, float(i)) for i in range(40)]
        cluster.add_rows(rows)
        # owner back up (same Storage, fresh servers on fresh ports)
        n0 = nodes2[0]
        handlers = make_storage_handlers(n0.store)
        n0.ins = RPCServer("127.0.0.1", 0, HELLO_INSERT, handlers)
        n0.sel = RPCServer("127.0.0.1", 0, HELLO_SELECT, handlers)
        n0.ins.start()
        n0.sel.start()
        old_name = cluster.nodes[0].name
        revived = StorageNodeClient("127.0.0.1", n0.ins.port, n0.sel.port,
                                    name=old_name)
        cluster._set_nodes([revived, cluster.nodes[1]])
        cols = fetch(cluster, "wr", hi=T0 + 120_000)
        assert cols.n_series == 40
        # every rerouted sample present despite the healthy owner
        assert int(cols.counts.sum()) == 40 * 4
        # and the exemption is durable state on the taker
        assert len(nodes2[1].store.ring_exempt_names) > 0
        cluster.close()


# ---------------------------------------------------------------------------
# live resharding: migrate / drain / join+rebalance
# ---------------------------------------------------------------------------

class TestLiveResharding:
    def test_export_adopt_roundtrip_direct(self):
        """Storage-level: an exported part adopts byte-exactly on a
        fresh node, foreign metric_ids resolve, and narrow (per-day
        indexed) searches see the adopted data."""
        a = Storage(tempfile.mkdtemp(prefix="mig-a-"))
        b = Storage(tempfile.mkdtemp(prefix="mig-b-"))
        try:
            rows = [({"__name__": "mg", "series": str(i)},
                     T0 + j * 15_000, float(i + j))
                    for i in range(25) for j in range(3)]
            a.add_rows(rows)
            a.force_flush()
            inv = a.list_file_parts()
            assert inv and all(r["rows"] > 0 for r in inv)
            want = a.search_columns([TagFilter(b"", b"mg")], T0,
                                    T0 + 60_000)
            for row in inv:
                files, entries, meta = a.export_part(row["partition"],
                                                     row["part"])
                assert entries, "registrations must ship with the part"
                got_rows, got_bytes = b.adopt_part(
                    row["partition"], files, entries,
                    meta["min_ts"], meta["max_ts"])
                assert got_rows == row["rows"]
            got = b.search_columns([TagFilter(b"", b"mg")], T0,
                                   T0 + 60_000)
            assert got.raw_names == want.raw_names
            assert np.array_equal(got.vals, want.vals)
            # metric names resolve through the adopted registrations
            assert got.metric_names[0].metric_group == b"mg"
            # the generator skipped past every adopted id (a later
            # local series can never collide with a migrated one)
            assert b._mid_gen.next_id() > max(
                int(m) for m in got.metric_ids)
        finally:
            a.close()
            b.close()

    def test_adopt_rejects_torn_transfer(self):
        """The PR-10 integrity gate holds for migration: a corrupted
        byte in a transferred file rejects the adoption."""
        from victoriametrics_tpu.utils import fs as fslib
        a = Storage(tempfile.mkdtemp(prefix="torn-a-"))
        b = Storage(tempfile.mkdtemp(prefix="torn-b-"))
        try:
            # varying multi-sample series so timestamps.bin/values.bin
            # hold real payloads (single-sample const blocks encode to
            # zero bytes and there would be nothing to corrupt)
            a.add_rows([({"__name__": "tn", "series": str(i)},
                         T0 + j * 15_000, float(i * 7 + j * 3 + 1))
                        for i in range(20) for j in range(5)])
            a.force_flush()
            row = a.list_file_parts()[0]
            files, entries, meta = a.export_part(row["partition"],
                                                 row["part"])
            victim = next(n for n, d in files
                          if n.endswith(".bin") and d)
            files = [(n, (bytes([d[0] ^ 0xFF]) + d[1:]
                          if n == victim else d))
                     for n, d in files]
            with pytest.raises(fslib.IntegrityError):
                b.adopt_part(row["partition"], files, entries,
                             meta["min_ts"], meta["max_ts"])
            assert b.list_file_parts() == []
            # and a wire-supplied partition name cannot escape the
            # data directory (strict YYYY_MM or rejected)
            with pytest.raises(ValueError):
                b.adopt_part("../a_bc", files, entries)
            with pytest.raises(ValueError):
                b.adopt_part("2026_xx", files, entries)
        finally:
            a.close()
            b.close()

    def test_drain_node_byte_exact(self, nodes3):
        """DRAIN: all parts migrate off, the ring shrinks, reads stay
        byte-exact, and vm_parts_migrated_total accounts the moves."""
        cluster = ClusterStorage([n.client() for n in nodes3])
        seed(cluster, n=90)
        for n in nodes3:
            n.store.force_flush()
        want = fetch(cluster)
        assert want.n_series == 90
        victim = cluster.node_names()[0]
        m0, b0 = _MIGRATED.get(), _MOVED_BYTES.get()
        stat = cluster.drain_node(victim)
        assert stat["removed"] and stat["parts"] >= 1
        assert _MIGRATED.get() > m0 and _MOVED_BYTES.get() > b0
        assert len(cluster.nodes) == 2
        got = fetch(cluster)
        assert_same(want, got)
        # the drained node's engine is empty of finalized parts
        assert nodes3[0].store.list_file_parts() == []
        cluster.close()

    def test_drain_includes_unflushed_acked_writes(self, nodes3):
        """Zero dropped acked writes: rows acked but NOT yet flushed on
        the victim are flushed by the drain itself and survive."""
        cluster = ClusterStorage([n.client() for n in nodes3])
        seed(cluster, name="uf", n=50)       # acked, still in memory
        want = fetch(cluster, "uf")
        victim = cluster.node_names()[2]
        cluster.drain_node(victim)
        got = fetch(cluster, "uf")
        assert_same(want, got)
        cluster.close()

    def test_join_and_rebalance(self, nodes2):
        """JOIN: a fresh node enters the ring without a restart; new
        writes shard onto it; rebalance_to moves a byte share of
        existing parts; reads stay byte-exact throughout."""
        joiner = Node("j")
        try:
            cluster = ClusterStorage([n.client() for n in nodes2])
            # several flush batches -> several movable parts
            for b in range(3):
                rows = [({"__name__": "jn", "series": str(i)},
                         T0 + (3 * b + j) * 15_000, float(i + b))
                        for i in range(40) for j in range(3)]
                cluster.add_rows(rows)
                for n in nodes2:
                    n.store.force_flush()
            want = fetch(cluster, "jn", hi=T0 + 10 * 15_000)
            cluster.add_node(joiner.spec)
            assert len(cluster.nodes) == 3
            # new writes reach the joiner
            rows = [({"__name__": "jn2", "series": str(i)}, T0, float(i))
                    for i in range(60)]
            cluster.add_rows(rows)
            assert joiner.store.rows_added > 0
            stat = cluster.rebalance_to(joiner.client().name)
            assert stat["parts"] >= 1, stat
            assert joiner.store.list_file_parts() != []
            got = fetch(cluster, "jn", hi=T0 + 10 * 15_000)
            assert_same(want, got)
            cluster.close()
        finally:
            joiner.close()

    def test_drain_rejects_when_no_targets(self, nodes2):
        cluster = ClusterStorage([n.client() for n in nodes2])
        seed(cluster, name="nt", n=10)
        cluster.drain_node(cluster.node_names()[0])
        last = cluster.node_names()[0]
        with pytest.raises((RPCError, ValueError)):
            cluster.drain_node(last)
        # a FAILED drain must not leave the node write-excluded forever
        assert last not in cluster._draining
        cluster.add_rows([({"__name__": "nt2", "series": "0"},
                           T0, 1.0)])
        assert fetch(cluster, "nt2").n_series == 1
        cluster.close()


# ---------------------------------------------------------------------------
# multilevel vmselect
# ---------------------------------------------------------------------------

class TestMultilevel:
    def test_parse_node_spec_forms(self):
        assert parse_node_spec("127.0.0.1:8400:8401") == \
            ("127.0.0.1", 8400, 8401)
        assert parse_node_spec("10.0.0.5:9000") == ("10.0.0.5", 9000, 9000)
        with pytest.raises(ValueError):
            parse_node_spec("nonsense")

    def test_tree_rows_byte_identical_to_flat(self, nodes2):
        """ISSUE acceptance: vmselect -> vmselect -> 2x vmstorage rows
        are byte-identical to the flat fan-out, and partials/traces
        propagate through the tree."""
        from victoriametrics_tpu.utils import querytracer
        flat = ClusterStorage([n.client() for n in nodes2])
        seed(flat, name="ml", n=80)
        mid = ClusterStorage([n.client() for n in nodes2])
        mid_srv = start_native_server("127.0.0.1:0", HELLO_SELECT, mid)
        try:
            top = ClusterStorage([StorageNodeClient(
                "127.0.0.1", mid_srv.port, mid_srv.port)])
            want = fetch(flat, "ml")
            got = fetch(top, "ml")
            assert want.n_series == 80
            assert_same(want, got)
            # cost propagation: the top-level query's tracker sees the
            # tree's node-side scan counts through the mid-level merge
            # (they land in storage_samples by design — .samples is the
            # evaluator's own merged-result count)
            from victoriametrics_tpu.utils import costacc
            tr = costacc.CostTracker()
            prev = costacc.set_current(tr)
            try:
                fetch(top, "ml")
            finally:
                costacc.set_current(prev)
            assert tr.storage_samples > 0
            assert tr.remote_nodes >= 1
            # trace composes: per-node rpc spans nested two levels deep
            qt = querytracer.new(True, "top")
            top.search_columns([TagFilter(b"", b"ml")], T0, T0 + 60_000,
                               tracer=qt)
            qt.donef("done")
            import json as _json
            assert _json.dumps(qt.to_dict()).count(
                "searchColumns_v1") >= 3
            # partial propagates up the tree
            mid.nodes[0].mark_down(30.0)
            top.reset_partial()
            part = fetch(top, "ml")
            assert top.last_partial and 0 < part.n_series < 80
            mid.nodes[0].down_until = 0.0
            top.close()
        finally:
            mid_srv.stop()
        flat.close()
