"""Graphite query API tests (reference app/vmselect/graphite/*_test.go
behaviors: find globbing, tags API, render with function pipeline)."""

import json

import numpy as np
import pytest

from tests.apptest_helpers import Client

T0 = 1_753_700_000_000


@pytest.fixture()
def app(tmp_path):
    from victoriametrics_tpu.apps.vmsingle import build, parse_flags
    args = parse_flags([f"-storageDataPath={tmp_path}/data",
                        "-httpListenAddr=127.0.0.1:0"])
    storage, srv, api = build(args)
    srv.start()
    # graphite-style series: dotted names + one tagged series
    rows = []
    for host in ("web1", "web2"):
        for j in range(30):
            rows.append(({"__name__": f"servers.{host}.cpu.load"},
                         T0 + j * 60_000, float(j)))
    for j in range(30):
        rows.append(({"__name__": "servers.web1.mem.used",
                      "dc": "east"}, T0 + j * 60_000, 100.0 + j))
    storage.add_rows(rows)
    yield Client(srv.port)
    srv.stop()
    storage.close()


class TestMetricsFind:
    def test_top_level(self, app):
        code, body = app.get("/metrics/find", query="*")
        assert code == 200
        nodes = json.loads(body)
        assert nodes == [{"text": "servers", "id": "servers", "leaf": 0,
                          "expandable": 1, "allowChildren": 1,
                          "context": {}}]

    def test_glob_level(self, app):
        code, body = app.get("/metrics/find", query="servers.*")
        names = [n["text"] for n in json.loads(body)]
        assert names == ["web1", "web2"]

    def test_leaf(self, app):
        code, body = app.get("/metrics/find", query="servers.web1.cpu.*")
        nodes = json.loads(body)
        assert nodes[0]["leaf"] == 1 and nodes[0]["id"] == \
            "servers.web1.cpu.load"

    def test_braces(self, app):
        code, body = app.get("/metrics/find", query="servers.{web1}.*")
        names = [n["text"] for n in json.loads(body)]
        assert names == ["cpu", "mem"]

    def test_expand(self, app):
        code, body = app.get("/metrics/expand", query="servers.*.cpu")
        assert json.loads(body)["results"] == [
            "servers.web1.cpu", "servers.web2.cpu"]


class TestTagsAPI:
    def test_tags_list(self, app):
        code, body = app.get("/tags")
        tags = [t["tag"] for t in json.loads(body)]
        assert "name" in tags and "dc" in tags

    def test_tag_values(self, app):
        code, body = app.get("/tags/dc")
        d = json.loads(body)
        assert d["tag"] == "dc"
        assert [v["value"] for v in d["values"]] == ["east"]

    def test_autocomplete(self, app):
        code, body = app.get("/tags/autoComplete/tags", tagPrefix="d")
        assert json.loads(body) == ["dc"]
        code, body = app.get("/tags/autoComplete/values", tag="dc",
                             valuePrefix="e")
        assert json.loads(body) == ["east"]

    def test_find_series(self, app):
        code, body = app.get("/tags/findSeries", expr="dc=east")
        assert json.loads(body) == ["servers.web1.mem.used;dc=east"]


class TestRender:
    def _render(self, app, target, **kw):
        params = {"target": target, "from": str((T0 - 60_000) // 1000),
                  "until": str((T0 + 29 * 60_000) // 1000),
                  "format": "json", **kw}
        code, body = app.get("/render", **params)
        assert code == 200, body
        return json.loads(body)

    def test_plain_path_glob(self, app):
        out = self._render(app, "servers.*.cpu.load")
        assert {s["target"] for s in out} == {
            "servers.web1.cpu.load", "servers.web2.cpu.load"}
        s0 = out[0]
        vals = [p[0] for p in s0["datapoints"] if p[0] is not None]
        assert vals[:3] == [0.0, 1.0, 2.0]
        # datapoint timestamps are epoch seconds
        assert s0["datapoints"][0][1] * 1000 >= T0 - 120_000

    def test_sum_and_alias(self, app):
        out = self._render(app, 'alias(sumSeries(servers.*.cpu.load), "tot")')
        assert len(out) == 1 and out[0]["target"] == "tot"
        vals = [p[0] for p in out[0]["datapoints"] if p[0] is not None]
        assert vals[:3] == [0.0, 2.0, 4.0]  # two series summed

    def test_scale_and_nnderivative(self, app):
        out = self._render(
            app, "scale(nonNegativeDerivative(servers.web1.cpu.load), 2)")
        vals = [p[0] for p in out[0]["datapoints"] if p[0] is not None]
        assert all(v == 2.0 for v in vals)  # slope 1/min * 2

    def test_alias_by_node_and_group(self, app):
        out = self._render(app, "aliasByNode(servers.*.cpu.load, 1)")
        assert {s["target"] for s in out} == {"web1", "web2"}
        out = self._render(
            app, 'groupByNode(servers.*.cpu.load, 1, "sum")')
        assert {s["target"] for s in out} == {"web1", "web2"}

    def test_series_by_tag(self, app):
        out = self._render(app, "seriesByTag('dc=east')")
        assert len(out) == 1
        assert out[0]["target"] == "servers.web1.mem.used"
        assert out[0]["tags"]["dc"] == "east"

    def test_max_data_points(self, app):
        out = self._render(app, "servers.web1.cpu.load", maxDataPoints="5")
        assert len(out[0]["datapoints"]) <= 7  # ceil-rounded grid ends

    def test_bad_target(self, app):
        code, body = app.get("/render", target="nosuchfunc(", **{
            "from": "-1h"})
        assert code == 400


class TestReviewRegressions:
    def test_leaf_and_branch_node(self, tmp_path):
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        args = parse_flags([f"-storageDataPath={tmp_path}/d",
                            "-httpListenAddr=127.0.0.1:0"])
        storage, srv, api = build(args)
        srv.start()
        storage.add_rows([({"__name__": "a.b"}, T0, 1.0),
                          ({"__name__": "a.b.c"}, T0, 2.0)])
        c = Client(srv.port)
        code, body = c.get("/metrics/find", query="a.*")
        n = json.loads(body)[0]
        assert n["leaf"] == 1 and n["expandable"] == 1  # both roles
        # '?' wildcard
        code, body = c.get("/metrics/find", query="?.b")
        assert [x["id"] for x in json.loads(body)] == ["a.b"]
        # bad from -> 400 not 500
        code, _ = c.get("/render", target="a.b", **{"from": "tomorrow"})
        assert code == 400
        srv.stop()
        storage.close()

    def test_alias_by_tags(self, app):
        code, body = app.get(
            "/render", target="aliasByTags(seriesByTag('dc=east'), 'dc')",
            **{"from": str((T0 - 60_000) // 1000),
               "until": str((T0 + 29 * 60_000) // 1000)})
        out = json.loads(body)
        assert out and out[0]["target"] == "east"
