"""Prometheus TSDB block format (utils/promtsdb + the vmctl
prometheus-tsdb / verify-block modes): encode/decode round-trips for the
Gorilla XOR chunks, index parsing, CRC verification, and an end-to-end
block -> vmsingle migration."""

import os
import struct

import numpy as np
import pytest

from victoriametrics_tpu.utils import promtsdb as pt

T0 = 1_753_700_000_000


def _mk_series(rng, n_series=6):
    out = []
    for i in range(n_series):
        n = int(rng.integers(1, 500))
        ts = np.cumsum(rng.integers(1, 30_000, n)) + T0
        kind = i % 3
        if kind == 0:
            vals = np.cumsum(rng.integers(0, 50, n)).astype(np.float64)
        elif kind == 1:
            vals = np.round(rng.uniform(-1000, 1000, n), 3)
        else:
            vals = rng.standard_normal(n) * 10.0 ** float(rng.integers(-5, 5))
        out.append(({"__name__": f"pm{i}", "job": "tsdb",
                     "idx": str(i)}, ts, vals))
    return out


class TestXorChunk:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_roundtrip_random(self, seed):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(1, 2000))
        ts = np.cumsum(rng.integers(1, 100_000, n)) + T0
        vals = rng.standard_normal(n)
        data = pt.encode_xor_chunk(ts, vals)
        ts2, v2 = pt.decode_xor_chunk(data)
        np.testing.assert_array_equal(ts, ts2)
        np.testing.assert_array_equal(vals, v2)

    def test_roundtrip_regular_scrape(self):
        # constant 15s interval: dod == 0 single-bit path
        ts = T0 + np.arange(1000, dtype=np.int64) * 15_000
        vals = np.full(1000, 42.5)
        data = pt.encode_xor_chunk(ts, vals)
        assert len(data) < 300  # ~2 bits/sample: dod=0 + repeat-value
        ts2, v2 = pt.decode_xor_chunk(data)
        np.testing.assert_array_equal(ts, ts2)
        np.testing.assert_array_equal(vals, v2)

    def test_roundtrip_special_values(self):
        ts = T0 + np.arange(6, dtype=np.int64) * 1000
        vals = np.array([0.0, np.inf, -np.inf, np.nan, 1e-300, -0.0])
        ts2, v2 = pt.decode_xor_chunk(pt.encode_xor_chunk(ts, vals))
        np.testing.assert_array_equal(ts, ts2)
        np.testing.assert_array_equal(
            np.asarray(vals).view(np.uint64), v2.view(np.uint64))

    def test_large_dod_paths(self):
        # hit every dod prefix class incl. the 64-bit escape
        deltas = [1000, 1000, 9000, 70_000, 600_000, 10 ** 10]
        ts = np.cumsum([T0] + deltas).astype(np.int64)
        vals = np.arange(len(ts), dtype=np.float64)
        ts2, v2 = pt.decode_xor_chunk(pt.encode_xor_chunk(ts, vals))
        np.testing.assert_array_equal(ts, ts2)
        np.testing.assert_array_equal(vals, v2)


class TestBlockRoundtrip:
    def test_write_read_verify(self, tmp_path):
        rng = np.random.default_rng(0)
        series = _mk_series(rng)
        blk = str(tmp_path / "b1")
        pt.write_block(blk, series)
        got = {tuple(sorted(l.items())): (t, v)
               for l, t, v in pt.read_block(blk, verify_crc=True)}
        assert len(got) == len(series)
        for labels, ts, vals in series:
            t2, v2 = got[tuple(sorted(labels.items()))]
            np.testing.assert_array_equal(np.asarray(ts, np.int64), t2)
            np.testing.assert_array_equal(vals, v2)
        rep = pt.verify_block(blk)
        assert rep["ok"], rep["errors"]
        assert rep["series"] == len(series)
        assert rep["samples"] == sum(len(t) for _, t, _ in series)

    def test_verify_detects_corruption(self, tmp_path):
        rng = np.random.default_rng(1)
        blk = str(tmp_path / "b2")
        pt.write_block(blk, _mk_series(rng, 3))
        p = os.path.join(blk, "chunks", "000001")
        data = bytearray(open(p, "rb").read())
        data[30] ^= 0xFF
        open(p, "wb").write(bytes(data))
        rep = pt.verify_block(blk)
        assert not rep["ok"]
        assert any("crc" in e or "chunk" in e for e in rep["errors"])

    def test_verify_detects_index_corruption(self, tmp_path):
        rng = np.random.default_rng(2)
        blk = str(tmp_path / "b4")
        pt.write_block(blk, _mk_series(rng, 3))
        p = os.path.join(blk, "index")
        data = bytearray(open(p, "rb").read())
        # flip a byte inside the series section (after the symbol table)
        blk_obj = pt.TSDBBlock(blk)
        data[blk_obj._toc["series"] + 3] ^= 0xFF
        open(p, "wb").write(bytes(data))
        rep = pt.verify_block(blk)
        assert not rep["ok"]
        assert any("crc" in e or "index" in e for e in rep["errors"])

    def test_unsupported_encoding_skipped_with_callback(self, tmp_path):
        rng = np.random.default_rng(3)
        blk = str(tmp_path / "b5")
        pt.write_block(blk, _mk_series(rng, 3))
        # rewrite one chunk's encoding byte to 2 (native histogram) and
        # fix up its crc so only the encoding is "unsupported"
        p = os.path.join(blk, "chunks", "000001")
        seg = bytearray(open(p, "rb").read())
        ln, i = pt._uvarint(seg, 8)
        seg[i] = 2
        body = bytes(seg[i:i + 1 + ln])
        seg[i + 1 + ln:i + 1 + ln + 4] = \
            pt.struct.pack(">I", pt.crc32c(body))
        open(p, "wb").write(bytes(seg))
        skipped = []
        got = list(pt.read_block(
            blk, on_unsupported=lambda l, e: skipped.append(l)))
        assert len(skipped) == 1
        assert len(got) == 2

    def test_verify_rejects_bad_magic(self, tmp_path):
        blk = tmp_path / "b3"
        (blk / "chunks").mkdir(parents=True)
        (blk / "index").write_bytes(struct.pack(">IB", 0xDEAD, 2))
        (blk / "chunks" / "000001").write_bytes(b"\x00" * 8)
        rep = pt.verify_block(str(blk))
        assert not rep["ok"]


class TestVmctlTsdbMigration:
    def test_block_to_vmsingle(self, tmp_path):
        from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
        from victoriametrics_tpu.httpapi.server import HTTPServer
        from victoriametrics_tpu.storage.storage import Storage
        from victoriametrics_tpu.apps.vmctl import prometheus_tsdb
        rng = np.random.default_rng(5)
        # recent timestamps so retention keeps them
        import time
        t0 = int(time.time() * 1000) - 3_600_000
        series = []
        for i in range(4):
            ts = t0 + np.arange(50, dtype=np.int64) * 15_000
            vals = np.round(rng.uniform(0, 100, 50), 2)
            series.append(({"__name__": "mig", "idx": str(i)}, ts, vals))
        data_dir = tmp_path / "tsdb" / "01ABCDEF"
        pt.write_block(str(data_dir), series)
        storage = Storage(str(tmp_path / "vm"))
        api = PrometheusAPI(storage, None)
        srv = HTTPServer("127.0.0.1", 0)
        api.register(srv)
        srv.start()
        try:
            n = prometheus_tsdb(str(tmp_path / "tsdb"),
                                f"http://127.0.0.1:{srv.port}")
            assert n == 200
            from victoriametrics_tpu.storage.tag_filters import \
                filters_from_dict
            cols = storage.search_columns(
                filters_from_dict({"__name__": "mig"}), 0, 1 << 62)
            assert cols.n_series == 4
            assert cols.n_samples == 200
            # values survive the text round-trip exactly (repr())
            by_raw = {cols.raw_names[i]: cols.vals[i, :cols.counts[i]]
                      for i in range(4)}
            for labels, ts, vals in series:
                raw = [r for r in by_raw
                       if f'idx\x01{labels["idx"]}'.encode() in r]
                assert len(raw) == 1
                np.testing.assert_array_equal(by_raw[raw[0]], vals)
        finally:
            srv.stop()
            storage.close()
