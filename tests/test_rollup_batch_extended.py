"""Differential tests: the vectorized long-tail rollups in
rollup_batch_packed vs their per-series twins (query/rollup_funcs
GENERIC_FUNCS run under generic_rollup) — same inputs, same windows, same
mpi-gated prevValue (reference doInternal semantics, rollup.go:688-960)."""

import numpy as np
import pytest

from victoriametrics_tpu.ops import rollup_np
from victoriametrics_tpu.ops.rollup_np import RollupConfig
from victoriametrics_tpu.query.rollup_funcs import rollup_series

T0 = 1_753_700_000_000

# (func, args) cases; None args means ()
CASES = [
    ("sum2_over_time", ()),
    ("range_over_time", ()),
    ("geomean_over_time", ()),
    ("count_eq_over_time", (5.0,)),
    ("count_ne_over_time", (5.0,)),
    ("count_le_over_time", (10.0,)),
    ("count_gt_over_time", (10.0,)),
    ("share_eq_over_time", (5.0,)),
    ("share_le_over_time", (10.0,)),
    ("share_gt_over_time", (10.0,)),
    ("sum_eq_over_time", (5.0,)),
    ("sum_le_over_time", (10.0,)),
    ("sum_gt_over_time", (10.0,)),
    ("resets", ()),
    ("increases_over_time", ()),
    ("decreases_over_time", ()),
    ("ascent_over_time", ()),
    ("descent_over_time", ()),
    ("integrate", ()),
    ("duration_over_time", (120.0,)),
    ("duration_over_time", ()),
    ("rate_over_sum", ()),
    ("ideriv", ()),
    ("changes_prometheus", ()),
    ("delta_prometheus", ()),
    ("increase_prometheus", ()),
    ("rate_prometheus", ()),
    ("predict_linear", (300.0,)),
    ("predict_linear", (0.0,)),
    ("zscore_over_time", ()),
    ("hoeffding_bound_lower", (0.95,)),
    ("hoeffding_bound_upper", (0.95,)),
    ("hoeffding_bound_upper", (2.0,)),   # out-of-range phi -> bound 0
    ("quantile_over_time", (0.5,)),
    ("quantile_over_time", (0.9,)),
    ("quantile_over_time", (-0.5,)),     # -> -inf on non-empty windows
    ("quantile_over_time", (1.5,)),      # -> +inf
    ("median_over_time", ()),
    ("mad_over_time", ()),
    ("iqr_over_time", ()),
    ("outlier_iqr_over_time", ()),
    ("tmin_over_time", ()),
    ("tmax_over_time", ()),
    ("distinct_over_time", ()),
    ("mode_over_time", ()),
    ("tlast_change_over_time", ()),
    ("timestamp_with_name", ()),
]


def make_series(rng, s, kind="gauge"):
    """Jittered scrape series with gaps; values chosen so eq-comparisons
    and mode/distinct see repeats."""
    n = rng.integers(5, 120)
    gaps = rng.integers(10_000, 20_000, size=n)
    # a couple of long gaps so some windows are empty / prev gets gated
    gaps[rng.integers(0, n, size=2)] += 200_000
    ts = T0 + np.cumsum(gaps)
    if kind == "counter":
        vals = np.cumsum(rng.integers(0, 8, size=n)).astype(np.float64)
        if n > 10:
            vals[n // 2:] -= vals[n // 2]  # counter reset
    else:
        vals = rng.integers(1, 20, size=n).astype(np.float64)
    return ts.astype(np.int64), vals


def pack(series):
    S = len(series)
    counts = np.array([t.size for t, _ in series], dtype=np.int64)
    N = int(counts.max())
    ts2 = np.full((S, N), np.iinfo(np.int64).max, dtype=np.int64)
    v2 = np.zeros((S, N))
    for i, (t, v) in enumerate(series):
        ts2[i, :t.size] = t
        v2[i, :v.size] = v
    return ts2, v2, counts


@pytest.mark.parametrize("func,args", CASES,
                         ids=[f"{f}-{a}" for f, a in CASES])
@pytest.mark.parametrize("kind", ["gauge", "counter"])
def test_matches_per_series(func, args, kind):
    if func == "geomean_over_time" and kind == "counter":
        pytest.skip("counters contain zeros: packed path defers (tested in "
                    "test_geomean_zero_falls_back)")
    rng = np.random.default_rng(hash((func, args, kind)) % 2**32)
    series = [make_series(rng, s, kind) for s in range(14)]
    cfg = RollupConfig(start=T0 + 60_000, end=T0 + 1_500_000,
                       step=30_000, window=90_000)
    got = rollup_np.rollup_batch(func, series, cfg, args)
    assert got is not None, f"{func} fell back unexpectedly"
    for i, (t, v) in enumerate(series):
        want = rollup_series(func, t, v, cfg, args)
        np.testing.assert_allclose(
            got[i], want, rtol=1e-9, atol=1e-9, equal_nan=True,
            err_msg=f"{func}{args} series {i}")


def test_geomean_zero_falls_back():
    ts = T0 + np.arange(10, dtype=np.int64) * 15_000
    vals = np.array([1.0, 2, 0, 4, 5, 6, 7, 8, 9, 10])
    cfg = RollupConfig(start=T0, end=T0 + 300_000, step=30_000, window=0)
    assert rollup_np.rollup_batch("geomean_over_time", [(ts, vals)] * 9,
                                  cfg) is None


def test_geomean_negative_values_match():
    rng = np.random.default_rng(3)
    series = []
    for _ in range(10):
        t, v = make_series(rng, 0)
        v = v - 10.0
        v[v == 0] = 1.0
        series.append((t, v))
    cfg = RollupConfig(start=T0 + 60_000, end=T0 + 900_000,
                       step=30_000, window=90_000)
    got = rollup_np.rollup_batch("geomean_over_time", series, cfg)
    for i, (t, v) in enumerate(series):
        want = rollup_series("geomean_over_time", t, v, cfg, ())
        np.testing.assert_allclose(got[i], want, rtol=1e-9, equal_nan=True)


def test_batch_supported_validation():
    assert rollup_np.batch_supported("quantile_over_time", (0.5,))
    assert not rollup_np.batch_supported("quantile_over_time", ())
    assert not rollup_np.batch_supported("quantile_over_time", ("x",))
    assert rollup_np.batch_supported("duration_over_time", ())
    assert rollup_np.batch_supported("duration_over_time", (60.0,))
    assert not rollup_np.batch_supported("holt_winters", (0.5, 0.5))
    assert rollup_np.batch_supported("rate", ())
    assert not rollup_np.batch_supported("rate", (1.0,))


def test_instant_query_grid():
    # start == end (instant query): mpi falls back to step for everyone
    rng = np.random.default_rng(11)
    series = [make_series(rng, s) for s in range(10)]
    cfg = RollupConfig(start=T0 + 600_000, end=T0 + 600_000,
                       step=60_000, window=300_000)
    for func, args in [("resets", ()), ("quantile_over_time", (0.75,)),
                       ("predict_linear", (60.0,)), ("zscore_over_time", ())]:
        got = rollup_np.rollup_batch(func, series, cfg, args)
        for i, (t, v) in enumerate(series):
            want = rollup_series(func, t, v, cfg, args)
            np.testing.assert_allclose(got[i], want, rtol=1e-9, atol=1e-9,
                                       equal_nan=True,
                                       err_msg=f"{func} instant")


class TestNewSeriesBaseline:
    """increase/delta for a series born INSIDE the window (no sample before
    it): the counter is assumed born at 0 — a histogram bucket appearing at
    value k carries k events — unless the first value dwarfs the first
    in-window step (already-running counter surfacing mid-window), in which
    case it is the baseline (rollup.go:2129 rollupDelta). Without this a
    freshly started process reports zero good events for the whole window
    and every latency SLO falsely pages."""

    TS = np.arange(10, dtype=np.int64) * 15_000 + T0
    CFG = RollupConfig(start=T0 + 285_000, end=T0 + 285_000,
                       step=60_000, window=300_000)

    def _all_engines(self, func, v):
        import victoriametrics_tpu.native as nat
        v = np.asarray(v, dtype=np.float64)
        oracle = rollup_np.rollup(func, self.TS, v, self.CFG)[0]
        ts2 = self.TS[None, :]
        counts = np.array([self.TS.size], dtype=np.int64)
        native = rollup_np.rollup_batch_packed(
            func, ts2, v[None, :], counts, self.CFG)[0][0]
        saved = nat.available
        try:
            nat.available = lambda: False
            fallback = rollup_np.rollup_batch_packed(
                func, ts2, v[None, :], counts, self.CFG)[0][0]
        finally:
            nat.available = saved
        return oracle, native, fallback

    @pytest.mark.parametrize("func", ["increase", "increase_pure", "delta"])
    def test_flat_bucket_birth_counts_once(self, func):
        # bucket born at 1, flat: increase over the window is 1, not 0
        for got in self._all_engines(func, np.ones(10)):
            assert got == pytest.approx(1.0)

    def test_large_first_value_is_baseline(self):
        # counter at 1e6 stepping +1: surfaced mid-window, not born here
        v = 1_000_000.0 + np.arange(10)
        for got in self._all_engines("increase", v):
            assert got == pytest.approx(9.0)
        # increase_pure always counts from 0 (rollup.go:2169)
        for got in self._all_engines("increase_pure", v):
            assert got == pytest.approx(1_000_009.0)

    def test_prev_sample_still_wins(self):
        # a sample BEFORE the window: baseline is that sample, heuristic off
        cfg = RollupConfig(start=T0 + 400_000, end=T0 + 400_000,
                           step=60_000, window=300_000)
        v = np.ones(10)
        got = rollup_np.rollup("increase", self.TS, v, cfg)[0]
        assert got == pytest.approx(0.0)
