"""The HTTP result cache's suffix eval over the DEVICE engine: served
refreshes must match a cold evaluation within the f32 tile bound.

Regression: layering the device rolling tail-reuse under the result
cache's own tail merge mis-advanced reused columns when BOTH grid edges
move (~35% rate error on the reused suffix columns). The suffix eval now
sets EvalConfig.no_device_roll (fresh fused tiles, no roll/aux reuse)."""

import time

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.tpu_engine import TPUEngine
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="needs native lib")

NS, NN, STEP = 256, 360, 60_000
JITTER_MS = 2_000  # must match every rng.integers jitter below


def test_direct_advancing_refresh_matches_cold_on_device(tmp_path):
    """Direct full evals with BOTH grid edges advancing (the uncacheable-
    query dashboard pattern, which bypasses the HTTP result cache) take
    the device rolling-reuse path and must match cold evals — this is
    the constant-shape advance the rolling tile is designed for, distinct
    from the variable-length suffix grids no_device_roll guards."""
    now = int(time.time() * 1000)
    t0 = (now - (NN - 1) * 15_000) // STEP * STEP
    rng = np.random.default_rng(1)
    s = Storage(str(tmp_path / "s"))
    try:
        base = np.arange(NN, dtype=np.int64) * 15_000 + t0
        keys = [f'da{{idx="{i}",instance="h-{i % 16}"}}'.encode()
                for i in range(NS)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, NS)
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
        ts2 = np.sort(base[None, :] +
                      rng.integers(-JITTER_MS, JITTER_MS + 1, (NS, NN)), axis=1)
        vals2 = np.cumsum(rng.integers(0, 50, (NS, NN)),
                          axis=1).astype(np.float64)
        s.add_rows_columnar(native.ColumnarRows(
            keybuf, np.repeat(koffs, NN), np.repeat(klens, NN),
            ts2.reshape(-1), vals2.reshape(-1)))
        s.force_flush()
        last = vals2[:, -1]
        eng = TPUEngine(value_dtype=np.float32, min_series=2)
        q = "sum by (instance)(rate(da[5m]))"
        dur = (NN - 1) * 15_000 - 300_000
        # round UP past all initial jittered samples (counter
        # monotonicity across the first refresh; see bench.py)
        end = t0 + -(-((NN - 1) * 15_000 + JITTER_MS) // STEP) * STEP
        kw = dict(step=STEP, storage=s, tpu=eng)
        exec_query(EvalConfig(start=end - dur, end=end, **kw), q)
        prev_warm = None
        for _ in range(3):
            end += STEP
            incr = rng.integers(0, 50, (NS, 4))
            v2 = last[:, None] + np.cumsum(incr, axis=1)
            last = v2[:, -1]
            tsf = (end - STEP +
                   (np.arange(4, dtype=np.int64) + 1)[None, :] * 15_000 +
                   rng.integers(-JITTER_MS, JITTER_MS + 1, (NS, 4)))
            tsf.sort(axis=1)
            s.add_rows_columnar(native.ColumnarRows(
                keybuf, np.repeat(koffs, 4), np.repeat(klens, 4),
                tsf.reshape(-1), v2.reshape(-1).astype(np.float64)))
            warm = exec_query(EvalConfig(start=end - dur, end=end, **kw),
                              q)
            cold = exec_query(EvalConfig(start=end - dur, end=end, **kw,
                                         disable_cache=True), q)
            dw = {ts.metric_name.marshal(): ts.values for ts in warm}
            dc = {ts.metric_name.marshal(): ts.values for ts in cold}
            assert set(dw) == set(dc)
            for k, vw in dw.items():
                vc = dc[k]
                np.testing.assert_array_equal(np.isnan(vw), np.isnan(vc))
                # The rolling path trades a bounded drift for zero
                # refetch: reused columns keep the scrape-interval
                # estimates they were computed under (the reference
                # rollupResultCache contract, rollup_result_cache.go:283)
                # and the tail kernel's estimate-dependent prev-sample
                # gating can flip vs a cold fresh-tile eval under
                # jittered scrape intervals. Bound: one gated sample's
                # worth of increase per 5m window (~scrape_interval /
                # window = 15/300), on a small fraction of columns.
                m = ~np.isnan(vw)
                rel = np.abs(vw[m] - vc[m]) / np.maximum(
                    np.abs(vc[m]), 1e-9)
                assert float(rel.max()) < 0.06, float(rel.max())
                assert (rel > 1e-4).mean() < 0.05
            if prev_warm is not None:
                # shift consistency: reused columns == previously served
                for k, vw in dw.items():
                    pv = prev_warm.get(k)
                    if pv is None:
                        continue
                    a, b = vw[:-1], pv[1:]
                    mm = ~np.isnan(a) & ~np.isnan(b)
                    np.testing.assert_array_equal(a[mm], b[mm])
            prev_warm = dw
    finally:
        s.close()


def test_served_refresh_matches_cold_on_device(tmp_path):
    now = int(time.time() * 1000)
    t0 = (now - (NN - 1) * 15_000) // STEP * STEP
    rng = np.random.default_rng(0)
    s = Storage(str(tmp_path / "s"))
    try:
        base = np.arange(NN, dtype=np.int64) * 15_000 + t0
        keys = [f'dv{{idx="{i}",instance="h-{i % 16}"}}'.encode()
                for i in range(NS)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, NS)
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])
        ts2 = np.sort(base[None, :] +
                      rng.integers(-JITTER_MS, JITTER_MS + 1, (NS, NN)), axis=1)
        vals2 = np.cumsum(rng.integers(0, 50, (NS, NN)),
                          axis=1).astype(np.float64)
        s.add_rows_columnar(native.ColumnarRows(
            keybuf, np.repeat(koffs, NN), np.repeat(klens, NN),
            ts2.reshape(-1), vals2.reshape(-1)))
        s.force_flush()
        last = vals2[:, -1]
        eng = TPUEngine(value_dtype=np.float32, min_series=2)
        api = PrometheusAPI(s, eng)
        q = "sum by (instance)(rate(dv[5m]))"
        dur = (NN - 1) * 15_000 - 300_000
        # round UP past all initial jittered samples (counter
        # monotonicity across the first refresh; see bench.py)
        end = t0 + -(-((NN - 1) * 15_000 + JITTER_MS) // STEP) * STEP
        kw = dict(step=STEP, storage=s, tpu=eng)
        api._exec_range_cached(EvalConfig(start=end - dur, end=end, **kw),
                               q, end)
        for _ in range(3):
            end += STEP
            incr = rng.integers(0, 50, (NS, 4))
            v2 = last[:, None] + np.cumsum(incr, axis=1)
            last = v2[:, -1]
            tsf = (end - STEP +
                   (np.arange(4, dtype=np.int64) + 1)[None, :] * 15_000 +
                   rng.integers(-JITTER_MS, JITTER_MS + 1, (NS, 4)))
            tsf.sort(axis=1)
            s.add_rows_columnar(native.ColumnarRows(
                keybuf, np.repeat(koffs, 4), np.repeat(klens, 4),
                tsf.reshape(-1), v2.reshape(-1).astype(np.float64)))
            rows = api._exec_range_cached(
                EvalConfig(start=end - dur, end=end, **kw), q, end)
        cold = exec_query(EvalConfig(start=end - dur, end=end, **kw,
                                     disable_cache=True), q)
        da = {ts.metric_name.marshal(): ts.values for ts in rows}
        db = {ts.metric_name.marshal(): ts.values for ts in cold}
        assert set(da) == set(db)
        for k, va in da.items():
            vb = db[k]
            fa, fb = np.isnan(va), np.isnan(vb)
            np.testing.assert_array_equal(fa, fb)
            m = ~fa
            np.testing.assert_allclose(va[m], vb[m], rtol=1e-4)
    finally:
        s.close()
