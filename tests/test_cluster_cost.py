"""Cluster half of the cost-and-profile plane: vmselect-merged
CostTracker totals equal single-node totals (exact for samples/bytes),
old<->new RPC metadata-frame tolerance in both directions, the or-set
filter union through real search RPCs (golden corpus conformance on the
cluster path), and the profile_v1 fan-out with node tagging."""

import json
import os

import numpy as np
import pytest

# NOTE: no zstandard gate — ops/compress falls back to runtime-zlib
# framing when the package is absent (PR 4), and test_cluster.py runs
# the same RPC stack ungated

from victoriametrics_tpu.parallel.cluster_api import (ClusterStorage,
                                                      StorageNodeClient,
                                                      make_storage_handlers)
from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT, HELLO_SELECT,
                                              RPCServer)
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.utils import costacc

HERE = os.path.dirname(__file__)
T0 = 1_753_700_000_000
STEP = 60_000

def seed_rows():
    rows = []
    for i in range(12):
        lab = {"__name__": "orm", "idx": str(i),
               "dc": "east" if i % 2 else "west",
               "team": "a" if i % 3 else "b"}
        for j in range(40):
            rows.append((lab, T0 - 600_000 + j * 15_000, float(i + j)))
    return rows


class _Cluster:
    def __init__(self, tmp, n=2, **kw):
        self.stores, self.servers, nodes = [], [], []
        for k in range(n):
            st = Storage(str(tmp / f"n{k}"))
            self.stores.append(st)
            h = make_storage_handlers(st)
            isrv = RPCServer("127.0.0.1", 0, HELLO_INSERT, h)
            ssrv = RPCServer("127.0.0.1", 0, HELLO_SELECT, h)
            isrv.start()
            ssrv.start()
            self.servers += [isrv, ssrv]
            nodes.append(StorageNodeClient("127.0.0.1", isrv.port,
                                           ssrv.port, name=f"n{k}"))
        self.cluster = ClusterStorage(nodes, **kw)

    def seed(self):
        self.cluster.add_rows(seed_rows())
        for st in self.stores:
            st.force_flush()

    def close(self):
        for srv in self.servers:
            srv.stop()
        self.cluster.close()
        for st in self.stores:
            st.close()


@pytest.fixture()
def cluster(tmp_path):
    c = _Cluster(tmp_path, n=2)
    c.seed()
    yield c.cluster
    c.close()


@pytest.fixture()
def single(tmp_path):
    s = Storage(str(tmp_path / "single"))
    s.add_rows(seed_rows())
    s.force_flush()
    yield s
    s.close()


def _kw(storage):
    return dict(start=T0 - 300_000, end=T0, step=STEP, storage=storage)


class TestClusterCostEquality:
    def test_fanout_merged_cost_equals_single_node(self, cluster, single):
        q = "sum(rate(orm[5m]))"
        ec_s = EvalConfig(**_kw(single))
        ec_c = EvalConfig(**_kw(cluster))
        rs = exec_query(ec_s, q)
        rc = exec_query(ec_c, q)
        assert len(rs) == len(rc) == 1
        np.testing.assert_allclose(rs[0].values, rc[0].values)
        cs, cc = ec_s.cost.summary(), ec_c.cost.summary()
        # exact equality for samples and bytes (RF=1: disjoint shards)
        assert cc["samplesScanned"] == cs["samplesScanned"] > 0
        assert cc["bytesRead"] == cs["bytesRead"] > 0
        # the storage-side shipped counts sum to the single-node scan
        assert cc["storageSamplesScanned"] == cs["samplesScanned"]
        # both nodes shipped a cost frame; no partial accounting
        assert ec_c.cost.remote_nodes == 2
        assert "costPartial" not in cc
        assert cc["rpcBytes"] > 0
        # remote fetch CPU buckets merged in under the same names
        assert any(k.startswith("fetch:")
                   for k in cc["cpuMsByPhase"])

    def test_old_server_new_client_degrades_to_partial(self, cluster,
                                                       monkeypatch):
        """New vmselect against old vmstorage (legacy meta dialect): the
        search works, cost accounting goes partial, no error."""
        monkeypatch.setenv("VM_RPC_LEGACY_META", "1")
        ec = EvalConfig(**_kw(cluster))
        rows = exec_query(ec, "sum(rate(orm[5m]))")
        assert len(rows) == 1
        s = ec.cost.summary()
        assert s["costPartial"] is True
        assert "storageSamplesScanned" not in s
        # the evaluator's own count still works
        assert s["samplesScanned"] > 0

    def test_old_client_new_server_ignores_extras(self, cluster):
        """Old vmselect against new vmstorage: emulate the pre-cost
        client read path (partial flag + optional trace only) at the
        marshal level and prove the response parses clean."""
        node = cluster.nodes[0]
        from victoriametrics_tpu.parallel.rpc import Writer
        from victoriametrics_tpu.parallel.cluster_api import (
            _write_filters, _write_tenant)
        w = _write_tenant(Writer(), (0, 0))
        _write_filters(w, [])
        w.i64(T0 - 900_000).i64(T0)
        # old clients send neither trace flag nor budget nor or_sets
        frames = list(node.select.call_stream("searchColumns_v1", w))
        meta = frames[-1]
        n = meta.u64()
        assert n == (1 << 32) - 1
        partial = bool(meta.u64())
        assert partial is False
        # legacy parse: first bytes field is "the trace"; an empty slot
        # fails json and is IGNORED by the old guard — exactly the old
        # client's behavior against this new frame
        assert meta.remaining
        b1 = meta.bytes_()
        with pytest.raises(ValueError):
            json.loads(b1)  # b"" — old client's except path
        # extras bytes follow; old clients never read them
        assert meta.remaining

    def test_tenant_usage_recorded_on_storage_nodes(self, cluster):
        """The vmstorage search handlers fold node-side cost into the
        per-tenant usage table (both node handlers run in-process
        here): one fan-out query leaves a non-zero 0:0 row WITHOUT any
        client-side record_usage call."""
        costacc.TENANT_USAGE.reset()
        ec = EvalConfig(**_kw(cluster))
        exec_query(ec, "orm")
        snap = costacc.TENANT_USAGE.snapshot()
        row = next(r for r in snap if r["tenant"] == "0:0")
        assert row["samplesScanned"] > 0
        assert row["queries"] >= 2  # one search RPC per node


CASES = json.load(open(os.path.join(HERE, "golden_or_corpus.json")))


class TestClusterOrUnion:
    @pytest.mark.parametrize("case", CASES, ids=lambda c: c["q"][:60])
    def test_golden_corpus_through_cluster(self, cluster, single, case):
        """{a="b" or c="d"} through a real vmselect fan-out returns
        identical rows to plain storage (acceptance: the golden corpus
        extended to the cluster path)."""
        got = exec_query(EvalConfig(**_kw(cluster)), case["q"])
        want = exec_query(EvalConfig(**_kw(single)), case["q"])
        gm = {r.metric_name.marshal(): np.asarray(r.values) for r in got}
        wm = {r.metric_name.marshal(): np.asarray(r.values) for r in want}
        assert set(gm) == set(wm) and len(gm) > 0, case["q"]
        for k in gm:
            np.testing.assert_array_equal(gm[k], wm[k], err_msg=case["q"])

    def test_union_against_legacy_node_falls_back_per_set(self, cluster,
                                                          single,
                                                          monkeypatch):
        """A union-less (old) storage node doesn't ack or_sets; the
        client re-issues one legacy call per set — same rows, no
        error."""
        monkeypatch.setenv("VM_RPC_LEGACY_META", "1")
        q = 'orm{dc="east" or team="b"}'
        got = exec_query(EvalConfig(**_kw(cluster)), q)
        want = exec_query(EvalConfig(**_kw(single)), q)
        assert len(got) == len(want) > 0
        for a, b in zip(got, want):
            assert a.metric_name.marshal() == b.metric_name.marshal()
            np.testing.assert_array_equal(a.values, b.values)

    def test_cluster_declares_union_support(self, cluster):
        assert cluster.supports_filter_union is True
        # the loud QueryError for union-less backends must be GONE on
        # the cluster path
        from victoriametrics_tpu.query.eval import filters_from_metric_expr
        from victoriametrics_tpu.query.metricsql import parse
        sets = filters_from_metric_expr(parse('{a="b" or c="d"}'), cluster)
        assert isinstance(sets[0], list) and len(sets) == 2


class TestProfileFanout:
    def test_profile_report_tags_nodes(self, cluster, monkeypatch):
        monkeypatch.setenv("VM_PROFILE_HZ", "50")
        from victoriametrics_tpu.utils import profiler
        try:
            profiler.PROFILER.take_sample()
            reps = cluster.profile_report()
            assert {r["node"] for r in reps} == {"n0", "n1"}
            for r in reps:
                assert r["stacks"], r["node"]
        finally:
            profiler.PROFILER.stop()

    def test_profile_report_reset_propagates_to_nodes(self, cluster,
                                                      monkeypatch):
        """?reset=1 must open a fresh window CLUSTER-wide: the reset
        flag rides profile_v1, so node aggregates clear too (an old
        node ignoring the trailing flag simply keeps its window)."""
        monkeypatch.setenv("VM_PROFILE_HZ", "50")
        from victoriametrics_tpu.utils import profiler
        try:
            profiler.PROFILER.take_sample()
            reps = cluster.profile_report(reset=True)
            # both fake nodes share ONE in-process profiler: the first
            # node's reset may empty the second's snapshot, so only
            # assert that the read happened and the reset stuck
            assert any(r["stacks"] for r in reps)
            assert profiler.PROFILER.snapshot()["samples"] == 0
        finally:
            profiler.PROFILER.stop()

    def test_profile_v1_disabled_node_tolerated(self, cluster,
                                                monkeypatch):
        monkeypatch.setenv("VM_PROFILE_HZ", "0")
        assert cluster.profile_report() == []

    def test_vmselect_http_profile_merges_nodes(self, cluster,
                                                monkeypatch):
        monkeypatch.setenv("VM_PROFILE_HZ", "50")
        from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
        from victoriametrics_tpu.httpapi.server import HTTPServer
        from victoriametrics_tpu.utils import profiler
        from tests.apptest_helpers import Client
        api = PrometheusAPI(cluster)
        srv = HTTPServer("127.0.0.1", 0)
        api.register(srv, mode="select")
        srv.start()
        try:
            profiler.PROFILER.take_sample()
            client = Client(srv.port)
            code, body = client.get("/api/v1/status/profile",
                                    format="raw")
            assert code == 200
            snaps = json.loads(body)["data"]
            nodes = {s.get("node") for s in snaps}
            assert {"vmselect", "n0", "n1"} <= nodes
            # collapsed rendering carries the node prefixes
            code, body = client.get("/api/v1/status/profile")
            assert code == 200
            assert b"n0/" in body and b"n1/" in body
        finally:
            srv.stop()
            profiler.PROFILER.stop()
