"""Fused host aggregation (aggr by(...)(rollup(selector)) computed as one
columnar fetch -> packed rollup -> per-group reduction, no per-series
Timeseries): results must be BIT-IDENTICAL to the unfused path
(VM_HOST_FUSED_AGGR=0), and the (G, T) eval-level cache it feeds must
serve repeated/rolling evaluations without rebuilding per-series state."""

import hashlib
import time

import numpy as np
import pytest

from victoriametrics_tpu.query import eval as eval_mod
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.rollup_result_cache import GLOBAL as rcache
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage

STEP = 60_000
NS, NN = 60, 300


def _sha(rows) -> str:
    h = hashlib.sha256()
    for ts in sorted(rows, key=lambda t: t.metric_name.marshal()):
        h.update(ts.metric_name.marshal())
        h.update(np.ascontiguousarray(ts.values).tobytes())
    return h.hexdigest()


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("hfa")
    s = Storage(str(tmp / "s"))
    rng = np.random.default_rng(11)
    t0 = (int(time.time() * 1000) - NN * 15_000) // STEP * STEP
    rows = []
    for i in range(NS):
        ts = np.sort(t0 + np.arange(NN) * 15_000 +
                     rng.integers(-2000, 2001, NN))
        vals = np.cumsum(rng.integers(0, 40, NN)).astype(np.float64)
        rows.extend((({"__name__": "hfa", "i": str(i), "g": f"g{i % 7}"},
                      int(ts[j]), float(vals[j])) for j in range(NN)))
    s.add_rows(rows)
    s.force_flush()
    yield s, t0
    s.close()


QUERIES = [
    "sum by (g)(rate(hfa[2m]))",
    "sum(rate(hfa[2m]))",
    "count by (g)(rate(hfa[2m]))",
    "avg by (g)(increase(hfa[2m]))",
    "min by (g)(hfa)",
    "max without (i)(delta(hfa[2m]))",
    # keep_name=False rollup grouped by __name__: blanked-name semantics
    "sum by (__name__)(rate(hfa[2m]))",
    # keep_name=True rollup grouped by __name__ keeps the group
    "sum by (__name__)(avg_over_time(hfa[2m]))",
]


class TestFusedEqualsUnfused:
    @pytest.mark.parametrize("q", QUERIES)
    def test_bit_identical(self, store, monkeypatch, q):
        s, t0 = store
        start = t0 + 40 * STEP
        end = t0 + 70 * STEP
        kw = dict(start=start, end=end, step=STEP, storage=s,
                  disable_cache=True)
        monkeypatch.setenv("VM_HOST_FUSED_AGGR", "0")
        unfused = exec_query(EvalConfig(**kw), q)
        monkeypatch.delenv("VM_HOST_FUSED_AGGR")
        fused = exec_query(EvalConfig(**kw), q)
        assert len(fused) == len(unfused) > 0
        assert _sha(fused) == _sha(unfused)

    def test_declines_unsupported_shapes(self, store):
        s, t0 = store
        ec = EvalConfig(start=t0 + 40 * STEP, end=t0 + 50 * STEP,
                        step=STEP, storage=s, disable_cache=True)
        from victoriametrics_tpu.query.exec import parse_cached
        # subquery, limit, multi-arg and non-chunk aggrs fall through
        for q in ("sum(rate(hfa[2m:30s]))",
                  "sum(topk(2, hfa))",
                  "median by (g)(rate(hfa[2m]))"):
            ae = parse_cached(q)
            assert eval_mod._try_host_fused_aggr(ec, ae) is None


class TestFusedCache:
    def test_repeated_eval_hits_aggr_cache(self, store):
        s, t0 = store
        rcache.reset()
        start = t0 + 40 * STEP
        end = t0 + 70 * STEP
        kw = dict(start=start, end=end, step=STEP, storage=s)
        q = "sum by (g)(rate(hfa[2m]))"
        r1 = exec_query(EvalConfig(**kw), q)
        h0 = rcache.hits
        r2 = exec_query(EvalConfig(**kw), q)
        assert rcache.hits > h0
        assert _sha(r1) == _sha(r2)

    def test_rolling_eval_merges_tail(self, store):
        s, t0 = store
        rcache.reset()
        q = "sum by (g)(rate(hfa[2m]))"
        kw = dict(step=STEP, storage=s)
        start, end = t0 + 30 * STEP, t0 + 60 * STEP
        exec_query(EvalConfig(start=start, end=end, **kw), q)
        from victoriametrics_tpu.utils import metrics as metricslib
        m0 = metricslib.REGISTRY.float_counter(
            "vm_rollup_cache_merge_seconds_total").get()
        got = exec_query(EvalConfig(start=start + STEP, end=end + STEP,
                                    **kw), q)
        cold = exec_query(EvalConfig(start=start + STEP, end=end + STEP,
                                     **kw, disable_cache=True), q)
        assert _sha(got) == _sha(cold)
        assert metricslib.REGISTRY.float_counter(
            "vm_rollup_cache_merge_seconds_total").get() > m0

    def test_group_memo_tracks_series_churn(self, store, tmp_path):
        """The grouping memo must recompute when the fetched series set
        changes (new series mid-window)."""
        s = Storage(str(tmp_path / "churn"))
        t0 = (int(time.time() * 1000) - 100 * 15_000) // STEP * STEP
        s.add_rows([({"__name__": "chn", "i": str(i), "g": f"g{i % 2}"},
                     t0 + j * 15_000, float(j))
                    for i in range(4) for j in range(100)])
        s.force_flush()
        q = "sum by (g)(rate(chn[2m]))"
        kw = dict(step=STEP, storage=s, disable_cache=True)
        end = t0 + 20 * STEP
        r1 = exec_query(EvalConfig(start=t0 + 5 * STEP, end=end, **kw), q)
        assert len(r1) == 2
        # a third group appears
        s.add_rows([({"__name__": "chn", "i": "99", "g": "g9"},
                     t0 + j * 15_000, float(j)) for j in range(100)])
        r2 = exec_query(EvalConfig(start=t0 + 5 * STEP, end=end, **kw), q)
        assert len(r2) == 3
        s.close()
