"""Deadline-taint pass tests (devtools/deadline_taint.py, rule VMT012).

Fixture packages are synthesized in tmp_path so the pass runs against a
known call graph: a serving entry (RPC dispatch dict) reaching a
blocking primitive with no deadline seam on the path must be flagged
with a witness chain; the budget-wrapped twin must be clean.  Also pins
the runtime fix the pass forced: RPCClientPool's deadline-free acquire
is bounded by VM_RPC_ACQUIRE_MAX_S instead of parking forever."""

import textwrap
import threading

import pytest

from victoriametrics_tpu.devtools import deadline_taint as dt
from victoriametrics_tpu.parallel import rpc


def _write_pkg(tmp_path, body: str):
    d = tmp_path / "fixture_pkg"
    d.mkdir()
    (d / "srv.py").write_text(textwrap.dedent(body), encoding="utf-8")
    return d


# An RPC dispatch dict is recognized as a serving entry when it has
# >= 3 "*_vN" string keys mapping to same-module handler names.
_DISPATCH = """
        HANDLERS = {
            "a_v1": h_a,
            "b_v1": h_b,
            "c_v1": h_c,
        }
"""


def test_blocking_call_behind_entry_is_flagged(tmp_path):
    pkg = _write_pkg(tmp_path, """
        import time

        def helper():
            time.sleep(0.5)

        def h_a(r, w):
            helper()

        def h_b(r, w):
            pass

        def h_c(r, w):
            pass
    """ + _DISPATCH)
    findings, _used = dt.run_pass(paths=[str(pkg)])
    assert len(findings) == 1, findings
    f = findings[0]
    assert f.rule == dt.RULE_ID
    assert "time.sleep" in f.message
    # the witness chain names the entry handler and the helper
    assert "h_a" in f.message and "helper" in f.message


def test_deadline_seam_cuts_the_taint(tmp_path):
    """settimeout() on the socket before recv makes the def a seam —
    blocking below a seam is budgeted, not flagged."""
    pkg = _write_pkg(tmp_path, """
        import socket

        def helper(s):
            s.settimeout(2.0)
            return s.recv(16)

        def h_a(r, w):
            helper(socket.socket())

        def h_b(r, w):
            pass

        def h_c(r, w):
            pass
    """ + _DISPATCH)
    findings, _used = dt.run_pass(paths=[str(pkg)])
    assert findings == [], [f.message for f in findings]


def test_suppressed_site_counts_as_used(tmp_path):
    pkg = _write_pkg(tmp_path, """
        import time

        def h_a(r, w):
            time.sleep(1)  # vmt: disable=VMT012

        def h_b(r, w):
            pass

        def h_c(r, w):
            pass
    """ + _DISPATCH)
    findings, used = dt.run_pass(paths=[str(pkg)])
    assert findings == [], [f.message for f in findings]
    # the disable comment is consumed -> VMT013 won't call it stale
    (rel,) = used
    assert any(rule == dt.RULE_ID for _ln, rule in used[rel])


def test_unreachable_blocking_code_not_flagged(tmp_path):
    """Blocking outside the entry closure (no caller path) is out of
    scope for a *serving* latency pass."""
    pkg = _write_pkg(tmp_path, """
        import time

        def offline_maintenance():
            time.sleep(30)

        def h_a(r, w):
            pass

        def h_b(r, w):
            pass

        def h_c(r, w):
            pass
    """ + _DISPATCH)
    findings, _used = dt.run_pass(paths=[str(pkg)])
    assert findings == [], [f.message for f in findings]


def test_repo_tree_is_clean():
    """The real tree carries ZERO baselined VMT012 findings — the pass
    found real gaps and they were fixed, not suppressed wholesale."""
    findings, _used = dt.run_pass()
    assert findings == [], [f.message for f in findings]


# -- the runtime fix VMT012 forced ------------------------------------------

def test_pool_acquire_without_deadline_is_bounded(monkeypatch):
    """Deadline-free RPCClientPool._acquire must not park forever on the
    connection semaphore: it waits at most VM_RPC_ACQUIRE_MAX_S and then
    raises a retryable RPCError (waited=False -> safe to reroute)."""
    monkeypatch.setenv("VM_RPC_ACQUIRE_MAX_S", "0.05")
    pool = rpc.RPCClientPool("127.0.0.1", 1, b"hello", max_conns=1)
    assert pool._sem.acquire(timeout=1)  # wedge the only slot
    try:
        with pytest.raises(rpc.RPCError) as ei:
            pool._acquire("writeRows_v1", 0.0)
        assert not isinstance(ei.value, rpc.RPCDeadlineError)
        assert ei.value.waited is False
    finally:
        pool._sem.release()


def test_pool_acquire_with_deadline_raises_deadline_error(monkeypatch):
    monkeypatch.setenv("VM_RPC_ACQUIRE_MAX_S", "5")
    pool = rpc.RPCClientPool("127.0.0.1", 1, b"hello", max_conns=1)
    assert pool._sem.acquire(timeout=1)
    try:
        import time
        with pytest.raises(rpc.RPCDeadlineError) as ei:
            pool._acquire("search_v1", time.monotonic() + 0.05)
        assert ei.value.waited is False
    finally:
        pool._sem.release()
