"""apptest harness (reference apptest/: spawns real binaries on localhost,
drives them over HTTP with typed helpers). Provides an in-process vmsingle
fixture for speed plus a subprocess spawner for process-level tests."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.parse
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class VmSingleProc:
    """vmsingle in a subprocess (apptest/app.go analog) — thin wrapper over
    AppProc that self-allocates the HTTP port."""

    def __init__(self, data_path: str, port: int = 0, extra_flags=()):
        if port == 0:
            port = free_ports(1)[0]
        self.port = port
        self._app = AppProc(
            "vmsingle",
            [f"-storageDataPath={data_path}",
             f"-httpListenAddr=127.0.0.1:{port}", *extra_flags],
            port, "vmsingle")
        self.proc = self._app.proc

    def stop(self):
        self._app.stop()


class Client:
    """HTTP driver (apptest/client.go analog)."""

    def __init__(self, port: int, host="127.0.0.1"):
        self.base = f"http://{host}:{port}"

    def get(self, path: str, **params) -> tuple[int, bytes]:
        url = self.base + path
        if params:
            url += "?" + urllib.parse.urlencode(params, doseq=True)
        try:
            with urllib.request.urlopen(url, timeout=30) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def post(self, path: str, body: bytes = b"", headers=None, **params
             ) -> tuple[int, bytes]:
        url = self.base + path
        if params:
            url += "?" + urllib.parse.urlencode(params, doseq=True)
        req = urllib.request.Request(url, data=body, method="POST",
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    # typed helpers (apptest/model.go analog)

    def query_range(self, query: str, start, end, step) -> dict:
        code, body = self.get("/api/v1/query_range", query=query,
                              start=start, end=end, step=step)
        assert code == 200, body
        return json.loads(body)

    def query(self, query: str, time_s=None) -> dict:
        params = {"query": query}
        if time_s is not None:
            params["time"] = time_s
        code, body = self.get("/api/v1/query", **params)
        assert code == 200, body
        return json.loads(body)

    def force_flush(self):
        code, _ = self.get("/internal/force_flush")
        assert code == 200


class AppProc:
    """Any apps/* module in a subprocess (cluster apptest processes).
    `env` adds/overrides environment variables for the child (chaos
    tests use it for VM_FAULTS / VM_TENANT_QUOTAS / RPC knobs)."""

    def __init__(self, module: str, flags: list, health_port: int,
                 name: str = "", env: dict | None = None):
        env_overrides = env
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env.setdefault("JAX_PLATFORMS", "cpu")
        if env_overrides:
            env.update(env_overrides)
        self.name = name or module
        self.port = health_port
        self.proc = subprocess.Popen(
            [sys.executable, "-m", f"victoriametrics_tpu.apps.{module}",
             *flags],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        self._wait_ready()

    def _wait_ready(self, timeout=30):
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{self.port}/health", timeout=1):
                    return
            except OSError:
                if self.proc.poll() is not None:
                    out = self.proc.stdout.read().decode()
                    raise RuntimeError(f"{self.name} died:\n{out}")
                time.sleep(0.1)
        raise TimeoutError(f"{self.name} did not become ready")

    def stop(self, kill=False):
        if kill:
            self.proc.kill()
        else:
            self.proc.terminate()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()


def free_ports(n: int) -> list:
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports
