"""Differential fuzz: native vm_f2d_grouped must be BIT-IDENTICAL to the
Python float_to_decimal_grouped pipeline (the flush hot path silently
routes through the native twin for batches >= 256 values; any drift
between the two would corrupt stored mantissas undetected).

Both sides share the recurrence-built pow10 table — np.power's SIMD path
differs from libm pow by an ulp at large exponents, which is exactly the
drift this suite guards against."""

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.ops import decimal as dec


def _python_grouped(v, starts):
    """Force the pure-Python pipeline (bypass the native dispatch)."""
    exps = np.zeros(starts.size, dtype=np.int64)
    ends = np.append(starts[1:], v.size)
    sizes = ends - starts
    m, e, normal, specials = dec._f2d_element_phase(v)
    BIG = np.int64(1 << 40)
    absm = np.maximum(np.abs(m).astype(np.float64), 1.0)
    allowed_up = np.floor(
        np.log10(dec.MAX_MANTISSA / absm)).astype(np.int64)
    emin_g = np.minimum.reduceat(np.where(normal, e, BIG), starts)
    floor_g = np.maximum.reduceat(
        np.where(normal, e - allowed_up, -BIG), starts)
    has_norm_g = np.logical_or.reduceat(normal, starts)
    exp_g = np.minimum(emin_g, dec._MAX_EXP)
    exp_g = np.where(floor_g > exp_g, floor_g, exp_g)
    exp_g = np.clip(exp_g, dec._MIN_EXP, dec._MAX_EXP)
    exp_g = np.where(has_norm_g, exp_g, 0)
    exp_elem = np.repeat(exp_g, sizes)
    m_all = dec._f2d_rescale(m, e, normal, exp_elem)
    m_out = dec._f2d_apply_specials(m_all, specials)
    return m_out, exp_g.astype(np.int64)


def _random_starts(rng, n):
    k = max(1, n // 37)
    starts = np.sort(rng.choice(n, size=k, replace=False))
    starts[0] = 0
    return np.unique(starts).astype(np.int64)


CASES = {
    "counters": lambda rng: np.cumsum(
        rng.integers(0, 50, 4000)).astype(np.float64),
    "gauges_3dp": lambda rng: np.round(rng.uniform(-1000, 1000, 4000), 3),
    "full_precision": lambda rng: rng.standard_normal(4000) *
    np.exp(rng.uniform(-200, 200, 4000)),
    "extreme_magnitudes": lambda rng: 10.0 ** rng.uniform(-300, 300, 2000)
    * np.where(rng.random(2000) < .5, -1, 1),
    "large_base_counters": lambda rng: 1e15 + np.cumsum(
        rng.integers(0, 3, 3000)).astype(np.float64),
}


@pytest.mark.skipif(not native.available(), reason="needs native codec")
@pytest.mark.parametrize("case", sorted(CASES))
def test_native_matches_python(case):
    rng = np.random.default_rng(hash(case) % 2 ** 31)
    v = CASES[case](rng)
    starts = _random_starts(rng, v.size)
    m_py, e_py = _python_grouped(v, starts)
    m_c, e_c = native.f2d_grouped(v, starts)
    np.testing.assert_array_equal(m_py, m_c, err_msg=case)
    np.testing.assert_array_equal(e_py, e_c, err_msg=case)


@pytest.mark.skipif(not native.available(), reason="needs native codec")
def test_native_matches_python_specials_and_edges():
    rng = np.random.default_rng(99)
    sp = rng.uniform(0, 100, 1000)
    sp[::7] = np.nan
    sp[1::13] = np.inf
    sp[2::17] = -np.inf
    sp[3::19] = dec.STALE_NAN
    sp[4::23] = 0.0
    edges = np.array([1e-3, 1e3, 0.001, 1000.0, 2 / 3, 1 / 3, 0.1, 0.2,
                      0.3, 123.456, 1e17, -1e17, 9.999999999999999e16,
                      5e-324, 1e-320, 1e-310, 2.2e-308, 1.7e308, -1.7e308])
    for v in (sp, edges):
        starts = _random_starts(rng, v.size)
        m_py, e_py = _python_grouped(v, starts)
        m_c, e_c = native.f2d_grouped(v, starts)
        np.testing.assert_array_equal(m_py, m_c)
        np.testing.assert_array_equal(e_py, e_c)


@pytest.mark.skipif(not native.available(), reason="needs native codec")
def test_grouped_dispatch_uses_native():
    """float_to_decimal_grouped itself (the dispatching entry) must agree
    with the forced-Python path at and above the dispatch threshold."""
    rng = np.random.default_rng(3)
    v = np.round(rng.uniform(-10, 10, 2048), 2)
    starts = _random_starts(rng, v.size)
    m_d, e_d = dec.float_to_decimal_grouped(v, starts)
    m_py, e_py = _python_grouped(v, starts)
    np.testing.assert_array_equal(m_d, m_py)
    np.testing.assert_array_equal(e_d, e_py)
