"""Tier-1 + device-suite guards for fleet-batched device serving
(ISSUE 19): every active materialized stream with a device-resident
window is served from ONE fused mesh launch per bucket per interval
(query/fleet.py), not one program per stream.

Guards:
  * exactly one fused launch per bucket per warm interval, zero
    recompiles (plane compile counter reads REAL backend compiles via
    the jax monitoring event, not jit-cache growth);
  * numeric parity at rtol=1e-12 with BOTH oracles — the cold polled
    host evaluation and the VM_DEVICE_FLEET=0 per-stream rolling path —
    across mixed grids landing in different buckets;
  * churn (new same-shaped subscriber, structural version bump) repacks
    members without recompiling the bucket and recovers parity;
  * the rows-share cost split of the shared launch sums exactly to the
    launch wall across /api/v1/status/usage rows;
  * a race-marked stress (tools/race.sh): subscriber churn + live
    ingest + concurrent pumps while the fleet serves.

Values are compared NUMERICALLY (not as formatted strings): mesh-device
and host summation orders differ at the last ulp, which is documented
drift, not a regression."""

import json
import threading
import time

import numpy as np
import pytest

from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.query import fleet as fleetmod
from victoriametrics_tpu.query import rollup_result_cache as rrc
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.matstream import StreamClient
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage

STEP = 60_000
SCRAPE = 15_000
NS = 16
NN = 240
DUR = 20 * STEP
PANELS = [
    "sum by (g)(rate(fl_m[5m]))",   # G=4  -> rung 8   (bucket A)
    "sum by (i)(rate(fl_m[5m]))",   # G=16 -> rung 16  (bucket B)
    "max by (g)(rate(fl_m[5m]))",   # bucket A (aggr code is traced)
    "count by (g)(rate(fl_m[5m]))",  # bucket A
]


def _mesh8():
    import jax

    from victoriametrics_tpu.parallel.mesh import make_mesh
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(n_series=8, n_time=1, devices=devs[:8])


def _seed(s: Storage, t0: int, ns: int = NS, n: int = NN, seed: int = 7):
    rng = np.random.default_rng(seed)
    rows = []
    last = np.empty(ns)
    for i in range(ns):
        vals = np.cumsum(rng.integers(0, 30, n)).astype(np.float64)
        last[i] = vals[-1]
        rows.extend((({"__name__": "fl_m", "i": str(i), "g": f"g{i % 4}"},
                      t0 + j * SCRAPE, float(vals[j])) for j in range(n)))
    s.add_rows(rows)
    s.force_flush()
    return last, rng


def _ingest(s: Storage, rng, last, end: int, ns: int = NS, k: int = 4):
    rows = []
    for i in range(ns):
        incr = np.cumsum(rng.integers(0, 30, k))
        rows.extend((({"__name__": "fl_m", "i": str(i), "g": f"g{i % 4}"},
                      end - STEP + (j + 1) * SCRAPE, float(last[i] + incr[j]))
                     for j in range(k)))
        last[i] += incr[-1]
    s.add_rows(rows)


def _grid_t0(n: int = NN) -> int:
    now = int(time.time() * 1000)
    return (now - (n - 1) * SCRAPE) // STEP * STEP


def _end0(t0: int, n: int = NN) -> int:
    return t0 + ((n - 1) * SCRAPE // STEP + 1) * STEP


def polled(storage, q, start, end, step):
    """The host-path cold oracle (no tpu engine, no caches)."""
    ec = EvalConfig(start=start, end=end, step=step, storage=storage,
                    disable_cache=True)
    rows = exec_query(ec, q)
    grid = ec.timestamps() / 1e3
    out = {}
    for r in rows:
        vals = np.array([[float(t), v] for t, v in zip(grid, r.values)
                         if not np.isnan(v)])
        if len(vals):
            out[json.dumps(r.metric_name.to_dict(), sort_keys=True)] = vals
    return out


def _np_rows(entries):
    return {json.dumps(e["metric"], sort_keys=True):
            np.array([[float(t), float(v)] for t, v in e["values"]])
            for e in entries}


def _assert_close(got: dict, want: dict, ctx: str = ""):
    assert set(got) == set(want), (
        ctx, sorted(set(got) ^ set(want))[:4])
    for k in sorted(got):
        assert got[k].shape == want[k].shape, (ctx, k)
        np.testing.assert_allclose(got[k], want[k], rtol=1e-12, atol=0,
                                   err_msg=f"{ctx} {k}")


def _pump(subs, clis, end):
    for sub, cli in zip(subs, clis):
        f = sub.next_frame(timeout_s=10.0, now_ms=end)
        assert f is not None, "stream did not advance"
        cli.apply(f)


def test_fleet_single_launch_per_interval(tmp_path):
    """THE fleet guard (tools/check.sh device stage): N panels of mixed
    aggregates over shared buckets cost exactly one fused launch per
    bucket per warm interval, recompile nothing, and stay at rtol=1e-12
    parity with the cold host oracle."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    mesh = _mesh8()
    rrc.GLOBAL.reset()
    s = Storage(str(tmp_path / "s"))
    try:
        t0 = _grid_t0()
        last, rng = _seed(s, t0)
        end = _end0(t0)
        engine = TPUEngine(min_series=4, mesh=mesh)
        api = PrometheusAPI(s, engine)
        subs = [api.matstreams.subscribe(q, STEP, DUR) for q in PANELS]
        clis = [StreamClient() for _ in PANELS]
        for sub, cli in zip(subs, clis):
            f = sub.next_frame(timeout_s=10.0, now_ms=end)
            assert f["type"] == "snapshot"
            cli.apply(f)
        plane = engine.fleet()
        for r in range(1, 5):
            end += STEP
            _ingest(s, rng, last, end)
            st0 = plane.stats()
            _pump(subs, clis, end)
            st1 = plane.stats()
            for q, cli in zip(PANELS, clis):
                _assert_close(_np_rows(cli.result()),
                              polled(s, q, end - DUR, end, STEP),
                              ctx=f"interval {r} {q!r}")
            if r >= 2:
                nb = st1["buckets"]
                assert nb == 2, st1
                assert st1["members"] == len(PANELS), st1
                assert st1["launches"] - st0["launches"] == nb, (
                    f"interval {r}: {st1['launches'] - st0['launches']} "
                    f"launches for {nb} buckets — fleet batching regressed "
                    "to per-stream programs")
                assert st1["served"] - st0["served"] == len(PANELS), st1
                assert st1["compiles"] - st0["compiles"] == 0, (
                    f"interval {r}: warm interval paid a backend compile")
    finally:
        s.close()


def _run_sequence(tmp_path, sub, mesh, t0, panels, intervals=4):
    """One deterministic rolling sequence (same t0 + seeds => identical
    rows); returns per-interval {query: rows-map}."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    rrc.GLOBAL.reset()
    s = Storage(str(tmp_path / sub))
    try:
        last, rng = _seed(s, t0)
        end = _end0(t0)
        engine = TPUEngine(min_series=4, mesh=mesh)
        api = PrometheusAPI(s, engine)
        subs = [api.matstreams.subscribe(q, STEP, d) for q, d in panels]
        clis = [StreamClient() for _ in panels]
        for sub_, cli in zip(subs, clis):
            cli.apply(sub_.next_frame(timeout_s=10.0, now_ms=end))
        out = []
        for _ in range(intervals):
            end += STEP
            _ingest(s, rng, last, end)
            _pump(subs, clis, end)
            out.append({q: _np_rows(cli.result())
                        for (q, _), cli in zip(panels, clis)})
        return out, engine.fleet().stats()
    finally:
        s.close()


def test_fleet_matches_per_stream_oracle_mixed_grids(tmp_path, monkeypatch):
    """Batched-vs-per-stream equality oracle: the same deterministic
    sequence served by the fleet and by VM_DEVICE_FLEET=0 (the
    per-stream rolling path) agrees at rtol=1e-12 — across two panels
    with DIFFERENT durations (different T rungs => different buckets)."""
    mesh = _mesh8()
    panels = [("sum by (g)(rate(fl_m[5m]))", DUR),
              ("max by (i)(rate(fl_m[5m]))", 30 * STEP)]
    t0 = _grid_t0()
    monkeypatch.delenv("VM_DEVICE_FLEET", raising=False)
    got, st = _run_sequence(tmp_path, "fleet-on", mesh, t0, panels)
    assert st["launches"] > 0 and st["members"] == 2, (
        f"fleet never engaged: {st}")
    monkeypatch.setenv("VM_DEVICE_FLEET", "0")
    want, st_off = _run_sequence(tmp_path, "fleet-off", mesh, t0, panels)
    assert st_off["launches"] == 0, (
        "VM_DEVICE_FLEET=0 still launched fleet programs")
    for r, (g, w) in enumerate(zip(got, want)):
        for q, _ in panels:
            _assert_close(g[q], w[q], ctx=f"interval {r} {q!r}")


def test_fleet_churn_repacks_without_recompiling(tmp_path):
    """Member churn within a bucket's ladder rungs never recompiles: a
    new same-shaped subscriber post-warm is adopted into the existing
    bucket (B_pad rung has headroom) with zero backend compiles; a
    structural bump (brand-new series) evicts to the loud cold-rebuild
    path and the fleet re-adopts with parity intact."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    mesh = _mesh8()
    rrc.GLOBAL.reset()
    s = Storage(str(tmp_path / "s"))
    try:
        t0 = _grid_t0()
        last, rng = _seed(s, t0)
        end = _end0(t0)
        engine = TPUEngine(min_series=4, mesh=mesh)
        api = PrometheusAPI(s, engine)
        panels = PANELS[:3]
        subs = [api.matstreams.subscribe(q, STEP, DUR) for q in panels]
        clis = [StreamClient() for _ in panels]
        for sub, cli in zip(subs, clis):
            cli.apply(sub.next_frame(timeout_s=10.0, now_ms=end))
        plane = engine.fleet()
        for _ in range(2):  # warm the buckets
            end += STEP
            _ingest(s, rng, last, end)
            _pump(subs, clis, end)
        warm = plane.stats()
        assert warm["members"] == 3, warm

        # (a) a new same-shaped subscriber: adopted, ZERO new compiles
        q_new = "avg by (g)(rate(fl_m[5m]))"
        sub_new = api.matstreams.subscribe(q_new, STEP, DUR)
        cli_new = StreamClient()
        cli_new.apply(sub_new.next_frame(timeout_s=10.0, now_ms=end))
        panels = panels + [q_new]
        subs.append(sub_new)
        clis.append(cli_new)
        for _ in range(2):
            end += STEP
            _ingest(s, rng, last, end)
            _pump(subs, clis, end)
        st = plane.stats()
        assert st["members"] == 4, st
        assert st["buckets"] == warm["buckets"], st
        assert st["compiles"] - warm["compiles"] == 0, (
            "adopting a same-shaped subscriber recompiled the bucket")

        # (b) structural churn: a NEW series bumps the structural
        # version, evicting every member to the loud cold-rebuild path
        # (S 16 -> 17 also crosses the S rung, so the re-adopted members
        # land in fresh buckets); the fleet re-adopts within the
        # post-eviction retry budget and parity holds again
        s.add_rows([({"__name__": "fl_m", "i": str(NS), "g": "g0"},
                     end + (j + 1) * SCRAPE, float(j)) for j in range(4)])
        last = np.append(last, 3.0)
        for _ in range(3):
            end += STEP
            _ingest(s, rng, last, end, ns=NS + 1)
            _pump(subs, clis, end)
        st2 = plane.stats()
        assert st2["members"] == 4, (
            f"fleet did not re-adopt after structural churn: {st2}")
        for q, cli in zip(panels, clis):
            _assert_close(_np_rows(cli.result()),
                          polled(s, q, end - DUR, end, STEP),
                          ctx=f"post-churn {q!r}")
    finally:
        s.close()


def test_fleet_cost_split_sums_to_launch_total(tmp_path, monkeypatch):
    """Per-stream cost attribution: the rows-share split of each shared
    launch lands in the streams' usage rows (deviceExecMs) and sums to
    the measured launch wall — the last member takes the exact
    remainder, so nothing is lost or double-billed."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    from victoriametrics_tpu.utils import flightrec
    mesh = _mesh8()
    rrc.GLOBAL.reset()
    s = Storage(str(tmp_path / "s"))
    walls = []
    orig_rec = flightrec.rec

    def spy(name, t0, dur, arg=None):
        if name == "device:fleet_launch":
            walls.append(dur)
        return orig_rec(name, t0, dur, arg)

    monkeypatch.setattr(flightrec, "rec", spy)
    try:
        t0 = _grid_t0()
        last, rng = _seed(s, t0)
        end = _end0(t0)
        engine = TPUEngine(min_series=4, mesh=mesh)
        api = PrometheusAPI(s, engine)
        subs = [api.matstreams.subscribe(q, STEP, DUR) for q in PANELS]
        clis = [StreamClient() for _ in PANELS]
        for sub, cli in zip(subs, clis):
            cli.apply(sub.next_frame(timeout_s=10.0, now_ms=end))

        def exec_ms():
            return sum(ms.usage_row().get("deviceExecMs", 0.0)
                       for ms in api.matstreams.streams())

        plane = engine.fleet()
        for r in range(1, 4):
            end += STEP
            _ingest(s, rng, last, end)
            walls.clear()
            e0 = exec_ms()
            st0 = plane.stats()
            _pump(subs, clis, end)
            if r < 2 or plane.stats()["served"] - st0["served"] != \
                    len(PANELS):
                continue  # adoption interval: shares partly pre-fleet
            billed = exec_ms() - e0
            launched = sum(walls) * 1e3
            assert launched > 0, "no fleet launch recorded"
            assert abs(billed - launched) < 0.05 + 0.002 * len(PANELS), (
                f"interval {r}: usage rows billed {billed:.3f}ms for "
                f"{launched:.3f}ms of shared launches")
    finally:
        s.close()


@pytest.mark.race
class TestFleetRace:
    def test_concurrent_pumps_ingest_churn(self, tmp_path):
        """Race stress (tools/race.sh): subscriber churn + live ingest +
        concurrent cooperative pumps while the fleet plane adopts,
        launches and serves; the steady subscriber keeps advancing, no
        exception escapes, and the quiesced state matches the host
        oracle numerically."""
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        mesh = _mesh8()
        rrc.GLOBAL.reset()
        s = Storage(str(tmp_path / "s"))
        q_steady = PANELS[0]
        try:
            t0 = _grid_t0()
            _seed(s, t0)
            end0 = _end0(t0)
            engine = TPUEngine(min_series=4, mesh=mesh)
            api = PrometheusAPI(s, engine)
            steady = api.matstreams.subscribe(q_steady, STEP, DUR)
            cli = StreamClient()
            cli.apply(steady.next_frame(timeout_s=10.0, now_ms=end0))
            stop = threading.Event()
            errors: list = []
            now_box = [end0]

            def ingester():
                # idempotent values (pure function of the timestamp):
                # rewrites racing an advance stay invisible to the final
                # poll-vs-push comparison
                while not stop.is_set():
                    end = now_box[0] + STEP
                    s.add_rows([
                        ({"__name__": "fl_m", "i": str(i), "g": f"g{i % 4}"},
                         end - STEP + (k + 1) * SCRAPE,
                         float((end // SCRAPE + k) % 1000))
                        for i in range(NS) for k in range(4)])
                    time.sleep(0.002)

            def churner():
                try:
                    while not stop.is_set():
                        sub = api.matstreams.subscribe(
                            "max by (g)(rate(fl_m[5m]))", STEP, DUR)
                        sub.next_frame(timeout_s=0.05, now_ms=now_box[0])
                        sub.close()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            def pumper():
                try:
                    while not stop.is_set():
                        api.matstreams.advance_due(now_box[0])
                        time.sleep(0.001)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=f, daemon=True)
                       for f in (ingester, churner, pumper, pumper)]
            for t in threads:
                t.start()
            end = end0
            try:
                for _ in range(4):
                    end += STEP
                    now_box[0] = end
                    deadline = time.monotonic() + 30.0
                    while time.monotonic() < deadline:
                        f = steady.next_frame(timeout_s=0.2, now_ms=end)
                        if f is not None:
                            cli.apply(f)
                        if cli.window and cli.window[1] >= end:
                            break
                    assert cli.window and cli.window[1] >= end, (
                        "stream stopped advancing under concurrency")
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=10)
            assert not errors, errors
            # quiesced: one final advance sees the final data, then the
            # oracle must hold (numerically; device vs host summation
            # order differs at the last ulp)
            end += STEP
            api.matstreams.advance_due(end)
            while True:
                f = steady.next_frame(timeout_s=0.0, now_ms=end)
                if f is None:
                    break
                cli.apply(f)
            assert cli.window[1] == end
            _assert_close(_np_rows(cli.result()),
                          polled(s, q_steady, cli.window[0], cli.window[1],
                                 STEP), ctx="post-quiesce")
            steady.close()
        finally:
            s.close()


def test_bucket_up_ladder_makes_progress_from_floor_one():
    # regression: cumulative floored multiplies stalled forever at b=1
    # (1*3//2 == 1), hanging any 1-device mesh or VM_FLEET_LADDER_MIN=1
    assert [fleetmod.bucket_up(n, 1) for n in range(1, 10)] == \
        [1, 2, 3, 4, 6, 6, 8, 8, 12]
    # rungs for floors >= 2 are the documented {1, 1.5} * 2^k ladder
    assert [fleetmod.bucket_up(n, 2) for n in (2, 3, 5, 7, 13, 17)] == \
        [2, 3, 6, 8, 16, 24]
    assert [fleetmod.bucket_up(n, 8) for n in (1, 9, 17, 25)] == \
        [8, 12, 24, 32]
    for m in (1, 2, 8):
        prev = 0
        for n in range(1, 600):
            b = fleetmod.bucket_up(n, m)
            assert b >= n and b >= prev
            prev = b
