"""Rolling device tiles: a repeated fused query whose window advances while
ingest appends must be served from the HBM-resident tile via incremental
appends (device scatter + traced grid shift), not a rebuild — and must agree
with the host evaluator exactly (VERDICT r2 #1 'incremental tile
maintenance'; the reference's tail-reuse is rollup_result_cache.go:283).
"""

import numpy as np
import pytest

T0 = 1_753_700_000_000
STEP = 60_000


def _mk_store(tmp_path, n_series=80, n_samples=60):
    from victoriametrics_tpu.storage.storage import Storage
    s = Storage(str(tmp_path / "s"))
    rng = np.random.default_rng(21)
    rows = []
    for i in range(n_series):
        base = np.arange(n_samples, dtype=np.int64) * 15_000 + \
            T0 - 600_000
        ts = np.sort(base + rng.integers(-2000, 2001, n_samples))
        vals = np.cumsum(rng.integers(0, 30, n_samples)).astype(float)
        lab = {"__name__": "rt", "instance": f"h{i % 8}", "job": f"j{i % 3}"}
        rows.extend(zip([lab] * n_samples, ts.tolist(), vals.tolist()))
    s.add_rows(rows)
    s.force_flush()
    return s


def _ingest_newer(s, t_lo, n=4, n_series=80):
    rng = np.random.default_rng(int(t_lo) % 2**31)
    rows = []
    for i in range(n_series):
        ts = t_lo + np.arange(n, dtype=np.int64) * 15_000 + \
            rng.integers(0, 2000)
        vals = (1000 + np.cumsum(rng.integers(0, 30, n))).astype(float)
        lab = {"__name__": "rt", "instance": f"h{i % 8}", "job": f"j{i % 3}"}
        rows.extend(zip([lab] * n, ts.tolist(), vals.tolist()))
    s.add_rows(rows)
    s.force_flush()


def _run(store, q, engine, start, end):
    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.types import EvalConfig
    kw = dict(start=start, end=end, step=STEP, storage=store)
    if engine is not None:
        kw["tpu"] = engine
    else:
        # the host oracle must be a FULL recompute: the eval rollup cache's
        # tail merge recomputes tail steps as instant sub-ranges, which
        # legitimately flips the reference's maxPrevInterval rule
        # (rollup.go:719-728) and shifts edge values
        kw["disable_cache"] = True
    return {r.metric_name.marshal(): np.asarray(r.values)
            for r in exec_query(EvalConfig(**kw), q)}


def _rolling_tiles(engine):
    # resident rolling windows live in the DeviceWindowCache now
    from victoriametrics_tpu.query.tpu_engine import RollingTile
    wc = engine._wcache
    vals = list(wc._entries.values()) if wc is not None else []
    return [v for v in vals if isinstance(v, RollingTile)]


def _check(host, dev, q=""):
    assert set(host) == set(dev) and len(host) > 0
    for k in host:
        np.testing.assert_allclose(dev[k], host[k], rtol=1e-9, atol=1e-9,
                                   equal_nan=True, err_msg=q)


QUERIES = [
    "sum by (instance)(rate(rt[5m]))",
    "avg by (job)(increase(rt[3m]))",
    "quantile(0.9, rate(rt[5m])) by (instance)",
]


class TestRollingTile:

    @pytest.mark.parametrize("q", QUERIES)
    def test_rolling_advance_matches_host(self, tmp_path, q):
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        store = _mk_store(tmp_path)
        try:
            engine = TPUEngine(min_series=4)
            # cold: builds the tile + rolling state
            _check(_run(store, q, None, T0 - 300_000, T0),
                   _run(store, q, engine, T0 - 300_000, T0), q)
            rts = _rolling_tiles(engine)
            assert len(rts) == 1
            # live ingest strictly newer than the covered range, window
            # advances one step: must append, not rebuild
            _ingest_newer(store, T0 + 10_000)
            start2, end2 = T0 - 240_000, T0 + STEP
            _check(_run(store, q, None, start2, end2),
                   _run(store, q, engine, start2, end2), q)
            assert rts[0].appends == 1, "slice was not appended on device"
            # a second advance over the same state
            _ingest_newer(store, T0 + 80_000)
            start3, end3 = T0 - 180_000, T0 + 2 * STEP
            _check(_run(store, q, None, start3, end3),
                   _run(store, q, engine, start3, end3), q)
            assert rts[0].appends == 2
        finally:
            store.close()

    def test_repeat_without_ingest_served_from_tile(self, tmp_path):
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        store = _mk_store(tmp_path)
        try:
            engine = TPUEngine(min_series=4)
            q = QUERIES[0]
            _run(store, q, engine, T0 - 300_000, T0)
            rts = _rolling_tiles(engine)
            # same end, later start: fully inside coverage, zero appends
            host = _run(store, q, None, T0 - 240_000, T0)
            dev = _run(store, q, engine, T0 - 240_000, T0)
            _check(host, dev)
            assert rts[0].appends == 0
            # end advances past the covered bound with NO new ingest: data
            # beyond the old fetch bound must still be sliced in
            host = _run(store, q, None, T0 - 240_000, T0 + STEP)
            dev = _run(store, q, engine, T0 - 240_000, T0 + STEP)
            _check(host, dev)
            assert rts[0].appends == 1
        finally:
            store.close()

    def test_late_data_forces_rebuild(self, tmp_path):
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        store = _mk_store(tmp_path)
        try:
            engine = TPUEngine(min_series=4)
            q = QUERIES[0]
            _run(store, q, engine, T0 - 300_000, T0)
            rts = _rolling_tiles(engine)
            # backfill INSIDE the covered range: the append watermark must
            # refuse the incremental path
            lab = {"__name__": "rt", "instance": "h0", "job": "j0"}
            store.add_rows([(lab, T0 - 450_000 + 7, 123.0)])
            store.force_flush()
            host = _run(store, q, None, T0 - 240_000, T0 + STEP)
            dev = _run(store, q, engine, T0 - 240_000, T0 + STEP)
            _check(host, dev)
            assert rts[0].appends == 0, "late data must not append"
        finally:
            store.close()

    def test_new_series_forces_rebuild(self, tmp_path):
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        store = _mk_store(tmp_path)
        try:
            engine = TPUEngine(min_series=4)
            q = QUERIES[0]
            _run(store, q, engine, T0 - 300_000, T0)
            rts = _rolling_tiles(engine)
            lab = {"__name__": "rt", "instance": "hNEW", "job": "jNEW"}
            ts = T0 + 10_000 + np.arange(4, dtype=np.int64) * 15_000
            store.add_rows([(lab, int(t), float(i))
                            for i, t in enumerate(ts)])
            store.force_flush()
            host = _run(store, q, None, T0 - 240_000, T0 + STEP)
            dev = _run(store, q, engine, T0 - 240_000, T0 + STEP)
            _check(host, dev)
            assert rts[0].appends == 0
        finally:
            store.close()

    def test_delete_forces_rebuild(self, tmp_path):
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        from victoriametrics_tpu.storage.tag_filters import TagFilter
        store = _mk_store(tmp_path)
        try:
            engine = TPUEngine(min_series=4)
            q = QUERIES[0]
            _run(store, q, engine, T0 - 300_000, T0)
            store.delete_series(
                [TagFilter(b"instance", b"h7", False, False)])
            host = _run(store, q, None, T0 - 240_000, T0 + STEP)
            dev = _run(store, q, engine, T0 - 240_000, T0 + STEP)
            _check(host, dev)
        finally:
            store.close()

    def test_rolling_on_mesh(self, tmp_path):
        import jax

        from victoriametrics_tpu.parallel.mesh import make_mesh
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = make_mesh(n_series=8, n_time=1, devices=devs[:8])
        store = _mk_store(tmp_path, n_series=81)  # pad path
        try:
            engine = TPUEngine(min_series=4, mesh=mesh)
            q = QUERIES[0]
            _check(_run(store, q, None, T0 - 300_000, T0),
                   _run(store, q, engine, T0 - 300_000, T0))
            rts = _rolling_tiles(engine)
            _ingest_newer(store, T0 + 10_000, n_series=81)
            host = _run(store, q, None, T0 - 240_000, T0 + STEP)
            dev = _run(store, q, engine, T0 - 240_000, T0 + STEP)
            _check(host, dev)
            assert rts and rts[0].appends == 1
        finally:
            store.close()

    def test_old_history_prev_sample_truncation(self, tmp_path):
        """A rolling tile keeps MORE history than a later query would fetch.
        Funcs seeded by the sample before the window (delta/increase/
        changes) must behave as if that history were truncated at the
        query's fetch bound — the kernel's min_ts gate."""
        from victoriametrics_tpu.storage.storage import Storage
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        s = Storage(str(tmp_path / "s"))
        rows = []
        for i in range(70):
            lab = {"__name__": "gap", "instance": f"h{i % 7}"}
            # one OLD sample, then a long silence, then in-window samples
            rows.append((lab, T0 - 550_000 + i, 100.0 + i))
            for k in range(12):
                rows.append((lab, T0 - 180_000 + k * 15_000 + i,
                             200.0 + k + i))
        s.add_rows(rows)
        s.force_flush()
        try:
            engine = TPUEngine(min_series=4)
            for q in ("sum by (instance)(delta(gap[4m]))",
                      "sum by (instance)(increase(gap[4m]))",
                      "sum by (instance)(changes(gap[4m]))"):
                # cold query: fetch_lo reaches the old sample -> in tile
                _check(_run(s, q, None, T0 - 300_000, T0),
                       _run(s, q, engine, T0 - 300_000, T0), q)
                # advanced query: host fetch_lo = start-240k-300k excludes
                # the old sample; the tile still holds it
                start2, end2 = T0 + 60_000, T0 + 120_000
                host = _run(s, q, None, start2, end2)
                dev = _run(s, q, engine, start2, end2)
                _check(host, dev, q + " (advanced)")
            rts = _rolling_tiles(engine)
            assert rts and all(rt.appends <= 1 for rt in rts)
        finally:
            s.close()

    def test_many_advances_until_capacity(self, tmp_path):
        """Keep advancing until headroom runs out: the rebuild must be
        seamless and every step must match the host."""
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        store = _mk_store(tmp_path, n_series=70)
        try:
            engine = TPUEngine(min_series=4)
            q = QUERIES[0]
            _run(store, q, engine, T0 - 300_000, T0)
            end = T0
            # append at the data FRONTIER (store seeds through T0+285s):
            # strictly-newest regular-cadence ingest, the production rolling
            # shape. Interleaving new batches BELOW existing samples would
            # create double-density intervals whose scrape-interval
            # estimate drift flips marginal prev gates — rollup-cache
            # reused columns legitimately keep compute-time estimates
            # (rollup_result_cache.go:283 contract).
            frontier = T0 + 285_000 + 15_000
            for k in range(12):
                _ingest_newer(store, frontier, n=8, n_series=70)
                frontier += 8 * 15_000
                end += STEP * 2
                host = _run(store, q, None, end - 300_000, end)
                dev = _run(store, q, engine, end - 300_000, end)
                _check(host, dev, f"advance {k}")
        finally:
            store.close()
