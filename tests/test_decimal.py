"""Decimal codec tests — semantics mirrored from the reference's
lib/decimal/decimal_test.go coverage: roundtrips, special values, scale
calibration, staleness markers."""

import numpy as np
import pytest

from victoriametrics_tpu.ops import decimal as dec


def roundtrip(vals):
    m, e = dec.float_to_decimal(np.asarray(vals, dtype=np.float64))
    return dec.decimal_to_float(m, e)


class TestFloatToDecimal:
    def test_empty(self):
        m, e = dec.float_to_decimal(np.array([], dtype=np.float64))
        assert m.size == 0

    def test_integers_exact(self):
        vals = np.array([0.0, 1, -1, 12345, -98765, 10, 100, 1e6, 123456789012345.0])
        out = roundtrip(vals)
        np.testing.assert_array_equal(out, vals)

    def test_common_exponent_strips_zeros(self):
        m, e = dec.float_to_decimal(np.array([100.0, 200.0, 300.0]))
        assert e == 2
        np.testing.assert_array_equal(m, [1, 2, 3])

    def test_decimal_fractions_exact(self):
        vals = np.array([0.1, 0.25, 1.5, -3.75, 123.456, 0.001, 9.99])
        out = roundtrip(vals)
        np.testing.assert_array_equal(out, vals)

    def test_mixed_scales(self):
        vals = np.array([1e-3, 1.0, 1e3])
        m, e = dec.float_to_decimal(vals)
        assert e == -3
        np.testing.assert_array_equal(m, [1, 1000, 1000000])

    def test_random_floats_narrow_spread_near_exact(self):
        # Values within ~2 decades keep full float64 precision.
        rng = np.random.default_rng(42)
        vals = rng.uniform(1.0, 100.0, 1000)
        out = roundtrip(vals)
        np.testing.assert_allclose(out, vals, rtol=1e-13)

    def test_random_floats_wide_spread(self):
        # A shared decimal exponent across ~8 decades costs digits on the
        # small end (same trade-off as the reference's CalibrateScale).
        rng = np.random.default_rng(42)
        vals = rng.standard_normal(1000) * np.exp(rng.uniform(-5, 5, 1000))
        out = roundtrip(vals)
        np.testing.assert_allclose(out, vals, rtol=1e-8)

    def test_huge_spread_is_lossy_but_close(self):
        vals = np.array([1e-300, 1e300])
        out = roundtrip(vals)
        # 1e300 must survive; 1e-300 may collapse given the shared exponent.
        assert out[1] == pytest.approx(1e300, rel=1e-12)

    def test_specials(self):
        vals = np.array([np.nan, np.inf, -np.inf, 1.0])
        out = roundtrip(vals)
        assert np.isnan(out[0])
        assert np.isposinf(out[1])
        assert np.isneginf(out[2])
        assert out[3] == 1.0

    def test_stale_nan_preserved_bit_exact(self):
        vals = np.array([dec.STALE_NAN, np.nan, 5.0])
        m, e = dec.float_to_decimal(vals)
        assert m[0] == dec.V_STALE_NAN
        assert m[1] == dec.V_NAN
        out = dec.decimal_to_float(m, e)
        assert dec.is_stale_nan(out[:1]).all()
        assert not dec.is_stale_nan(out[1:2]).any()  # plain NaN stays plain

    def test_negative_zero(self):
        out = roundtrip(np.array([-0.0, 0.0]))
        np.testing.assert_array_equal(out, [0.0, 0.0])

    def test_single_value(self):
        for v in (3.0, 0.02, -7e9, 6.62607015e-34):
            out = roundtrip(np.array([v]))
            assert out[0] == pytest.approx(v, rel=1e-13)


class TestCalibrateScale:
    def test_same_exp(self):
        a = np.array([1, 2], dtype=np.int64)
        b = np.array([3, 4], dtype=np.int64)
        a2, b2, e = dec.calibrate_scale(a, 0, b, 0)
        assert e == 0
        np.testing.assert_array_equal(a2, a)
        np.testing.assert_array_equal(b2, b)

    def test_scale_down_b(self):
        a = np.array([15, 25], dtype=np.int64)   # e=-1 -> 1.5, 2.5
        b = np.array([3, 4], dtype=np.int64)     # e=0  -> 3, 4
        a2, b2, e = dec.calibrate_scale(a, -1, b, 0)
        assert e == -1
        np.testing.assert_array_equal(a2, [15, 25])
        np.testing.assert_array_equal(b2, [30, 40])

    def test_specials_pass_through(self):
        a = np.array([dec.V_STALE_NAN, 10], dtype=np.int64)
        b = np.array([5], dtype=np.int64)
        a2, b2, e = dec.calibrate_scale(a, -2, b, 0)
        assert a2[0] == dec.V_STALE_NAN
        assert e == -2
        assert b2[0] == 500

    def test_values_preserved(self):
        rng = np.random.default_rng(7)
        av = np.round(rng.uniform(-100, 100, 50), 3)
        bv = np.round(rng.uniform(-1e6, 1e6, 50), 1)
        am, ae = dec.float_to_decimal(av)
        bm, be = dec.float_to_decimal(bv)
        a2, b2, e = dec.calibrate_scale(am, ae, bm, be)
        np.testing.assert_allclose(dec.decimal_to_float(a2, e), av, rtol=1e-10)
        np.testing.assert_allclose(dec.decimal_to_float(b2, e), bv, rtol=1e-10)


class TestReviewRegressions:
    def test_tiny_values_do_not_hit_sentinels(self):
        # 1e-300 must not overflow into V_NAN (pow10 overflow guard)
        m, e = dec.float_to_decimal(np.array([1e-300, 2e-308]))
        assert m[0] != dec.V_NAN and m[1] != dec.V_NAN
        out = dec.decimal_to_float(m, e)
        assert out[0] == pytest.approx(1e-300, rel=1e-8)

    def test_calibrate_all_zero_b_keeps_a(self):
        a = np.array([123456], dtype=np.int64)
        b = np.array([0, dec.V_STALE_NAN], dtype=np.int64)
        a2, b2, e = dec.calibrate_scale(a, -25, b, 0)
        assert e == -25
        np.testing.assert_array_equal(a2, a)
        assert b2[1] == dec.V_STALE_NAN

    def test_large_mantissa_upshift_exact(self):
        # int64 up-shift must stay exact above 2^53
        vals = np.array([0.1, 1900000000000001.0])
        out = roundtrip(vals)
        np.testing.assert_array_equal(out, vals)
