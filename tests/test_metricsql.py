"""Parser tests — corpus modeled on the metricsql package's parser_test.go
coverage: selectors, rollups, subqueries, aggregates, binary ops with
matching modifiers, WITH templates, durations, weird-but-legal inputs."""

import pytest

from victoriametrics_tpu.query.metricsql import (AggrFuncExpr, BinaryOpExpr,
                                                 DurationExpr, FuncExpr,
                                                 MetricExpr, NumberExpr,
                                                 ParseError, RollupExpr,
                                                 StringExpr, parse)


def test_plain_metric():
    e = parse("http_requests_total")
    assert isinstance(e, MetricExpr)
    assert e.metric_name == "http_requests_total"


def test_selector_with_filters():
    e = parse('m{job="api", instance!="h1", path=~"/v[12]", q!~"x.*"}')
    assert isinstance(e, MetricExpr)
    ops = [(f.label, f.op()) for f in e.label_filters]
    assert ops == [("__name__", "="), ("job", "="), ("instance", "!="),
                   ("path", "=~"), ("q", "!~")]


def test_nameless_selector():
    e = parse('{job="api"}')
    assert isinstance(e, MetricExpr)
    assert e.metric_name is None


def test_rollup_window():
    e = parse("rate(m[5m])")
    assert isinstance(e, FuncExpr) and e.name == "rate"
    r = e.args[0]
    assert isinstance(r, RollupExpr)
    assert r.window.ms == 300_000


def test_compound_duration():
    e = parse("m[1h30m]")
    assert e.window.ms == 5_400_000


def test_bare_number_window_is_seconds():
    e = parse("m[300]")
    assert e.window.ms == 300_000


def test_step_based_duration():
    e = parse("m[5i]")
    assert e.window.step_based and e.window.ms == 5
    assert e.window.value_ms(30_000) == 150_000


def test_offset_and_at():
    e = parse("m offset 1h @ 1700000000")
    assert isinstance(e, RollupExpr)
    assert e.offset.ms == 3_600_000
    assert isinstance(e.at, NumberExpr)


def test_negative_offset():
    e = parse("m offset -30m")
    assert e.offset.ms == -1_800_000


def test_subquery():
    e = parse("max_over_time(rate(m[5m])[1h:1m])")
    r = e.args[0]
    assert isinstance(r, RollupExpr)
    assert r.window.ms == 3_600_000 and r.step.ms == 60_000
    assert isinstance(r.expr, FuncExpr)


def test_subquery_inherit_step():
    r = parse("q[1h:]")
    assert r.inherit_step and r.step is None


def test_aggregate_by():
    e = parse("sum by (job, instance) (rate(m[5m]))")
    assert isinstance(e, AggrFuncExpr)
    assert e.name == "sum" and e.grouping == ["job", "instance"]
    assert not e.without


def test_aggregate_without_trailing():
    e = parse("sum(rate(m[5m])) without (pod)")
    assert e.without and e.grouping == ["pod"]


def test_aggregate_limit():
    e = parse("sum(m) by (job) limit 10")
    assert e.limit == 10 and e.grouping == ["job"]


def test_topk():
    e = parse("topk(5, m)")
    assert isinstance(e, AggrFuncExpr)
    assert isinstance(e.args[0], NumberExpr) and e.args[0].value == 5


def test_binary_precedence():
    e = parse("a + b * c")
    assert isinstance(e, BinaryOpExpr) and e.op == "+"
    assert isinstance(e.right, BinaryOpExpr) and e.right.op == "*"


def test_power_right_assoc():
    e = parse("a ^ b ^ c")
    assert e.op == "^"
    assert isinstance(e.right, BinaryOpExpr) and e.right.op == "^"


def test_comparison_bool():
    e = parse("a > bool 5")
    assert e.op == ">" and e.bool_modifier


def test_vector_matching():
    e = parse("a / on(job) group_left(extra) b")
    assert e.group_modifier.op == "on" and e.group_modifier.args == ["job"]
    assert e.join_modifier.op == "group_left"
    assert e.join_modifier.args == ["extra"]


def test_and_or_unless():
    e = parse("a and b or c unless d")
    assert e.op == "or"


def test_metricsql_default_if():
    e = parse("a default 0")
    assert e.op == "default"
    e = parse("a if b")
    assert e.op == "if"
    e = parse("a ifnot b")
    assert e.op == "ifnot"


def test_unary_minus():
    e = parse("-m")
    assert isinstance(e, BinaryOpExpr) and e.op == "*"
    assert e.left.value == -1.0


def test_number_formats():
    assert parse("0x1F").value == 31.0
    assert parse("1.5e3").value == 1500.0
    assert parse("2Ki").value == 2048.0
    assert parse("1M").value == 1e6
    assert parse("NaN").value != parse("NaN").value
    assert parse("Inf").value == float("inf")


def test_duration_as_scalar():
    e = parse("now() - 5m")
    assert isinstance(e.right, DurationExpr)


def test_keep_metric_names():
    e = parse("rate(m[5m]) keep_metric_names")
    assert e.keep_metric_names


def test_with_template_simple():
    e = parse('WITH (x = m{a="1"}) rate(x[5m])')
    r = e.args[0]
    assert isinstance(r.expr, MetricExpr)
    assert r.expr.label_filters[1].value == "1"


def test_with_template_function():
    e = parse("WITH (f(q) = sum(rate(q[5m]))) f(m)")
    assert isinstance(e, AggrFuncExpr) and e.name == "sum"
    inner = e.args[0].args[0]
    assert isinstance(inner.expr, MetricExpr)
    assert inner.expr.metric_name == "m"


def test_string_literal():
    e = parse('label_set(m, "foo", "bar")')
    assert isinstance(e.args[1], StringExpr) and e.args[1].value == "foo"


def test_parens_grouping():
    e = parse("(a + b) * c")
    assert e.op == "*"
    assert isinstance(e.left, BinaryOpExpr) and e.left.op == "+"


def test_recording_rule_colon_names():
    e = parse("job:request_rate:5m")
    assert e.metric_name == "job:request_rate:5m"


def test_canonical_string_roundtrip():
    for q in ["sum by (job) (rate(http_requests_total[5m]))",
              'm{a="1", b!~"x|y"} offset 1h',
              "max_over_time(rate(m[5m])[1h:1m])",
              "a / on (job) group_left () b",
              "histogram_quantile(0.99, sum by (le) (rate(b[5m])))"]:
        e = parse(q)
        e2 = parse(str(e))
        assert str(e) == str(e2)


@pytest.mark.parametrize("bad", [
    "", "   ", "sum(", "m{", 'm{a=}', "m[", "m[5m", "a +", "((a)",
    "m{a=\"1\"", "m offset", "1 +", "by (x) sum(m)",
])
def test_parse_errors(bad):
    with pytest.raises(ParseError):
        parse(bad)


def test_comments_ignored():
    e = parse("m # trailing comment")
    assert isinstance(e, MetricExpr)
