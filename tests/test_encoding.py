"""Codec tests — coverage modeled on the reference's
lib/encoding/encoding_test.go + int_test.go + nearest_delta*_test.go:
varint roundtrips, marshal-type selection, lossy precision bounds,
timestamp validation."""

import numpy as np
import pytest

from victoriametrics_tpu.ops import encoding as enc
from victoriametrics_tpu.ops import varint
from victoriametrics_tpu.ops.nearest_delta import (
    nearest_delta2_decode, nearest_delta2_encode, nearest_delta_decode,
    nearest_delta_encode)


class TestVarint:
    def test_roundtrip_simple(self):
        vals = np.array([0, 1, -1, 63, -64, 64, -65, 1 << 40, -(1 << 40)],
                        dtype=np.int64)
        data = varint.marshal_varint64s(vals)
        out = varint.unmarshal_varint64s(data, vals.size)
        np.testing.assert_array_equal(out, vals)

    def test_roundtrip_extremes(self):
        vals = np.array([(1 << 62), -(1 << 62), (1 << 63) - 1, -(1 << 63)],
                        dtype=np.int64)
        out = varint.unmarshal_varint64s(varint.marshal_varint64s(vals), 4)
        np.testing.assert_array_equal(out, vals)

    def test_roundtrip_random(self):
        rng = np.random.default_rng(3)
        for size in (1, 2, 100, 8192):
            vals = rng.integers(-(1 << 62), 1 << 62, size, dtype=np.int64)
            out = varint.unmarshal_varint64s(varint.marshal_varint64s(vals), size)
            np.testing.assert_array_equal(out, vals)

    def test_small_values_one_byte(self):
        vals = np.arange(-64, 64, dtype=np.int64)
        data = varint.marshal_varint64s(vals)
        assert len(data) == vals.size

    def test_empty(self):
        assert varint.marshal_varint64s(np.array([], dtype=np.int64)) == b""
        assert varint.unmarshal_varint64s(b"").size == 0

    def test_varuint_scalar(self):
        for x in (0, 1, 127, 128, 300, 1 << 32, (1 << 64) - 1):
            data = varint.marshal_varuint64(x)
            v, off = varint.unmarshal_varuint64(data)
            assert v == x and off == len(data)


class TestNearestDelta:
    def test_lossless_roundtrip(self):
        rng = np.random.default_rng(1)
        v = rng.integers(-(1 << 50), 1 << 50, 1000, dtype=np.int64)
        first, d = nearest_delta_encode(v, 64)
        np.testing.assert_array_equal(nearest_delta_decode(first, d), v)

    def test_lossy_bounded_error(self):
        rng = np.random.default_rng(2)
        v = np.cumsum(rng.integers(-1000, 1000, 500)).astype(np.int64) + 10**9
        for bits in (4, 8, 16, 32):
            first, d = nearest_delta_encode(v, bits)
            out = nearest_delta_decode(first, d)
            # error per step bounded by delta magnitude / 2^(bits-1); with
            # error feedback it never accumulates beyond one step's rounding.
            max_err = np.abs(np.diff(v)).max() / (1 << (bits - 1)) + 1
            assert np.abs(out - v).max() <= max_err

    def test_delta2_lossless_roundtrip(self):
        rng = np.random.default_rng(4)
        v = np.cumsum(np.cumsum(rng.integers(-5, 5, 300))).astype(np.int64)
        first, fd, d2 = nearest_delta2_encode(v, 64)
        np.testing.assert_array_equal(nearest_delta2_decode(first, fd, d2), v)

    def test_delta2_linear_is_zeros(self):
        v = np.arange(0, 10000, 15, dtype=np.int64)
        _, _, d2 = nearest_delta2_encode(v, 64)
        assert (d2 == 0).all()


class TestMarshalInt64Array:
    def roundtrip(self, v, bits=64):
        data, mt, first = enc.marshal_int64_array(v, bits)
        return enc.unmarshal_int64_array(data, mt, first, v.size), mt

    def test_const(self):
        v = np.full(100, 42, dtype=np.int64)
        out, mt = self.roundtrip(v)
        assert mt == enc.MarshalType.CONST
        np.testing.assert_array_equal(out, v)

    def test_delta_const(self):
        v = np.arange(1000, 9000, 15, dtype=np.int64)
        out, mt = self.roundtrip(v)
        assert mt == enc.MarshalType.DELTA_CONST
        np.testing.assert_array_equal(out, v)

    def test_counter_uses_delta2(self):
        rng = np.random.default_rng(5)
        v = np.cumsum(rng.integers(0, 100, 500)).astype(np.int64)
        out, mt = self.roundtrip(v)
        assert mt in (enc.MarshalType.NEAREST_DELTA2,
                      enc.MarshalType.ZSTD_NEAREST_DELTA2)
        np.testing.assert_array_equal(out, v)

    def test_gauge_uses_delta(self):
        rng = np.random.default_rng(6)
        v = rng.integers(-1000, 1000, 500).astype(np.int64)
        out, mt = self.roundtrip(v)
        assert mt in (enc.MarshalType.NEAREST_DELTA,
                      enc.MarshalType.ZSTD_NEAREST_DELTA)
        np.testing.assert_array_equal(out, v)

    def test_compressible_uses_zstd(self):
        # long, highly regular but not delta-const payload
        v = np.cumsum(np.tile([1, 2, 3, 4], 2048)).astype(np.int64)
        data, mt, first = enc.marshal_int64_array(v, 64)
        assert mt in (enc.MarshalType.ZSTD_NEAREST_DELTA2,
                      enc.MarshalType.ZSTD_NEAREST_DELTA)
        out = enc.unmarshal_int64_array(data, mt, first, v.size)
        np.testing.assert_array_equal(out, v)

    def test_tiny_blocks_not_compressed(self):
        v = np.array([1, 5, 2, 9, 3], dtype=np.int64)
        _, mt, _ = enc.marshal_int64_array(v, 64)
        assert mt not in (enc.MarshalType.ZSTD_NEAREST_DELTA,
                          enc.MarshalType.ZSTD_NEAREST_DELTA2)

    def test_single_value(self):
        v = np.array([-7], dtype=np.int64)
        out, mt = self.roundtrip(v)
        assert mt == enc.MarshalType.CONST
        np.testing.assert_array_equal(out, v)

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            enc.marshal_int64_array(np.array([], dtype=np.int64))


class TestTimestamps:
    def test_scrape_timestamps_compact(self):
        # 8k timestamps at fixed 15s interval -> DELTA_CONST, ~few bytes
        ts = np.arange(0, 8192 * 15000, 15000, dtype=np.int64) + 1700000000000
        data, mt, first = enc.marshal_timestamps(ts)
        assert mt == enc.MarshalType.DELTA_CONST
        assert len(data) < 8
        out = enc.unmarshal_timestamps(data, mt, first, ts.size)
        np.testing.assert_array_equal(out, ts)

    def test_jittered_timestamps(self):
        rng = np.random.default_rng(8)
        ts = (np.arange(4096, dtype=np.int64) * 15000 + 1700000000000
              + rng.integers(-50, 50, 4096))
        data, mt, first = enc.marshal_timestamps(ts)
        out = enc.unmarshal_timestamps(data, mt, first, ts.size)
        np.testing.assert_array_equal(out, ts)

    def test_validation_clamps(self):
        out = enc.ensure_non_decreasing_sequence(
            np.array([1, 5, 3, 7, 6], dtype=np.int64))
        np.testing.assert_array_equal(out, [1, 5, 5, 7, 7])


class TestVarintMalformed:
    def test_unterminated_trailing_varint_raises(self):
        with pytest.raises(ValueError):
            varint.unmarshal_varint64s(b"\x01\x81", 1)

    def test_all_continuation_raises(self):
        with pytest.raises(ValueError):
            varint.unmarshal_varint64s(b"\x80")

    def test_overlong_varint_raises(self):
        with pytest.raises(ValueError):
            varint.unmarshal_varint64s(b"\x81" * 10 + b"\x01", 1)


class TestSentinelLossyEncode:
    def test_delta2_lossy_with_sentinels_no_overflow(self):
        v = np.array([0, (1 << 63) - 1, -(1 << 63) + 5, 7], dtype=np.int64)
        first, fd, d2 = nearest_delta2_encode(v, 32)
        out = nearest_delta2_decode(first, fd, d2)
        assert out.dtype == np.int64  # wrapped, no crash

    def test_delta_lossy_with_sentinels_no_overflow(self):
        v = np.array([5, -(1 << 63) + 1, 5], dtype=np.int64)
        first, d = nearest_delta_encode(v, 16)
        nearest_delta_decode(first, d)


class TestNativeCodec:
    def test_native_available_and_equivalent(self):
        from victoriametrics_tpu import native
        if not native.available():
            pytest.skip("no compiler")
        rng = np.random.default_rng(9)
        for size in (1, 2, 5, 1000, 8192):
            v = rng.integers(-(1 << 55), 1 << 55, size, dtype=np.int64)
            data = native.varint_encode(v)
            assert data == varint.marshal_varint64s(v)  # format-identical
            np.testing.assert_array_equal(native.varint_decode(data, size), v)
        v = np.cumsum(rng.integers(0, 100, 5000)).astype(np.int64)
        payload, first, fd = native.delta2_encode(v)
        out = native.delta2_decode(payload, first, fd, v.size)
        np.testing.assert_array_equal(out, v)

    def test_native_blocks_interop_with_python_blocks(self):
        """Blocks encoded with native kernels decode via pure python & vice
        versa (same wire format)."""
        from victoriametrics_tpu.ops import encoding as enc_mod
        if not getattr(enc_mod, "_HAVE_NATIVE", False):
            pytest.skip("no native lib")
        rng = np.random.default_rng(10)
        counter = np.cumsum(rng.integers(0, 100, 3000)).astype(np.int64)
        gauge = rng.integers(-500, 500, 3000).astype(np.int64)
        for v in (counter, gauge):
            data, mt, first = enc_mod.marshal_int64_array(v, 64)
            # force python decode
            enc_mod._HAVE_NATIVE = False
            try:
                out_py = enc_mod.unmarshal_int64_array(data, mt, first, v.size)
            finally:
                enc_mod._HAVE_NATIVE = True
            out_nat = enc_mod.unmarshal_int64_array(data, mt, first, v.size)
            np.testing.assert_array_equal(out_py, v)
            np.testing.assert_array_equal(out_nat, v)

    def test_native_malformed_raises(self):
        from victoriametrics_tpu import native
        if not native.available():
            pytest.skip("no compiler")
        with pytest.raises(ValueError):
            native.varint_decode(b"\x81" * 12, 1)
        with pytest.raises(ValueError):
            native.delta2_decode(b"\x81", 0, 1, 5)


class TestNativePromParser:
    """native/parse.cpp vm_parse_prom vs the Python reference parser."""

    def _native(self, data: bytes, default_ts: int = 7):
        from victoriametrics_tpu import native
        rows = native.parse_prom_raw(data, default_ts)
        assert rows is not None, "native library must build in CI"
        return rows

    def test_differential_vs_python(self):
        from victoriametrics_tpu.ingest.parsers import (
            labels_from_series_key, parse_prometheus)
        text = "\n".join([
            'up 1 1700000000000',
            'http_total{job="a",code="200"} 42.5',
            'weird{a="x}y",b="c\\"d",e="sp ace"} -3e2 1700000000001',
            '# HELP up help',
            '   spaced{x="1"}   2.5   1700000000002  ',
            'nanv NaN',
            'infv +Inf 1700000000003',
        ])
        got = self._native(text.encode(), default_ts=7)
        want = [(r.labels, r.timestamp or 7, r.value)
                for r in parse_prometheus(text, 7)]
        assert len(got) == len(want)
        for (key, ts, val), (labels, wts, wval) in zip(got, want):
            assert labels_from_series_key(key) == labels
            assert ts == wts
            assert (val == wval) or (val != val and wval != wval)

    def test_junk_lines_skipped(self):
        rows = self._native(
            b'# c\n\nbad{unterminated 1\nnoval{x="1"}\nok 5\nnotnum x\n')
        assert [(k, v) for k, _, v in rows] == [(b"ok", 5.0)]

    def test_storage_raw_key_roundtrip(self, tmp_path):
        import numpy as np

        from victoriametrics_tpu.storage.storage import Storage
        st = Storage(str(tmp_path / "s"))
        try:
            rows = self._native(
                b'm1{a="1"} 10 1700000000000\n'
                b'm1{a="1"} 11 1700000015000\n'
                b'm1{a="2"} 20 1700000000000\n')
            assert st.add_rows(rows) == 3
            st.force_flush()
            found = st.search_series(
                [], 1699999000000, 1700001000000)
            assert len(found) == 2
            vals = sorted(float(sd.values[0]) for sd in found)
            assert vals == [10.0, 20.0]
        finally:
            st.close()

    def test_malformed_key_skipped_not_fatal(self, tmp_path):
        from victoriametrics_tpu.storage.storage import Storage
        st = Storage(str(tmp_path / "s2"))
        try:
            rows = self._native(b'ok 1 1700000000000\n'
                                b'm{a} 1 1700000000000\n'
                                b'ok2 2 1700000000000\n')
            assert len(rows) == 3  # native accepts the blob as a key
            assert st.add_rows(rows) == 2  # malformed row dropped mid-batch
        finally:
            st.close()

    def test_zero_and_dup_label_parity(self):
        from victoriametrics_tpu.ingest.parsers import labels_from_series_key
        rows = self._native(b'm 1 0\n', default_ts=777)
        assert rows[0][1] == 777  # explicit 0 ts = absent, like Python path
        assert labels_from_series_key(b'm{a="1",a="2"}') == [
            ("__name__", "m"), ("a", "2")]  # dup labels collapse last-wins
