"""Full multi-PROCESS cluster apptest (reference apptest/tests/
vminsert_vmstorage_vmselect paths): 2 vmstorage OS processes with TCP RPC,
one vminsert and one vmselect process, driven over HTTP — ingest shards
across nodes, queries scatter-gather, a killed node yields partial results.
"""

import json
import time
import urllib.request

import pytest

from tests.apptest_helpers import AppProc, Client, free_ports

T0 = 1_753_700_000_000


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("cluster")
    ports = free_ports(8)
    (s1h, s1i, s1s, s2h, s2i, s2s, ih, sh) = ports
    procs = []
    try:
        st1 = AppProc("vmstorage", [
            f"-storageDataPath={d}/s1", f"-httpListenAddr=127.0.0.1:{s1h}",
            f"-vminsertAddr=127.0.0.1:{s1i}",
            f"-vmselectAddr=127.0.0.1:{s1s}"], s1h, "vmstorage-1")
        procs.append(st1)
        st2 = AppProc("vmstorage", [
            f"-storageDataPath={d}/s2", f"-httpListenAddr=127.0.0.1:{s2h}",
            f"-vminsertAddr=127.0.0.1:{s2i}",
            f"-vmselectAddr=127.0.0.1:{s2s}"], s2h, "vmstorage-2")
        procs.append(st2)
        nodes = [f"-storageNode=127.0.0.1:{s1i}:{s1s}",
                 f"-storageNode=127.0.0.1:{s2i}:{s2s}"]
        vi = AppProc("vminsert",
                     nodes + [f"-httpListenAddr=127.0.0.1:{ih}"],
                     ih, "vminsert")
        procs.append(vi)
        vs = AppProc("vmselect",
                     nodes + [f"-httpListenAddr=127.0.0.1:{sh}"],
                     sh, "vmselect")
        procs.append(vs)
        yield {"st1": st1, "st2": st2, "vi": vi, "vs": vs}
    finally:
        for p in procs:
            p.stop(kill=True)


def test_cluster_end_to_end(cluster):
    vi = Client(cluster["vi"].port)
    vs = Client(cluster["vs"].port)
    lines = []
    for i in range(200):
        for k in range(3):
            lines.append(f'clm_metric{{series="{i}"}} {i + k} '
                         f'{T0 + k * 15000}')
    code, _ = vi.post("/insert/0/prometheus/api/v1/import/prometheus",
                      "\n".join(lines).encode())
    assert code == 204
    # flush both storage nodes to make rows searchable
    for key in ("st1", "st2"):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{cluster[key].port}/internal/force_flush",
                timeout=10):
            pass
    code, body = vs.get("/select/0/prometheus/api/v1/query",
                        query="sum(clm_metric)",
                        time=str((T0 + 30000) // 1000))
    assert code == 200
    res = json.loads(body)
    assert res["status"] == "success"
    total = float(res["data"]["result"][0]["value"][1])
    assert total == sum(i + 2 for i in range(200))
    # sharding: both nodes must hold some of the 200 series
    counts = []
    for key in ("st1", "st2"):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{cluster[key].port}/metrics",
                timeout=10) as r:
            text = r.read().decode()
        rows = [ln for ln in text.splitlines()
                if ln.startswith("vm_rows_added_to_storage_total")]
        counts.append(float(rows[0].split()[-1]) if rows else 0.0)
    assert all(c > 0 for c in counts), counts


def test_partial_results_after_node_kill(cluster):
    vs = Client(cluster["vs"].port)
    cluster["st2"].stop(kill=True)
    time.sleep(0.5)
    code, body = vs.get("/select/0/prometheus/api/v1/query",
                        query="count(clm_metric)",
                        time=str((T0 + 30000) // 1000))
    assert code == 200
    res = json.loads(body)
    assert res["status"] == "success"
    assert res.get("isPartial") is True
    # the surviving node still answers with its shard
    n = float(res["data"]["result"][0]["value"][1])
    assert 0 < n < 200
