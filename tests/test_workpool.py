"""Shared fetch/decode work pool (utils/workpool): ordering, nesting,
inline modes, the search-concurrency gate, the vectorized decimal
fallback, and — the acceptance property — bit-identical parallel vs
sequential fetch results on multi-partition, multi-part stores."""

import os
import threading
import time
from functools import partial

import numpy as np
import pytest

from victoriametrics_tpu.utils import metrics as metricslib
from victoriametrics_tpu.utils import workpool
from victoriametrics_tpu.utils.workpool import (SearchGate, SearchLimitError,
                                                WorkPool)

try:
    from victoriametrics_tpu.storage.storage import Storage
    from victoriametrics_tpu.storage.tag_filters import filters_from_dict
    _HAVE_STORAGE = True
except ImportError:  # optional native deps missing
    _HAVE_STORAGE = False

needs_storage = pytest.mark.skipif(not _HAVE_STORAGE,
                                   reason="storage deps unavailable")

T0 = 1_753_700_000_000  # 2025-07-28 (a few days before the month edge)


# -- pool semantics ----------------------------------------------------------

class TestWorkPool:
    def test_run_preserves_submit_order(self):
        pool = WorkPool(workers=4)
        try:
            def job(i):
                time.sleep(0.001 * (7 - i % 7))  # finish out of order
                return i * i
            assert pool.run([partial(job, i) for i in range(40)]) == \
                [i * i for i in range(40)]
        finally:
            pool.shutdown()

    def test_run_actually_uses_worker_threads(self):
        pool = WorkPool(workers=3)
        try:
            names = set()

            def job():
                names.add(threading.current_thread().name)
                time.sleep(0.02)
            pool.run([job for _ in range(6)])
            assert any(n.startswith("vm-workpool-") for n in names)
        finally:
            pool.shutdown()

    def test_exception_propagates_after_batch_drains(self):
        pool = WorkPool(workers=2)
        try:
            ran = []

            def ok(i):
                ran.append(i)

            def boom():
                raise ValueError("task failed")

            with pytest.raises(ValueError, match="task failed"):
                pool.run([partial(ok, 0), boom, partial(ok, 1),
                          partial(ok, 2)])
            # every sibling task still ran (no cancellation surprises)
            assert sorted(ran) == [0, 1, 2]
        finally:
            pool.shutdown()

    def test_nested_run_does_not_deadlock(self):
        """A task fanning out on the same pool (cluster fanout -> local
        table collect) must complete even when tasks outnumber workers:
        waiters help execute queued work."""
        pool = WorkPool(workers=2)
        try:
            def inner(i):
                return i + 1

            def outer(k):
                return pool.run([partial(inner, 10 * k + j)
                                 for j in range(4)])

            got = pool.run([partial(outer, k) for k in range(6)])
            assert got == [[10 * k + j + 1 for j in range(4)]
                           for k in range(6)]
        finally:
            pool.shutdown()

    def test_workers_1_runs_inline_without_threads(self, monkeypatch):
        monkeypatch.setenv("VM_SEARCH_WORKERS", "1")
        pool = WorkPool()
        tid = threading.get_ident()
        out = pool.run([lambda: threading.get_ident() for _ in range(5)])
        assert out == [tid] * 5
        assert pool._threads == []          # never lazily started
        assert not pool.parallel_enabled()

    def test_submit_pipelines_and_inline_mode(self, monkeypatch):
        pool = WorkPool(workers=2)
        try:
            fut = pool.submit(lambda: 42)
            assert fut.result() == 42
        finally:
            pool.shutdown()
        monkeypatch.setenv("VM_SEARCH_WORKERS", "1")
        inline = WorkPool()
        assert inline.submit(lambda: 7).result() == 7
        assert inline._threads == []

    def test_submit_error_reraises(self):
        pool = WorkPool(workers=2)
        try:
            fut = pool.submit(partial(int, "nope"))
            with pytest.raises(ValueError):
                fut.result()
        finally:
            pool.shutdown()

    def test_env_resize_default_is_cpu_count(self, monkeypatch):
        monkeypatch.delenv("VM_SEARCH_WORKERS", raising=False)
        assert workpool.configured_workers() == (os.cpu_count() or 1)
        monkeypatch.setenv("VM_SEARCH_WORKERS", "7")
        assert workpool.configured_workers() == 7
        monkeypatch.setenv("VM_SEARCH_WORKERS", "garbage")
        assert workpool.configured_workers() == (os.cpu_count() or 1)

    def test_lowered_worker_count_retires_excess_threads(self, monkeypatch):
        monkeypatch.setenv("VM_SEARCH_WORKERS", "4")
        pool = WorkPool()
        try:
            pool.run([(lambda: time.sleep(0.01)) for _ in range(8)])
            assert len(pool._threads) == 4
            monkeypatch.setenv("VM_SEARCH_WORKERS", "2")
            pool.run([(lambda: time.sleep(0.01)) for _ in range(8)])
            assert len(pool._threads) <= 2
        finally:
            pool.shutdown()

    def test_decompress_fallback_is_size_bounded(self):
        """The zlib fallback must cap allocation like the zstd path's
        max_output_size (a small frame must not balloon into RAM)."""
        import zlib

        from victoriametrics_tpu.ops import compress
        bomb = zlib.compress(b"\0" * (8 << 20))
        with pytest.raises(ValueError, match="exceeds"):
            compress.decompress(bomb, max_size=1 << 20)
        ok = zlib.compress(b"payload" * 100)
        assert compress.decompress(ok, max_size=1 << 20) == b"payload" * 100

    def test_tasks_total_metric_counts(self):
        c = metricslib.REGISTRY.counter("vm_workpool_tasks_total")
        before = c.get()
        workpool.POOL.run([lambda: None, lambda: None, lambda: None])
        assert c.get() >= before + 3


# -- search concurrency gate -------------------------------------------------

class TestSearchGate:
    def test_admits_up_to_limit_then_queues(self):
        gate = SearchGate(limit=2, max_queue_ms=5000)
        release = threading.Event()
        inside = []

        def hold():
            with gate:
                inside.append(1)
                release.wait(10)

        ts = [threading.Thread(target=hold, daemon=True) for _ in range(2)]
        for t in ts:
            t.start()
        for _ in range(100):
            if len(inside) == 2:
                break
            time.sleep(0.01)
        assert len(inside) == 2
        queued = metricslib.REGISTRY.counter(
            "vm_search_requests_queued_total")
        q_before = queued.get()
        t3 = threading.Thread(target=hold, daemon=True)
        t3.start()
        for _ in range(100):
            if queued.get() > q_before:
                break
            time.sleep(0.01)
        assert queued.get() == q_before + 1   # third caller had to queue
        release.set()
        t3.join(10)
        for t in ts:
            t.join(10)
        assert len(inside) == 3               # ... and then got admitted

    def test_rejects_after_queue_timeout_with_metric(self):
        gate = SearchGate(limit=1, max_queue_ms=50)
        rejected = metricslib.REGISTRY.counter(
            "vm_search_requests_rejected_total")
        r_before = rejected.get()
        release = threading.Event()

        def hold():
            with gate:
                release.wait(10)

        t = threading.Thread(target=hold, daemon=True)
        t.start()
        for _ in range(100):
            if gate._current.get() == 1:
                break
            time.sleep(0.01)
        with pytest.raises(SearchLimitError, match="concurrent searches"):
            with gate:
                pass
        assert rejected.get() == r_before + 1
        release.set()
        t.join(10)

    def test_current_gauge_tracks(self):
        gate = SearchGate(limit=3, max_queue_ms=1000)
        cur = gate._current
        base = cur.get()
        with gate:
            assert cur.get() == base + 1
        assert cur.get() == base

    def test_metrics_surface_in_exposition(self):
        txt = metricslib.REGISTRY.write_prometheus()
        for name in ("vm_search_concurrent_limit",
                     "vm_search_concurrent_current",
                     "vm_search_requests_queued_total",
                     "vm_search_requests_rejected_total",
                     "vm_workpool_tasks_total", "vm_workpool_workers",
                     "vm_workpool_queue_depth"):
            assert name in txt, name


# -- vectorized decimal fallback ---------------------------------------------

class TestDecimalBlocksFallback:
    def _reference(self, mants, goff, scales):
        from victoriametrics_tpu.ops import decimal as dec
        out = np.empty(mants.size, np.float64)
        for k in range(scales.size):
            a, b = int(goff[k]), int(goff[k + 1])
            out[a:b] = dec.decimal_to_float(mants[a:b], int(scales[k]))
        return out

    @pytest.mark.parametrize("seed,k", [(0, 1), (1, 7), (2, 64), (3, 300)])
    def test_matches_per_block_reference(self, seed, k):
        from victoriametrics_tpu.ops import decimal as dec
        rng = np.random.default_rng(seed)
        cnts = rng.integers(0, 50, k)
        goff = np.concatenate([[0], np.cumsum(cnts)]).astype(np.int64)
        n = int(goff[-1])
        mants = rng.integers(-10**12, 10**12, n)
        # sprinkle specials
        for v in (dec.V_STALE_NAN, dec.V_NAN, dec.V_INF_POS, dec.V_INF_NEG):
            idx = rng.integers(0, n, max(n // 17, 1))
            mants[idx] = v
        scales = rng.integers(-6, 4, k)
        want = self._reference(mants, goff, scales)
        out = np.empty(n, np.float64)
        dec.decimal_to_float_blocks_py(mants, goff, scales, out)
        np.testing.assert_array_equal(
            out.view(np.int64), want.view(np.int64))  # bit-exact, NaN-safe

    def test_pool_split_is_bit_identical(self, monkeypatch):
        from victoriametrics_tpu.ops import decimal as dec
        monkeypatch.setattr(dec, "_BLOCKS_SPLIT_MIN", 64)
        rng = np.random.default_rng(9)
        k = 40
        cnts = rng.integers(1, 64, k)
        goff = np.concatenate([[0], np.cumsum(cnts)]).astype(np.int64)
        n = int(goff[-1])
        mants = rng.integers(-10**9, 10**9, n)
        scales = rng.integers(-3, 3, k)
        want = self._reference(mants, goff, scales)
        pool = WorkPool(workers=3)
        try:
            out = np.empty(n, np.float64)
            dec.decimal_to_float_blocks_py(mants, goff, scales, out,
                                           pool=pool)
            np.testing.assert_array_equal(out.view(np.int64),
                                          want.view(np.int64))
        finally:
            pool.shutdown()

    @needs_storage
    def test_search_columns_no_native_fallback(self, tmp_path, monkeypatch):
        """The fallback decode path (native unavailable) must return the
        same result as the native path — exercised through the full
        search_columns stack."""
        s = Storage(str(tmp_path / "s"))
        # distinct exponents per series: 0.5 vs 3.0 vs 1e-3 step values
        rows = []
        for i, scale in enumerate((0.5, 3.0, 0.001, 12345.0)):
            rows += [({"__name__": "fb", "i": str(i)},
                      T0 + j * 15_000, (j + 1) * scale) for j in range(40)]
        s.add_rows(rows)
        s.force_flush()
        flt = filters_from_dict({"__name__": "fb"})
        native_cols = s.search_columns(flt, T0 - 1, T0 + 10**7)
        from victoriametrics_tpu import native as native_mod
        monkeypatch.setattr(native_mod, "available", lambda: False)
        fb_cols = s.search_columns(flt, T0 - 1, T0 + 10**7)
        assert native_cols.ts.tobytes() == fb_cols.ts.tobytes()
        assert native_cols.vals.tobytes() == fb_cols.vals.tobytes()
        np.testing.assert_array_equal(native_cols.counts, fb_cols.counts)
        assert native_cols.raw_names == fb_cols.raw_names
        s.close()


# -- parallel vs sequential fetch equivalence --------------------------------

def _assert_cols_identical(a, b):
    assert a.n_series == b.n_series
    np.testing.assert_array_equal(a.metric_ids, b.metric_ids)
    np.testing.assert_array_equal(a.counts, b.counts)
    assert a.ts.tobytes() == b.ts.tobytes()
    assert a.vals.tobytes() == b.vals.tobytes()
    assert a.raw_names == b.raw_names
    if a.stale_rows is None or b.stale_rows is None:
        assert a.stale_rows is None and b.stale_rows is None
    else:
        np.testing.assert_array_equal(a.stale_rows, b.stale_rows)


@needs_storage
class TestParallelSequentialEquivalence:
    def _build_multipart(self, path, coalescing: bool):
        """Two monthly partitions; several file parts each; plus pending
        in-memory rows.  With coalescing=True each series spans many
        span-capped blocks per part (the coalesce branch in
        search_columns runs); with False every series is a single tiny
        block per part."""
        s = Storage(str(path))
        n_series = 12
        per_flush = 60 if not coalescing else 700  # 700*15s ≈ 2.9h: >2 span
        #                                            blocks after the merge
        for part_i in range(3):
            rows = []
            for i in range(n_series):
                base = T0 + part_i * per_flush * 15_000
                rows += [({"__name__": "eq", "i": str(i)},
                          base + j * 15_000 + i, float((i + 1) * (j + 1)))
                         for j in range(per_flush)]
            s.add_rows(rows)
            s.force_flush()
        if coalescing:
            s.force_merge()  # one part, many adjacent same-series blocks
        # second month partition + unflushed pending rows
        t1 = T0 + 10 * 86_400_000  # crosses into 2025-08
        s.add_rows([({"__name__": "eq", "i": str(i)}, t1 + j * 15_000,
                     float(i + j)) for i in range(n_series)
                    for j in range(30)])
        s.force_flush()
        s.add_rows([({"__name__": "eq", "i": str(i)}, t1 + 10**6 + i, 1.0)
                    for i in range(n_series)])  # stays pending/in-memory
        return s

    @pytest.mark.parametrize("coalescing", [False, True])
    def test_bitwise_equal_and_faster_path_used(self, tmp_path, monkeypatch,
                                                coalescing):
        s = self._build_multipart(tmp_path / f"s{coalescing}", coalescing)
        flt = filters_from_dict({"__name__": "eq"})
        lo, hi = T0 - 1, T0 + 20 * 86_400_000
        monkeypatch.setenv("VM_SEARCH_WORKERS", "4")
        tasks = metricslib.REGISTRY.counter("vm_workpool_tasks_total")
        before = tasks.get()
        par = s.search_columns(flt, lo, hi)
        assert tasks.get() > before, "pool was not used"
        monkeypatch.setenv("VM_SEARCH_WORKERS", "1")
        seq = s.search_columns(flt, lo, hi)
        _assert_cols_identical(par, seq)
        assert par.n_series == 12 and par.n_samples > 0
        s.close()

    def test_chunked_prefetch_equivalence(self, tmp_path, monkeypatch):
        # >64 series (the per-chunk floor) so the tiny sample budget
        # splits the fetch into several chunks and the prefetch pipeline
        # actually runs
        s = Storage(str(tmp_path / "sc"))
        for flush in range(2):
            s.add_rows([({"__name__": "eq", "i": str(i)},
                         T0 + (flush * 10 + j) * 15_000, float(i + j))
                        for i in range(150) for j in range(10)])
            s.force_flush()
        flt = filters_from_dict({"__name__": "eq"})
        lo, hi = T0 - 1, T0 + 3_600_000
        monkeypatch.setenv("VM_SEARCH_WORKERS", "4")
        par_chunks = list(s.search_columns_chunked(
            flt, lo, hi, max_chunk_samples=400))
        monkeypatch.setenv("VM_SEARCH_WORKERS", "1")
        seq_chunks = list(s.search_columns_chunked(
            flt, lo, hi, max_chunk_samples=400))
        assert len(par_chunks) == len(seq_chunks) > 1
        for a, b in zip(par_chunks, seq_chunks):
            _assert_cols_identical(a, b)
        s.close()

    def test_chunked_early_close_drains_prefetch(self, tmp_path,
                                                 monkeypatch):
        s = self._build_multipart(tmp_path / "se", False)
        flt = filters_from_dict({"__name__": "eq"})
        monkeypatch.setenv("VM_SEARCH_WORKERS", "4")
        gen = s.search_columns_chunked(flt, T0 - 1,
                                       T0 + 20 * 86_400_000,
                                       max_chunk_samples=400)
        next(gen)
        gen.close()  # must not leave a background fetch racing close()
        s.close()
