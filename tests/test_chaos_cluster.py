"""Chaos scenario harness over the subprocess cluster apptests
(ROADMAP item 3, the robustness counterpart of the perf substrate):
real OS processes, real TCP, real faults — kill/restart a vmstorage
mid-query, slow-node injection through devtools/faultinject, RF=2
failover serving identical results, an ingest storm racing force_merge,
per-tenant QoS isolation under a saturating tenant, and deadline
propagation (a stalled node costs one query deadline, not a per-hop
timeout).

Every scenario asserts BOTH liveness (partial/rerouted results within
the deadline, bounded latency, no wedged requests) and the correctness
invariants the race harness checks single-node (exact counts/sums and
result equality across failover).

All tests are ``slow``-marked: tier-1 time is unaffected.  Run them via
``tools/chaos.sh`` (or ``pytest -m slow tests/test_chaos_cluster.py``).
"""

import json
import threading
import time
import urllib.parse
import urllib.request

import pytest

from tests.apptest_helpers import AppProc, Client, free_ports

pytestmark = pytest.mark.slow

T0 = 1_753_700_000_000


def _metric(port: int, name: str) -> float:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    total = 0.0
    hit = False
    for ln in text.splitlines():
        if ln.startswith(name + " ") or ln.startswith(name + "{"):
            total += float(ln.split()[-1])
            hit = True
    return total if hit else 0.0


def _flush(port: int):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/internal/force_flush", timeout=10):
        pass


def _set_faults(port: int, spec: str):
    q = urllib.parse.urlencode({"set": spec}) if spec else "clear=1"
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/internal/faults?{q}", timeout=10) as r:
        assert r.status == 200


def _pXX(samples, frac=0.99):
    xs = sorted(samples)
    return xs[min(int(frac * len(xs)), len(xs) - 1)]


def _storage_flags(d, name, hh, ii, ss):
    return [f"-storageDataPath={d}/{name}",
            f"-httpListenAddr=127.0.0.1:{hh}",
            f"-vminsertAddr=127.0.0.1:{ii}",
            f"-vmselectAddr=127.0.0.1:{ss}"]


def _spawn_cluster(d, ports, rf=1, select_extra=(), insert_extra=(),
                   env=None):
    (s1h, s1i, s1s, s2h, s2i, s2s, ih, sh) = ports
    procs = {}
    procs["st1"] = AppProc("vmstorage",
                           _storage_flags(d, "s1", s1h, s1i, s1s), s1h,
                           "vmstorage-1", env=env)
    procs["st2"] = AppProc("vmstorage",
                           _storage_flags(d, "s2", s2h, s2i, s2s), s2h,
                           "vmstorage-2", env=env)
    nodes = [f"-storageNode=127.0.0.1:{s1i}:{s1s}",
             f"-storageNode=127.0.0.1:{s2i}:{s2s}"]
    procs["vi"] = AppProc(
        "vminsert",
        nodes + [f"-httpListenAddr=127.0.0.1:{ih}",
                 f"-replicationFactor={rf}", *insert_extra],
        ih, "vminsert", env=env)
    procs["vs"] = AppProc(
        "vmselect",
        nodes + [f"-httpListenAddr=127.0.0.1:{sh}",
                 f"-replicationFactor={rf}", *select_extra],
        sh, "vmselect", env=env)
    return procs


def _ingest(vi: Client, name: str, n_series: int, n_samples: int = 3,
            tenant: str = "0"):
    lines = [f'{name}{{series="{i}"}} {i + k} {T0 + k * 15000}'
             for i in range(n_series) for k in range(n_samples)]
    code, body = vi.post(
        f"/insert/{tenant}/prometheus/api/v1/import/prometheus",
        "\n".join(lines).encode())
    assert code == 204, body
    return lines


def _query(vs: Client, q: str, t_s: float, tenant: str = "0"):
    return vs.get(f"/select/{tenant}/prometheus/api/v1/query",
                  query=q, time=str(t_s))


# ---------------------------------------------------------------------------
# scenario 1: kill/restart a vmstorage mid-query
# ---------------------------------------------------------------------------

@pytest.fixture()
def cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("chaos")
    ports = free_ports(8)
    procs = _spawn_cluster(d, ports)
    try:
        yield {"procs": procs, "ports": ports, "dir": d}
    finally:
        for p in procs.values():
            p.stop(kill=True)


def test_kill_restart_vmstorage_mid_query(cluster):
    """Liveness through a node death and rebirth: a continuous query
    stream never wedges or errors while st2 is killed mid-flight (some
    responses go partial), and after a restart the cluster serves the
    pre-kill complete result again."""
    procs, ports, d = (cluster["procs"], cluster["ports"], cluster["dir"])
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)
    _ingest(vi, "ckm", 200)
    for key in ("st1", "st2"):
        _flush(procs[key].port)
    t_s = (T0 + 30000) // 1000
    code, body = _query(vs, "count(ckm)", t_s)
    res = json.loads(body)
    assert code == 200 and res["status"] == "success"
    full = float(res["data"]["result"][0]["value"][1])
    assert full == 200.0

    results = []
    stop = threading.Event()

    def query_loop():
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                code, body = _query(vs, "count(ckm)", t_s)
                res = json.loads(body)
                results.append((code, res.get("isPartial"),
                                time.perf_counter() - t0, None))
            except Exception as e:  # noqa: BLE001 — recorded, asserted below
                results.append((0, None, time.perf_counter() - t0, e))
            time.sleep(0.05)

    threads = [threading.Thread(target=query_loop) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    procs["st2"].stop(kill=True)      # the kill, mid query-stream
    time.sleep(2.0)
    # rebirth on the SAME ports and data path
    (s1h, s1i, s1s, s2h, s2i, s2s, ih, sh) = ports
    procs["st2"] = AppProc("vmstorage",
                           _storage_flags(d, "s2", s2h, s2i, s2s), s2h,
                           "vmstorage-2-reborn")
    time.sleep(2.5)                   # node-down cooldown + reconnect
    stop.set()
    for t in threads:
        t.join(timeout=30)

    # liveness: every query completed, quickly, with an HTTP answer
    errs = [e for *_, e in results if e is not None]
    assert not errs, f"queries raised during chaos: {errs[:3]}"
    assert all(code == 200 for code, *_ in results), \
        [c for c, *_ in results if c != 200][:5]
    worst = max(dur for _, _, dur, _ in results)
    assert worst < 12.0, f"a query took {worst:.1f}s during the kill"
    # the kill was actually observed (partial responses happened)
    assert any(p for _, p, _, _ in results), "no partial results seen"
    # recovery: the reborn node serves its shard again, result complete
    deadline = time.time() + 20
    while time.time() < deadline:
        code, body = _query(vs, "count(ckm)", t_s)
        res = json.loads(body)
        if code == 200 and not res.get("isPartial") and \
                res["data"]["result"] and \
                float(res["data"]["result"][0]["value"][1]) == full:
            break
        time.sleep(0.5)
    else:
        pytest.fail(f"cluster never recovered the complete result "
                    f"({body!r})")


# ---------------------------------------------------------------------------
# scenario 2: slow node — deadline propagation, not per-hop timeouts
# ---------------------------------------------------------------------------

@pytest.fixture()
def deadline_cluster(tmp_path_factory):
    """rpc timeout 10s (deliberately long) + 2s query deadline: only
    deadline propagation can make a stalled node cheap."""
    d = tmp_path_factory.mktemp("chaos_dl")
    ports = free_ports(8)
    procs = _spawn_cluster(
        d, ports,
        select_extra=["-rpc.timeout=10.0", "-search.maxQueryDuration=2s"],
        env={"VM_FAULT_INJECT": "1"})  # opt into the live faults toggle
    try:
        yield procs
    finally:
        for p in procs.values():
            p.stop(kill=True)


def test_slow_node_costs_one_deadline(deadline_cluster):
    """The acceptance property: with a stalled vmstorage (fault-injected
    stall at the RPC seam — TCP-alive, never answers) and a 10s RPC
    default, the query comes back PARTIAL in ~the 2s query deadline.
    vm_rpc_deadline_exceeded_total goes loud on the vmselect."""
    procs = deadline_cluster
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)
    _ingest(vi, "slm", 120)
    for key in ("st1", "st2"):
        _flush(procs[key].port)
    t_s = (T0 + 30000) // 1000
    code, body = _query(vs, "count(slm)", t_s)
    assert code == 200
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) == 120.0

    _set_faults(procs["st2"].port, "rpc:searchColumns_v1=stall;"
                                   "rpc:search_v1=stall")
    try:
        t0 = time.perf_counter()
        code, body = _query(vs, "count(slm)", t_s)
        took = time.perf_counter() - t0
        res = json.loads(body)
        assert code == 200, body
        assert res.get("isPartial") is True
        n = float(res["data"]["result"][0]["value"][1])
        assert 0 < n < 120
        # one deadline (2s) + slack, NOT the 10s per-hop rpc timeout
        assert took < 7.0, f"stalled node cost {took:.1f}s"
        assert _metric(procs["vs"].port,
                       "vm_rpc_deadline_exceeded_total") >= 1
        # injected faults are observable on the storage node
        assert _metric(procs["st2"].port,
                       "vm_fault_injections_total") >= 1
    finally:
        _set_faults(procs["st2"].port, "")


def test_storage_side_deadline_abort(deadline_cluster):
    """ROADMAP item 3's named leftover, measured e2e: the remaining
    budget ships INSIDE the search request, so a vmstorage whose scan
    outlives the budget aborts mid-flight (vm_storage_deadline_aborts_
    total ticks within ~one check interval) and the vmselect receives
    the TYPED deadline error — partial result, node NOT marked down."""
    procs = deadline_cluster
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)
    _ingest(vi, "sda", 120)
    for key in ("st1", "st2"):
        _flush(procs[key].port)
    t_s = (T0 + 30000) // 1000
    code, body = _query(vs, "count(sda)", t_s)
    assert code == 200
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) == 120.0

    # burn most of the shipped budget inside the admission slot, then
    # dilate every budget check: the abort lands at the FIRST check
    # after expiry, and its typed error beats the socket cutoff (the
    # client allows bounded slack past the shipped budget exactly so a
    # budget-honoring node can answer instead of being marked down)
    _set_faults(procs["st2"].port,
                "storage:search:*=delay:1500;storage:scan=delay:200")
    try:
        t0 = time.perf_counter()
        code, body = _query(vs, "count(sda)", t_s)
        took = time.perf_counter() - t0
        res = json.loads(body)
        assert code == 200, body
        assert res.get("isPartial") is True
        n = float(res["data"]["result"][0]["value"][1])
        assert 0 < n < 120          # the surviving node's shard
        assert took < 7.0, f"aborted query cost {took:.1f}s"
        # the storage-side abort is loud on the aborting node
        assert _metric(procs["st2"].port,
                       "vm_storage_deadline_aborts_total") >= 1
        assert _metric(procs["st2"].port,
                       "vm_rpc_server_deadline_total") >= 1
        # ...and typed on the vmselect (deadline, not node failure)
        assert _metric(procs["vs"].port,
                       "vm_rpc_deadline_exceeded_total") >= 1
    finally:
        _set_faults(procs["st2"].port, "")
    # the node was NEVER marked down: with faults cleared, the very next
    # query (inside what would be the 2s down-cooldown) is complete
    code, body = _query(vs, "count(sda)", t_s)
    res = json.loads(body)
    assert code == 200
    assert not res.get("isPartial"), \
        "deadline-aborting node was wrongly marked down"
    assert float(res["data"]["result"][0]["value"][1]) == 120.0


# ---------------------------------------------------------------------------
# scenario 3: RF=2 failover serves identical results
# ---------------------------------------------------------------------------

@pytest.fixture()
def rf2_cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("chaos_rf2")
    procs = _spawn_cluster(d, free_ports(8), rf=2)
    try:
        yield procs
    finally:
        for p in procs.values():
            p.stop(kill=True)


def test_rf2_failover_identical_results(rf2_cluster):
    """With RF=2 over 2 nodes, killing one node changes NOTHING about
    the data returned: the full instant vector (every series, every
    value) is byte-identical before and after the kill."""
    procs = rf2_cluster
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)
    _ingest(vi, "rfc", 80)
    for key in ("st1", "st2"):
        _flush(procs[key].port)
    t_s = (T0 + 30000) // 1000
    code, before_body = _query(vs, "rfc", t_s)
    before = json.loads(before_body)
    assert code == 200 and len(before["data"]["result"]) == 80

    procs["st2"].stop(kill=True)
    time.sleep(0.3)
    t0 = time.perf_counter()
    code, after_body = _query(vs, "rfc", t_s)
    took = time.perf_counter() - t0
    after = json.loads(after_body)
    assert code == 200
    assert took < 12.0, f"failover query took {took:.1f}s"
    # identical results — replication, not luck
    assert after["data"] == before["data"]
    # replica-aware partial accounting: every hash range of the dead
    # node is RF-covered by the surviving responder, so the result is
    # NOT flagged partial; vm_partial_avoided_total ticks instead
    assert not after.get("isPartial"), \
        "RF-covered failover wrongly flagged partial"
    assert _metric(procs["vs"].port, "vm_partial_avoided_total") >= 1
    # also under aggregation
    code, body = _query(vs, "sum(rfc)", t_s)
    res = json.loads(body)
    assert float(res["data"]["result"][0]["value"][1]) == \
        float(sum(i + 2 for i in range(80)))
    assert not res.get("isPartial")


# ---------------------------------------------------------------------------
# scenario 4: ingest storm racing force_merge
# ---------------------------------------------------------------------------

def test_ingest_storm_during_force_merge(cluster):
    """Liveness + no lost rows: a multi-writer ingest storm runs while
    both storage nodes are repeatedly force-merged and force-flushed;
    every write is accepted and the final counts/sums are exact."""
    procs = cluster["procs"]
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)
    n_writers, n_batches, n_series = 3, 12, 40
    codes = []
    stop = threading.Event()

    def writer(w):
        for b in range(n_batches):
            lines = [f'storm{{w="{w}",series="{i}"}} {i} '
                     f'{T0 + b * 15000}' for i in range(n_series)]
            code, _ = vi.post(
                "/insert/0/prometheus/api/v1/import/prometheus",
                "\n".join(lines).encode())
            codes.append(code)

    def merger():
        while not stop.is_set():
            for key in ("st1", "st2"):
                try:
                    for ep in ("force_flush", "force_merge"):
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{procs[key].port}"
                                f"/internal/{ep}", timeout=30):
                            pass
                except OSError:
                    pass
            time.sleep(0.05)

    writers = [threading.Thread(target=writer, args=(w,))
               for w in range(n_writers)]
    mt = threading.Thread(target=merger)
    mt.start()
    t0 = time.perf_counter()
    for t in writers:
        t.start()
    for t in writers:
        t.join(timeout=120)
    stop.set()
    mt.join(timeout=30)
    assert all(c == 204 for c in codes), codes
    assert time.perf_counter() - t0 < 120
    for key in ("st1", "st2"):
        _flush(procs[key].port)
    # exactness: every series from every writer present, values intact
    t_s = (T0 + n_batches * 15000) // 1000
    code, body = _query(vs, "count(storm)", t_s)
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) == \
        float(n_writers * n_series)
    code, body = _query(vs, "sum(storm)", t_s)
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) == \
        float(n_writers * sum(range(n_series)))


# ---------------------------------------------------------------------------
# scenario 5: per-tenant QoS — a saturating tenant cannot starve another
# ---------------------------------------------------------------------------

@pytest.fixture()
def qos_single(tmp_path_factory):
    """One vmsingle with tenant quotas armed: tenant 1 capped at 1
    concurrent search with a 100ms queue budget; tenant 1's searches
    fault-delayed 250ms INSIDE the gate slot, tenant 2's delayed 60ms
    (a stable, machine-independent baseline for the p99 ratio)."""
    d = tmp_path_factory.mktemp("chaos_qos")
    port = free_ports(1)[0]
    app = AppProc(
        "vmsingle",
        [f"-storageDataPath={d}/data",
         f"-httpListenAddr=127.0.0.1:{port}"],
        port, "vmsingle-qos",
        env={"VM_TENANT_QUOTAS": "1:0=1:100:low",
             "VM_SEARCH_CONCURRENCY": "4",
             "VM_FAULTS": "storage:search:1:0=delay:250;"
                          "storage:search:2:0=delay:60"})
    try:
        yield app
    finally:
        app.stop(kill=True)


def test_tenant_qos_saturating_tenant_sheds_other_tenant_unharmed(
        qos_single):
    """The acceptance property: with VM_TENANT_QUOTAS set, a tenant
    saturating its quota gets 429s (shed load, accounted) while a
    second tenant's p99 stays within 2x its unloaded p99."""
    c = Client(qos_single.port)
    for tenant, name in (("1:0", "tm1"), ("2:0", "tm2")):
        _ingest(c, name, 8, tenant=tenant)
    t_s = (T0 + 30000) // 1000

    def one_query(tenant, name, i):
        t0 = time.perf_counter()
        code, body = c.get(
            f"/select/{tenant}/prometheus/api/v1/query",
            query=f"count({name})", time=str(t_s + i))
        return code, time.perf_counter() - t0

    # unloaded baseline for tenant 2
    unloaded = [one_query("2:0", "tm2", i)[1] for i in range(25)]
    p99_unloaded = _pXX(unloaded)

    # tenant 1 storm: 3 threads hammering a quota of 1
    stop = threading.Event()
    t1_codes = []

    def storm():
        i = 1000
        while not stop.is_set():
            code, _ = one_query("1:0", "tm1", i)
            t1_codes.append(code)
            i += 1

    storm_threads = [threading.Thread(target=storm) for _ in range(3)]
    for t in storm_threads:
        t.start()
    time.sleep(0.5)
    try:
        loaded = [one_query("2:0", "tm2", 500 + i)[1] for i in range(25)]
    finally:
        stop.set()
        for t in storm_threads:
            t.join(timeout=30)
    p99_loaded = _pXX(loaded)

    # the saturating tenant was shed with 429s, and kept partial service
    assert t1_codes.count(429) > 0, f"no shed load: {t1_codes[:20]}"
    assert t1_codes.count(200) > 0, "tenant 1 was starved outright"
    assert set(t1_codes) <= {200, 429}, set(t1_codes)
    # rejection accounting is visible like the ingest limiter's
    assert _metric(qos_single.port,
                   'vm_tenant_search_rejected_total{tenant="1:0"}') > 0
    assert _metric(qos_single.port,
                   'vm_tenant_search_requests_total{tenant="2:0"}') > 0
    # isolation: tenant 2's p99 within 2x its unloaded p99
    assert p99_loaded <= 2 * p99_unloaded, \
        (f"tenant 2 starved: p99 loaded {p99_loaded * 1e3:.0f}ms vs "
         f"unloaded {p99_unloaded * 1e3:.0f}ms")


# ---------------------------------------------------------------------------
# scenario 6: live resharding — join mid-ingest, drain mid-query-storm
# ---------------------------------------------------------------------------

def _cluster_admin(port: int, action: str, **params):
    q = urllib.parse.urlencode(params)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/internal/cluster/{action}?{q}",
        method="POST" if params else "GET")
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _full_vector(vs: Client, name: str, t_s: float):
    code, body = _query(vs, name, t_s)
    assert code == 200, body
    res = json.loads(body)["data"]["result"]
    return sorted((json.dumps(e["metric"], sort_keys=True),
                   e["value"][1]) for e in res)


def test_join_and_drain_under_chaos(cluster):
    """ISSUE 15 acceptance: a node joins mid-ingest and a node drains
    mid-query-storm — no restart, zero dropped acked writes, byte-exact
    reads post-migration, vm_parts_migrated_total accounting."""
    procs, ports, d = (cluster["procs"], cluster["ports"], cluster["dir"])
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)

    # ---- phase 1: JOIN mid-ingest --------------------------------------
    stop = threading.Event()
    write_codes = []
    batches_done = [0]

    def writer():
        b = 0
        while not stop.is_set() and b < 40:
            lines = [f'els{{series="{i}"}} {i + b} {T0 + b * 15000}'
                     for i in range(60)]
            code, _ = vi.post(
                "/insert/0/prometheus/api/v1/import/prometheus",
                "\n".join(lines).encode())
            write_codes.append(code)
            b += 1
            batches_done[0] = b
            time.sleep(0.02)

    wt = threading.Thread(target=writer)
    wt.start()
    time.sleep(0.3)                      # ingest is live mid-join
    s3h, s3i, s3s = free_ports(3)
    procs["st3"] = AppProc("vmstorage",
                           _storage_flags(d, "s3", s3h, s3i, s3s), s3h,
                           "vmstorage-3")
    spec = f"127.0.0.1:{s3i}:{s3s}"
    # reads learn the node FIRST (a read ring missing the node would
    # not see the writes the insert ring routes to it)
    _cluster_admin(procs["vs"].port, "join", node=spec)
    _cluster_admin(procs["vi"].port, "join", node=spec)
    wt.join(timeout=60)
    stop.set()
    assert all(c == 204 for c in write_codes)
    n_batches = batches_done[0]

    for key in ("st1", "st2", "st3"):
        _flush(procs[key].port)
    t_s = (T0 + n_batches * 15000) // 1000
    code, body = _query(vs, "count(els)", t_s)
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) == 60.0
    code, body = _query(vs, "sum(els)", t_s)
    want_sum = float(sum(i + n_batches - 1 for i in range(60)))
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) == \
        want_sum
    # the joiner actually took writes (no restart anywhere)
    assert _metric(procs["st3"].port,
                   "vm_rows_added_to_storage_total") > 0

    # rebalance a byte share of EXISTING parts onto the joiner
    out = _cluster_admin(procs["vi"].port, "rebalance",
                         node=f"127.0.0.1:{s3i}")
    assert out["status"] == "success", out
    assert _metric(procs["vi"].port, "vm_parts_migrated_total") == \
        out["data"]["parts"]
    assert _metric(procs["st3"].port, "vm_parts_migrated_total") == \
        out["data"]["parts"]
    if out["data"]["parts"]:
        assert _metric(procs["vi"].port,
                       "vm_rebalance_moved_bytes_total") > 0

    want = _full_vector(vs, "els", t_s)
    assert len(want) == 60

    # ---- phase 2: DRAIN mid-query-storm --------------------------------
    storm_stop = threading.Event()
    storm_results = []

    def storm():
        while not storm_stop.is_set():
            try:
                code, body = _query(vs, "sum(els)", t_s)
                res = json.loads(body)
                storm_results.append(
                    (code, float(res["data"]["result"][0]["value"][1]),
                     res.get("isPartial")))
            except Exception as e:  # noqa: BLE001 — asserted below
                storm_results.append((0, None, e))
            time.sleep(0.03)

    st_threads = [threading.Thread(target=storm) for _ in range(2)]
    for t in st_threads:
        t.start()
    time.sleep(0.3)
    # the write router drains st2 (stops writes, migrates parts off,
    # drops it from ITS ring)...
    out = _cluster_admin(procs["vi"].port, "drain",
                         node=f"127.0.0.1:{ports[4]}")
    assert out["status"] == "success", out
    assert out["data"]["removed"] and out["data"]["parts"] >= 1
    # ...and only then the read ring lets go of the (now empty) node
    _cluster_admin(procs["vs"].port, "remove",
                   node=f"127.0.0.1:{ports[4]}")
    time.sleep(0.5)
    storm_stop.set()
    for t in st_threads:
        t.join(timeout=30)

    errs = [e for _, _, e in storm_results if not isinstance(e, (bool,
                                                                 type(None)))]
    assert not errs, f"storm errors during drain: {errs[:3]}"
    assert all(c == 200 for c, _, _ in storm_results)
    # every storm answer saw the COMPLETE sum (migration never dropped
    # or double-served a row)
    bad = [(v, p) for _, v, p in storm_results if v != want_sum]
    assert not bad, f"storm saw wrong sums during drain: {bad[:5]}"
    # byte-exact post-migration reads, now served without st2
    procs["st2"].stop()
    assert _full_vector(vs, "els", t_s) == want
    code, body = _query(vs, "sum(els)", t_s)
    res = json.loads(body)
    assert not res.get("isPartial")
    assert float(res["data"]["result"][0]["value"][1]) == want_sum


# ---------------------------------------------------------------------------
# scenario 7: multilevel vmselect over the subprocess cluster
# ---------------------------------------------------------------------------

def test_multilevel_vmselect_matches_flat(cluster):
    """vmselect -> vmselect -> 2x vmstorage: the top of the tree serves
    rows byte-identical to the flat fan-out, through real processes."""
    procs, ports, d = (cluster["procs"], cluster["ports"], cluster["dir"])
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)
    _ingest(vi, "mlp", 120)
    for key in ("st1", "st2"):
        _flush(procs[key].port)
    (s1h, s1i, s1s, s2h, s2i, s2s, ih, sh) = ports
    mid_http, mid_native, top_http = free_ports(3)
    nodes = [f"-storageNode=127.0.0.1:{s1i}:{s1s}",
             f"-storageNode=127.0.0.1:{s2i}:{s2s}"]
    procs["vs_mid"] = AppProc(
        "vmselect",
        nodes + [f"-httpListenAddr=127.0.0.1:{mid_http}",
                 f"-clusternativeListenAddr=127.0.0.1:{mid_native}"],
        mid_http, "vmselect-mid")
    procs["vs_top"] = AppProc(
        "vmselect",
        [f"-storageNode=127.0.0.1:{mid_native}",
         f"-httpListenAddr=127.0.0.1:{top_http}"],
        top_http, "vmselect-top")
    top = Client(top_http)
    t_s = (T0 + 30000) // 1000
    code, flat_body = _query(vs, "mlp", t_s)
    assert code == 200
    code, top_body = _query(top, "mlp", t_s)
    assert code == 200
    flat = json.loads(flat_body)["data"]
    tree = json.loads(top_body)["data"]
    assert len(flat["result"]) == 120
    assert tree == flat
    # aggregation through the tree too
    code, body = _query(top, "sum(mlp)", t_s)
    assert float(json.loads(body)["data"]["result"][0]["value"][1]) == \
        float(sum(i + 2 for i in range(120)))


# ---------------------------------------------------------------------------
# scenario 8: SLO burn + incident auto-diagnosis through a faulted node
# ---------------------------------------------------------------------------

@pytest.fixture()
def slo_cluster(tmp_path_factory):
    """2 nodes, RF=1, fault toggle armed, the vmselect self-scraping
    every 250ms; tight burn windows (5s/15s, threshold 5x) so the storm
    fires within two pumped evals and recovery resolves in seconds.
    VM_SLO_EVAL_INTERVAL is huge: every eval round is pump-driven, so
    'within 2 eval intervals' is two ?pump=1 calls, deterministically."""
    d = tmp_path_factory.mktemp("chaos_slo")
    ports = free_ports(8)
    procs = _spawn_cluster(
        d, ports,
        select_extra=["-selfScrapeInterval=0.25"],
        env={"VM_FAULT_INJECT": "1",
             "VM_SLO_WINDOWS": "5s:15s:5",
             "VM_SLO_PERIOD": "30s",
             "VM_SLO_EVAL_INTERVAL": "3600"})
    try:
        yield {"procs": procs, "ports": ports}
    finally:
        for p in procs.values():
            p.stop(kill=True)


def _slo_status(vs: Client, pump: bool = False) -> dict:
    params = {"pump": "1"} if pump else {}
    code, body = vs.get("/api/v1/status/slo", **params)
    assert code == 200, body
    return json.loads(body)


def _slo_of(status: dict, name: str) -> dict:
    return next(s for s in status["slos"] if s["slo"] == name)


def test_slo_burn_incident_autodiagnosis_and_recovery(slo_cluster):
    """The ISSUE 17 acceptance chain, end to end through real processes:
    a fault-injected erroring vmstorage drives a deny_partial 503 storm,
    the availability SLO burns over threshold within 2 pumped evals, the
    auto-opened incident links a flight capture + profiler snapshot +
    a degraded cluster verdict NAMING the faulted node — and after the
    fault clears, the incident resolves and the verdict returns to ok."""
    procs, ports = slo_cluster["procs"], slo_cluster["ports"]
    (s1h, s1i, s1s, s2h, s2i, s2s, ih, sh) = ports
    vi, vs = Client(procs["vi"].port), Client(procs["vs"].port)
    _ingest(vi, "slom", 40)
    for key in ("st1", "st2"):
        _flush(procs[key].port)

    # the vmselect's self-scrape must be landing in the cluster before
    # any burn math can see indicator series
    deadline = time.time() + 20
    while time.time() < deadline:
        code, body = _query(vs, "vm_http_requests_total", time.time())
        if code == 200 and json.loads(body)["data"]["result"]:
            break
        time.sleep(0.25)
    else:
        pytest.fail("self-scraped series never appeared in the cluster")

    # baseline: availability healthy, verdict ok
    avail = _slo_of(_slo_status(vs, pump=True), "http-availability")
    assert not avail["firing"], avail
    code, body = vs.get("/api/v1/status/health")
    assert code == 200 and json.loads(body)["verdict"] == "ok", body

    # fault the node that does NOT own the error-indicator series: the
    # SLO evals (partial-tolerant) keep reading it from the healthy
    # node.  Placement is the write path's own consistent hash, so the
    # test reconstructs it instead of guessing.  (If the OTHER side of
    # the ratio lands on the faulted node, the total<=0 & bad>0 ->
    # ratio=1.0 fold rule covers it — but determinism beats luck.)
    import struct

    from victoriametrics_tpu.parallel.consistenthash import ConsistentHash
    from victoriametrics_tpu.storage.metric_name import MetricName
    bad_series = {"__name__": "vm_http_request_errors_total",
                  "path": "/select/", "job": "victoria-metrics",
                  "instance": f"vmselect:{sh}"}
    ch = ConsistentHash([f"127.0.0.1:{s1i}", f"127.0.0.1:{s2i}"])
    owner = ch.nodes_for_key(
        struct.pack(">II", 0, 0) +
        MetricName.from_dict(bad_series).marshal(), 1, set())[0]
    victim = "st2" if owner == 0 else "st1"
    victim_name = f"127.0.0.1:{s2i if owner == 0 else s1i}"

    _set_faults(procs[victim].port,
                "rpc:searchColumns_v1=error;rpc:search_v1=error")
    try:
        # the error storm: strict clients demand complete answers while
        # one shard errors -> 503s, ticking the availability indicator
        t_s = (T0 + 30000) // 1000
        codes = []
        for _ in range(40):
            code, _body = vs.get("/select/0/prometheus/api/v1/query",
                                 query="count(slom)", time=str(t_s),
                                 deny_partial="1")
            codes.append(code)
            time.sleep(0.02)
        assert codes.count(503) >= 10, codes
        time.sleep(0.6)            # >= 2 scrape ticks: errors are stored

        # two pumps = the 2-eval-interval acceptance budget
        for _ in range(2):
            avail = _slo_of(_slo_status(vs, pump=True),
                            "http-availability")
            if avail["firing"]:
                break
        assert avail["firing"], avail
        assert avail["severity"] == "page"
        assert avail["openIncidentId"] is not None

        # the frozen incident links every diagnosis surface
        code, body = vs.get("/api/v1/status/incidents",
                            id=str(avail["openIncidentId"]))
        assert code == 200, body
        rec = json.loads(body)["data"]
        assert rec["slo"] == "http-availability"
        assert rec["resolvedMs"] is None
        assert rec["flightCaptureId"] is not None
        assert rec["profile"] is not None
        health_at_breach = rec["health"]
        assert health_at_breach["verdict"] in ("degraded", "critical")
        assert any(r.get("node") == victim_name
                   for r in health_at_breach["reasons"]), \
            health_at_breach["reasons"]
        # ...and the flight capture is fetchable as a real trace
        code, body = vs.get("/api/v1/status/flight",
                            id=str(rec["flightCaptureId"]))
        assert code == 200, body

        # the live roll-up names the node too, while it is down
        code, _body = vs.get("/select/0/prometheus/api/v1/query",
                             query="count(slom)", time=str(t_s),
                             deny_partial="1")   # refresh the down mark
        code, body = vs.get("/api/v1/status/health")
        h = json.loads(body)
        assert h["verdict"] in ("degraded", "critical")
        assert any(r.get("node") == victim_name for r in h["reasons"]), \
            h["reasons"]
        assert h["ring"]["rerouteActive"] is True
    finally:
        _set_faults(procs[victim].port, "")

    # recovery: the 15s window drains, the incident resolves, and the
    # verdict returns to ok
    deadline = time.time() + 45
    avail = h = None
    while time.time() < deadline:
        avail = _slo_of(_slo_status(vs, pump=True), "http-availability")
        code, body = vs.get("/api/v1/status/health")
        h = json.loads(body)
        if not avail["firing"] and h["verdict"] == "ok":
            break
        time.sleep(1.0)
    else:
        pytest.fail(f"never recovered: firing={avail and avail['firing']}"
                    f" verdict={h and h['verdict']} reasons="
                    f"{h and h['reasons']}")
    assert avail["openIncidentId"] is None
    # the resolved incident stays in the log, resolvedMs stamped
    code, body = vs.get("/api/v1/status/incidents")
    assert code == 200, body
    summaries = json.loads(body)["data"]
    mine = [s for s in summaries if s["slo"] == "http-availability"]
    assert mine and mine[0]["resolvedMs"] is not None, summaries
