"""Golden conformance corpus: 579 query cases transcribed mechanically from
the reference's app/vmselect/promql/exec_test.go (TestExecSuccess harness:
start=1000e3 end=2000e3 step=200e3, 6 output points per series), plus 10
binary-op label-matching pins added with the common-filter pushdown
optimizer (the optimizer runs by default in exec, so every case here also
conforms THROUGH it; the pushdown-specific table lives in
tests/test_optimizer.py).

tests/golden_known_gaps.json is EMPTY: all extracted cases pass,
including the Go-PRNG rand() family (bit-exact math/rand replica in
query/gorand.py). Keep it empty.
"""

import json
import math
import os

import numpy as np
import pytest

from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig

HERE = os.path.dirname(__file__)
CASES = json.load(open(os.path.join(HERE, "golden_corpus.json")))


def _tovals(vs):
    return [math.nan if v is None else
            (math.inf if v == "inf" else -math.inf) if isinstance(v, str)
            else float(v) for v in vs]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c["q"][:60])
def test_golden(case):
    ec = EvalConfig(start=1_000_000, end=2_000_000, step=200_000,
                    storage=None)
    rows = exec_query(ec, case["q"])
    # exec-level removeEmptySeries semantics (reference exec.go)
    rows = [r for r in rows if not np.isnan(r.values).all()]
    want = case["results"]
    assert len(rows) == len(want), \
        f"{case['q']}: {len(rows)} series, want {len(want)}"
    wmap = {}
    for w in want:
        wmap.setdefault(json.dumps(w["labels"], sort_keys=True),
                        []).append(w)
    for r in rows:
        key = json.dumps(r.metric_name.to_dict(), sort_keys=True)
        lst = wmap.get(key)
        assert lst, f"{case['q']}: unexpected series {key}"
        w = lst.pop(0)
        np.testing.assert_allclose(
            r.values, _tovals(w["values"]), rtol=2e-9, atol=2e-9,
            equal_nan=True, err_msg=case["q"])


def test_known_gaps_do_not_grow():
    gaps = json.load(open(os.path.join(HERE, "golden_known_gaps.json")))
    assert len(gaps) == 0, (
        "golden_known_gaps.json grew — a previously passing case regressed")
