"""exec_query over a multi-device mesh must agree with the host path and
with the single-device engine (VERDICT r2 #2: the reference's read scaling
is scatter-gather + merged partial aggregates, aggr_incremental.go:98-168 +
vmselectapi/server.go:1010; the TPU equivalent shards the series axis of a
real fetched workload over the mesh and psums partial group moments).

conftest.py forces a virtual 8-device CPU platform, so the mesh here is a
real 8-way series-axis mesh.
"""

import numpy as np
import pytest


T0 = 1_753_700_000_000


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    from victoriametrics_tpu.storage.storage import Storage
    s = Storage(str(tmp_path_factory.mktemp("meshq") / "s"))
    rng = np.random.default_rng(11)
    rows = []
    # 97 series: NOT a multiple of 8, so the mesh pad path is exercised.
    for i in range(97):
        base = np.arange(60, dtype=np.int64) * 15_000 + T0 - 600_000
        ts = np.sort(base + rng.integers(-2000, 2001, 60))
        # integer-valued counters: group sums are exact in float64, so the
        # per-shard psum order cannot change the result bits
        vals = np.cumsum(rng.integers(0, 30, 60)).astype(float)
        lab = {"__name__": "mq", "instance": f"h{i % 8}", "job": f"j{i % 3}"}
        rows.extend(zip([lab] * 60, ts.tolist(), vals.tolist()))
    s.add_rows(rows)
    s.force_flush()
    yield s
    s.close()


def _mesh8():
    import jax

    from victoriametrics_tpu.parallel.mesh import make_mesh
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(n_series=8, n_time=1, devices=devs[:8])


def _run(store, q, engine):
    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.types import EvalConfig
    kw = dict(start=T0 - 300_000, end=T0, step=60_000, storage=store)
    if engine is not None:
        kw["tpu"] = engine
    return exec_query(EvalConfig(**kw), q)


def _as_map(rows):
    return {r.metric_name.marshal(): np.asarray(r.values) for r in rows}


EXACT_QUERIES = [
    # integer-exact aggregations: bit-equality across 1 vs 8 devices
    "sum by (instance)(last_over_time(mq[2m]))",
    "count(last_over_time(mq[2m]))",
    "max by (job)(last_over_time(mq[2m]))",
    "min by (instance,job)(last_over_time(mq[2m]))",
    "sum by (job)(delta(mq[4m]))",
]

CLOSE_QUERIES = [
    "sum by (instance)(rate(mq[5m]))",
    "avg by (job)(increase(mq[3m]))",
    "stddev by (job)(avg_over_time(mq[5m]))",
    "quantile(0.9, rate(mq[5m])) by (instance)",
    "median(increase(mq[3m])) by (instance)",
]


class TestExecQueryMesh:

    @pytest.mark.parametrize("q", EXACT_QUERIES)
    def test_bit_equal_1_vs_8_devices(self, store, q):
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        mesh = _mesh8()
        one = _run(store, q, TPUEngine(min_series=4))
        eight = _run(store, q, TPUEngine(min_series=4, mesh=mesh))
        m1, m8 = _as_map(one), _as_map(eight)
        assert set(m1) == set(m8) and len(m1) > 0
        for k in m1:
            np.testing.assert_array_equal(m8[k], m1[k], err_msg=q)

    @pytest.mark.parametrize("q", EXACT_QUERIES + CLOSE_QUERIES)
    def test_mesh_matches_host(self, store, q):
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        mesh = _mesh8()
        host = _run(store, q, None)
        eight = _run(store, q, TPUEngine(min_series=4, mesh=mesh))
        hm, m8 = _as_map(host), _as_map(eight)
        assert set(hm) == set(m8) and len(hm) > 0
        for k in hm:
            np.testing.assert_allclose(m8[k], hm[k], rtol=1e-9, atol=1e-9,
                                       equal_nan=True, err_msg=q)

    def test_mesh_warm_path(self, store):
        """Second run takes the resident-tile shortcut on the SHARDED tile."""
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        mesh = _mesh8()
        engine = TPUEngine(min_series=4, mesh=mesh)
        q = "sum by (instance)(rate(mq[5m]))"
        host = _as_map(_run(store, q, None))
        cold = _as_map(_run(store, q, engine))
        warm = _as_map(_run(store, q, engine))
        for m in (cold, warm):
            assert set(m) == set(host)
            for k in host:
                np.testing.assert_allclose(m[k], host[k], rtol=1e-9,
                                           atol=1e-9, equal_nan=True)

    def test_tile_is_actually_sharded(self, store):
        """The cached tile must be laid out over the mesh, not replicated."""
        from victoriametrics_tpu.query.tpu_engine import TPUEngine
        mesh = _mesh8()
        engine = TPUEngine(min_series=4, mesh=mesh)
        _run(store, "sum by (instance)(rate(mq[5m]))", engine)
        tiles = list(engine.cache()._entries.values())
        assert tiles, "tile cache empty after device query"
        ts_t = tiles[0][0]
        assert ts_t.shape[0] % 8 == 0  # padded to the series axis
        assert len(ts_t.sharding.device_set) == 8
