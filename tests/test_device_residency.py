"""Tier-1 regression guards for device-resident mesh-sharded rollup
serving (ISSUE 12): over a rolling dashboard loop on the virtual 8-device
CPU mesh, a steady-state refresh must UPLOAD only the suffix tail columns
(< 5% of the cold-window upload, by vm_device_bytes_uploaded_total) and be
served from the resident window (vm_device_window_cache_hits_total ticks).
Churn (a new series appearing) must fall back LOUDLY to the full-upload
rebuild and still agree with the VM_DEVICE_RESIDENT=0 oracle; window-slide
compaction (ops.device_rollup.compact_tile) must keep the window rolling
once column headroom runs out, without touching results.

Mirrors tests/test_refresh_suffix_guard.py on the device plane."""

import time

import numpy as np
import pytest

from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.models import tile_cache as tclib
from victoriametrics_tpu.query import rollup_result_cache as rrc
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.utils import metrics as metricslib

STEP = 60_000
SCRAPE = 15_000
NS = 64
NN = 1440
Q = "sum by (g)(rate(resg[5m]))"


def _mesh8():
    import jax

    from victoriametrics_tpu.parallel.mesh import make_mesh
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(n_series=8, n_time=1, devices=devs[:8])


def _mk_store(path, n_samples=NN, name="resg"):
    s = Storage(str(path))
    now = int(time.time() * 1000)
    t0 = (now - (n_samples - 1) * SCRAPE) // STEP * STEP
    rng = np.random.default_rng(5)
    rows = []
    vals0 = np.empty(NS)
    for i in range(NS):
        ts = np.sort(np.arange(n_samples, dtype=np.int64) * SCRAPE + t0 +
                     rng.integers(-2000, 2001, n_samples))
        vals = np.cumsum(rng.integers(0, 30, n_samples)).astype(np.float64)
        vals0[i] = vals[-1]
        rows.extend(zip([{"__name__": name, "i": str(i),
                          "g": f"g{i % 4}"}] * n_samples,
                        ts.tolist(), vals.tolist()))
    s.add_rows(rows)
    s.force_flush()
    # first window end: past every jittered initial sample
    end0 = t0 + -(-((n_samples - 1) * SCRAPE + 2000) // STEP) * STEP
    return s, end0, vals0, rng


def _ingest(s, rng, vals0, end, name="resg", k=4, scrape=SCRAPE,
            n_series=NS):
    """k fresh scrapes per series in (end - k*scrape, end]."""
    rows = []
    for i in range(n_series):
        incr = np.cumsum(rng.integers(0, 30, k))
        ts = end - (np.arange(k, dtype=np.int64)[::-1]) * scrape - \
            rng.integers(0, 2000)
        rows.extend(zip([{"__name__": name, "i": str(i),
                          "g": f"g{i % 4}"}] * k,
                        ts.tolist(), (vals0[i] + incr).tolist()))
        vals0[i] += incr[-1]
    s.add_rows(rows)


def _as_map(rows):
    return {r.metric_name.marshal(): np.asarray(r.values) for r in rows}


def test_refresh_uploads_only_tail_on_mesh(tmp_path):
    """THE residency guard: rolling refreshes on the virtual 8-device mesh
    upload < 5% of the cold-window upload each, and the resident-window
    hit counter ticks every refresh."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    mesh = _mesh8()
    s, end, vals0, rng = _mk_store(tmp_path / "s")
    try:
        rrc.GLOBAL.reset()
        engine = TPUEngine(min_series=4, mesh=mesh)
        api = PrometheusAPI(s, engine)
        dur = (NN - 1) * SCRAPE // STEP * STEP - 10 * STEP
        kw = dict(step=STEP, storage=s, tpu=engine)
        up0 = tclib.bytes_uploaded()
        # warm-up: cold full-window eval builds the resident sharded
        # window (and pays the full upload ONCE)
        api._exec_range_cached(EvalConfig(start=end - dur, end=end, **kw),
                               Q, end)
        cold_upload = tclib.bytes_uploaded() - up0
        assert cold_upload > 0
        hits0 = metricslib.REGISTRY.counter(
            "vm_device_window_cache_hits_total").get()
        for r in range(3):
            end += STEP
            _ingest(s, rng, vals0, end)
            up_r = tclib.bytes_uploaded()
            served = api._exec_range_cached(
                EvalConfig(start=end - dur, end=end, **kw), Q, end)
            refresh_upload = tclib.bytes_uploaded() - up_r
            assert len(served) == 4
            # THE guard: a refresh must ship only tail columns
            assert refresh_upload < 0.05 * cold_upload, (
                f"refresh {r} uploaded {refresh_upload} bytes "
                f"(cold window = {cold_upload}): device serving has "
                "regressed to full re-upload")
        hits = metricslib.REGISTRY.counter(
            "vm_device_window_cache_hits_total").get()
        assert hits >= hits0 + 3, "resident-window hits did not tick"
        # the resident window really is mesh-sharded
        from victoriametrics_tpu.query.tpu_engine import RollingTile
        rts = [v for v in engine.window_cache()._entries.values()
               if isinstance(v, RollingTile)]
        assert rts and len(rts[0].tiles[0].sharding.device_set) == 8
    finally:
        s.close()


def _run_sequence(tmp_path, sub, mesh, churn=False):
    """One deterministic rolling sequence; returns the per-refresh row
    maps.  churn=True ingests a NEW series before the last refresh (the
    loud-fallback case)."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    s, end, vals0, rng = _mk_store(tmp_path / sub, n_samples=240)
    try:
        rrc.GLOBAL.reset()
        engine = TPUEngine(min_series=4, mesh=mesh)
        api = PrometheusAPI(s, engine)
        dur = 239 * SCRAPE // STEP * STEP - 10 * STEP
        kw = dict(step=STEP, storage=s, tpu=engine)
        api._exec_range_cached(EvalConfig(start=end - dur, end=end, **kw),
                               Q, end)
        out = []
        churn_pair = None
        for r in range(3):
            end += STEP
            _ingest(s, rng, vals0, end)
            if churn and r == 2:
                # a brand-new series appears: advance must decline loudly
                # and rebuild via the full-upload path
                s.add_rows([({"__name__": "resg", "i": "new", "g": "g0"},
                             end - 7_000, 1.0)])
            rows = api._exec_range_cached(
                EvalConfig(start=end - dur, end=end, **kw), Q, end)
            out.append(_as_map(rows))
            if churn and r == 2:
                # the fallback rebuild must BE the cold full-upload eval:
                # a fresh nocache eval of the same window is bit-identical
                cold = exec_query(EvalConfig(start=end - dur, end=end,
                                             **kw, disable_cache=True), Q)
                churn_pair = (out[-1], _as_map(cold))
        return out, churn_pair
    finally:
        s.close()


def test_churn_falls_back_and_matches_oracle(tmp_path, monkeypatch):
    """New-series churn: the resident window declines, rebuilds full, and
    every refresh agrees with the VM_DEVICE_RESIDENT=0 full-upload oracle
    (bit-exact on the rebuild refresh; rtol=1e-12 on resident refreshes —
    XLA orders group sums differently across suffix/full grids)."""
    mesh = _mesh8()
    got, churn_pair = _run_sequence(tmp_path, "a", mesh, churn=True)
    # loud fallback really is the full-upload path: the churn refresh is
    # bit-identical to a fresh nocache eval of the same window
    served_map, cold_map = churn_pair
    assert set(served_map) == set(cold_map)
    for k in served_map:
        np.testing.assert_array_equal(served_map[k], cold_map[k])
    monkeypatch.setenv("VM_DEVICE_RESIDENT", "0")
    want, _ = _run_sequence(tmp_path, "b", mesh, churn=True)
    assert len(got) == len(want)
    for r, (gm, wm) in enumerate(zip(got, want)):
        assert set(gm) == set(wm), r
        for k in gm:
            # rtol=1e-12: the oracle serves through the host ring cache
            # (suffix grids), the resident path through the rolling
            # window — XLA orders group sums differently per grid shape
            fa, fb = np.isnan(gm[k]), np.isnan(wm[k])
            np.testing.assert_array_equal(fa, fb)
            np.testing.assert_allclose(gm[k][~fa], wm[k][~fb],
                                       rtol=1e-12, err_msg=str(r))


def test_oracle_disables_resident_reuse(tmp_path, monkeypatch):
    """VM_DEVICE_RESIDENT=0: no resident-window hits, every refresh
    re-uploads (the loud escape hatch really is a full-upload path)."""
    mesh = _mesh8()
    monkeypatch.setenv("VM_DEVICE_RESIDENT", "0")
    hits0 = metricslib.REGISTRY.counter(
        "vm_device_window_cache_hits_total").get()
    _run_sequence(tmp_path, "c", mesh)
    assert metricslib.REGISTRY.counter(
        "vm_device_window_cache_hits_total").get() == hits0


def test_window_slide_compaction_keeps_rolling(tmp_path):
    """Column-headroom exhaustion triggers on-device compaction (samples
    older than the fetch bound dropped, origin rebased) instead of a
    rebuild: the compaction counter ticks, the window keeps advancing
    in place, and results still match a cold eval at rtol=1e-12."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    s, end, vals0, rng = _mk_store(tmp_path / "s", n_samples=80)
    try:
        rrc.GLOBAL.reset()
        engine = TPUEngine(min_series=4)
        api = PrometheusAPI(s, engine)
        q = "sum by (g)(rate(resg[2m]))"
        dur = 10 * STEP
        kw = dict(step=STEP, storage=s, tpu=engine)
        api._exec_range_cached(EvalConfig(start=end - dur, end=end, **kw),
                               q, end)
        comp0 = metricslib.REGISTRY.counter(
            "vm_device_window_compactions_total").get()
        hits0 = metricslib.REGISTRY.counter(
            "vm_device_window_cache_hits_total").get()
        # each refresh jumps 5 minutes (constant-shape advance, scrape
        # cadence unchanged): 20 new columns per refresh exhaust the
        # ~48-column headroom of an 80-sample tile within a few refreshes
        for r in range(6):
            end += 5 * STEP
            _ingest(s, rng, vals0, end, k=20, scrape=SCRAPE)
            served = api._exec_range_cached(
                EvalConfig(start=end - dur, end=end, **kw), q, end)
            cold = exec_query(EvalConfig(start=end - dur, end=end, **kw,
                                         disable_cache=True), q)
            gm, cm = _as_map(served), _as_map(cold)
            assert set(gm) == set(cm)
            for k in gm:
                fa = np.isnan(gm[k])
                np.testing.assert_array_equal(fa, np.isnan(cm[k]))
                np.testing.assert_allclose(gm[k][~fa], cm[k][~fa],
                                           rtol=1e-12, err_msg=str(r))
        assert metricslib.REGISTRY.counter(
            "vm_device_window_compactions_total").get() > comp0, \
            "headroom exhaustion never compacted"
        assert metricslib.REGISTRY.counter(
            "vm_device_window_cache_hits_total").get() >= hits0 + 6, \
            "compaction fell back to rebuild instead of keeping residency"
    finally:
        s.close()


def test_compact_tile_kernel_bitexact():
    """compact_tile == numpy reference: prefix drop + left shift + rebase,
    TS_PAD restored in freed tails."""
    import jax.numpy as jnp

    from victoriametrics_tpu.ops.device_rollup import TS_PAD, compact_tile
    rng = np.random.default_rng(9)
    S, N = 5, 32
    counts = rng.integers(0, N + 1, S).astype(np.int32)
    ts = np.full((S, N), TS_PAD, np.int32)
    vals = np.zeros((S, N))
    for i in range(S):
        ts[i, :counts[i]] = np.sort(rng.integers(0, 10_000, counts[i]))
        vals[i, :counts[i]] = rng.normal(size=counts[i])
    cutoff, delta = np.int32(4_000), np.int32(4_000)
    ts2, v2, c2 = compact_tile(jnp.asarray(ts), jnp.asarray(vals),
                               jnp.asarray(counts), cutoff, delta)
    ts2, v2, c2 = np.asarray(ts2), np.asarray(v2), np.asarray(c2)
    for i in range(S):
        keep = ts[i, :counts[i]] >= cutoff
        want_ts = ts[i, :counts[i]][keep] - delta
        want_v = vals[i, :counts[i]][keep]
        assert c2[i] == keep.sum()
        np.testing.assert_array_equal(ts2[i, :c2[i]], want_ts)
        np.testing.assert_array_equal(v2[i, :c2[i]], want_v)
        assert (ts2[i, c2[i]:] == TS_PAD).all()


def test_compact_window_declines_past_int32(tmp_path):
    """A cutoff beyond the int32 frame (dashboard resumed after a very
    long pause on an old tile) must DECLINE — not raise OverflowError —
    and must not touch the tile state."""
    import jax.numpy as jnp

    from victoriametrics_tpu.ops.device_rollup import TS_PAD
    from victoriametrics_tpu.query.tpu_engine import (RollingTile,
                                                      TPUEngine,
                                                      compact_window)
    engine = TPUEngine(min_series=4)
    ts = jnp.full((2, 8), TS_PAD, jnp.int32).at[:, :3].set(
        jnp.arange(3, dtype=jnp.int32) * 1000)
    vals = jnp.zeros((2, 8))
    counts = jnp.full((2,), 3, jnp.int32)
    rt = RollingTile(tiles=(ts, vals, counts, None), base_ms=1_000_000,
                     n_cap=8, lo_ms=990_000, hi_ms=1_002_000, version=1,
                     structural=0, counts_host=np.full(2, 3, np.int64),
                     row_of_raw={}, n_samples=6, adopted_key=None)
    assert compact_window(engine, rt, 1_000_000 + 2**31 + 5) is False
    assert rt.base_ms == 1_000_000 and rt.n_samples == 6
    # and an in-range cutoff still compacts
    assert compact_window(engine, rt, 1_000_000 + 1_500) is True
    assert rt.base_ms == 1_001_500 and int(rt.counts_host.sum()) == 2


def test_persistent_churn_backs_off_to_host_suffix(tmp_path):
    """Nonstop series churn must not turn every refresh into a full-window
    device rebuild: after 2 consecutive rolling declines the serving
    layer routes the shape back to the host suffix path (O(new samples);
    small suffix-tile uploads only) until the periodic residency retry."""
    from victoriametrics_tpu.query.tpu_engine import TPUEngine
    s, end, vals0, rng = _mk_store(tmp_path / "s", n_samples=720)
    try:
        rrc.GLOBAL.reset()
        engine = TPUEngine(min_series=4)
        api = PrometheusAPI(s, engine)
        dur = 719 * SCRAPE // STEP * STEP - 10 * STEP
        kw = dict(step=STEP, storage=s, tpu=engine)
        up0 = tclib.bytes_uploaded()
        api._exec_range_cached(EvalConfig(start=end - dur, end=end, **kw),
                               Q, end)
        cold_upload = tclib.bytes_uploaded() - up0
        inpl = metricslib.REGISTRY.counter("vm_rollup_cache_inplace_total")
        inpl0 = inpl.get()
        late_uploads = []
        for r in range(5):
            end += STEP
            _ingest(s, rng, vals0, end)
            # a NEW series every refresh: the rolling advance declines
            s.add_rows([({"__name__": "resg", "i": f"new{r}", "g": "g0"},
                         end - 7_000, 1.0)])
            u0 = tclib.bytes_uploaded()
            api._exec_range_cached(
                EvalConfig(start=end - dur, end=end, **kw), Q, end)
            if r >= 2:
                late_uploads.append(tclib.bytes_uploaded() - u0)
        # after the backoff engages, refreshes must not re-upload the
        # window (suffix tiles are a fraction of the cold upload)
        for r, u in enumerate(late_uploads):
            assert u < 0.3 * cold_upload, (
                f"late refresh {r} uploaded {u}B of {cold_upload}B cold: "
                "churn backoff did not engage")
        # and they really served through the host ring cache
        assert inpl.get() > inpl0
    finally:
        s.close()
