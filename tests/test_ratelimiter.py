"""Ingestion rate limiter (lib/ratelimiter analog): budget semantics with
a fake clock, burst smoothing, per-tenant composition, and the HTTP
429 + Retry-After surface."""

import threading
import time

import pytest

from victoriametrics_tpu.ingest.ratelimiter import (RateLimitedError,
                                                    RateLimiter,
                                                    TenantRateLimiters)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestRateLimiter:
    def test_disabled_when_zero(self):
        rl = RateLimiter(0)
        assert rl.register_bounded(10 ** 9, max_wait_s=0) == 0.0

    def test_first_burst_within_limit_admitted(self):
        clk = FakeClock()
        rl = RateLimiter(1000, clock=clk)
        assert rl.register_bounded(1000, max_wait_s=0) == 0.0

    def test_over_budget_reports_retry_after(self):
        clk = FakeClock()
        rl = RateLimiter(1000, clock=clk)
        rl.register_bounded(1000, max_wait_s=0)  # budget exhausted
        retry = rl.register_bounded(500, max_wait_s=0)
        assert retry > 0
        assert rl.limit_reached == 1
        # a huge burst advertises a proportionally longer retry
        retry_big = rl.register_bounded(5000, max_wait_s=0)
        assert retry_big > retry

    def test_budget_refills_with_time(self):
        clk = FakeClock()
        rl = RateLimiter(1000, clock=clk)
        rl.register_bounded(1000, max_wait_s=0)
        assert rl.register_bounded(1, max_wait_s=0) > 0
        clk.t += 1.1  # one refill period passes
        assert rl.register_bounded(900, max_wait_s=0) == 0.0

    def test_burst_is_smoothed_by_blocking(self):
        # real clock: 3000 rows at limit=2000/s must take >= ~0.5s (one
        # refill wait), demonstrating the burst is spread over time
        rl = RateLimiter(2000)
        t0 = time.monotonic()
        for _ in range(3):
            rl.register(1000)  # blocking variant
        dt = time.monotonic() - t0
        assert dt >= 0.4, f"burst was not smoothed: {dt:.3f}s"
        assert rl.limit_reached >= 1

    def test_stop_unblocks_waiters(self):
        rl = RateLimiter(10)
        rl.register(10)  # exhaust
        done = threading.Event()

        def waiter():
            rl.register(1000)  # would block ~100s
            done.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        rl.stop()
        assert done.wait(2.0), "stop() must unblock register()"


class TestTenantRateLimiters:
    def test_global_limit_raises(self):
        clk = FakeClock()
        trl = TenantRateLimiters(global_limit=100, max_wait_s=0,
                                 clock=clk)
        trl.register(100)
        with pytest.raises(RateLimitedError) as ei:
            trl.register(50)
        assert ei.value.retry_after_s >= 1

    def test_per_tenant_isolation(self):
        clk = FakeClock()
        trl = TenantRateLimiters(per_tenant_limit=100, max_wait_s=0,
                                 clock=clk)
        trl.register(100, tenant=(1, 0))
        with pytest.raises(RateLimitedError):
            trl.register(1, tenant=(1, 0))
        # a different tenant still has its own budget
        trl.register(100, tenant=(2, 0))

    def test_disabled(self):
        trl = TenantRateLimiters()
        assert not trl.enabled()
        trl.register(10 ** 9)  # no-op

    def test_saturated_tenant_does_not_starve_global(self):
        """A tenant-rejected batch must not consume global budget (the
        tenant check runs first; a global rejection refunds the tenant)."""
        clk = FakeClock()
        trl = TenantRateLimiters(global_limit=1000, per_tenant_limit=100,
                                 max_wait_s=0, clock=clk)
        trl.register(100, tenant=(1, 0))  # tenant A exhausted
        for _ in range(20):  # A's retries are tenant-rejected
            with pytest.raises(RateLimitedError):
                trl.register(100, tenant=(1, 0))
        # the other tenants still get the full remaining global budget
        for t in range(2, 11):
            trl.register(100, tenant=(t, 0))

    def test_empty_batch_never_limited(self):
        clk = FakeClock()
        trl = TenantRateLimiters(global_limit=10, max_wait_s=0, clock=clk)
        trl.register(10)
        trl.register(0)  # metadata-only post: must not 429


class TestHTTP429:
    def test_429_with_retry_after(self, tmp_path):
        """Sustained overload through the real server returns 429 with a
        Retry-After header; admitted rows still land."""
        from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
        from victoriametrics_tpu.httpapi.server import HTTPServer
        from victoriametrics_tpu.ingest.ratelimiter import \
            TenantRateLimiters
        from victoriametrics_tpu.storage.storage import Storage
        import http.client

        storage = Storage(str(tmp_path / "s"))
        api = PrometheusAPI(
            storage, None,
            rate_limiter=TenantRateLimiters(global_limit=100,
                                            max_wait_s=0))
        srv = HTTPServer("127.0.0.1", 0)
        api.register(srv)
        srv.start()
        try:
            port = srv.port
            now_ms = int(time.time() * 1000)
            body = "\n".join(
                f'rlm{{i="{i}"}} {i} {now_ms}' for i in range(100)
            ).encode()

            def post(b):
                c = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=10)
                c.request("POST", "/api/v1/import/prometheus", body=b)
                r = c.getresponse()
                data = r.read()
                c.close()
                return r.status, dict(r.getheaders()), data

            st1, _, _ = post(body)
            assert st1 == 204
            st2, hdrs, data = post(body)
            assert st2 == 429, (st2, data)
            ra = {k.lower(): v for k, v in hdrs.items()}.get("retry-after")
            assert ra is not None and int(ra) >= 1
        finally:
            srv.stop()
            storage.close()
