"""Common-filter pushdown optimizer (query/metricsql/optimizer):

- the pushdown TABLE: optimized canonical strings for representative
  shapes, mirroring the reference's optimizer_test.go pins;
- CONFORMANCE over real storage: optimized and unoptimized evaluations
  return identical rows for every shape (VM_MQL_OPTIMIZE=0 oracle);
- the WIN: pushdown measurably reduces samples scanned for a
  partially-filtered binary op.
"""

import time

import numpy as np
import pytest

from victoriametrics_tpu.query import exec as qexec
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.metricsql import parse
from victoriametrics_tpu.query.metricsql.optimizer import optimize
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage

# (input, expected canonical optimized form)
PUSHDOWN_TABLE = [
    # scalars / plain selectors: untouched
    ("foo", "foo"),
    ('foo{bar="1"} / 234', 'foo{bar="1"} / 234'),
    # the canonical case: both sides get both filter sets
    ('foo + bar{b=~"a.*", a!="ss"}',
     'foo{a!="ss", b=~"a.*"} + bar{b=~"a.*", a!="ss"}'),
    ('foo{bar="1"} / foo{baz="2"}',
     'foo{bar="1", baz="2"} / foo{bar="1", baz="2"}'),
    # filters cross rollups and series-preserving transforms
    ('rate(foo[1m]) / rate(bar{baz="a"}[5m])',
     'rate(foo{baz="a"}[1m]) / rate(bar{baz="a"}[5m])'),
    ('abs(foo{x="1"}) + bar',
     'abs(foo{x="1"}) + bar{x="1"}'),
    ('histogram_quantile(0.5, foo{a="1"}) + bar{c="3"}',
     'histogram_quantile(0.5, foo{a="1", c="3"}) + bar{a="1", c="3"}'),
    # label-manipulating transforms BLOCK propagation
    ('label_set(foo{a="1"}, "x", "y") + bar',
     'label_set(foo{a="1"}, "x", "y") + bar'),
    ('label_replace(foo{a="1"}, "b", "$1", "a", "(.*)") + bar',
     'label_replace(foo{a="1"}, "b", "$1", "a", "(.*)") + bar'),
    # aggregations propagate through by/without; modifier-less blocks
    ('sum by (x) (foo{bar="1"}) + sum by (x) (baz{x="2"})',
     'sum(foo{bar="1", x="2"}) by (x) + sum(baz{x="2"}) by (x)'),
    ('sum without (a) (foo{a="1", b="2"}) + bar{c="3"}',
     'sum(foo{a="1", b="2", c="3"}) without (a) + bar{b="2", c="3"}'),
    ('sum(foo{bar="1"}) + sum(baz{x="2"})',
     'sum(foo{bar="1"}) + sum(baz{x="2"})'),
    # on/ignoring trim what may cross
    ('foo{a="1"} * on (b) bar{b="2"}',
     'foo{a="1", b="2"} * on (b) bar{b="2"}'),
    ('foo{a="1"} * ignoring (a) bar{b="2"}',
     'foo{a="1", b="2"} * ignoring (a) bar{b="2"}'),
    # set ops: only the surviving side's filters may cross
    ('foo{a="1"} unless bar{b="2"}',
     'foo{a="1"} unless bar{a="1", b="2"}'),
    ('foo{a="1"} default bar', 'foo{a="1"} default bar{a="1"}'),
    ('foo{a="1"} or bar{b="2"}', 'foo{a="1"} or bar{b="2"}'),
    # or-set selectors push only filters common to EVERY set
    ('foo{a="1" or b="2"} + bar{c="3"}',
     'foo{a="1", c="3" or b="2", c="3"} + bar{c="3"}'),
    # nesting: inner binop's combined filters reach the outer operand
    ('(foo{a="1"} + bar{b="2"}) * baz{c="3"}',
     '(foo{a="1", b="2", c="3"} + bar{a="1", b="2", c="3"}) * '
     'baz{a="1", b="2", c="3"}'),
    # __name__ never crosses
    ('{__name__="foo", a="1"} + bar',
     'foo{a="1"} + bar{a="1"}'),
    # scalar-arg aggrs keep the series arg; count_values blocks
    ('topk(3, foo{a="1"}) + bar{b="2"}',
     'topk(3, foo{a="1"}) + bar{b="2"}'),
    ('count_values("v", foo{a="1"}) + bar{b="2"}',
     'count_values("v", foo{a="1"}) + bar{b="2"}'),
]


class TestPushdownTable:
    @pytest.mark.parametrize("q,want", PUSHDOWN_TABLE,
                             ids=[c[0][:50] for c in PUSHDOWN_TABLE])
    def test_optimized_form(self, q, want):
        got = str(optimize(parse(q)))
        assert got == want
        # the optimized form must itself reparse and be a fixed point
        assert str(optimize(parse(got))) == want

    def test_input_ast_never_mutated(self):
        e = parse('foo{a="1"} + bar')
        before = str(e)
        optimize(e)
        assert str(e) == before

    def test_escape_hatch(self, monkeypatch):
        monkeypatch.setenv("VM_MQL_OPTIMIZE", "0")
        assert str(qexec.parse_cached('foo{a="1"} + bar')) == \
            'foo{a="1"} + bar'
        monkeypatch.setenv("VM_MQL_OPTIMIZE", "1")
        assert str(qexec.parse_cached('foo{a="1"} + bar')) == \
            'foo{a="1"} + bar{a="1"}'


STEP = 60_000
SCRAPE = 15_000
NN = 120

CONFORMANCE_QUERIES = [
    'rate(opt_m{dc="east"}[2m]) * rate(opt_m[2m])',
    'sum by (i)(rate(opt_m{dc="east"}[2m])) + sum by (i)(rate(opt_m[2m]))',
    'opt_m{team="a"} > opt_m',
    'opt_m{dc="east"} unless opt_m{team="b"}',
    'opt_m{dc="east"} or opt_m{team="b"}',
    'opt_m{dc="east"} * on (i) opt_m{team="a"}',
    'opt_m{dc="east"} * ignoring (dc, team) opt_m{team="a"}',
    'avg_over_time(opt_m{dc="east"}[2m]) / avg_over_time(opt_m[2m])',
    'opt_m{dc="east"} default opt_m{team="a"}',
    'opt_m{dc="east" or team="b"} + opt_m{i="3"}',
    'opt_m{dc="east"} if opt_m{team="a"}',
    'opt_m{dc="east"} ifnot opt_m{team="b"}',
    '(opt_m{dc="east"} + opt_m{team="a"}) * opt_m{i="2"}',
]


@pytest.fixture()
def store(tmp_path):
    s = Storage(str(tmp_path / "s"))
    now = int(time.time() * 1000)
    t0 = (now - (NN - 1) * SCRAPE) // STEP * STEP
    rng = np.random.default_rng(11)
    rows = []
    for i in range(12):
        vals = np.cumsum(rng.integers(0, 30, NN)).astype(np.float64)
        lab = {"__name__": "opt_m", "i": str(i),
               "dc": "east" if i % 2 else "west",
               "team": "a" if i % 3 else "b"}
        rows.extend(((lab, t0 + j * SCRAPE, float(vals[j]))
                     for j in range(NN)))
    s.add_rows(rows)
    s.force_flush()
    end = t0 + ((NN - 1) * SCRAPE // STEP + 1) * STEP
    yield s, end
    s.close()


def _rows_map(rows):
    return {ts.metric_name.marshal(): ts.values for ts in rows}


class TestPushdownConformance:
    @pytest.mark.parametrize("q", CONFORMANCE_QUERIES,
                             ids=[q[:50] for q in CONFORMANCE_QUERIES])
    def test_optimized_equals_unoptimized_rows(self, store, q,
                                               monkeypatch):
        s, end = store
        kw = dict(start=end - 20 * STEP, end=end, step=STEP, storage=s,
                  disable_cache=True)
        monkeypatch.setenv("VM_MQL_OPTIMIZE", "0")
        plain = _rows_map(exec_query(EvalConfig(**kw), q))
        monkeypatch.setenv("VM_MQL_OPTIMIZE", "1")
        opt = _rows_map(exec_query(EvalConfig(**kw), q))
        assert set(plain) == set(opt), (
            f"{q}: optimizer changed the result series set")
        for k, va in plain.items():
            assert np.array_equal(va, opt[k], equal_nan=True), (
                f"{q}: optimizer changed values for {k!r}")

    def test_pushdown_reduces_samples_scanned(self, store, monkeypatch):
        s, end = store
        q = 'rate(opt_m{dc="east"}[2m]) * rate(opt_m[2m])'
        kw = dict(start=end - 20 * STEP, end=end, step=STEP, storage=s,
                  disable_cache=True)
        monkeypatch.setenv("VM_MQL_OPTIMIZE", "0")
        ec0 = EvalConfig(**kw)
        exec_query(ec0, q)
        monkeypatch.setenv("VM_MQL_OPTIMIZE", "1")
        ec1 = EvalConfig(**kw)
        exec_query(ec1, q)
        assert ec1.samples_scanned < ec0.samples_scanned, (
            "pushdown stopped reducing storage traffic "
            f"({ec1.samples_scanned} vs {ec0.samples_scanned})")
