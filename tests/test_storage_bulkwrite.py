"""PartWriter.write_blocks_bulk must be byte-identical to the per-block
write_block path (same marshal-type choices, zstd gates, headers, index
layout) — the flush hot path swaps implementations, not formats."""

import filecmp
import os

import numpy as np
import pytest

from victoriametrics_tpu import native
from victoriametrics_tpu.storage.block import Block
from victoriametrics_tpu.storage.part import Part, PartWriter
from victoriametrics_tpu.storage.tsid import TSID

T0 = 1_753_700_000_000


def _mk_blocks():
    rng = np.random.default_rng(5)
    out = []
    for i in range(64):
        tsid = TSID(0, 0, 7, 1, 2, 1000 + i)
        n = int(rng.integers(1, 400))
        ts = np.sort(T0 + np.arange(n, dtype=np.int64) * 15000 +
                     rng.integers(-2000, 2001, n))
        kind = i % 5
        if kind == 0:      # const
            vals = np.full(n, 42.0)
        elif kind == 1:    # delta-const (linear)
            vals = np.arange(n, dtype=np.float64) * 5
        elif kind == 2:    # counter
            vals = np.cumsum(rng.integers(0, 50, n)).astype(np.float64)
        elif kind == 3:    # gauge (noisy)
            vals = np.round(rng.uniform(-100, 100, n), 3)
        else:              # counter w/ large values (compressible)
            vals = 1e9 + np.cumsum(rng.integers(0, 3, n)).astype(np.float64)
        out.append(Block.from_floats(tsid, ts, vals))
    return out


@pytest.mark.skipif(not native.available(), reason="needs native codec")
def test_bulk_write_matches_per_block(tmp_path):
    blocks = _mk_blocks()
    wa = PartWriter(str(tmp_path / "a"))
    for b in blocks:
        wa.write_block(b)
    wa.close()
    wb = PartWriter(str(tmp_path / "b"))
    wb.write_blocks_bulk(blocks)
    wb.close()
    for fn in ("timestamps.bin", "values.bin", "index.bin",
               "metaindex.bin"):
        fa = os.path.join(str(tmp_path / "a"), fn)
        fb = os.path.join(str(tmp_path / "b"), fn)
        assert filecmp.cmp(fa, fb, shallow=False), fn


@pytest.mark.skipif(not native.available(), reason="needs native codec")
def test_bulk_write_roundtrip(tmp_path):
    blocks = _mk_blocks()
    w = PartWriter(str(tmp_path / "p"))
    w.write_blocks_bulk(blocks)
    w.close()
    p = Part(str(tmp_path / "p"))
    got = list(p.iter_blocks())
    assert len(got) == len(blocks)
    for a, b in zip(got, blocks):
        np.testing.assert_array_equal(a.timestamps, b.timestamps)
        np.testing.assert_allclose(a.float_values(), b.float_values(),
                                   rtol=1e-12)
