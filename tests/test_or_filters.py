"""Selector-level `or` filters (VERDICT #1 conformance gap): `{a="b" or
c="d"}` parses into a filter-set UNION (metricsql labelFilterss) and
evaluates as the union of the matching series — pinned against the
equivalent expression-level `or` queries (tests/golden_or_corpus.json)
and exercised through parse, storage tsid union, eval, and /series."""

import json
import os

import numpy as np
import pytest

from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.metricsql import parse
from victoriametrics_tpu.query.metricsql.ast import MetricExpr
from victoriametrics_tpu.query.metricsql.parser import ParseError
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage

HERE = os.path.dirname(__file__)
T0 = 1_753_700_000_000
STEP = 60_000


# -- parse ----------------------------------------------------------------

def test_parse_or_filter_sets():
    e = parse('{a="b" or c="d"}')
    assert isinstance(e, MetricExpr)
    assert [(f.label, f.value) for f in e.label_filters] == [("a", "b")]
    assert [[(f.label, f.value) for f in fs] for fs in e.or_sets] == \
        [[("c", "d")]]


def test_parse_name_distributes_over_sets():
    e = parse('foo{a="b", x!="y" or c=~"d"}')
    sets = e.filter_sets()
    assert len(sets) == 2
    assert [(f.label, f.value) for f in sets[0]] == \
        [("__name__", "foo"), ("a", "b"), ("x", "y")]
    assert [(f.label, f.value) for f in sets[1]] == \
        [("__name__", "foo"), ("c", "d")]
    assert sets[1][1].is_regexp


def test_parse_or_roundtrip_str():
    for q in ['foo{a="b" or c="d"}', '{a="b" or c="d", e!="f"}',
              'foo{a="b", b="c" or a="x"}']:
        e = parse(q)
        assert str(parse(str(e))) == str(e), q


def test_parse_or_label_name_still_works():
    e = parse('{or="x"}')
    assert [(f.label, f.value) for f in e.label_filters] == [("or", "x")]
    assert not e.or_sets


def test_parse_trailing_or_is_an_error():
    with pytest.raises(ParseError):
        parse('{a="b" or }')


def test_parse_or_inside_rollup_and_aggr():
    e = parse('sum by (dc)(rate(foo{a="b" or c="d"}[5m]))')
    assert "or" in str(e)


# -- eval (golden conformance corpus) -------------------------------------

@pytest.fixture(scope="module")
def store(tmp_path_factory):
    s = Storage(str(tmp_path_factory.mktemp("orf") / "s"))
    rng = np.random.default_rng(17)
    rows = []
    for i in range(12):
        base = np.arange(40, dtype=np.int64) * 15_000 + T0 - 600_000
        ts = np.sort(base + rng.integers(-2000, 2001, 40))
        vals = np.cumsum(rng.integers(0, 30, 40)).astype(float)
        lab = {"__name__": "orm", "idx": str(i),
               "dc": "east" if i % 2 else "west",
               "team": "a" if i % 3 else "b"}
        rows.extend(zip([lab] * 40, ts.tolist(), vals.tolist()))
    s.add_rows(rows)
    s.force_flush()
    yield s
    s.close()


CASES = json.load(open(os.path.join(HERE, "golden_or_corpus.json")))


@pytest.mark.parametrize("case", CASES, ids=lambda c: c["q"][:60])
def test_or_filters_match_expression_level_or(store, case):
    """Each or-filter selector must evaluate exactly like the equivalent
    expression-level `or` union (the established conformance baseline)."""
    kw = dict(start=T0 - 300_000, end=T0, step=STEP, storage=store)
    got = exec_query(EvalConfig(**kw), case["q"])
    want = exec_query(EvalConfig(**kw), case["equiv"])
    gm = {r.metric_name.marshal(): np.asarray(r.values) for r in got}
    wm = {r.metric_name.marshal(): np.asarray(r.values) for r in want}
    assert set(gm) == set(wm) and len(gm) > 0, case["q"]
    for k in gm:
        np.testing.assert_array_equal(gm[k], wm[k], err_msg=case["q"])


def test_or_filters_series_endpoint(store):
    """/api/v1/series with an or-filter match expands to the set union."""
    from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
    api = PrometheusAPI(store)

    class Req:
        def __init__(self, q):
            self._q = q

        def args(self, k):
            return [self._q] if k == "match[]" else []

        def arg(self, k, default=None):
            return default
    sets = api._matches_to_filters(Req('orm{dc="east" or team="b"}'))
    assert len(sets) == 2
    names = {mn.get_label(b"idx")
             for fs in sets
             for mn in store.search_metric_names(fs, T0 - 900_000, T0)}
    east = {str(i).encode() for i in range(12) if i % 2}
    teamb = {str(i).encode() for i in range(12) if i % 3 == 0}
    assert names == east | teamb


def test_or_filters_fused_and_chunked_paths(store):
    """The host fused-aggregation path takes or-filter unions through the
    same storage-side tsid union; results match the unfused oracle."""
    q = 'sum by (dc)(rate({__name__="orm", team="a" or __name__="orm", ' \
        'team="b"}[3m]))'
    kw = dict(start=T0 - 300_000, end=T0, step=STEP, storage=store)
    got = exec_query(EvalConfig(**kw), q)
    os.environ["VM_HOST_FUSED_AGGR"] = "0"
    try:
        want = exec_query(EvalConfig(**kw), q)
    finally:
        os.environ.pop("VM_HOST_FUSED_AGGR", None)
    gm = {r.metric_name.marshal(): np.asarray(r.values) for r in got}
    wm = {r.metric_name.marshal(): np.asarray(r.values) for r in want}
    assert set(gm) == set(wm) and len(gm) == 2
    for k in gm:
        np.testing.assert_array_equal(gm[k], wm[k])


def test_or_filters_cluster_backend_fails_loudly(store):
    """A storage without filter-union support answers with a clear query
    error, never a silent first-set-only result."""
    from victoriametrics_tpu.query.eval import QueryError

    class NoUnion:
        # duck-typed storage lacking supports_filter_union
        def search_series(self, *a, **k):  # pragma: no cover
            return []
    with pytest.raises(QueryError, match="or"):
        exec_query(EvalConfig(start=T0 - 300_000, end=T0, step=STEP,
                              storage=NoUnion()),
                   'orm{a="b" or c="d"}')


def test_absent_over_time_or_sets_drop_selector_labels(store):
    """absent_over_time over an OR'd selector must not stamp the first
    set's literal labels on the result (reference applies selector labels
    only for single-set selectors)."""
    q = 'absent_over_time({__name__="nope", x="a" or __name__="nope", ' \
        'x="b"}[2m])'
    rows = exec_query(EvalConfig(start=T0 - 300_000, end=T0, step=STEP,
                                 storage=store), q)
    assert len(rows) == 1
    assert rows[0].metric_name.labels == []
    single = exec_query(EvalConfig(start=T0 - 300_000, end=T0, step=STEP,
                                   storage=store),
                        'absent_over_time(nope{x="a"}[2m])')
    assert [(k, v) for k, v in single[0].metric_name.labels] == \
        [(b"x", b"a")]


def test_parse_or_name_only_set_roundtrips():
    """A shared-name union where one set is name-only must render a form
    that re-parses (not a dangling ` or `)."""
    q = '{__name__="foo" or __name__="foo", a="b"}'
    e = parse(q)
    e2 = parse(str(e))
    assert [[(f.label, f.value) for f in fs] for fs in e2.filter_sets()] \
        == [[(f.label, f.value) for f in fs] for fs in e.filter_sets()]


def test_or_filters_chunked_aggr_path(store, monkeypatch):
    """The bounded-memory chunked aggregation path takes or-set unions
    through the same storage-side tsid union (estimate + chunked fetch
    both handle filter sets)."""
    monkeypatch.setenv("VM_CHUNKED_AGGR_MIN_BYTES", "1")
    q = 'sum by (dc)(rate({__name__="orm", team="a" or __name__="orm", ' \
        'team="b"}[3m]))'
    kw = dict(start=T0 - 300_000, end=T0, step=STEP, storage=store)
    got = exec_query(EvalConfig(**kw), q)
    monkeypatch.delenv("VM_CHUNKED_AGGR_MIN_BYTES")
    want = exec_query(EvalConfig(**kw, disable_cache=True), q)
    gm = {r.metric_name.marshal(): np.asarray(r.values) for r in got}
    wm = {r.metric_name.marshal(): np.asarray(r.values) for r in want}
    assert set(gm) == set(wm) and len(gm) == 2
    for k in gm:
        np.testing.assert_allclose(gm[k], wm[k], rtol=1e-12,
                                   equal_nan=True)
