"""Concurrency stress harness for the threaded host plane (the
reference's `-race` CI + synctest role, Makefile test-race): hammer ONE
Storage with concurrent columnar ingest, queries, flushes, merges,
snapshots and deletes under randomized scheduling, with assertion-checked
invariants.

Torn reads are detectable by construction: every written sample satisfies
value == timestamp % 1e9, so any mixed-up (ts, value) pairing, partial
block, or cross-series contamination trips an exact-equality check.
"""

import random
import threading
import time

import numpy as np
import pytest

from victoriametrics_tpu.devtools import locktrace
from victoriametrics_tpu.devtools.locktrace import (LockHeldTooLongWarning,
                                                    LockOrderError,
                                                    TracedLock)

try:
    from victoriametrics_tpu import native
    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.types import EvalConfig
    from victoriametrics_tpu.storage.storage import Storage
    from victoriametrics_tpu.storage.tag_filters import filters_from_dict
    _HAVE_NATIVE = native.available()
except ImportError:  # optional deps (zstandard) missing
    _HAVE_NATIVE = False

needs_native = pytest.mark.skipif(not _HAVE_NATIVE,
                                  reason="needs native lib")

T0 = 1_753_700_000_000
DURATION_S = 8.0
N_WRITERS = 2
SERIES_PER_WRITER = 24


def _val(ts_arr):
    return (ts_arr % 1_000_000_000).astype(np.float64)


class _Stress:
    def __init__(self, storage):
        self.storage = storage
        self.stop = threading.Event()
        self.errors: list[BaseException] = []
        self.appended = [0] * N_WRITERS  # samples per writer (monotonic)
        self.lock = threading.Lock()

    def guard(self, fn):
        def run():
            rng = random.Random(id(fn) & 0xFFFF)
            try:
                while not self.stop.is_set():
                    fn(rng)
                    time.sleep(rng.uniform(0, 0.01))  # chaos scheduling
            except BaseException as e:  # noqa: BLE001 - harness boundary
                self.errors.append(e)
                self.stop.set()
        return run

    # -- workers ---------------------------------------------------------

    def writer(self, w):
        step = [0]
        keys = [f'stress{{w="{w}",i="{i}"}}'.encode()
                for i in range(SERIES_PER_WRITER)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, len(keys))
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])

        def run(rng):
            k = rng.randint(1, 6)  # scrapes per series this batch
            base = T0 + step[0] * 15_000
            step[0] += k
            ts = (base + np.arange(k, dtype=np.int64)[None, :] * 15_000 +
                  w)  # writer-unique phase: series never collide
            ts = np.broadcast_to(ts, (len(keys), k)).reshape(-1).copy()
            cr = native.ColumnarRows(
                keybuf, np.repeat(koffs, k), np.repeat(klens, k),
                ts, _val(ts))
            self.storage.add_rows_columnar(cr)
            with self.lock:
                self.appended[w] += k
        return run

    def reader(self, rng):
        w = rng.randrange(N_WRITERS)
        cols = self.storage.search_columns(
            filters_from_dict({"__name__": "stress", "w": str(w)}),
            T0 - 10**6, T0 + 10**10)
        for s in range(cols.n_series):
            n = int(cols.counts[s])
            ts = cols.ts[s, :n]
            vals = cols.vals[s, :n]
            assert bool((np.diff(ts) > 0).all()), \
                "timestamps not strictly increasing"
            np.testing.assert_array_equal(vals, _val(ts))

    def querier(self, rng):
        rows = exec_query(
            EvalConfig(start=T0, end=T0 + 4_000_000, step=60_000,
                       storage=self.storage, tpu=None,
                       disable_cache=bool(rng.getrandbits(1))),
            'count(last_over_time(stress[10m]))')
        for ts in rows:
            v = ts.values[np.isfinite(ts.values)]
            assert bool((v <= N_WRITERS * SERIES_PER_WRITER).all())

    def flusher(self, rng):
        if rng.random() < 0.3:
            self.storage.force_merge()
        else:
            self.storage.force_flush()

    def snapshotter(self, rng):
        name = self.storage.create_snapshot()
        time.sleep(rng.uniform(0, 0.02))
        assert self.storage.delete_snapshot(name)

    def deleter(self, rng):
        # disposable series: create then delete; must never affect the
        # stress/metric invariants
        self.storage.add_rows(
            [({"__name__": "victim", "i": str(rng.randrange(4))},
              T0 + rng.randrange(10**6), 1.0)])
        self.storage.delete_series(
            filters_from_dict({"__name__": "victim"}))


@needs_native
def test_concurrent_ingest_query_flush_snapshot(tmp_path):
    s = Storage(str(tmp_path / "s"))
    st = _Stress(s)
    workers = [st.guard(st.writer(w)) for w in range(N_WRITERS)]
    workers += [st.guard(st.reader), st.guard(st.querier),
                st.guard(st.flusher), st.guard(st.snapshotter),
                st.guard(st.deleter)]
    threads = [threading.Thread(target=f, daemon=True) for f in workers]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    while time.monotonic() - t0 < DURATION_S and not st.stop.is_set():
        time.sleep(0.1)
    st.stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "stress worker wedged (deadlock?)"
    if st.errors:
        raise st.errors[0]
    # final invariant: exactly the appended samples are durable and
    # correct after a full flush+merge
    s.force_flush()
    s.force_merge()
    for w in range(N_WRITERS):
        cols = s.search_columns(
            filters_from_dict({"__name__": "stress", "w": str(w)}),
            T0 - 10**6, T0 + 10**10)
        assert cols.n_series == SERIES_PER_WRITER
        expected = st.appended[w]
        for i in range(cols.n_series):
            n = int(cols.counts[i])
            assert n == expected, (w, i, n, expected)
            ts = cols.ts[i, :n]
            np.testing.assert_array_equal(cols.vals[i, :n], _val(ts))
    s.close()

# -- runtime lock-order tracing (devtools/locktrace) -------------------------


class TestLockTrace:
    def test_cycle_detected_fails_fast(self):
        """A->B in one thread then B->A in another must raise
        LockOrderError promptly — the whole point is that the synthetic
        deadlock FAILS instead of hanging the suite."""
        g = locktrace.LockGraph()
        a = TracedLock("stress.A", graph=g, mode="raise")
        b = TracedLock("stress.B", graph=g, mode="raise")
        phase1_done = threading.Event()
        errors: list[BaseException] = []

        def t1():
            with a:
                with b:
                    pass
            phase1_done.set()

        def t2():
            assert phase1_done.wait(10)
            try:
                with b:
                    with a:  # reverse order: potential ABBA deadlock
                        pass
            except LockOrderError as e:
                errors.append(e)

        threads = [threading.Thread(target=t1, daemon=True),
                   threading.Thread(target=t2, daemon=True)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
            assert not t.is_alive(), "locktrace test wedged"
        assert time.monotonic() - t0 < 15
        assert len(errors) == 1
        assert "stress.A" in str(errors[0]) and "stress.B" in str(errors[0])

    def test_consistent_order_is_quiet(self):
        g = locktrace.LockGraph()
        a = TracedLock("q.A", graph=g)
        b = TracedLock("q.B", graph=g)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert g.edges() == {"q.A": {"q.B"}}

    def test_rlock_reentry_and_nonreentrant_self_deadlock(self):
        g = locktrace.LockGraph()
        r = TracedLock("q.R", graph=g, reentrant=True)
        with r:
            with r:  # fine: RLock semantics
                assert r.locked()
        plain = TracedLock("q.P", graph=g)
        with plain:
            with pytest.raises(LockOrderError, match="re-acquired"):
                plain.acquire()

    def test_failed_trylock_leaves_no_phantom_edge(self):
        """hold A, try-lock B, fail, retake in the safe B->A order: the
        aborted attempt must not have poisoned the graph."""
        g = locktrace.LockGraph()
        a = TracedLock("t.A", graph=g)
        b = TracedLock("t.B", graph=g)
        acquired, release = threading.Event(), threading.Event()

        def holder():
            with b:
                acquired.set()
                release.wait(10)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert acquired.wait(10)
        with a:
            assert b.acquire(blocking=False) is False  # contended: aborts
        release.set()
        t.join(10)
        assert "t.B" not in g.edges().get("t.A", set())
        with b:
            with a:  # safe order must stay legal
                pass

    def test_cycle_abort_rolls_back_partial_edges(self):
        """When acquiring C while holding A and B raises on the B->C
        cycle, the A->C edge recorded a moment earlier must be rolled
        back too — C->A later is legitimate."""
        g = locktrace.LockGraph()
        a = TracedLock("r.A", graph=g)
        b = TracedLock("r.B", graph=g)
        c = TracedLock("r.C", graph=g)
        with c:
            with b:  # establishes C->B
                pass
        with a:
            with b:
                with pytest.raises(LockOrderError):
                    c.acquire()  # A->C recorded, then B->C finds cycle
        assert "r.C" not in g.edges().get("r.A", set())
        with c:
            with a:  # must stay legal
                pass

    def test_cross_thread_handoff_reacquire(self):
        lk = TracedLock("t.H", graph=locktrace.LockGraph())
        lk.acquire()
        t = threading.Thread(target=lk.release)
        t.start(); t.join()
        lk.acquire()  # stale stack entry must be purged, not fatal
        lk.release()

    def test_held_too_long_warns(self):
        lk = TracedLock("q.slow", graph=locktrace.LockGraph(),
                        max_hold_ms=1.0)
        with pytest.warns(LockHeldTooLongWarning):
            with lk:
                time.sleep(0.02)

    def test_factory_injects_traced_locks(self, monkeypatch):
        monkeypatch.setenv("VMT_LOCKTRACE", "1")
        assert isinstance(locktrace.make_lock("x"), TracedLock)
        assert isinstance(locktrace.make_rlock("x"), TracedLock)
        monkeypatch.setenv("VMT_LOCKTRACE", "0")
        assert isinstance(locktrace.make_lock("x"), type(threading.Lock()))

    @needs_native
    def test_storage_lock_hierarchy_under_tracing(self, tmp_path,
                                                  monkeypatch):
        """The real ingest/flush path runs clean under the tracer: the
        Table -> Partition -> flush-mutex hierarchy is acyclic."""
        monkeypatch.setenv("VMT_LOCKTRACE", "1")
        s = Storage(str(tmp_path / "lt"))
        t0 = 1_753_700_000_000
        s.add_rows([({"__name__": "lt", "i": str(i)}, t0 + i * 1000, 1.0)
                    for i in range(32)])
        s.force_flush()
        s.force_merge()
        assert len(s.search_series(
            filters_from_dict({"__name__": "lt"}), t0 - 1, t0 + 10**6)) == 32
        s.close()
