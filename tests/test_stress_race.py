"""Concurrency stress harness for the threaded host plane (the
reference's `-race` CI + synctest role, Makefile test-race): hammer ONE
Storage with concurrent columnar ingest, queries, flushes, merges,
snapshots and deletes under randomized scheduling, with assertion-checked
invariants.

Torn reads are detectable by construction: every written sample satisfies
value == timestamp % 1e9, so any mixed-up (ts, value) pairing, partial
block, or cross-series contamination trips an exact-equality check.
"""

import queue
import random
import threading
import time
import warnings

import numpy as np
import pytest

from victoriametrics_tpu.devtools import locktrace, racetrace
from victoriametrics_tpu.devtools.locktrace import (LockHeldTooLongWarning,
                                                    LockOrderError,
                                                    TracedLock, make_lock)
from victoriametrics_tpu.devtools.racetrace import RaceWarning, traced_fields
from victoriametrics_tpu.devtools.sched import DeterministicScheduler

pytestmark = pytest.mark.race  # the tools/race.sh selection

try:
    from victoriametrics_tpu import native
    from victoriametrics_tpu.query.exec import exec_query
    from victoriametrics_tpu.query.types import EvalConfig
    from victoriametrics_tpu.storage.storage import Storage
    from victoriametrics_tpu.storage.tag_filters import filters_from_dict
except ImportError:  # optional deps (zstandard) missing
    pass

# canonical native gate (conftest skips the marked tests when the codec
# library is unavailable)
needs_native = pytest.mark.requires_native

T0 = 1_753_700_000_000
DURATION_S = 8.0
N_WRITERS = 2
SERIES_PER_WRITER = 24


def _val(ts_arr):
    return (ts_arr % 1_000_000_000).astype(np.float64)


class _Stress:
    def __init__(self, storage):
        self.storage = storage
        self.stop = threading.Event()
        self.errors: list[BaseException] = []
        self.appended = [0] * N_WRITERS  # samples per writer (monotonic)
        self.lock = threading.Lock()

    def guard(self, fn):
        def run():
            rng = random.Random(id(fn) & 0xFFFF)
            try:
                while not self.stop.is_set():
                    fn(rng)
                    time.sleep(rng.uniform(0, 0.01))  # chaos scheduling
            except BaseException as e:  # noqa: BLE001 - harness boundary
                self.errors.append(e)
                self.stop.set()
        return run

    # -- workers ---------------------------------------------------------

    def writer(self, w):
        step = [0]
        keys = [f'stress{{w="{w}",i="{i}"}}'.encode()
                for i in range(SERIES_PER_WRITER)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, len(keys))
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])

        def run(rng):
            k = rng.randint(1, 6)  # scrapes per series this batch
            base = T0 + step[0] * 15_000
            step[0] += k
            ts = (base + np.arange(k, dtype=np.int64)[None, :] * 15_000 +
                  w)  # writer-unique phase: series never collide
            ts = np.broadcast_to(ts, (len(keys), k)).reshape(-1).copy()
            cr = native.ColumnarRows(
                keybuf, np.repeat(koffs, k), np.repeat(klens, k),
                ts, _val(ts))
            self.storage.add_rows_columnar(cr)
            with self.lock:
                self.appended[w] += k
        return run

    def reader(self, rng):
        w = rng.randrange(N_WRITERS)
        cols = self.storage.search_columns(
            filters_from_dict({"__name__": "stress", "w": str(w)}),
            T0 - 10**6, T0 + 10**10)
        for s in range(cols.n_series):
            n = int(cols.counts[s])
            ts = cols.ts[s, :n]
            vals = cols.vals[s, :n]
            assert bool((np.diff(ts) > 0).all()), \
                "timestamps not strictly increasing"
            np.testing.assert_array_equal(vals, _val(ts))

    def querier(self, rng):
        rows = exec_query(
            EvalConfig(start=T0, end=T0 + 4_000_000, step=60_000,
                       storage=self.storage, tpu=None,
                       disable_cache=bool(rng.getrandbits(1))),
            'count(last_over_time(stress[10m]))')
        for ts in rows:
            v = ts.values[np.isfinite(ts.values)]
            assert bool((v <= N_WRITERS * SERIES_PER_WRITER).all())

    def flusher(self, rng):
        if rng.random() < 0.3:
            self.storage.force_merge()
        else:
            self.storage.force_flush()

    def snapshotter(self, rng):
        name = self.storage.create_snapshot()
        time.sleep(rng.uniform(0, 0.02))
        assert self.storage.delete_snapshot(name)

    def deleter(self, rng):
        # disposable series: create then delete; must never affect the
        # stress/metric invariants
        self.storage.add_rows(
            [({"__name__": "victim", "i": str(rng.randrange(4))},
              T0 + rng.randrange(10**6), 1.0)])
        self.storage.delete_series(
            filters_from_dict({"__name__": "victim"}))


@needs_native
def test_concurrent_ingest_query_flush_snapshot(tmp_path):
    s = Storage(str(tmp_path / "s"))
    st = _Stress(s)
    workers = [st.guard(st.writer(w)) for w in range(N_WRITERS)]
    workers += [st.guard(st.reader), st.guard(st.querier),
                st.guard(st.flusher), st.guard(st.snapshotter),
                st.guard(st.deleter)]
    threads = [threading.Thread(target=f, daemon=True) for f in workers]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    while time.monotonic() - t0 < DURATION_S and not st.stop.is_set():
        time.sleep(0.1)
    st.stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "stress worker wedged (deadlock?)"
    if st.errors:
        raise st.errors[0]
    # final invariant: exactly the appended samples are durable and
    # correct after a full flush+merge
    s.force_flush()
    s.force_merge()
    for w in range(N_WRITERS):
        cols = s.search_columns(
            filters_from_dict({"__name__": "stress", "w": str(w)}),
            T0 - 10**6, T0 + 10**10)
        assert cols.n_series == SERIES_PER_WRITER
        expected = st.appended[w]
        for i in range(cols.n_series):
            n = int(cols.counts[i])
            assert n == expected, (w, i, n, expected)
            ts = cols.ts[i, :n]
            np.testing.assert_array_equal(cols.vals[i, :n], _val(ts))
    s.close()

# -- runtime lock-order tracing (devtools/locktrace) -------------------------


class TestLockTrace:
    def test_cycle_detected_fails_fast(self):
        """A->B in one thread then B->A in another must raise
        LockOrderError promptly — the whole point is that the synthetic
        deadlock FAILS instead of hanging the suite."""
        g = locktrace.LockGraph()
        a = TracedLock("stress.A", graph=g, mode="raise")
        b = TracedLock("stress.B", graph=g, mode="raise")
        phase1_done = threading.Event()
        errors: list[BaseException] = []

        def t1():
            with a:
                with b:
                    pass
            phase1_done.set()

        def t2():
            assert phase1_done.wait(10)
            try:
                with b:
                    with a:  # reverse order: potential ABBA deadlock
                        pass
            except LockOrderError as e:
                errors.append(e)

        threads = [threading.Thread(target=t1, daemon=True),
                   threading.Thread(target=t2, daemon=True)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
            assert not t.is_alive(), "locktrace test wedged"
        assert time.monotonic() - t0 < 15
        assert len(errors) == 1
        assert "stress.A" in str(errors[0]) and "stress.B" in str(errors[0])

    def test_consistent_order_is_quiet(self):
        g = locktrace.LockGraph()
        a = TracedLock("q.A", graph=g)
        b = TracedLock("q.B", graph=g)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert g.edges() == {"q.A": {"q.B"}}

    def test_rlock_reentry_and_nonreentrant_self_deadlock(self):
        g = locktrace.LockGraph()
        r = TracedLock("q.R", graph=g, reentrant=True)
        with r:
            with r:  # fine: RLock semantics
                assert r.locked()
        plain = TracedLock("q.P", graph=g)
        with plain:
            with pytest.raises(LockOrderError, match="re-acquired"):
                plain.acquire()

    def test_failed_trylock_leaves_no_phantom_edge(self):
        """hold A, try-lock B, fail, retake in the safe B->A order: the
        aborted attempt must not have poisoned the graph."""
        g = locktrace.LockGraph()
        a = TracedLock("t.A", graph=g)
        b = TracedLock("t.B", graph=g)
        acquired, release = threading.Event(), threading.Event()

        def holder():
            with b:
                acquired.set()
                release.wait(10)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert acquired.wait(10)
        with a:
            assert b.acquire(blocking=False) is False  # contended: aborts
        release.set()
        t.join(10)
        assert "t.B" not in g.edges().get("t.A", set())
        with b:
            with a:  # safe order must stay legal
                pass

    def test_cycle_abort_rolls_back_partial_edges(self):
        """When acquiring C while holding A and B raises on the B->C
        cycle, the A->C edge recorded a moment earlier must be rolled
        back too — C->A later is legitimate."""
        g = locktrace.LockGraph()
        a = TracedLock("r.A", graph=g)
        b = TracedLock("r.B", graph=g)
        c = TracedLock("r.C", graph=g)
        with c:
            with b:  # establishes C->B
                pass
        with a:
            with b:
                with pytest.raises(LockOrderError):
                    c.acquire()  # A->C recorded, then B->C finds cycle
        assert "r.C" not in g.edges().get("r.A", set())
        with c:
            with a:  # must stay legal
                pass

    def test_cross_thread_handoff_reacquire(self):
        lk = TracedLock("t.H", graph=locktrace.LockGraph())
        lk.acquire()
        t = threading.Thread(target=lk.release)
        t.start(); t.join()
        lk.acquire()  # stale stack entry must be purged, not fatal
        lk.release()

    def test_held_too_long_warns(self):
        lk = TracedLock("q.slow", graph=locktrace.LockGraph(),
                        max_hold_ms=1.0)
        with pytest.warns(LockHeldTooLongWarning):
            with lk:
                time.sleep(0.02)

    def test_factory_injects_traced_locks(self, monkeypatch):
        monkeypatch.setenv("VMT_LOCKTRACE", "1")
        assert isinstance(locktrace.make_lock("x"), TracedLock)
        assert isinstance(locktrace.make_rlock("x"), TracedLock)
        monkeypatch.setenv("VMT_LOCKTRACE", "0")
        if racetrace.enabled():
            # the racetrace sanitizer also claims the factory seam
            assert isinstance(locktrace.make_lock("x"), TracedLock)
        else:
            assert isinstance(locktrace.make_lock("x"),
                              type(threading.Lock()))

    @needs_native
    def test_storage_lock_hierarchy_under_tracing(self, tmp_path,
                                                  monkeypatch):
        """The real ingest/flush path runs clean under the tracer: the
        Table -> Partition -> flush-mutex hierarchy is acyclic."""
        monkeypatch.setenv("VMT_LOCKTRACE", "1")
        s = Storage(str(tmp_path / "lt"))
        t0 = 1_753_700_000_000
        s.add_rows([({"__name__": "lt", "i": str(i)}, t0 + i * 1000, 1.0)
                    for i in range(32)])
        s.force_flush()
        s.force_merge()
        assert len(s.search_series(
            filters_from_dict({"__name__": "lt"}), t0 - 1, t0 + 10**6)) == 32
        s.close()

# -- happens-before race sanitizer (devtools/racetrace) -----------------------


@pytest.fixture
def race_on(monkeypatch):
    """Sanitizer on for the test body; restores prior state after (no-op
    teardown when the whole run came in via tools/race.sh with
    VMT_RACETRACE=1)."""
    monkeypatch.setenv("VMT_LOCKTRACE_MAX_HOLD_MS", "60000")
    was = racetrace.enabled()
    racetrace.enable()
    racetrace.reset()
    yield racetrace
    racetrace.reset()
    if not was:
        racetrace.disable()


@traced_fields("n")
class _Scratch:
    """The seeded-race fixture: one traced int, no lock."""

    def __init__(self):
        self.n = 0
        self.d = {}


class TestRaceTrace:
    def test_seeded_race_is_detected_with_both_stacks(self, race_on):
        """Two unjoined threads bump the same unsynchronized field: a
        happens-before race EXISTS regardless of how the OS interleaves
        them, so detection is deterministic — no lucky timing needed."""
        b = _Scratch()

        def bump():
            for _ in range(4):
                b.n = b.n + 1
                b.d["k"] = b.d.get("k", 0) + 1  # dict update, same story

        ts = [threading.Thread(target=bump) for _ in range(2)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RaceWarning)
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        reps = racetrace.reports()
        assert reps, "unsynchronized cross-thread access not reported"
        r = reps[0]
        assert r.field == "n" and r.cls_name == "_Scratch"
        assert r.kind in ("write-write", "read-write", "write-read")
        first = "".join(str(f) for f in r.first_stack.format())
        second = "".join(str(f) for f in r.second_stack.format())
        assert "test_stress_race" in first and "bump" in first
        assert "test_stress_race" in second and "bump" in second
        assert r.first_thread != r.second_thread

    def test_report_counted_in_registry(self, race_on):
        from victoriametrics_tpu.utils import metrics as metricslib
        c = metricslib.REGISTRY.counter("vm_race_reports_total")
        before = c.get()
        b = _Scratch()
        ts = [threading.Thread(target=lambda: setattr(b, "n", b.n + 1))
              for _ in range(2)]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RaceWarning)
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        assert c.get() > before

    def test_make_lock_synchronized_twin_is_clean(self, race_on):
        b = _Scratch()
        lk = make_lock("race.scratch._lock")
        assert isinstance(lk, TracedLock)  # racetrace reached the seam

        def bump():
            for _ in range(8):
                with lk:
                    b.n = b.n + 1

        ts = [threading.Thread(target=bump) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert racetrace.reports() == []
        assert b.n == 24

    def test_queue_handoff_is_clean(self, race_on):
        b = _Scratch()
        q = queue.Queue()

        def producer():
            b.n = 41
            q.put("ready")

        def consumer():
            q.get()
            b.n = b.n + 1

        t1 = threading.Thread(target=producer)
        t2 = threading.Thread(target=consumer)
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        assert racetrace.reports() == []
        assert b.n == 42

    def test_thread_start_join_create_edges(self, race_on):
        b = _Scratch()
        b.n = 1                       # parent write before fork

        def child():
            b.n += 1                  # ordered after start()

        t = threading.Thread(target=child)
        t.start()
        t.join()
        b.n += 1                      # ordered after join()
        assert racetrace.reports() == []
        assert b.n == 3

    def test_disabled_is_plain_attribute(self, monkeypatch):
        """With the sanitizer off, traced classes carry no descriptor (the
        zero-overhead guarantee bench.py relies on)."""
        if racetrace.enabled():
            pytest.skip("suite running under VMT_RACETRACE=1")
        monkeypatch.setenv("VMT_LOCKTRACE", "0")
        assert not isinstance(_Scratch.__dict__.get("n"),
                              racetrace._TracedField)
        try:
            from victoriametrics_tpu.storage.partition import Partition
        except ImportError:          # zstandard absent: storage not loadable
            Partition = None
        if Partition is not None:
            assert not isinstance(Partition.__dict__.get("_pending"),
                                  racetrace._TracedField)
        assert isinstance(make_lock("x"), type(threading.Lock()))


# -- deterministic interleaving scheduler (devtools/sched) --------------------


class TestDeterministicScheduler:
    def _racy_run(self, seed):
        racetrace.reset()
        sched = DeterministicScheduler(seed=seed, change_prob=0.3)
        b = _Scratch()

        def bump():
            for _ in range(6):
                b.n = b.n + 1

        for i in range(3):
            sched.spawn(f"w{i}", bump)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RaceWarning)
            sched.run(timeout=30)
        reps = racetrace.reports()
        pairs = [(r.field, r.kind, r.first_thread, r.second_thread)
                 for r in reps]
        return sched.trace, pairs

    def test_same_seed_replays_same_interleaving_and_reports(self, race_on):
        """The acceptance property: the seed IS the interleaving.  Two
        runs with one seed produce the identical traced-point schedule and
        the identical race reports; the report's seed is therefore a full
        reproducer."""
        t1, p1 = self._racy_run(1234)
        t2, p2 = self._racy_run(1234)
        assert t1 == t2
        assert p1 == p2
        assert p1, "the seeded racy workload must be flagged"
        assert len(t1) > 10

    def test_locked_workload_is_clean_and_deterministic(self, race_on):
        def run(seed):
            racetrace.reset()
            sched = DeterministicScheduler(seed=seed, change_prob=0.3)
            b = _Scratch()
            lk = make_lock("sched.locked._lock")

            def bump():
                for _ in range(6):
                    with lk:
                        b.n = b.n + 1

            for i in range(3):
                sched.spawn(f"w{i}", bump)
            sched.run(timeout=30)
            return sched.trace, b.n, racetrace.reports()

        t1, n1, r1 = run(77)
        t2, n2, r2 = run(77)
        assert t1 == t2 and n1 == n2 == 18
        assert r1 == [] and r2 == []
        # lock contention descheduled someone at least once
        assert any(x.endswith("/blocked") for x in t1)

    def test_workpool_runs_inline_under_scheduler(self, race_on):
        """A scheduled thread's pool batches execute INLINE (pool workers
        are not turnstile participants), so the interleaving stays a pure
        function of the seed: two runs with one seed produce identical
        traces and identical results."""
        from victoriametrics_tpu.utils.workpool import WorkPool

        pool = WorkPool(workers=4)

        def run(seed):
            racetrace.reset()
            sched = DeterministicScheduler(seed=seed, change_prob=0.3)
            b = _Scratch()
            lk = make_lock("sched.pool._lock")
            logs = {}

            def body(w):
                def job(j):
                    with lk:
                        b.n = b.n + 1
                    return (w, j, threading.current_thread().name)
                got = pool.run([lambda j=j: job(j) for j in range(4)])
                logs[w] = got

            for i in range(3):
                sched.spawn(f"w{i}", body, i)
            sched.run(timeout=60)
            return sched.trace, b.n, dict(logs), racetrace.reports()

        t1, n1, l1, r1 = run(321)
        t2, n2, l2, r2 = run(321)
        assert t1 == t2 and n1 == n2 == 12
        assert l1 == l2
        # inline: every job ran on its submitting (scheduled) thread
        for w, got in l1.items():
            assert [g[:2] for g in got] == [(w, j) for j in range(4)]
            assert all(g[2] == f"w{w}" for g in got)
        assert r1 == [] and r2 == []
        assert pool._threads == []   # the pool never started workers

    @needs_native
    @pytest.mark.parametrize("assemble", ["1", "0"])
    def test_parallel_fetch_stress_racetrace_clean(self, tmp_path, race_on,
                                                   monkeypatch, assemble):
        """The concurrent fetch stress with the WORK POOL engaged: several
        reader threads fan multi-part collection across pool workers while
        a writer appends and a flusher compacts — the sanitizer must stay
        silent and every read must satisfy the value == f(ts) invariant.
        Runs once with the fused native assemble kernel (the per-part
        vm_assemble_part calls race on the _dec memo + budget seams) and
        once on the split Python oracle path."""
        monkeypatch.setenv("VM_SEARCH_WORKERS", "2")
        monkeypatch.setenv("VM_NATIVE_ASSEMBLE", assemble)
        s = Storage(str(tmp_path / "pf"))
        keys = [f'pfetch{{i="{i}"}}'.encode() for i in range(16)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, len(keys))
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])

        def append(step, k):
            ts = (T0 + (step + np.arange(k, dtype=np.int64))[None, :]
                  * 15_000)
            ts = np.broadcast_to(ts, (len(keys), k)).reshape(-1).copy()
            s.add_rows_columnar(native.ColumnarRows(
                keybuf, np.repeat(koffs, k), np.repeat(klens, k),
                ts, _val(ts)))

        # seed several file parts so readers fan >1 unit per query
        for p in range(3):
            append(p * 8, 8)
            s.force_flush()

        stop = threading.Event()
        errors: list[BaseException] = []

        def guard(fn):
            def run():
                try:
                    i = 0
                    while not stop.is_set() and i < 40:
                        fn(i)
                        i += 1
                except BaseException as e:  # noqa: BLE001 — harness edge
                    errors.append(e)
                    stop.set()
            return run

        def reader(_i):
            cols = s.search_columns(
                filters_from_dict({"__name__": "pfetch"}),
                T0 - 10**6, T0 + 10**10)
            for r in range(cols.n_series):
                n = int(cols.counts[r])
                np.testing.assert_array_equal(cols.vals[r, :n],
                                              _val(cols.ts[r, :n]))

        def writer(i):
            append(24 + i, 2)

        def flusher(i):
            if i % 4 == 0:
                s.force_flush()

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", LockHeldTooLongWarning)
            threads = [threading.Thread(target=f, daemon=True)
                       for f in (guard(reader), guard(reader),
                                 guard(writer), guard(flusher))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "parallel fetch stress wedged"
        if errors:
            raise errors[0]
        assert racetrace.reports() == [], "\n\n".join(
            r.format() for r in racetrace.reports())
        s.close()

    @needs_native
    def test_sharded_ingest_query_stress_racetrace_clean(self, tmp_path,
                                                         race_on,
                                                         monkeypatch):
        """The striped WRITE path under the sanitizer: concurrent
        columnar + legacy writers fan registration stripes and pending
        conversions across the pool (VM_INGEST_SHARDS=4) while readers
        fetch and a flusher compacts — zero race reports, and every read
        satisfies the value == f(ts) invariant.  VM_INGEST_SHARDS=1 is
        the bisection escape hatch (tools/race.sh notes)."""
        monkeypatch.setenv("VM_INGEST_SHARDS", "4")
        monkeypatch.setenv("VM_SEARCH_WORKERS", "2")
        s = Storage(str(tmp_path / "si"))
        keys = [f'shing{{i="{i}"}}'.encode() for i in range(16)]
        keybuf = b"".join(keys)
        klens = np.fromiter((len(k) for k in keys), np.int64, len(keys))
        koffs = np.concatenate([[0], np.cumsum(klens)[:-1]])

        stop = threading.Event()
        errors: list[BaseException] = []

        def guard(fn):
            def run():
                try:
                    i = 0
                    while not stop.is_set() and i < 30:
                        fn(i)
                        i += 1
                except BaseException as e:  # noqa: BLE001 — harness edge
                    errors.append(e)
                    stop.set()
            return run

        def col_writer(i):
            k = 4
            ts = (T0 + (i * k + np.arange(k, dtype=np.int64))[None, :]
                  * 15_000)
            ts = np.broadcast_to(ts, (len(keys), k)).reshape(-1).copy()
            s.add_rows_columnar(native.ColumnarRows(
                keybuf, np.repeat(koffs, k), np.repeat(klens, k),
                ts, _val(ts)))

        def leg_writer(i):
            ts = T0 + i * 15_000 + 7_000
            s.add_rows([({"__name__": "shleg", "i": str(j)}, ts,
                         float(ts % 1_000_000_000)) for j in range(8)])

        def reader(_i):
            cols = s.search_columns(
                filters_from_dict({"__name__": "shing"}),
                T0 - 10**6, T0 + 10**10)
            for r in range(cols.n_series):
                n = int(cols.counts[r])
                np.testing.assert_array_equal(cols.vals[r, :n],
                                              _val(cols.ts[r, :n]))

        def flusher(i):
            if i % 5 == 0:
                s.force_flush()

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", LockHeldTooLongWarning)
            threads = [threading.Thread(target=f, daemon=True)
                       for f in (guard(col_writer), guard(col_writer),
                                 guard(leg_writer), guard(reader),
                                 guard(flusher))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "sharded ingest stress wedged"
        if errors:
            raise errors[0]
        assert racetrace.reports() == [], "\n\n".join(
            r.format() for r in racetrace.reports())
        s.close()

    @needs_native
    def test_sharded_ingest_inline_under_scheduler(self, tmp_path,
                                                   race_on, monkeypatch):
        """With the deterministic scheduler driving the threads, the
        sharded write path must execute INLINE (no pool workers) and
        stay clean: same seed == same interleaving."""
        monkeypatch.setenv("VM_INGEST_SHARDS", "4")
        s = Storage(str(tmp_path / "sched"))

        def writer(w):
            for j in range(5):
                s.add_rows([({"__name__": "sw", "w": str(w), "j": str(j)},
                             T0 + j * 1000 + w, float(j))])

        sched = DeterministicScheduler(seed=77, change_prob=0.2)
        sched.spawn("w0", writer, 0)
        sched.spawn("w1", writer, 1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", LockHeldTooLongWarning)
            sched.run(timeout=120)
        assert racetrace.reports() == [], "\n\n".join(
            r.format() for r in racetrace.reports())
        res = s.search_series(filters_from_dict({"__name__": "sw"}),
                              T0 - 10**6, T0 + 10**9)
        assert len(res) == 10
        s.close()

    @needs_native
    def test_partition_and_mergeset_stress_clean_under_scheduler(
            self, tmp_path, race_on):
        """The real LSM paths — partition ingest/flush/merge/read and
        mergeset add/flush/search — run under seeded preemption with the
        sanitizer on and produce ZERO race reports."""
        from victoriametrics_tpu.storage import mergeset
        from victoriametrics_tpu.storage.partition import Partition
        from victoriametrics_tpu.storage.tsid import TSID

        part = Partition(str(tmp_path / "p"), "2025_07")
        mtab = mergeset.Table(str(tmp_path / "m"))
        t0 = 1_753_700_000_000

        def writer(w):
            for i in range(6):
                tsid = TSID(metric_group_id=1, metric_id=w * 100 + i)
                part.add_rows([(tsid, t0 + i * 1000 + w, float(i))])
                mtab.add_items([b"k%02d_%03d" % (w, i)])

        def flusher():
            for _ in range(3):
                part.flush_to_disk()
                mtab.flush_to_disk()

        def reader():
            for _ in range(4):
                _ = part.rows
                list(part.iter_blocks())
                mtab.first_with_prefix(b"k00")
                list(mtab.search_prefix(b"k01"))

        sched = DeterministicScheduler(seed=4242, change_prob=0.2)
        sched.spawn("w0", writer, 0)
        sched.spawn("w1", writer, 1)
        sched.spawn("flush", flusher)
        sched.spawn("read", reader)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", LockHeldTooLongWarning)
            sched.run(timeout=120)
        assert racetrace.reports() == [], "\n\n".join(
            r.format() for r in racetrace.reports())
        part.flush_to_disk()
        assert part.rows == 12
        assert sum(1 for _ in mtab.iter_from(b"")) == 12
        part.close()
        mtab.close()
