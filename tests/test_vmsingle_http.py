"""End-to-end HTTP API tests (apptest/tests analog): every ingest protocol
in, Prometheus API out. Uses an in-process server for speed plus one real
subprocess test."""

import json
import math
import time

import numpy as np
import pytest

from victoriametrics_tpu.ingest import remote_write
from tests.apptest_helpers import Client, VmSingleProc

T0 = 1_753_700_000_000


@pytest.fixture()
def app(tmp_path):
    """In-process vmsingle."""
    from victoriametrics_tpu.apps.vmsingle import build, parse_flags
    args = parse_flags([f"-storageDataPath={tmp_path}/data",
                        "-httpListenAddr=127.0.0.1:0"])
    storage, srv, api = build(args)
    srv.start()
    yield Client(srv.port)
    srv.stop()
    storage.close()


def ingest_remote_write(app, n_series=4, n_samples=20):
    series = []
    for i in range(n_series):
        labels = [("__name__", "rw_metric"), ("idx", str(i))]
        samples = [(T0 + j * 15_000, float(i * 100 + j))
                   for j in range(n_samples)]
        series.append((labels, samples))
    body = remote_write.build_write_request(series)
    code, resp = app.post("/api/v1/write", body,
                          headers={"Content-Encoding": "snappy"})
    assert code == 204, resp


class TestRemoteWrite:
    def test_write_then_query_range(self, app):
        ingest_remote_write(app)
        res = app.query_range("rw_metric", T0 / 1e3, (T0 + 300_000) / 1e3, 15)
        assert res["status"] == "success"
        assert len(res["data"]["result"]) == 4
        s0 = [r for r in res["data"]["result"]
              if r["metric"]["idx"] == "0"][0]
        assert s0["values"][0][1] == "0"
        assert s0["metric"]["__name__"] == "rw_metric"

    def test_zstd_encoding(self, app):
        body = remote_write.build_write_request(
            [([("__name__", "zm")], [(T0, 5.0)])], compress="zstd")
        code, _ = app.post("/api/v1/write", body,
                           headers={"Content-Encoding": "zstd"})
        assert code == 204
        res = app.query("zm", T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "5"

    def test_instant_query_and_rate(self, app):
        ingest_remote_write(app)
        res = app.query("sum(rate(rw_metric[1m]))", (T0 + 290_000) / 1e3)
        v = float(res["data"]["result"][0]["value"][1])
        # each series grows 1 per 15s -> rate 1/15 x 4 series
        assert abs(v - 4 / 15) < 1e-9


class TestOtherProtocols:
    def test_influx_line(self, app):
        line = f"cpu,host=h1 usage_user=42.5,usage_system=7 {T0 * 1_000_000}"
        code, _ = app.post("/write", line.encode())
        assert code == 204
        res = app.query("cpu_usage_user", T0 / 1e3 + 10)
        r = res["data"]["result"][0]
        assert r["metric"] == {"__name__": "cpu_usage_user", "host": "h1"}
        assert r["value"][1] == "42.5"

    def test_jsonl_import_export_roundtrip(self, app):
        line = json.dumps({"metric": {"__name__": "jm", "a": "b"},
                           "values": [1.5, 2.5],
                           "timestamps": [T0, T0 + 60_000]})
        code, _ = app.post("/api/v1/import", line.encode())
        assert code == 204
        code, body = app.get("/api/v1/export", **{"match[]": "jm"})
        assert code == 200
        out = json.loads(body.splitlines()[0])
        assert out["metric"] == {"__name__": "jm", "a": "b"}
        assert out["values"] == [1.5, 2.5]
        assert out["timestamps"] == [T0, T0 + 60_000]

    def test_prometheus_text_import(self, app):
        text = f'pm{{x="1"}} 3.5 {T0}\npm{{x="2"}} 4.5 {T0}\n'
        code, _ = app.post("/api/v1/import/prometheus", text.encode())
        assert code == 204
        res = app.query("sum(pm)", T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "8"

    def test_csv_import(self, app):
        csv = "h1,42.5,1753700000\nh2,7.5,1753700000\n"
        code, _ = app.post("/api/v1/import/csv", csv.encode(),
                           format="1:label:host,2:metric:temp,3:time:unix_s")
        assert code == 204
        res = app.query("temp", T0 / 1e3 + 10)
        assert len(res["data"]["result"]) == 2

    def test_graphite(self, app):
        line = f"foo.bar.baz;dc=east 10.5 {T0 // 1000}"
        code, _ = app.post("/graphite", line.encode())
        assert code == 204
        res = app.query('{__name__="foo.bar.baz"}', T0 / 1e3 + 10)
        assert res["data"]["result"][0]["metric"]["dc"] == "east"

    def test_opentsdb_http(self, app):
        body = json.dumps([{"metric": "ot.m", "timestamp": T0 // 1000,
                            "value": 9.5, "tags": {"t": "x"}}])
        code, _ = app.post("/api/put", body.encode())
        assert code == 204
        res = app.query('{__name__="ot.m"}', T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "9.5"

    def test_datadog_v1(self, app):
        body = json.dumps({"series": [{
            "metric": "dd.metric", "points": [[T0 // 1000, 3.25]],
            "host": "h9", "tags": ["env:prod"]}]})
        code, _ = app.post("/datadog/api/v1/series", body.encode())
        assert code == 202
        res = app.query("dd_metric", T0 / 1e3 + 10)
        m = res["data"]["result"][0]["metric"]
        assert m["host"] == "h9" and m["env"] == "prod"

    def test_datadog_v2(self, app):
        body = json.dumps({"series": [{
            "metric": "dd2.m", "points": [{"timestamp": T0 // 1000,
                                           "value": 1.5}],
            "resources": [{"type": "host", "name": "h3"}]}]})
        code, _ = app.post("/datadog/api/v2/series", body.encode())
        assert code == 202
        res = app.query("dd2_m", T0 / 1e3 + 10)
        assert res["data"]["result"][0]["metric"]["host"] == "h3"

    def test_newrelic(self, app):
        body = json.dumps([{"Events": [{
            "eventType": "SystemSample", "timestamp": T0 // 1000,
            "cpuPercent": 12.5, "hostname": "nr1"}]}])
        code, _ = app.post("/newrelic/infra/v2/metrics/events/bulk",
                           body.encode())
        assert code == 202
        res = app.query("system_sample_cpu_percent", T0 / 1e3 + 10)
        assert res["data"]["result"][0]["metric"]["hostname"] == "nr1"


class TestMetadataAPIs:
    def test_series_labels_values(self, app):
        ingest_remote_write(app)
        code, body = app.get("/api/v1/series", **{
            "match[]": "rw_metric", "start": T0 / 1e3,
            "end": (T0 + 600_000) / 1e3})
        data = json.loads(body)["data"]
        assert len(data) == 4
        code, body = app.get("/api/v1/labels", start=T0 / 1e3,
                             end=(T0 + 600_000) / 1e3)
        assert "idx" in json.loads(body)["data"]
        code, body = app.get("/api/v1/label/idx/values", start=T0 / 1e3,
                             end=(T0 + 600_000) / 1e3)
        assert json.loads(body)["data"] == ["0", "1", "2", "3"]

    def test_status_tsdb(self, app):
        ingest_remote_write(app)
        code, body = app.get("/api/v1/status/tsdb")
        data = json.loads(body)["data"]
        assert data["totalSeries"] == 4
        assert data["labelValueCountByLabelName"]

    def test_status_tsdb_drilldown(self, app):
        ingest_remote_write(app)
        app.post("/api/v1/import/prometheus", b'other{idx="9"} 1\n')
        code, body = app.get("/api/v1/status/tsdb",
                             **{"match[]": "rw_metric",
                                "focusLabel": "idx"})
        data = json.loads(body)["data"]
        assert data["totalSeries"] == 4  # `other` filtered out
        focus = {e["name"]: e["count"]
                 for e in data["seriesCountByFocusLabelValue"]}
        assert focus == {"0": 1, "1": 1, "2": 1, "3": 1}

    def test_relabel_debug(self, app):
        cfg = ("- action: drop\n  source_labels: [idx]\n  regex: '1'\n"
               "- action: replace\n  target_label: dc\n"
               "  replacement: eu1\n")
        code, body = app.get("/metric-relabel-debug",
                             metric='m{idx="0"}', relabel_configs=cfg)
        assert code == 200
        d = json.loads(body)
        assert d["resultingLabels"]["dc"] == "eu1"
        assert len(d["steps"]) == 2 and not d["dropped"]
        code, body = app.get("/metric-relabel-debug",
                             metric='m{idx="1"}', relabel_configs=cfg)
        d = json.loads(body)
        assert d["dropped"] and d["steps"][0]["out"] is None

    def test_prettify_and_parse_query(self, app):
        code, body = app.get("/prettify-query",
                             query="sum(rate(m[5m]))by(job)")
        d = json.loads(body)
        assert d["status"] == "success" and "by (job)" in d["query"] \
            or "by(job)" in d["query"].replace(" ", "")
        code, body = app.get("/api/v1/parse-query",
                             query="sum(rate(m[5m]))")
        d = json.loads(body)
        assert d["status"] == "success"
        assert d["ast"]["kind"] == "AggrFuncExpr"
        kinds = []

        def walk(n):
            kinds.append(n["kind"])
            for c in n.get("children", []):
                walk(c)
        walk(d["ast"])
        assert "RollupExpr" in kinds or "FuncExpr" in kinds
        code, body = app.get("/prettify-query", query="sum((")
        assert json.loads(body)["status"] == "error"

    def test_delete_series(self, app):
        ingest_remote_write(app)
        code, _ = app.post("/api/v1/admin/tsdb/delete_series", b"",
                           **{"match[]": 'rw_metric{idx="0"}'})
        assert code == 204
        res = app.query_range("rw_metric", T0 / 1e3, (T0 + 300_000) / 1e3, 15)
        assert len(res["data"]["result"]) == 3

    def test_federate(self, app):
        now = time.time()
        text = f'fm{{x="1"}} 3.5 {int(now * 1000)}\n'
        app.post("/api/v1/import/prometheus", text.encode())
        code, body = app.get("/federate", **{"match[]": "fm"})
        assert code == 200
        assert b'fm{x="1"} 3.5' in body

    def test_top_and_active_queries(self, app):
        ingest_remote_write(app)
        app.query("rw_metric", T0 / 1e3)
        code, body = app.get("/api/v1/status/top_queries")
        data = json.loads(body)
        assert any(e["query"] == "rw_metric" for e in data["topByCount"])
        code, body = app.get("/api/v1/status/active_queries")
        assert code == 200

    def test_metrics_page(self, app):
        ingest_remote_write(app)
        code, body = app.get("/metrics")
        assert code == 200
        assert b"vm_rows_inserted_total" in body

    def test_snapshots(self, app):
        ingest_remote_write(app)
        app.force_flush()
        code, body = app.get("/snapshot/create")
        name = json.loads(body)["snapshot"]
        code, body = app.get("/snapshot/list")
        assert name in json.loads(body)["snapshots"]
        code, _ = app.get("/snapshot/delete", snapshot=name)
        assert code == 200

    def test_errors(self, app):
        code, body = app.get("/api/v1/query")
        assert code == 422
        code, body = app.get("/api/v1/query_range", query="rate(",
                             start="0", end="1", step="15")
        assert code == 422
        assert json.loads(body)["status"] == "error"
        code, _ = app.get("/nope/nope")
        assert code == 404


class TestSubprocess:
    def test_real_process_lifecycle(self, tmp_path):
        """Spawn the actual vmsingle process, ingest, query, restart, verify
        persistence (the apptest way)."""
        app = VmSingleProc(str(tmp_path / "data"))
        c = Client(app.port)
        line = json.dumps({"metric": {"__name__": "persisted"},
                           "values": [7.0], "timestamps": [T0]})
        code, _ = c.post("/api/v1/import", line.encode())
        assert code == 204
        c.force_flush()
        res = c.query("persisted", T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "7"
        app.stop()
        # restart on same data dir
        app2 = VmSingleProc(str(tmp_path / "data"))
        c2 = Client(app2.port)
        res = c2.query("persisted", T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "7"
        app2.stop()


class TestTracingAndCache:
    def test_trace_embedded(self, app):
        ingest_remote_write(app)
        code, body = app.get("/api/v1/query_range", query="sum(rate(rw_metric[1m]))",
                             start=T0 / 1e3, end=(T0 + 300_000) / 1e3,
                             step=15, trace="1")
        d = json.loads(body)
        assert "trace" in d
        msgs = json.dumps(d["trace"])
        assert "fetch" in msgs and "rollup" in msgs
        assert d["trace"]["duration_msec"] >= 0

    def test_rollup_cache_hit_and_backfill_reset(self, app):
        from victoriametrics_tpu.query.rollup_result_cache import GLOBAL
        GLOBAL.reset()
        ingest_remote_write(app)
        q = dict(query="rw_metric", start=T0 / 1e3,
                 end=(T0 + 300_000) / 1e3, step=15)
        r1 = app.get("/api/v1/query_range", **q)[1]
        h0 = GLOBAL.hits
        r2 = app.get("/api/v1/query_range", **q)[1]
        assert GLOBAL.hits > h0          # second run hits the cache
        assert json.loads(r1)["data"] == json.loads(r2)["data"]
        # backfill (old timestamps) resets the cache
        line = json.dumps({"metric": {"__name__": "rw_metric", "idx": "0"},
                           "values": [1.0], "timestamps": [T0 - 86_400_000]})
        app.post("/api/v1/import", line.encode())
        assert GLOBAL.stats()["entries"] == 0


class TestIngestServersAndGate:
    def test_tcp_udp_line_protocols(self, tmp_path):
        import socket

        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        args = parse_flags([f"-storageDataPath={tmp_path}/d",
                            "-httpListenAddr=127.0.0.1:0",
                            "-graphiteListenAddr=127.0.0.1:0",
                            "-opentsdbListenAddr=127.0.0.1:0"])
        storage, srv, api = build(args)
        srv.start()
        try:
            c = Client(srv.port)
            gport = api.ingest_servers[0].port
            oport = api.ingest_servers[1].port
            # graphite over TCP
            s = socket.create_connection(("127.0.0.1", gport), timeout=5)
            s.sendall(f"tcp.metric;src=tcp 5.5 {T0 // 1000}\n".encode())
            s.close()
            # graphite over UDP
            u = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            u.sendto(f"udp.metric 6.5 {T0 // 1000}\n".encode(),
                     ("127.0.0.1", gport))
            u.close()
            # opentsdb telnet over TCP
            s = socket.create_connection(("127.0.0.1", oport), timeout=5)
            s.sendall(f"put ot.tcp {T0 // 1000} 7.5 k=v\n".encode())
            s.close()
            deadline = time.time() + 10
            got = {}
            while time.time() < deadline and len(got) < 3:
                for name in ("tcp.metric", "udp.metric", "ot.tcp"):
                    res = c.query(f'{{__name__="{name}"}}', T0 / 1e3 + 10)
                    if res["data"]["result"]:
                        got[name] = res["data"]["result"][0]["value"][1]
                time.sleep(0.2)
            assert got == {"tcp.metric": "5.5", "udp.metric": "6.5",
                           "ot.tcp": "7.5"}
        finally:
            srv.stop()
            for isrv in api.ingest_servers:
                isrv.stop()
            storage.close()

    def test_concurrency_gate_rejects_with_429(self, tmp_path):
        """A saturated 1-slot gate must reject HTTP queries with 429 +
        Retry-After through the real endpoint."""
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        from victoriametrics_tpu.httpapi.prometheus_api import ConcurrencyGate
        args = parse_flags([f"-storageDataPath={tmp_path}/d",
                            "-httpListenAddr=127.0.0.1:0"])
        storage, srv, api = build(args)
        api.gate = ConcurrencyGate(max_concurrent=1, max_queue_duration_s=0.2)
        srv.start()
        try:
            c = Client(srv.port)
            with api.gate:  # hold the only slot
                code, body = c.get("/api/v1/query", query="up")
                assert code == 429, body
                assert json.loads(body)["errorType"] == "too_many_requests"
            code, _ = c.get("/api/v1/query", query="up")
            assert code == 200  # slot released
            assert api.gate.rejected == 1
        finally:
            srv.stop()
            storage.close()

    def test_relative_time_param(self, app):
        import time as _t
        now = _t.time()
        line = f"rel_metric 9.5 {int((now - 60) * 1000)}\n"
        app.post("/api/v1/import/prometheus", line.encode())
        code, body = app.get("/api/v1/query", query="rel_metric")
        assert code == 200
        code, body = app.get("/api/v1/query_range", query="rel_metric",
                             start="-5m", end=str(now), step="15")
        assert code == 200
        assert json.loads(body)["data"]["result"]


class TestOTLP:
    def _build_payload(self):
        """Hand-build an ExportMetricsServiceRequest with a gauge, a
        cumulative sum and a histogram using the protowire writer."""
        import struct

        from victoriametrics_tpu.ingest.protowire import (w_bytes, w_tag,
                                                          w_varint)

        def kv(key, val):
            b = bytearray()
            w_bytes(b, 1, key.encode())
            av = bytearray()
            w_bytes(av, 1, val.encode())
            w_bytes(b, 2, bytes(av))
            return bytes(b)

        def fixed64(buf, fnum, u):
            w_tag(buf, fnum, 1)
            buf += struct.pack("<Q", u)

        def num_dp(ts_ns, val, attrs=()):
            dp = bytearray()
            fixed64(dp, 3, ts_ns)
            w_tag(dp, 4, 1)
            dp += struct.pack("<d", val)
            for k, v in attrs:
                w_bytes(dp, 7, kv(k, v))
            return bytes(dp)

        def metric_gauge(name, dp):
            m = bytearray()
            w_bytes(m, 1, name.encode())
            g = bytearray()
            w_bytes(g, 1, dp)
            w_bytes(m, 5, bytes(g))
            return bytes(m)

        def metric_hist(name, ts_ns):
            dp = bytearray()
            fixed64(dp, 3, ts_ns)
            fixed64(dp, 4, 10)               # count
            w_tag(dp, 5, 1)
            dp += struct.pack("<d", 55.5)    # sum
            w_bytes(dp, 6, struct.pack("<QQQ", 6, 3, 1))   # bucket counts
            w_bytes(dp, 7, struct.pack("<dd", 0.1, 1.0))   # bounds
            m = bytearray()
            w_bytes(m, 1, name.encode())
            h = bytearray()
            w_bytes(h, 1, bytes(dp))
            w_bytes(m, 9, bytes(h))
            return bytes(m)

        ts_ns = T0 * 1_000_000
        sm = bytearray()
        w_bytes(sm, 2, metric_gauge("otlp.gauge",
                                    num_dp(ts_ns, 3.5, [("env", "dev")])))
        w_bytes(sm, 2, metric_hist("otlp.latency", ts_ns))
        resource = bytearray()
        w_bytes(resource, 1, kv("service.name", "svc1"))
        rm = bytearray()
        w_bytes(rm, 1, bytes(resource))
        w_bytes(rm, 2, bytes(sm))
        req = bytearray()
        w_bytes(req, 1, bytes(rm))
        return bytes(req)

    def test_otlp_ingest(self, app):
        code, body = app.post("/opentelemetry/v1/metrics",
                              self._build_payload())
        assert code == 200, body
        res = app.query('{__name__="otlp.gauge"}', T0 / 1e3 + 10)
        r = res["data"]["result"][0]
        assert r["value"][1] == "3.5"
        assert r["metric"]["env"] == "dev"
        assert r["metric"]["service.name"] == "svc1"
        # histogram expansion works with histogram_quantile
        res = app.query('{__name__="otlp.latency_bucket", le="0.1"}', T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "6"
        res = app.query('{__name__="otlp.latency_count"}', T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "10"
        res = app.query(
            'histogram_quantile(0.5, {__name__="otlp.latency_bucket"})', T0 / 1e3 + 10)
        v = float(res["data"]["result"][0]["value"][1])
        assert 0 < v <= 0.1

    def test_otlp_garbage(self, app):
        code, _ = app.post("/v1/metrics", b"\x01\x02 not a protobuf")
        assert code == 400


class TestSeriesLimitsAndPush:
    def test_series_limits_drop(self, tmp_path):
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        args = parse_flags([f"-storageDataPath={tmp_path}/d",
                            "-httpListenAddr=127.0.0.1:0",
                            "-maxLabelsPerTimeseries=3"])
        storage, srv, api = build(args)
        srv.start()
        try:
            c = Client(srv.port)
            ok = f'fits{{a="1"}} 1 {T0}\n'
            bad = f'toomany{{a="1",b="2",c="3",d="4"}} 1 {T0}\n'
            code, _ = c.post("/api/v1/import/prometheus", (ok + bad).encode())
            assert code == 204
            assert c.query("fits", T0 / 1e3 + 5)["data"]["result"]
            assert not c.query("toomany", T0 / 1e3 + 5)["data"]["result"]
            code, body = c.get("/metrics")
            assert b'vm_rows_ignored_total{reason="too_many_labels"} 1' in body
        finally:
            srv.stop()
            storage.close()

    def test_pushmetrics(self, tmp_path):
        from victoriametrics_tpu.httpapi.server import HTTPServer, Response
        from victoriametrics_tpu.utils.pushmetrics import MetricsPusher
        got = []
        sink = HTTPServer("127.0.0.1", 0)
        sink.route("/push", lambda req: (got.append(req.body),
                                         Response.text("OK"))[1])
        sink.start()
        p = MetricsPusher([f"http://127.0.0.1:{sink.port}/push"],
                          lambda: "m1 42\nm2{x=\"y\"} 7\n",
                          interval_s=0.2, extra_labels='job="t"')
        p.start()
        deadline = time.time() + 10
        while not got and time.time() < deadline:
            time.sleep(0.1)
        p.stop()
        sink.stop()
        assert got
        assert b'm1{job="t"} 42' in got[0]
        assert b'm2{job="t",x="y"} 7' in got[0]


class TestMultitenantHTTP:
    """Cluster-style /insert|/select/<accountID[:projectID]>/ routing."""

    def test_insert_select_tenant_paths(self, app):
        line = f"mt_metric{{t=\"a\"}} 41 {T0}\n"
        code, _ = app.post("/insert/7:3/prometheus/api/v1/import/prometheus",
                           line.encode())
        assert code == 204
        code, _ = app.post("/insert/8/prometheus/api/v1/import/prometheus",
                           f"mt_metric{{t=\"a\"}} 42 {T0}\n".encode())
        assert code == 204
        # tenant 7:3 sees only its own value
        code, body = app.get("/select/7:3/prometheus/api/v1/query",
                             query="mt_metric", time=str(T0 // 1000))
        assert code == 200, body
        res = json.loads(body)["data"]["result"]
        assert len(res) == 1 and res[0]["value"][1] == "41"
        # tenant 8 (project 0) sees its own
        code, body = app.get("/select/8/prometheus/api/v1/query",
                             query="mt_metric", time=str(T0 // 1000))
        assert json.loads(body)["data"]["result"][0]["value"][1] == "42"
        # default tenant sees nothing
        code, body = app.get("/api/v1/query",
                             query="mt_metric", time=str(T0 // 1000))
        assert json.loads(body)["data"]["result"] == []
        # tenants listing
        code, body = app.get("/admin/tenants")
        assert code == 200 and set(json.loads(body)["data"]) >= {"7:3", "8:0"}

    def test_bad_tenant_rejected(self, app):
        code, _ = app.post("/insert/xx/prometheus/api/v1/import/prometheus",
                           b"m 1\n")
        assert code == 400
        code, _ = app.get("/select/1:2")
        assert code == 400

    def test_rollup_cache_is_tenant_scoped(self, app):
        # regression: query_range results must never be served across
        # tenants from the rollup result cache
        for tenant, v in (("7", "111"), ("8", "222")):
            code, _ = app.post(
                f"/insert/{tenant}/prometheus/api/v1/import/prometheus",
                f"leak{{x=\"y\"}} {v} {T0}\n".encode())
            assert code == 204
        out = {}
        for tenant in ("7", "8"):
            code, body = app.get(
                f"/select/{tenant}/prometheus/api/v1/query_range",
                query="leak", start=str(T0 // 1000),
                end=str(T0 // 1000 + 60), step="30")
            res = json.loads(body)["data"]["result"]
            out[tenant] = res[0]["values"][0][1]
        assert out == {"7": "111", "8": "222"}
        # default tenant: nothing, even after both cached
        code, body = app.get("/api/v1/query_range", query="leak",
                             start=str(T0 // 1000),
                             end=str(T0 // 1000 + 60), step="30")
        assert json.loads(body)["data"]["result"] == []


class TestVMUI:
    def test_vmui_served(self, app):
        code, body = app.get("/vmui")
        assert code == 200
        text = body.decode()
        assert "<title>vmui" in text
        # the explorer drives these APIs; they must exist
        for ep in ("/api/v1/status/tsdb", "/api/v1/status/top_queries"):
            code, body = app.get(ep)
            assert code == 200, ep


class TestNativeExport:
    def test_roundtrip(self, app, tmp_path):
        ingest_remote_write(app, n_series=3, n_samples=10)
        code, body = app.get("/api/v1/export/native",
                             **{"match[]": "rw_metric"})
        assert code == 200 and body.startswith(b"vmtpu-native-v1\n")
        # import into a second instance
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        args = parse_flags([f"-storageDataPath={tmp_path}/native2",
                            "-httpListenAddr=127.0.0.1:0"])
        storage2, srv2, _ = build(args)
        srv2.start()
        try:
            c2 = Client(srv2.port)
            code, _ = c2.post("/api/v1/import/native", body)
            assert code == 204
            r = c2.query_range("rw_metric", T0 / 1e3,
                               (T0 + 300_000) / 1e3, 15)
            assert len(r["data"]["result"]) == 3
            vals = r["data"]["result"][0]["values"]
            # 10 raw samples land on the grid with lookback fill
            assert {v for _, v in vals} == {str(i) for i in range(10)}
        finally:
            srv2.stop()
            storage2.close()

    def test_bad_header(self, app):
        code, _ = app.post("/api/v1/import/native", b"garbage")
        assert code == 400


class TestMetadataAndZabbix:
    def test_zabbix_connector_history(self, app):
        line = json.dumps({
            "host": {"host": "zhost", "name": "Zabbix Host"},
            "name": "system.cpu.load", "value": 1.25,
            "clock": T0 // 1000, "ns": 500000,
            "item_tags": [{"tag": "component", "value": "cpu"}]})
        code, _ = app.post("/zabbixconnector/api/v1/history", line.encode())
        assert code == 204
        r = app.query('{host="zhost"}', T0 / 1e3)
        res = r["data"]["result"][0]
        assert res["metric"]["__name__"] == "system.cpu.load"
        assert res["metric"]["tag_component"] == "cpu"
        assert res["value"][1] == "1.25"

    def test_type_help_metadata(self, app):
        body = (b"# HELP my_counter Counts the things.\n"
                b"# TYPE my_counter counter\n"
                b"my_counter 5\n")
        code, _ = app.post("/api/v1/import/prometheus", body)
        assert code == 204
        code, body = app.get("/api/v1/metadata")
        d = json.loads(body)["data"]
        assert d["my_counter"] == [{"type": "counter",
                                    "help": "Counts the things.",
                                    "unit": ""}]
        code, body = app.get("/api/v1/metadata", metric="my_counter")
        assert list(json.loads(body)["data"]) == ["my_counter"]

    def test_metric_names_stats(self, app):
        ingest_remote_write(app, n_series=2, n_samples=3)
        app.query("rw_metric", T0 / 1e3)
        code, body = app.get("/api/v1/status/metric_names_stats")
        recs = json.loads(body)["records"]
        # storage-authoritative stats count one hit per distinct name per
        # query (reference lib/storage/metricnamestats semantics)
        assert any(r["metricName"] == "rw_metric" and r["requestsCount"] >= 1
                   for r in recs)


class TestOpsEndpoints:
    def test_flags_page(self, app):
        code, body = app.get("/flags")
        assert code == 200 and b"storageDataPath=" in body

    def test_pprof_threads(self, app):
        code, body = app.get("/debug/pprof/goroutine")
        assert code == 200 and b"Thread" in body

    def test_tenant_metrics(self, app):
        app.post("/insert/3:4/prometheus/api/v1/import/prometheus",
                 f"tm_m 1 {T0}\n".encode())
        code, body = app.get("/metrics")
        assert b'vm_tenant_inserted_rows_total{accountID="3",projectID="4"} 1' \
            in body

    def test_tls_server(self, tmp_path):
        import ssl, subprocess, urllib.request
        cert = tmp_path / "cert.pem"
        key = tmp_path / "key.pem"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout",
             str(key), "-out", str(cert), "-days", "1", "-nodes", "-subj",
             "/CN=localhost"], check=True, capture_output=True)
        from victoriametrics_tpu.apps.vmsingle import build, parse_flags
        args = parse_flags([f"-storageDataPath={tmp_path}/tls", "-tls",
                            f"-tlsCertFile={cert}", f"-tlsKeyFile={key}",
                            "-httpListenAddr=127.0.0.1:0"])
        storage, srv, api = build(args)
        srv.start()
        try:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            with urllib.request.urlopen(
                    f"https://127.0.0.1:{srv.port}/health",
                    context=ctx, timeout=10) as r:
                assert r.read() == b"OK"
        finally:
            srv.stop()
            storage.close()
