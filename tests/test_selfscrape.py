"""Self-scrape plane tests: interval parsing, row collection, the
single-node e2e loop (a subprocess vmsingle whose own metrics become
queryable TSDB series within one interval), and the cluster write path
(a SelfScraper sinking into ClusterStorage shards across real RPC
nodes)."""

import json
import time
import urllib.request

import pytest

from tests.apptest_helpers import Client, VmSingleProc, free_ports
from victoriametrics_tpu.parallel.cluster_api import (ClusterStorage,
                                                      make_storage_handlers)
from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT, HELLO_SELECT,
                                              RPCServer)
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.utils import selfscrape
from victoriametrics_tpu.utils.selfscrape import (SelfScraper,
                                                  configured_interval,
                                                  parse_interval)


class TestParseInterval:
    def test_off_spellings(self):
        for raw in (None, "", "0", "0s", "false", "no"):
            assert parse_interval(raw) == 0.0

    def test_bare_one_means_default(self):
        assert parse_interval("1") == selfscrape.DEFAULT_INTERVAL_S

    def test_durations_and_seconds(self):
        assert parse_interval("15s") == 15.0
        assert parse_interval("500ms") == 0.5
        assert parse_interval("2.5") == 2.5
        assert parse_interval("1m") == 60.0

    def test_garbage_disables(self):
        assert parse_interval("often") == 0.0

    def test_env_wins_over_flag(self, monkeypatch):
        monkeypatch.setenv("VM_SELF_SCRAPE_INTERVAL", "3s")
        assert configured_interval("30s") == 3.0
        monkeypatch.delenv("VM_SELF_SCRAPE_INTERVAL")
        assert configured_interval("30s") == 30.0


class TestCollectRows:
    def test_rows_are_labeled_ingest_shape(self):
        rows = SelfScraper(lambda rows, tenant: None, job="j",
                           instance="i").collect_rows(ts_ms=1234)
        assert rows, "registry snapshot produced no rows"
        names = set()
        for labels, ts, val in rows:
            assert ts == 1234
            assert labels["job"] == "j" and labels["instance"] == "i"
            assert labels["__name__"]
            assert val == val          # no NaN leaks into storage
            names.add(labels["__name__"])
        # process-level and vm-level families both present
        assert "vm_app_uptime_seconds" in names
        assert any(n.startswith("process_") for n in names)

    def test_extra_metrics_are_included(self):
        s = SelfScraper(lambda rows, tenant: None,
                        extra=lambda: {"vm_extra_metric": 7.0})
        rows = s.collect_rows(ts_ms=1)
        vals = {labels["__name__"]: v for labels, _, v in rows}
        assert vals.get("vm_extra_metric") == 7.0

    def test_sink_failure_counts_not_raises(self):
        def sink(rows, tenant):
            raise OSError("down")
        s = SelfScraper(sink)
        before = selfscrape._ERRORS.get()
        assert s.scrape_once() == 0
        assert selfscrape._ERRORS.get() == before + 1

    def test_persistent_handshake_failure_disables_sink(self):
        # a wrong-plane spec (insert hello at a select port) fails the
        # handshake deterministically: after 3 consecutive failures the
        # scraper must stop dialing (each retry can mark healthy nodes
        # down in the cluster router), not hammer forever
        calls = []

        def sink(rows, tenant):
            calls.append(1)
            raise ConnectionError("handshake failed: b'bad hello'")
        s = SelfScraper(sink)
        for _ in range(5):
            s.scrape_once()
        assert s._sink_disabled
        assert len(calls) == 3

    def test_transient_failures_keep_retrying(self):
        # non-handshake errors (storage restarting) never trip the
        # disable latch, and a success resets the streak
        flaky = {"n": 0}

        def sink(rows, tenant):
            flaky["n"] += 1
            if flaky["n"] < 5:
                raise OSError("connection refused")
        s = SelfScraper(sink)
        for _ in range(6):
            s.scrape_once()
        assert not s._sink_disabled
        assert s._sink_fails == 0  # reset by the success
        assert flaky["n"] == 6


def test_scrape_into_local_storage_queryable(tmp_path):
    """Storage.add_rows sink: one scrape, the registry is real series."""
    s = Storage(str(tmp_path / "data"))
    try:
        scraper = SelfScraper(s.add_rows, job="victoria-metrics",
                              instance="test:1")
        n = scraper.scrape_once()
        assert n > 50
        s.force_flush()
        from victoriametrics_tpu.storage.tag_filters import \
            filters_from_dict
        now_ms = int(time.time() * 1e3)
        res = s.search_series(filters_from_dict(
            {"__name__": "vm_app_uptime_seconds"}),
            now_ms - 60_000, now_ms + 60_000)
        assert res, "scraped series not found in storage"
        mn = res[0].metric_name
        assert mn.get_label(b"job") == b"victoria-metrics"
        assert mn.get_label(b"instance") == b"test:1"
    finally:
        s.close()


def test_cluster_sink_shards_across_nodes(tmp_path):
    """ClusterStorage.add_rows sink: the self-scraped registry shards
    across both nodes like any ingested data (no special-casing)."""
    storages = [Storage(str(tmp_path / f"n{i}")) for i in range(2)]
    servers = []
    try:
        specs = []
        for st in storages:
            h = make_storage_handlers(st)
            ins = RPCServer("127.0.0.1", 0, HELLO_INSERT, h)
            sel = RPCServer("127.0.0.1", 0, HELLO_SELECT, h)
            ins.start()
            sel.start()
            servers += [ins, sel]
            specs.append((ins.port, sel.port))
        from victoriametrics_tpu.parallel.cluster_api import \
            StorageNodeClient
        cluster = ClusterStorage([
            StorageNodeClient("127.0.0.1", ip, sp) for ip, sp in specs])
        scraper = SelfScraper(cluster.add_rows, instance="self")
        n = scraper.scrape_once()
        assert n > 50
        assert cluster.rows_sent == n
        for st in storages:
            st.force_flush()
        from victoriametrics_tpu.storage.tag_filters import \
            filters_from_dict
        now_ms = int(time.time() * 1e3)
        per_node = [len(st.search_series(
            filters_from_dict({"job": "victoria-metrics"}),
            now_ms - 60_000, now_ms + 60_000)) for st in storages]
        # consistent-hash sharding: every node holds a share, and the
        # union is the whole scrape
        assert all(c > 0 for c in per_node), per_node
        assert sum(per_node) == n
        cluster.close()
    finally:
        for srv in servers:
            srv.stop()
        for st in storages:
            st.close()


@pytest.mark.slow
def test_vmsingle_selfscrape_e2e(tmp_path):
    """The acceptance loop through a real process: a vmsingle started
    with -selfScrapeInterval serves its OWN history via query_range
    within one interval, correctly labeled."""
    port = free_ports(1)[0]
    app = VmSingleProc(str(tmp_path / "data"), port=port,
                       extra_flags=["-selfScrapeInterval=0.2"])
    try:
        c = Client(port)
        deadline = time.time() + 15
        rows = []
        while time.time() < deadline:
            now = time.time()
            res = c.query_range("vm_app_uptime_seconds", now - 60, now,
                                "1s")
            rows = res["data"]["result"]
            # step-fill repeats one sample across steps: demand two
            # DISTINCT uptime values, i.e. two real scrapes landed
            if rows and len({v for _, v in rows[0]["values"]}) >= 2:
                break
            time.sleep(0.2)
        assert rows, "self-scraped series never became queryable"
        metric = rows[0]["metric"]
        assert metric["job"] == "victoria-metrics"
        assert metric["instance"] == f"vmsingle:{port}"
        # uptime counts up between scrapes
        vals = [float(v) for _, v in rows[0]["values"]]
        assert vals[-1] > vals[0] >= 0.0
        # the scraper's own accounting is on /metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "vm_selfscrape_scrapes_total" in text
    finally:
        app.stop()


@pytest.mark.slow
def test_vmsingle_health_and_slo_endpoints(tmp_path):
    """A self-scraping vmsingle serves the whole plane: /status/health
    verdict ok, /status/slo evaluates on ?pump=1, incident log empty."""
    port = free_ports(1)[0]
    app = VmSingleProc(str(tmp_path / "data"), port=port,
                       extra_flags=["-selfScrapeInterval=0.2"])
    try:
        c = Client(port)
        code, body = c.get("/api/v1/status/health")
        assert code == 200, body
        h = json.loads(body)
        assert h["verdict"] == "ok" and h["role"] == "vmsingle"
        assert h["reasons"] == []
        assert h["uptimeSeconds"] >= 0.0
        code, body = c.get("/api/v1/status/slo", pump="1")
        assert code == 200, body
        st = json.loads(body)
        assert st["evalRounds"] >= 1
        assert {s["slo"] for s in st["slos"]} >= {
            "http-availability", "http-latency", "ingest-durability",
            "search-admission"}
        assert all(not s["firing"] for s in st["slos"]), st["slos"]
        code, body = c.get("/api/v1/status/incidents")
        assert code == 200 and json.loads(body)["data"] == []
    finally:
        app.stop()
