"""Downsampling & retention tiers (tentpole + satellites).

Covers:

1. ``VM_DOWNSAMPLE`` grammar (offset:resolution[:retention] tiers).
2. The dedup/downsample GOLDEN agreement: query-time dedup and the
   downsample bucketing share right-inclusive window semantics —
   boundary samples at exact interval multiples close their own window,
   timestamp ties prefer the max non-stale value, staleness markers
   survive in the ``last`` column and are excluded from min/max/count/
   sum.  Pinned against BOTH the python ``deduplicate`` and the native
   ``vm_dedup_rows`` assemble path.
3. Tier-selection oracle equality: a tier-served rollup equals the same
   query over raw (``VM_DOWNSAMPLE_READ=0``) at a bucket-aligned step —
   bit-exact for sum/count/min/max/last (integer-representable values),
   documented float tolerance for avg.
4. The partial-resolution flag: raw dropped by retention + no tier
   satisfying the step => served from the finest surviving tier and
   LOUDLY flagged (storage flag, EvalConfig accumulator, HTTP
   ``partialResolution``); ``VM_DOWNSAMPLE_READ=0`` disables even the
   fallback.
5. Per-tier retention sweep: raw parts dropped at raw retention while
   tiers survive to their own deadlines; keep-forever tiers suppress
   whole-partition and index-month drops.
6. Tier recovery discipline: reopen round-trip, torn tier.json =>
   whole-tier quarantine + self-heal from raw on the next pass.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from victoriametrics_tpu.ops import decimal as dec
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage import downsample as ds
from victoriametrics_tpu.storage.dedup import _buckets, deduplicate
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import TagFilter

NOW = 1_754_000_000_000          # fixed "now" for deterministic cycles
RES = 300_000                    # finest test tier: 5m
FILTER_M = [TagFilter(b"", b"m")]


# ---------------------------------------------------------------------------
# 1. spec grammar
# ---------------------------------------------------------------------------

class TestSpec:
    def test_two_tiers_with_default_retention(self):
        tiers = ds.parse_spec("30d:5m,180d:1h")
        assert [(t.offset_ms, t.resolution_ms, t.retention_ms)
                for t in tiers] == [
            (30 * 86_400_000, 300_000, 180 * 86_400_000),  # next offset
            (180 * 86_400_000, 3_600_000, 0),              # forever
        ]

    def test_explicit_retention_and_units(self):
        tiers = ds.parse_spec("1h:30s:2d")
        assert [(t.offset_ms, t.resolution_ms, t.retention_ms)
                for t in tiers] == [(3_600_000, 30_000, 2 * 86_400_000)]

    def test_empty_spec_is_no_tiers(self):
        assert ds.parse_spec("") == []
        assert ds.parse_spec(None) == []

    @pytest.mark.parametrize("spec", [
        "30d",                       # missing resolution
        "30d:5m:10d",                # retention <= offset
        "30d:5m,20d:1h",             # offsets not increasing
        "30d:1h,180d:5m",            # resolutions not increasing
        "1h:5m,2h:7m",               # resolutions do not nest (7m % 5m)
        "30d:0m",                    # zero resolution
        "xx:5m",                     # bad duration
    ])
    def test_rejects(self, spec):
        with pytest.raises(ValueError):
            ds.parse_spec(spec)


# ---------------------------------------------------------------------------
# 2. golden dedup/downsample agreement
# ---------------------------------------------------------------------------

# one shared golden input: boundary samples (exact multiples of the
# interval), an intra-bucket run, a timestamp tie (stale vs real), and an
# all-stale bucket.  Interval = 100.
GOLD_TS = np.array([
    100,            # exact multiple: closes ITS OWN window (right-incl.)
    101, 150, 200,  # (100, 200] bucket: last sample at the right edge
    205, 210,       # (200, 300] bucket: plain run
    400, 400,       # tie at the boundary of (300, 400]
    450,            # (400, 500]: lone stale marker
], dtype=np.int64)
GOLD_VALS = np.array([
    1.0,
    2.0, 3.0, 4.0,
    5.0, 6.0,
    7.0, dec.STALE_NAN,     # tie: the NON-stale value must win
    dec.STALE_NAN,
], dtype=np.float64)
# deduplicate keeps the highest-ts sample per bucket; the 400-tie keeps
# the max non-stale (7.0); the all-stale bucket keeps its marker
GOLD_KEEP_TS = np.array([100, 200, 210, 400, 450], dtype=np.int64)
GOLD_KEEP_VALS = [1.0, 4.0, 6.0, 7.0, "stale"]


def _assert_vals(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        if w == "stale":
            assert dec.is_stale_nan(np.array([g]))[0]
        else:
            assert g == w


class TestGoldenAgreement:
    def test_buckets_right_inclusive(self):
        # an exact multiple lands in its OWN window, not the next one
        assert _buckets(np.array([100, 101, 200]), 100).tolist() == [1, 2, 2]

    def test_python_dedup(self):
        ts, vals = deduplicate(GOLD_TS, GOLD_VALS, 100)
        assert ts.tolist() == GOLD_KEEP_TS.tolist()
        _assert_vals(vals, GOLD_KEEP_VALS)

    def test_downsample_last_is_dedup_restamped(self):
        out = ds.aggregate_series(GOLD_TS, GOLD_VALS, 100)
        lts, lvals = out["last"]
        # same kept samples, restamped to the bucket right edges
        assert lts.tolist() == (_buckets(GOLD_KEEP_TS, 100) * 100).tolist()
        _assert_vals(lvals, GOLD_KEEP_VALS)

    def test_downsample_aggregates_exclude_stale(self):
        out = ds.aggregate_series(GOLD_TS, GOLD_VALS, 100)
        # the all-stale (400, 500] bucket appears ONLY in `last`
        for agg in ("min", "max", "count", "sum"):
            assert out[agg][0].tolist() == [100, 200, 300, 400]
        assert out["count"][1].tolist() == [1, 3, 2, 1]  # tie: stale excl.
        assert out["sum"][1].tolist() == [1.0, 9.0, 11.0, 7.0]
        assert out["min"][1].tolist() == [1.0, 2.0, 5.0, 7.0]
        assert out["max"][1].tolist() == [1.0, 4.0, 6.0, 7.0]

    def test_native_assemble_dedup_matches(self):
        """The same golden input through the columnar assemble path
        (native vm_dedup_rows when available, its python oracle loop
        otherwise) keeps identical samples."""
        from victoriametrics_tpu.storage.columnar import assemble
        cols = assemble(np.array([0]), 1, np.array([GOLD_TS.size]),
                        GOLD_TS.copy(), GOLD_VALS.copy(),
                        0, 1_000, dedup_interval_ms=100)
        n = int(cols.counts[0])
        assert cols.ts[0, :n].tolist() == GOLD_KEEP_TS.tolist()
        _assert_vals(cols.vals[0, :n], GOLD_KEEP_VALS)


# ---------------------------------------------------------------------------
# shared fixtures for storage-level tests
# ---------------------------------------------------------------------------

def _fill(s, base, span_ms, step_ms=30_000, n_series=3, seed=7):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(0, span_ms, step_ms):
        for k in range(n_series):
            rows.append(({"__name__": "m", "i": str(k)}, base + i,
                         float(int(rng.integers(0, 1000)))))
    s.add_rows(rows)
    s.table.flush_to_disk()


def _aligned_cfg(s, base, end, step):
    start = ((base // RES) + 2) * RES
    start += (step - (start % step)) % step
    return EvalConfig(start=start, end=end, step=step, storage=s,
                      disable_cache=True)


def _run(s, base, end, step, q):
    s.reset_partial()
    ec = _aligned_cfg(s, base, end, step)
    rows = exec_query(ec, q)
    return ({bytes(r.metric_name.marshal()): r.values for r in rows}, ec)


@pytest.fixture
def aged_store(tmp_path):
    """5 days of 30s data for 3 series, aged 60 days: fully covered by
    the 5m tier of a 30d:5m,180d:1h config."""
    base = NOW - 60 * 86_400_000
    s = Storage(str(tmp_path / "s"), retention_ms=10 ** 15,
                downsample="30d:5m,180d:1h")
    _fill(s, base, 5 * 86_400_000)
    s.run_downsample_cycle(now_ms=NOW)
    yield s, base
    s.close()


# ---------------------------------------------------------------------------
# 3. tier-selection oracle
# ---------------------------------------------------------------------------

EXACT_QUERIES = ["sum_over_time(m[1h])", "count_over_time(m[1h])",
                 "min_over_time(m[1h])", "max_over_time(m[1h])",
                 "last_over_time(m[1h])"]


class TestOracle:
    @pytest.mark.parametrize("q", EXACT_QUERIES)
    def test_bit_exact(self, aged_store, monkeypatch, q):
        s, base = aged_store
        end, step = base + 4 * 86_400_000, 3_600_000
        tier, ec_t = _run(s, base, end, step, q)
        monkeypatch.setenv("VM_DOWNSAMPLE_READ", "0")
        raw, _ = _run(s, base, end, step, q)
        assert tier.keys() == raw.keys() and len(tier) == 3
        for k in tier:
            a, b = tier[k], raw[k]
            assert (np.isnan(a) == np.isnan(b)).all()
            m = ~np.isnan(a)
            # integer-representable values + the sequential reduceat sum:
            # bit-exact equality, not a tolerance
            assert (a[m] == b[m]).all(), q
        assert ec_t._partial_res[0] is False

    def test_avg_composed_within_tolerance(self, aged_store, monkeypatch):
        """avg composes sum/count; the division reorders float ops vs the
        raw mean, so equality is to ~1 ulp of the magnitude, not exact."""
        s, base = aged_store
        end, step = base + 4 * 86_400_000, 3_600_000
        tier, _ = _run(s, base, end, step, "avg_over_time(m[1h])")
        monkeypatch.setenv("VM_DOWNSAMPLE_READ", "0")
        raw, _ = _run(s, base, end, step, "avg_over_time(m[1h])")
        assert tier.keys() == raw.keys() and len(tier) == 3
        for k in tier:
            a, b = tier[k], raw[k]
            assert (np.isnan(a) == np.isnan(b)).all()
            m = ~np.isnan(a)
            np.testing.assert_allclose(a[m], b[m], rtol=1e-12)

    def test_tier_actually_served(self, aged_store):
        """The oracle equality must not be vacuous: the tier-served fetch
        reads ~step_ms/res fewer samples than the raw oracle."""
        s, base = aged_store
        end = base + 4 * 86_400_000
        s.reset_partial()
        cols = s.search_columns(FILTER_M, base, end, ds=("sum", 3_600_000))
        raw = s.search_columns(FILTER_M, base, end)
        assert cols.ds_res == RES
        assert raw.ds_res == 0
        assert raw.n_samples >= 9 * cols.n_samples  # 30s -> 5m buckets

    def test_count_mixed_tier_and_raw_tail(self, tmp_path, monkeypatch):
        """count_over_time across the tier/raw coverage boundary: aged
        buckets come from the count column, the raw tail contributes 1
        per sample — the sum of the mixture is the exact count."""
        base = NOW - 3 * 86_400_000
        s = Storage(str(tmp_path / "s"), retention_ms=10 ** 15,
                    downsample="1d:5m")
        try:
            _fill(s, base, 3 * 86_400_000 - 3_600_000)
            # cycle at NOW: covers only the aged (> 1d old) prefix; the
            # final ~day stays raw-only
            s.run_downsample_cycle(now_ms=NOW)
            st = next(iter(s.table._partitions.values()))._tiers[RES]
            assert base < st.covered_max_ts < NOW - 86_400_000 + RES
            end, step = base + 3 * 86_400_000 - 2 * 3_600_000, 3_600_000
            for q in ("count_over_time(m[1h])", "sum_over_time(m[1h])",
                      "avg_over_time(m[1h])"):
                tier, _ = _run(s, base, end, step, q)
                monkeypatch.setenv("VM_DOWNSAMPLE_READ", "0")
                raw, _ = _run(s, base, end, step, q)
                monkeypatch.delenv("VM_DOWNSAMPLE_READ")
                assert tier.keys() == raw.keys() and len(tier) == 3
                for k in tier:
                    a, b = tier[k], raw[k]
                    assert (np.isnan(a) == np.isnan(b)).all()
                    m = ~np.isnan(a)
                    np.testing.assert_allclose(a[m], b[m], rtol=1e-12)
        finally:
            s.close()

    def test_month_seam_bucket_exact(self, tmp_path, monkeypatch):
        """A right-inclusive bucket whose edge is midnight of the 1st is
        SPLIT across two monthly partitions: the old month holds
        (edge-res, edge) and the new month the sample at exactly the
        edge.  The old partition's final bucket must restamp INSIDE the
        partition (its last inclusive ms) — an unclamped edge stamp
        collides with the new partition's first bucket and assembly
        drops one of the duplicate-ts rows, under-counting the seam
        window."""
        boundary = 1_748_736_000_000          # 2025-06-01T00:00:00Z
        base = boundary - 86_400_000
        s = Storage(str(tmp_path / "s"), retention_ms=10 ** 15,
                    downsample="30d:5m")
        try:
            # 30s cadence across the seam INCLUDING a sample at exactly
            # the boundary (it lands in the June partition)
            _fill(s, base, 2 * 86_400_000 + 30_000)
            s.run_downsample_cycle(now_ms=NOW)
            # both monthly partitions produced a tier; the May one's
            # final bucket is clamped to the partition's last ms
            tiers = [p._tiers[RES] for p in
                     s.table._partitions.values() if p._tiers]
            assert len(tiers) == 2
            assert min(t.covered_max_ts for t in tiers) == boundary - 1
            end, step = base + 2 * 86_400_000, 3_600_000
            for q in ("sum_over_time(m[1h])", "count_over_time(m[1h])",
                      "last_over_time(m[1h])"):
                tier, _ = _run(s, base, end, step, q)
                monkeypatch.setenv("VM_DOWNSAMPLE_READ", "0")
                raw, _ = _run(s, base, end, step, q)
                monkeypatch.delenv("VM_DOWNSAMPLE_READ")
                assert tier.keys() == raw.keys() and len(tier) == 3
                for k in tier:
                    a, b = tier[k], raw[k]
                    assert (np.isnan(a) == np.isnan(b)).all(), q
                    m = ~np.isnan(a)
                    assert (a[m] == b[m]).all(), q
        finally:
            s.close()

    def test_tier_cascade_coarse_fine_raw(self, tmp_path, monkeypatch):
        """A long-range fetch cascades 1h-tier -> 5m-tier -> raw: each
        finer source serves only the span past the previous watermark,
        the composition is disjoint, and the result stays bit-exact
        against the raw oracle."""
        base = NOW - 5 * 86_400_000
        s = Storage(str(tmp_path / "s"), retention_ms=10 ** 15,
                    downsample="1d:5m,3d:1h")
        try:
            _fill(s, base, 5 * 86_400_000 - 3_600_000)
            s.run_downsample_cycle(now_ms=NOW)
            s.reset_partial()
            end = base + 5 * 86_400_000 - 2 * 3_600_000
            cols = s.search_columns(FILTER_M, base, end,
                                    ds=("sum", 3_600_000))
            raw = s.search_columns(FILTER_M, base, end)
            # coarsest contributing tier is reported; the 5m middle span
            # and raw tail make the fetch strictly richer than 1h-only
            assert cols.ds_res == 3_600_000
            n_1h_only = 3 * (4 * 86_400_000 // 3_600_000)
            assert cols.n_samples > n_1h_only
            assert raw.n_samples > 4 * cols.n_samples
            for q in ("sum_over_time(m[1h])", "count_over_time(m[1h])",
                      "max_over_time(m[1h])"):
                tier, ec = _run(s, base, end, 3_600_000, q)
                monkeypatch.setenv("VM_DOWNSAMPLE_READ", "0")
                oracle, _ = _run(s, base, end, 3_600_000, q)
                monkeypatch.delenv("VM_DOWNSAMPLE_READ")
                assert tier.keys() == oracle.keys() and len(tier) == 3
                for k in tier:
                    a, b = tier[k], oracle[k]
                    assert (np.isnan(a) == np.isnan(b)).all(), q
                    m = ~np.isnan(a)
                    assert (a[m] == b[m]).all(), q
                assert ec._partial_res[0] is False
        finally:
            s.close()


# ---------------------------------------------------------------------------
# 4. partial-resolution flag
# ---------------------------------------------------------------------------

class TestPartialResolution:
    def test_fallback_sets_flag(self, aged_store):
        s, base = aged_store
        for p in s.table._partitions.values():
            p.drop_raw_parts()
        s.reset_partial()
        # ds asks for finer than any tier -> fallback to finest, flagged
        cols = s.search_columns(FILTER_M, base, base + 86_400_000,
                                ds=("sum", 1))
        assert cols.partial_res is True and cols.ds_res == RES
        assert cols.n_samples > 0
        assert s.last_partial_resolution is True
        s.reset_partial()
        assert s.last_partial_resolution is False

    def test_flag_reaches_eval_config(self, aged_store):
        s, base = aged_store
        for p in s.table._partitions.values():
            p.drop_raw_parts()
        # 1m step over 5m buckets: no tier satisfies, fallback + flag
        _, ec = _run(s, base, base + 6 * 3_600_000, 60_000,
                     "sum_over_time(m[1m])")
        assert ec._partial_res[0] is True

    def test_read_disabled_disables_fallback(self, aged_store,
                                             monkeypatch):
        s, base = aged_store
        for p in s.table._partitions.values():
            p.drop_raw_parts()
        monkeypatch.setenv("VM_DOWNSAMPLE_READ", "0")
        s.reset_partial()
        cols = s.search_columns(FILTER_M, base, base + 86_400_000,
                                ds=("sum", 1))
        assert cols.n_samples == 0 and cols.ds_res == 0
        assert s.last_partial_resolution is False

    def test_http_partial_resolution_field(self, aged_store):
        from tests.apptest_helpers import Client
        from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
        from victoriametrics_tpu.httpapi.server import HTTPServer
        s, base = aged_store
        for p in s.table._partitions.values():
            p.drop_raw_parts()
        srv = HTTPServer("127.0.0.1", 0)
        PrometheusAPI(s).register(srv, mode="select")
        srv.start()
        try:
            c = Client(srv.port)
            t = ((base // RES) + 20) * RES
            code, body = c.get("/api/v1/query_range",
                               query="sum_over_time(m[1m])",
                               start=str(t // 1000),
                               end=str((t + 3_600_000) // 1000), step="60")
            assert code == 200
            rep = json.loads(body)
            assert rep["partialResolution"] is True
            assert rep["isPartial"] is False
            # full-resolution query on a healthy window: flag stays off
            code, body = c.get("/api/v1/query_range",
                               query="sum_over_time(m[1h])",
                               start=str(t // 1000),
                               end=str((t + 6 * 3_600_000) // 1000),
                               step="3600")
            assert json.loads(body)["partialResolution"] is False
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# 5. per-tier retention sweep
# ---------------------------------------------------------------------------

class TestRetentionSweep:
    def test_raw_dropped_tiers_survive(self, tmp_path):
        """Raw retention expires a partition's raw parts while a
        keep-forever tier still serves it; the index months survive so
        the tier stays QUERYABLE.  (Retention is partition-granular: the
        whole MONTH must be past the raw deadline, hence 90d-old data
        against a 40d raw retention.)"""
        base = NOW - 90 * 86_400_000
        s = Storage(str(tmp_path / "s"), retention_ms=40 * 86_400_000,
                    downsample="30d:5m")
        try:
            _fill(s, base, 2 * 86_400_000)
            s.run_downsample_cycle(now_ms=NOW)
            assert s.enforce_retention(now_ms=NOW) >= 1
            p = next(iter(s.table._partitions.values()))
            assert not p._file_parts and p.has_tier_parts
            # still queryable straight from the tier (fallback + flag)
            s.reset_partial()
            cols = s.search_columns(FILTER_M, base, base + 86_400_000,
                                    ds=("sum", RES))
            assert cols.n_samples > 0 and cols.ds_res == RES
        finally:
            s.close()

    def test_tier_dropped_at_own_deadline(self, tmp_path):
        """A bounded tier is dropped once its retention passes while a
        longer-lived coarser tier keeps the partition alive."""
        base = NOW - 200 * 86_400_000
        s = Storage(str(tmp_path / "s"), retention_ms=10 ** 15,
                    downsample="30d:5m:100d,180d:1h")
        try:
            _fill(s, base, 86_400_000)
            s.run_downsample_cycle(now_ms=NOW)
            p = next(iter(s.table._partitions.values()))
            assert sorted(res for res, _ in s.tier_deadlines()) == \
                [RES, 3_600_000]
            assert set(st.resolution_ms for st in p.tier_states()) == \
                {RES, 3_600_000}
            assert s.enforce_retention(now_ms=NOW) >= 1
            assert [st.resolution_ms for st in p.tier_states()] == \
                [3_600_000]
            assert not os.path.isdir(os.path.join(p.path, f"ds_{RES}"))
        finally:
            s.close()

    def test_everything_expired_drops_partition(self, tmp_path):
        """When raw AND every tier deadline have passed, the partition
        dir (and its index months) drop whole — same as before tiers."""
        base = NOW - 200 * 86_400_000
        s = Storage(str(tmp_path / "s"), retention_ms=40 * 86_400_000,
                    downsample="30d:5m:100d")
        try:
            _fill(s, base, 86_400_000)
            s.run_downsample_cycle(now_ms=NOW)
            assert s.enforce_retention(now_ms=NOW) >= 1
            assert s.table.partition_names == []
        finally:
            s.close()


# ---------------------------------------------------------------------------
# 6. recovery discipline
# ---------------------------------------------------------------------------

class TestRecovery:
    def test_reopen_roundtrip(self, tmp_path):
        base = NOW - 60 * 86_400_000
        d = str(tmp_path / "s")
        s = Storage(d, retention_ms=10 ** 15, downsample="30d:5m")
        _fill(s, base, 86_400_000)
        s.run_downsample_cycle(now_ms=NOW)
        want = s.search_columns(FILTER_M, base, base + 86_400_000,
                                ds=("sum", 3_600_000))
        s.close()
        s2 = Storage(d, retention_ms=10 ** 15, downsample="30d:5m")
        try:
            assert s2.table.quarantined() == []
            got = s2.search_columns(FILTER_M, base, base + 86_400_000,
                                    ds=("sum", 3_600_000))
            assert got.ds_res == RES
            assert got.n_samples == want.n_samples
        finally:
            s2.close()

    def test_torn_tier_quarantined_whole_then_self_heals(self, tmp_path):
        base = NOW - 60 * 86_400_000
        d = str(tmp_path / "s")
        s = Storage(d, retention_ms=10 ** 15, downsample="30d:5m")
        _fill(s, base, 86_400_000)
        s.run_downsample_cycle(now_ms=NOW)
        s.close()
        tj = os.path.join(
            d, "data",
            next(n for n in os.listdir(os.path.join(d, "data"))
                 if os.path.isdir(os.path.join(d, "data", n))),
            f"ds_{RES}", "tier.json")
        with open(tj, "r+b") as f:
            b = bytearray(f.read())
            b[len(b) // 2] ^= 0xFF
            f.seek(0)
            f.write(b)
        s = Storage(d, retention_ms=10 ** 15, downsample="30d:5m")
        try:
            rep = s.table.quarantined()
            assert [q["store"] for q in rep] == ["downsample"], rep
            # raw survives: queries fall back to raw, tier ignored
            cols = s.search_columns(FILTER_M, base, base + 86_400_000,
                                    ds=("sum", 3_600_000))
            assert cols.ds_res == 0 and cols.n_samples > 0
            # next pass rebuilds the tier from raw
            s.run_downsample_cycle(now_ms=NOW)
            cols = s.search_columns(FILTER_M, base, base + 86_400_000,
                                    ds=("sum", 3_600_000))
            assert cols.ds_res == RES
        finally:
            s.close()
