"""Deterministic-time tests (the reference covers flush/rotation/retention
with synctest bubbles, lib/storage/storage_synctest_test.go; here a fake
clock via monkeypatch drives the same policies without sleeps)."""

import pytest

from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import filters_from_dict

DAY = 86_400_000


class FakeClock:
    def __init__(self, ms: int):
        self.ms = ms

    def time(self) -> float:
        return self.ms / 1000.0

    def advance(self, ms: int):
        self.ms += ms


@pytest.fixture()
def clock(monkeypatch):
    c = FakeClock(1_753_700_000_000)
    import victoriametrics_tpu.storage.storage as st
    monkeypatch.setattr(st.time, "time", c.time)
    from victoriametrics_tpu.query.rollup_result_cache import GLOBAL
    GLOBAL.reset()  # fake-clock tests must not see real-clock entries
    return c


class TestRetentionClock:
    def test_partitions_drop_exactly_at_boundary(self, tmp_path, clock):
        s = Storage(str(tmp_path / "rt"), retention_ms=40 * DAY)
        t0 = clock.ms
        old = t0 - 35 * DAY   # inside retention today
        s.add_rows([({"__name__": "rm"}, old, 1.0),
                    ({"__name__": "rm"}, t0, 2.0)])
        s.force_flush()
        assert s.enforce_retention() == 0  # still inside the window
        f = filters_from_dict({"__name__": "rm"})
        assert len(s.search_series(f, old - 1000, t0 + 1000)) == 1
        # advance the clock: the old partition crosses the boundary
        clock.advance(40 * DAY)
        dropped = s.enforce_retention()
        assert dropped >= 1
        res = s.search_series(f, old - 1000, old + 1000)
        assert res == [] or all(
            (sd.timestamps > s.min_valid_ts).all() for sd in res)
        s.close()

    def test_min_valid_ts_tracks_clock(self, tmp_path, clock):
        s = Storage(str(tmp_path / "mv"), retention_ms=10 * DAY)
        before = s.min_valid_ts
        clock.advance(3 * DAY)
        assert s.min_valid_ts - before == 3 * DAY
        s.close()


class TestFlushDiscipline:
    def test_rows_visible_at_every_flush_stage(self, tmp_path, clock):
        """pending -> in-memory part -> file part: reads see the rows at
        each stage with no sleeps (partition.go 2s/5s discipline driven
        explicitly)."""
        s = Storage(str(tmp_path / "fd"))
        t0 = clock.ms
        f = filters_from_dict({"__name__": "fm"})
        s.add_rows([({"__name__": "fm"}, t0, 1.0)])
        # stage 1: raw pending rows
        assert len(s.search_series(f, t0 - 1000, t0 + 1000)) == 1
        p = s.table.partition_for_ts(t0)
        assert len(p._pending) == 1 and not p._mem_parts
        # stage 2: in-memory part (the 2s flush tick)
        s.table.flush_pending()
        assert not p._pending and len(p._mem_parts) == 1
        assert len(s.search_series(f, t0 - 1000, t0 + 1000)) == 1
        # stage 3: durable file part (the 5s disk tick)
        s.table.flush_to_disk()
        assert not p._mem_parts and len(p._file_parts) == 1
        assert len(s.search_series(f, t0 - 1000, t0 + 1000)) == 1
        s.close()


class TestLimiterClock:
    def test_hourly_rotation_boundary(self, monkeypatch):
        import victoriametrics_tpu.storage.cardinality as card
        base = (1_753_700_000_000 // 3_600_000) * 3_600_000  # hour-aligned
        c = FakeClock(base + 1000)
        monkeypatch.setattr(card.time, "time", c.time)
        lim = card.BloomLimiter(1, rotation_s=3600)
        assert lim.add(1) and not lim.add(2)
        c.advance(3_597_000)       # :59:58 — same hour bucket
        assert not lim.add(2)
        c.advance(2_000)           # crosses the hour boundary
        assert lim.add(2)
        assert lim.current_series == 1
