"""Deterministic-time tests (the reference covers flush/rotation/retention
with synctest bubbles, lib/storage/storage_synctest_test.go; here a fake
clock via monkeypatch drives the same policies without sleeps)."""

import pytest

try:
    from victoriametrics_tpu.storage.storage import Storage
    from victoriametrics_tpu.storage.tag_filters import filters_from_dict
    _STORAGE_ERR = None
except ImportError as e:  # optional native deps (zstandard) missing
    Storage = filters_from_dict = None
    _STORAGE_ERR = e

needs_storage = pytest.mark.skipif(
    Storage is None, reason=f"storage deps unavailable: {_STORAGE_ERR}")

DAY = 86_400_000


class FakeClock:
    def __init__(self, ms: int):
        self.ms = ms

    def time(self) -> float:
        return self.ms / 1000.0

    def advance(self, ms: int):
        self.ms += ms


@pytest.fixture()
def clock(monkeypatch):
    c = FakeClock(1_753_700_000_000)
    import victoriametrics_tpu.storage.storage as st
    monkeypatch.setattr(st.time, "time", c.time)
    from victoriametrics_tpu.query.rollup_result_cache import GLOBAL
    GLOBAL.reset()  # fake-clock tests must not see real-clock entries
    return c


@needs_storage
class TestRetentionClock:
    def test_partitions_drop_exactly_at_boundary(self, tmp_path, clock):
        s = Storage(str(tmp_path / "rt"), retention_ms=40 * DAY)
        t0 = clock.ms
        old = t0 - 35 * DAY   # inside retention today
        s.add_rows([({"__name__": "rm"}, old, 1.0),
                    ({"__name__": "rm"}, t0, 2.0)])
        s.force_flush()
        assert s.enforce_retention() == 0  # still inside the window
        f = filters_from_dict({"__name__": "rm"})
        assert len(s.search_series(f, old - 1000, t0 + 1000)) == 1
        # advance the clock: the old partition crosses the boundary
        clock.advance(40 * DAY)
        dropped = s.enforce_retention()
        assert dropped >= 1
        res = s.search_series(f, old - 1000, old + 1000)
        assert res == [] or all(
            (sd.timestamps > s.min_valid_ts).all() for sd in res)
        s.close()

    def test_min_valid_ts_tracks_clock(self, tmp_path, clock):
        s = Storage(str(tmp_path / "mv"), retention_ms=10 * DAY)
        before = s.min_valid_ts
        clock.advance(3 * DAY)
        assert s.min_valid_ts - before == 3 * DAY
        s.close()


@needs_storage
class TestFlushDiscipline:
    def test_rows_visible_at_every_flush_stage(self, tmp_path, clock):
        """pending -> in-memory part -> file part: reads see the rows at
        each stage with no sleeps (partition.go 2s/5s discipline driven
        explicitly)."""
        s = Storage(str(tmp_path / "fd"))
        t0 = clock.ms
        f = filters_from_dict({"__name__": "fm"})
        s.add_rows([({"__name__": "fm"}, t0, 1.0)])
        # stage 1: raw pending rows
        assert len(s.search_series(f, t0 - 1000, t0 + 1000)) == 1
        p = s.table.partition_for_ts(t0)
        assert len(p._pending) == 1 and not p._mem_parts
        # stage 2: in-memory part (the 2s flush tick)
        s.table.flush_pending()
        assert not p._pending and len(p._mem_parts) == 1
        assert len(s.search_series(f, t0 - 1000, t0 + 1000)) == 1
        # stage 3: durable file part (the 5s disk tick)
        s.table.flush_to_disk()
        assert not p._mem_parts and len(p._file_parts) == 1
        assert len(s.search_series(f, t0 - 1000, t0 + 1000)) == 1
        s.close()


class TestLimiterClock:
    def test_hourly_rotation_boundary(self, monkeypatch):
        import victoriametrics_tpu.storage.cardinality as card
        base = (1_753_700_000_000 // 3_600_000) * 3_600_000  # hour-aligned
        c = FakeClock(base + 1000)
        # cardinality reads the clock through the fasttime seam now
        monkeypatch.setattr(card.fasttime, "unix_timestamp",
                            lambda: int(c.time()))
        lim = card.BloomLimiter(1, rotation_s=3600)
        assert lim.add(1) and not lim.add(2)
        c.advance(3_597_000)       # :59:58 — same hour bucket
        assert not lim.add(2)
        c.advance(2_000)           # crosses the hour boundary
        assert lim.add(2)
        assert lim.current_series == 1


@needs_storage
class TestMergerScheduling:
    def test_small_part_merge_policy(self, tmp_path, clock):
        """Repeated disk flushes accumulate small parts; crossing
        MAX_SMALL_PARTS triggers the merger, which consolidates without
        losing rows (partition.go merger pools, driven explicitly)."""
        from victoriametrics_tpu.storage.partition import MAX_SMALL_PARTS
        s = Storage(str(tmp_path / "mg"))
        t0 = clock.ms
        total = 0
        p = None
        for i in range(MAX_SMALL_PARTS + 3):
            s.add_rows([({"__name__": "mm", "i": str(i)},
                         t0 + i * 1000, float(i))])
            s.table.flush_to_disk()
            total += 1
            p = s.table.partition_for_ts(t0)
        assert len(p._file_parts) <= MAX_SMALL_PARTS + 1
        f = filters_from_dict({"__name__": "mm"})
        assert len(s.search_series(f, t0 - 1000,
                                   t0 + total * 1000 + 1000)) == total
        s.close()

    def test_merge_drops_deleted_and_expired(self, tmp_path, clock):
        """A forced merge under an advanced clock drops tombstoned series
        and out-of-retention rows in the same pass (merge.go:19 filters)."""
        s = Storage(str(tmp_path / "md"), retention_ms=30 * DAY)
        t0 = clock.ms
        s.add_rows([({"__name__": "keep"}, t0, 1.0),
                    ({"__name__": "drop"}, t0, 2.0),
                    ({"__name__": "old"}, t0 - 25 * DAY, 3.0)])
        s.force_flush()
        s.delete_series(filters_from_dict({"__name__": "drop"}))
        clock.advance(10 * DAY)  # "old" rows cross the retention boundary
        s.force_merge()
        f_all = lambda n: s.search_series(filters_from_dict(
            {"__name__": n}), t0 - 30 * DAY, t0 + DAY)
        assert len(f_all("keep")) == 1
        assert f_all("drop") == []
        assert f_all("old") == []
        s.close()


class TestStreamAggrClock:
    def _agg(self, cfg, sink):
        from victoriametrics_tpu.ingest.streamaggr import Aggregator
        return Aggregator(cfg, sink)

    def test_interval_flush_alignment(self):
        """State resets exactly at each flush: samples land in their own
        interval's output rows, stamped with the flush-time now_ms
        (streamaggr.go flushers, driven with explicit virtual times)."""
        from victoriametrics_tpu.ingest.streamaggr import _interval_str
        base = (1_753_700_000_000 // 60_000) * 60_000
        out = []
        a = self._agg({"interval": "60s", "outputs": ["sum_samples"],
                       "by": ["job"]}, out.extend)
        sfx = _interval_str(60_000)
        for k in range(3):
            a.push({"__name__": "m", "job": "j"}, base + k * 1000, 10.0)
        a.flush(now_ms=base + 60_000)
        a.push({"__name__": "m", "job": "j"}, base + 61_000, 5.0)
        a.flush(now_ms=base + 120_000)
        a.flush(now_ms=base + 180_000)  # empty interval: no output
        assert [(r[0]["__name__"], r[1], r[2]) for r in out] == [
            (f"m:{sfx}_sum_samples", base + 60_000, 30.0),
            (f"m:{sfx}_sum_samples", base + 120_000, 5.0)]

    def test_total_state_survives_flushes(self):
        """total is cumulative ACROSS intervals (only the delta within each
        interval is new), matching the reference's total output."""
        base = (1_753_700_000_000 // 60_000) * 60_000
        out = []
        a = self._agg({"interval": "60s", "outputs": ["total"]}, out.extend)
        a.push({"__name__": "c", "job": "j"}, base + 1000, 5.0)
        a.push({"__name__": "c", "job": "j"}, base + 2000, 8.0)
        a.flush(now_ms=base + 60_000)
        a.push({"__name__": "c", "job": "j"}, base + 61_000, 11.0)
        a.flush(now_ms=base + 120_000)
        vals = [r[2] for r in out]
        assert vals == [8.0, 11.0]  # counts from 0 at first sight, then +3

    def test_dedup_keeps_last_per_interval(self):
        from victoriametrics_tpu.ingest.streamaggr import Deduplicator
        rows = []
        d = Deduplicator(30_000, lambda rs: rows.extend(rs))
        d.push({"__name__": "m"}, 1000, 1.0)
        d.push({"__name__": "m"}, 2000, 2.0)
        d.push({"__name__": "m"}, 3000, 3.0)
        d.flush(now_ms=30_000)
        assert [(r[1], r[2]) for r in rows] == [(3000, 3.0)]


class TestAlertingClock:
    class FakeDS:
        def __init__(self):
            self.results = []

        def query(self, expr, now):
            return list(self.results)

    def _rule(self, for_s):
        from victoriametrics_tpu.apps import vmalert

        class G:
            name = "g"
            interval = 30.0
        return vmalert.AlertingRule(
            {"alert": "HighLoad", "expr": "up == 0",
             "for": f"{for_s}s", "labels": {"sev": "page"}}, G())

    def test_pending_to_firing_to_resolved(self):
        from victoriametrics_tpu.apps.vmalert import (STATE_FIRING,
                                                      STATE_PENDING)
        ds = self.FakeDS()
        ds.results = [{"metric": {"instance": "h1"}, "value": 1.0}]
        r = self._rule(300)
        t = 1_753_700_000.0
        st = r.eval(ds, t)
        assert [s["state"] for s in st] == [STATE_PENDING]
        st = r.eval(ds, t + 299)      # one second short of `for`
        assert [s["state"] for s in st] == [STATE_PENDING]
        st = r.eval(ds, t + 300)      # exactly at the boundary
        assert [s["state"] for s in st] == [STATE_FIRING]
        ds.results = []               # condition clears
        st = r.eval(ds, t + 330)
        assert st == []               # resolved: removed from active set

    def test_flapping_resets_pending_timer(self):
        from victoriametrics_tpu.apps.vmalert import (STATE_FIRING,
                                                      STATE_PENDING)
        ds = self.FakeDS()
        ds.results = [{"metric": {"instance": "h1"}, "value": 1.0}]
        r = self._rule(300)
        t = 1_753_700_000.0
        r.eval(ds, t)
        ds.results = []
        r.eval(ds, t + 200)           # clears before firing
        ds.results = [{"metric": {"instance": "h1"}, "value": 1.0}]
        st = r.eval(ds, t + 290)      # re-activates: timer restarts
        assert [s["state"] for s in st] == [STATE_PENDING]
        st = r.eval(ds, t + 290 + 299)
        assert [s["state"] for s in st] == [STATE_PENDING]
        st = r.eval(ds, t + 290 + 300)
        assert [s["state"] for s in st] == [STATE_FIRING]

    def test_restore_preserves_active_at_across_restart(self):
        """ALERTS_FOR_STATE restore: a restarted rule resumes the original
        activeAt, so `for` continuity survives the restart
        (rule/alerting.go Restore)."""
        from victoriametrics_tpu.apps.vmalert import STATE_FIRING
        t = 1_753_700_000.0

        class RestoreDS:
            def query(self, expr, now):
                if "ALERTS_FOR_STATE" in expr:
                    return [{"metric": {"alertname": "HighLoad",
                                        "instance": "h1", "sev": "page"},
                             "value": t}]
                return [{"metric": {"instance": "h1"}, "value": 1.0}]

        r = self._rule(300)
        ds = RestoreDS()
        r.restore(ds, t + 200, lookback_s=3600)
        assert len(r._active) == 1
        # next eval happens 300s after the ORIGINAL activeAt: fires
        st = r.eval(ds, t + 300)
        assert [s["state"] for s in st] == [STATE_FIRING]
