"""Crash-consistency + part-integrity harness (the recovery counterpart
of PR 9's liveness chaos suite).

Three layers:

1. **Torn-part matrix** (tier-1): truncate / bit-flip each of the four
   data-part files plus metadata.json, reopen, and assert the part is
   QUARANTINED loudly — moved to ``quarantine/``, counted in
   ``vm_parts_quarantined_total``, listed at
   ``/api/v1/status/quarantine``, every result flagged partial.  This
   doubles as the regression test that the OLD behavior — a listed part
   that fails to open being logged once and silently dropped from every
   future result — is gone.

2. **Crashpoint matrix** (tier-1): a subprocess ingest/flush/merge/
   snapshot loop is hard-killed (``os._exit`` via the ``crash`` fault
   action) at each named seam of the part lifecycle, then the store is
   reopened and checked against the recovery invariants: opens clean,
   every sample acked before the last successful flush is present
   byte-exact, no orphan ``.tmp`` dirs, no unlisted part dirs, no
   quarantine (a clean kill can lose un-acked work but never tear
   fsynced bytes).

3. **Randomized kill -9 matrix** (``slow`` + ``crash`` markers,
   tools/chaos.sh): the same subprocess storm killed with SIGKILL at
   random instants, >= 20 cycles against one accumulating store.

Plus the storage-side deadline unit tests (typed abort, RPC wire
marker, no node-down marking) for ROADMAP item 3's named leftover.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tests.apptest_helpers import REPO, Client
from victoriametrics_tpu.devtools import faultinject
from victoriametrics_tpu.storage.metric_name import MetricName
from victoriametrics_tpu.storage.storage import (DeadlineExceededError,
                                                 Storage)
from victoriametrics_tpu.storage.tag_filters import TagFilter

T0 = 1_753_700_000_000
N_SERIES = 8
NAME_FILTER = [TagFilter(b"", b"crashm")]

# ---------------------------------------------------------------------------
# child program: ingest/flush loop that dies at armed crashpoints
# ---------------------------------------------------------------------------

_CHILD_SRC = r"""
import os, sys
sys.path.insert(0, os.getcwd())
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.metric_name import MetricName

data_dir, ack_path, scenario, n_batches, t_base = sys.argv[1:6]
n_batches = int(n_batches)
T0 = int(t_base)
N_SERIES = 8

acked = -1
try:
    with open(ack_path) as f:
        lines = [ln for ln in f.read().splitlines() if ln]
    if lines:
        acked = int(lines[-1])
except FileNotFoundError:
    pass

kw = {}
if scenario == "retention":
    kw["retention_ms"] = 40 * 86_400_000
elif scenario == "downsample":
    kw["downsample"] = "1d:5m"
s = Storage(data_dir, **kw)
names = [MetricName.from_dict({"__name__": "crashm", "s": str(i)})
         for i in range(N_SERIES)]
if scenario == "retention":
    # out-of-retention month: its partition + month index table exist so
    # enforce_retention has something to rotate (indexdb:rotate seam)
    import time as _t
    t_old = int(_t.time() * 1000) - 100 * 86_400_000
    s.add_rows([(MetricName.from_dict({"__name__": "oldm", "s": str(i)}),
                 t_old, float(i)) for i in range(4)])
    s.force_flush()

ackf = open(ack_path, "a")
stormers = []
if scenario == "storm":
    # racing flush/merge/snapshot threads (the PR-9 ingest-storm shape):
    # the randomized SIGKILL lands wherever it lands
    import threading

    def churn():
        while True:
            try:
                s.force_merge()
                s.create_snapshot()
            except Exception:
                # benign churn races (two threads picking one snapshot
                # name, merge vs close) must not fail the child with a
                # non-kill exit code; the SIGKILL is the only exit
                pass
    for _ in range(2):
        th = threading.Thread(target=churn, daemon=True)
        th.start()
        stormers.append(th)

for b in range(acked + 1, acked + 1 + n_batches):
    rows = [(names[i], T0 + b * 1000, float(i * 1_000_000 + b))
            for i in range(N_SERIES)]
    # one fresh series per batch: every flush has NEW index items, so
    # the mergeset/indexdb seams fire each cycle (not only on batch 0)
    rows.append((MetricName.from_dict({"__name__": "churn",
                                       "b": str(b)}),
                 T0 + b * 1000, float(b)))
    s.add_rows(rows)
    s.force_flush()   # durable: data part + index, fsync + rename + dirsync
    ackf.write(f"{b}\n")
    ackf.flush()
    os.fsync(ackf.fileno())
    if scenario == "merge" and b % 2 == 1:
        s.force_merge()
    elif scenario == "snapshot" and b % 2 == 1:
        s.create_snapshot()
    elif scenario == "retention" and b % 2 == 1:
        s.enforce_retention()
    elif scenario == "downsample" and b % 2 == 1:
        # fresh AGED samples each cycle so every run_downsample_cycle has
        # an uncovered (covered, cutoff] range to rewrite — the seam
        # between tier-part publication and the tier.json commit fires
        # on every odd batch, not only the first
        t_hi = T0 - 5 * 86_400_000 + b * 600_000
        s.add_rows([(MetricName.from_dict({"__name__": "agedm",
                                           "s": str(i)}),
                     t_hi - i * 300_000, float(i)) for i in range(3)])
        s.force_flush()
        s.run_downsample_cycle(now_ms=t_hi + 86_400_000 + 300_000)
s.close()
os._exit(0)
"""


def _t_base(scenario: str) -> int:
    # the retention scenario needs IN-retention (recent) sample times —
    # T0 is over a year old and would itself be retention-dropped; the
    # base is fixed per test run and shared child/verifier via argv
    if scenario == "retention":
        return (int(time.time() * 1000) - 2 * 86_400_000) // 1000 * 1000
    return T0


def _run_child(data_dir, ack_path, scenario, n_batches, faults="",
               t_base: int = T0):
    env = dict(os.environ)
    env["VM_FAULTS"] = faults
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-c", _CHILD_SRC, str(data_dir), str(ack_path),
         scenario, str(n_batches), str(t_base)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _read_acked(ack_path) -> list[int]:
    try:
        with open(ack_path) as f:
            return [int(x) for x in f.read().splitlines() if x]
    except FileNotFoundError:
        return []


def _assert_acked_present(storage: Storage, acked: list[int],
                          t_base: int = T0):
    """Every sample acked before the last successful flush must be
    present BYTE-EXACT after recovery (value encodes (series, batch))."""
    if not acked:
        return
    lo, hi = t_base, t_base + (max(acked) + 1) * 1000
    series = storage.search_series(NAME_FILTER, lo, hi)
    got: dict[tuple[int, int], float] = {}
    for sd in series:
        si = int(dict(sd.metric_name.labels)[b"s"])
        for ts, v in zip(sd.timestamps, sd.values):
            got[(si, int((ts - t_base) // 1000))] = float(v)
    for b in acked:
        for i in range(N_SERIES):
            v = got.get((i, b))
            assert v is not None, \
                f"acked sample (series {i}, batch {b}) LOST after recovery"
            assert v == float(i * 1_000_000 + b), \
                f"acked sample (series {i}, batch {b}) corrupted: {v}"


def _assert_disk_invariants(data_dir: str):
    """Post-recovery disk state: no orphan tmp dirs anywhere, every part
    dir inside a partition is either listed in parts.json or lives in
    the quarantine dir."""
    for root, dirs, _files in os.walk(data_dir):
        for n in dirs:
            assert not n.endswith(".tmp"), \
                f"orphan tmp dir survived recovery: {os.path.join(root, n)}"
    droot = os.path.join(data_dir, "data")
    if not os.path.isdir(droot):
        return
    for pname in os.listdir(droot):
        pdir = os.path.join(droot, pname)
        if not os.path.isdir(pdir):
            continue
        manifest = os.path.join(pdir, "parts.json")
        listed = []
        if os.path.exists(manifest):
            with open(manifest) as f:
                listed = json.load(f)["parts"]
        for n in os.listdir(pdir):
            if not os.path.isdir(os.path.join(pdir, n)):
                continue
            if n.startswith("ds_"):
                # downsampled tier dir: every part dir inside must be
                # listed in the tier's own manifest (tier.json) — the
                # crash seam between part publication and the manifest
                # commit must never leak an unlisted dir past recovery
                tdir = os.path.join(pdir, n)
                tman = os.path.join(tdir, "tier.json")
                tlisted = []
                if os.path.exists(tman):
                    with open(tman) as f:
                        tlisted = json.load(f)["parts"]
                for tn in os.listdir(tdir):
                    if not os.path.isdir(os.path.join(tdir, tn)):
                        continue
                    assert tn in tlisted, \
                        f"unlisted tier part survived recovery: {tdir}/{tn}"
                continue
            assert n in listed or n == "quarantine", \
                f"unlisted part dir survived recovery: {pdir}/{n}"


def _verify_recovery(data_dir, ack_path, retention=False,
                     t_base: int = T0):
    """Reopen the store and check every recovery invariant; returns the
    acked batch list for extra assertions."""
    acked = _read_acked(ack_path)
    kw = {"retention_ms": 40 * 86_400_000} if retention else {}
    s = Storage(str(data_dir), **kw)
    try:
        # crash injection never tears fsynced bytes: quarantine must stay
        # empty (it fires only when bytes are actually corrupt)
        assert s.quarantine_report() == [], s.quarantine_report()
        assert s.last_partial is False
        _assert_acked_present(s, acked, t_base)
    finally:
        s.close()
    _assert_disk_invariants(str(data_dir))
    return acked


# ---------------------------------------------------------------------------
# 1. torn-part matrix (tier-1)
# ---------------------------------------------------------------------------

def _build_store(tmp_path, n_batches=3):
    d = str(tmp_path / "store")
    s = Storage(d)
    names = [MetricName.from_dict({"__name__": "crashm", "s": str(i)})
             for i in range(N_SERIES)]
    for b in range(n_batches):
        s.add_rows([(names[i], T0 + b * 1000, float(i * 1_000_000 + b))
                    for i in range(N_SERIES)])
    s.force_flush()
    s.close()
    return d


def _find_data_part(d):
    droot = os.path.join(d, "data")
    for pname in sorted(os.listdir(droot)):
        pdir = os.path.join(droot, pname)
        if not os.path.isdir(pdir):
            continue
        with open(os.path.join(pdir, "parts.json")) as f:
            listed = json.load(f)["parts"]
        if listed:
            return os.path.join(pdir, listed[0])
    raise AssertionError("no file part found")


def _corrupt(path: str, mode: str):
    size = os.path.getsize(path)
    assert size > 0, f"{path} is empty; matrix needs real bytes"
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    else:  # bitflip
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0x10]))


@pytest.mark.parametrize("mode", ["truncate", "bitflip"])
@pytest.mark.parametrize("fname", ["timestamps.bin", "values.bin",
                                   "index.bin", "metaindex.bin",
                                   "metadata.json"])
def test_torn_part_is_quarantined(tmp_path, fname, mode):
    """A torn/bit-flipped part file is detected at open, the part moves
    to quarantine/, the counter ticks, and the store serves PARTIAL —
    never the old silent drop."""
    from victoriametrics_tpu.storage.partition import _PARTS_QUARANTINED
    d = _build_store(tmp_path)
    part = _find_data_part(d)
    _corrupt(os.path.join(part, fname), mode)
    before = _PARTS_QUARANTINED.get()
    s = Storage(d)
    try:
        rep = s.quarantine_report()
        assert len(rep) == 1 and rep[0]["store"] == "storage", rep
        assert os.path.isdir(rep[0]["path"])
        assert "quarantine" in rep[0]["path"]
        assert not os.path.exists(part), "corrupt part left in place"
        assert _PARTS_QUARANTINED.get() == before + 1
        # the loud-partial regression assert: results flag partial
        assert s.last_partial is True
        # the flushed rows lived in that one part: the query result is
        # missing them AND says so (the old behavior returned the same
        # empty result with partial=False — silent data loss)
        series = s.search_series(NAME_FILTER, T0, T0 + 100_000)
        assert series == []
        assert s.last_partial is True
    finally:
        s.close()
    # partiality survives a restart until the operator acts
    s2 = Storage(d)
    try:
        assert s2.last_partial is True
        assert s2.quarantine_report()
    finally:
        s2.close()


def test_torn_mergeset_part_is_quarantined(tmp_path):
    """Recovery parity: the indexdb's mergeset parts get the same
    verify-at-open + quarantine treatment as data parts."""
    d = _build_store(tmp_path)
    gdir = os.path.join(d, "indexdb", "global")
    part = next(n for n in sorted(os.listdir(gdir))
                if n.startswith("part_"))
    _corrupt(os.path.join(gdir, part, "items.bin"), "bitflip")
    s = Storage(d)
    try:
        rep = s.quarantine_report()
        assert [q["store"] for q in rep] == ["mergeset"], rep
        assert s.last_partial is True
    finally:
        s.close()


def test_quarantine_status_endpoint(tmp_path):
    """/api/v1/status/quarantine lists quarantined parts, and query
    responses over the same server carry isPartial=true."""
    from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
    from victoriametrics_tpu.httpapi.server import HTTPServer
    d = _build_store(tmp_path)
    _corrupt(os.path.join(_find_data_part(d), "values.bin"), "bitflip")
    s = Storage(d)
    srv = HTTPServer("127.0.0.1", 0)
    PrometheusAPI(s).register(srv, mode="select")
    srv.start()
    try:
        c = Client(srv.port)
        code, body = c.get("/api/v1/status/quarantine")
        assert code == 200
        data = json.loads(body)["data"]
        assert data["count"] == 1 and data["partial"] is True
        assert data["quarantined"][0]["store"] == "storage"
        # the regression assert at the HTTP surface: the query names the
        # loss instead of silently serving an empty complete result
        code, body = c.get("/api/v1/query", query="count(crashm)",
                           time=str((T0 + 30_000) // 1000))
        assert code == 200
        assert json.loads(body).get("isPartial") is True
    finally:
        srv.stop()
        s.close()


def test_cluster_quarantine_fanout(tmp_path):
    """The vmselect's /api/v1/status/quarantine is backed by a real RPC
    fan-out (quarantineReport_v1): storage-node quarantines surface at
    the select plane, tagged per node."""
    from victoriametrics_tpu.parallel.cluster_api import (
        ClusterStorage, StorageNodeClient, make_storage_handlers)
    from victoriametrics_tpu.parallel.rpc import HELLO_SELECT, RPCServer
    d = _build_store(tmp_path)
    _corrupt(os.path.join(_find_data_part(d), "index.bin"), "truncate")
    s = Storage(d)
    srv = RPCServer("127.0.0.1", 0, HELLO_SELECT,
                    make_storage_handlers(s))
    srv.start()
    node = StorageNodeClient("127.0.0.1", srv.port, srv.port)
    cs = ClusterStorage([node])
    try:
        rep = cs.quarantine_report()
        assert len(rep) == 1 and rep[0]["store"] == "storage"
        assert rep[0]["node"] == node.name
    finally:
        node.close()
        srv.stop()
        s.close()


def test_clean_store_reports_nothing(tmp_path):
    d = _build_store(tmp_path)
    s = Storage(d)
    try:
        assert s.quarantine_report() == []
        assert s.last_partial is False
        assert len(s.search_series(NAME_FILTER, T0, T0 + 100_000)) == \
            N_SERIES
    finally:
        s.close()


# ---------------------------------------------------------------------------
# 2. crashpoint matrix (tier-1): each armed seam, subprocess, clean reopen
# ---------------------------------------------------------------------------

_SEAMS = [
    ("part:finalize:pre_rename", "flush"),
    ("part:finalize:post_rename", "flush"),
    ("partition:parts_json:pre_replace", "flush"),
    ("merge:post_rename_pre_manifest", "merge"),
    ("downsample:post_rename_pre_manifest", "downsample"),
    ("mergeset:flush", "flush"),
    ("indexdb:rotate", "retention"),
    ("snapshot:mid", "snapshot"),
]


@pytest.mark.parametrize("seam,scenario", _SEAMS,
                         ids=[s for s, _ in _SEAMS])
def test_crashpoint_seam(tmp_path, seam, scenario):
    """kill -9 (os._exit at the armed seam) mid-lifecycle, then reopen:
    acked-before-flush data byte-exact, no tmp orphans, no silent part
    loss, no quarantine."""
    d = tmp_path / "store"
    ack = tmp_path / "acks"
    tb = _t_base(scenario)  # ONE base: child runs + verifier must agree
    # run 1, unfaulted: establish a durable acked baseline
    p = _run_child(d, ack, scenario, 2, t_base=tb)
    out, err = p.communicate(timeout=120)
    assert p.returncode == 0, err.decode()[-2000:]
    baseline = _read_acked(ack)
    assert baseline, "baseline run acked nothing"
    # run 2, armed: must die AT the seam (exit code 86)
    p = _run_child(d, ack, scenario, 50, faults=f"{seam}=crash",
                   t_base=tb)
    out, err = p.communicate(timeout=120)
    assert p.returncode == faultinject.CRASH_EXIT_CODE, \
        (p.returncode, err.decode()[-2000:])
    assert f"CRASH at {seam}" in err.decode()
    acked = _verify_recovery(d, ack, retention=(scenario == "retention"),
                             t_base=tb)
    assert set(baseline) <= set(acked)


# ---------------------------------------------------------------------------
# 3. randomized kill -9 storm (slow; tools/chaos.sh)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.crash
def test_kill9_randomized_matrix(tmp_path):
    """>= 20 SIGKILL cycles at randomized instants against ONE
    accumulating store (recovery-from-recovered-state compounds), with
    flush/merge/snapshot churn racing ingest.  Every cycle must reopen
    with zero invariant violations."""
    rng = np.random.default_rng(0xC0FFEE)
    d = tmp_path / "store"
    ack = tmp_path / "acks"
    cycles = 20
    for cyc in range(cycles):
        before = len(_read_acked(ack))
        p = _run_child(d, ack, "storm", 10_000)
        # wait until the storm makes at least one NEW durable ack, then
        # kill at a randomized instant inside the flush/merge/snapshot
        # churn — progress is guaranteed, the kill point is not
        deadline = time.time() + 20
        while len(_read_acked(ack)) <= before and time.time() < deadline:
            time.sleep(0.02)
        assert len(_read_acked(ack)) > before, \
            f"cycle {cyc}: no durable progress before the kill window"
        time.sleep(float(rng.uniform(0.0, 0.5)))
        p.send_signal(signal.SIGKILL)
        p.communicate(timeout=60)
        assert p.returncode == -signal.SIGKILL
        _verify_recovery(d, ack)
    assert len(_read_acked(ack)) >= cycles, \
        "the storm never made durable progress between kills"


# ---------------------------------------------------------------------------
# storage-side deadline enforcement (ROADMAP item 3 leftover)
# ---------------------------------------------------------------------------

class TestStorageDeadline:
    def test_local_abort_typed_and_counted(self, tmp_path):
        """An expired budget aborts the scan with the typed error and
        ticks vm_storage_deadline_aborts_total."""
        from victoriametrics_tpu.storage.storage import _DEADLINE_ABORTS
        d = _build_store(tmp_path)
        s = Storage(d)
        try:
            before = _DEADLINE_ABORTS.get()
            with pytest.raises(DeadlineExceededError):
                s.search_columns(NAME_FILTER, T0, T0 + 100_000,
                                 deadline=time.monotonic() - 0.001)
            assert _DEADLINE_ABORTS.get() == before + 1
            # no deadline => no budget machinery, full result
            assert s.search_columns(NAME_FILTER, T0,
                                    T0 + 100_000).n_series == N_SERIES
        finally:
            s.close()

    def test_rpc_budget_field_aborts_server_side(self, tmp_path):
        """The shipped budget_ms field alone (no client-side socket
        deadline) makes the storage handler abort mid-flight, within
        ~one check interval once the budget expires."""
        from victoriametrics_tpu.parallel.cluster_api import (
            _write_filters, make_storage_handlers)
        from victoriametrics_tpu.parallel.rpc import Reader, Writer
        d = _build_store(tmp_path)
        s = Storage(d)
        handlers = make_storage_handlers(s)
        w = Writer().u64(0).u64(0)          # tenant
        _write_filters(w, NAME_FILTER)
        w.i64(T0).i64(T0 + 100_000)
        w.u64(0)                            # trace flag
        w.u64(1)                            # budget: 1ms — expires at once
        faultinject.configure("storage:scan=delay:30")
        try:
            t0 = time.perf_counter()
            with pytest.raises(DeadlineExceededError):
                # streaming handlers build frames lazily; drain them
                list(handlers["searchColumns_v1"](Reader(w.payload())))
            took = time.perf_counter() - t0
            # one injected 30ms check interval + slack, NOT the full scan
            assert took < 2.0
        finally:
            faultinject.configure("")
            s.close()

    def test_wire_deadline_is_typed_and_never_marks_down(self):
        """A storage-side abort crosses the RPC boundary as a typed
        deadline error (vm:deadline marker -> RPCDeadlineError with
        waited=False) and the fan-out does NOT mark the node down."""
        from victoriametrics_tpu.parallel.cluster_api import (
            ClusterStorage, ClusterUnavailableError, StorageNodeClient)
        from victoriametrics_tpu.parallel.rpc import (HELLO_SELECT,
                                                      RPCDeadlineError,
                                                      RPCServer)

        def h_abort(r):
            raise DeadlineExceededError(
                "storage-side deadline exceeded: test")

        srv = RPCServer("127.0.0.1", 0, HELLO_SELECT,
                        {"searchColumns_v1": h_abort,
                         "search_v1": h_abort})
        srv.start()
        node = StorageNodeClient("127.0.0.1", srv.port, srv.port)
        try:
            with pytest.raises(RPCDeadlineError) as ei:
                node.search_columns(NAME_FILTER, T0, T0 + 1000)
            assert ei.value.waited is False
            assert "deadline" in str(ei.value)
            cs = ClusterStorage([node])
            with pytest.raises(ClusterUnavailableError):
                cs.search_columns(NAME_FILTER, T0, T0 + 1000)
            # the node did exactly what the budget asked: still healthy
            assert node.healthy, \
                "deadline abort wrongly marked the node down"
        finally:
            node.close()
            srv.stop()


# ---------------------------------------------------------------------------
# replica-aware partial accounting (satellite)
# ---------------------------------------------------------------------------

def test_rf_covered_failure_not_partial():
    """With RF=2 over two nodes, one failed node whose every hash range
    is covered by the surviving responder does NOT set partial;
    vm_partial_avoided_total ticks instead.  RF=1 keeps strict
    accounting."""
    from victoriametrics_tpu.parallel.cluster_api import (_PARTIAL_AVOIDED,
                                                          ClusterStorage)
    from victoriametrics_tpu.parallel.rpc import RPCError

    class FakeNode:
        def __init__(self, name, fail=False):
            self.name = name
            self.fail = fail
            self.down_until = 0.0
            self.marked = False

        @property
        def healthy(self):
            return True

        def mark_down(self, seconds=2.0):
            self.marked = True

        def label_names(self, *a, **k):
            if self.fail:
                raise RPCError("boom")
            return ["a", "b"]

    good, bad = FakeNode("n1"), FakeNode("n2", fail=True)
    cs = ClusterStorage([good, bad], replication_factor=2)
    cs.reset_partial()
    before = _PARTIAL_AVOIDED.get()
    assert cs.label_names() == ["a", "b"]
    assert cs.last_partial is False, \
        "RF-covered failure must not flag partial"
    assert _PARTIAL_AVOIDED.get() == before + 1
    assert bad.marked, "a genuinely failing node is still marked down"

    # RF=1: the same failure IS partial
    good2, bad2 = FakeNode("n1"), FakeNode("n2", fail=True)
    cs1 = ClusterStorage([good2, bad2], replication_factor=1)
    cs1.reset_partial()
    assert cs1.label_names() == ["a", "b"]
    assert cs1.last_partial is True


def test_rf_covered_delete_stays_partial():
    """Mutating fan-outs (deleteSeries) never claim replica coverage: a
    missed node means a missed tombstone."""
    from victoriametrics_tpu.parallel.cluster_api import ClusterStorage
    from victoriametrics_tpu.parallel.rpc import RPCError

    class FakeNode:
        def __init__(self, name, fail=False):
            self.name = name
            self.fail = fail
            self.down_until = 0.0

        @property
        def healthy(self):
            return True

        def mark_down(self, seconds=2.0):
            pass

        def delete_series(self, *a, **k):
            if self.fail:
                raise RPCError("boom")
            return 3

    cs = ClusterStorage([FakeNode("n1"), FakeNode("n2", fail=True)],
                        replication_factor=2)
    cs.reset_partial()
    assert cs.delete_series([]) == 3
    assert cs.last_partial is True
