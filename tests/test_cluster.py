"""Cluster tests (reference apptest/tests/{sharding,replication,
vmsingle_vmselect_rpc}_test.go): N vmstorage nodes with real TCP RPC on
localhost, vminsert sharding/replication/rerouting, vmselect scatter-gather
with partial results."""

import json

import numpy as np
import pytest

from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.httpapi.server import HTTPServer
from victoriametrics_tpu.parallel.cluster_api import (ClusterStorage,
                                                      PartialResultError,
                                                      StorageNodeClient,
                                                      make_storage_handlers)
from victoriametrics_tpu.parallel.consistenthash import ConsistentHash
from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT, HELLO_SELECT,
                                              RPCServer)
from victoriametrics_tpu.query.exec import exec_query
from victoriametrics_tpu.query.types import EvalConfig
from victoriametrics_tpu.storage.storage import Storage
from victoriametrics_tpu.storage.tag_filters import filters_from_dict

T0 = 1_753_700_000_000


class StorageNode:
    """One in-process vmstorage with real TCP RPC servers."""

    def __init__(self, path):
        self.storage = Storage(str(path))
        handlers = make_storage_handlers(self.storage)
        self.insert_srv = RPCServer("127.0.0.1", 0, HELLO_INSERT, handlers)
        self.select_srv = RPCServer("127.0.0.1", 0, HELLO_SELECT, handlers)
        self.insert_srv.start()
        self.select_srv.start()

    def client(self):
        return StorageNodeClient("127.0.0.1", self.insert_srv.port,
                                 self.select_srv.port)

    def stop(self):
        self.insert_srv.stop()
        self.select_srv.stop()
        self.storage.close()


@pytest.fixture()
def nodes3(tmp_path):
    nodes = [StorageNode(tmp_path / f"n{i}") for i in range(3)]
    yield nodes
    for n in nodes:
        try:
            n.stop()
        except Exception:
            pass


def seed_rows(n_series=30, n_samples=10):
    rows = []
    for i in range(n_series):
        for j in range(n_samples):
            rows.append(({"__name__": "cm", "idx": str(i)},
                        T0 + j * 15_000, float(i * 100 + j)))
    return rows


class TestConsistentHash:
    def test_stable_and_balanced(self):
        ch = ConsistentHash(["a", "b", "c"])
        keys = [f"key{i}".encode() for i in range(3000)]
        place = [ch.nodes_for_key(k, 1)[0] for k in keys]
        # stable
        assert place == [ch.nodes_for_key(k, 1)[0] for k in keys]
        # balanced within 30%
        counts = [place.count(i) for i in range(3)]
        assert min(counts) > 1000 * 0.7
        # replication gives distinct nodes
        reps = ch.nodes_for_key(b"x", 3)
        assert len(set(reps)) == 3

    def test_exclusion_reroutes_minimally(self):
        ch = ConsistentHash(["a", "b", "c"])
        keys = [f"key{i}".encode() for i in range(1000)]
        base = [ch.nodes_for_key(k, 1)[0] for k in keys]
        moved = 0
        for k, b in zip(keys, base):
            n = ch.nodes_for_key(k, 1, {2})[0]
            if b != 2 and n != b:
                moved += 1
        assert moved == 0  # only keys on the excluded node move


class TestClusterWriteRead:
    def test_sharding_distributes_series(self, nodes3):
        cluster = ClusterStorage([n.client() for n in nodes3])
        cluster.add_rows(seed_rows())
        for n in nodes3:
            n.storage.force_flush()
        per_node = [n.storage.series_count() for n in nodes3]
        assert sum(per_node) == 30       # every series exactly once (RF=1)
        assert all(c > 0 for c in per_node)  # spread across all nodes
        res = cluster.search_series(
            filters_from_dict({"__name__": "cm"}), T0, T0 + 10_000_000)
        assert len(res) == 30
        assert all(r.timestamps.size == 10 for r in res)
        cluster.close()

    def test_replication_and_dedup(self, nodes3):
        cluster = ClusterStorage([n.client() for n in nodes3],
                                 replication_factor=2)
        cluster.add_rows(seed_rows())
        per_node = [n.storage.series_count() for n in nodes3]
        assert sum(per_node) == 60       # each series on exactly 2 nodes
        res = cluster.search_series(
            filters_from_dict({"__name__": "cm"}), T0, T0 + 10_000_000)
        assert len(res) == 30            # replica dedup at read time
        assert all(r.timestamps.size == 10 for r in res)
        cluster.close()

    def test_node_failure_rf2_full_results(self, nodes3):
        from victoriametrics_tpu.parallel.cluster_api import \
            _PARTIAL_AVOIDED
        cluster = ClusterStorage([n.client() for n in nodes3],
                                 replication_factor=2)
        cluster.add_rows(seed_rows())
        nodes3[0].stop()
        before = _PARTIAL_AVOIDED.get()
        res = cluster.search_series(
            filters_from_dict({"__name__": "cm"}), T0, T0 + 10_000_000)
        # one failed node out of RF=2: every hash range is covered by a
        # surviving responder, so the COMPLETE result is not partial —
        # the failure is accounted in vm_partial_avoided_total instead
        assert not cluster.last_partial
        assert _PARTIAL_AVOIDED.get() > before
        assert len(res) == 30            # RF=2 kept every series
        cluster.close()

    def test_node_failure_rf1_partial(self, nodes3):
        cluster = ClusterStorage([n.client() for n in nodes3])
        cluster.add_rows(seed_rows())
        nodes3[1].stop()
        res = cluster.search_series(
            filters_from_dict({"__name__": "cm"}), T0, T0 + 10_000_000)
        assert cluster.last_partial
        assert 0 < len(res) < 30
        cluster.close()

    def test_deny_partial_response(self, nodes3):
        cluster = ClusterStorage([n.client() for n in nodes3],
                                 deny_partial_response=True)
        cluster.add_rows(seed_rows())
        nodes3[2].stop()
        with pytest.raises(PartialResultError):
            cluster.search_series(filters_from_dict({"__name__": "cm"}),
                                  T0, T0 + 10_000_000)
        cluster.close()

    def test_write_rerouting_on_dead_node(self, nodes3):
        clients = [n.client() for n in nodes3]
        cluster = ClusterStorage(clients)
        nodes3[0].stop()
        cluster.add_rows(seed_rows())    # must not raise
        assert cluster.reroutes >= 0
        alive = [nodes3[1], nodes3[2]]
        total = sum(n.storage.series_count() for n in alive)
        assert total == 30               # everything landed on healthy nodes
        cluster.close()

    def test_label_apis_and_delete(self, nodes3):
        cluster = ClusterStorage([n.client() for n in nodes3])
        cluster.add_rows(seed_rows(n_series=6))
        assert cluster.label_names() == ["__name__", "idx"]
        assert cluster.label_values("idx") == [str(i) for i in range(6)]
        assert cluster.series_count() == 6
        st = cluster.tsdb_status()
        assert st["totalSeries"] == 6
        assert cluster.delete_series(
            filters_from_dict({"idx": "0"})) == 1
        res = cluster.search_series(filters_from_dict({"__name__": "cm"}),
                                    T0, T0 + 10_000_000)
        assert len(res) == 5
        cluster.close()


class TestClusterQueryEngine:
    def test_metricsql_over_cluster(self, nodes3):
        """vmselect semantics: the full query engine over ClusterStorage."""
        cluster = ClusterStorage([n.client() for n in nodes3],
                                 replication_factor=2)
        rows = []
        for i in range(12):
            for j in range(41):
                rows.append(({"__name__": "reqs", "inst": f"h{i % 4}",
                              "cpu": str(i)}, T0 + j * 15_000,
                             float(10 * j)))  # rate 2/3 per series
        cluster.add_rows(rows)
        ec = EvalConfig(start=T0 + 300_000, end=T0 + 600_000, step=60_000,
                        storage=cluster)
        out = exec_query(ec, "sum by (inst) (rate(reqs[5m]))")
        assert len(out) == 4
        for ts in out:
            np.testing.assert_allclose(ts.values, 3 * 10 / 15, rtol=1e-9)
        cluster.close()

    def test_http_cluster_roundtrip(self, nodes3, tmp_path):
        """vminsert + vmselect HTTP front-ends over the same nodes."""
        from tests.apptest_helpers import Client
        insert_cluster = ClusterStorage([n.client() for n in nodes3])
        select_cluster = ClusterStorage([n.client() for n in nodes3])
        isrv = HTTPServer("127.0.0.1", 0)
        PrometheusAPI(insert_cluster).register(isrv, mode="insert")
        isrv.start()
        ssrv = HTTPServer("127.0.0.1", 0)
        PrometheusAPI(select_cluster).register(ssrv, mode="select")
        ssrv.start()
        ic, sc = Client(isrv.port), Client(ssrv.port)
        line = json.dumps({"metric": {"__name__": "hm", "a": "b"},
                           "values": [4.5], "timestamps": [T0]})
        code, _ = ic.post("/api/v1/import", line.encode())
        assert code == 204
        res = sc.query("hm", T0 / 1e3 + 10)
        assert res["data"]["result"][0]["value"][1] == "4.5"
        assert res["isPartial"] is False
        # insert node must not serve queries, select node must not ingest
        code, _ = ic.get("/api/v1/query", query="hm")
        assert code == 404
        code, _ = sc.post("/api/v1/import", line.encode())
        assert code == 404
        isrv.stop()
        ssrv.stop()
        insert_cluster.close()
        select_cluster.close()


class TestRPCFailureHandling:
    def test_no_deadlock_on_dead_node_concurrent_calls(self, tmp_path):
        """Regression: RPCClient.close() under the connection lock
        self-deadlocked when a transport error hit mid-call, hanging every
        later caller (found by kill -9 probing a real cluster)."""
        import threading
        node = StorageNode(tmp_path / "n")
        client = node.client()
        client.write_rows([(b"m", T0, 1.0)])  # establish connections
        node.stop()  # sockets die under the client
        errs, done = [], []

        def caller():
            try:
                client.search_series(
                    filters_from_dict({"__name__": "m"}), T0, T0 + 1000)
            except Exception as e:
                errs.append(type(e).__name__)
            done.append(1)

        ths = [threading.Thread(target=caller, daemon=True) for _ in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=15)
        assert len(done) == 3, "callers deadlocked on the connection lock"
        assert len(errs) == 3  # all failed cleanly, none hung
        client.close()

    def test_stale_connection_retries_after_node_restart(self, tmp_path):
        """A kept-alive connection to a restarted node must transparently
        reconnect (write lands in the send buffer; failure shows at read)."""
        node = StorageNode(tmp_path / "n")
        insert_port = node.insert_srv.port
        select_port = node.select_srv.port
        client = StorageNodeClient("127.0.0.1", insert_port, select_port)
        client.write_rows([(b"m1", T0, 1.0)])
        node.insert_srv.stop()
        node.select_srv.stop()
        # restart RPC servers on the same ports over the same storage
        from victoriametrics_tpu.parallel.rpc import RPCServer
        handlers = make_storage_handlers(node.storage)
        node.insert_srv = RPCServer("127.0.0.1", insert_port, HELLO_INSERT,
                                    handlers)
        node.select_srv = RPCServer("127.0.0.1", select_port, HELLO_SELECT,
                                    handlers)
        node.insert_srv.start()
        node.select_srv.start()
        client.write_rows([(b"m2", T0, 2.0)])  # must not raise
        assert node.storage.series_count() >= 1
        client.close()
        node.stop()


class TestMultilevel:
    def test_vmselect_over_vmselect(self, nodes3, tmp_path):
        """Multilevel federation: an upper vmselect uses a lower vmselect
        (exposing the cluster-native RPC) as its only storage node."""
        lower = ClusterStorage([n.client() for n in nodes3])
        lower.add_rows(seed_rows(n_series=8))
        lower_rpc = RPCServer("127.0.0.1", 0, HELLO_SELECT,
                              make_storage_handlers(lower))
        lower_rpc.start()
        upper_node = StorageNodeClient("127.0.0.1", lower_rpc.port,
                                       lower_rpc.port)
        upper = ClusterStorage([upper_node])
        res = upper.search_series(filters_from_dict({"__name__": "cm"}),
                                  T0, T0 + 10_000_000)
        assert len(res) == 8
        assert upper.label_values("idx") == [str(i) for i in range(8)]
        ec = EvalConfig(start=T0, end=T0 + 120_000, step=60_000,
                        storage=upper)
        out = exec_query(ec, "count(cm)")
        assert out[0].values[-1] == 8.0
        upper.close()
        lower_rpc.stop()
        lower.close()


class TestClusterMultitenancy:
    def test_tenant_isolation_across_nodes(self, nodes3):
        cs = ClusterStorage([n.client() for n in nodes3],
                            replication_factor=1)
        t1, t2 = (5, 0), (5, 1)
        cs.add_rows([({"__name__": "mt", "i": str(i)}, T0, float(i))
                     for i in range(20)], tenant=t1)
        cs.add_rows([({"__name__": "mt", "i": str(i)}, T0, float(i + 100))
                     for i in range(10)], tenant=t2)
        f = filters_from_dict({"__name__": "mt"})
        r1 = cs.search_series(f, T0 - 1000, T0 + 1000, tenant=t1)
        r2 = cs.search_series(f, T0 - 1000, T0 + 1000, tenant=t2)
        assert len(r1) == 20 and len(r2) == 10
        assert {float(s.values[0]) for s in r2} == {float(i + 100)
                                                    for i in range(10)}
        assert cs.search_series(f, T0 - 1000, T0 + 1000) == []
        assert set(cs.tenants()) >= {t1, t2}
        assert cs.series_count(tenant=t1) == 20
        # tenant-scoped delete
        assert cs.delete_series(f, tenant=t2) == 10
        assert cs.search_series(f, T0 - 1000, T0 + 1000, tenant=t2) == []
        assert len(cs.search_series(f, T0 - 1000, T0 + 1000, tenant=t1)) == 20
        cs.close()
