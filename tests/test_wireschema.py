"""Wire-schema ratchet tests (devtools/wireschema.py).

The extractor derives field-order/type/tolerance schemas from the
marshal/unmarshal code and ratchets them against the committed
``devtools/wire_schema.lock.json``.  These tests pin the contract by
MUTATION: each compatibility-break class is injected into the real
source (via the ``sources`` override — nothing on disk changes) and
must fail with the schema exit code, while an additive trailing
extension must pass once the lockfile is regenerated."""

import json
import os

import pytest

from victoriametrics_tpu.devtools import wireschema as ws

CA = "victoriametrics_tpu/parallel/cluster_api.py"
ST = "victoriametrics_tpu/storage/storage.py"


@pytest.fixture(scope="module")
def srcs():
    return ws._load_sources()


def _mutate(src: str, old: str, new: str, count: int = -1) -> str:
    assert old in src, f"mutation anchor vanished: {old[:60]!r}"
    return src.replace(old, new) if count < 0 else \
        src.replace(old, new, count)


# -- lockfile round-trip ----------------------------------------------------

def test_lockfile_matches_tree():
    """The committed lockfile IS the current extraction (round-trip)."""
    code, msgs, cur = ws.check()
    assert code == ws.EXIT_OK, "\n".join(msgs)
    with open(ws.LOCKFILE, encoding="utf-8") as fh:
        lock = json.load(fh)
    assert lock == cur


def test_lockfile_covers_every_rpc_method_and_format():
    with open(ws.LOCKFILE, encoding="utf-8") as fh:
        lock = json.load(fh)
    # every *_vN method in the live dispatch dict is locked
    import ast
    with open(os.path.join(ws.REPO_ROOT, CA), encoding="utf-8") as fh:
        dispatch = ws._handler_map(ast.parse(fh.read()))
    assert dispatch, "dispatch dict not found?"
    missing = sorted(set(dispatch) - set(lock["rpc"]))
    assert missing == [], f"RPC methods missing from lockfile: {missing}"
    for fmt in ("metadata.json", "parts.json", "ring_exempt.bin",
                "adopted_mid.json", "ring_config", "health_v1_report",
                "incident_record"):
        assert fmt in lock["formats"], fmt
    # the four search_v1 trailing generations are all tracked tolerant
    req = lock["rpc"]["search_v1"]["request"]
    trailing = [f for f in req if f.get("optional")]
    assert len(trailing) >= 4, req


# -- breaking mutations -> schema exit code ---------------------------------

def test_reordered_frame_field_is_breaking(srcs):
    """Moving the flags u64 ahead of the key/value bytes in the filter
    record reorders every request that carries filters."""
    mut = _mutate(
        srcs[CA],
        "        key = r.bytes_()\n"
        "        value = r.bytes_()\n"
        "        flags = r.u64()\n",
        "        flags = r.u64()\n"
        "        key = r.bytes_()\n"
        "        value = r.bytes_()\n")
    code, msgs, _ = ws.check(sources={CA: mut})
    assert code == ws.EXIT_BREAKING, msgs
    assert any("field" in m for m in msgs)


def test_dropped_trailing_tolerance_is_breaking(srcs):
    """Removing the ``if r.remaining`` guard on the trace flag makes a
    trailing field required — every pre-trace peer's frame misparses."""
    mut = _mutate(srcs[CA],
                  "bool(r.u64()) if r.remaining else False",
                  "bool(r.u64())")
    code, msgs, _ = ws.check(sources={CA: mut})
    assert code == ws.EXIT_BREAKING, msgs
    assert any("tolerance" in m for m in msgs)


def test_unconsumed_client_field_is_breaking(srcs):
    """A client writing a field the server handler never reads is a
    silent no-op feature — the pairing check calls it breaking."""
    mut = _mutate(srcs[CA],
                  'self.insert.call("writeRows_v1", w)',
                  'w.u64(7)\n        self.insert.call("writeRows_v1", w)')
    code, msgs, _ = ws.check(sources={CA: mut})
    assert code == ws.EXIT_BREAKING, msgs
    assert any("never consumes" in m for m in msgs)


def test_removed_trailing_read_is_breaking(srcs):
    mut = _mutate(srcs[CA], "ring_b = r.bytes_()", "ring_b = b''")
    code, msgs, _ = ws.check(sources={CA: mut})
    assert code == ws.EXIT_BREAKING, msgs


def test_torn_tail_tolerance_loss_is_breaking(srcs):
    """ring_exempt.bin is append-mode; a reader that stops tolerating a
    torn final record bricks the open after a crashed append."""
    mut = _mutate(
        srcs[ST],
        "        off = 0\n"
        "        try:\n"
        "            while off < len(data):\n"
        "                n, off = unmarshal_varuint64(data, off)\n"
        "                if off + n > len(data):\n"
        "                    break  # torn tail append: keep the "
        "complete prefix\n"
        "                self._ring_exempt.add(data[off:off + n])\n"
        "                off += n\n"
        "        except (ValueError, IndexError):\n"
        "            pass  # torn record: the loaded prefix still serves",
        "        off = 0\n"
        "        while off < len(data):\n"
        "            n, off = unmarshal_varuint64(data, off)\n"
        "            self._ring_exempt.add(data[off:off + n])\n"
        "            off += n")
    code, msgs, _ = ws.check(sources={ST: mut})
    assert code == ws.EXIT_BREAKING, msgs
    assert any("torn-tail" in m for m in msgs)


def test_renamed_json_key_is_breaking(srcs):
    """Renaming the reader's key orphans the writer's — old files stop
    being readable and new writes stop being read."""
    mut = _mutate(srcs[ST], 'int(_json.load(f)["max"])',
                  'int(_json.load(f)["maxid"])')
    code, msgs, _ = ws.check(sources={ST: mut})
    assert code == ws.EXIT_BREAKING, msgs


# -- PR-17 surfaces: health_v1 report + incident record ---------------------

SL = "victoriametrics_tpu/query/sloplane.py"


def test_health_and_incident_formats_are_locked():
    """The health_v1 response body and the persisted incident record are
    under the ratchet, with the keys the repo itself depends on."""
    with open(ws.LOCKFILE, encoding="utf-8") as fh:
        lock = json.load(fh)
    health = lock["formats"]["health_v1_report"]
    assert health["external_readers"] is True
    for k in ("status", "verdict", "reasons", "nodes", "ring", "node"):
        assert k in health["writer_keys"], k
    # the roll-up must TOLERATE, never require, what an old node omits
    assert health["reader_required"] == []
    assert "verdict" in health["reader_tolerated"]
    assert "reasons" in health["reader_tolerated"]
    inc = lock["formats"]["incident_record"]
    assert inc["reader_required"] == ["id", "slo"]
    for k in ("severity", "burn", "flightCaptureId", "profile",
              "topQueries", "tenantUsage", "health"):
        assert k in inc["writer_keys"], k


def test_incident_required_key_removal_is_breaking(srcs):
    """Dropping ``slo`` from the frozen record orphans the ring's own
    required read — pairing catches it before the lockfile diff."""
    mut = _mutate(srcs[SL], '"slo": spec.name, ', '')
    code, msgs, _ = ws.check(sources={SL: mut})
    assert code == ws.EXIT_BREAKING, msgs
    assert any("reader requires" in m for m in msgs), msgs


def test_incident_reader_new_requirement_is_breaking(srcs):
    """A summary projection that starts REQUIRING a key old records may
    lack (pre-upgrade incidents still in the ring) is breaking."""
    mut = _mutate(srcs[SL], '"burn": rec.get("burn"),',
                  '"burn": rec["burn"],', count=1)
    code, msgs, _ = ws.check(sources={SL: mut})
    assert code == ws.EXIT_BREAKING, msgs
    assert any("REQUIRES" in m for m in msgs), msgs


def test_health_new_writer_key_is_additive(srcs, tmp_path):
    """external_readers: a new health key with no in-repo reader is NOT
    a dead-key pairing failure (dashboards read it) — just additive
    drift until the lockfile is regenerated."""
    mut = _mutate(srcs[SL], '"status": "success",',
                  '"status": "success",\n        "buildId": 1,')
    code, msgs, cur = ws.check(sources={SL: mut})
    assert code == ws.EXIT_ADDITIVE, msgs
    assert any("buildId" in m for m in msgs), msgs
    lockfile = str(tmp_path / "wire_schema.lock.json")
    ws.write_lockfile(cur, lockfile)
    code, msgs, _ = ws.check(sources={SL: mut}, lockfile=lockfile)
    assert code == ws.EXIT_OK, msgs


# -- additive extension: drift until --update-schema, then clean ------------

def test_additive_trailing_field_regenerates_clean(srcs, tmp_path):
    mut = _mutate(
        srcs[CA],
        "flags = r.u64() if r.remaining else 0",
        "flags = r.u64() if r.remaining else 0\n"
        "        xtra = r.u64() if r.remaining else 0",
        count=1)
    # against the committed lockfile: drift, NOT a break
    code, msgs, cur = ws.check(sources={CA: mut})
    assert code == ws.EXIT_ADDITIVE, msgs
    assert all("BREAKING" not in m for m in msgs)
    # regenerate (what --update-schema does), re-check: clean
    lockfile = str(tmp_path / "wire_schema.lock.json")
    ws.write_lockfile(cur, lockfile)
    code, msgs, _ = ws.check(sources={CA: mut}, lockfile=lockfile)
    assert code == ws.EXIT_OK, msgs


def test_update_schema_refuses_breaking_without_allow(srcs, tmp_path,
                                                     monkeypatch):
    """--update-schema must not quietly lock in a compatibility break."""
    mut = _mutate(srcs[CA],
                  "bool(r.u64()) if r.remaining else False",
                  "bool(r.u64())")
    # check() is source-injected; main() reads disk, so drive the same
    # decision through check + the CLI's refusal branch
    code, _msgs, cur = ws.check(sources={CA: mut})
    assert code == ws.EXIT_BREAKING
    # the lockfile write path itself stays available for --allow-breaking
    lockfile = str(tmp_path / "lock.json")
    ws.write_lockfile(cur, lockfile)
    code2, msgs2, _ = ws.check(sources={CA: mut}, lockfile=lockfile)
    assert code2 == ws.EXIT_OK, msgs2


def test_cli_exit_codes_are_distinct():
    """4 (breaking) and 2 (additive drift) don't collide with lint's
    1 (new findings) / 3 (stale baseline)."""
    assert ws.EXIT_BREAKING == 4
    assert ws.EXIT_ADDITIVE == 2
    assert len({0, 1, 2, 3, ws.EXIT_BREAKING}) == 5
