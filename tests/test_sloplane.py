"""SLO plane unit/integration tests: window parsing, fold math, the
flat-in-SLO-count eval invariant (counter-asserted), the incident
open/resolve lifecycle with linked diagnosis surfaces, and the health
roll-up including old-node health_v1 tolerance."""

import time

import pytest

from victoriametrics_tpu.httpapi.prometheus_api import PrometheusAPI
from victoriametrics_tpu.query import sloplane
from victoriametrics_tpu.query.sloplane import (IncidentRing, SLOEngine,
                                                SLOSpec, default_specs,
                                                latency_fold,
                                                parse_windows, ratio_fold)
from victoriametrics_tpu.storage.storage import Storage

T0_MS = int(time.time() * 1e3)


class TestParseWindows:
    def test_default(self):
        assert parse_windows(None) == [("5m", "1h", 14.4),
                                       ("30m", "6h", 6.0)]

    def test_custom(self):
        assert parse_windows("5s:15s:5") == [("5s", "15s", 5.0)]

    def test_garbage_falls_back(self):
        assert parse_windows("nope,also:bad") == parse_windows(
            sloplane.DEFAULT_WINDOWS)
        assert parse_windows("a:b:notafloat") == parse_windows(
            sloplane.DEFAULT_WINDOWS)


class TestFolds:
    def test_ratio_fold(self):
        vals = {"bad": [{"value": 3.0}, {"value": 2.0}],
                "total": [{"value": 100.0}]}
        assert ratio_fold(vals) == (5.0, 100.0)
        assert ratio_fold({}) == (0.0, 0.0)

    def test_latency_fold_buckets(self):
        fold = latency_fold(1.0)
        vals = {
            "total": [{"value": 100.0}],
            "buckets": [
                {"metric": {"vmrange": "8.799e-01...1.000e+00"},
                 "value": 90.0},                      # good: <= 1s
                {"metric": {"vmrange": "1.000e+00...1.136e+00"},
                 "value": 10.0},                      # bad: > 1s
                {"metric": {"vmrange": "garbage"}, "value": 5.0},
            ],
        }
        bad, total = fold(vals)
        assert (bad, total) == (10.0, 100.0)

    def test_latency_fold_clamps_drift(self):
        # bucket sums past _count (non-atomic registry snapshot)
        fold = latency_fold(1.0)
        vals = {"total": [{"value": 10.0}],
                "buckets": [{"metric":
                             {"vmrange": "0...1.000e-09"},
                             "value": 12.0}]}
        assert fold(vals) == (0.0, 10.0)


@pytest.fixture()
def api(tmp_path):
    s = Storage(str(tmp_path / "data"))
    a = PrometheusAPI(s)
    try:
        yield a
    finally:
        s.close()


def _counter_rows(name: str, points):
    return [({"__name__": name, "job": "t"}, ts, v) for ts, v in points]


def test_flat_in_slo_count_counter_asserted(api):
    """The acceptance invariant: adding an objective over an already-
    watched indicator adds ZERO expression evals per round — asserted
    on vm_slo_evals_total itself."""
    windows = parse_windows("5m:1h:14.4,30m:6h:6")
    e1 = SLOEngine(api, windows=windows, interval_s=0.01, period="24h")
    before = sloplane._EVALS.get()
    assert e1.maybe_eval(force=True)
    n1 = sloplane._EVALS.get() - before
    assert n1 == e1.exprs_last_round > 0

    # a fifth objective duplicating the availability indicator
    specs = default_specs()
    specs.append(SLOSpec("dup-availability", 99.5,
                         dict(specs[0].exprs)))
    e2 = SLOEngine(api, specs=specs, windows=windows, interval_s=0.01,
                   period="24h")
    before = sloplane._EVALS.get()
    assert e2.maybe_eval(force=True)
    n2 = sloplane._EVALS.get() - before
    assert n2 == n1, (n1, n2)
    # ...and the duplicate objective is still independently reported
    assert {s["slo"] for s in e2.status()["slos"]} == {
        sp.name for sp in specs}


def test_interval_gating(api):
    eng = SLOEngine(api, specs=[], windows=parse_windows("5s:15s:5"),
                    interval_s=3600, period="1m")
    assert eng.maybe_eval(now_ms=T0_MS) is True
    assert eng.maybe_eval(now_ms=T0_MS + 1000) is False     # gated
    assert eng.maybe_eval(now_ms=T0_MS + 1000, force=True) is True
    assert eng.maybe_eval(now_ms=T0_MS + 3601 * 1000) is True


def test_burn_incident_lifecycle_and_diagnosis(api):
    """Synthetic indicator: 30% error ratio -> burn 30x over a 1%
    budget -> page fires, an incident freezes every diagnosis surface;
    an eval with empty windows resolves it; gauges track throughout."""
    s = api.storage
    spec = SLOSpec(
        "unit-avail", 99.0,
        {"bad": "sum(increase(unit_bad_total[{w}]))",
         "total": "sum(increase(unit_total_total[{w}]))"},
        description="unit test objective")
    # counters sampled every 2s over 10s: bad 0->30, total 0->100
    pts_bad = [(T0_MS - 10_000 + i * 2_000, 3.0 * i) for i in range(6)]
    pts_total = [(T0_MS - 10_000 + i * 2_000, 10.0 * i)
                 for i in range(6)]
    s.add_rows(_counter_rows("unit_bad_total", pts_bad) +
               _counter_rows("unit_total_total", pts_total))
    s.force_flush()

    eng = SLOEngine(api, specs=[spec],
                    windows=parse_windows("5s:10s:2"),
                    interval_s=0.01, period="1m")
    eng.maybe_eval(now_ms=T0_MS, force=True)
    st = eng.status()["slos"][0]
    assert st["firing"] and st["severity"] == "page"
    # burn math: 30% ratio over a 1% budget = 30x (windowed increase
    # wobbles at the edges; the order of magnitude is the contract)
    assert 10 < st["burn"]["10s"] < 50, st["burn"]
    assert st["openIncidentId"] is not None
    assert st["budgetRemaining"] == 0.0  # period window burned through

    rec = eng.incidents.get(st["openIncidentId"])
    assert rec["slo"] == "unit-avail" and rec["resolvedMs"] is None
    # every diagnosis surface linked (flightrec + profiler are on by
    # default in-process)
    assert rec["flightCaptureId"] is not None
    assert rec["profile"] is not None and "stacks" in rec["profile"]
    assert rec["health"] is not None
    assert rec["health"]["verdict"] == "critical"   # page -> critical
    assert any(r["code"] == "slo_burn" and r["slo"] == "unit-avail"
               for r in rec["health"]["reasons"])

    # exported gauges follow the state
    from victoriametrics_tpu.utils import metrics as metricslib
    g = metricslib.REGISTRY._metrics[metricslib.format_name(
        "vm_slo_burn_rate", {"slo": "unit-avail", "window": "10s"})]
    assert g.get() == st["burn"]["10s"]

    # ten minutes later every window is empty -> ratio 0 -> resolved
    eng.maybe_eval(now_ms=T0_MS + 600_000, force=True)
    st = eng.status()["slos"][0]
    assert not st["firing"] and st["openIncidentId"] is None
    assert st["budgetRemaining"] == 1.0
    rec = eng.incidents.get(rec["id"])
    assert rec["resolvedMs"] is not None
    # the summary listing reflects the closed incident
    listed = eng.incidents.list()
    assert listed[0]["id"] == rec["id"]
    assert listed[0]["resolvedMs"] == rec["resolvedMs"]
    assert listed[0]["hasProfile"] is True


def test_total_on_dead_shard_still_burns(api):
    """The chaos fold rule: when the total-series shard is unreadable
    (total<=0) but bad events exist, the ratio reads 1.0 — a dark
    denominator must not mask a live error signal."""
    s = api.storage
    pts = [(T0_MS - 8_000 + i * 2_000, 2.0 * i) for i in range(5)]
    s.add_rows(_counter_rows("orphan_bad_total", pts))
    s.force_flush()
    spec = SLOSpec(
        "orphan", 99.0,
        {"bad": "sum(increase(orphan_bad_total[{w}]))",
         "total": "sum(increase(orphan_total_total[{w}]))"})
    eng = SLOEngine(api, specs=[spec],
                    windows=parse_windows("5s:10s:2"),
                    interval_s=0.01, period="1m")
    eng.maybe_eval(now_ms=T0_MS, force=True)
    st = eng.status()["slos"][0]
    assert st["firing"], st
    assert st["burn"]["10s"] == pytest.approx(1.0 / spec.budget)


def test_incident_ring_bounded():
    ring = IncidentRing(2)
    for i in range(3):
        ring.open({"slo": f"s{i}", "startedMs": i, "resolvedMs": None})
    assert [r["slo"] for r in ring.list()] == ["s2", "s1"]
    assert ring.get(1) is None          # evicted
    assert ring.get(3)["slo"] == "s2"
    assert ring.resolve("s0", 9) is None   # evicted: nothing to resolve


def test_local_health_reasons():
    class Quarantined:
        def quarantine_report(self):
            return [{"part": "x"}]
    h = sloplane.local_health(storage=Quarantined(), role="vmstorage")
    assert h["verdict"] == "degraded"
    assert [r["code"] for r in h["reasons"]] == ["quarantined_parts"]
    assert h["stats"]["quarantinedParts"] == 1
    assert h["role"] == "vmstorage" and h["uptimeSeconds"] >= 0

    class ReadOnly:
        readonly = True
    h = sloplane.local_health(storage=ReadOnly())
    assert any(r["code"] == "readonly" for r in h["reasons"])

    h = sloplane.local_health()
    assert h["verdict"] == "ok" and h["reasons"] == []


def test_cluster_health_tolerates_old_nodes(tmp_path):
    """A pre-upgrade vmstorage without health_v1 answers 'unknown
    rpc method'; the roll-up treats it as verdict=unknown, NOT as a
    degradation — mixed-version clusters stay green."""
    from victoriametrics_tpu.parallel.cluster_api import (
        ClusterStorage, StorageNodeClient, make_storage_handlers)
    from victoriametrics_tpu.parallel.rpc import (HELLO_INSERT,
                                                  HELLO_SELECT, RPCServer)
    storages = [Storage(str(tmp_path / f"n{i}")) for i in range(2)]
    servers = []
    try:
        clients = []
        for i, st in enumerate(storages):
            h = make_storage_handlers(st)
            if i == 1:
                del h["health_v1"]      # the "old binary" node
            ins = RPCServer("127.0.0.1", 0, HELLO_INSERT, h)
            sel = RPCServer("127.0.0.1", 0, HELLO_SELECT, h)
            ins.start()
            sel.start()
            servers += [ins, sel]
            clients.append(
                StorageNodeClient("127.0.0.1", ins.port, sel.port))
        cluster = ClusterStorage(clients)
        # direct client: modern node reports, old node returns None
        assert clients[0].health()["verdict"] in ("ok", "degraded",
                                                  "critical")
        assert clients[0].health()["role"] == "vmstorage"
        assert clients[1].health() is None
        reports = cluster.health_report()
        by_node = {r["node"]: r for r in reports}
        assert by_node[clients[0].name]["verdict"] in (
            "ok", "degraded", "critical")
        assert by_node[clients[1].name]["verdict"] == "unknown"
        # the roll-up: both nodes up, old node is NOT a reason
        h = sloplane.cluster_health(cluster, role="vmselect")
        assert h["verdict"] == "ok", h["reasons"]
        assert {n["name"] for n in h["nodes"]} == \
            {c.name for c in clients}
        # ring-ownership filtering is a healthy-cluster optimization,
        # reported as state, never as a reason; no node down -> no
        # reroute
        assert h["ring"]["rerouteActive"] is False
        assert isinstance(h["ring"]["filterActive"], bool)
        cluster.close()
    finally:
        for srv in servers:
            srv.stop()
        for st in storages:
            st.close()
